"""Worker server: task execution + pull-based output buffers over HTTP.

Re-designed equivalent of the reference's worker surface (SURVEY L6 + L8):
TaskResource (`POST /v1/task/{id}`, server/TaskResource.java:120),
SqlTaskExecution running a PlanFragment, partitioned output buffers
(execution/buffer/PartitionedOutputBuffer) and the pull protocol
`GET /v1/task/{id}/results/{bufferId}/{token}` (TaskResource.java:239).

This is the DCN path of the communication backend (SURVEY §2.7): pages
move between processes as serde bytes over HTTP; the in-process shard_map
path (exec/dist.py) remains the ICI path within one slice. A task's
fragment is a pickled plan subtree whose exchange inputs appear as
RemoteSource placeholders resolved by pulling upstream buffers.
"""

from __future__ import annotations

import base64
import dataclasses
import json
import pickle
import threading
import time
import traceback
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import types as T
from ..exec.executor import Executor
from ..exec.stream import StreamingExecutor
from ..ops.union import concat_pages
from ..page import Block, Page
from ..plan import nodes as N
from . import knobs
from .serde import serialize_page


@dataclasses.dataclass(frozen=True)
class RemoteSource(N.PlanNode):
    """Placeholder for an exchange input materialized by pulling upstream
    task buffers (reference RemoteSourceNode)."""

    source_id: str
    schema: Tuple[Tuple[str, object], ...]  # (channel, Type)

    @property
    def fields(self):
        return self.schema


class QueryKilledError(RuntimeError):
    """Raised into blocked tasks when the cluster memory manager kills
    their query (reference: ExceededMemoryLimitException from
    LowMemoryKiller)."""


class WorkerMemoryPool:
    """Worker-wide memory accounting (reference: worker MemoryPool polled
    by ClusterMemoryManager.process, memory/ClusterMemoryManager.java:89).

    Two ledgers share one limit:
    * OUTPUT buffers (`reserve`/`free`): reservations past the limit
      BLOCK (the reference's blocking futures) until space frees, a
      revocation frees executor state, or the cluster memory manager
      kills a query.
    * EXECUTION state (`reserve_execution`/`free_execution`): build
      tables, accumulator state and spilled-pending bytes mirrored from
      each task's exec MemoryPool (exec/memory.py parent mirroring) —
      accounting-only (the executor enforces its own device budget), but
      counted against the limit/watermark so `/v1/memory` and the killer
      see REAL usage.

    Crossing the revocation watermark asks running executors to revoke
    (offload -> disk spill) in largest-revocable-first order — the
    MemoryRevokingScheduler analog (MemoryRevokingScheduler.java:46) —
    BEFORE anything blocks long enough for the killer to fire."""

    def __init__(self, limit: Optional[int] = None,
                 revoke_watermark: Optional[float] = None):
        import os

        self.limit = limit
        self.revoke_watermark = (
            knobs.revoke_watermark()
            if revoke_watermark is None else revoke_watermark
        )
        self.reserved = 0  # output-buffer bytes
        self.by_query: Dict[str, int] = {}
        self.exec_reserved = 0  # executor-held bytes (mirrored)
        self.exec_by_query: Dict[str, int] = {}
        self.blocked: set = set()  # query ids currently waiting
        # double-free observability (never silently clamp)
        self.over_frees = 0
        self.over_freed_bytes = 0
        # leaked exec reservations force-released at task unregister —
        # nonzero means a driver leak (the chaos suite asserts zero)
        self.leaked_exec_bytes = 0
        self.revocations_requested = 0
        self.watermark_breaches = 0
        self._revocations_base = 0  # completed, from unregistered pools
        self._exec_pools: Dict[int, object] = {}  # id -> exec MemoryPool
        # attached serving caches (exec/qcache.py ResultCache): bytes are
        # counted toward the watermark and the caches are revoked FIRST —
        # cached results are the cheapest memory on the node to give back
        self._caches: Dict[str, object] = {}
        self._cond = threading.Condition()

    # -- attached serving caches --

    def attach_cache(self, cache) -> None:
        with self._cond:
            self._caches[getattr(cache, "name", "cache")] = cache

    def detach_cache(self, cache) -> None:
        with self._cond:
            self._caches.pop(getattr(cache, "name", "cache"), None)

    def _cache_bytes_locked(self) -> int:
        return sum(c.stats.bytes for c in self._caches.values())

    # -- execution ledger (exec/memory.MemoryPool parent mirroring) --

    def register_exec_pool(self, pool) -> None:
        with self._cond:
            self._exec_pools[id(pool)] = pool

    def unregister_exec_pool(self, pool) -> None:
        """Detach a finished task's pool; any bytes it still holds are a
        driver leak — force-release them so the worker stays healthy, but
        COUNT them (tests assert zero)."""
        with self._cond:
            self._exec_pools.pop(id(pool), None)
            self._revocations_base += pool.revocations
        leaked = pool.reserved
        if leaked:
            with self._cond:
                self.leaked_exec_bytes += leaked
            self.free_execution(pool.query_id, leaked)

    def reserve_execution(self, query_id: str, nbytes: int) -> None:
        maybe_revoke = False
        with self._cond:
            self.exec_reserved += nbytes
            self.exec_by_query[query_id] = (
                self.exec_by_query.get(query_id, 0) + nbytes
            )
            maybe_revoke = (
                self.limit is not None
                and self.reserved + self.exec_reserved
                + self._cache_bytes_locked()
                > self.revoke_watermark * self.limit
            )
            if maybe_revoke:
                self._request_revocations_locked(0)

    def free_execution(self, query_id: str, nbytes: int) -> None:
        from ..exec.memory import GLOBAL_ACCOUNTING

        with self._cond:
            if nbytes > self.exec_reserved:
                self.over_frees += 1
                self.over_freed_bytes += nbytes - self.exec_reserved
                GLOBAL_ACCOUNTING["over_frees"] += 1
                GLOBAL_ACCOUNTING["over_freed_bytes"] += (
                    nbytes - self.exec_reserved
                )
                nbytes = self.exec_reserved
            self.exec_reserved -= nbytes
            left = self.exec_by_query.get(query_id, 0) - nbytes
            if left > 0:
                self.exec_by_query[query_id] = left
            else:
                self.exec_by_query.pop(query_id, None)
            self._cond.notify_all()

    def total_reserved(self) -> int:
        with self._cond:
            return self.reserved + self.exec_reserved

    # -- revocation (the rung between "blocked" and "killed") --

    def _request_revocations_locked(self, need: int) -> None:
        """Ask executors to revoke until the projected freeing covers the
        excess over the watermark, largest-revocable-first (reference
        MemoryRevokingScheduler.requestMemoryRevoking)."""
        if self.limit is None:
            return
        floor = int(self.revoke_watermark * self.limit)
        excess = (
            self.reserved + self.exec_reserved
            + self._cache_bytes_locked() + need - floor
        )
        if excess <= 0:
            return
        self.watermark_breaches += 1
        # serving caches revoke FIRST: evicting a cached result is free
        # (the entry re-materializes on the next miss) while revoking an
        # executor forces a spill — only the remaining excess reaches the
        # spill ladder
        for cache in self._caches.values():
            if excess <= 0:
                return
            excess -= cache.revoke(excess)
        if excess <= 0:
            return
        pools = sorted(
            self._exec_pools.values(),
            key=lambda p: -p.revocable_bytes(),
        )
        for pool in pools:
            if excess <= 0:
                break
            if pool.request_revoke():
                self.revocations_requested += 1
            # even a pool with nothing revocable RIGHT NOW is asked: its
            # next accumulation window observes the pending revoke and
            # offloads instead of growing
            excess -= max(pool.revocable_bytes(), 1)

    def revocations_completed(self) -> int:
        with self._cond:
            return self._revocations_base + sum(
                p.revocations for p in self._exec_pools.values()
            )

    # -- output-buffer ledger --

    def reserve(self, query_id: str, nbytes: int, abort: threading.Event,
                timeout: float = 600.0) -> None:
        if self.limit is None:
            with self._cond:
                self.reserved += nbytes
                self.by_query[query_id] = self.by_query.get(query_id, 0) + nbytes
            return
        deadline = time.time() + timeout
        with self._cond:
            while self.reserved + self.exec_reserved + nbytes > self.limit:
                if abort.is_set():
                    self.blocked.discard(query_id)
                    raise QueryKilledError(
                        "Query killed: the cluster ran out of memory "
                        "(TotalReservation low-memory killer)"
                    )
                if time.time() > deadline:
                    self.blocked.discard(query_id)
                    raise MemoryError(
                        f"worker memory exhausted: {nbytes:,}B requested, "
                        f"{self.reserved:,}B of {self.limit:,}B reserved"
                    )
                # revoke-before-kill: ask executors to free revocable
                # state instead of waiting for the low-memory killer
                self._request_revocations_locked(nbytes)
                self.blocked.add(query_id)
                self._cond.wait(timeout=0.05)
            self.blocked.discard(query_id)
            self.reserved += nbytes
            self.by_query[query_id] = self.by_query.get(query_id, 0) + nbytes
            # the watermark can be crossed by buffer growth alone: ask
            # for revocations BEFORE anything blocks, not only after
            self._request_revocations_locked(0)

    def free(self, query_id: str, nbytes: int) -> None:
        from ..exec.memory import GLOBAL_ACCOUNTING

        with self._cond:
            if nbytes > self.reserved:
                self.over_frees += 1
                self.over_freed_bytes += nbytes - self.reserved
                GLOBAL_ACCOUNTING["over_frees"] += 1
                GLOBAL_ACCOUNTING["over_freed_bytes"] += (
                    nbytes - self.reserved
                )
                nbytes = self.reserved
            self.reserved -= nbytes
            left = self.by_query.get(query_id, 0) - nbytes
            if left > 0:
                self.by_query[query_id] = left
            else:
                self.by_query.pop(query_id, None)
            self._cond.notify_all()

    def wake(self) -> None:
        with self._cond:
            self._cond.notify_all()

    def snapshot(self) -> dict:
        with self._cond:
            queries: Dict[str, int] = dict(self.by_query)
            for qid, nbytes in self.exec_by_query.items():
                queries[qid] = queries.get(qid, 0) + nbytes
            revoke_pending = any(
                p.revoke_pending for p in self._exec_pools.values()
            )
            return {
                "limit": self.limit,
                # total usage: buffers + executor-held bytes, so the
                # cluster memory manager kills on REAL reservation
                "reserved": self.reserved + self.exec_reserved,
                "buffer_reserved": self.reserved,
                "exec_reserved": self.exec_reserved,
                "queries": queries,
                "buffers": dict(self.by_query),
                "execution": dict(self.exec_by_query),
                "blocked": sorted(self.blocked),
                "over_frees": self.over_frees,
                "over_freed_bytes": self.over_freed_bytes,
                "leaked_exec_bytes": self.leaked_exec_bytes,
                "revocations": {
                    "watermark_breaches": self.watermark_breaches,
                    "requested": self.revocations_requested,
                    "completed": self._revocations_base + sum(
                        p.revocations for p in self._exec_pools.values()
                    ),
                    "pending": revoke_pending,
                },
                "watermark": self.revoke_watermark,
                # attached serving caches (exec/qcache.py): bytes held +
                # bytes given back under pressure, per cache
                "cache_reserved": self._cache_bytes_locked(),
                "caches": {
                    name: {
                        "bytes": c.stats.bytes,
                        "entries": len(c),
                        "revoked_bytes": c.stats.revoked_bytes,
                        "evictions": c.stats.evictions,
                    }
                    for name, c in self._caches.items()
                },
            }


class OutputBuffers:
    """Bounded, ack-consumed task output buffers (reference
    PartitionedOutputBuffer + OutputBufferMemoryManager,
    execution/buffer/): producers append page-at-a-time and BLOCK while
    unacknowledged bytes exceed the bound (backpressure); consumers pull
    by token and acknowledge, which frees producer budget. Bytes are also
    accounted in the worker memory pool so the cluster memory manager
    sees them."""

    def __init__(self, pool: "WorkerMemoryPool", query_id: str,
                 abort: threading.Event, bound: Optional[int] = None):
        self.pool = pool
        self.query_id = query_id
        self.abort = abort
        self.bound = bound
        self._pages: Dict[int, List[Optional[bytes]]] = {}
        self._unacked = 0
        self._finished = False
        self._drained = False
        self._cond = threading.Condition()

    def put(self, buffer_id: int, data: bytes,
            timeout: float = 600.0) -> None:
        deadline = time.time() + timeout
        with self._cond:
            while self.bound is not None and self._unacked + len(data) > max(
                self.bound, len(data)
            ):
                if self.abort.is_set():
                    raise QueryKilledError(
                        "Query killed: the cluster ran out of memory "
                        "(TotalReservation low-memory killer)"
                    )
                if time.time() > deadline:
                    raise MemoryError(
                        "output buffer consumer stalled past the bound"
                    )
                self._cond.wait(timeout=0.05)
            if self._drained:
                raise QueryKilledError("task deleted while producing")
            # claim the bound bytes under the SAME lock acquisition as
            # the check: concurrent producers can no longer all pass the
            # check and overshoot the bound while one of them sits in
            # pool.reserve below
            self._unacked += len(data)
        try:
            # prestolint: allow(memory-reserve-no-finally) -- both
            # failure paths DO undo: this except hands back _unacked,
            # and the drained branch below frees the pool bytes
            self.pool.reserve(self.query_id, len(data), self.abort)
        except BaseException:
            with self._cond:
                if not self._drained:  # drain() already zeroed _unacked
                    self._unacked -= len(data)
                self._cond.notify_all()
            raise
        with self._cond:
            if self._drained:
                # task was deleted while this producer was mid-stream:
                # hand the bytes straight back, never strand them
                # (drain() zeroed _unacked, so only the pool needs undo)
                self.pool.free(self.query_id, len(data))
                raise QueryKilledError("task deleted while producing")
            self._pages.setdefault(buffer_id, []).append(data)
            self._cond.notify_all()

    def finish(self) -> None:
        with self._cond:
            self._finished = True
            self._cond.notify_all()

    def get(self, buffer_id: int, token: int,
            timeout: float = 60.0):
        """(serialized page | None, complete, ready): ready=False means
        long-poll again (the page is not produced yet)."""
        with self._cond:
            deadline = time.time() + timeout
            while True:
                pages = self._pages.get(buffer_id, [])
                if token < len(pages):
                    if pages[token] is None:
                        raise RuntimeError(
                            f"buffer {buffer_id} token {token} was already "
                            "acknowledged (exchange protocol violation)"
                        )
                    return pages[token], False, True
                if self._finished:
                    return None, True, True
                if time.time() > deadline:
                    return None, False, False
                self._cond.wait(timeout=0.1)

    def get_many(self, buffer_id: int, token: int, max_bytes: int,
                 timeout: float = 60.0):
        """([serialized pages], complete, ready): as many consecutive
        already-produced pages from `token` as fit the `max_bytes`
        response budget (the reference's `exchange.max-response-size`
        batching, TaskResource.java:239). At least one page is always
        returned when one exists; `complete` is True when the returned
        batch drains a finished buffer, saving the final round trip."""
        first, complete, ready = self.get(buffer_id, token, timeout=timeout)
        if not ready or first is None:
            return [], complete, ready
        out = [first]
        total = len(first)
        with self._cond:
            pages = self._pages.get(buffer_id, [])
            t = token + 1
            while t < len(pages) and total < max_bytes:
                p = pages[t]
                if p is None:
                    raise RuntimeError(
                        f"buffer {buffer_id} token {t} was already "
                        "acknowledged (exchange protocol violation)"
                    )
                out.append(p)
                total += len(p)
                t += 1
            complete = self._finished and t >= len(pages)
        return out, complete, True

    def ack(self, buffer_id: int, upto_token: int) -> None:
        """Acknowledge pages [0, upto_token): their bytes free the bound
        and the worker pool (reference: acknowledge + delete results)."""
        with self._cond:
            pages = self._pages.get(buffer_id, [])
            freed = 0
            for i in range(min(upto_token, len(pages))):
                if pages[i] is not None:
                    freed += len(pages[i])
                    pages[i] = None
            if freed:
                self._unacked -= freed
                self._cond.notify_all()
        if freed:
            self.pool.free(self.query_id, freed)

    def drain(self) -> None:
        """Free everything still held (task deleted); later puts are
        rejected so a mid-stream producer cannot leak reservations."""
        with self._cond:
            self._drained = True
            freed = sum(
                len(p)
                for pages in self._pages.values()
                for p in pages
                if p is not None
            )
            self._pages.clear()
            self._unacked = 0
            self._cond.notify_all()
        if freed:
            self.pool.free(self.query_id, freed)


class TaskState:
    def __init__(self, query_id: str = ""):
        self.state = "RUNNING"
        self.error: Optional[str] = None
        # structured failure cause the coordinator classifies as
        # retryable vs. fatal (see _classify_failure)
        self.error_info: Optional[dict] = None
        self.buffers: Optional[OutputBuffers] = None
        self.done = threading.Event()
        self.query_id = query_id
        self.abort = threading.Event()  # set by the low-memory killer
        # dynamic-filter summaries accumulated over this task's output
        # (spec dyn_filter_produce; exec/dynfilter.HostFilterAccumulator),
        # exposed to the coordinator through the status endpoint
        self.dyn_filters: dict = {}
        # wire observability: encode stats for this task's serialized
        # output + pull stats for its upstream exchange clients, exposed
        # through the status endpoint as "exchangeStats" (the substrate
        # of EXPLAIN ANALYZE's per-exchange wire numbers)
        from .serde import WireStats

        self.wire_stats = WireStats()
        self.pull_stats = None  # ExchangeStats, set when sources exist
        self.hier_stats = None  # HierExchangeStats, set when this task
        # partitions output through the hierarchical exchange plane
        # memory-arbitration observability, filled at task end: the exec
        # pool snapshot (peak/revocations/over-frees) and spill stats
        # (events, disk bytes, hybrid join partition/recursion counters)
        self.executor = None
        self.spill_space = None
        self.memory_stats: Optional[dict] = None
        self.spill_stats: Optional[dict] = None
        # serialized span dicts for this task (obs/span.py), shipped in
        # the status payload and merged into the coordinator's trace —
        # the worker NEVER registers its trace globally, so the HTTP
        # merge path is exercised even by in-process workers
        self.spans: list = []


# message fragments marking failures that would recur identically on any
# worker — retrying them only wastes the retry budget
_FATAL_MARKERS = (
    "Query killed",  # low-memory killer chose this query
    "memory exhausted",  # worker pool limit: the retry would also exceed it
    "protocol violation",
    "not yet supported",
    # disk spill tier (exec/spillspace.py): a retry on another worker
    # would hit the same quota; a corrupt spill file must fail the query
    # with its structured error, never be retried into wrong rows
    "spill quota exceeded",
    "spill file corrupt",
)

# exception-type / message fragments identifying accelerator kernel
# faults (XLA / Mosaic): retryable, because the kernel circuit breaker
# (exec/breaker.py) degrades the faulting kernel to its XLA fallback on
# the retry attempt
_KERNEL_FAULT_MARKERS = (
    "XlaRuntimeError", "Mosaic", "INTERNAL:", "mosaic", "pallas",
)


def _classify_failure(exc: BaseException) -> dict:
    """Serialize an exception into the structured error the coordinator's
    retry policy consumes (reference: ExecutionFailureInfo + ErrorCode
    retryability, spi/StandardErrorCode.java)."""
    text = f"{type(exc).__name__}: {exc}"
    kernel_fault = any(m in text for m in _KERNEL_FAULT_MARKERS)
    retryable = not any(m in text for m in _FATAL_MARKERS)
    if isinstance(exc, (QueryKilledError, MemoryError)):
        retryable = False
    return {
        "type": type(exc).__name__,
        "message": str(exc)[:500],
        "retryable": retryable,
        "kernelFault": kernel_fault,
    }


class FragmentExecutor(Executor):
    """Executes a fragment subtree; scans are split-limited, RemoteSources
    read pulled pages (reference SqlTaskExecution + LocalExecutionPlanner)."""

    def __init__(self, catalog, splits, sources):
        super().__init__(catalog)
        self.splits = splits or {}
        self.sources = sources or {}
        self.sample_salt = _split_salt(self.splits)

    def _exec_tablescan(self, node: N.TableScan) -> Page:
        rng = self.splits.get(node.table)
        if rng is None:
            return super()._exec_tablescan(node)
        start, stop = rng
        scan = getattr(self.catalog, "scan", None)
        cols = [c for _, c, _ in node.columns]
        src = scan(node.table, start, stop, columns=cols)
        blocks, names = [], []
        for ch, colname, _t in node.columns:
            blocks.append(src.block(colname))
            names.append(ch)
        return Page(tuple(blocks), tuple(names), src.count)

    def _exec_remotesource(self, node: RemoteSource) -> Page:
        pages = self.sources[node.source_id]
        if not pages:
            raise RuntimeError(f"no pages for source {node.source_id}")
        return pages[0] if len(pages) == 1 else concat_pages(pages)


class StreamingFragmentExecutor(StreamingExecutor):
    """Streaming task execution (reference Driver pipeline fed by
    ExchangeOperator): scans honor split ranges batch-by-batch, and
    RemoteSource inputs arrive PAGE-AT-A-TIME from the pull clients —
    never materialize-then-concat. Budget-aware sinks (aggregation state
    merging, join build offload, external sort) compose unchanged, so an
    upstream stage larger than this worker's memory flows through in
    bounded pieces."""

    def __init__(self, catalog, splits, source_streams,
                 batch_rows: int = 1 << 18,
                 memory_budget: Optional[int] = None,
                 query_id: str = "",
                 worker_pool=None,
                 spill_space=None,
                 coalesce_remote: bool = False):
        super().__init__(
            catalog, batch_rows=batch_rows, memory_budget=memory_budget,
            query_id=query_id, worker_pool=worker_pool,
            spill_space=spill_space,
        )
        self.splits = splits or {}
        self.source_streams = source_streams or {}
        self.coalesce_remote = coalesce_remote
        # TABLESAMPLE: distinct per-worker hash salt derived from this
        # task's split assignment, so workers sampling disjoint row
        # ranges never reuse one positional mask (ops/filter.sample_page)
        self.local.sample_salt = _split_salt(self.splits)

    def stream(self, node: N.PlanNode):
        if isinstance(node, RemoteSource):
            if self.coalesce_remote:
                # the hierarchical exchange ships ragged wire pages
                # (small, skew-proportional); coalesce them back into
                # full batches so the sinks dispatch one kernel per
                # batch_rows, not one per wire sliver
                # (exec/stream.coalesce_pages). Flat-path exchanges
                # stream straight through — buffering full-size pages
                # would only stall the pull pipeline.
                from ..exec.stream import coalesce_pages
                from ..ops.ragged import page_rows_default

                target = min(self.batch_rows, 4 * page_rows_default())
                yield from coalesce_pages(
                    self.source_streams[node.source_id](), target
                )
                return
            yield from self.source_streams[node.source_id]()
            return
        yield from super().stream(node)

    def _stream_scan(self, node: N.TableScan, predicate=None):
        rng = self.splits.get(node.table)
        if rng is None:
            yield from super()._stream_scan(node, predicate)
            return
        if node.dynamic_filters:
            # dynamic-filter SPI hints (coordinator-shipped or published
            # by an in-fragment join) prune connector units before decode
            dyn = self._dyn_scan_hints(node)
            if dyn:
                predicate = list(predicate or []) + dyn
        start, stop = rng
        B = self.batch_rows
        pos = start
        first = True
        while pos < stop or first:
            # split bounds are exact, so connector pruning hints stay safe
            # (a pruned short batch cannot be mistaken for end-of-table)
            src = self.catalog.scan(
                node.table, pos, min(pos + B, stop), pad_to=B,
                columns=[c for _, c, _ in node.columns],
                predicate=predicate,
            )
            yield self._scan_out(node, self._rename_scan(node, src))
            first = False
            pos += B


class WorkerServer:
    """One worker process/port: executes tasks against its own catalog
    instance (catalogs must be deterministic across nodes — the TPC-H
    generator and parquet files are)."""

    def __init__(self, catalog, host: str = "127.0.0.1", port: int = 0,
                 memory_limit: Optional[int] = None,
                 buffer_bound: Optional[int] = 32 << 20,
                 task_concurrency: int = 2,
                 fault_rate: float = 0.0,
                 task_timeout: Optional[float] = None,
                 wire_caps: Optional[dict] = None,
                 exec_budget: Optional[int] = None,
                 revoke_watermark: Optional[float] = None,
                 spill_dir: Optional[str] = None,
                 spill_node_quota: Optional[int] = None,
                 spill_query_quota: Optional[int] = None,
                 account_result_cache: bool = False):
        from ..exec.spillspace import SPILL_MANAGER, SpillSpaceManager
        from ..exec.taskqueue import MultilevelScheduler

        self.catalog = catalog
        # per-task streaming-executor device budget: past it, operator
        # state offloads to host RAM and then the disk spill tier
        self.exec_budget = exec_budget
        # disk spill tier (exec/spillspace.py): workers with explicit
        # quotas/dirs get their own manager; otherwise the process-global
        # one (both register in the suite-wide leak oracle)
        if spill_dir or spill_node_quota or spill_query_quota:
            self.spill = SpillSpaceManager(
                directory=spill_dir, node_quota=spill_node_quota,
                query_quota=spill_query_quota,
            )
        else:
            self.spill = SPILL_MANAGER
        # capability-advertisement override (tests: simulate an old node
        # or one without the zstandard wheel in an in-process fleet)
        self.wire_caps = wire_caps
        # fault injection knob: probability a task fails at start
        self.fault_rate = float(fault_rate)
        # wall-clock ceiling per task, checked between batches: a wedged
        # kernel cannot hold a task RUNNING forever (the coordinator's
        # per-task deadline is the outer guard; this one frees the
        # worker's own slot)
        self.task_timeout = task_timeout
        self.tasks: Dict[str, TaskState] = {}
        self.pool = WorkerMemoryPool(
            memory_limit, revoke_watermark=revoke_watermark
        )
        # opt-in: account the process-wide result cache (exec/qcache.py)
        # in THIS worker's pool — its bytes then show in /v1/memory,
        # count toward the revocation watermark, and are revoked first.
        # Opt-in because one process can host several in-process workers
        # (tests) and the cache can only be charged to one of them.
        self._accounted_cache = None
        if account_result_cache:
            from ..exec.qcache import RESULT_CACHE

            self.pool.attach_cache(RESULT_CACHE)
            self._accounted_cache = RESULT_CACHE
        self.buffer_bound = buffer_bound
        # multilevel feedback gate over per-batch quanta (reference
        # TaskExecutor + MultilevelSplitQueue)
        self.scheduler = MultilevelScheduler(task_concurrency)
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _send(self, code, payload):
                body = (
                    payload
                    if isinstance(payload, bytes)
                    else json.dumps(payload).encode()
                )
                self.send_response(code)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                parts = [p for p in self.path.split("/") if p]
                if parts[:2] == ["v1", "task"] and len(parts) == 3:
                    # containment: a malformed spec must 500 with a
                    # structured error, never tear down the connection
                    # (the round-5 failure mode: one bad task wedged the
                    # serving loop)
                    try:
                        n = int(self.headers.get("Content-Length", 0))
                        spec = json.loads(self.rfile.read(n))
                        outer._start_task(parts[2], spec)
                    except Exception as exc:  # noqa: BLE001
                        self._send(500, {
                            "error": traceback.format_exc(limit=10),
                            "errorInfo": _classify_failure(exc),
                        })
                        return
                    self._send(200, {"taskId": parts[2], "state": "RUNNING"})
                    return
                self._send(404, {"error": "not found"})

            def do_GET(self):
                try:
                    self._do_get()
                except (BrokenPipeError, ConnectionResetError):
                    raise
                except Exception:  # noqa: BLE001 - surface handler bugs
                    self._send(
                        500, {"error": traceback.format_exc(limit=10)}
                    )

            def _do_get(self):
                path, _, query = self.path.partition("?")
                parts = [p for p in path.split("/") if p]
                if parts == ["v1", "status"]:
                    # capability handshake: the coordinator intersects
                    # every member's advertised wire caps and ships the
                    # result in task specs, so a mixed fleet (one node
                    # without the zstandard wheel, or still on wire v1)
                    # agrees on a format instead of failing deserialize
                    from .serde import local_capabilities
                    from ..exec import qcache

                    self._send(200, {
                        "state": "ACTIVE",
                        "wire": outer.wire_caps or local_capabilities(),
                        "caches": qcache.snapshot_all(),
                    })
                    return
                if parts == ["v1", "metrics"]:
                    # Prometheus text exposition — same registry the
                    # coordinator scrapes (process-global), so an
                    # in-process fleet shares one plane and a real
                    # remote worker exposes its own
                    from ..obs.metrics import METRICS

                    body = METRICS.render().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8",
                    )
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if parts == ["v1", "memory"]:
                    # reference MemoryResource polled by the coordinator's
                    # ClusterMemoryManager: buffer + execution ledgers,
                    # revocation counters, and the disk spill tier
                    snap = outer.pool.snapshot()
                    snap["spill"] = outer.spill.snapshot()
                    self._send(200, snap)
                    return
                if parts[:2] == ["v1", "task"] and len(parts) == 3:
                    t = outer.tasks.get(parts[2])
                    if t is None:
                        self._send(404, {"error": "unknown task"})
                        return
                    t.done.wait(timeout=0.5)  # short-poll: consumers
                    # pipeline against RUNNING producers; failures also
                    # surface as 500s on the results pull
                    ex_stats = t.wire_stats.snapshot()
                    if t.pull_stats is not None:
                        ex_stats["pull"] = t.pull_stats.snapshot()
                    if t.hier_stats is not None:
                        ex_stats["hier"] = t.hier_stats.snapshot()
                    self._send(200, {
                        "state": t.state, "error": t.error,
                        "errorInfo": t.error_info,
                        "dynFilters": t.dyn_filters or None,
                        "exchangeStats": ex_stats,
                        "memoryStats": t.memory_stats,
                        "spillStats": t.spill_stats,
                        # serialized span dicts the coordinator merges
                        # (Trace.add_remote) into the query's one tree
                        "spans": t.spans or None,
                    })
                    return
                if (
                    parts[:2] == ["v1", "task"]
                    and len(parts) == 6
                    and parts[3] == "results"
                ):
                    tid, buffer_id, token = parts[2], int(parts[4]), int(parts[5])
                    t = outer.tasks.get(tid)
                    if t is None:
                        self._send(404, {"error": "unknown task"})
                        return
                    if t.state == "FAILED":
                        self._send(500, {"error": t.error,
                                         "errorInfo": t.error_info})
                        return
                    if t.buffers is None:  # task thread not started yet
                        self._send(503, {"retry": True, "state": t.state})
                        return
                    max_bytes = 0
                    for kv in query.split("&"):
                        if kv.startswith("max_bytes="):
                            try:
                                max_bytes = int(kv.split("=", 1)[1])
                            except ValueError:
                                pass
                    if max_bytes > 0:
                        # multi-page response bounded by the client's
                        # max_response_bytes budget (the
                        # exchange.max-response-size analog); "page"
                        # stays populated so old pullers interoperate
                        datas, complete, ready = t.buffers.get_many(
                            buffer_id, token, max_bytes, timeout=50
                        )
                    else:
                        data, complete, ready = t.buffers.get(
                            buffer_id, token, timeout=50
                        )
                        datas = [] if data is None else [data]
                    if t.state == "FAILED":
                        # finish() fires in the task's finally, so a failed
                        # producer must never look like a complete stream
                        self._send(500, {"error": t.error,
                                         "errorInfo": t.error_info})
                        return
                    if not ready:
                        self._send(503, {"retry": True, "state": t.state})
                        return
                    encoded = [
                        base64.b64encode(d).decode() for d in datas
                    ]
                    self._send(
                        200,
                        {
                            "page": encoded[0] if encoded else None,
                            "pages": encoded,
                            "complete": complete,
                        },
                    )
                    return
                self._send(404, {"error": "not found"})

            def do_DELETE(self):
                parts = [p for p in self.path.split("/") if p]
                if (
                    parts[:2] == ["v1", "task"]
                    and len(parts) == 6
                    and parts[3] == "results"
                ):
                    # acknowledge pages [0, token): frees producer budget
                    t = outer.tasks.get(parts[2])
                    if t is not None and t.buffers is not None:
                        t.buffers.ack(int(parts[4]), int(parts[5]))
                    self._send(200, {"acknowledged": True})
                    return
                if parts[:2] == ["v1", "task"] and len(parts) == 3:
                    t = outer.tasks.pop(parts[2], None)
                    if t is not None:
                        t.abort.set()
                        if t.buffers is not None:
                            t.buffers.drain()
                    self._send(200, {"deleted": True})
                    return
                if parts[:2] == ["v1", "query"] and len(parts) == 3:
                    # low-memory kill: abort every task of this query;
                    # blocked reservations raise QueryKilledError
                    outer.kill_query(parts[2])
                    self._send(200, {"killed": parts[2]})
                    return
                self._send(404, {"error": "not found"})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._httpd.server_address
        self.node_id = f"{self.host}:{self.port}"
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )

    # -- task execution --

    def _start_task(self, task_id: str, spec: dict):
        state = TaskState(query_id=spec.get("query_id") or task_id)
        self.tasks[task_id] = state
        threading.Thread(
            target=self._run_task, args=(task_id, spec, state), daemon=True
        ).start()

    def _run_task(self, task_id: str, spec: dict, state: TaskState):
        # broadcast consumers never ack (pages are shared; freed at task
        # DELETE), so a bounded buffer would deadlock its producer
        stream_iter = None  # closed in the finally for deterministic
        # generator teardown (reservations return before unregister)
        bound = None if spec.get("buffer_unbounded") else self.buffer_bound
        buffers = OutputBuffers(
            self.pool, state.query_id, state.abort, bound=bound
        )
        state.buffers = buffers
        # task span opened BEFORE fault injection: a failed attempt must
        # still ship an error-status span in its FAILED status payload so
        # the coordinator's merged tree shows the attempt (retry =
        # sibling spans, never an overwrite). The Trace is standalone —
        # never registered in the global TRACES store — so the only way
        # home is the status payload, same as a real remote worker.
        from ..obs.span import Trace, enabled as _trace_enabled

        tctx = spec.get("trace") or {}
        task_trace = task_span = None
        if tctx.get("trace_id") and _trace_enabled():
            task_trace = Trace(str(tctx["trace_id"]))
            task_span = task_trace.begin(
                f"task {task_id}", parent_id=tctx.get("parent"),
                worker=self.node_id,
            )
        try:
            if self.fault_rate > 0:
                # fault injection (reference: test-only task failures,
                # e.g. TestEventListener's failing connector; here a
                # worker-level knob so cluster tests can exercise the
                # failure-propagation path deterministically)
                import random

                if random.random() < self.fault_rate:
                    raise RuntimeError(
                        f"injected fault on worker {self.node_id} "
                        f"(fault_rate={self.fault_rate})"
                    )
            fragment = pickle.loads(base64.b64decode(spec["fragment"]))
            splits = {
                t: tuple(rng) for t, rng in (spec.get("splits") or {}).items()
            }
            # fleet-negotiated wire capabilities (coordinator handshake):
            # this task's output must only use codecs/encodings every
            # consumer can decode. A spec WITHOUT the field came from a
            # coordinator that does not negotiate (an old build) — its
            # decoder is unknown, so degrade to the universal baseline
            # rather than assuming this process's own capabilities.
            from .serde import baseline_capabilities

            wire_caps = spec.get("wire") or baseline_capabilities()
            if spec.get("sources"):
                from .exchange import ExchangeStats

                state.pull_stats = ExchangeStats()

            def make_stream(locations, exclusive):
                def gen():
                    # pipelined concurrent pull: one puller per producer
                    # task, multi-page responses, deserialize overlapped
                    # with in-flight requests (server/exchange.py). Acks
                    # free producer pages — only safe when this task is
                    # the buffer's sole consumer (replicated buffers are
                    # pulled by every consumer and freed on task DELETE)
                    from .exchange import ExchangeClient

                    client = ExchangeClient(
                        [(u, t, b) for u, t, b in locations],
                        ack=exclusive,
                        stats=state.pull_stats,
                    )
                    for page in client.pages():
                        yield _min_capacity(page)
                return gen

            streams = {
                sid: make_stream(
                    src["locations"], bool(src.get("exclusive", True))
                )
                for sid, src in (spec.get("sources") or {}).items()
            }
            # per-task spill space: quota-accounted under the QUERY id,
            # released in this thread's finally — kills, failures and
            # clean finishes all delete their spill files
            spill_space = self.spill.open(state.query_id)
            state.spill_space = spill_space
            # incoming ragged slivers are possible only when the fleet
            # negotiated the hierarchical exchange AND the knob is on
            # (upstream producers share this negotiation); otherwise
            # stream remote pages through untouched
            from .hier import hier_negotiated as _hier_neg

            coalesce_remote = (
                bool(spec.get("sources"))
                and knobs.hier_exchange_enabled()
                and _hier_neg(wire_caps)
            )
            ex = StreamingFragmentExecutor(
                self.catalog, splits, streams,
                memory_budget=self.exec_budget,
                query_id=state.query_id,
                worker_pool=self.pool,
                spill_space=spill_space,
                coalesce_remote=coalesce_remote,
            )
            state.executor = ex
            # executor-held bytes join the worker ledger + the revoking
            # scheduler's candidate set (revoke-before-kill)
            self.pool.register_exec_pool(ex.pool)
            # cross-task dynamic filters shipped by the coordinator: seed
            # the executor registry so annotated scans in this fragment
            # prune (exec/dynfilter.py). Missing/late filters simply stay
            # unpublished — the scan runs unfiltered (proceed-without).
            for fid, summary in (spec.get("dyn_filters") or {}).items():
                try:
                    from ..exec.dynfilter import filter_from_summary

                    df = filter_from_summary(summary, None)
                    if df is not None:
                        ex.dyn_ctx.publish(fid, df)
                except Exception:  # noqa: BLE001 — filters are best-effort
                    pass
            # summaries to accumulate over THIS task's output pages
            # (the build side of some downstream dynamic-filter join)
            from ..exec.dynfilter import HostFilterAccumulator

            dyn_accs = {
                fid: HostFilterAccumulator(channel)
                for fid, channel in (spec.get("dyn_filter_produce") or [])
            }
            part_keys = spec.get("partition_keys")
            nparts = int(spec.get("num_partitions", 1))
            keys = (
                pickle.loads(base64.b64decode(part_keys))
                if part_keys and nparts > 1
                else None
            )
            # hierarchical exchange (server/hier.py): regroup partitioned
            # output with ONE device step + ragged wire pages, when the
            # fleet negotiated the capability, the knob is on, and the
            # breaker is closed. Any fault mid-task trips the breaker
            # and degrades the REST of this task (and, once open, every
            # later task) to the flat per-partition loop — monotonic.
            use_hier = False
            if keys is not None:
                from ..exec.breaker import BREAKERS
                from .hier import HierExchangeStats, hier_negotiated, \
                    hier_partition

                use_hier = (
                    knobs.hier_exchange_enabled()
                    and hier_negotiated(wire_caps)
                    and BREAKERS.allow("hier_exchange")
                )
                if use_hier:
                    state.hier_stats = HierExchangeStats()
            # page-at-a-time into the bounded buffers: put() applies
            # backpressure when the consumer lags past the bound; pages
            # bigger than the bound split into row slices first
            # (reference PageSplitterUtil). Each batch passes through the
            # multilevel scheduler gate (exec/taskqueue.py) so a fresh
            # query's quanta preempt a long-running one BETWEEN batches;
            # buffer emission stays outside the quantum — blocking on a
            # slow consumer must not hold an execution slot.
            stream_iter = iter(ex.stream(fragment))
            deadline = (
                time.time() + self.task_timeout
                if self.task_timeout else None
            )
            while True:
                # crash containment checkpoints between batches: an
                # aborted (killed/deleted) task stops producing, and a
                # task past its deadline FAILS instead of holding its
                # slot forever (the round-5 wedge)
                if state.abort.is_set():
                    raise QueryKilledError("task aborted")
                if deadline is not None and time.time() > deadline:
                    raise TimeoutError(
                        f"task {task_id} exceeded task_timeout="
                        f"{self.task_timeout}s on worker {self.node_id}"
                    )
                with self.scheduler.quantum(state.query_id):
                    page = next(stream_iter, None)
                if page is None:
                    break
                for acc in dyn_accs.values():
                    try:
                        acc.add_page(page)
                    except Exception:  # noqa: BLE001 — best-effort
                        acc.unsupported = True
                for piece in _split_to_bound(page, bound):
                    if keys is not None:
                        if use_hier:
                            try:
                                parts = hier_partition(
                                    piece, keys, nparts, caps=wire_caps,
                                    stats=state.wire_stats,
                                    hier=state.hier_stats,
                                )
                                BREAKERS.record_success("hier_exchange")
                            except Exception as e:  # noqa: BLE001 — any
                                # hier fault degrades to the flat loop;
                                # output correctness must not depend on
                                # the optimized path
                                BREAKERS.record_failure(
                                    "hier_exchange", repr(e)
                                )
                                state.hier_stats.record_fallback()
                                use_hier = False
                                parts = _hash_partition(
                                    piece, keys, nparts, caps=wire_caps,
                                    stats=state.wire_stats,
                                )
                        else:
                            parts = _hash_partition(
                                piece, keys, nparts, caps=wire_caps,
                                stats=state.wire_stats,
                            )
                        for p, data in parts.items():
                            for d in data:
                                buffers.put(p, d)
                    else:
                        buffers.put(0, serialize_page(
                            piece, caps=wire_caps, stats=state.wire_stats,
                        ))
            if dyn_accs:
                state.dyn_filters = {
                    fid: s
                    for fid, acc in dyn_accs.items()
                    if (s := acc.summary()) is not None
                }
            state.state = "FINISHED"
        except BaseException as exc:  # noqa: BLE001 - kernel faults
            # (XLA/Mosaic aborts surface as various exception types)
            # must transition the task to FAILED with a structured cause
            # the coordinator can classify — never tear down the thread
            # silently or wedge the HTTP serving side
            state.error = traceback.format_exc(limit=20)
            state.error_info = _classify_failure(exc)
            state.state = "FAILED"
        finally:
            # deterministic teardown (not GC): closing the stream runs
            # every suspended generator's finally, returning executor
            # reservations before the pool unregisters
            if stream_iter is not None:
                try:
                    stream_iter.close()
                except Exception:  # noqa: BLE001 - teardown best-effort
                    pass
            ex_obj = getattr(state, "executor", None)
            if ex_obj is not None:
                release_error = None
                try:
                    ex_obj.release_spill()  # fold disk counters
                except Exception as exc:  # noqa: BLE001 — teardown must
                    # finish; the failure is recorded into spill_stats
                    # below instead of vanishing (prestolint burndown)
                    release_error = repr(exc)
                state.memory_stats = ex_obj.pool.snapshot()
                state.spill_stats = dict(ex_obj.spill_stats)
                if release_error is not None:
                    state.spill_stats["release_error"] = release_error
                state.spill_stats["events"] = sorted(
                    set(ex_obj.spill_events)
                )
                self.pool.unregister_exec_pool(ex_obj.pool)
            space = getattr(state, "spill_space", None)
            if space is not None:
                # guaranteed spill cleanup on finish, failure AND kill
                space.release()
            buffers.finish()
            try:
                self._finish_observability(task_id, state, task_trace,
                                           task_span)
            except Exception:  # noqa: BLE001 — observability must never
                # change task outcome or wedge teardown
                pass
            state.done.set()

    def _finish_observability(self, task_id: str, state: TaskState,
                              task_trace, task_span) -> None:
        """Close the task span (rows/bytes attrs from the wire stats,
        error status for FAILED) into state.spans, and fold this task's
        serde/pull accounting + outcome counter into the metrics plane."""
        from ..obs.export import (
            METRICS, export_exchange_stats, export_wire_stats,
        )

        wire_snap = state.wire_stats.snapshot()
        if task_trace is not None and task_span is not None:
            status = "error" if state.state == "FAILED" else "ok"
            attrs = {
                "pages": wire_snap.get("pages", 0),
                "bytes": wire_snap.get("wire_bytes", 0),
            }
            if state.error_info:
                attrs["error"] = state.error_info.get("message", "")[:200]
            if state.hier_stats is not None:
                hs = state.hier_stats.snapshot()
                if hs.get("exchanges"):
                    attrs["hier_collective_ms"] = hs["collective_ms"]
                    attrs["hier_wire_pages"] = hs["wire_pages"]
            if state.pull_stats is not None:
                # the span's overlap proof: wire wall the pullers spent
                # vs the fraction the consumer's device compute hid
                ps = state.pull_stats.snapshot()
                if ps.get("pull_ms"):
                    attrs["wire_ms"] = ps["pull_ms"]
                    attrs["wire_hidden_ms"] = ps["hidden_ms"]
            task_trace.finish(task_span, status=status, **attrs)
            state.spans = task_trace.to_dicts()
        METRICS.counter(
            "presto_worker_tasks_total", 1, {"state": state.state},
            help="Worker tasks run",
        )
        export_wire_stats("task_encode", state.wire_stats)
        if state.pull_stats is not None:
            export_exchange_stats(state.pull_stats)
        if state.hier_stats is not None:
            from ..obs.export import export_hier_stats

            export_hier_stats(state.hier_stats)

    def start(self) -> "WorkerServer":
        self._thread.start()
        return self

    def kill_query(self, query_id: str) -> None:
        for t in list(self.tasks.values()):
            if t.query_id == query_id:
                t.abort.set()
        self.pool.wake()

    def stop(self):
        if self._accounted_cache is not None:
            self.pool.detach_cache(self._accounted_cache)
            self._accounted_cache = None
        self._httpd.shutdown()
        self._httpd.server_close()

    @property
    def uri(self) -> str:
        return f"http://{self.host}:{self.port}"


def _split_salt(splits: Dict[str, Tuple[int, int]]) -> int:
    """Deterministic per-task sample salt from the split assignment: the
    summed range starts are distinct across workers of one stage (their
    row ranges are disjoint), so TABLESAMPLE's positional hash never
    reuses a mask across workers."""
    return sum(int(start) for start, _stop in splits.values())


def _split_to_bound(page: Page, bound: Optional[int]):
    """Split a page into row slices whose RAW bytes fit the output-buffer
    bound (serialized bytes are smaller), so one page never blows through
    the backpressure budget (reference PageSplitterUtil.splitPage)."""
    n = int(page.count)
    if bound is None or n == 0:
        yield page
        return
    row_bytes = max(
        sum(
            b.data.dtype.itemsize * (b.data.size // max(b.data.shape[0], 1))
            + (1 if b.valid is not None else 0)
            for b in page.blocks
        ),
        1,
    )
    max_rows = max(bound // (2 * row_bytes), 256)
    if n <= max_rows:
        yield page
        return
    for start in range(0, n, max_rows):
        stop = min(start + max_rows, n)
        idx = slice(start, stop)
        blocks = tuple(b.take_rows(idx) for b in page.blocks)
        yield Page(blocks, page.names, stop - start)


def _min_capacity(page: Page, minimum: int = 16) -> Page:
    """Empty wire pages deserialize with ZERO capacity; the streaming
    sinks' static-shape kernels need at least one slot — pad up."""
    if not page.blocks or page.blocks[0].data.shape[0] >= minimum:
        return page
    from ..page import _pad_block

    return Page(
        tuple(_pad_block(b, minimum) for b in page.blocks),
        page.names,
        page.count,
    )


def _hash_partition(page: Page, key_exprs, nparts: int,
                    caps: Optional[dict] = None,
                    stats=None) -> Dict[int, List[bytes]]:
    """Partition live rows by key hash -> serialized per-partition pages
    (reference PartitionedOutputOperator.partitionPage + PagesSerde)."""
    import jax.numpy as jnp

    from ..ops.filter import compact
    from ..ops.hashing import hash_rows
    from ..expr.compiler import evaluate

    keys = [evaluate(e, page) for e in key_exprs]
    h = hash_rows(keys)
    part = (h % jnp.uint64(nparts)).astype(jnp.int32)
    out: Dict[int, List[bytes]] = {}
    for p in range(nparts):
        sub = compact(page, part == p)
        out[p] = [serialize_page(sub, caps=caps, stats=stats)]
    return out


def _pull_buffer(uri: str, task_id: str, buffer_id: int, ack: bool = True,
                 deadline: Optional[float] = None,
                 max_bytes: Optional[int] = None):
    """Generator of serialized pages from ONE upstream buffer, batched
    long-polls + acks (reference HttpPageBufferClient pull/ack/delete
    loop). The multi-producer pipelined path is server/exchange.py's
    ExchangeClient; this sequential form remains for single-location
    pulls and as the oracle the concurrent client is tested against.

    `deadline` caps the wall time between PAGES (a progress deadline): a
    wedged producer (RUNNING forever, producing nothing) must fail the
    pull — retryably — instead of hanging its consumer forever (the
    round-5 relay stall). None reads PRESTO_TPU_TASK_DEADLINE_S
    (default 600)."""
    from .exchange import ack_pages, fetch_pages

    if deadline is None:
        deadline = knobs.task_deadline_s()
    give_up = time.time() + deadline

    token = 0
    while True:
        pages, complete, ready = fetch_pages(
            uri, task_id, buffer_id, token, max_bytes=max_bytes
        )
        if pages:
            token += len(pages)
            for data in pages:
                yield data
            give_up = time.time() + deadline  # progress resets the clock
            if ack:
                ack_pages(uri, task_id, buffer_id, token)
            if complete:
                return
            continue
        if complete:
            return
        if not ready and time.time() >= give_up:
            raise RuntimeError(
                f"upstream task {task_id} on {uri} produced no "
                f"page within the {deadline:.0f}s task deadline "
                "(wedged worker?)"
            ) from None
