"""Hierarchical exchange producer: device collectives intra-host, ragged
paged partitions on the PTP2 wire inter-host.

The engine used to run two disconnected shuffle worlds: the shard_map
mesh path (`parallel/exchange.py`) repartitions with ONE `lax.all_to_all`
collective, while the HTTP cluster's partitioned task output
(`server/worker.py:_hash_partition`) looped `compact(page, part == p)`
once PER PARTITION — nparts separate device dispatches and full-page
scans per output batch. This module unifies them into a hierarchy:

* **intra-host** — rows regroup by destination partition in ONE device
  step. On a multi-device host the step is the shard_map
  `lax.all_to_all` collective itself (`shuffle_write_parts` routes each
  row to device `part % d`, the collective swaps buffers over ICI, and
  each device sorts its received rows by partition); on a single chip a
  fused jitted grouping kernel (argsort + searchsorted boundaries + one
  gather per column) does the same in one dispatch. Either way the cost
  is O(rows log rows) once, not O(nparts * rows).

* **inter-host** — the grouped rows ship over the PR 4 PTP2 wire as
  RAGGED paged partitions (ops/ragged.py): each partition's rows cut
  into chunks of at most `PRESTO_TPU_RAGGED_PAGE_ROWS` live rows, the
  last chunk partial. A dense collective output buffer pads every
  partition to the largest one — at 100:1 skew that pads ~99% of the
  wire; the ragged unit ships live rows only, and `wire_padding`
  accounts for exactly how much the skew would have cost.

The producer path is capability-negotiated (`serde.local_capabilities`
advertises ``"hier"``; `negotiate` intersects it fleet-wide) and gated
by the `hier_exchange` circuit breaker — any fault degrades the task to
the flat per-partition loop, monotonically, with oracle-equal output.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import types as T
from ..expr.compiler import evaluate
from ..ops.hashing import hash_rows
from ..ops.ragged import page_rows_default, wire_padding
from ..page import Block, Page
from . import knobs
from .serde import serialize_page

_PART_COL = "$hier_part"


class HierExchangeStats:
    """Thread-safe accounting for one task's hierarchical exchange
    output (the producer half; the consumer-side overlap lives in
    ExchangeStats). Shipped in the task status payload under
    ``exchangeStats["hier"]``, folded by the coordinator, exported to
    /v1/metrics via obs/export.export_hier_stats."""

    def __init__(self):
        self._lock = threading.Lock()
        self.exchanges = 0  # output batches regrouped hierarchically
        self.collective_exchanges = 0  # of those, via the all_to_all path
        self.rows = 0
        self.collective_s = 0.0  # intra-host regroup wall (device step
        # dispatch + host readback), the "collective wall" of the footer
        self.wire_pages = 0  # ragged pages put on the wire
        self.ragged_pad_rows = 0  # pad the ragged paged layout carries
        self.fixed_pad_rows = 0  # pad a pad-to-max wire unit would carry
        self.fallbacks = 0  # batches that fell back to the flat loop

    def record_batch(self, rows: int, seconds: float, collective: bool,
                     pages: int, pad: dict) -> None:
        with self._lock:
            self.exchanges += 1
            if collective:
                self.collective_exchanges += 1
            self.rows += int(rows)
            self.collective_s += float(seconds)
            self.wire_pages += int(pages)
            self.ragged_pad_rows += int(pad.get("ragged_pad_rows", 0))
            self.fixed_pad_rows += int(pad.get("fixed_pad_rows", 0))

    def record_fallback(self) -> None:
        with self._lock:
            self.fallbacks += 1

    def merge_snapshot(self, snap: Optional[dict]) -> None:
        """Fold a remote snapshot (task status payload) into this
        accumulator — the coordinator sums its producers' hier stats."""
        if not snap:
            return
        with self._lock:
            self.exchanges += int(snap.get("exchanges", 0))
            self.collective_exchanges += int(
                snap.get("collective_exchanges", 0)
            )
            self.rows += int(snap.get("rows", 0))
            self.collective_s += (snap.get("collective_ms") or 0) / 1e3
            self.wire_pages += int(snap.get("wire_pages", 0))
            self.ragged_pad_rows += int(snap.get("ragged_pad_rows", 0))
            self.fixed_pad_rows += int(snap.get("fixed_pad_rows", 0))
            self.fallbacks += int(snap.get("fallbacks", 0))

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "exchanges": self.exchanges,
                "collective_exchanges": self.collective_exchanges,
                "rows": self.rows,
                "collective_ms": round(self.collective_s * 1e3, 2),
                "wire_pages": self.wire_pages,
                "ragged_pad_rows": self.ragged_pad_rows,
                "fixed_pad_rows": self.fixed_pad_rows,
                "pad_saved_rows": max(
                    self.fixed_pad_rows - self.ragged_pad_rows, 0
                ),
                "fallbacks": self.fallbacks,
            }


def hier_negotiated(caps: Optional[dict]) -> bool:
    """Did the fleet-wide wire negotiation keep the hierarchical
    capability? A spec without the advert (old coordinator, or any
    worker that did not advertise it) degrades to the flat loop."""
    return bool(isinstance(caps, dict) and (caps.get("hier") or {}).get(
        "ragged"
    ))


# ---------------------------------------------------------------------------
# intra-host regroup: one device step, not one dispatch per partition
# ---------------------------------------------------------------------------

_FUSED_JIT = None  # lazily-built jitted regroup (one per process)
_COLLECTIVE_CACHE: dict = {}  # (n_devices, nparts) -> shard_map'd fn


def _fused_regroup_fn():
    global _FUSED_JIT
    if _FUSED_JIT is None:
        import functools

        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit, static_argnames=("nparts",))
        def fused(page, part, nparts):
            # dead rows carry the nparts sentinel: stable argsort puts
            # them LAST, searchsorted boundaries never include them
            order = jnp.argsort(part, stable=True)
            ps = part[order]
            bins = jnp.arange(nparts, dtype=ps.dtype)
            starts = jnp.searchsorted(ps, bins, side="left")
            ends = jnp.searchsorted(ps, bins, side="right")
            blocks = tuple(b.take_rows(order) for b in page.blocks)
            return blocks, starts.astype(jnp.int32), ends.astype(jnp.int32)

        _FUSED_JIT = fused
    return _FUSED_JIT


def _collective_regroup_fn(n_dev: int, nparts: int, names: Tuple[str, ...]):
    """Build (and cache) the shard_map'd collective regroup for this
    (device count, partition count) topology: each device scatters its
    rows toward owner device ``part % n_dev`` (`shuffle_write_parts`),
    ONE `lax.all_to_all` swaps the buffers over ICI, and the receiver
    sorts its rows by destination partition so the host can slice each
    owned partition's contiguous range."""
    key = (n_dev, nparts, names)
    fn = _COLLECTIVE_CACHE.get(key)
    if fn is not None:
        return fn
    import jax
    import jax.numpy as jnp

    try:
        from jax import shard_map  # jax >= 0.8 home
    except ImportError:
        from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from ..parallel.exchange import all_to_all_page, shuffle_write_parts
    from ..parallel.mesh import default_mesh

    mesh = default_mesh(n_dev)
    axis = mesh.axis_names[0]
    ppd = -(-nparts // n_dev)  # partitions owned per device

    def shard_fn(blocks, part):
        cap = part.shape[0]  # per-device shard rows R
        carrying = blocks + (Block(part, T.INTEGER),)
        page_l = Page(carrying, names + (_PART_COL,),
                      jnp.asarray(cap, jnp.int32))
        # destination device owns partitions congruent to it mod n_dev;
        # the sentinel (part >= nparts: dead/pad rows) drops in the
        # scatter. part_capacity == R is overflow-free by construction
        # (a shard holds at most R rows, however skewed).
        dest = jnp.where(part < nparts, part % n_dev, n_dev)
        buf, counts, _dropped = shuffle_write_parts(
            page_l, dest, n_dev, cap
        )
        recv = all_to_all_page(buf, counts, axis, cap)
        pcol = recv.blocks[-1].data
        pcol = jnp.where(recv.live_mask(), pcol, nparts + n_dev)
        order = jnp.argsort(pcol, stable=True)
        ps = pcol[order]
        j = jax.lax.axis_index(axis)
        bins = j + jnp.arange(ppd, dtype=ps.dtype) * n_dev
        starts = jnp.searchsorted(ps, bins, side="left")
        ends = jnp.searchsorted(ps, bins, side="right")
        out = tuple(b.take_rows(order) for b in recv.blocks[:-1])
        return out, starts.astype(jnp.int32), ends.astype(jnp.int32)

    kw = dict(
        mesh=mesh,
        in_specs=(P(axis), P(axis)),
        out_specs=(P(axis), P(axis), P(axis)),
    )
    try:
        smapped = shard_map(shard_fn, check_vma=False, **kw)
    except TypeError:
        smapped = shard_map(shard_fn, check_rep=False, **kw)
    fn = jax.jit(smapped)
    _COLLECTIVE_CACHE[key] = fn
    return fn


def _pad_rows(arr, rows: int):
    import jax.numpy as jnp

    if arr.shape[0] >= rows:
        return arr
    pad = [(0, rows - arr.shape[0])] + [(0, 0)] * (arr.ndim - 1)
    return jnp.pad(arr, pad)


def _pad_block_rows(b: Block, rows: int) -> Block:
    return Block(
        _pad_rows(b.data, rows), b.type,
        None if b.valid is None else _pad_rows(b.valid, rows),
        b.dict_id,
    )


def _collective_eligible(page: Page, n_dev: int, rows: int) -> bool:
    if n_dev < knobs.hier_exchange_min_devices():
        return False
    if rows < knobs.hier_exchange_min_rows():
        return False
    # the collective swaps data/valid arrays only: collection blocks
    # (lengths/elem_valid/key_block companions) take the fused kernel
    return all(
        b.lengths is None and b.elem_valid is None and b.key_block is None
        for b in page.blocks
    )


def _host_block(b: Block) -> Block:
    return Block(
        np.asarray(b.data), b.type,
        None if b.valid is None else np.asarray(b.valid),
        b.dict_id,
        lengths=None if b.lengths is None else np.asarray(b.lengths),
        elem_valid=(
            None if b.elem_valid is None else np.asarray(b.elem_valid)
        ),
        key_block=None if b.key_block is None else _host_block(b.key_block),
    )


def hier_partition(
    page: Page,
    key_exprs,
    nparts: int,
    caps: Optional[dict] = None,
    stats=None,
    hier: Optional[HierExchangeStats] = None,
    page_rows: Optional[int] = None,
) -> Dict[int, List[bytes]]:
    """Partition live rows by key hash into serialized RAGGED wire pages
    — the hierarchical replacement for the flat `_hash_partition` loop.
    Output contract matches flat exactly: every partition gets at least
    one page (possibly empty), and the union of decoded rows per
    partition equals the flat path's."""
    import jax

    pr = page_rows or page_rows_default()
    t0 = time.perf_counter()
    n = int(page.count)
    keys = [evaluate(e, page) for e in key_exprs]
    import jax.numpy as jnp

    h = hash_rows(keys)
    part = (h % jnp.uint64(nparts)).astype(jnp.int32)
    part = jnp.where(page.live_mask(), part, nparts)

    n_dev = len(jax.devices())
    collective = _collective_eligible(page, n_dev, n)
    if collective:
        # shard the batch over the local mesh (rows padded to a multiple
        # of the device count; pad rows carry the drop sentinel)
        cap = -(-page.capacity // n_dev) * n_dev
        blocks = tuple(_pad_block_rows(b, cap) for b in page.blocks)
        part_in = jnp.pad(
            part, (0, cap - page.capacity), constant_values=nparts
        )
        fn = _collective_regroup_fn(n_dev, nparts, page.names)
        out_blocks, starts, ends = fn(blocks, part_in)
        local_cap = cap  # each device receives up to n_dev * (cap/n_dev)
        ppd = -(-nparts // n_dev)
        starts = np.asarray(starts).reshape(n_dev, ppd)
        ends = np.asarray(ends).reshape(n_dev, ppd)
        host = [_host_block(b) for b in out_blocks]
        regions: Dict[int, List[Tuple[int, int]]] = {}
        for j in range(n_dev):
            base = j * local_cap
            for i in range(ppd):
                p = j + i * n_dev
                if p >= nparts:
                    break
                lo, hi = base + int(starts[j, i]), base + int(ends[j, i])
                if hi > lo:
                    regions.setdefault(p, []).append((lo, hi))
    else:
        fn = _fused_regroup_fn()
        out_blocks, starts, ends = fn(page, part, nparts)
        starts = np.asarray(starts)
        ends = np.asarray(ends)
        host = [_host_block(b) for b in out_blocks]
        regions = {
            p: [(int(starts[p]), int(ends[p]))]
            for p in range(nparts)
            if int(ends[p]) > int(starts[p])
        }
    regroup_s = time.perf_counter() - t0

    out: Dict[int, List[bytes]] = {}
    counts: List[int] = []
    pages_emitted = 0
    for p in range(nparts):
        rows_p = sum(hi - lo for lo, hi in regions.get(p, ()))
        counts.append(rows_p)
        datas: List[bytes] = []
        for lo, hi in regions.get(p, ()):
            # ragged wire unit: chunks of at most page_rows LIVE rows,
            # last chunk partial — skew never pads the wire
            for start in range(lo, hi, pr):
                stop = min(start + pr, hi)
                sl = slice(start, stop)
                chunk = Page(
                    tuple(b.take_rows(sl) for b in host),
                    page.names,
                    stop - start,
                )
                datas.append(serialize_page(chunk, caps=caps, stats=stats))
        if not datas:
            # contract parity with the flat loop: an empty partition
            # still ships one (empty) page, so consumers that require at
            # least one page per source see identical streams
            empty = Page(
                tuple(b.take_rows(slice(0, 0)) for b in host),
                page.names, 0,
            )
            datas.append(serialize_page(empty, caps=caps, stats=stats))
        pages_emitted += len(datas)
        out[p] = datas
    if hier is not None:
        hier.record_batch(
            n, regroup_s, collective, pages_emitted,
            wire_padding(counts, pr),
        )
    return out
