"""Query manager + state machine.

Re-designed equivalent of the reference's coordinator query tracking:
SqlQueryManager (execution/SqlQueryManager.java:88), QueryStateMachine and
the generic listener-based StateMachine (execution/StateMachine.java:44),
and the /v1/statement paging buffer (server/protocol/Query.java:90,357).

Admission control is delegated to hierarchical resource groups
(server/resource_groups.py — reference InternalResourceGroup.run,
resourceGroups/InternalResourceGroup.java:584): submissions enter a group
chosen by user/source selectors, wait for a slot, and are executed by a
bounded worker pool. Query lifecycle events fan out to EventListeners
(server/events.py)."""

from __future__ import annotations

import dataclasses
import itertools
import queue
import threading
import time
import traceback
from typing import Dict, List, Optional

QUEUED = "QUEUED"
RUNNING = "RUNNING"
FINISHED = "FINISHED"
FAILED = "FAILED"
CANCELED = "CANCELED"

_TERMINAL = (FINISHED, FAILED, CANCELED)


@dataclasses.dataclass
class QueryInfo:
    query_id: str
    sql: str
    state: str = QUEUED
    error: Optional[str] = None
    created_at: float = dataclasses.field(default_factory=time.time)
    started_at: Optional[float] = None
    finished_at: Optional[float] = None
    columns: Optional[List[dict]] = None
    rows: Optional[List[tuple]] = None  # materialized result (root buffer)
    plan: Optional[str] = None
    user: str = "user"
    source: Optional[str] = None
    properties: dict = dataclasses.field(default_factory=dict)
    # observability (obs/): set from the QueryResult when the executing
    # session traced the query; ride the query_completed event
    trace_id: Optional[str] = None
    phase_ms: Optional[dict] = None

    @property
    def priority(self) -> int:  # query_priority scheduling policy input
        return int(self.properties.get("query_priority", 1))

    @property
    def done(self) -> bool:
        return self.state in _TERMINAL


class QueryManager:
    """Tracks every query's lifecycle; executes via the supplied session
    factory on worker threads (max_concurrent = admission control)."""

    def __init__(self, session, max_concurrent: int = 1,
                 max_history: int = 100, resource_groups: Optional[dict] = None,
                 selectors: Optional[list] = None, listeners=None,
                 access_control=None, cluster_pressure=None):
        from .events import EventBus
        from .resource_groups import ResourceGroupManager

        self.session = session
        # explicit access control covers duck-typed sessions
        # (HttpClusterSession) that cannot carry one themselves — without
        # this the manager would silently fail open for them
        self.access_control = access_control or getattr(
            session, "access_control", None
        )
        self.queries: Dict[str, QueryInfo] = {}
        self.max_history = max_history
        self._ids = itertools.count(1)
        self._lock = threading.Lock()
        self._queue: "queue.Queue[str]" = queue.Queue()
        self._events: Dict[str, threading.Event] = {}
        self.events = EventBus(listeners)
        spec = resource_groups or {
            "name": "global",
            "hard_concurrency_limit": max_concurrent,
            "max_queued": 10_000,
        }
        # cluster_pressure (typically ClusterMemoryManager.above_watermark
        # when serving an HttpClusterSession): admission refuses to start
        # queries while the cluster is above the revocation watermark
        self.groups = ResourceGroupManager(
            spec, selectors,
            dispatch=lambda info: self._queue.put(info.query_id),
            cluster_pressure=cluster_pressure,
        )
        # enough executor threads to honor the root group's concurrency;
        # beyond the thread cap, clamp the group limit so admission never
        # exceeds what can actually run (stats stay truthful)
        pool = min(max(max_concurrent, self.groups.root.hard_concurrency_limit), 32)
        if self.groups.root.hard_concurrency_limit > pool:
            import logging

            logging.getLogger("presto_tpu.server").warning(
                "clamping root hard_concurrency_limit %d to worker pool %d",
                self.groups.root.hard_concurrency_limit, pool,
            )
            self.groups.root.hard_concurrency_limit = pool
        self._workers = [
            threading.Thread(target=self._run_loop, daemon=True)
            for _ in range(pool)
        ]
        for w in self._workers:
            w.start()

    # -- submission / lifecycle --

    def submit(self, sql: str, user: str = "user",
               source: Optional[str] = None,
               properties: Optional[dict] = None) -> QueryInfo:
        from .resource_groups import QueryRejected

        with self._lock:
            qid = f"q_{next(self._ids)}"
            info = QueryInfo(
                qid, sql, user=user, source=source,
                properties=dict(properties or {}),
            )
            self.queries[qid] = info
            self._events[qid] = threading.Event()
            self._expire_locked()
        self.events.fire_created(info)
        try:
            # multi-statement transactions are SESSION-scoped (an overlay
            # catalog swapped into one Session, exec/transaction.py); the
            # REST Session is shared across clients and worker threads, so
            # a BEGIN here would entangle every client's reads and writes.
            # The reference scopes wire transactions with
            # X-Presto-Transaction handles — unsupported here, so reject
            # by PARSING (a first-token sniff is bypassed by ';'/comments)
            try:
                from ..sql import parser as _p
                from ..sql import tree as _t

                ast = _p.parse(sql)
            except Exception:  # noqa: BLE001 - surfaces at execution
                ast = None
            if isinstance(
                ast, (_t.StartTransaction, _t.Commit, _t.Rollback)
            ):
                raise QueryRejected(
                    "multi-statement transactions are not supported over "
                    "the shared REST session; use an in-process Session"
                )
            self.groups.submit(info)
        except QueryRejected as e:
            info.state = FAILED
            info.error = str(e)
            info.finished_at = time.time()
            ev = self._events.get(qid)  # may already be expired from history
            if ev is not None:
                ev.set()
            self.events.fire_completed(info)
        return info

    def _expire_locked(self):
        """Bound coordinator memory: drop the oldest completed queries
        beyond max_history (reference PurgeQueriesRunnable +
        query expiration in SqlQueryManager)."""
        done = [q for q in self.queries.values() if q.done]
        excess = len(done) - self.max_history
        if excess > 0:
            done.sort(key=lambda q: q.finished_at or 0)
            for q in done[:excess]:
                self.queries.pop(q.query_id, None)
                self._events.pop(q.query_id, None)

    def get(self, query_id: str) -> Optional[QueryInfo]:
        return self.queries.get(query_id)

    def cancel(self, query_id: str) -> bool:
        info = self.queries.get(query_id)
        if info is None:
            return False
        if info.done:
            # DELETE on a finished query purges it (result acknowledged)
            with self._lock:
                self.queries.pop(query_id, None)
                self._events.pop(query_id, None)
            return True
        # cooperative: QUEUED queries are dropped; RUNNING queries finish
        # their current kernel then observe the canceled state. The state
        # write is under the manager lock so it cannot interleave with a
        # worker's QUEUED->RUNNING transition and get lost.
        with self._lock:
            if info.done:
                return True
            was_queued = info.state == QUEUED
            info.state = CANCELED
            info.finished_at = time.time()
        if was_queued and self.groups.remove_queued(info):
            # never admitted: no slot to release
            self.events.fire_completed(info)
        ev = self._events.get(query_id)
        if ev is not None:
            ev.set()
        return True

    def wait(self, query_id: str, timeout: float) -> Optional[QueryInfo]:
        """Long-poll support (reference max-wait on statement GETs).
        None when the query was purged while waiting."""
        ev = self._events.get(query_id)
        if ev is not None:
            ev.wait(timeout)
        return self.queries.get(query_id)

    def list_queries(self) -> List[QueryInfo]:
        return list(self.queries.values())

    # -- execution --

    def _run_loop(self):
        while True:
            qid = self._queue.get()
            with self._lock:
                info = self.queries.get(qid)
                runnable = info is not None and info.state == QUEUED
                if runnable:
                    info.state = RUNNING
                    info.started_at = time.time()
            if not runnable:
                # canceled/purged after its group admitted it: the slot
                # was taken at dispatch, release it (by id — the info may
                # be gone from history)
                self.groups.finished_by_id(qid, 0.0)
                if info is not None:
                    self.events.fire_completed(info)
                continue
            try:
                session = self.session
                if info.properties and hasattr(session, "with_properties"):
                    session = session.with_properties(info.properties)
                if getattr(session, "access_control", None) is not None:
                    # the session enforces itself, as the REQUEST user
                    result = session.query(info.sql, user=info.user)
                elif self.access_control is not None:
                    # duck-typed session that cannot carry an access
                    # control: the manager enforces before executing
                    from ..security import enforce
                    from ..sql.parser import parse

                    enforce(self.access_control, info.user, parse(info.sql))
                    result = session.query(info.sql)
                else:
                    result = session.query(info.sql)
                info.columns = [
                    {"name": t, "type": str(b.type)}
                    for t, b in zip(result.titles, result.page.blocks)
                ]
                info.rows = result.rows()
                info.trace_id = getattr(result, "trace_id", None)
                info.phase_ms = getattr(result, "phase_ms", None)
                with self._lock:
                    if info.state != CANCELED:
                        info.state = FINISHED
            except Exception:  # noqa: BLE001 - query failure is data
                info.error = traceback.format_exc(limit=20)
                with self._lock:
                    if info.state != CANCELED:
                        info.state = FAILED
            info.finished_at = time.time()
            self.groups.finished(info, info.finished_at - info.started_at)
            ev = self._events.get(qid)
            if ev is not None:
                ev.set()
            self.events.fire_completed(info)
