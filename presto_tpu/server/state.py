"""Query manager + state machine.

Re-designed equivalent of the reference's coordinator query tracking:
SqlQueryManager (execution/SqlQueryManager.java:88), QueryStateMachine and
the generic listener-based StateMachine (execution/StateMachine.java:44),
and the /v1/statement paging buffer (server/protocol/Query.java:90,357).

One background executor thread per coordinator drains a submission queue
(admission control hook — the minimal resource-group analog: a bounded
number of concurrently RUNNING queries)."""

from __future__ import annotations

import dataclasses
import itertools
import queue
import threading
import time
import traceback
from typing import Dict, List, Optional

QUEUED = "QUEUED"
RUNNING = "RUNNING"
FINISHED = "FINISHED"
FAILED = "FAILED"
CANCELED = "CANCELED"

_TERMINAL = (FINISHED, FAILED, CANCELED)


@dataclasses.dataclass
class QueryInfo:
    query_id: str
    sql: str
    state: str = QUEUED
    error: Optional[str] = None
    created_at: float = dataclasses.field(default_factory=time.time)
    started_at: Optional[float] = None
    finished_at: Optional[float] = None
    columns: Optional[List[dict]] = None
    rows: Optional[List[tuple]] = None  # materialized result (root buffer)
    plan: Optional[str] = None

    @property
    def done(self) -> bool:
        return self.state in _TERMINAL


class QueryManager:
    """Tracks every query's lifecycle; executes via the supplied session
    factory on worker threads (max_concurrent = admission control)."""

    def __init__(self, session, max_concurrent: int = 1,
                 max_history: int = 100):
        self.session = session
        self.queries: Dict[str, QueryInfo] = {}
        self.max_history = max_history
        self._ids = itertools.count(1)
        self._lock = threading.Lock()
        self._queue: "queue.Queue[str]" = queue.Queue()
        self._events: Dict[str, threading.Event] = {}
        self._workers = [
            threading.Thread(target=self._run_loop, daemon=True)
            for _ in range(max_concurrent)
        ]
        for w in self._workers:
            w.start()

    # -- submission / lifecycle --

    def submit(self, sql: str) -> QueryInfo:
        with self._lock:
            qid = f"q_{next(self._ids)}"
            info = QueryInfo(qid, sql)
            self.queries[qid] = info
            self._events[qid] = threading.Event()
            self._expire_locked()
        self._queue.put(qid)
        return info

    def _expire_locked(self):
        """Bound coordinator memory: drop the oldest completed queries
        beyond max_history (reference PurgeQueriesRunnable +
        query expiration in SqlQueryManager)."""
        done = [q for q in self.queries.values() if q.done]
        excess = len(done) - self.max_history
        if excess > 0:
            done.sort(key=lambda q: q.finished_at or 0)
            for q in done[:excess]:
                self.queries.pop(q.query_id, None)
                self._events.pop(q.query_id, None)

    def get(self, query_id: str) -> Optional[QueryInfo]:
        return self.queries.get(query_id)

    def cancel(self, query_id: str) -> bool:
        info = self.queries.get(query_id)
        if info is None:
            return False
        if info.done:
            # DELETE on a finished query purges it (result acknowledged)
            with self._lock:
                self.queries.pop(query_id, None)
                self._events.pop(query_id, None)
            return True
        # cooperative: QUEUED queries are dropped; RUNNING queries finish
        # their current kernel then observe the canceled state
        info.state = CANCELED
        info.finished_at = time.time()
        ev = self._events.get(query_id)
        if ev is not None:
            ev.set()
        return True

    def wait(self, query_id: str, timeout: float) -> Optional[QueryInfo]:
        """Long-poll support (reference max-wait on statement GETs).
        None when the query was purged while waiting."""
        ev = self._events.get(query_id)
        if ev is not None:
            ev.wait(timeout)
        return self.queries.get(query_id)

    def list_queries(self) -> List[QueryInfo]:
        return list(self.queries.values())

    # -- execution --

    def _run_loop(self):
        while True:
            qid = self._queue.get()
            info = self.queries.get(qid)
            if info is None or info.state != QUEUED:
                continue  # canceled/purged while queued
            info.state = RUNNING
            info.started_at = time.time()
            try:
                result = self.session.query(info.sql)
                info.columns = [
                    {"name": t, "type": str(b.type)}
                    for t, b in zip(result.titles, result.page.blocks)
                ]
                info.rows = result.rows()
                if info.state != CANCELED:
                    info.state = FINISHED
            except Exception:  # noqa: BLE001 - query failure is data
                info.error = traceback.format_exc(limit=20)
                if info.state != CANCELED:
                    info.state = FAILED
            info.finished_at = time.time()
            ev = self._events.get(qid)
            if ev is not None:
                ev.set()
