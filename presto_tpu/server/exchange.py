"""Pipelined concurrent exchange client for the pull-based HTTP shuffle.

Re-designed equivalent of the reference's ExchangeClient +
HttpPageBufferClient (presto-main/.../operator/ExchangeClient.java:55 —
one concurrent HTTP client per upstream task, responses bounded by
`exchange.max-response-size`, pages landing in a bounded buffer the
operator drains). The previous implementation pulled producer buffers
SEQUENTIALLY on the consumer thread (server/cluster.py round-5 review):
with N producers the wire was idle N-1/N of the time and deserialization
serialized behind the network.

Shape here:

* one **puller thread per producer location**, each long-polling
  `GET /v1/task/{id}/results/{buffer}/{token}?max_bytes=B` — the worker
  packs as many already-produced pages as fit the `max_response_bytes`
  budget into one response (the `exchange.max-response-size` analog);
* a **bounded staging deque** (bytes-bounded) between pullers and the
  consumer: pullers block when staging is full, which stops their pulls,
  which backpressures the producer's bounded output buffer — end-to-end
  flow control with no unbounded queue anywhere;
* pages are **acknowledged as they are staged** (DELETE up to token),
  freeing producer budget while the consumer is still decoding earlier
  pages — the ack IS the backpressure release;
* **deserialization overlaps the network**: the consumer thread decodes
  while every puller has the next response in flight.

Failure semantics match `_pull_buffer`: upstream failures surface as
RuntimeError with the upstream cause in the message (the coordinator's
retry classifier matches on it), annotated with the failing location.
"""

from __future__ import annotations

import base64
import json
import os
import threading
import time
import urllib.error
import urllib.request
from collections import deque
from typing import Callable, List, Optional, Sequence, Tuple

from . import knobs
from .serde import WireStats, deserialize_page


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


DEFAULT_MAX_RESPONSE_BYTES = _env_int(
    "PRESTO_TPU_MAX_RESPONSE_BYTES", 8 << 20
)
DEFAULT_STAGING_BYTES = _env_int(
    "PRESTO_TPU_EXCHANGE_STAGING_BYTES", 64 << 20
)
DEFAULT_CONCURRENCY = _env_int("PRESTO_TPU_EXCHANGE_CONCURRENCY", 16)


class ExchangeError(RuntimeError):
    """A pull failed. Carries the failing location so the scheduler can
    attribute the failure (blacklist streaks, query retry)."""

    def __init__(self, message: str, uri: str = "", task_id: str = ""):
        super().__init__(message)
        self.uri = uri
        self.task_id = task_id


class ExchangeStats:
    """Observable pull-side accounting (acceptance: concurrency must be
    visible, not inferred from timing). `peak_concurrent` is the high
    water of simultaneously ALIVE pullers; `peak_inflight` counts
    overlapping HTTP requests."""

    def __init__(self):
        self._lock = threading.Lock()
        self.pages = 0
        self.wire_bytes = 0
        self.responses = 0
        self.pull_s = 0.0
        self.decode_s = 0.0
        self.sources = 0
        self.active = 0
        self.peak_concurrent = 0
        self.inflight = 0
        self.peak_inflight = 0
        self.by_source: dict = {}
        # overlap accounting (hierarchical exchange): `consumer_wait_s`
        # is the wall the consumer spent BLOCKED on an empty staging
        # deque — wire time the prefetch failed to hide. pull_s minus it
        # is the wire wall hidden behind the consumer's device compute.
        self.consumer_wait_s = 0.0

    def add_sources(self, n: int) -> None:
        """Locked source-count bump: one stats object may span several
        clients (a task with many sources), whose __init__ runs on task
        threads while the scheduler snapshots — the += must not tear."""
        with self._lock:
            self.sources += int(n)

    def consumer_waited(self, seconds: float) -> None:
        with self._lock:
            self.consumer_wait_s += seconds

    def puller_started(self) -> None:
        with self._lock:
            self.active += 1
            self.peak_concurrent = max(self.peak_concurrent, self.active)

    def puller_finished(self) -> None:
        with self._lock:
            self.active -= 1

    def request_started(self) -> None:
        with self._lock:
            self.inflight += 1
            self.peak_inflight = max(self.peak_inflight, self.inflight)

    def request_finished(self, seconds: float) -> None:
        with self._lock:
            self.inflight -= 1
            self.responses += 1
            self.pull_s += seconds

    def pages_staged(self, source: str, count: int, nbytes: int) -> None:
        with self._lock:
            self.pages += count
            self.wire_bytes += nbytes
            self.by_source[source] = self.by_source.get(source, 0) + count

    def page_decoded(self, seconds: float) -> None:
        with self._lock:
            self.decode_s += seconds

    def snapshot(self) -> dict:
        """One consistent snapshot under the stats lock: the scheduler
        and the worker status endpoint read this while pullers mutate
        counters, so every field (including the derived overlap numbers)
        comes from a single locked read — pages always equals the
        by_source sum, hidden_ms is never computed from a torn pair."""
        with self._lock:
            pull_ms = round(self.pull_s * 1e3, 2)
            wait_ms = round(self.consumer_wait_s * 1e3, 2)
            hidden_ms = round(max(pull_ms - wait_ms, 0.0), 2)
            return {
                "pages": self.pages,
                "wire_bytes": self.wire_bytes,
                "responses": self.responses,
                "sources": self.sources,
                "peak_concurrent": self.peak_concurrent,
                "peak_inflight": self.peak_inflight,
                "pull_ms": pull_ms,
                "decode_ms": round(self.decode_s * 1e3, 2),
                "consumer_wait_ms": wait_ms,
                "hidden_ms": hidden_ms,
                "overlap_frac": round(hidden_ms / pull_ms, 3)
                if pull_ms > 0 else 0.0,
                "by_source": dict(self.by_source),
            }


def fetch_pages(
    uri: str,
    task_id: str,
    buffer_id: int,
    token: int,
    max_bytes: Optional[int] = None,
    timeout: float = 300.0,
) -> Tuple[List[bytes], bool, bool]:
    """One results request: (pages, complete, ready). ready=False means
    the producer has nothing at `token` yet (HTTP 503 long-poll miss).
    Raises RuntimeError with the upstream cause on failure — the message
    shapes the coordinator's retryable/fatal classification."""
    url = f"{uri}/v1/task/{task_id}/results/{buffer_id}/{token}"
    if max_bytes:
        url += f"?max_bytes={int(max_bytes)}"
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            payload = json.loads(resp.read())
    except urllib.error.HTTPError as e:
        if e.code == 503:
            return [], False, False
        # surface the UPSTREAM failure cause (e.g. a low-memory kill),
        # not a bare HTTP 500 — the coordinator matches on the message
        # (reference: HttpPageBufferClient propagates the task error)
        try:
            detail = json.loads(e.read()).get("error") or str(e)
        except Exception:  # noqa: BLE001 — body parse is best-effort
            # detail; the ExchangeError below carries the failure anyway
            detail = str(e)
        raise ExchangeError(
            f"upstream task {task_id} on {uri} results fetch "
            f"failed: {detail}",
            uri=uri, task_id=task_id,
        ) from None
    except (urllib.error.URLError, ConnectionError, OSError) as e:
        # a worker dying mid-stream must surface as a RETRYABLE
        # RuntimeError (the query-level retry contract), never as a
        # raw URLError that escapes the scheduler's retry handler
        raise ExchangeError(
            f"upstream task {task_id} on {uri} connection lost "
            f"mid-stream: {e}",
            uri=uri, task_id=task_id,
        ) from None
    if payload.get("pages") is not None:
        pages = [base64.b64decode(p) for p in payload["pages"]]
    elif payload.get("page"):
        pages = [base64.b64decode(payload["page"])]
    else:
        pages = []
    # an old worker answers without "pages"; an empty single-page answer
    # with complete unset means long-poll timed out server-side
    ready = bool(pages) or bool(payload.get("complete", not pages))
    return pages, bool(payload.get("complete", not pages)), ready


def ack_pages(uri: str, task_id: str, buffer_id: int, upto_token: int) -> None:
    """Acknowledge pages [0, upto_token) — frees the producer's bounded
    buffer budget. Advisory: a lost ack only delays the free."""
    try:
        req = urllib.request.Request(
            f"{uri}/v1/task/{task_id}/results/{buffer_id}/{upto_token}",
            method="DELETE",
        )
        urllib.request.urlopen(req, timeout=5).read()
    except Exception:  # noqa: BLE001 - ack is advisory
        pass


def _page_nbytes(page) -> int:
    """Decoded footprint of a Page: every array a Block carries."""
    total = 0
    stack = list(getattr(page, "blocks", ()))
    while stack:
        b = stack.pop()
        for arr in (b.data, b.valid, b.lengths, b.elem_valid):
            if arr is not None:
                total += arr.size * arr.dtype.itemsize
        if b.key_block is not None:
            stack.append(b.key_block)
    return total


class ExchangeClient:
    """Concurrent pull over a set of producer buffer locations.

    `locations` is a sequence of (uri, task_id, buffer_id). `pages()`
    yields deserialized Pages in ARRIVAL order — per-location token order
    is preserved, interleaving across locations is whatever the network
    delivers (exchange consumers are order-insensitive across producers,
    exactly like the reference's ExchangeOperator)."""

    def __init__(
        self,
        locations: Sequence[Tuple[str, str, int]],
        ack: bool = True,
        max_response_bytes: Optional[int] = None,
        staging_bytes: Optional[int] = None,
        deadline: Optional[float] = None,
        concurrency: Optional[int] = None,
        stats: Optional[ExchangeStats] = None,
        wire_stats: Optional[WireStats] = None,
        decode: Optional[Callable] = None,
        decode_in_pullers: bool = True,
    ):
        self.locations = list(locations)
        self.ack = ack
        self.max_response_bytes = (
            DEFAULT_MAX_RESPONSE_BYTES
            if max_response_bytes is None
            else max_response_bytes
        )
        self.staging_bytes = (
            DEFAULT_STAGING_BYTES if staging_bytes is None else staging_bytes
        )
        # hierarchical-exchange tranche prefetch: guarantee each puller
        # can keep PRESTO_TPU_HIER_EXCHANGE_PREFETCH max-size responses
        # staged ahead of the consumer, so the next inter-host tranche
        # is on the wire while the current one's device-side collective
        # runs — the staging budget is a floor here, never a shrink
        prefetch = max(knobs.hier_exchange_prefetch(), 0)
        if prefetch:
            self.staging_bytes = max(
                self.staging_bytes,
                prefetch * self.max_response_bytes * max(len(self.locations), 1),
            )
        if deadline is None:
            deadline = knobs.task_deadline_s()
        self.deadline = deadline
        self.concurrency = max(
            1, DEFAULT_CONCURRENCY if concurrency is None else concurrency
        )
        self.stats = stats or ExchangeStats()
        self.stats.add_sources(len(self.locations))
        # decode on the puller threads: deserialization parallelizes
        # across producers AND overlaps the consumer (numpy/stripe
        # decompression release the GIL). Off = stage raw bytes and
        # decode lazily on the consumer thread.
        self.decode_in_pullers = decode_in_pullers
        self.wire_stats = wire_stats
        self._decode = decode or deserialize_page
        self._cond = threading.Condition()
        self._staged: deque = deque()  # (loc_index, bytes)
        self._staged_bytes = 0
        self._done = 0
        self._error: Optional[ExchangeError] = None
        self._stop = threading.Event()
        self._sem = threading.Semaphore(self.concurrency)
        self._threads: List[threading.Thread] = []
        self._started = False

    # -- pull side --

    def _stage(self, idx: int, pages: List[bytes]) -> None:
        # page-at-a-time: decode (outside the lock), then admit against
        # the staging budget accounted at DECODED size — the light-weight
        # encodings routinely achieve 5-50x ratios, so bounding by wire
        # bytes while holding decoded Pages would amplify the bound by
        # the compression ratio. A blocked puller holds at most the one
        # page it just decoded.
        for p in pages:
            dec = None
            nbytes = len(p)
            if self.decode_in_pullers:
                t0 = time.perf_counter()
                dec = self._decode(p)
                dt = time.perf_counter() - t0
                self.stats.page_decoded(dt)
                if self.wire_stats is not None:
                    self.wire_stats.record_decode(len(p), dt)
                nbytes = max(nbytes, _page_nbytes(dec))
            with self._cond:
                # bounded staging: block while full (unless empty — one
                # page must always be admissible or nothing moves)
                while (
                    self._staged_bytes + nbytes > self.staging_bytes
                    and self._staged
                    and not self._stop.is_set()
                ):
                    self._cond.wait(timeout=0.1)
                if self._stop.is_set():
                    return
                self._staged.append((idx, p, dec, nbytes))
                self._staged_bytes += nbytes
                self._cond.notify_all()
        uri, task, _buf = self.locations[idx]
        self.stats.pages_staged(
            f"{uri}/{task}", len(pages), sum(len(p) for p in pages)
        )

    def _pull_one(self, idx: int) -> None:
        uri, task, buf = self.locations[idx]
        token = 0
        give_up = time.time() + self.deadline
        while not self._stop.is_set():
            t0 = time.perf_counter()
            self.stats.request_started()
            try:
                pages, complete, ready = fetch_pages(
                    uri, task, buf, token,
                    max_bytes=self.max_response_bytes,
                )
            finally:
                self.stats.request_finished(time.perf_counter() - t0)
            if pages:
                token += len(pages)
                self._stage(idx, pages)
                # ack AFTER staging admitted the bytes: the bounded
                # staging deque is the consumer-side half of the
                # backpressure loop, the ack releases the producer half
                if self.ack:
                    ack_pages(uri, task, buf, token)
                give_up = time.time() + self.deadline  # progress
            if complete:
                return
            if not ready and not pages:
                # `deadline` caps the wall time between PAGES (a progress
                # deadline): a wedged producer (RUNNING forever,
                # producing nothing) must fail the pull — retryably —
                # instead of hanging its consumer forever
                if time.time() >= give_up:
                    raise ExchangeError(
                        f"upstream task {task} on {uri} produced no page "
                        f"within the {self.deadline:.0f}s task deadline "
                        "(wedged worker?)",
                        uri=uri, task_id=task,
                    )

    def _run_puller(self, idx: int) -> None:
        with self._sem:  # bound total concurrent pullers
            self.stats.puller_started()
            try:
                self._pull_one(idx)
            except ExchangeError as e:
                with self._cond:
                    if self._error is None:
                        self._error = e
                    self._cond.notify_all()
            except Exception as e:  # noqa: BLE001 - never die silently
                uri, task, _b = self.locations[idx]
                with self._cond:
                    if self._error is None:
                        self._error = ExchangeError(
                            f"upstream task {task} on {uri} pull failed: "
                            f"{e!r}",
                            uri=uri, task_id=task,
                        )
                    self._cond.notify_all()
            finally:
                self.stats.puller_finished()
                with self._cond:
                    self._done += 1
                    self._cond.notify_all()

    def start(self) -> "ExchangeClient":
        if self._started:
            return self
        self._started = True
        for i in range(len(self.locations)):
            t = threading.Thread(
                target=self._run_puller, args=(i,), daemon=True,
                name=f"ptpu-pull-{i}",
            )
            self._threads.append(t)
            t.start()
        return self

    def close(self) -> None:
        self._stop.set()
        with self._cond:
            self._cond.notify_all()

    # -- consume side --

    def _drain(self):
        """Yield staged (location_index, bytes, decoded-or-None) in
        arrival order. Raises the first puller failure once staged pages
        drain — pages already pulled are still delivered (acked)."""
        self.start()
        try:
            while True:
                with self._cond:
                    # time the consumer spends HERE with an empty deque
                    # is wire latency the prefetch failed to hide; time
                    # between _drain calls is the consumer's device
                    # compute, which the pullers' in-flight tranches
                    # overlap. pull_s - consumer_wait_s = hidden wall.
                    waited = time.perf_counter()
                    while (
                        not self._staged
                        and self._error is None
                        and self._done < len(self.locations)
                    ):
                        self._cond.wait(timeout=0.5)
                    waited = time.perf_counter() - waited
                    if self._staged:
                        idx, data, dec, nbytes = self._staged.popleft()
                        self._staged_bytes -= nbytes
                        self._cond.notify_all()
                    elif self._error is not None:
                        self.stats.consumer_waited(waited)
                        raise self._error
                    else:
                        self.stats.consumer_waited(waited)
                        return
                self.stats.consumer_waited(waited)
                yield idx, data, dec
        finally:
            self.close()

    def raw_pages(self):
        """Yield (location_index, serialized_page_bytes) in arrival
        order."""
        for idx, data, _dec in self._drain():
            yield idx, data

    def pages(self):
        """Yield deserialized Pages. With decode_in_pullers (default)
        pages arrive pre-decoded — deserialization ran concurrently on
        the puller threads, overlapped with in-flight pulls; otherwise
        the consumer decodes while pullers fetch ahead."""
        for _idx, data, dec in self._drain():
            if dec is not None:
                yield dec
                continue
            t0 = time.perf_counter()
            page = self._decode(data)
            dt = time.perf_counter() - t0
            self.stats.page_decoded(dt)
            if self.wire_stats is not None:
                self.wire_stats.record_decode(len(data), dt)
            yield page
