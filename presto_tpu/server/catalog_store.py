"""Static catalog store + connector-factory registry — the server
bootstrap's catalog loading.

Re-designed equivalent of the reference's PluginManager +
StaticCatalogStore (presto-main/.../metadata/StaticCatalogStore.java:45
loadCatalogs; server/PluginManager.java): every `<name>.properties` file
in a catalog directory declares one catalog; `connector.name` selects the
connector factory and the remaining keys are that connector's config.
Third-party "plugins" register factories at import time via
`register_connector` (the ConnectorFactory SPI analog — no classloader
isolation: one process, one interpreter).

Loaded catalogs mount under their file-stem name: a table is reachable
bare (first catalog wins, MultiCatalog flat federation) or qualified as
`catalog.table` / `catalog.default.table`, matching the reference's
MetadataManager qualified-name resolution.

Example::

    etc/catalog/tpch.properties:
        connector.name=tpch
        tpch.scale-factor=0.1
    etc/catalog/files.properties:
        connector.name=localfile
        localfile.data-dir=/data/csvs

    cat = load_catalog_store("etc/catalog")
    Session(cat).query("select count(*) from tpch.lineitem")
"""

from __future__ import annotations

import glob
import os
from typing import Callable, Dict, List

from ..connectors.jdbc import MultiCatalog
from ..connectors.spi import Connector

Factory = Callable[[Dict[str, str]], Connector]
_FACTORIES: Dict[str, Factory] = {}


def register_connector(name: str, factory: Factory) -> None:
    """ConnectorFactory registration (Plugin.getConnectorFactories analog)."""
    _FACTORIES[name] = factory


def connector_names() -> List[str]:
    return sorted(_FACTORIES)


def _f_tpch(props):
    sf = float(props.get("tpch.scale-factor", 1.0))
    if props.get("tpch.device-generated", "").lower() in ("true", "1"):
        from ..connectors.tpch_device import DeviceTpchCatalog

        return DeviceTpchCatalog(sf=sf)
    from ..connectors.tpch import TpchCatalog

    return TpchCatalog(sf=sf)


def _f_tpcds(props):
    from ..connectors.tpcds import TpcdsCatalog

    return TpcdsCatalog(sf=float(props.get("tpcds.scale-factor", 1.0)))


def _f_memory(props):
    from ..connectors.memory import MemoryCatalog

    return MemoryCatalog({})


def _f_localfile(props):
    from ..connectors.localfile import LocalFileCatalog

    return LocalFileCatalog(props["localfile.data-dir"])


def _f_hive(props):
    from ..connectors.hive import HiveCatalog

    return HiveCatalog(props["hive.warehouse-dir"])


def _f_sqlite(props):
    from ..connectors.jdbc import SqliteCatalog

    url = props.get("connection-url", ":memory:")
    if url.startswith("jdbc:sqlite:"):  # accept the reference's URL shape
        url = url[len("jdbc:sqlite:"):]
    return SqliteCatalog(url)


def _f_blackhole(props):
    from ..connectors.blackhole import BlackHoleCatalog

    return BlackHoleCatalog()


def _f_shardstore(props):
    from ..connectors.shardstore import ShardStoreCatalog

    return ShardStoreCatalog(props["shardstore.data-dir"])


for _n, _f in (
    ("tpch", _f_tpch),
    ("tpcds", _f_tpcds),
    ("memory", _f_memory),
    ("localfile", _f_localfile),
    ("hive", _f_hive),
    ("sqlite", _f_sqlite),
    ("blackhole", _f_blackhole),
    ("shardstore", _f_shardstore),
):
    register_connector(_n, _f)


def parse_properties(path: str) -> Dict[str, str]:
    """Minimal java-properties subset: key=value lines, # / ! comments,
    trailing whitespace stripped (what catalog files actually use)."""
    props: Dict[str, str] = {}
    with open(path, "r", encoding="utf-8") as f:
        for raw in f:
            line = raw.strip()
            if not line or line[0] in "#!":
                continue
            if "=" not in line:
                raise ValueError(f"{path}: malformed line {raw!r}")
            k, _, v = line.partition("=")
            props[k.strip()] = v.strip()
    return props


class CatalogStore(MultiCatalog):
    """Named federation: tables resolve bare (first catalog wins) or as
    `catalog.table` (registered as dotted names, which the planner's
    qualified-name resolution already accepts)."""

    name = "catalogs"

    def __init__(self, catalogs: Dict[str, Connector]):
        super().__init__(list(catalogs.values()))
        self.catalogs = dict(catalogs)

    def _owner_and_table(self, table: str):
        if "." in table:
            cat, _, rest = table.partition(".")
            m = self.catalogs.get(cat)
            if m is not None:
                if rest.startswith("default."):
                    rest = rest[len("default."):]
                if rest in m.table_names():
                    return m, rest
        for m in self.members:
            if table in m.table_names():
                return m, table
        raise KeyError(f"unknown table {table!r}")

    # -- Connector surface, routed through qualified resolution --
    def table_names(self) -> List[str]:
        out: List[str] = []
        for cname, m in self.catalogs.items():
            for t in m.table_names():
                out.append(f"{cname}.{t}")
                if t not in out:
                    out.append(t)
        return out

    def _owner(self, table: str):  # MultiCatalog hook
        return self._owner_and_table(table)[0]

    def schema(self, table: str):
        m, t = self._owner_and_table(table)
        return m.schema(t)

    def row_count(self, table: str) -> int:
        m, t = self._owner_and_table(table)
        return m.row_count(t)

    def exact_row_count(self, table: str) -> int:
        m, t = self._owner_and_table(table)
        return m.exact_row_count(t)

    def unique_columns(self, table: str):
        m, t = self._owner_and_table(table)
        return m.unique_columns(t)

    def column_stats(self, table: str, column: str):
        m, t = self._owner_and_table(table)
        return m.column_stats(t, column)

    def table_version(self, table: str):
        # snapshot versions route to the owning catalog; catalogs without
        # versioning stay uncacheable (exec/qcache.py)
        m, t = self._owner_and_table(table)
        fn = getattr(m, "table_version", None)
        return None if fn is None else fn(t)

    def page(self, table: str):
        m, t = self._owner_and_table(table)
        return m.page(t)

    def scan(self, table: str, start: int, stop: int, pad_to=None,
             columns=None, predicate=None):
        m, t = self._owner_and_table(table)
        return m.scan(t, start, stop, pad_to=pad_to, columns=columns,
                      predicate=predicate)

    def supports_index(self, table: str, column: str) -> bool:
        m, t = self._owner_and_table(table)
        fn = getattr(m, "supports_index", None)
        return bool(fn and fn(t, column))

    def index_lookup(self, table: str, column: str, keys, columns):
        m, t = self._owner_and_table(table)
        return m.index_lookup(t, column, keys, columns)


def load_catalog_store(directory: str) -> CatalogStore:
    """Boot every `<name>.properties` in `directory` (StaticCatalogStore
    .loadCatalogs analog: file stem = catalog name)."""
    catalogs: Dict[str, Connector] = {}
    for path in sorted(glob.glob(os.path.join(directory, "*.properties"))):
        cname = os.path.splitext(os.path.basename(path))[0]
        props = parse_properties(path)
        conn_name = props.get("connector.name")
        if not conn_name:
            raise ValueError(f"{path}: missing connector.name")
        factory = _FACTORIES.get(conn_name)
        if factory is None:
            raise ValueError(
                f"{path}: unknown connector {conn_name!r} "
                f"(registered: {', '.join(connector_names())})"
            )
        catalogs[cname] = factory(props)
    if not catalogs:
        raise ValueError(f"no *.properties catalogs in {directory!r}")
    return CatalogStore(catalogs)
