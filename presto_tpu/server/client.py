"""REST protocol client.

Re-designed equivalent of the reference's client library
(presto-client/.../StatementClientV1.java + QueryResults nextUri paging,
presto-cli's transport): POST the statement, follow nextUri until the
terminal state, yield rows. stdlib urllib — no dependencies."""

from __future__ import annotations

import json
import time
import urllib.request
from typing import Iterator, List, Optional, Tuple


class QueryError(RuntimeError):
    pass


class Client:
    def __init__(self, server_uri: str, timeout: float = 30.0,
                 user: Optional[str] = None, password: Optional[str] = None,
                 cafile: Optional[str] = None,
                 max_retries: int = 8, backoff_base: float = 0.05,
                 backoff_cap: float = 2.0):
        """user/password: Basic credentials for an authenticating
        coordinator; cafile: CA bundle pinning an https coordinator
        (reference StatementClient auth + OkHttp TLS setup).

        max_retries / backoff_base / backoff_cap bound the capped
        exponential backoff applied to `503 {"retry": true}` responses
        (a worker/coordinator saying "not ready yet, poll again" —
        server/worker.py results long-poll); a transient connection
        reset is additionally retried once."""
        self.server = server_uri.rstrip("/")
        self.timeout = timeout
        self.user = user
        self.password = password
        self.max_retries = max_retries
        self.backoff_base = backoff_base
        self.backoff_cap = backoff_cap
        self._ssl_ctx = None
        if self.server.startswith("https"):
            from .auth import client_ssl_context

            self._ssl_ctx = client_ssl_context(cafile)

    def _request(self, method: str, url: str, body: Optional[bytes] = None):
        import urllib.error

        retries = 0
        transient_retried = False
        while True:
            req = urllib.request.Request(url, data=body, method=method)
            if self.user is not None and self.password is not None:
                from .auth import basic_auth_header

                req.add_header(
                    "Authorization",
                    basic_auth_header(self.user, self.password),
                )
            try:
                with urllib.request.urlopen(
                    req, timeout=self.timeout, context=self._ssl_ctx
                ) as resp:
                    return json.loads(resp.read())
            except urllib.error.HTTPError as e:
                # coordinator errors carry JSON bodies (404 unknown query,
                # 503 draining) — surface them as QueryError, not HTTPError
                try:
                    payload = json.loads(e.read())
                except Exception:  # noqa: BLE001 — a non-JSON error body
                    # becomes the QueryError message itself
                    payload = {"error": str(e)}
                if (
                    e.code == 503
                    and isinstance(payload, dict)
                    and payload.get("retry")
                    and retries < self.max_retries
                ):
                    # "not ready yet" — NOT an error: back off and repoll
                    time.sleep(
                        min(self.backoff_base * (2 ** retries),
                            self.backoff_cap)
                    )
                    retries += 1
                    continue
                if isinstance(payload, dict) and "canceled" in payload:
                    return payload
                raise QueryError(
                    f"{e.code}: {payload.get('error', payload)}"
                ) from None
            except (ConnectionResetError, urllib.error.URLError) as e:
                # one transient-network retry (reference OkHttp
                # retryOnConnectionFailure): a coordinator restarting its
                # accept loop or a dropped keep-alive connection. A POST
                # is only re-sent when the connection was REFUSED (no
                # bytes reached the server) — a reset mid-exchange could
                # mean the statement was already accepted, and a blind
                # re-send would execute the query twice.
                refused = isinstance(
                    getattr(e, "reason", e), ConnectionRefusedError
                )
                idempotent = method in ("GET", "DELETE", "HEAD", "PUT")
                if not transient_retried and (idempotent or refused):
                    transient_retried = True
                    time.sleep(self.backoff_base)
                    continue
                raise QueryError(f"connection failed: {e}") from None

    def execute(self, sql: str) -> Tuple[List[dict], List[list]]:
        """Run to completion; returns (columns, rows)."""
        cols: List[dict] = []
        rows: List[list] = []
        payload = self._request(
            "POST", f"{self.server}/v1/statement", sql.encode()
        )
        while True:
            if "error" in payload:
                raise QueryError(str(payload["error"].get("message")))
            if payload.get("columns"):
                cols = payload["columns"]
            rows.extend(payload.get("data", []))
            nxt = payload.get("nextUri")
            if nxt is None:
                return cols, rows
            payload = self._request("GET", nxt + "?maxWait=5")

    def cancel(self, query_id: str) -> bool:
        out = self._request(
            "DELETE", f"{self.server}/v1/statement/{query_id}"
        )
        return bool(out.get("canceled"))

    def queries(self) -> List[dict]:
        return self._request("GET", f"{self.server}/v1/query")

    def node_info(self) -> dict:
        return self._request("GET", f"{self.server}/v1/info")
