"""Interactive SQL CLI.

Equivalent of the reference's presto-cli (presto-cli/src/main/java/com/
facebook/presto/cli/ — jline REPL, table rendering, timing). Runs against
an in-process Session by default; `--server` mode (HTTP client against a
coordinator) arrives with the server layer.

Usage:
  python -m presto_tpu.cli                 # REPL on tpch sf0.01
  python -m presto_tpu.cli --sf 1 "SELECT ...;"
  python -m presto_tpu.cli --server http://host:port "SELECT ...;"
  python -m presto_tpu.cli --serve --port 8080   # start a coordinator
"""

from __future__ import annotations

import argparse
import sys
import time


def _render(rows, titles, max_rows: int = 200) -> str:
    cells = [[_fmt(v) for v in r] for r in rows[:max_rows]]
    widths = [len(t) for t in titles]
    for r in cells:
        for i, c in enumerate(r):
            widths[i] = max(widths[i], len(c))
    sep = "-+-".join("-" * w for w in widths)
    out = [" | ".join(t.ljust(w) for t, w in zip(titles, widths)), sep]
    for r in cells:
        out.append(" | ".join(c.rjust(w) for c, w in zip(r, widths)))
    if len(rows) > max_rows:
        out.append(f"... ({len(rows) - max_rows} more rows)")
    return "\n".join(out)


def _fmt(v) -> str:
    if v is None:
        return "NULL"
    return str(v)




def split_statements(text: str):
    """Split a multi-statement string on top-level semicolons (respects
    single/double-quoted spans — the reference CLI's --execute accepts
    'stmt; stmt; ...')."""
    out, buf, q = [], [], None
    i, n = 0, len(text)
    while i < n:
        c = text[i]
        if q:
            buf.append(c)
            if c == q:
                if i + 1 < n and text[i + 1] == q:  # escaped quote
                    buf.append(text[i + 1])
                    i += 1
                else:
                    q = None
        elif c in ("'", '"'):
            q = c
            buf.append(c)
        elif c == ";":
            if "".join(buf).strip():
                out.append("".join(buf).strip())
            buf = []
        else:
            buf.append(c)
        i += 1
    if "".join(buf).strip():
        out.append("".join(buf).strip())
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(prog="presto-tpu")
    ap.add_argument("query", nargs="?", help="SQL to run (REPL if omitted)")
    ap.add_argument("--sf", type=float, default=0.01, help="tpch/tpcds scale factor")
    ap.add_argument(
        "--catalog", default="tpch",
        help="tpch | tpcds | memory | a directory of csv/tsv/jsonl files",
    )
    ap.add_argument(
        "--catalog-dir",
        help="directory of <name>.properties catalog files (server-style "
        "bootstrap; tables reachable bare or as <name>.<table>)",
    )
    ap.add_argument("--server", help="coordinator URI (remote REST mode)")
    ap.add_argument("--serve", action="store_true",
                    help="start a coordinator server instead of a REPL")
    ap.add_argument("--port", type=int, default=8080)
    args = ap.parse_args(argv)

    import os

    from .session import Session

    def build_catalog():
        # only the --serve and local-REPL paths need one; remote mode
        # must not validate a path that exists only on the coordinator
        if args.catalog_dir:
            from .server.catalog_store import load_catalog_store

            return load_catalog_store(args.catalog_dir)
        if args.catalog == "tpch":
            from .connectors.tpch import TpchCatalog

            return TpchCatalog(sf=args.sf)
        if args.catalog == "tpcds":
            from .connectors.tpcds import TpcdsCatalog

            return TpcdsCatalog(sf=args.sf)
        if args.catalog == "memory":
            from .connectors.memory import MemoryCatalog

            return MemoryCatalog({})
        if os.path.isdir(args.catalog):
            from .connectors.localfile import LocalFileCatalog

            return LocalFileCatalog(args.catalog)
        ap.error(
            f"unknown catalog {args.catalog!r} "
            "(tpch | tpcds | memory | directory path)"
        )

    def banner_name():
        if args.catalog in ("tpch", "tpcds"):
            return f"{args.catalog} sf{args.sf:g}"
        return args.catalog

    if os.environ.get("JAX_PLATFORMS") and not args.server:
        # the axon sitecustomize overrides the env var before we run;
        # restore its meaning for every mode that builds a local Session
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    if args.serve:
        from .server import CoordinatorServer

        server = CoordinatorServer(
            Session(build_catalog()), port=args.port
        ).start()
        print(f"coordinator listening on {server.uri} ({banner_name()})")
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            server.stop()
        return

    if args.server:
        from .server import Client

        client = Client(args.server)

        def run_remote(sql: str):
            sql = sql.strip().rstrip(";")
            if not sql:
                return
            t0 = time.perf_counter()
            cols, rows = client.execute(sql)
            dt = time.perf_counter() - t0
            print(_render(rows, [c["name"] for c in cols]))
            print(f"({len(rows)} rows in {dt:.2f}s)")

        if args.query:
            for stmt in split_statements(args.query):
                run_remote(stmt)
            return
        print(f"presto-tpu CLI — remote {args.server}. End statements with ';'.")
        buf = []
        while True:
            try:
                line = input("presto> " if not buf else "     -> ")
            except (EOFError, KeyboardInterrupt):
                print()
                return
            if line.strip().lower() in ("quit", "exit"):
                return
            buf.append(line)
            if line.rstrip().endswith(";"):
                sql = "\n".join(buf)
                buf = []
                try:
                    run_remote(sql)
                except Exception as e:
                    print(f"error: {e}", file=sys.stderr)
        return

    session = Session(build_catalog())

    def run_one(sql: str):
        sql = sql.strip().rstrip(";")
        if not sql:
            return
        low = sql.lower()
        t0 = time.perf_counter()
        if low.startswith("explain"):
            print(session.explain(sql))
            return
        if low == "show tables":
            for t in session.catalog.table_names():
                print(t)
            return
        if low.startswith("show columns from "):
            tname = sql.split()[-1]
            for c, ty in session.catalog.schema(tname).items():
                print(f"{c:24s} {ty}")
            return
        r = session.query(sql)
        dt = time.perf_counter() - t0
        print(_render(r.rows(), r.titles))
        print(f"({r.row_count()} rows in {dt:.2f}s)")

    if args.query:
        for stmt in split_statements(args.query):
            run_one(stmt)
        return

    print(f"presto-tpu CLI — {banner_name()}. End statements with ';'.")
    buf = []
    while True:
        try:
            prompt = "presto> " if not buf else "     -> "
            line = input(prompt)
        except (EOFError, KeyboardInterrupt):
            print()
            return
        if line.strip().lower() in ("quit", "exit"):
            return
        buf.append(line)
        if line.rstrip().endswith(";"):
            sql = "\n".join(buf)
            buf = []
            try:
                run_one(sql)
            except Exception as e:  # keep the REPL alive
                print(f"error: {e}", file=sys.stderr)


if __name__ == "__main__":
    main()
