from .ir import (  # noqa: F401
    Call,
    ColumnRef,
    Literal,
    RowExpression,
    and_,
    between,
    binary,
    call,
    cast,
    col,
    comparison,
    if_,
    in_list,
    is_null,
    like,
    lit,
    not_,
    or_,
)
from .compiler import compile_projection, evaluate, project_page  # noqa: F401
from .functions import Val, infer_call_type  # noqa: F401
