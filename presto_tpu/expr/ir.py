"""Row-expression IR.

Re-designed equivalent of the reference's RowExpression layer
(presto-main/src/main/java/com/facebook/presto/sql/relational/RowExpression.java
and SqlToRowExpressionTranslator.java). The analyzer produces *typed* nodes;
expr/compiler.py traces them into fused jax functions — the TPU answer to the
reference's runtime bytecode generation (sql/gen/ExpressionCompiler.java:93).

Only three node kinds, like the reference (InputReference / ConstantExpression /
CallExpression): special forms (AND/OR/IF/...) are Calls with reserved names,
mirroring the reference's Signatures.

Expressions are frozen dataclasses — hashable, so compiled plans can be cached
on the expression tree itself.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

from .. import types as T


@dataclasses.dataclass(frozen=True)
class RowExpression:
    def __post_init__(self):
        pass

    @property
    def type(self) -> T.Type:
        raise NotImplementedError


@dataclasses.dataclass(frozen=True)
class ColumnRef(RowExpression):
    """Reference to an input column by name (the planner guarantees unique
    names per pipeline — equivalent of the reference's channel-indexed
    InputReferenceExpression)."""

    name: str
    _type: T.Type

    @property
    def type(self) -> T.Type:
        return self._type

    def __str__(self):
        return self.name


@dataclasses.dataclass(frozen=True)
class Literal(RowExpression):
    value: Any  # python scalar; None = SQL NULL; str for varchar
    _type: T.Type
    # EXECUTE-parameter provenance (exec/qcache.py): literals bound from a
    # prepared statement's USING list carry their parameter index so a
    # cached plan skeleton can be rebound to new values by a tree walk.
    # Param-tagged literals are opaque to constant folding and to
    # value-sensitive plan rules — the plan SHAPE must not depend on the
    # value, only the kernels traced from it.
    param: Optional[int] = None

    @property
    def type(self) -> T.Type:
        return self._type

    def __str__(self):
        return repr(self.value)


@dataclasses.dataclass(frozen=True)
class Lambda(RowExpression):
    """Lambda argument to a higher-order function: params are synthetic
    channel names the body references (reference LambdaDefinitionExpression)."""

    params: Tuple[str, ...]
    body: RowExpression
    param_types: Tuple[T.Type, ...]

    @property
    def type(self) -> T.Type:
        return self.body.type

    def __str__(self):
        return f"({', '.join(self.params)}) -> {self.body}"


@dataclasses.dataclass(frozen=True)
class Call(RowExpression):
    """Function call. `name` is either a scalar function from
    expr/functions.py or a special form (see compiler.SPECIAL_FORMS)."""

    name: str
    args: Tuple[RowExpression, ...]
    _type: T.Type

    @property
    def type(self) -> T.Type:
        return self._type

    def __str__(self):
        return f"{self.name}({', '.join(map(str, self.args))})"


# ---- convenience constructors (used by tests and the planner) ----


def col(name: str, typ: T.Type) -> ColumnRef:
    return ColumnRef(name, typ)


def lit(value: Any, typ: Optional[T.Type] = None) -> Literal:
    if typ is None:
        if value is None:
            typ = T.UNKNOWN
        elif isinstance(value, bool):
            typ = T.BOOLEAN
        elif isinstance(value, int):
            typ = T.BIGINT
        elif isinstance(value, float):
            typ = T.DOUBLE
        elif isinstance(value, str):
            typ = T.VARCHAR
        else:
            raise TypeError(f"cannot infer literal type for {value!r}")
    return Literal(value, typ)


def call(name: str, args, typ: T.Type) -> Call:
    return Call(name, tuple(args), typ)


def _binary_result_type(name: str, a: T.Type, b: T.Type) -> T.Type:
    from . import functions

    return functions.infer_call_type(name, (a, b))


def binary(name: str, left: RowExpression, right: RowExpression) -> Call:
    return Call(name, (left, right), _binary_result_type(name, left.type, right.type))


def comparison(name: str, left: RowExpression, right: RowExpression) -> Call:
    return Call(name, (left, right), T.BOOLEAN)


def and_(*args: RowExpression) -> Call:
    return Call("and", tuple(args), T.BOOLEAN)


def or_(*args: RowExpression) -> Call:
    return Call("or", tuple(args), T.BOOLEAN)


def not_(arg: RowExpression) -> Call:
    return Call("not", (arg,), T.BOOLEAN)


def is_null(arg: RowExpression) -> Call:
    return Call("is_null", (arg,), T.BOOLEAN)


def cast(arg: RowExpression, to: T.Type) -> Call:
    return Call("cast", (arg, Literal(to.display(), T.VARCHAR)), to)


def between(v: RowExpression, lo: RowExpression, hi: RowExpression) -> Call:
    return Call("between", (v, lo, hi), T.BOOLEAN)


def in_list(v: RowExpression, options) -> Call:
    return Call("in", (v,) + tuple(options), T.BOOLEAN)


def like(v: RowExpression, pattern: str, escape: Optional[str] = None) -> Call:
    args = (v, Literal(pattern, T.VARCHAR))
    if escape is not None:
        args = args + (Literal(escape, T.VARCHAR),)
    return Call("like", args, T.BOOLEAN)


def if_(cond: RowExpression, then: RowExpression, else_: RowExpression) -> Call:
    return Call("if", (cond, then, else_), T.common_super_type(then.type, else_.type))
