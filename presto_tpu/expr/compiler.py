"""Expression compiler: typed RowExpression -> fused jax computation.

The TPU-native equivalent of the reference's runtime bytecode generation
(presto-main/.../sql/gen/ExpressionCompiler.java:93 compilePageProcessor and
BytecodeGenerator visitors). Tracing with jax *is* the codegen: `evaluate`
walks the tree once inside a jit trace and XLA fuses the result into the
surrounding kernel, exactly where the reference emits JVM bytecode.

Special forms implemented here (the reference's special BytecodeGenerators,
sql/gen/AndCodeGenerator.java etc.):
  and / or      — SQL three-valued (Kleene) logic
  not, is_null, is_not_null
  if / case     — searched CASE via nested jnp.where
  coalesce, nullif
  in            — OR of equalities (dictionary fast path via functions.eq)
  between       — lo <= v AND v <= hi
  cast          — numeric/decimal/date conversions

Everything else dispatches to the scalar registry (expr/functions.py).
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..page import Block, Page, intern_dictionary
from . import datetime_kernels as dt
from .functions import Val, and_valid, apply_function
from .ir import Call, ColumnRef, Lambda, Literal, RowExpression

LAMBDA_FORMS = {
    "transform",
    "filter",
    "reduce",
    "zip_with",
    "map_zip_with",
    "any_match",
    "all_match",
    "none_match",
    "map_filter",
    "transform_values",
    "transform_keys",
}

SPECIAL_FORMS = {
    "and",
    "or",
    "not",
    "is_null",
    "is_not_null",
    "if",
    "case",
    "coalesce",
    "nullif",
    "in",
    "between",
    "cast",
    "try_cast",
}


def evaluate(expr: RowExpression, page: Page, n: Optional[int] = None) -> Val:
    """Trace `expr` against the page's blocks. Returns a capacity-length Val."""
    cap = page.capacity

    if isinstance(expr, ColumnRef):
        blk = page.block(expr.name)
        keys_val = None
        if blk.key_block is not None:
            kb = blk.key_block
            keys_val = Val(
                kb.data, None, T.ArrayType(blk.type.key), kb.dict_id,
                lengths=kb.lengths, elem_valid=kb.elem_valid,
            )
        return Val(
            blk.data, blk.valid, blk.type, blk.dict_id,
            lengths=blk.lengths, elem_valid=blk.elem_valid, keys=keys_val,
        )

    if isinstance(expr, Literal):
        return _literal_val(expr, cap)

    if isinstance(expr, Lambda):
        # exhaustive over the IR: a Lambda is only meaningful as an
        # argument of a lambda-form Call (transform/filter/reduce...),
        # where _eval_lambda_form binds its parameters. Reaching one
        # bare means the planner emitted it in a value position.
        raise TypeError(
            f"bare Lambda {expr} outside a lambda-form call — planner bug"
        )

    assert isinstance(expr, Call), expr
    name = expr.name

    if name in LAMBDA_FORMS:
        return _eval_lambda_form(expr, page)

    if name == "and":
        return _kleene_and([evaluate(a, page) for a in expr.args])
    if name == "or":
        return _kleene_or([evaluate(a, page) for a in expr.args])
    if name == "not":
        v = evaluate(expr.args[0], page)
        return Val(~v.data, v.valid, T.BOOLEAN)
    if name == "is_null":
        v = evaluate(expr.args[0], page)
        data = jnp.zeros(cap, jnp.bool_) if v.valid is None else ~v.valid
        return Val(data, None, T.BOOLEAN)
    if name == "is_not_null":
        v = evaluate(expr.args[0], page)
        data = jnp.ones(cap, jnp.bool_) if v.valid is None else v.valid
        return Val(data, None, T.BOOLEAN)
    if name == "if":
        cond, then, els = (evaluate(a, page) for a in expr.args)
        return _if_val(cond, then, els, expr.type)
    if name == "case":
        # args = [cond1, val1, cond2, val2, ..., else]
        args = [evaluate(a, page) for a in expr.args]
        *pairs, els = args
        out = els
        for i in range(len(pairs) - 2, -1, -2):
            out = _if_val(pairs[i], pairs[i + 1], out, expr.type)
        return out
    if name == "coalesce":
        vals = [evaluate(a, page) for a in expr.args]
        out = vals[-1]
        for v in vals[-2::-1]:
            out = _if_val(
                Val(v.valid_mask(), None, T.BOOLEAN), v, out, expr.type
            )
        return out
    if name == "nullif":
        a, b = (evaluate(x, page) for x in expr.args)
        eq = apply_function("eq", [a, b], T.BOOLEAN)
        new_valid = and_valid(a.valid, ~(eq.data & eq.valid_mask()))
        return Val(a.data, new_valid, expr.type, a.dict_id)
    if name == "in":
        v = evaluate(expr.args[0], page)
        hits = [
            apply_function("eq", [v, evaluate(o, page)], T.BOOLEAN)
            for o in expr.args[1:]
        ]
        return _kleene_or(hits)
    if name == "between":
        v, lo, hi = (evaluate(a, page) for a in expr.args)
        ge = apply_function("ge", [v, lo], T.BOOLEAN)
        le = apply_function("le", [v, hi], T.BOOLEAN)
        return _kleene_and([ge, le])
    if name == "cast":
        v = evaluate(expr.args[0], page)
        return _cast_val(v, expr.type)
    if name == "try_cast":
        v = evaluate(expr.args[0], page)
        return _cast_val(v, expr.type, null_on_failure=True)

    vals = [evaluate(a, page) for a in expr.args]
    return apply_function(name, vals, expr.type)


# ---------------------------------------------------------------------------


def _literal_val(expr: Literal, cap: int) -> Val:
    t = expr.type
    if expr.value is None:
        return Val(
            jnp.zeros(cap, t.storage_dtype), jnp.zeros(cap, jnp.bool_), t
        )
    if isinstance(t, T.VarcharType):
        did = intern_dictionary((expr.value,))
        return Val(jnp.zeros(cap, jnp.int32), None, t, did, literal=expr.value)
    if isinstance(t, T.DateType) and isinstance(expr.value, str):
        days = dt.parse_date_literal(expr.value)
        return Val(jnp.full(cap, days, jnp.int32), None, t, literal=days)
    if isinstance(t, T.DecimalType):
        # any numeric literal -> scaled int in the decimal's units
        from decimal import Decimal

        scaled = int(
            (Decimal(str(expr.value)) * (10**t.scale)).to_integral_value()
        )
        if t.is_long:
            # beyond int64: (hi, lo) radix-2^32 lanes (ops/decimal128.py)
            if abs(scaled) >= (1 << 95):
                raise ValueError(
                    f"decimal literal {expr.value} exceeds the two-lane "
                    "range (~2^95)"
                )
            lanes = np.array(
                [[scaled >> 32, scaled & 0xFFFFFFFF]], np.int64
            )
            data = jnp.broadcast_to(jnp.asarray(lanes), (cap, 2))
            return Val(data, None, t, literal=expr.value)
        return Val(jnp.full(cap, scaled, jnp.int64), None, t, literal=expr.value)
    return Val(
        jnp.full(cap, expr.value, t.storage_dtype), None, t, literal=expr.value
    )


def _kleene_and(vals: Sequence[Val]) -> Val:
    data, valid = vals[0].data, vals[0].valid
    for v in vals[1:]:
        new_data = data & v.data
        if valid is None and v.valid is None:
            valid = None
        else:
            av = jnp.ones_like(data) if valid is None else valid
            bv = v.valid_mask()
            # result valid if: both valid, or either side is a valid FALSE
            valid = (av & bv) | (av & ~data) | (bv & ~v.data)
        data = new_data
    return Val(data, valid, T.BOOLEAN)


def _kleene_or(vals: Sequence[Val]) -> Val:
    data, valid = vals[0].data, vals[0].valid
    for v in vals[1:]:
        new_data = data | v.data
        if valid is None and v.valid is None:
            valid = None
        else:
            av = jnp.ones_like(data) if valid is None else valid
            bv = v.valid_mask()
            # result valid if: both valid, or either side is a valid TRUE
            valid = (av & bv) | (av & data) | (bv & v.data)
        data = new_data
    return Val(data, valid, T.BOOLEAN)


def _if_val(cond: Val, then: Val, els: Val, out_type: T.Type) -> Val:
    c = cond.data & cond.valid_mask()
    a, b = _align_pair(then, els, out_type)  # same dict_id after alignment
    da, db = a.data, b.data
    if da.ndim != db.ndim:
        # one branch is long-decimal lanes, the other a scalar column
        # (e.g. a NULL/int literal): widen the scalar side exactly
        from ..ops import decimal128 as d128

        if da.ndim == 1:
            da = d128.from_int64(da.astype(jnp.int64))
        else:
            db = d128.from_int64(db.astype(jnp.int64))
    cw = c[:, None] if da.ndim == 2 else c
    data = jnp.where(cw, da, db)
    if a.valid is None and b.valid is None:
        valid = None
    else:
        valid = jnp.where(c, a.valid_mask(), b.valid_mask())
    return Val(data, valid, out_type, a.dict_id)


def _align_pair(a: Val, b: Val, out_type: T.Type):
    """Bring two Vals into the same representation for jnp.where."""
    if isinstance(out_type, T.VarcharType):
        if a.dict_id == b.dict_id:
            return a, b
        from .functions import unify_dictionaries

        xa, xb, did = unify_dictionaries(a, b)
        return Val(xa, a.valid, out_type, did), Val(xb, b.valid, out_type, did)
    ca = _cast_val(a, out_type)
    cb = _cast_val(b, out_type)
    return ca, cb


def _cast_val(v: Val, to: T.Type, null_on_failure: bool = False) -> Val:
    frm = v.type
    if frm == to:
        return v
    if isinstance(frm, T.UnknownType):
        return Val(jnp.zeros(v.data.shape, to.storage_dtype), jnp.zeros(v.data.shape, jnp.bool_), to)
    if isinstance(frm, T.VarcharType) and not isinstance(
        to, (T.VarcharType, T.DateType)
    ):
        # varchar -> numeric/boolean: parse once per DICTIONARY entry on
        # host (the date-cast model below). CAST raises on any
        # unparseable entry; TRY_CAST maps those entries to NULL.
        return _cast_varchar_entries(v, to, null_on_failure)
    if isinstance(to, T.VarcharType):
        if isinstance(frm, T.VarcharType):
            return Val(v.data, v.valid, to, v.dict_id)
        raise NotImplementedError(f"cast {frm} -> varchar")
    frm_long = isinstance(frm, T.DecimalType) and frm.is_long
    if isinstance(to, T.DoubleType) or isinstance(to, T.RealType):
        s = frm.scale if isinstance(frm, T.DecimalType) else 0
        if frm_long:
            from ..ops import decimal128 as d128

            d = d128.to_float64(v.data).astype(to.storage_dtype)
        else:
            d = v.data.astype(to.storage_dtype)
        return Val(d / (10**s) if s else d, v.valid, to)
    if isinstance(to, T.DecimalType):
        if to.is_long:
            from .functions import _to_lanes

            if T.is_floating(frm):
                from ..ops import decimal128 as d128
                from .functions import _round_half_away

                d = _round_half_away(v.data * (10**to.scale)).astype(jnp.int64)
                return Val(d128.from_int64(d), v.valid, to)
            return Val(_to_lanes(v, to.scale), v.valid, to)
        if frm_long:
            from ..ops import decimal128 as d128

            lanes = d128.rescale(v.data, to.scale - frm.scale)
            return Val(d128.to_int64(lanes), v.valid, to)
        if isinstance(frm, T.DecimalType):
            return Val(
                _rescale_int(v.data, frm.scale, to.scale), v.valid, to
            )
        if T.is_floating(frm):
            from .functions import _round_half_away

            d = _round_half_away(v.data * (10**to.scale)).astype(jnp.int64)
            return Val(d, v.valid, to)
        return Val(v.data.astype(jnp.int64) * (10**to.scale), v.valid, to)
    if T.is_integral(to):
        if frm_long:
            from ..ops import decimal128 as d128

            lanes = d128.rescale(v.data, -frm.scale)
            return Val(d128.to_int64(lanes).astype(to.storage_dtype), v.valid, to)
        if isinstance(frm, T.DecimalType):
            d = _rescale_int(v.data, frm.scale, 0)
            return Val(d.astype(to.storage_dtype), v.valid, to)
        if T.is_floating(frm):
            from .functions import _round_half_away

            return Val(_round_half_away(v.data).astype(to.storage_dtype), v.valid, to)
        return Val(v.data.astype(to.storage_dtype), v.valid, to)
    if isinstance(to, T.BooleanType):
        return Val(v.data != 0, v.valid, to)
    if isinstance(to, T.DateType) and isinstance(frm, T.VarcharType):
        d = v.dictionary or ()
        table = jnp.asarray(
            np.array([dt.parse_date_literal(s) for s in d], np.int32)
        )
        return Val(table[v.data], v.valid, to)
    raise NotImplementedError(f"cast {frm} -> {to}")


def _cast_varchar_entries(v: Val, to: T.Type, null_on_failure: bool) -> Val:
    import decimal as _dec

    d = v.dictionary or ()

    def parse(s: str):
        s2 = s.strip()
        try:
            if isinstance(to, T.BooleanType):
                low = s2.lower()
                if low in ("true", "t", "1"):
                    return 1, True
                if low in ("false", "f", "0"):
                    return 0, True
                return 0, False
            if T.is_integral(to):
                return int(s2), True
            if T.is_floating(to):
                return float(s2), True
            if isinstance(to, T.DecimalType):
                q = _dec.Decimal(s2).scaleb(to.scale).to_integral_value(
                    rounding=_dec.ROUND_HALF_UP
                )
                x = int(q)
                # two-int64-lane representation bound (ops/decimal128.py)
                if to.is_long and abs(x) >= (1 << 95):
                    return 0, False
                if not to.is_long and abs(x) >= (1 << 63):
                    return 0, False
                return x, True
        except (ValueError, _dec.InvalidOperation, ArithmeticError):
            return 0, False
        return 0, False

    parsed = [parse(s) for s in d]
    bad = [s for s, (_, ok) in zip(d, parsed) if not ok]
    if bad and not null_on_failure:
        raise ValueError(
            f"Cannot cast {bad[0]!r} to {to.display()} (CAST; use "
            "TRY_CAST for NULL-on-failure)"
        )
    if isinstance(to, T.DecimalType) and to.is_long:
        # long decimals: build (hi, lo) 32-bit lanes from python ints
        lanes = np.zeros((max(len(parsed), 1), 2), np.int64)
        for i, (x, _ok) in enumerate(parsed):
            lanes[i, 0] = x >> 32
            lanes[i, 1] = x & 0xFFFFFFFF
        table = jnp.asarray(lanes)
        data = table[v.data]
    else:
        if isinstance(to, T.BooleanType):
            npdt = np.bool_
        elif T.is_floating(to):
            npdt = np.float64 if isinstance(to, T.DoubleType) else np.float32
        else:
            npdt = np.int64
        table = jnp.asarray(
            np.array([x for x, _ in parsed] or [0], npdt).astype(
                to.storage_dtype
            )
        )
        data = table[v.data]
    okt = jnp.asarray(np.array([ok for _, ok in parsed] or [True], bool))
    ok = okt[v.data]
    valid = ok if v.valid is None else (v.valid & ok)
    if not bad:
        valid = v.valid  # all entries parse: keep original nullability
    return Val(data, valid, to)


def _rescale_int(data, from_scale: int, to_scale: int):
    from .functions import _rescale

    return _rescale(data.astype(jnp.int64), from_scale, to_scale)


# ---------------------------------------------------------------------------
# page-level entry points (the PageProcessor analog,
# reference operator/project/PageProcessor.java)
# ---------------------------------------------------------------------------


def project_page(
    page: Page, exprs: Sequence[RowExpression], names: Sequence[str]
) -> Page:
    """Evaluate projections; returns a new page with the same live count."""
    blocks = []
    for e in exprs:
        v = evaluate(e, page)
        kb = None
        if v.keys is not None:
            k = v.keys
            kb = Block(
                k.data, v.type.key, None, k.dict_id,
                lengths=k.lengths, elem_valid=k.elem_valid,
            )
        blocks.append(
            Block(
                v.data, v.type, v.valid, v.dict_id,
                lengths=v.lengths, elem_valid=v.elem_valid, key_block=kb,
            )
        )
    return Page(tuple(blocks), tuple(names), page.count)


def compile_projection(exprs, names) -> Callable[[Page], Page]:
    exprs = tuple(exprs)
    names = tuple(names)

    @jax.jit
    def run(page: Page) -> Page:
        return project_page(page, exprs, names)

    return run


# ---------------------------------------------------------------------------
# higher-order (lambda) functions over arrays
# ---------------------------------------------------------------------------
# Strategy (reference ArrayTransformFunction & friends, re-designed for
# XLA): flatten the (capacity, width) element matrix to one (capacity *
# width) column, append every outer column row-repeated `width` times, and
# evaluate the lambda BODY as an ordinary scalar expression over that flat
# page — every scalar kernel is reused unchanged, and XLA fuses the whole
# thing. Results reshape back to (capacity, width).


def _flat_page_for(page: Page, width: int, params) -> Page:
    """Outer columns row-repeated `width` times + lambda-param blocks."""
    blocks, names = [], []
    for nm, b in zip(page.names, page.blocks):
        data = jnp.repeat(b.data, width, axis=0)
        valid = None if b.valid is None else jnp.repeat(b.valid, width)
        blocks.append(Block(data, b.type, valid, b.dict_id))
        names.append(nm)
    for nm, v in params:
        blocks.append(Block(v.data, v.type, v.valid, v.dict_id))
        names.append(nm)
    cap = page.capacity * width
    return Page(tuple(blocks), tuple(names), jnp.asarray(cap, jnp.int32))


def _elements_val(arr: Val, elem_t: T.Type) -> Val:
    """Flatten an array Val's elements to a (capacity*width,) Val."""
    width = arr.data.shape[1]
    data = arr.data.reshape((arr.data.shape[0] * width,) + arr.data.shape[2:])
    valid = (
        None if arr.elem_valid is None else arr.elem_valid.reshape(-1)
    )
    return Val(data, valid, elem_t, arr.dict_id)


def _in_bounds(arr: Val) -> jnp.ndarray:
    """(capacity, width) mask of slots inside each row's length."""
    cap, width = arr.data.shape[0], arr.data.shape[1]
    lens = (
        arr.lengths
        if arr.lengths is not None
        else jnp.full(cap, width, jnp.int32)
    )
    return jnp.arange(width, dtype=jnp.int32)[None, :] < lens[:, None]


def _eval_lambda_form(expr: Call, page: Page) -> Val:
    name = expr.name
    out_type = expr.type
    if name == "zip_with":
        return _eval_zip_with(expr, page)
    if name == "map_zip_with":
        return _eval_map_zip_with(expr, page)
    if name == "reduce":
        return _eval_reduce(expr, page)
    if name in ("map_filter", "transform_values", "transform_keys"):
        return _eval_map_lambda(expr, page)
    arr = evaluate(expr.args[0], page)
    lam: Lambda = expr.args[1]
    if arr.data.ndim != 2:
        raise TypeError(f"{name} expects an array value")
    cap, width = arr.data.shape[0], arr.data.shape[1]
    elems = _elements_val(arr, lam.param_types[0])
    flat = _flat_page_for(page, width, [(lam.params[0], elems)])
    body = evaluate(lam.body, flat)
    inb = _in_bounds(arr)

    if name == "transform":
        data = body.data.reshape((cap, width) + body.data.shape[1:])
        evalid = (
            None
            if body.valid is None
            else body.valid.reshape(cap, width)
        )
        return Val(
            data, arr.valid, out_type, body.dict_id,
            lengths=arr.lengths
            if arr.lengths is not None
            else jnp.full(cap, width, jnp.int32),
            elem_valid=evalid,
        )
    if name == "filter":
        keep = (body.data & body.valid_mask()).reshape(cap, width) & inb
        # stable left-compaction per row: kept slots first, order preserved
        order = jnp.argsort(~keep, axis=1, stable=True)
        data = jnp.take_along_axis(
            arr.data, order.reshape(order.shape + (1,) * (arr.data.ndim - 2)),
            axis=1,
        ) if arr.data.ndim > 2 else jnp.take_along_axis(arr.data, order, axis=1)
        evalid = (
            None
            if arr.elem_valid is None
            else jnp.take_along_axis(arr.elem_valid, order, axis=1)
        )
        lengths = jnp.sum(keep, axis=1).astype(jnp.int32)
        return Val(
            data, arr.valid, out_type, arr.dict_id,
            lengths=lengths, elem_valid=evalid,
        )
    # any/all/none_match over in-bounds elements (SQL semantics: NULL
    # lambda results participate in three-valued logic; the engine takes
    # the two-valued reduction like the reference's simplified matchers)
    truthy = (body.data & body.valid_mask()).reshape(cap, width)
    if name == "any_match":
        agg = jnp.any(truthy & inb, axis=1)
    elif name == "all_match":
        agg = jnp.all(truthy | ~inb, axis=1)
    else:  # none_match
        agg = ~jnp.any(truthy & inb, axis=1)
    return Val(agg, arr.valid, T.BOOLEAN)


def _eval_map_zip_with(expr: Call, page: Page) -> Val:
    """map_zip_with(m1, m2, (k, v1, v2) -> ...) — reference
    MapZipWithFunction: output keys are the UNION of the two key sets;
    a side's value is NULL where its map lacks the key.

    TPU shape: concat the two key lanes, one per-row sort clusters
    duplicates, a shifted-compare marks first occurrences, and a stable
    compaction left-packs the union; each side's value is then a masked
    equality-join of the union keys against that side's (short) key lane
    — O(W^2) per row on lanes that are all collection-width bounded."""
    m1 = evaluate(expr.args[0], page)
    m2 = evaluate(expr.args[1], page)
    lam: Lambda = expr.args[2]
    if m1.keys is None or m2.keys is None:
        raise TypeError("map_zip_with expects two map values")
    k1, k2 = m1.keys, m2.keys
    kd1, kd2, kdict = k1.data, k2.data, k1.dict_id
    # the keys companion is typed array(varchar) — gate on dict ids
    if (k1.dict_id is not None or k2.dict_id is not None) and (
        k1.dict_id != k2.dict_id
    ):
        from .functions import unify_dictionaries

        kd1, kd2, kdict = unify_dictionaries(k1, k2)
    if kd1.dtype != kd2.dtype:
        wide = jnp.promote_types(kd1.dtype, kd2.dtype)
        kd1, kd2 = kd1.astype(wide), kd2.astype(wide)
    cap, w1 = m1.data.shape[0], m1.data.shape[1]
    w2 = m2.data.shape[1]
    W = w1 + w2
    inb1, inb2 = _in_bounds(m1), _in_bounds(m2)
    allk = jnp.concatenate([kd1, kd2], axis=1)
    inb = jnp.concatenate([inb1, inb2], axis=1)
    # sort on the explicit (out_of_bounds, key) composite — the dead-flag
    # approach of ops/sort.py — instead of overloading dtype-max/+inf as
    # padding: a REAL key equal to the sentinel would otherwise be
    # indistinguishable from padding and silently dropped/mis-joined.
    # Out-of-bounds lanes sort last; in-bounds duplicates stay adjacent.
    oob = (~inb).astype(jnp.int8)
    sort_oob, sk = jax.lax.sort(
        (oob, allk), dimension=1, num_keys=2, is_stable=True
    )
    sinb = sort_oob == 0
    first = jnp.concatenate(
        [jnp.ones((cap, 1), jnp.bool_), sk[:, 1:] != sk[:, :-1]], axis=1
    )
    uniq = sinb & first
    pack = jnp.argsort(~uniq, axis=1, stable=True)
    ukeys = jnp.take_along_axis(sk, pack, axis=1)
    ulen = uniq.sum(axis=1).astype(jnp.int32)

    def lookup(m: Val, kd, inbm):
        eq = (ukeys[:, :, None] == kd[:, None, :]) & inbm[:, None, :]
        found = jnp.any(eq, axis=2)
        idx = jnp.argmax(eq, axis=2).astype(jnp.int32)
        vdat = jnp.take_along_axis(m.data, idx, axis=1)
        ev = found
        if m.elem_valid is not None:
            ev = ev & jnp.take_along_axis(m.elem_valid, idx, axis=1)
        return vdat, ev

    v1, ev1 = lookup(m1, kd1, inb1)
    v2, ev2 = lookup(m2, kd2, inb2)
    kelems = Val(ukeys.reshape(-1), None, lam.param_types[0], kdict)
    v1e = Val(v1.reshape(-1), ev1.reshape(-1), lam.param_types[1], m1.dict_id)
    v2e = Val(v2.reshape(-1), ev2.reshape(-1), lam.param_types[2], m2.dict_id)
    flat = _flat_page_for(
        page,
        W,
        [
            (lam.params[0], kelems),
            (lam.params[1], v1e),
            (lam.params[2], v2e),
        ],
    )
    body = evaluate(lam.body, flat)
    bdata = body.data.reshape(cap, W)
    bvalid = None if body.valid is None else body.valid.reshape(cap, W)
    out_type = expr.type
    new_keys = Val(ukeys, None, out_type.key, kdict, lengths=ulen)
    return Val(
        bdata,
        and_valid(m1.valid, m2.valid),
        out_type,
        body.dict_id,
        lengths=ulen,
        elem_valid=bvalid,
        keys=new_keys,
    )


def _eval_map_lambda(expr: Call, page: Page) -> Val:
    """map_filter / transform_values / transform_keys: the lambda body
    evaluates over flattened (key, value) element pairs (reference
    MapFilterFunction + MapTransform*Function)."""
    name = expr.name
    out_type = expr.type
    m = evaluate(expr.args[0], page)
    lam: Lambda = expr.args[1]
    if m.keys is None or m.data.ndim != 2:
        raise TypeError(f"{name} expects a map value")
    keys = m.keys
    cap, width = m.data.shape[0], m.data.shape[1]
    kelems = _elements_val(keys, lam.param_types[0])
    velems = _elements_val(m, lam.param_types[1])
    flat = _flat_page_for(
        page, width, [(lam.params[0], kelems), (lam.params[1], velems)]
    )
    body = evaluate(lam.body, flat)
    inb = _in_bounds(m)

    if name == "map_filter":
        keep = (body.data & body.valid_mask()).reshape(cap, width) & inb
        order = jnp.argsort(~keep, axis=1, stable=True)
        vdata = jnp.take_along_axis(m.data, order, axis=1)
        kdata = jnp.take_along_axis(keys.data, order, axis=1)
        ev = m.elem_valid
        if ev is not None:
            ev = jnp.take_along_axis(ev, order, axis=1)
        lengths = jnp.sum(keep, axis=1).astype(jnp.int32)
        new_keys = Val(
            kdata, None, keys.type, keys.dict_id, lengths=lengths
        )
        return Val(
            vdata, m.valid, out_type, m.dict_id, lengths=lengths,
            elem_valid=ev, keys=new_keys,
        )
    bdata = body.data.reshape(cap, width)
    bvalid = (
        None if body.valid is None else body.valid.reshape(cap, width)
    )
    if name == "transform_values":
        # the body's OWN validity is the only per-entry nullability: a
        # lambda ignoring v yields non-null even for null input values
        # (its valid mask already folds in elem_valid when it reads v)
        return Val(
            bdata, m.valid, out_type, body.dict_id, lengths=m.lengths,
            elem_valid=bvalid, keys=keys,
        )
    # transform_keys: values unchanged; keys replaced by the body
    new_keys = Val(
        bdata, None, out_type.key, body.dict_id, lengths=m.lengths
    )
    return Val(
        m.data, m.valid, out_type, m.dict_id, lengths=m.lengths,
        elem_valid=m.elem_valid, keys=new_keys,
    )


def _eval_zip_with(expr: Call, page: Page) -> Val:
    a = evaluate(expr.args[0], page)
    b = evaluate(expr.args[1], page)
    lam: Lambda = expr.args[2]
    cap = a.data.shape[0]
    wa, wb = a.data.shape[1], b.data.shape[1]
    width = max(wa, wb)

    def widen(v: Val, w: int) -> Val:
        if v.data.shape[1] == w:
            return v
        pad = w - v.data.shape[1]
        data = jnp.pad(v.data, ((0, 0), (0, pad)) + ((0, 0),) * (v.data.ndim - 2))
        ev = v.elem_valid
        ev = (
            jnp.pad(ev, ((0, 0), (0, pad)))
            if ev is not None
            else jnp.ones((cap, v.data.shape[1]), jnp.bool_)
        )
        if ev.shape[1] != w:
            ev = jnp.pad(ev, ((0, 0), (0, w - ev.shape[1])))
        return Val(data, v.valid, v.type, v.dict_id,
                   lengths=v.lengths, elem_valid=ev)

    a2, b2 = widen(a, width), widen(b, width)
    la = a.lengths if a.lengths is not None else jnp.full(cap, wa, jnp.int32)
    lb = b.lengths if b.lengths is not None else jnp.full(cap, wb, jnp.int32)
    out_len = jnp.maximum(la, lb)
    # shorter array's missing elements are NULL (Presto zip_with)
    ev_a = (
        a2.elem_valid
        if a2.elem_valid is not None
        else jnp.ones((cap, width), jnp.bool_)
    ) & (jnp.arange(width, dtype=jnp.int32)[None, :] < la[:, None])
    ev_b = (
        b2.elem_valid
        if b2.elem_valid is not None
        else jnp.ones((cap, width), jnp.bool_)
    ) & (jnp.arange(width, dtype=jnp.int32)[None, :] < lb[:, None])
    ea = Val(
        a2.data.reshape((cap * width,) + a2.data.shape[2:]),
        ev_a.reshape(-1), lam.param_types[0], a.dict_id,
    )
    eb = Val(
        b2.data.reshape((cap * width,) + b2.data.shape[2:]),
        ev_b.reshape(-1), lam.param_types[1], b.dict_id,
    )
    flat = _flat_page_for(
        page, width, [(lam.params[0], ea), (lam.params[1], eb)]
    )
    body = evaluate(lam.body, flat)
    data = body.data.reshape((cap, width) + body.data.shape[1:])
    evalid = (
        body.valid.reshape(cap, width)
        if body.valid is not None
        else None
    )
    valid = and_valid(a.valid, b.valid)
    return Val(
        data, valid, expr.type, body.dict_id,
        lengths=out_len, elem_valid=evalid,
    )


def _eval_reduce(expr: Call, page: Page) -> Val:
    """reduce(array, init, (s, x) -> s', s -> r): the state folds over a
    STATIC-width python loop (widths are trace constants), masked past
    each row's length — XLA unrolls and fuses the chain."""
    arr = evaluate(expr.args[0], page)
    init = evaluate(expr.args[1], page)
    input_fn: Lambda = expr.args[2]
    output_fn: Lambda = expr.args[3]
    cap, width = arr.data.shape[0], arr.data.shape[1]
    inb = _in_bounds(arr)
    state = init
    if state.type != input_fn.param_types[0]:
        state = _cast_val(state, input_fn.param_types[0])
    for j in range(width):
        edata = arr.data[:, j]
        evalid = None if arr.elem_valid is None else arr.elem_valid[:, j]
        ev = Val(edata, evalid, input_fn.param_types[1], arr.dict_id)
        flat = _flat_page_for(
            page, 1, [(input_fn.params[0], state), (input_fn.params[1], ev)]
        )
        nxt = evaluate(input_fn.body, flat)
        live = inb[:, j]
        data = jnp.where(_bcast(live, nxt.data), nxt.data, state.data)
        if state.valid is None and nxt.valid is None:
            valid = None
        else:
            valid = jnp.where(live, nxt.valid_mask(), state.valid_mask())
        state = Val(data, valid, nxt.type, nxt.dict_id)
    flat = _flat_page_for(page, 1, [(output_fn.params[0], state)])
    out = evaluate(output_fn.body, flat)
    return Val(out.data, and_valid(out.valid, arr.valid), expr.type, out.dict_id)


def _bcast(mask, data):
    """Broadcast a row mask over trailing lanes (long-decimal data)."""
    return mask.reshape(mask.shape + (1,) * (data.ndim - 1))
