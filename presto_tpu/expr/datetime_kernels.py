"""Vectorized civil-calendar math on int32 days-since-epoch.

The reference implements date/time scalars over JodaTime
(presto-main/.../operator/scalar/DateTimeFunctions.java). On TPU we need
branch-free integer algorithms that vmap/fuse; these are the classic
Euclidean-affine civil conversions (public-domain algorithms, as used by
Howard Hinnant's date library), expressed in jnp int32/int64 arithmetic.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def days_to_civil(days):
    """days since 1970-01-01 -> (year, month, day), elementwise."""
    z = days.astype(jnp.int64) + 719468
    era = jnp.floor_divide(jnp.where(z >= 0, z, z - 146096), 146097)
    doe = z - era * 146097  # [0, 146096]
    yoe = jnp.floor_divide(doe - doe // 1460 + doe // 36524 - doe // 146096, 365)
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)  # [0, 365]
    mp = jnp.floor_divide(5 * doy + 2, 153)  # [0, 11]
    d = doy - jnp.floor_divide(153 * mp + 2, 5) + 1  # [1, 31]
    m = mp + jnp.where(mp < 10, 3, -9)  # [1, 12]
    y = y + (m <= 2)
    return y.astype(jnp.int32), m.astype(jnp.int32), d.astype(jnp.int32)


def civil_to_days(y, m, d):
    """(year, month, day) -> days since 1970-01-01, elementwise."""
    y = y.astype(jnp.int64) - (m <= 2)
    era = jnp.floor_divide(jnp.where(y >= 0, y, y - 399), 400)
    yoe = y - era * 400  # [0, 399]
    mp = (m.astype(jnp.int64) + jnp.where(m > 2, -3, 9)) % 12
    doy = jnp.floor_divide(153 * mp + 2, 5) + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return (era * 146097 + doe - 719468).astype(jnp.int32)


def last_day_of_month(y, m):
    is_leap = ((y % 4 == 0) & (y % 100 != 0)) | (y % 400 == 0)
    lengths = jnp.array([31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31], jnp.int32)
    base = lengths[m - 1]
    return jnp.where((m == 2) & is_leap, 29, base)


def add_months(days, n_months):
    """SQL date + INTERVAL 'n' MONTH with end-of-month clamping
    (reference DateTimeFunctions uses Joda's addMonths, same semantics)."""
    y, m, d = days_to_civil(days)
    total = (y.astype(jnp.int64)) * 12 + (m - 1) + n_months
    ny = jnp.floor_divide(total, 12).astype(jnp.int32)
    nm = (total - ny.astype(jnp.int64) * 12).astype(jnp.int32) + 1
    nd = jnp.minimum(d, last_day_of_month(ny, nm))
    return civil_to_days(ny, nm, nd)


def extract_year(days):
    return days_to_civil(days)[0].astype(jnp.int64)


def extract_month(days):
    return days_to_civil(days)[1].astype(jnp.int64)


def extract_day(days):
    return days_to_civil(days)[2].astype(jnp.int64)


def extract_quarter(days):
    m = days_to_civil(days)[1]
    return ((m - 1) // 3 + 1).astype(jnp.int64)


def parse_date_literal(text: str) -> int:
    """Host-side: 'YYYY-MM-DD' -> days since epoch (for DATE literals)."""
    return (np.datetime64(text, "D") - np.datetime64("1970-01-01", "D")).astype(int)
