"""Scalar function registry.

Re-designed equivalent of the reference's FunctionRegistry
(presto-main/.../metadata/FunctionRegistry.java:360, ~380 built-ins) plus the
scalar implementations under presto-main/.../operator/scalar/. Each function
declares a type-inference rule and a trace-time implementation over `Val`
(data array + validity mask + type + dictionary id). Implementations run
inside jit tracing, so everything fuses into the surrounding kernel — the TPU
replacement for per-function JVM bytecode.

Varchar strategy: functions/predicates over strings are evaluated once per
*dictionary entry* on the host at trace time (dictionaries are static pytree
aux), then applied to the code array with one device gather. This turns
O(rows) string work into O(|dict|) host work + O(rows) int gather.

Null semantics: scalar functions are null-propagating (RETURNS NULL ON NULL
INPUT, the reference default); special forms in compiler.py implement Kleene
AND/OR, IS NULL, COALESCE, IF/CASE.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import re
from typing import Callable, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..page import dictionary_by_id, intern_dictionary
from . import datetime_kernels as dt


@dataclasses.dataclass
class Val:
    """A vectorized SQL value during expression tracing.

    `literal` holds the Python value when this Val came from a constant —
    functions with literal-only arguments (LIKE patterns, substr positions)
    must read it instead of peeking device data, which would fail under a
    jit trace."""

    data: jnp.ndarray
    valid: Optional[jnp.ndarray]  # None = no nulls
    type: T.Type
    dict_id: Optional[int] = None
    literal: object = None
    # array-typed values only (T.ArrayType): data is (capacity, width),
    # lengths is (capacity,) int32 per-row element counts, elem_valid an
    # optional (capacity, width) element-level null mask. See
    # T.ArrayType.__doc__ — arrays live in expressions, not in Pages.
    lengths: Optional[jnp.ndarray] = None
    elem_valid: Optional[jnp.ndarray] = None
    # map-typed values only (T.MapType): `keys` holds the keys as an
    # array-shaped Val; data/lengths/elem_valid describe the VALUES
    keys: Optional["Val"] = None

    @property
    def dictionary(self) -> Optional[Tuple[str, ...]]:
        return None if self.dict_id is None else dictionary_by_id(self.dict_id)

    def valid_mask(self):
        # validity is per ROW: long-decimal data is (n, 2) lanes but the
        # mask must stay (n,) (a 2-D all-true mask poisons every
        # row-shaped jnp.where it later meets)
        if self.valid is None:
            return jnp.ones(self.data.shape[:1], jnp.bool_)
        return self.valid


def and_valid(*valids):
    """Combine validity masks; None means all-valid."""
    out = None
    for v in valids:
        if v is None:
            continue
        out = v if out is None else (out & v)
    return out


# ---------------------------------------------------------------------------
# registry plumbing
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ScalarFunction:
    name: str
    infer: Callable[[Tuple[T.Type, ...]], T.Type]
    impl: Callable[..., Val]  # (*vals, out_type=Type) -> Val


FUNCTIONS: dict = {}


def register(name, infer):
    def deco(fn):
        FUNCTIONS[name] = ScalarFunction(name, infer, fn)
        return fn

    return deco


def infer_call_type(name: str, arg_types: Tuple[T.Type, ...]) -> T.Type:
    f = FUNCTIONS.get(name)
    if f is None:
        raise KeyError(f"unknown function {name!r}")
    return f.infer(arg_types)


def apply_function(name: str, vals: Sequence[Val], out_type: T.Type) -> Val:
    f = FUNCTIONS.get(name)
    if f is None:
        raise KeyError(f"unknown function {name!r}")
    return f.impl(*vals, out_type=out_type)


# ---------------------------------------------------------------------------
# type rules
# ---------------------------------------------------------------------------


def _arith_infer(op):
    def infer(ts: Tuple[T.Type, ...]) -> T.Type:
        a, b = ts
        # date/interval arithmetic
        if isinstance(a, T.DateType) or isinstance(b, T.DateType):
            if op in ("add", "subtract"):
                if isinstance(a, T.DateType) and isinstance(b, T.DateType):
                    return T.BIGINT  # date difference in days
                return T.DATE
        if isinstance(a, T.TimestampType) or isinstance(b, T.TimestampType):
            if op in ("add", "subtract") and any(
                isinstance(x, (T.IntervalDayType, T.IntervalYearMonthType))
                for x in (a, b)
            ):
                return T.TIMESTAMP
        da, db = isinstance(a, T.DecimalType), isinstance(b, T.DecimalType)
        if T.is_floating(a) or T.is_floating(b):
            return T.DOUBLE
        if da or db:
            sa = a.scale if da else 0
            sb = b.scale if db else 0
            # precision 38 when either side is long (reference
            # DecimalOperators: decimal(38) arithmetic stays decimal(38))
            long = (da and a.precision > 18) or (db and b.precision > 18)
            p = 38 if long else 18
            if op in ("add", "subtract"):
                return T.DecimalType(p, max(sa, sb))
            if op == "multiply":
                return T.DecimalType(p, min(sa + sb, 18))
            if op == "divide":
                # reference: decimal division stays decimal
                # (DecimalOperators.java); scale = max(sa, sb) after rescale
                return T.DecimalType(p, max(sa, sb, 6))
            if op == "modulus":
                return T.DecimalType(p, max(sa, sb))
        # integral
        return T.common_super_type(a, b)

    return infer


def _bool_infer(ts):
    return T.BOOLEAN


def _same_as_first(ts):
    return ts[0]


def _double_infer(ts):
    return T.DOUBLE


def _bigint_infer(ts):
    return T.BIGINT


# ---------------------------------------------------------------------------
# numeric helpers
# ---------------------------------------------------------------------------


def _scale_of(t: T.Type) -> int:
    return t.scale if isinstance(t, T.DecimalType) else 0


def _div_round(x, y):
    """Round-half-up signed integer division (SQL decimal semantics,
    reference Decimals.java HALF_UP rescale)."""
    sign = jnp.sign(x) * jnp.sign(y)
    q = (2 * jnp.abs(x) + jnp.abs(y)) // (2 * jnp.abs(y))
    return sign * q


def _rescale(data, from_scale: int, to_scale: int):
    """Rescale a scaled-int decimal; scale-down rounds half-up."""
    if to_scale == from_scale:
        return data
    if to_scale > from_scale:
        return data * (10 ** (to_scale - from_scale))
    d = 10 ** (from_scale - to_scale)
    return _div_round(data, jnp.asarray(d, data.dtype))


def _round_half_away(x):
    """SQL ROUND for floats: half away from zero (not banker's rounding)."""
    return jnp.sign(x) * jnp.floor(jnp.abs(x) + 0.5)


def _to_double(v: Val):
    s = _scale_of(v.type)
    if v.data.ndim == 2:  # long-decimal lanes
        from ..ops import decimal128 as d128

        d = d128.to_float64(v.data)
    else:
        d = v.data.astype(jnp.float64)
    return d / (10**s) if s else d


def _numeric_align(a: Val, b: Val, out_type: T.Type):
    """Bring both operands into the output type's representation.
    Long-decimal outputs align as lane pairs (exact int128-style path)."""
    if isinstance(out_type, T.DoubleType) or isinstance(out_type, T.RealType):
        return _to_double(a), _to_double(b)
    if isinstance(out_type, T.DecimalType):
        if out_type.is_long:
            return (
                _to_lanes(a, out_type.scale),
                _to_lanes(b, out_type.scale),
            )
        return (
            _rescale(a.data.astype(jnp.int64), _scale_of(a.type), out_type.scale),
            _rescale(b.data.astype(jnp.int64), _scale_of(b.type), out_type.scale),
        )
    dtype = out_type.storage_dtype
    return a.data.astype(dtype), b.data.astype(dtype)


# ---------------------------------------------------------------------------
# arithmetic
# ---------------------------------------------------------------------------


def _ts_interval_shift(ts_data, delta: Val, sign: int):
    """timestamp +/- interval: day intervals move whole microseconds;
    year-month intervals move the day component via the civil-calendar
    month add while preserving time-of-day."""
    day_us = 86400 * _TS_US
    if isinstance(delta.type, T.IntervalYearMonthType):
        days = ts_data // day_us
        rem = ts_data - days * day_us
        return dt.add_months(days, sign * delta.data).astype(
            jnp.int64
        ) * day_us + rem
    return ts_data + sign * delta.data.astype(jnp.int64) * day_us


@register("add", _arith_infer("add"))
def _add(a: Val, b: Val, out_type: T.Type) -> Val:
    valid = and_valid(a.valid, b.valid)
    if isinstance(out_type, T.TimestampType) and any(
        isinstance(x.type, (T.IntervalDayType, T.IntervalYearMonthType))
        for x in (a, b)
    ):
        ts, delta = (a, b) if isinstance(a.type, T.TimestampType) else (b, a)
        return Val(_ts_interval_shift(ts.data, delta, 1), valid, T.TIMESTAMP)
    if isinstance(out_type, T.DateType):
        date, delta = (a, b) if isinstance(a.type, T.DateType) else (b, a)
        if isinstance(delta.type, T.IntervalYearMonthType):
            data = dt.add_months(date.data, delta.data)
        else:
            data = (date.data.astype(jnp.int64) + delta.data).astype(jnp.int32)
        return Val(data, valid, T.DATE)
    x, y = _numeric_align(a, b, out_type)
    if _is_long_dec(out_type):
        from ..ops import decimal128 as d128

        return Val(d128.dadd(x, y), valid, out_type)
    return Val(x + y, valid, out_type)


@register("subtract", _arith_infer("subtract"))
def _subtract(a: Val, b: Val, out_type: T.Type) -> Val:
    valid = and_valid(a.valid, b.valid)
    if isinstance(out_type, T.TimestampType) and isinstance(
        b.type, (T.IntervalDayType, T.IntervalYearMonthType)
    ):
        return Val(_ts_interval_shift(a.data, b, -1), valid, T.TIMESTAMP)
    if isinstance(out_type, T.DateType):
        if isinstance(b.type, T.IntervalYearMonthType):
            data = dt.add_months(a.data, -b.data)
        else:
            data = (a.data.astype(jnp.int64) - b.data).astype(jnp.int32)
        return Val(data, valid, T.DATE)
    if isinstance(a.type, T.DateType) and isinstance(b.type, T.DateType):
        return Val(a.data.astype(jnp.int64) - b.data.astype(jnp.int64), valid, T.BIGINT)
    x, y = _numeric_align(a, b, out_type)
    if _is_long_dec(out_type):
        from ..ops import decimal128 as d128

        return Val(d128.dsub(x, y), valid, out_type)
    return Val(x - y, valid, out_type)


@register("multiply", _arith_infer("multiply"))
def _multiply(a: Val, b: Val, out_type: T.Type) -> Val:
    valid = and_valid(a.valid, b.valid)
    if isinstance(out_type, T.DecimalType):
        have = _scale_of(a.type) + _scale_of(b.type)
        if out_type.is_long:
            from ..ops import decimal128 as d128

            # one side long x one side int64-representable: exact limb
            # multiply, then rescale lanes (both-long multiply exceeds the
            # 2^95 contract and the narrow side is always p<=18 in plans)
            wide, narrow = (a, b) if a.data.ndim == 2 else (b, a)
            if wide.data.ndim != 2:  # both short but long result type
                lanes = d128.dmul_int64(
                    d128.from_int64(a.data.astype(jnp.int64)),
                    b.data.astype(jnp.int64),
                )
            else:
                if narrow.data.ndim == 2:
                    raise NotImplementedError(
                        "multiply of two long decimals is not supported"
                    )
                lanes = d128.dmul_int64(
                    wide.data, narrow.data.astype(jnp.int64)
                )
            return Val(
                d128.rescale(lanes, out_type.scale - have), valid, out_type
            )
        # scales add under multiplication: compute in raw units then the
        # result scale is sa+sb == out_type.scale (capped by inference)
        x = a.data.astype(jnp.int64)
        y = b.data.astype(jnp.int64)
        raw = x * y
        return Val(_rescale(raw, have, out_type.scale), valid, out_type)
    x, y = _numeric_align(a, b, out_type)
    return Val(x * y, valid, out_type)


@register("divide", _arith_infer("divide"))
def _divide(a: Val, b: Val, out_type: T.Type) -> Val:
    valid = and_valid(a.valid, b.valid)
    if isinstance(out_type, T.DecimalType):
        xs, ys = _scale_of(a.type), _scale_of(b.type)
        if out_type.is_long:
            from ..ops import decimal128 as d128

            x = _to_lanes(a, out_type.scale + ys)
            if b.data.ndim == 2:
                # long divisor: narrow to int64 raw units (exact while the
                # divisor magnitude < 2^63 — decimal ratios like Q8's market
                # share; quotients of larger divisors exceed no int64 anyway)
                y = d128.to_int64(b.data)
            else:
                y = b.data.astype(jnp.int64)
            q = d128.ddiv_wide(x, y)  # handles the full int64 divisor range
            valid = and_valid(valid, y != 0)
            return Val(d128.from_int64(q), valid, out_type)
        # scale numerator so raw-int division yields out_type.scale
        x_src = a.data
        if x_src.ndim == 2:
            # long-decimal numerator with a short result type (avg's
            # sum/count division): narrow lanes to raw int64 units first
            # (exact while the value fits — the checked-cast contract)
            from ..ops import decimal128 as d128

            x_src = d128.to_int64(x_src)
        x = _rescale(x_src.astype(jnp.int64), xs, out_type.scale + ys)
        y = b.data.astype(jnp.int64)
        safe = jnp.where(y == 0, 1, y)
        q = _div_round(x, safe)
        valid = and_valid(valid, b.data != 0)
        return Val(q, valid, out_type)
    x, y = _numeric_align(a, b, out_type)
    if jnp.issubdtype(jnp.result_type(x), jnp.integer):
        safe = jnp.where(y == 0, 1, y)
        q = jnp.sign(x) * jnp.sign(safe) * (jnp.abs(x) // jnp.abs(safe))
        return Val(q, and_valid(valid, y != 0), out_type)
    return Val(x / y, valid, out_type)


@register("modulus", _arith_infer("modulus"))
def _modulus(a: Val, b: Val, out_type: T.Type) -> Val:
    valid = and_valid(a.valid, b.valid)
    x, y = _numeric_align(a, b, out_type)
    if jnp.issubdtype(jnp.result_type(x), jnp.integer):
        safe = jnp.where(y == 0, 1, y)
        # truncated division remainder (sign follows dividend, SQL semantics)
        r = x - (jnp.sign(x) * jnp.sign(safe) * (jnp.abs(x) // jnp.abs(safe))) * safe
        return Val(r, and_valid(valid, y != 0), out_type)
    r = x - jnp.trunc(x / y) * y
    return Val(r, valid, out_type)


@register("negate", _same_as_first)
def _negate(a: Val, out_type: T.Type) -> Val:
    if a.data.ndim == 2:
        from ..ops import decimal128 as d128

        return Val(d128.dneg(a.data), a.valid, out_type)
    return Val(-a.data, a.valid, out_type)


# ---------------------------------------------------------------------------
# comparisons (null-propagating; varchar via dictionary codes)
# ---------------------------------------------------------------------------


def require_sorted_dict(v: Val, what: str):
    d = v.dictionary
    if d is not None and not getattr(d, "is_sorted", True):
        raise NotImplementedError(
            f"{what} on a column with an unsorted dictionary "
            f"({type(d).__name__}); codes do not order like strings"
        )


def _bisect(d, s: str, side: str) -> int:
    """Binary search over any (possibly lazy) sorted dictionary — O(log n)
    __getitem__ calls, never materializes the dictionary."""
    lo, hi = 0, len(d)
    while lo < hi:
        mid = (lo + hi) // 2
        e = d[mid]
        if e < s or (side == "right" and e == s):
            lo = mid + 1
        else:
            hi = mid
    return lo


_LITERAL_CMP = {
    "eq": lambda codes, bl, br: (codes >= bl) & (codes < br),
    "ne": lambda codes, bl, br: (codes < bl) | (codes >= br),
    "lt": lambda codes, bl, br: codes < bl,
    "le": lambda codes, bl, br: codes < br,
    "gt": lambda codes, bl, br: codes >= br,
    "ge": lambda codes, bl, br: codes >= bl,
}


def _literal_cmp_fastpath(name: str, a: Val, b: Val):
    """column <op> 'literal' without unifying dictionaries: bisect the
    literal's position in the (sorted, possibly lazy) column dictionary and
    compare codes against it. Critical for LazyDict columns (tpch c_name …)
    where unify would materialize millions of strings."""
    col_v, lit_v, flip = (a, b, False) if len(b.dictionary or ()) == 1 else (b, a, True)
    d = col_v.dictionary
    if d is None:
        return None
    require_sorted_dict(col_v, f"comparison {name!r}")
    s = lit_v.dictionary[0]
    bl = _bisect(d, s, "left")
    br = _bisect(d, s, "right")
    if flip:
        name = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le"}.get(name, name)
    return _LITERAL_CMP[name](col_v.data, jnp.int32(bl), jnp.int32(br))


def _is_long_dec(t: T.Type) -> bool:
    return isinstance(t, T.DecimalType) and t.precision > 18


def _to_lanes(v: Val, to_scale: int):
    """Any numeric Val -> two-lane representation at `to_scale`
    (ops/decimal128.py). Integral/short-decimal operands widen first so the
    rescale itself cannot overflow int64."""
    from ..ops import decimal128 as d128

    s = _scale_of(v.type)
    lanes = v.data if v.data.ndim == 2 else d128.from_int64(
        v.data.astype(jnp.int64)
    )
    return d128.rescale(lanes, to_scale - s)


def _compare_long(name: str, a: Val, b: Val):
    from ..ops import decimal128 as d128

    s = max(_scale_of(a.type), _scale_of(b.type))
    x, y = _to_lanes(a, s), _to_lanes(b, s)
    if name == "eq":
        return d128.dcmp_eq(x, y)
    if name == "ne":
        return ~d128.dcmp_eq(x, y)
    if name == "lt":
        return d128.dcmp_lt(x, y)
    if name == "gt":
        return d128.dcmp_lt(y, x)
    if name == "le":
        return ~d128.dcmp_lt(y, x)
    if name == "ge":
        return ~d128.dcmp_lt(x, y)
    raise KeyError(name)


def _compare(op, a: Val, b: Val, name: str = ""):
    if _is_long_dec(a.type) or _is_long_dec(b.type):
        if T.is_floating(a.type) or T.is_floating(b.type):
            return op(_to_double(a), _to_double(b))
        return _compare_long(name, a, b)
    if isinstance(a.type, T.VarcharType) and isinstance(b.type, T.VarcharType):
        if name in _LITERAL_CMP and (
            len(a.dictionary or ()) == 1 or len(b.dictionary or ()) == 1
        ):
            fast = _literal_cmp_fastpath(name, a, b)
            if fast is not None:
                return fast
        if name in ("lt", "le", "gt", "ge"):
            require_sorted_dict(a, f"comparison {name!r}")
            require_sorted_dict(b, f"comparison {name!r}")
        x, y = _unify_codes(a, b)
        return op(x, y)
    if T.is_floating(a.type) or T.is_floating(b.type):
        return op(_to_double(a), _to_double(b))
    sa, sb = _scale_of(a.type), _scale_of(b.type)
    s = max(sa, sb)
    return op(
        _rescale(a.data.astype(jnp.int64) if sa != s else a.data, sa, s),
        _rescale(b.data.astype(jnp.int64) if sb != s else b.data, sb, s),
    )


def _unify_codes(a: Val, b: Val):
    """Remap two dictionary-coded columns onto one merged sorted dictionary.
    Returns (codes_a, codes_b); `unify_dictionaries` also returns the merged
    interned dictionary id for callers that need the result dictionary."""
    xa, xb, _ = unify_dictionaries(a, b)
    return xa, xb


_UNIFY_MATERIALIZE_LIMIT = 1_000_000


def unify_dictionaries(a: Val, b: Val):
    if a.dict_id is not None and a.dict_id == b.dict_id:
        return a.data, b.data, a.dict_id
    da = a.dictionary or ()
    db = b.dictionary or ()
    if len(da) + len(db) > _UNIFY_MATERIALIZE_LIMIT:
        raise NotImplementedError(
            f"dictionary unification would materialize {len(da)}+{len(db)} "
            "entries; use a literal fast path or dictionary-preserving plan"
        )
    merged = tuple(sorted(set(da) | set(db)))
    index = {s: i for i, s in enumerate(merged)}
    map_a = jnp.asarray(np.array([index[s] for s in da], np.int32))
    map_b = jnp.asarray(np.array([index[s] for s in db], np.int32))
    xa = map_a[a.data] if len(da) else a.data
    xb = map_b[b.data] if len(db) else b.data
    return xa, xb, intern_dictionary(merged)


def _cmp_factory(name, op):
    @register(name, _bool_infer)
    def _cmp(a: Val, b: Val, out_type: T.Type) -> Val:
        return Val(_compare(op, a, b, name), and_valid(a.valid, b.valid), T.BOOLEAN)

    return _cmp


_cmp_factory("eq", lambda x, y: x == y)
_cmp_factory("ne", lambda x, y: x != y)
_cmp_factory("lt", lambda x, y: x < y)
_cmp_factory("le", lambda x, y: x <= y)
_cmp_factory("gt", lambda x, y: x > y)
_cmp_factory("ge", lambda x, y: x >= y)


# ---------------------------------------------------------------------------
# math scalars
# ---------------------------------------------------------------------------


@register("abs", _same_as_first)
def _abs(a: Val, out_type: T.Type) -> Val:
    if a.data.ndim == 2:
        from ..ops import decimal128 as d128

        return Val(d128.dabs(a.data), a.valid, out_type)
    return Val(jnp.abs(a.data), a.valid, out_type)


@register("sqrt", _double_infer)
def _sqrt(a: Val, out_type: T.Type) -> Val:
    x = _to_double(a)
    return Val(jnp.sqrt(jnp.maximum(x, 0.0)), and_valid(a.valid, x >= 0), T.DOUBLE)


@register("ln", _double_infer)
def _ln(a: Val, out_type: T.Type) -> Val:
    x = _to_double(a)
    return Val(jnp.log(jnp.maximum(x, 1e-300)), and_valid(a.valid, x > 0), T.DOUBLE)


@register("exp", _double_infer)
def _exp(a: Val, out_type: T.Type) -> Val:
    return Val(jnp.exp(_to_double(a)), a.valid, T.DOUBLE)


@register("power", _double_infer)
def _power(a: Val, b: Val, out_type: T.Type) -> Val:
    return Val(jnp.power(_to_double(a), _to_double(b)), and_valid(a.valid, b.valid), T.DOUBLE)


@register("floor", _same_as_first)
def _floor(a: Val, out_type: T.Type) -> Val:
    if T.is_floating(a.type):
        return Val(jnp.floor(a.data), a.valid, out_type)
    if isinstance(a.type, T.DecimalType):
        s = 10 ** a.type.scale
        d = jnp.where(a.data >= 0, a.data // s, -((-a.data + s - 1) // s)) * s
        return Val(d, a.valid, out_type)
    return Val(a.data, a.valid, out_type)


@register("ceil", _same_as_first)
def _ceil(a: Val, out_type: T.Type) -> Val:
    if T.is_floating(a.type):
        return Val(jnp.ceil(a.data), a.valid, out_type)
    if isinstance(a.type, T.DecimalType):
        s = 10 ** a.type.scale
        d = jnp.where(a.data >= 0, (a.data + s - 1) // s, -((-a.data) // s)) * s
        return Val(d, a.valid, out_type)
    return Val(a.data, a.valid, out_type)


def _round_infer(ts):
    a = ts[0]
    if isinstance(a, T.DecimalType):
        return a
    if T.is_floating(a):
        return T.DOUBLE
    return a


def _require_literal(v: Val, what: str):
    if v.literal is None:
        raise NotImplementedError(f"{what} requires a literal argument")
    return v.literal


@register("round", _round_infer)
def _round(a: Val, *rest, out_type: T.Type) -> Val:
    ndigits = 0
    if rest:
        (nd,) = rest
        ndigits = int(_require_literal(nd, "round precision"))
    if T.is_floating(a.type):
        f = 10.0**ndigits
        return Val(_round_half_away(a.data * f) / f, a.valid, T.DOUBLE)
    if isinstance(a.type, T.DecimalType):
        drop = a.type.scale - ndigits
        if drop <= 0:
            return Val(a.data, a.valid, a.type)
        s = 10**drop
        d = _div_round(a.data, jnp.asarray(s, a.data.dtype)) * s
        return Val(d, a.valid, a.type)
    return Val(a.data, a.valid, a.type)


# ---------------------------------------------------------------------------
# date/time
# ---------------------------------------------------------------------------


@register("year", _bigint_infer)
def _year(a: Val, out_type: T.Type) -> Val:
    return Val(dt.extract_year(a.data), a.valid, T.BIGINT)


@register("month", _bigint_infer)
def _month(a: Val, out_type: T.Type) -> Val:
    return Val(dt.extract_month(a.data), a.valid, T.BIGINT)


@register("day", _bigint_infer)
def _day(a: Val, out_type: T.Type) -> Val:
    return Val(dt.extract_day(a.data), a.valid, T.BIGINT)


@register("quarter", _bigint_infer)
def _quarter(a: Val, out_type: T.Type) -> Val:
    return Val(dt.extract_quarter(a.data), a.valid, T.BIGINT)


# ---------------------------------------------------------------------------
# varchar functions (host dictionary transforms + device gather)
# ---------------------------------------------------------------------------


def _dict_transform(a: Val, fn: Callable[[str], str], out_type=T.VARCHAR) -> Val:
    """Apply a host string function entry-wise to the dictionary; produce a
    re-sorted dictionary and remap codes with one gather."""
    d = a.dictionary
    if d is None:
        raise TypeError("varchar value lost its dictionary")
    transformed = [fn(s) for s in d]
    new_dict = tuple(sorted(set(transformed)))
    index = {s: i for i, s in enumerate(new_dict)}
    mapping = jnp.asarray(np.array([index[t] for t in transformed], np.int32))
    return Val(mapping[a.data], a.valid, out_type, intern_dictionary(new_dict))


def _dict_predicate(a: Val, pred: Callable[[str], bool]) -> Val:
    d = a.dictionary
    if d is None:
        raise TypeError("varchar value lost its dictionary")
    table = jnp.asarray(np.array([bool(pred(s)) for s in d], np.bool_))
    return Val(table[a.data], a.valid, T.BOOLEAN)


def _varchar_infer(ts):
    return T.VARCHAR


@register("lower", _varchar_infer)
def _lower(a: Val, out_type: T.Type) -> Val:
    return _dict_transform(a, str.lower)


@register("upper", _varchar_infer)
def _upper(a: Val, out_type: T.Type) -> Val:
    return _dict_transform(a, str.upper)


@register("length", _bigint_infer)
def _length(a: Val, out_type: T.Type) -> Val:
    d = a.dictionary or ()
    table = jnp.asarray(np.array([len(s) for s in d], np.int64))
    return Val(table[a.data], a.valid, T.BIGINT)


@register("substr", _varchar_infer)
def _substr(a: Val, start: Val, *rest, out_type: T.Type) -> Val:
    s0 = int(_require_literal(start, "substr start"))
    ln = int(_require_literal(rest[0], "substr length")) if rest else None

    def f(s: str) -> str:
        i = s0 - 1 if s0 > 0 else len(s) + s0
        return s[i : i + ln] if ln is not None else s[i:]

    return _dict_transform(a, f)


@register("trim", _varchar_infer)
def _trim(a: Val, out_type: T.Type) -> Val:
    return _dict_transform(a, str.strip)


@register("concat", _varchar_infer)
def _concat(*vals, out_type: T.Type) -> Val:
    # concat of dictionary columns multiplies dictionaries; support the
    # common literal/column cases by materializing the cross dictionary only
    # when both sides are small.
    a, b = vals
    da, db = a.dictionary or (), b.dictionary or ()
    if len(da) * len(db) > 1_000_000:
        raise NotImplementedError("concat of two large-dictionary columns")
    merged = tuple(sorted({x + y for x in da for y in db}))
    index = {s: i for i, s in enumerate(merged)}
    table = np.empty((len(da), len(db)), np.int32)
    for i, x in enumerate(da):
        for j, y in enumerate(db):
            table[i, j] = index[x + y]
    t = jnp.asarray(table)
    return Val(
        t[a.data, b.data], and_valid(a.valid, b.valid), T.VARCHAR, intern_dictionary(merged)
    )


def like_pattern_to_regex(pattern: str, escape: Optional[str] = None) -> "re.Pattern":
    out = []
    i = 0
    while i < len(pattern):
        c = pattern[i]
        if escape and c == escape and i + 1 < len(pattern):
            out.append(re.escape(pattern[i + 1]))
            i += 2
            continue
        if c == "%":
            out.append(".*")
        elif c == "_":
            out.append(".")
        else:
            out.append(re.escape(c))
        i += 1
    return re.compile("".join(out), re.DOTALL)


@register("like", _bool_infer)
def _like(a: Val, pattern: Val, *rest, out_type: T.Type) -> Val:
    pat = _require_literal(pattern, "LIKE pattern")
    esc = None
    if rest:
        esc = _require_literal(rest[0], "LIKE escape")
    rx = like_pattern_to_regex(pat, esc)
    return _dict_predicate(a, lambda s: rx.fullmatch(s) is not None)


@register("strpos", _bigint_infer)
def _strpos(a: Val, needle: Val, out_type: T.Type) -> Val:
    n = _require_literal(needle, "strpos needle")
    d = a.dictionary or ()
    table = jnp.asarray(np.array([s.find(n) + 1 for s in d], np.int64))
    return Val(table[a.data], a.valid, T.BIGINT)


# ---------------------------------------------------------------------------
# math scalars, batch 2 (reference operator/scalar/MathFunctions.java)
# ---------------------------------------------------------------------------


def _unary_double(name, fn, domain=None):
    @register(name, _double_infer)
    def _f(a: Val, out_type: T.Type) -> Val:
        x = _to_double(a)
        data = fn(x)
        valid = a.valid
        if domain is not None:
            valid = and_valid(valid, domain(x))
        return Val(data, valid, T.DOUBLE)

    return _f


_unary_double("log10", jnp.log10, domain=lambda x: x > 0)
_unary_double("log2", jnp.log2, domain=lambda x: x > 0)
_unary_double("cbrt", jnp.cbrt)
_unary_double("degrees", jnp.degrees)
_unary_double("radians", jnp.radians)
_unary_double("sin", jnp.sin)
_unary_double("cos", jnp.cos)
_unary_double("tan", jnp.tan)
_unary_double("asin", jnp.arcsin, domain=lambda x: jnp.abs(x) <= 1)
_unary_double("acos", jnp.arccos, domain=lambda x: jnp.abs(x) <= 1)
_unary_double("atan", jnp.arctan)
_unary_double("sinh", jnp.sinh)
_unary_double("cosh", jnp.cosh)
_unary_double("tanh", jnp.tanh)


@register("atan2", _double_infer)
def _atan2(a: Val, b: Val, out_type: T.Type) -> Val:
    return Val(
        jnp.arctan2(_to_double(a), _to_double(b)),
        and_valid(a.valid, b.valid),
        T.DOUBLE,
    )


@register("log", _double_infer)
def _log(a: Val, b: Val, out_type: T.Type) -> Val:
    """log(base, x) (reference MathFunctions.log)."""
    base = _to_double(a)
    x = _to_double(b)
    data = jnp.log(x) / jnp.log(base)
    ok = (x > 0) & (base > 0) & (base != 1)
    return Val(data, and_valid(a.valid, b.valid, ok), T.DOUBLE)


@register("sign", _same_as_first)
def _sign(a: Val, out_type: T.Type) -> Val:
    if a.data.ndim == 2:
        from ..ops import decimal128 as d128

        sg = d128.dsign(a.data)
        return Val(d128.from_int64(sg * 10**out_type.scale), a.valid, out_type)
    if isinstance(out_type, T.DecimalType):
        data = jnp.sign(a.data) * (10**out_type.scale)
        return Val(data.astype(jnp.int64), a.valid, out_type)
    return Val(jnp.sign(a.data), a.valid, out_type)


@register("mod", _arith_infer("modulus"))
def _mod(a: Val, b: Val, out_type: T.Type) -> Val:
    return FUNCTIONS["modulus"].impl(a, b, out_type=out_type)


def _truncate_infer(ts):
    return ts[0]


@register("truncate", _truncate_infer)
def _truncate(a: Val, out_type: T.Type) -> Val:
    """Truncate toward zero (reference MathFunctions.truncate)."""
    if T.is_floating(a.type):
        return Val(jnp.trunc(a.data), a.valid, out_type)
    if isinstance(a.type, T.DecimalType):
        s = a.type.scale
        if s == 0:
            return a
        if a.data.ndim == 2:  # long decimal: lane-exact trunc to scale 0
            from ..ops import decimal128 as d128

            neg = a.data[..., 0] < 0
            mag = d128.dabs(a.data)
            p = s
            while p > 0:
                step = min(p, 9)
                mag, _ = d128._divmod_nonneg(mag, jnp.int64(10**step))
                p -= step
            mag = d128.rescale(mag, s)
            data = jnp.where(neg[..., None], d128.dneg(mag), mag)
            return Val(data, a.valid, out_type)
        p = 10**s
        data = (jnp.abs(a.data) // p) * p * jnp.sign(a.data)
        return Val(data, a.valid, out_type)
    return a


@register("width_bucket", _bigint_infer)
def _width_bucket(x: Val, lo: Val, hi: Val, n: Val, out_type: T.Type) -> Val:
    xv, lov, hiv = _to_double(x), _to_double(lo), _to_double(hi)
    nv = n.data.astype(jnp.int64)
    frac = (xv - lov) / (hiv - lov)
    b = jnp.floor(frac * nv.astype(jnp.float64)).astype(jnp.int64) + 1
    b = jnp.clip(b, 0, nv + 1)
    return Val(b, and_valid(x.valid, lo.valid, hi.valid, n.valid), T.BIGINT)


@register("is_nan", _bool_infer)
def _is_nan(a: Val, out_type: T.Type) -> Val:
    return Val(jnp.isnan(_to_double(a)), a.valid, T.BOOLEAN)


@register("is_finite", _bool_infer)
def _is_finite(a: Val, out_type: T.Type) -> Val:
    return Val(jnp.isfinite(_to_double(a)), a.valid, T.BOOLEAN)


@register("is_infinite", _bool_infer)
def _is_infinite(a: Val, out_type: T.Type) -> Val:
    return Val(jnp.isinf(_to_double(a)), a.valid, T.BOOLEAN)


def _nary_common_infer(ts):
    out = ts[0]
    for t2 in ts[1:]:
        out = T.common_super_type(out, t2)
    return out


def _minmax_nary(name, op, want_larger: bool):
    @register(name, _nary_common_infer)
    def _f(*vals, out_type: T.Type) -> Val:
        # NULL-propagating (reference greatest/least return NULL on any NULL)
        valid = and_valid(*[v.valid for v in vals])
        if isinstance(out_type, T.VarcharType):
            acc = vals[0]
            for v in vals[1:]:
                require_sorted_dict(acc, name)
                require_sorted_dict(v, name)
                xa, xb, did = unify_dictionaries(acc, v)
                acc = Val(op(xa, xb), None, out_type, did)
            return Val(acc.data, valid, out_type, acc.dict_id)
        if isinstance(out_type, T.DecimalType) and out_type.is_long:
            from ..ops import decimal128 as d128

            acc = _to_lanes(vals[0], out_type.scale)
            for v in vals[1:]:
                c = _to_lanes(v, out_type.scale)
                take = d128.dcmp_lt(acc, c) if want_larger else d128.dcmp_lt(c, acc)
                acc = jnp.where(take[..., None], c, acc)
            return Val(acc, valid, out_type)
        from .compiler import _cast_val

        cs = [_cast_val(v, out_type) for v in vals]
        data = cs[0].data
        for c in cs[1:]:
            data = op(data, c.data)
        return Val(data, valid, out_type)

    return _f


_minmax_nary("greatest", jnp.maximum, True)
_minmax_nary("least", jnp.minimum, False)


# -- bitwise (reference operator/scalar/BitwiseFunctions.java) --------------


def _bitwise(name, fn):
    @register(name, _bigint_infer)
    def _f(a: Val, b: Val, out_type: T.Type) -> Val:
        x = a.data.astype(jnp.int64)
        y = b.data.astype(jnp.int64)
        return Val(fn(x, y), and_valid(a.valid, b.valid), T.BIGINT)

    return _f


_bitwise("bitwise_and", lambda x, y: x & y)
_bitwise("bitwise_or", lambda x, y: x | y)
_bitwise("bitwise_xor", lambda x, y: x ^ y)
_bitwise("bitwise_left_shift", lambda x, y: x << y)
_bitwise("bitwise_right_shift", lambda x, y: (x.view(jnp.uint64) >> y.view(jnp.uint64)).view(jnp.int64))
_bitwise("bitwise_arithmetic_shift_right", lambda x, y: x >> y)


@register("bitwise_not", _bigint_infer)
def _bitwise_not(a: Val, out_type: T.Type) -> Val:
    return Val(~a.data.astype(jnp.int64), a.valid, T.BIGINT)


@register("bit_count", _bigint_infer)
def _bit_count(a: Val, b: Val, out_type: T.Type) -> Val:
    """bit_count(x, bits) (reference BitwiseFunctions.bitCount)."""
    bits = int(_require_literal(b, "bit_count bits"))
    x = a.data.astype(jnp.int64)
    if bits < 64:
        mask = (np.int64(1) << bits) - 1
        x = x & mask
    cnt = jnp.bitwise_count(x.view(jnp.uint64)).astype(jnp.int64)
    return Val(cnt, a.valid, T.BIGINT)


# ---------------------------------------------------------------------------
# string scalars, batch 2 (reference operator/scalar/StringFunctions.java)
# ---------------------------------------------------------------------------


def _dict_str_fn(name, fn):
    @register(name, _varchar_infer)
    def _f(a: Val, *rest, out_type: T.Type) -> Val:
        lits = [_require_literal(r, f"{name} argument") for r in rest]
        return _dict_transform(a, lambda s: fn(s, *lits))

    return _f


_dict_str_fn("reverse", lambda s: s[::-1])
_dict_str_fn("ltrim", lambda s, *a: s.lstrip(*a))
_dict_str_fn("rtrim", lambda s, *a: s.rstrip(*a))
_dict_str_fn("replace", lambda s, old, new="": s.replace(old, new))
_dict_str_fn(
    "lpad",
    lambda s, n, pad=" ": s[: int(n)]
    if len(s) >= int(n)
    else (pad * int(n))[: int(n) - len(s)] + s,
)
_dict_str_fn(
    "rpad",
    lambda s, n, pad=" ": s[: int(n)]
    if len(s) >= int(n)
    else s + (pad * int(n))[: int(n) - len(s)],
)
# split_part is registered in the breadth-pass section below (NULL past
# the last field, which the simple _dict_str_fn form cannot express)


@register("starts_with", _bool_infer)
def _starts_with(a: Val, prefix: Val, out_type: T.Type) -> Val:
    p = _require_literal(prefix, "starts_with prefix")
    return _dict_predicate(a, lambda s: s.startswith(p))


@register("ends_with", _bool_infer)
def _ends_with(a: Val, suffix: Val, out_type: T.Type) -> Val:
    p = _require_literal(suffix, "ends_with suffix")
    return _dict_predicate(a, lambda s: s.endswith(p))


@register("codepoint", _bigint_infer)
def _codepoint(a: Val, out_type: T.Type) -> Val:
    d = a.dictionary or ()
    table = jnp.asarray(
        np.array([ord(s[0]) if s else 0 for s in d], np.int64)
    )
    return Val(table[a.data], a.valid, T.BIGINT)


@register("chr", _varchar_infer)
def _chr(a: Val, out_type: T.Type) -> Val:
    n = int(_require_literal(a, "chr codepoint"))
    d = (chr(n),)
    return Val(
        jnp.zeros_like(a.data, dtype=jnp.int32), a.valid, T.VARCHAR,
        intern_dictionary(d),
    )


@register("levenshtein_distance", _bigint_infer)
def _levenshtein(a: Val, b: Val, out_type: T.Type) -> Val:
    target = _require_literal(b, "levenshtein_distance target")

    def lev(s: str) -> int:
        prev = list(range(len(target) + 1))
        for i, cs in enumerate(s, 1):
            cur = [i]
            for j, ct in enumerate(target, 1):
                cur.append(
                    min(prev[j] + 1, cur[j - 1] + 1, prev[j - 1] + (cs != ct))
                )
            prev = cur
        return prev[-1]

    d = a.dictionary or ()
    table = jnp.asarray(np.array([lev(s) for s in d], np.int64))
    return Val(table[a.data], a.valid, T.BIGINT)


@register("hamming_distance", _bigint_infer)
def _hamming(a: Val, b: Val, out_type: T.Type) -> Val:
    target = _require_literal(b, "hamming_distance target")

    def ham(s: str) -> int:
        if len(s) != len(target):
            return -1  # reference raises; NULL-out below
        return sum(x != y for x, y in zip(s, target))

    d = a.dictionary or ()
    arr = np.array([ham(s) for s in d], np.int64)
    table = jnp.asarray(arr)
    got = table[a.data]
    return Val(got, and_valid(a.valid, got >= 0), T.BIGINT)


# -- regexp (reference operator/scalar/Re2JRegexpFunctions / joni) ----------


@register("regexp_like", _bool_infer)
def _regexp_like(a: Val, pattern: Val, out_type: T.Type) -> Val:
    pat = re.compile(_require_literal(pattern, "regexp pattern"))
    return _dict_predicate(a, lambda s: pat.search(s) is not None)


@register("regexp_replace", _varchar_infer)
def _regexp_replace(a: Val, pattern: Val, *rest, out_type: T.Type) -> Val:
    pat = re.compile(_require_literal(pattern, "regexp pattern"))
    repl = _require_literal(rest[0], "replacement") if rest else ""
    repl = re.sub(r"\$(\d+)", r"\\\1", repl)  # $1 -> \1 group syntax
    return _dict_transform(a, lambda s: pat.sub(repl, s))


@register("regexp_extract", _varchar_infer)
def _regexp_extract(a: Val, pattern: Val, *rest, out_type: T.Type) -> Val:
    pat = re.compile(_require_literal(pattern, "regexp pattern"))
    group = int(_require_literal(rest[0], "group")) if rest else 0
    d = a.dictionary or ()
    results = []
    nulls = []
    for s in d:
        m = pat.search(s)
        g = None if m is None else m.group(group)
        if g is None:  # no match, or the group didn't participate
            results.append("")
            nulls.append(True)
        else:
            results.append(g)
            nulls.append(False)
    new_dict = tuple(sorted(set(results)))
    index = {s: i for i, s in enumerate(new_dict)}
    mapping = jnp.asarray(np.array([index[r] for r in results], np.int32))
    nullmap = jnp.asarray(np.array(nulls, np.bool_))
    valid = and_valid(a.valid, ~nullmap[a.data])
    return Val(mapping[a.data], valid, T.VARCHAR, intern_dictionary(new_dict))


@register("regexp_count", _bigint_infer)
def _regexp_count(a: Val, pattern: Val, out_type: T.Type) -> Val:
    pat = re.compile(_require_literal(pattern, "regexp pattern"))
    d = a.dictionary or ()
    table = jnp.asarray(
        np.array([len(pat.findall(s)) for s in d], np.int64)
    )
    return Val(table[a.data], a.valid, T.BIGINT)


# ---------------------------------------------------------------------------
# datetime scalars, batch 2 (reference operator/scalar/DateTimeFunctions.java)
# ---------------------------------------------------------------------------


@register("day_of_week", _bigint_infer)
def _day_of_week(a: Val, out_type: T.Type) -> Val:
    # ISO: Monday=1..Sunday=7; 1970-01-01 was a Thursday (=4)
    dow = (a.data.astype(jnp.int64) + 3) % 7 + 1
    return Val(dow, a.valid, T.BIGINT)


@register("dow", _bigint_infer)
def _dow(a: Val, out_type: T.Type) -> Val:
    return FUNCTIONS["day_of_week"].impl(a, out_type=out_type)


@register("day_of_year", _bigint_infer)
def _day_of_year(a: Val, out_type: T.Type) -> Val:
    days = a.data.astype(jnp.int64)
    y, _, _ = dt.days_to_civil(days)
    jan1 = dt.civil_to_days(y, jnp.ones_like(y), jnp.ones_like(y))
    return Val(days - jan1 + 1, a.valid, T.BIGINT)


@register("doy", _bigint_infer)
def _doy(a: Val, out_type: T.Type) -> Val:
    return FUNCTIONS["day_of_year"].impl(a, out_type=out_type)


@register("week", _bigint_infer)
def _week(a: Val, out_type: T.Type) -> Val:
    """ISO-8601 week number (reference DateTimeFunctions.weekFromDate)."""
    days = a.data.astype(jnp.int64)
    # ISO week = week containing the first Thursday of the year.
    # thursday of this date's week:
    thursday = days - ((days + 3) % 7) + 3
    y, _, _ = dt.days_to_civil(thursday)
    jan1 = dt.civil_to_days(y, jnp.ones_like(y), jnp.ones_like(y))
    week = (thursday - jan1) // 7 + 1
    return Val(week, a.valid, T.BIGINT)


def _date_infer(ts):
    return T.DATE


@register("last_day_of_month", _date_infer)
def _last_day_of_month_fn(a: Val, out_type: T.Type) -> Val:
    days = a.data.astype(jnp.int64)
    y, m, _ = dt.days_to_civil(days)
    ld = dt.last_day_of_month(y, m)
    out = dt.civil_to_days(y, m, ld)
    return Val(out.astype(jnp.int32), a.valid, T.DATE)


_TS_US = 1_000_000


@register("hour", _bigint_infer)
def _hour(a: Val, out_type: T.Type) -> Val:
    if isinstance(a.type, T.DateType):
        return Val(jnp.zeros_like(a.data, dtype=jnp.int64), a.valid, T.BIGINT)
    us = a.data.astype(jnp.int64)
    return Val((us // (3600 * _TS_US)) % 24, a.valid, T.BIGINT)


@register("minute", _bigint_infer)
def _minute(a: Val, out_type: T.Type) -> Val:
    if isinstance(a.type, T.DateType):
        return Val(jnp.zeros_like(a.data, dtype=jnp.int64), a.valid, T.BIGINT)
    us = a.data.astype(jnp.int64)
    return Val((us // (60 * _TS_US)) % 60, a.valid, T.BIGINT)


@register("second", _bigint_infer)
def _second(a: Val, out_type: T.Type) -> Val:
    if isinstance(a.type, T.DateType):
        return Val(jnp.zeros_like(a.data, dtype=jnp.int64), a.valid, T.BIGINT)
    us = a.data.astype(jnp.int64)
    return Val((us // _TS_US) % 60, a.valid, T.BIGINT)


@register("millisecond", _bigint_infer)
def _millisecond(a: Val, out_type: T.Type) -> Val:
    us = a.data.astype(jnp.int64)
    return Val((us // 1000) % 1000, a.valid, T.BIGINT)


def _datetrunc_infer(ts):
    return ts[1]


@register("date_trunc", _datetrunc_infer)
def _date_trunc(unit: Val, a: Val, out_type: T.Type) -> Val:
    u = _require_literal(unit, "date_trunc unit").lower()
    if isinstance(a.type, T.TimestampType):
        us = a.data.astype(jnp.int64)
        per = {
            "second": _TS_US,
            "minute": 60 * _TS_US,
            "hour": 3600 * _TS_US,
            "day": 86400 * _TS_US,
        }.get(u)
        if per is None:
            raise NotImplementedError(f"date_trunc({u!r}) on timestamp")
        return Val((us // per) * per, a.valid, a.type)
    days = a.data.astype(jnp.int64)
    y, m, d = dt.days_to_civil(days)
    one = jnp.ones_like(y)
    if u == "day":
        out = days
    elif u == "week":
        out = days - (days + 3) % 7  # back to Monday
    elif u == "month":
        out = dt.civil_to_days(y, m, one)
    elif u == "quarter":
        qm = ((m - 1) // 3) * 3 + 1
        out = dt.civil_to_days(y, qm, one)
    elif u == "year":
        out = dt.civil_to_days(y, one, one)
    else:
        raise NotImplementedError(f"date_trunc unit {u!r}")
    return Val(out.astype(jnp.int32), a.valid, T.DATE)


@register("date_add", lambda ts: ts[2])  # result type = the datetime arg's
def _date_add(unit: Val, n: Val, a: Val, out_type: T.Type) -> Val:
    u = _require_literal(unit, "date_add unit").lower()
    amount = n.data.astype(jnp.int64)
    if isinstance(a.type, T.TimestampType):
        per = {
            "second": _TS_US,
            "minute": 60 * _TS_US,
            "hour": 3600 * _TS_US,
            "day": 86400 * _TS_US,
            "week": 7 * 86400 * _TS_US,
        }.get(u)
        if per is None:
            raise NotImplementedError(f"date_add({u!r}) on timestamp")
        return Val(a.data + amount * per, and_valid(a.valid, n.valid), a.type)
    days = a.data.astype(jnp.int64)
    if u == "day":
        out = days + amount
    elif u == "week":
        out = days + 7 * amount
    elif u == "month":
        out = dt.add_months(days, amount)
    elif u == "quarter":
        out = dt.add_months(days, 3 * amount)
    elif u == "year":
        out = dt.add_months(days, 12 * amount)
    else:
        raise NotImplementedError(f"date_add unit {u!r}")
    return Val(out.astype(jnp.int32), and_valid(a.valid, n.valid), T.DATE)


@register("date_diff", _bigint_infer)
def _date_diff(unit: Val, a: Val, b: Val, out_type: T.Type) -> Val:
    u = _require_literal(unit, "date_diff unit").lower()
    valid = and_valid(a.valid, b.valid)
    if isinstance(a.type, T.TimestampType) or isinstance(b.type, T.TimestampType):
        per = {
            "second": _TS_US,
            "minute": 60 * _TS_US,
            "hour": 3600 * _TS_US,
            "day": 86400 * _TS_US,
            "week": 7 * 86400 * _TS_US,
        }.get(u)
        if per is None:
            raise NotImplementedError(f"date_diff({u!r}) on timestamp")
        delta = b.data - a.data
        # truncate toward zero (reference DateTimeFunctions.diff semantics)
        return Val(jnp.sign(delta) * (jnp.abs(delta) // per), valid, T.BIGINT)
    d1 = a.data.astype(jnp.int64)
    d2 = b.data.astype(jnp.int64)
    if u == "day":
        out = d2 - d1
    elif u == "week":
        out = jnp.sign(d2 - d1) * (jnp.abs(d2 - d1) // 7)
    elif u in ("month", "quarter", "year"):
        y1, m1, dd1 = dt.days_to_civil(d1)
        y2, m2, dd2 = dt.days_to_civil(d2)
        months = (y2 - y1) * 12 + (m2 - m1)
        # partial months don't count (reference: diffMonth truncates)
        months = months - jnp.where(
            (d2 >= d1) & (dd2 < dd1), 1, 0
        ) + jnp.where((d2 < d1) & (dd2 > dd1), 1, 0)
        if u == "month":
            out = months
        elif u == "quarter":
            out = months // 3
        else:
            out = months // 12
    else:
        raise NotImplementedError(f"date_diff unit {u!r}")
    return Val(out, valid, T.BIGINT)


# ---------------------------------------------------------------------------
# breadth pass 2: datetime formatting/parsing, JSON, URL
# (reference operator/scalar/DateTimeFunctions.java, JsonFunctions.java +
# JsonExtract.java, UrlFunctions.java)
# ---------------------------------------------------------------------------


def _alias(new: str, existing: str):
    f = FUNCTIONS[existing]
    FUNCTIONS[new] = ScalarFunction(new, f.infer, f.impl)


_alias("day_of_month", "day")
_alias("week_of_year", "week")


@register("year_of_week", _bigint_infer)
def _year_of_week(a: Val, out_type: T.Type) -> Val:
    """ISO week-numbering year (reference DateTimeFunctions.yearOfWeek)."""
    if isinstance(a.type, T.TimestampType):
        days = (a.data // (86400 * _TS_US)).astype(jnp.int64)
    else:
        days = a.data.astype(jnp.int64)
    thursday = days - ((days + 3) % 7) + 3
    y, _, _ = dt.days_to_civil(thursday)
    return Val(y.astype(jnp.int64), a.valid, T.BIGINT)


_alias("yow", "year_of_week")


@register("from_unixtime", lambda ts: T.TIMESTAMP)
def _from_unixtime(a: Val, out_type: T.Type) -> Val:
    secs = _to_double(a)
    return Val((secs * _TS_US).astype(jnp.int64), a.valid, T.TIMESTAMP)


@register("to_unixtime", _double_infer)
def _to_unixtime(a: Val, out_type: T.Type) -> Val:
    if isinstance(a.type, T.DateType):
        return Val(a.data.astype(jnp.float64) * 86400.0, a.valid, T.DOUBLE)
    return Val(a.data.astype(jnp.float64) / _TS_US, a.valid, T.DOUBLE)


# split_part returns NULL past the last field (reference
# StringFunctions.splitPart)
@register("split_part", _varchar_infer)
def _split_part_null(a: Val, delim: Val, index: Val, out_type: T.Type) -> Val:
    d = _require_literal(delim, "split_part delimiter")
    i = int(_require_literal(index, "split_part index"))
    if i < 1:
        raise ValueError("split_part index must be >= 1")

    def f(s: str) -> str:
        parts = s.split(d)
        return parts[i - 1] if i <= len(parts) else ""

    out = _dict_transform(a, f)
    has = _dict_predicate(a, lambda s: i <= len(s.split(d)))
    return Val(out.data, and_valid(out.valid, has.data), out.type, out.dict_id)


def _mysql_format_date(d, fmt: str) -> str:
    """MySQL format specifiers over a python date (the reference's
    date_format uses MySQL syntax, DateTimeFunctions.DATE_FORMATTER)."""
    out = []
    i = 0
    while i < len(fmt):
        c = fmt[i]
        if c == "%" and i + 1 < len(fmt):
            s = fmt[i + 1]
            i += 2
            if s == "Y":
                out.append(f"{d.year:04d}")
            elif s == "y":
                out.append(f"{d.year % 100:02d}")
            elif s == "m":
                out.append(f"{d.month:02d}")
            elif s == "c":
                out.append(str(d.month))
            elif s == "d":
                out.append(f"{d.day:02d}")
            elif s == "e":
                out.append(str(d.day))
            elif s == "j":
                out.append(f"{d.timetuple().tm_yday:03d}")
            elif s == "M":
                out.append(d.strftime("%B"))
            elif s == "b":
                out.append(d.strftime("%b"))
            elif s == "W":
                out.append(d.strftime("%A"))
            elif s == "a":
                out.append(d.strftime("%a"))
            elif s in ("H", "i", "s"):
                out.append("00")  # date has no time part
            elif s == "%":
                out.append("%")
            else:
                raise NotImplementedError(f"date_format specifier %{s}")
        else:
            out.append(c)
            i += 1
    return "".join(out)


_DATE_FMT_BASE = -141427  # 1582-10-15 (Gregorian adoption)
_DATE_FMT_N = 335371  # through 2500-12-31
_DATE_FMT_CACHE: dict = {}


def _date_format_table(fmt: str):
    """day-number -> formatted-string mapping over 1582..2500, deduped into
    a sorted dictionary (eager: ~335k format calls once per format, then
    cached). Dedup keeps GROUP BY/equality on the result correct — equal
    strings always map to equal codes."""
    cached = _DATE_FMT_CACHE.get(fmt)
    if cached is not None:
        return cached
    import datetime as _dt

    base = _dt.date(1582, 10, 15)
    strings = [
        _mysql_format_date(base + _dt.timedelta(days=i), fmt)
        for i in range(_DATE_FMT_N)
    ]
    dictionary = tuple(sorted(set(strings)))
    index = {s: i for i, s in enumerate(dictionary)}
    # cache host-side: a jnp array created inside one jit trace must not
    # leak into another (UnexpectedTracerError); jnp.asarray at use site
    # folds it into each kernel as a constant
    mapping = np.array([index[s] for s in strings], np.int32)
    out = (dictionary, mapping)
    _DATE_FMT_CACHE[fmt] = out
    return out


@register("date_format", _varchar_infer)
def _date_format(a: Val, fmt: Val, out_type: T.Type) -> Val:
    f = _require_literal(fmt, "date_format format")
    if isinstance(a.type, T.TimestampType):
        if any(
            spec in f for spec in ("%H", "%i", "%s", "%f", "%T", "%r", "%h")
        ):
            raise NotImplementedError(
                "date_format with time-of-day specifiers on timestamp"
            )
        days = (a.data // (86400 * _TS_US)).astype(jnp.int64)
    elif isinstance(a.type, T.DateType):
        days = a.data.astype(jnp.int64)
    else:
        raise TypeError(f"date_format on {a.type}")
    dictionary, mapping = _date_format_table(f)
    off = days - _DATE_FMT_BASE
    in_range = (off >= 0) & (off < _DATE_FMT_N)
    codes = jnp.asarray(mapping)[
        jnp.clip(off, 0, _DATE_FMT_N - 1).astype(jnp.int32)
    ]
    # dates outside the precomputed 1582..2500 table come out NULL rather
    # than silently clamped to a boundary date's string
    return Val(
        codes,
        and_valid(a.valid, in_range),
        T.VARCHAR,
        intern_dictionary(dictionary),
    )


def _mysql_to_strptime(fmt: str) -> str:
    """MySQL date_parse format -> python strptime format."""
    table = {
        "Y": "%Y", "y": "%y", "m": "%m", "c": "%m", "d": "%d", "e": "%d",
        "H": "%H", "i": "%M", "s": "%S", "j": "%j", "M": "%B", "b": "%b",
        "%": "%%",
    }
    out = []
    i = 0
    while i < len(fmt):
        c = fmt[i]
        if c == "%" and i + 1 < len(fmt):
            s = fmt[i + 1]
            if s not in table:
                raise NotImplementedError(f"date_parse specifier %{s}")
            out.append(table[s])
            i += 2
        else:
            out.append(c.replace("%", "%%"))
            i += 1
    return "".join(out)


def _dict_table_nullable(a: Val, fn, np_dtype, out_type: T.Type) -> Val:
    """Numeric sibling of _dict_transform_nullable: evaluate
    fn(entry) -> (value, ok) per dictionary entry into a lookup table;
    not-ok entries come out NULL after the per-row gather."""
    d = a.dictionary
    if d is None:
        raise TypeError("varchar value lost its dictionary")
    values = np.zeros(len(d), np_dtype)
    ok = np.zeros(len(d), np.bool_)
    for i, s in enumerate(d):
        v, good = fn(s)
        if good:
            values[i] = v
            ok[i] = True
    table = jnp.asarray(values)
    oktab = jnp.asarray(ok)
    return Val(table[a.data], and_valid(a.valid, oktab[a.data]), out_type)


@register("date_parse", lambda ts: T.TIMESTAMP)
def _date_parse(a: Val, fmt: Val, out_type: T.Type) -> Val:
    import datetime as _dt

    f = _mysql_to_strptime(_require_literal(fmt, "date_parse format"))
    epoch = _dt.datetime(1970, 1, 1)

    def parse(s: str):
        try:
            return (
                int((_dt.datetime.strptime(s, f) - epoch).total_seconds() * _TS_US),
                True,
            )
        except ValueError:
            return 0, False

    return _dict_table_nullable(a, parse, np.int64, T.TIMESTAMP)


@register("from_iso8601_date", _date_infer)
def _from_iso8601_date(a: Val, out_type: T.Type) -> Val:
    def parse(s: str):
        try:
            return dt.parse_date_literal(s), True
        except Exception:  # noqa: BLE001 — malformed input -> SQL NULL
            return 0, False

    return _dict_table_nullable(a, parse, np.int32, T.DATE)


# -- JSON (reference operator/scalar/JsonFunctions.java, JsonExtract.java;
# JSON values are varchar here — dictionary host-eval per entry) -----------


def _json_path_steps(path: str):
    """Parse the JsonPath subset $.a.b[0]["c"] into access steps."""
    if not path.startswith("$"):
        raise ValueError(f"invalid JSON path {path!r}")
    steps = []
    i = 1
    n = len(path)
    while i < n:
        c = path[i]
        if c == ".":
            j = i + 1
            while j < n and path[j] not in ".[":
                j += 1
            steps.append(path[i + 1 : j])
            i = j
        elif c == "[":
            j = path.index("]", i)
            inner = path[i + 1 : j].strip()
            if inner[:1] in ("'", '"'):
                steps.append(inner[1:-1])
            else:
                steps.append(int(inner))
            i = j + 1
        else:
            raise ValueError(f"invalid JSON path {path!r}")
    return steps


def _json_get(s: str, steps):
    import json as _json

    try:
        v = _json.loads(s)
    except Exception:  # noqa: BLE001 — malformed JSON -> SQL NULL
        return None, False
    for step in steps:
        if isinstance(step, int):
            if not isinstance(v, list) or not (-len(v) <= step < len(v)):
                return None, False
            v = v[step]
        else:
            if not isinstance(v, dict) or step not in v:
                return None, False
            v = v[step]
    return v, True


def _dict_transform_nullable(a: Val, fn, out_type=T.VARCHAR) -> Val:
    """Like _dict_transform but fn returns (string, ok); not-ok entries
    come out NULL."""
    d = a.dictionary
    if d is None:
        raise TypeError("varchar value lost its dictionary")
    values, oks = [], np.empty(len(d), np.bool_)
    for i, s in enumerate(d):
        v, ok = fn(s)
        values.append(v if ok else "")
        oks[i] = ok
    new_dict = tuple(sorted(set(values)))
    index = {s: i for i, s in enumerate(new_dict)}
    codes = jnp.asarray(np.array([index[v] for v in values], np.int32))
    oktab = jnp.asarray(oks)
    return Val(
        codes[a.data],
        and_valid(a.valid, oktab[a.data]),
        out_type,
        intern_dictionary(new_dict),
    )


@register("json_extract_scalar", _varchar_infer)
def _json_extract_scalar(a: Val, path: Val, out_type: T.Type) -> Val:
    steps = _json_path_steps(_require_literal(path, "JSON path"))

    def f(s: str):
        import json as _json

        v, ok = _json_get(s, steps)
        if not ok or isinstance(v, (dict, list)) or v is None:
            return "", False
        if isinstance(v, str):
            return v, True
        # numbers/booleans keep their JSON text (1.0 stays '1.0')
        return _json.dumps(v), True

    return _dict_transform_nullable(a, f)


@register("json_extract", _varchar_infer)
def _json_extract(a: Val, path: Val, out_type: T.Type) -> Val:
    import json as _json

    steps = _json_path_steps(_require_literal(path, "JSON path"))

    def f(s: str):
        v, ok = _json_get(s, steps)
        if not ok:
            return "", False
        return _json.dumps(v, separators=(",", ":"), sort_keys=True), True

    return _dict_transform_nullable(a, f)


@register("json_array_length", _bigint_infer)
def _json_array_length(a: Val, out_type: T.Type) -> Val:
    import json as _json

    def f(s: str):
        try:
            v = _json.loads(s)
        except Exception:  # noqa: BLE001 — malformed JSON -> SQL NULL
            return 0, False
        return (len(v), True) if isinstance(v, list) else (0, False)

    return _dict_table_nullable(a, f, np.int64, T.BIGINT)


@register("json_array_contains", _bool_infer)
def _json_array_contains(a: Val, needle: Val, out_type: T.Type) -> Val:
    import json as _json

    want = _require_literal(needle, "json_array_contains value")

    def f(s: str):
        # NULL (not false) for invalid JSON / non-arrays (reference
        # JsonFunctions is @SqlNullable)
        try:
            v = _json.loads(s)
        except Exception:  # noqa: BLE001 — malformed JSON -> SQL NULL
            return False, False
        if not isinstance(v, list):
            return False, False
        if isinstance(want, bool):
            return any(x is want for x in v), True
        if isinstance(want, (int, float)):
            return (
                any(
                    not isinstance(x, bool)
                    and isinstance(x, (int, float))
                    and x == want
                    for x in v
                ),
                True,
            )
        return any(isinstance(x, str) and x == want for x in v), True

    return _dict_table_nullable(a, f, np.bool_, T.BOOLEAN)


@register("json_format", _varchar_infer)
def _json_format(a: Val, out_type: T.Type) -> Val:
    import json as _json

    def f(s: str) -> str:
        try:
            return _json.dumps(_json.loads(s), separators=(",", ":"))
        except Exception:  # noqa: BLE001 — non-JSON passes through
            # verbatim (reference json_format behavior)
            return s

    return _dict_transform(a, f)


# -- URL (reference operator/scalar/UrlFunctions.java) ----------------------


def _url_host_raw(u) -> str:
    """Case-preserving host: urlparse().hostname lowercases, the reference
    keeps the authority as written."""
    netloc = u.netloc
    if "@" in netloc:
        netloc = netloc.rsplit("@", 1)[1]
    if netloc.startswith("["):  # [ipv6]:port
        return netloc.split("]", 1)[0] + "]"
    return netloc.split(":", 1)[0]


def _url_part(name: str, getter):
    """getter(parse_result, raw) -> str or None; None/parse failure -> NULL
    (reference UrlFunctions returns null for absent components)."""

    @register(name, _varchar_infer)
    def _f(a: Val, out_type: T.Type) -> Val:
        from urllib.parse import urlparse

        def f(s: str):
            try:
                v = getter(urlparse(s), s)
            except Exception:  # noqa: BLE001 — unparseable URL -> SQL NULL
                return "", False
            return (v, True) if v is not None else ("", False)

        return _dict_transform_nullable(a, f)

    return _f


_url_part("url_extract_host", lambda u, s: _url_host_raw(u) or None)
_url_part("url_extract_protocol", lambda u, s: u.scheme or None)
_url_part("url_extract_path", lambda u, s: u.path)
_url_part(
    "url_extract_query",
    lambda u, s: u.query if "?" in s.split("#", 1)[0] else None,
)
_url_part(
    "url_extract_fragment", lambda u, s: u.fragment if "#" in s else None
)


@register("url_extract_port", _bigint_infer)
def _url_extract_port(a: Val, out_type: T.Type) -> Val:
    from urllib.parse import urlparse

    def f(s: str):
        try:
            p = urlparse(s).port
        except Exception:  # noqa: BLE001 — invalid port -> SQL NULL
            p = None
        return (p, True) if p is not None else (0, False)

    return _dict_table_nullable(a, f, np.int64, T.BIGINT)


@register("url_encode", _varchar_infer)
def _url_encode(a: Val, out_type: T.Type) -> Val:
    from urllib.parse import quote_plus

    return _dict_transform(a, lambda s: quote_plus(s))


@register("url_decode", _varchar_infer)
def _url_decode(a: Val, out_type: T.Type) -> Val:
    from urllib.parse import unquote_plus

    return _dict_transform(a, lambda s: unquote_plus(s))


# ---------------------------------------------------------------------------
# arrays (reference operator/scalar/ArrayFunctions.java + ArrayConstructor,
# ArraySubscriptOperator, SequenceFunction, StringFunctions.split).
# Representation per T.ArrayType: (capacity, width) data + per-row lengths.
# ---------------------------------------------------------------------------


def _array_infer_element(ts):
    return ts[0].element


@register("array_constructor", lambda ts: T.ArrayType(ts[0] if ts else T.UNKNOWN))
def _array_constructor(*vals, out_type: T.Type) -> Val:
    if not vals:
        raise NotImplementedError("empty ARRAY[] requires a typed context")
    width = len(vals)
    did = None
    if isinstance(out_type.element, T.VarcharType):
        # varchar elements may arrive with different dictionaries
        # (literals intern as singletons) — remap all onto one
        acc = vals[0]
        for v in vals[1:]:
            xa, xb, did = unify_dictionaries(acc, v)
            acc = Val(xa, acc.valid, acc.type, did)
        did = acc.dict_id
        remapped = []
        for v in vals:
            x, _, _ = unify_dictionaries(v, acc)
            remapped.append(x)
        data = jnp.stack(remapped, axis=1)
    else:
        data = jnp.stack([v.data for v in vals], axis=1)
    evalid = None
    if any(v.valid is not None for v in vals):
        evalid = jnp.stack([v.valid_mask() for v in vals], axis=1)
    lengths = jnp.full(vals[0].data.shape[0], width, jnp.int32)
    return Val(
        data, None, out_type, did, lengths=lengths, elem_valid=evalid
    )


@register("split", lambda ts: T.ArrayType(T.VARCHAR))
def _split(a: Val, delim: Val, *rest, out_type: T.Type) -> Val:
    """split(str, delim [, limit]) -> array(varchar) via dictionary
    host-eval: each dictionary entry splits once; codes/lengths are
    per-entry lookup tables."""
    d = a.dictionary
    if d is None:
        raise TypeError("varchar value lost its dictionary")
    sep = _require_literal(delim, "split delimiter")
    limit = int(_require_literal(rest[0], "split limit")) if rest else None
    parts_per = [
        (s.split(sep, limit - 1) if limit else s.split(sep)) for s in d
    ]
    width = max((len(p) for p in parts_per), default=1) or 1
    out_dict = tuple(sorted({p for parts in parts_per for p in parts}))
    index = {s: i for i, s in enumerate(out_dict)}
    codes = np.zeros((len(d), width), np.int32)
    lens = np.zeros(len(d), np.int32)
    for i, parts in enumerate(parts_per):
        lens[i] = len(parts)
        for j, p in enumerate(parts):
            codes[i, j] = index[p]
    ctab = jnp.asarray(codes)
    ltab = jnp.asarray(lens)
    return Val(
        ctab[a.data],
        a.valid,
        T.ArrayType(T.VARCHAR),
        intern_dictionary(out_dict),
        lengths=ltab[a.data],
    )


@register("cardinality", _bigint_infer)
def _cardinality(a: Val, out_type: T.Type) -> Val:
    if getattr(a.type, "sketch", None) == "hll":
        from ..ops.aggregate import hll_estimate

        return Val(hll_estimate(a.data), a.valid, T.BIGINT)
    if a.lengths is None:
        raise TypeError("cardinality requires an array value")
    return Val(a.lengths.astype(jnp.int64), a.valid, T.BIGINT)


def _map_infer_element(ts):
    if isinstance(ts[0], T.MapType):
        return ts[0].value
    return ts[0].element


@register("element_at", _map_infer_element)
def _element_at(a: Val, idx: Val, out_type: T.Type) -> Val:
    """1-based access; negative indexes from the end; out of range -> NULL
    (reference ArraySubscriptOperator errors on OOR, element_at nulls —
    both spellings route here, with element_at's forgiving semantics).
    For MAP values, key lookup -> value or NULL."""
    if isinstance(a.type, T.MapType):
        return _map_element_at(a, idx, out_type)
    if a.lengths is None and a.data.ndim != 2:
        raise TypeError("element_at requires an array value")
    i64 = idx.data.astype(jnp.int64)
    if a.lengths is None:
        # fixed-width array with no per-row lengths (e.g. an accumulator
        # column rebuilt from an exchange): every lane is live
        lens = jnp.full(a.data.shape[0], a.data.shape[1], jnp.int64)
    else:
        lens = a.lengths.astype(jnp.int64)
    pos = jnp.where(i64 < 0, lens + i64, i64 - 1)
    in_range = (pos >= 0) & (pos < lens)
    safe = jnp.clip(pos, 0, max(a.data.shape[1] - 1, 0)).astype(jnp.int32)
    data = jnp.take_along_axis(a.data, safe[:, None], axis=1)[:, 0]
    valid = and_valid(a.valid, idx.valid)
    valid = and_valid(valid, in_range)
    if a.elem_valid is not None:
        ev = jnp.take_along_axis(a.elem_valid, safe[:, None], axis=1)[:, 0]
        valid = and_valid(valid, ev)
    return Val(data, valid, out_type, a.dict_id)


def _array_elem_eq(a: Val, needle: Val, what: str):
    """(eq, in_len) matrices for element-vs-needle comparison, handling
    varchar dictionary mismatch (literal needles resolve against the
    array's SORTED dictionary; the guard matches _literal_cmp_fastpath)."""
    if a.lengths is None:
        raise TypeError(f"{what} requires an array value")
    width = a.data.shape[1]
    pos = jnp.arange(width, dtype=jnp.int32)[None, :]
    in_len = pos < jnp.maximum(a.lengths, 0)[:, None]
    if isinstance(needle.type, T.VarcharType) and needle.dict_id != a.dict_id:
        require_sorted_dict(a, what)
        s = _require_literal(needle, f"{what} needle")
        d = a.dictionary
        code = _bisect(d, s, "left")
        if code < len(d) and d[code] == s:
            eq = a.data == code
        else:
            eq = jnp.zeros(a.data.shape, dtype=jnp.bool_)
    else:
        eq = a.data == needle.data[:, None]
    return eq, in_len


@register("contains", _bool_infer)
def _contains(a: Val, needle: Val, out_type: T.Type) -> Val:
    eq, in_len = _array_elem_eq(a, needle, "contains")
    null_elem = jnp.zeros(in_len.shape[0], jnp.bool_)
    if a.elem_valid is not None:
        eq = eq & a.elem_valid
        null_elem = jnp.any(~a.elem_valid & in_len, axis=1)
    hit = jnp.any(eq & in_len, axis=1)
    # three-valued: not found but a NULL element present -> NULL
    valid = and_valid(a.valid, needle.valid)
    valid = and_valid(valid, hit | ~null_elem)
    return Val(hit, valid, T.BOOLEAN)


@register("array_position", _bigint_infer)
def _array_position(a: Val, needle: Val, out_type: T.Type) -> Val:
    """1-based index of the first match, 0 when absent."""
    eq, in_len = _array_elem_eq(a, needle, "array_position")
    if a.elem_valid is not None:
        eq = eq & a.elem_valid
    eq = eq & in_len
    first = jnp.where(
        jnp.any(eq, axis=1),
        jnp.argmax(eq, axis=1).astype(jnp.int64) + 1,
        0,
    )
    return Val(first, and_valid(a.valid, needle.valid), T.BIGINT)


def _map_element_at(m: Val, k: Val, out_type: T.Type) -> Val:
    eq, in_len = _array_elem_eq(m.keys, k, "map key lookup")
    hit = eq & in_len
    found = jnp.any(hit, axis=1)
    pos = jnp.argmax(hit, axis=1)
    data = jnp.take_along_axis(m.data, pos[:, None], axis=1)[:, 0]
    valid = and_valid(and_valid(m.valid, k.valid), found)
    if m.elem_valid is not None:
        ev = jnp.take_along_axis(m.elem_valid, pos[:, None], axis=1)[:, 0]
        valid = and_valid(valid, ev)
    return Val(data, valid, out_type, m.dict_id)


def _map_infer(ts):
    return T.MapType(ts[0].element, ts[1].element)


@register("map", _map_infer)
def _map_constructor(karr: Val, varr: Val, out_type: T.Type) -> Val:
    """map(key_array, value_array) (reference MapConstructor). Key and
    value arrays must be equal-length per row."""
    if karr.lengths is None or varr.lengths is None:
        raise TypeError("map() takes two array arguments")
    valid = and_valid(karr.valid, varr.valid)
    # mismatched lengths -> NULL map (the reference raises; NULL keeps the
    # kernel jittable, matching the engine's data-dependent-error policy)
    valid = and_valid(valid, karr.lengths == varr.lengths)
    keys = Val(
        karr.data, None, T.ArrayType(out_type.key), karr.dict_id,
        lengths=karr.lengths, elem_valid=karr.elem_valid,
    )
    w = max(karr.data.shape[1], varr.data.shape[1])

    def widen(d, width):
        pad = width - d.shape[1]
        if pad <= 0:
            return d
        return jnp.pad(d, ((0, 0), (0, pad)) + ((0, 0),) * (d.ndim - 2))

    keys = Val(
        widen(keys.data, w), None, keys.type, keys.dict_id,
        lengths=keys.lengths,
        elem_valid=None if keys.elem_valid is None else widen(keys.elem_valid, w),
    )
    return Val(
        widen(varr.data, w), valid, out_type, varr.dict_id,
        lengths=karr.lengths,
        elem_valid=None if varr.elem_valid is None else widen(varr.elem_valid, w),
        keys=keys,
    )


@register("map_keys", lambda ts: T.ArrayType(ts[0].key))
def _map_keys(m: Val, out_type: T.Type) -> Val:
    k = m.keys
    return Val(
        k.data, m.valid, out_type, k.dict_id,
        lengths=m.lengths, elem_valid=k.elem_valid,
    )


@register("map_values", lambda ts: T.ArrayType(ts[0].value))
def _map_values(m: Val, out_type: T.Type) -> Val:
    return Val(
        m.data, m.valid, out_type, m.dict_id,
        lengths=m.lengths, elem_valid=m.elem_valid,
    )


@register("sequence", lambda ts: T.ArrayType(ts[0]))
def _sequence(a: Val, b: Val, *rest, out_type: T.Type) -> Val:
    """sequence(start, stop [, step]) with literal bounds (static width)."""
    start = int(_require_literal(a, "sequence start"))
    stop = int(_require_literal(b, "sequence stop"))
    if rest:
        step = int(_require_literal(rest[0], "sequence step"))
    else:
        step = 1 if stop >= start else -1  # Presto: implicit descending
    if step == 0:
        raise ValueError("sequence step must be non-zero")
    if (stop - start) * step < 0:
        # reference SequenceFunction: step must move toward stop
        raise ValueError(
            f"sequence step {step} cannot reach stop {stop} from {start}"
        )
    values = list(range(start, stop + (1 if step > 0 else -1), step))
    n_elem = len(values)
    if not values:
        values = [start]
        n_elem = 0
    cap = a.data.shape[0]
    row = jnp.asarray(np.array(values, np.int64))
    data = jnp.broadcast_to(row[None, :], (cap, len(values)))
    lengths = jnp.full(cap, n_elem, jnp.int32)
    return Val(data, None, out_type, lengths=lengths)


# breadth families (math/bitwise/string/digest/array/json tail) register on
# import — keep last so they can reuse every helper above
from . import functions_ext  # noqa: E402,F401  (registration side effects)
