"""Scalar function registry.

Re-designed equivalent of the reference's FunctionRegistry
(presto-main/.../metadata/FunctionRegistry.java:360, ~380 built-ins) plus the
scalar implementations under presto-main/.../operator/scalar/. Each function
declares a type-inference rule and a trace-time implementation over `Val`
(data array + validity mask + type + dictionary id). Implementations run
inside jit tracing, so everything fuses into the surrounding kernel — the TPU
replacement for per-function JVM bytecode.

Varchar strategy: functions/predicates over strings are evaluated once per
*dictionary entry* on the host at trace time (dictionaries are static pytree
aux), then applied to the code array with one device gather. This turns
O(rows) string work into O(|dict|) host work + O(rows) int gather.

Null semantics: scalar functions are null-propagating (RETURNS NULL ON NULL
INPUT, the reference default); special forms in compiler.py implement Kleene
AND/OR, IS NULL, COALESCE, IF/CASE.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import re
from typing import Callable, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..page import dictionary_by_id, intern_dictionary
from . import datetime_kernels as dt


@dataclasses.dataclass
class Val:
    """A vectorized SQL value during expression tracing.

    `literal` holds the Python value when this Val came from a constant —
    functions with literal-only arguments (LIKE patterns, substr positions)
    must read it instead of peeking device data, which would fail under a
    jit trace."""

    data: jnp.ndarray
    valid: Optional[jnp.ndarray]  # None = no nulls
    type: T.Type
    dict_id: Optional[int] = None
    literal: object = None

    @property
    def dictionary(self) -> Optional[Tuple[str, ...]]:
        return None if self.dict_id is None else dictionary_by_id(self.dict_id)

    def valid_mask(self):
        if self.valid is None:
            return jnp.ones(self.data.shape, jnp.bool_)
        return self.valid


def and_valid(*valids):
    """Combine validity masks; None means all-valid."""
    out = None
    for v in valids:
        if v is None:
            continue
        out = v if out is None else (out & v)
    return out


# ---------------------------------------------------------------------------
# registry plumbing
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ScalarFunction:
    name: str
    infer: Callable[[Tuple[T.Type, ...]], T.Type]
    impl: Callable[..., Val]  # (*vals, out_type=Type) -> Val


FUNCTIONS: dict = {}


def register(name, infer):
    def deco(fn):
        FUNCTIONS[name] = ScalarFunction(name, infer, fn)
        return fn

    return deco


def infer_call_type(name: str, arg_types: Tuple[T.Type, ...]) -> T.Type:
    f = FUNCTIONS.get(name)
    if f is None:
        raise KeyError(f"unknown function {name!r}")
    return f.infer(arg_types)


def apply_function(name: str, vals: Sequence[Val], out_type: T.Type) -> Val:
    f = FUNCTIONS.get(name)
    if f is None:
        raise KeyError(f"unknown function {name!r}")
    return f.impl(*vals, out_type=out_type)


# ---------------------------------------------------------------------------
# type rules
# ---------------------------------------------------------------------------


def _arith_infer(op):
    def infer(ts: Tuple[T.Type, ...]) -> T.Type:
        a, b = ts
        # date/interval arithmetic
        if isinstance(a, T.DateType) or isinstance(b, T.DateType):
            if op in ("add", "subtract"):
                if isinstance(a, T.DateType) and isinstance(b, T.DateType):
                    return T.BIGINT  # date difference in days
                return T.DATE
        da, db = isinstance(a, T.DecimalType), isinstance(b, T.DecimalType)
        if T.is_floating(a) or T.is_floating(b):
            return T.DOUBLE
        if da or db:
            sa = a.scale if da else 0
            sb = b.scale if db else 0
            if op in ("add", "subtract"):
                return T.DecimalType(18, max(sa, sb))
            if op == "multiply":
                return T.DecimalType(18, min(sa + sb, 18))
            if op == "divide":
                # reference: decimal division stays decimal
                # (DecimalOperators.java); scale = max(sa, sb) after rescale
                return T.DecimalType(18, max(sa, sb, 6))
            if op == "modulus":
                return T.DecimalType(18, max(sa, sb))
        # integral
        return T.common_super_type(a, b)

    return infer


def _bool_infer(ts):
    return T.BOOLEAN


def _same_as_first(ts):
    return ts[0]


def _double_infer(ts):
    return T.DOUBLE


def _bigint_infer(ts):
    return T.BIGINT


# ---------------------------------------------------------------------------
# numeric helpers
# ---------------------------------------------------------------------------


def _scale_of(t: T.Type) -> int:
    return t.scale if isinstance(t, T.DecimalType) else 0


def _div_round(x, y):
    """Round-half-up signed integer division (SQL decimal semantics,
    reference Decimals.java HALF_UP rescale)."""
    sign = jnp.sign(x) * jnp.sign(y)
    q = (2 * jnp.abs(x) + jnp.abs(y)) // (2 * jnp.abs(y))
    return sign * q


def _rescale(data, from_scale: int, to_scale: int):
    """Rescale a scaled-int decimal; scale-down rounds half-up."""
    if to_scale == from_scale:
        return data
    if to_scale > from_scale:
        return data * (10 ** (to_scale - from_scale))
    d = 10 ** (from_scale - to_scale)
    return _div_round(data, jnp.asarray(d, data.dtype))


def _round_half_away(x):
    """SQL ROUND for floats: half away from zero (not banker's rounding)."""
    return jnp.sign(x) * jnp.floor(jnp.abs(x) + 0.5)


def _to_double(v: Val):
    s = _scale_of(v.type)
    d = v.data.astype(jnp.float64)
    return d / (10**s) if s else d


def _numeric_align(a: Val, b: Val, out_type: T.Type):
    """Bring both operands into the output type's representation."""
    if isinstance(out_type, T.DoubleType) or isinstance(out_type, T.RealType):
        return _to_double(a), _to_double(b)
    if isinstance(out_type, T.DecimalType):
        return (
            _rescale(a.data.astype(jnp.int64), _scale_of(a.type), out_type.scale),
            _rescale(b.data.astype(jnp.int64), _scale_of(b.type), out_type.scale),
        )
    dtype = out_type.storage_dtype
    return a.data.astype(dtype), b.data.astype(dtype)


# ---------------------------------------------------------------------------
# arithmetic
# ---------------------------------------------------------------------------


@register("add", _arith_infer("add"))
def _add(a: Val, b: Val, out_type: T.Type) -> Val:
    valid = and_valid(a.valid, b.valid)
    if isinstance(out_type, T.DateType):
        date, delta = (a, b) if isinstance(a.type, T.DateType) else (b, a)
        if isinstance(delta.type, T.IntervalYearMonthType):
            data = dt.add_months(date.data, delta.data)
        else:
            data = (date.data.astype(jnp.int64) + delta.data).astype(jnp.int32)
        return Val(data, valid, T.DATE)
    x, y = _numeric_align(a, b, out_type)
    return Val(x + y, valid, out_type)


@register("subtract", _arith_infer("subtract"))
def _subtract(a: Val, b: Val, out_type: T.Type) -> Val:
    valid = and_valid(a.valid, b.valid)
    if isinstance(out_type, T.DateType):
        if isinstance(b.type, T.IntervalYearMonthType):
            data = dt.add_months(a.data, -b.data)
        else:
            data = (a.data.astype(jnp.int64) - b.data).astype(jnp.int32)
        return Val(data, valid, T.DATE)
    if isinstance(a.type, T.DateType) and isinstance(b.type, T.DateType):
        return Val(a.data.astype(jnp.int64) - b.data.astype(jnp.int64), valid, T.BIGINT)
    x, y = _numeric_align(a, b, out_type)
    return Val(x - y, valid, out_type)


@register("multiply", _arith_infer("multiply"))
def _multiply(a: Val, b: Val, out_type: T.Type) -> Val:
    valid = and_valid(a.valid, b.valid)
    if isinstance(out_type, T.DecimalType):
        # scales add under multiplication: compute in raw units then the
        # result scale is sa+sb == out_type.scale (capped by inference)
        x = a.data.astype(jnp.int64)
        y = b.data.astype(jnp.int64)
        raw = x * y
        have = _scale_of(a.type) + _scale_of(b.type)
        return Val(_rescale(raw, have, out_type.scale), valid, out_type)
    x, y = _numeric_align(a, b, out_type)
    return Val(x * y, valid, out_type)


@register("divide", _arith_infer("divide"))
def _divide(a: Val, b: Val, out_type: T.Type) -> Val:
    valid = and_valid(a.valid, b.valid)
    if isinstance(out_type, T.DecimalType):
        xs, ys = _scale_of(a.type), _scale_of(b.type)
        # scale numerator so raw-int division yields out_type.scale
        x = _rescale(a.data.astype(jnp.int64), xs, out_type.scale + ys)
        y = b.data.astype(jnp.int64)
        safe = jnp.where(y == 0, 1, y)
        q = _div_round(x, safe)
        valid = and_valid(valid, b.data != 0)
        return Val(q, valid, out_type)
    x, y = _numeric_align(a, b, out_type)
    if jnp.issubdtype(jnp.result_type(x), jnp.integer):
        safe = jnp.where(y == 0, 1, y)
        q = jnp.sign(x) * jnp.sign(safe) * (jnp.abs(x) // jnp.abs(safe))
        return Val(q, and_valid(valid, y != 0), out_type)
    return Val(x / y, valid, out_type)


@register("modulus", _arith_infer("modulus"))
def _modulus(a: Val, b: Val, out_type: T.Type) -> Val:
    valid = and_valid(a.valid, b.valid)
    x, y = _numeric_align(a, b, out_type)
    if jnp.issubdtype(jnp.result_type(x), jnp.integer):
        safe = jnp.where(y == 0, 1, y)
        # truncated division remainder (sign follows dividend, SQL semantics)
        r = x - (jnp.sign(x) * jnp.sign(safe) * (jnp.abs(x) // jnp.abs(safe))) * safe
        return Val(r, and_valid(valid, y != 0), out_type)
    r = x - jnp.trunc(x / y) * y
    return Val(r, valid, out_type)


@register("negate", _same_as_first)
def _negate(a: Val, out_type: T.Type) -> Val:
    return Val(-a.data, a.valid, out_type)


# ---------------------------------------------------------------------------
# comparisons (null-propagating; varchar via dictionary codes)
# ---------------------------------------------------------------------------


def require_sorted_dict(v: Val, what: str):
    d = v.dictionary
    if d is not None and not getattr(d, "is_sorted", True):
        raise NotImplementedError(
            f"{what} on a column with an unsorted dictionary "
            f"({type(d).__name__}); codes do not order like strings"
        )


def _bisect(d, s: str, side: str) -> int:
    """Binary search over any (possibly lazy) sorted dictionary — O(log n)
    __getitem__ calls, never materializes the dictionary."""
    lo, hi = 0, len(d)
    while lo < hi:
        mid = (lo + hi) // 2
        e = d[mid]
        if e < s or (side == "right" and e == s):
            lo = mid + 1
        else:
            hi = mid
    return lo


_LITERAL_CMP = {
    "eq": lambda codes, bl, br: (codes >= bl) & (codes < br),
    "ne": lambda codes, bl, br: (codes < bl) | (codes >= br),
    "lt": lambda codes, bl, br: codes < bl,
    "le": lambda codes, bl, br: codes < br,
    "gt": lambda codes, bl, br: codes >= br,
    "ge": lambda codes, bl, br: codes >= bl,
}


def _literal_cmp_fastpath(name: str, a: Val, b: Val):
    """column <op> 'literal' without unifying dictionaries: bisect the
    literal's position in the (sorted, possibly lazy) column dictionary and
    compare codes against it. Critical for LazyDict columns (tpch c_name …)
    where unify would materialize millions of strings."""
    col_v, lit_v, flip = (a, b, False) if len(b.dictionary or ()) == 1 else (b, a, True)
    d = col_v.dictionary
    if d is None:
        return None
    require_sorted_dict(col_v, f"comparison {name!r}")
    s = lit_v.dictionary[0]
    bl = _bisect(d, s, "left")
    br = _bisect(d, s, "right")
    if flip:
        name = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le"}.get(name, name)
    return _LITERAL_CMP[name](col_v.data, jnp.int32(bl), jnp.int32(br))


def _compare(op, a: Val, b: Val, name: str = ""):
    if isinstance(a.type, T.VarcharType) and isinstance(b.type, T.VarcharType):
        if name in _LITERAL_CMP and (
            len(a.dictionary or ()) == 1 or len(b.dictionary or ()) == 1
        ):
            fast = _literal_cmp_fastpath(name, a, b)
            if fast is not None:
                return fast
        if name in ("lt", "le", "gt", "ge"):
            require_sorted_dict(a, f"comparison {name!r}")
            require_sorted_dict(b, f"comparison {name!r}")
        x, y = _unify_codes(a, b)
        return op(x, y)
    if T.is_floating(a.type) or T.is_floating(b.type):
        return op(_to_double(a), _to_double(b))
    sa, sb = _scale_of(a.type), _scale_of(b.type)
    s = max(sa, sb)
    return op(
        _rescale(a.data.astype(jnp.int64) if sa != s else a.data, sa, s),
        _rescale(b.data.astype(jnp.int64) if sb != s else b.data, sb, s),
    )


def _unify_codes(a: Val, b: Val):
    """Remap two dictionary-coded columns onto one merged sorted dictionary.
    Returns (codes_a, codes_b); `unify_dictionaries` also returns the merged
    interned dictionary id for callers that need the result dictionary."""
    xa, xb, _ = unify_dictionaries(a, b)
    return xa, xb


_UNIFY_MATERIALIZE_LIMIT = 1_000_000


def unify_dictionaries(a: Val, b: Val):
    if a.dict_id is not None and a.dict_id == b.dict_id:
        return a.data, b.data, a.dict_id
    da = a.dictionary or ()
    db = b.dictionary or ()
    if len(da) + len(db) > _UNIFY_MATERIALIZE_LIMIT:
        raise NotImplementedError(
            f"dictionary unification would materialize {len(da)}+{len(db)} "
            "entries; use a literal fast path or dictionary-preserving plan"
        )
    merged = tuple(sorted(set(da) | set(db)))
    index = {s: i for i, s in enumerate(merged)}
    map_a = jnp.asarray(np.array([index[s] for s in da], np.int32))
    map_b = jnp.asarray(np.array([index[s] for s in db], np.int32))
    xa = map_a[a.data] if len(da) else a.data
    xb = map_b[b.data] if len(db) else b.data
    return xa, xb, intern_dictionary(merged)


def _cmp_factory(name, op):
    @register(name, _bool_infer)
    def _cmp(a: Val, b: Val, out_type: T.Type) -> Val:
        return Val(_compare(op, a, b, name), and_valid(a.valid, b.valid), T.BOOLEAN)

    return _cmp


_cmp_factory("eq", lambda x, y: x == y)
_cmp_factory("ne", lambda x, y: x != y)
_cmp_factory("lt", lambda x, y: x < y)
_cmp_factory("le", lambda x, y: x <= y)
_cmp_factory("gt", lambda x, y: x > y)
_cmp_factory("ge", lambda x, y: x >= y)


# ---------------------------------------------------------------------------
# math scalars
# ---------------------------------------------------------------------------


@register("abs", _same_as_first)
def _abs(a: Val, out_type: T.Type) -> Val:
    return Val(jnp.abs(a.data), a.valid, out_type)


@register("sqrt", _double_infer)
def _sqrt(a: Val, out_type: T.Type) -> Val:
    x = _to_double(a)
    return Val(jnp.sqrt(jnp.maximum(x, 0.0)), and_valid(a.valid, x >= 0), T.DOUBLE)


@register("ln", _double_infer)
def _ln(a: Val, out_type: T.Type) -> Val:
    x = _to_double(a)
    return Val(jnp.log(jnp.maximum(x, 1e-300)), and_valid(a.valid, x > 0), T.DOUBLE)


@register("exp", _double_infer)
def _exp(a: Val, out_type: T.Type) -> Val:
    return Val(jnp.exp(_to_double(a)), a.valid, T.DOUBLE)


@register("power", _double_infer)
def _power(a: Val, b: Val, out_type: T.Type) -> Val:
    return Val(jnp.power(_to_double(a), _to_double(b)), and_valid(a.valid, b.valid), T.DOUBLE)


@register("floor", _same_as_first)
def _floor(a: Val, out_type: T.Type) -> Val:
    if T.is_floating(a.type):
        return Val(jnp.floor(a.data), a.valid, out_type)
    if isinstance(a.type, T.DecimalType):
        s = 10 ** a.type.scale
        d = jnp.where(a.data >= 0, a.data // s, -((-a.data + s - 1) // s)) * s
        return Val(d, a.valid, out_type)
    return Val(a.data, a.valid, out_type)


@register("ceil", _same_as_first)
def _ceil(a: Val, out_type: T.Type) -> Val:
    if T.is_floating(a.type):
        return Val(jnp.ceil(a.data), a.valid, out_type)
    if isinstance(a.type, T.DecimalType):
        s = 10 ** a.type.scale
        d = jnp.where(a.data >= 0, (a.data + s - 1) // s, -((-a.data) // s)) * s
        return Val(d, a.valid, out_type)
    return Val(a.data, a.valid, out_type)


def _round_infer(ts):
    a = ts[0]
    if isinstance(a, T.DecimalType):
        return a
    if T.is_floating(a):
        return T.DOUBLE
    return a


def _require_literal(v: Val, what: str):
    if v.literal is None:
        raise NotImplementedError(f"{what} requires a literal argument")
    return v.literal


@register("round", _round_infer)
def _round(a: Val, *rest, out_type: T.Type) -> Val:
    ndigits = 0
    if rest:
        (nd,) = rest
        ndigits = int(_require_literal(nd, "round precision"))
    if T.is_floating(a.type):
        f = 10.0**ndigits
        return Val(_round_half_away(a.data * f) / f, a.valid, T.DOUBLE)
    if isinstance(a.type, T.DecimalType):
        drop = a.type.scale - ndigits
        if drop <= 0:
            return Val(a.data, a.valid, a.type)
        s = 10**drop
        d = _div_round(a.data, jnp.asarray(s, a.data.dtype)) * s
        return Val(d, a.valid, a.type)
    return Val(a.data, a.valid, a.type)


# ---------------------------------------------------------------------------
# date/time
# ---------------------------------------------------------------------------


@register("year", _bigint_infer)
def _year(a: Val, out_type: T.Type) -> Val:
    return Val(dt.extract_year(a.data), a.valid, T.BIGINT)


@register("month", _bigint_infer)
def _month(a: Val, out_type: T.Type) -> Val:
    return Val(dt.extract_month(a.data), a.valid, T.BIGINT)


@register("day", _bigint_infer)
def _day(a: Val, out_type: T.Type) -> Val:
    return Val(dt.extract_day(a.data), a.valid, T.BIGINT)


@register("quarter", _bigint_infer)
def _quarter(a: Val, out_type: T.Type) -> Val:
    return Val(dt.extract_quarter(a.data), a.valid, T.BIGINT)


# ---------------------------------------------------------------------------
# varchar functions (host dictionary transforms + device gather)
# ---------------------------------------------------------------------------


def _dict_transform(a: Val, fn: Callable[[str], str], out_type=T.VARCHAR) -> Val:
    """Apply a host string function entry-wise to the dictionary; produce a
    re-sorted dictionary and remap codes with one gather."""
    d = a.dictionary
    if d is None:
        raise TypeError("varchar value lost its dictionary")
    transformed = [fn(s) for s in d]
    new_dict = tuple(sorted(set(transformed)))
    index = {s: i for i, s in enumerate(new_dict)}
    mapping = jnp.asarray(np.array([index[t] for t in transformed], np.int32))
    return Val(mapping[a.data], a.valid, out_type, intern_dictionary(new_dict))


def _dict_predicate(a: Val, pred: Callable[[str], bool]) -> Val:
    d = a.dictionary
    if d is None:
        raise TypeError("varchar value lost its dictionary")
    table = jnp.asarray(np.array([bool(pred(s)) for s in d], np.bool_))
    return Val(table[a.data], a.valid, T.BOOLEAN)


def _varchar_infer(ts):
    return T.VARCHAR


@register("lower", _varchar_infer)
def _lower(a: Val, out_type: T.Type) -> Val:
    return _dict_transform(a, str.lower)


@register("upper", _varchar_infer)
def _upper(a: Val, out_type: T.Type) -> Val:
    return _dict_transform(a, str.upper)


@register("length", _bigint_infer)
def _length(a: Val, out_type: T.Type) -> Val:
    d = a.dictionary or ()
    table = jnp.asarray(np.array([len(s) for s in d], np.int64))
    return Val(table[a.data], a.valid, T.BIGINT)


@register("substr", _varchar_infer)
def _substr(a: Val, start: Val, *rest, out_type: T.Type) -> Val:
    s0 = int(_require_literal(start, "substr start"))
    ln = int(_require_literal(rest[0], "substr length")) if rest else None

    def f(s: str) -> str:
        i = s0 - 1 if s0 > 0 else len(s) + s0
        return s[i : i + ln] if ln is not None else s[i:]

    return _dict_transform(a, f)


@register("trim", _varchar_infer)
def _trim(a: Val, out_type: T.Type) -> Val:
    return _dict_transform(a, str.strip)


@register("concat", _varchar_infer)
def _concat(*vals, out_type: T.Type) -> Val:
    # concat of dictionary columns multiplies dictionaries; support the
    # common literal/column cases by materializing the cross dictionary only
    # when both sides are small.
    a, b = vals
    da, db = a.dictionary or (), b.dictionary or ()
    if len(da) * len(db) > 1_000_000:
        raise NotImplementedError("concat of two large-dictionary columns")
    merged = tuple(sorted({x + y for x in da for y in db}))
    index = {s: i for i, s in enumerate(merged)}
    table = np.empty((len(da), len(db)), np.int32)
    for i, x in enumerate(da):
        for j, y in enumerate(db):
            table[i, j] = index[x + y]
    t = jnp.asarray(table)
    return Val(
        t[a.data, b.data], and_valid(a.valid, b.valid), T.VARCHAR, intern_dictionary(merged)
    )


def like_pattern_to_regex(pattern: str, escape: Optional[str] = None) -> "re.Pattern":
    out = []
    i = 0
    while i < len(pattern):
        c = pattern[i]
        if escape and c == escape and i + 1 < len(pattern):
            out.append(re.escape(pattern[i + 1]))
            i += 2
            continue
        if c == "%":
            out.append(".*")
        elif c == "_":
            out.append(".")
        else:
            out.append(re.escape(c))
        i += 1
    return re.compile("".join(out), re.DOTALL)


@register("like", _bool_infer)
def _like(a: Val, pattern: Val, *rest, out_type: T.Type) -> Val:
    pat = _require_literal(pattern, "LIKE pattern")
    esc = None
    if rest:
        esc = _require_literal(rest[0], "LIKE escape")
    rx = like_pattern_to_regex(pat, esc)
    return _dict_predicate(a, lambda s: rx.fullmatch(s) is not None)


@register("strpos", _bigint_infer)
def _strpos(a: Val, needle: Val, out_type: T.Type) -> Val:
    n = _require_literal(needle, "strpos needle")
    d = a.dictionary or ()
    table = jnp.asarray(np.array([s.find(n) + 1 for s in d], np.int64))
    return Val(table[a.data], a.valid, T.BIGINT)
