"""Scalar function breadth — the registry's long tail.

Families mirroring the reference's FunctionRegistry.java:360 registrations
(operator/scalar/MathFunctions.java, StringFunctions.java,
VarbinaryFunctions.java, HmacFunctions.java, ArrayFunctions + array/*.java,
JsonFunctions.java, BitwiseFunctions.java, CombineHashFunction ...),
implemented TPU-first: numeric functions are jnp elementwise kernels that
fuse into the surrounding expression; varchar functions evaluate once per
DICTIONARY entry on host and remap codes with one device gather
(functions.py `_dict_transform` model). Binary-typed functions
(md5/sha/base64/hex) operate on the utf8 bytes of varchar values and
return lowercase-hex / base64 varchar — this engine has no VARBINARY
column type, so the digest surface is exposed at the string layer.

Imported for its registration side effects at the bottom of functions.py.
"""

from __future__ import annotations

import base64
import hashlib
import hmac as _hmac
import json
import math
import unicodedata
import zlib
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as T
from .functions import (
    FUNCTIONS,
    Val,
    _alias,
    _bigint_infer,
    _bool_infer,
    _dict_predicate,
    _dict_transform,
    _dict_transform_nullable,
    _double_infer,
    _require_literal,
    _varchar_infer,
    and_valid,
    intern_dictionary,
    register,
)

# ---------------------------------------------------------------------------
# math tail (reference MathFunctions.java)
# ---------------------------------------------------------------------------


def _as_float(v: Val) -> jnp.ndarray:
    x = v.data
    if isinstance(v.type, T.DecimalType):
        if x.ndim == 2:
            from ..ops import decimal128 as d128

            x = d128.to_float64(x)
        return x.astype(jnp.float64) / (10**v.type.scale)
    return x.astype(jnp.float64)


def _f1(name: str, fn):
    @register(name, _double_infer)
    def _impl(a: Val, out_type: T.Type, _fn=fn) -> Val:
        return Val(_fn(_as_float(a)), a.valid, T.DOUBLE)

    return _impl


# (trig/log/cbrt/degrees/radians already live in functions.py with domain
# masks — only the genuinely-new tail registers here)
_f1("expm1", jnp.expm1)
_f1("log1p", jnp.log1p)


@register("e", _double_infer)
def _e(out_type: T.Type) -> Val:
    return Val(jnp.asarray(math.e), None, T.DOUBLE, literal=math.e)


@register("pi", _double_infer)
def _pi(out_type: T.Type) -> Val:
    return Val(jnp.asarray(math.pi), None, T.DOUBLE, literal=math.pi)


@register("infinity", _double_infer)
def _infinity(out_type: T.Type) -> Val:
    return Val(jnp.asarray(math.inf), None, T.DOUBLE, literal=math.inf)


@register("nan", _double_infer)
def _nan(out_type: T.Type) -> Val:
    return Val(jnp.asarray(math.nan), None, T.DOUBLE, literal=math.nan)


@register("to_base", _varchar_infer)
def _to_base(a: Val, radix: Val, out_type: T.Type) -> Val:
    """Integer literal -> digits in base 2..36. Varchar values here are
    dictionary-encoded; an arbitrary integer COLUMN has an unbounded
    output dictionary, so (unlike the reference's slice-returning
    MathFunctions.toBase) only literal/constant inputs are supported —
    the common SQL usage (`to_base(25, 2)` style)."""
    r = int(_require_literal(radix, "to_base radix"))
    if not (2 <= r <= 36):
        raise ValueError("radix must be in [2, 36]")
    v = _require_literal(a, "to_base value (column inputs unsupported: "
                            "unbounded output dictionary)")
    digits = "0123456789abcdefghijklmnopqrstuvwxyz"
    neg, n = v < 0, abs(int(v))
    out = ""
    while True:
        out = digits[n % r] + out
        n //= r
        if n == 0:
            break
    s = ("-" if neg else "") + out
    return Val(
        jnp.zeros(a.data.shape, jnp.int32),
        a.valid,
        T.VARCHAR,
        intern_dictionary((s,)),
        literal=s,
    )


@register("from_base", _bigint_infer)
def _from_base(a: Val, radix: Val, out_type: T.Type) -> Val:
    r = int(_require_literal(radix, "from_base radix"))

    def f(s: str):
        try:
            return int(s, r), True
        except ValueError:
            return 0, False

    d = a.dictionary
    if d is None:
        raise TypeError("varchar value lost its dictionary")
    vals, oks = np.zeros(len(d), np.int64), np.empty(len(d), np.bool_)
    for i, s in enumerate(d):
        vals[i], oks[i] = f(s)
    vt, ot = jnp.asarray(vals), jnp.asarray(oks)
    return Val(vt[a.data], and_valid(a.valid, ot[a.data]), T.BIGINT)


@register("cosine_distance", _double_infer)
def _cosine_distance(a: Val, b: Val, out_type: T.Type) -> Val:
    """1 - cosine similarity of two numeric arrays (reference
    ArrayDistanceFunctions); element-wise over the trace-static width."""
    if a.lengths is None or b.lengths is None:
        raise TypeError("cosine_distance requires array values")
    x = a.data.astype(jnp.float64)
    y = b.data.astype(jnp.float64)
    w = min(x.shape[1], y.shape[1])
    x, y = x[:, :w], y[:, :w]
    inb = jnp.arange(w)[None, :] < jnp.minimum(a.lengths, b.lengths)[:, None]
    x = jnp.where(inb, x, 0.0)
    y = jnp.where(inb, y, 0.0)
    num = jnp.sum(x * y, axis=1)
    den = jnp.sqrt(jnp.sum(x * x, axis=1)) * jnp.sqrt(jnp.sum(y * y, axis=1))
    return Val(
        1.0 - num / jnp.where(den == 0, 1.0, den),
        and_valid(a.valid, b.valid, den != 0),
        T.DOUBLE,
    )


# ---------------------------------------------------------------------------
# bitwise tail (the main family is in functions.py)
# ---------------------------------------------------------------------------


@register("bitwise_logical_shift_right", _bigint_infer)
def _bitwise_logical_shift_right(a: Val, b: Val, out_type: T.Type) -> Val:
    x = a.data.astype(jnp.int64)
    s = b.data.astype(jnp.int64)
    out = (x.view(jnp.uint64) >> (s.view(jnp.uint64) & jnp.uint64(63))).view(
        jnp.int64
    )
    return Val(out, and_valid(a.valid, b.valid), T.BIGINT)


# ---------------------------------------------------------------------------
# string tail (reference StringFunctions.java)
# ---------------------------------------------------------------------------


_base_reverse = FUNCTIONS["reverse"].impl


@register("reverse", lambda ts: ts[0])
def _reverse(a: Val, out_type: T.Type) -> Val:
    """reverse(array) element reversal; varchar delegates to the existing
    dictionary implementation (reference has both overloads)."""
    if isinstance(a.type, T.ArrayType):
        w = a.data.shape[1]
        idx = a.lengths[:, None] - 1 - jnp.arange(w)[None, :]
        idx = jnp.clip(idx, 0, w - 1)
        data = jnp.take_along_axis(a.data, idx, axis=1)
        ev = a.elem_valid
        if ev is not None:
            ev = jnp.take_along_axis(ev, idx, axis=1)
        return Val(
            data, a.valid, a.type, a.dict_id, lengths=a.lengths,
            elem_valid=ev,
        )
    return _base_reverse(a, out_type=T.VARCHAR)


@register("translate", _varchar_infer)
def _translate(a: Val, frm: Val, to: Val, out_type: T.Type) -> Val:
    f = _require_literal(frm, "translate from")
    t = _require_literal(to, "translate to")
    table = {ord(c): (t[i] if i < len(t) else None) for i, c in enumerate(f)}
    return _dict_transform(a, lambda s: s.translate(table))


@register("strrpos", _bigint_infer)
def _strrpos(a: Val, sub: Val, out_type: T.Type) -> Val:
    needle = _require_literal(sub, "strrpos substring")
    d = a.dictionary
    if d is None:
        raise TypeError("varchar value lost its dictionary")
    table = jnp.asarray(
        np.array([s.rfind(needle) + 1 for s in d], np.int64)
    )
    return Val(table[a.data], a.valid, T.BIGINT)


@register("normalize", _varchar_infer)
def _normalize(a: Val, *rest, out_type: T.Type) -> Val:
    form = (
        _require_literal(rest[0], "normalize form") if rest else "NFC"
    ).upper()
    if form not in ("NFC", "NFD", "NFKC", "NFKD"):
        raise ValueError(f"invalid normalization form {form}")
    return _dict_transform(a, lambda s: unicodedata.normalize(form, s))


@register("concat_ws", _varchar_infer)
def _concat_ws(sep: Val, *vals: Val, out_type: T.Type) -> Val:
    s = _require_literal(sep, "concat_ws separator")
    cat = FUNCTIONS["concat"]
    out: Optional[Val] = None
    sep_val = Val(
        jnp.asarray(0, jnp.int32), None, T.VARCHAR,
        intern_dictionary((s,)), literal=s,
    )
    for v in vals:
        if out is None:
            out = v
        else:
            out = cat.impl(out, sep_val, out_type=T.VARCHAR)
            out = cat.impl(out, v, out_type=T.VARCHAR)
    return out if out is not None else sep_val


# ---------------------------------------------------------------------------
# digests / encodings over utf8(varchar) (reference VarbinaryFunctions.java,
# HmacFunctions.java — surfaced at the string layer, see module docstring)
# ---------------------------------------------------------------------------


def _digest(name: str, fn):
    @register(name, _varchar_infer)
    def _impl(a: Val, out_type: T.Type, _fn=fn) -> Val:
        return _dict_transform(a, lambda s: _fn(s.encode("utf-8")))

    return _impl


_digest("md5", lambda b: hashlib.md5(b).hexdigest())
_digest("sha1", lambda b: hashlib.sha1(b).hexdigest())
_digest("sha256", lambda b: hashlib.sha256(b).hexdigest())
_digest("sha512", lambda b: hashlib.sha512(b).hexdigest())
_digest("crc32", lambda b: format(zlib.crc32(b) & 0xFFFFFFFF, "x"))
_digest(
    "xxhash64",
    lambda b: format(
        int.from_bytes(
            hashlib.blake2b(b, digest_size=8).digest(), "big"
        ),
        "016x",
    ),
)
_digest("to_base64", lambda b: base64.b64encode(b).decode("ascii"))
_digest("to_base64url", lambda b: base64.urlsafe_b64encode(b).decode("ascii"))
_digest("to_hex", lambda b: b.hex().upper())


def _decode(name: str, fn):
    @register(name, _varchar_infer)
    def _impl(a: Val, out_type: T.Type, _fn=fn) -> Val:
        def g(s: str):
            try:
                return _fn(s), True
            except Exception:  # noqa: BLE001 - malformed input -> NULL
                return "", False

        return _dict_transform_nullable(a, g)

    return _impl


_decode("from_base64", lambda s: base64.b64decode(s).decode("utf-8"))
_decode(
    "from_base64url", lambda s: base64.urlsafe_b64decode(s).decode("utf-8")
)
_decode("from_hex", lambda s: bytes.fromhex(s).decode("utf-8"))


def _hmac_register(name: str, algo):
    @register(name, _varchar_infer)
    def _impl(a: Val, key: Val, out_type: T.Type, _algo=algo) -> Val:
        k = _require_literal(key, f"{name} key").encode("utf-8")
        return _dict_transform(
            a,
            lambda s: _hmac.new(k, s.encode("utf-8"), _algo).hexdigest(),
        )

    return _impl


_hmac_register("hmac_md5", hashlib.md5)
_hmac_register("hmac_sha1", hashlib.sha1)
_hmac_register("hmac_sha256", hashlib.sha256)
_hmac_register("hmac_sha512", hashlib.sha512)


@register("typeof", _varchar_infer)
def _typeof(a: Val, out_type: T.Type) -> Val:
    name = str(a.type)
    return Val(
        jnp.zeros(a.data.shape[:1] or (), jnp.int32),
        None,
        T.VARCHAR,
        intern_dictionary((name,)),
        literal=name,
    )


# ---------------------------------------------------------------------------
# array tail (reference array/*.java)
# ---------------------------------------------------------------------------


def _in_bounds(a: Val) -> jnp.ndarray:
    w = a.data.shape[1]
    return jnp.arange(w)[None, :] < a.lengths[:, None]


def _elem_live(a: Val) -> jnp.ndarray:
    live = _in_bounds(a)
    if a.elem_valid is not None:
        live = live & a.elem_valid
    return live


def _array_sort_key(a: Val):
    """Key arrays sort/dedup by: the element data (dictionary codes order
    varchar correctly — dictionaries are sorted)."""
    return a.data


@register("array_max", lambda ts: ts[0].element)
def _array_max(a: Val, out_type: T.Type) -> Val:
    live = _elem_live(a)
    has = jnp.any(live, axis=1)
    lo = jnp.iinfo(jnp.int32).min if a.data.dtype == jnp.int32 else -(2**62)
    x = jnp.where(live, a.data, lo)
    out = jnp.max(x, axis=1).astype(a.data.dtype)
    return Val(out, and_valid(a.valid, has), out_type, a.dict_id)


@register("array_min", lambda ts: ts[0].element)
def _array_min(a: Val, out_type: T.Type) -> Val:
    live = _elem_live(a)
    has = jnp.any(live, axis=1)
    hi = jnp.iinfo(jnp.int32).max if a.data.dtype == jnp.int32 else 2**62
    x = jnp.where(live, a.data, hi)
    out = jnp.min(x, axis=1).astype(a.data.dtype)
    return Val(out, and_valid(a.valid, has), out_type, a.dict_id)


def _sort_key(data: jnp.ndarray, live: jnp.ndarray) -> jnp.ndarray:
    """Order-preserving int64 sort key; dead/NULL elements sort last.

    Floats are bitcast to int64 with the negative range bit-reversed (the
    IEEE754 total-order trick), so the key orders AND equality-compares
    exactly like the original values — the element data itself is never
    cast (round-4 advisor: the old int64 cast corrupted ARRAY(DOUBLE))."""
    if jnp.issubdtype(data.dtype, jnp.floating):
        # canonicalize -0.0 to +0.0 so signed zeros compare equal (an
        # explicit where: XLA folds the usual `x + 0.0` idiom away)
        f = data.astype(jnp.float64)
        f = jnp.where(f == 0.0, 0.0, f)
        b = jax.lax.bitcast_convert_type(f, jnp.int64)
        sign = jnp.int64(-(2**63))
        k = jnp.where(b < 0, (~b) ^ sign, b)
    else:
        k = data.astype(jnp.int64)
    return jnp.where(live, k, jnp.iinfo(jnp.int64).max)


def _dedup_sorted(a: Val):
    """Sort elements per row (NULL/absent last), mark first occurrences.

    Returns (sorted original data, sorted live mask, first-occurrence mask)."""
    live = _elem_live(a)
    key = _sort_key(a.data, live)
    order = jnp.argsort(key, axis=1)
    sdata = jnp.take_along_axis(a.data, order, axis=1)
    slive = jnp.take_along_axis(live, order, axis=1)
    skey = jnp.take_along_axis(key, order, axis=1)
    first = jnp.concatenate(
        [
            jnp.ones((key.shape[0], 1), bool),
            skey[:, 1:] != skey[:, :-1],
        ],
        axis=1,
    ) & slive
    return sdata, slive, first


@register("array_distinct", lambda ts: ts[0])
def _array_distinct(a: Val, out_type: T.Type) -> Val:
    sdata, slive, first = _dedup_sorted(a)
    w = a.data.shape[1]
    # compact the kept elements to the front, preserving sorted order
    pos = jnp.cumsum(first.astype(jnp.int32), axis=1) - 1
    rows = jnp.arange(sdata.shape[0])[:, None]
    # rejects write 0 to slot w-1; safe because a row with any reject keeps
    # at most w-1 elements, so no kept element ever lands in slot w-1
    scatter_pos = jnp.where(first, pos, w - 1)
    out = jnp.zeros_like(sdata)
    out = out.at[rows, scatter_pos].set(jnp.where(first, sdata, 0))
    lens = jnp.sum(first, axis=1).astype(jnp.int32)
    return Val(
        out, a.valid, a.type, a.dict_id, lengths=lens
    )


@register("array_sort", lambda ts: ts[0])
def _array_sort(a: Val, out_type: T.Type) -> Val:
    live = _elem_live(a)
    order = jnp.argsort(_sort_key(a.data, live), axis=1)
    sdata = jnp.take_along_axis(a.data, order, axis=1)
    slive = jnp.take_along_axis(live, order, axis=1)
    lens = jnp.sum(live, axis=1).astype(jnp.int32)
    return Val(
        jnp.where(slive, sdata, 0),
        a.valid,
        a.type,
        a.dict_id,
        lengths=lens,
    )


@register("array_remove", lambda ts: ts[0])
def _array_remove(a: Val, needle: Val, out_type: T.Type) -> Val:
    live = _elem_live(a)
    n = needle.data
    if n.ndim == 0:
        n = n[None]
    keep = live & (a.data != n[:, None] if n.shape[0] == a.data.shape[0] else a.data != n[0])
    big = 2**62
    key = jnp.where(keep, a.data.astype(jnp.int64), big)
    order = jnp.argsort(jnp.where(keep, 0, 1), axis=1, stable=True)
    data = jnp.take_along_axis(a.data, order, axis=1)
    lens = jnp.sum(keep, axis=1).astype(jnp.int32)
    del key
    return Val(data, a.valid, a.type, a.dict_id, lengths=lens)


@register("arrays_overlap", _bool_infer)
def _arrays_overlap(a: Val, b: Val, out_type: T.Type) -> Val:
    la, lb = _elem_live(a), _elem_live(b)
    eq = a.data[:, :, None] == b.data[:, None, :]
    hit = jnp.any(eq & la[:, :, None] & lb[:, None, :], axis=(1, 2))
    return Val(hit, and_valid(a.valid, b.valid), T.BOOLEAN)


@register("array_intersect", lambda ts: ts[0])
def _array_intersect(a: Val, b: Val, out_type: T.Type) -> Val:
    la, lb = _elem_live(a), _elem_live(b)
    in_b = jnp.any(
        (a.data[:, :, None] == b.data[:, None, :]) & lb[:, None, :], axis=2
    )
    masked = Val(
        a.data, a.valid, a.type, a.dict_id,
        lengths=a.lengths,
        elem_valid=(la & in_b),
    )
    return _array_distinct(masked, out_type=out_type)


@register("array_except", lambda ts: ts[0])
def _array_except(a: Val, b: Val, out_type: T.Type) -> Val:
    la, lb = _elem_live(a), _elem_live(b)
    in_b = jnp.any(
        (a.data[:, :, None] == b.data[:, None, :]) & lb[:, None, :], axis=2
    )
    masked = Val(
        a.data, a.valid, a.type, a.dict_id,
        lengths=a.lengths,
        elem_valid=(la & ~in_b),
    )
    return _array_distinct(masked, out_type=out_type)


@register("array_union", lambda ts: ts[0])
def _array_union(a: Val, b: Val, out_type: T.Type) -> Val:
    la, lb = _elem_live(a), _elem_live(b)
    data = jnp.concatenate([a.data, b.data], axis=1)
    ev = jnp.concatenate([la, lb], axis=1)
    lens = (a.lengths + b.lengths).astype(jnp.int32)
    merged = Val(
        data, and_valid(a.valid, b.valid), a.type, a.dict_id,
        lengths=jnp.full_like(lens, data.shape[1]),
        elem_valid=ev,
    )
    return _array_distinct(merged, out_type=out_type)


@register("slice", lambda ts: ts[0])
def _slice(a: Val, start: Val, length: Val, out_type: T.Type) -> Val:
    s0 = int(_require_literal(start, "slice start"))
    ln = int(_require_literal(length, "slice length"))
    w = a.data.shape[1]
    base = jnp.where(
        jnp.asarray(s0 > 0), s0 - 1, a.lengths + s0
    )
    idx = base[:, None] + jnp.arange(w)[None, :]
    take = jnp.arange(w)[None, :] < ln
    inb = (idx >= 0) & (idx < a.lengths[:, None]) & take
    idxc = jnp.clip(idx, 0, w - 1)
    data = jnp.take_along_axis(a.data, idxc, axis=1)
    ev = inb
    if a.elem_valid is not None:
        ev = ev & jnp.take_along_axis(a.elem_valid, idxc, axis=1)
    lens = jnp.sum(inb, axis=1).astype(jnp.int32)
    return Val(
        data, a.valid, a.type, a.dict_id, lengths=lens, elem_valid=ev
    )


@register("repeat", lambda ts: T.ArrayType(ts[0]))
def _repeat(elem: Val, count: Val, out_type: T.Type) -> Val:
    n = int(_require_literal(count, "repeat count"))
    e = elem.data
    if e.ndim == 0:
        e = e[None]
    data = jnp.broadcast_to(e[:, None], (e.shape[0], max(n, 1)))
    lens = jnp.full((e.shape[0],), n, jnp.int32)
    ev = None
    if elem.valid is not None:
        ev = jnp.broadcast_to(
            elem.valid[:, None], (e.shape[0], max(n, 1))
        )
    return Val(
        data,
        None,
        T.ArrayType(elem.type),
        elem.dict_id,
        lengths=lens,
        elem_valid=ev,
    )


# ---------------------------------------------------------------------------
# regex + json tail
# ---------------------------------------------------------------------------


@register("regexp_split", lambda ts: T.ArrayType(T.VARCHAR))
def _regexp_split(a: Val, patv: Val, out_type: T.Type) -> Val:
    import re as _re

    pat = _re.compile(_require_literal(patv, "regexp pattern"))
    d = a.dictionary
    if d is None:
        raise TypeError("varchar value lost its dictionary")
    parts_per = [pat.split(s) for s in d]
    width = max((len(p) for p in parts_per), default=1) or 1
    out_dict = tuple(sorted({p for parts in parts_per for p in parts}))
    index = {s: i for i, s in enumerate(out_dict)}
    codes = np.zeros((len(d), width), np.int32)
    lens = np.zeros(len(d), np.int32)
    for i, parts in enumerate(parts_per):
        lens[i] = len(parts)
        for j, p in enumerate(parts):
            codes[i, j] = index[p]
    return Val(
        jnp.asarray(codes)[a.data],
        a.valid,
        T.ArrayType(T.VARCHAR),
        intern_dictionary(out_dict),
        lengths=jnp.asarray(lens)[a.data],
    )


@register("regexp_extract_all", lambda ts: T.ArrayType(T.VARCHAR))
def _regexp_extract_all(a: Val, patv: Val, *rest, out_type: T.Type) -> Val:
    import re as _re

    pat = _re.compile(_require_literal(patv, "regexp pattern"))
    group = int(_require_literal(rest[0], "group")) if rest else 0
    d = a.dictionary
    if d is None:
        raise TypeError("varchar value lost its dictionary")
    parts_per = []
    for s in d:
        hits = []
        for m in pat.finditer(s):
            hits.append(m.group(group) or "")
        parts_per.append(hits)
    width = max((len(p) for p in parts_per), default=1) or 1
    out_dict = tuple(sorted({p for parts in parts_per for p in parts}))
    index = {s: i for i, s in enumerate(out_dict)}
    codes = np.zeros((len(d), width), np.int32)
    lens = np.zeros(len(d), np.int32)
    for i, parts in enumerate(parts_per):
        lens[i] = len(parts)
        for j, p in enumerate(parts):
            codes[i, j] = index[p]
    return Val(
        jnp.asarray(codes)[a.data],
        a.valid,
        T.ArrayType(T.VARCHAR),
        intern_dictionary(out_dict),
        lengths=jnp.asarray(lens)[a.data],
    )


@register("json_size", _bigint_infer)
def _json_size(a: Val, path: Val, out_type: T.Type) -> Val:
    from .functions import _json_get, _json_path_steps

    steps = _json_path_steps(_require_literal(path, "JSON path"))
    d = a.dictionary
    if d is None:
        raise TypeError("varchar value lost its dictionary")
    sizes, oks = np.zeros(len(d), np.int64), np.empty(len(d), np.bool_)
    for i, s in enumerate(d):
        v, ok = _json_get(s, steps)
        if ok and isinstance(v, (dict, list)):
            sizes[i] = len(v)
        elif ok:
            sizes[i] = 0
        oks[i] = ok
    st, ot = jnp.asarray(sizes), jnp.asarray(oks)
    return Val(st[a.data], and_valid(a.valid, ot[a.data]), T.BIGINT)


@register("is_json_scalar", _bool_infer)
def _is_json_scalar(a: Val, out_type: T.Type) -> Val:
    def p(s: str) -> bool:
        try:
            v = json.loads(s)
        except ValueError:
            return False
        return not isinstance(v, (dict, list))

    return _dict_predicate(a, p)


@register("json_array_get", _varchar_infer)
def _json_array_get(a: Val, idx: Val, out_type: T.Type) -> Val:
    i0 = int(_require_literal(idx, "json_array_get index"))

    def f(s: str):
        try:
            v = json.loads(s)
        except ValueError:
            return "", False
        if not isinstance(v, list):
            return "", False
        i = i0 if i0 >= 0 else len(v) + i0
        if not (0 <= i < len(v)):
            return "", False
        e = v[i]
        return (
            e if isinstance(e, str) else json.dumps(e, separators=(",", ":"))
        ), True

    return _dict_transform_nullable(a, f)


# ---------------------------------------------------------------------------
# aliases rounding out the reference surface
# ---------------------------------------------------------------------------

_alias("ceiling", "ceil")
_alias("pow", "power")
_alias("char_length", "length")
_alias("character_length", "length")
_alias("lcase", "lower")
_alias("ucase", "upper")
_alias("position", "strpos")


# ---------------------------------------------------------------------------
# statistical distribution functions (reference MathFunctions.java's
# normal_cdf/beta_cdf/... family) — jax.scipy kernels, fuse on device
# ---------------------------------------------------------------------------


def _cdf3(name: str, fn):
    """cdf(param1, param2, value) family."""

    @register(name, _double_infer)
    def _impl(p1: Val, p2: Val, v: Val, out_type: T.Type, _fn=fn) -> Val:
        x1, x2, xv = _as_float(p1), _as_float(p2), _as_float(v)
        return Val(
            _fn(x1, x2, xv), and_valid(p1.valid, p2.valid, v.valid), T.DOUBLE
        )

    return _impl


def _cdf2(name: str, fn):
    @register(name, _double_infer)
    def _impl(p1: Val, v: Val, out_type: T.Type, _fn=fn) -> Val:
        return Val(
            _fn(_as_float(p1), _as_float(v)),
            and_valid(p1.valid, v.valid),
            T.DOUBLE,
        )

    return _impl


def _stats():
    import jax.scipy.stats as st
    from jax.scipy import special

    _cdf3("normal_cdf", lambda m, sd, x: st.norm.cdf(x, loc=m, scale=sd))
    _cdf3(
        "inverse_normal_cdf",
        lambda m, sd, p: m + sd * special.ndtri(p),
    )
    _cdf3("beta_cdf", lambda a, b, x: special.betainc(a, b, x))
    _cdf3("cauchy_cdf", lambda m, g, x: st.cauchy.cdf(x, loc=m, scale=g))
    _cdf3("gamma_cdf", lambda sh, sc, x: special.gammainc(sh, x / sc))
    _cdf3("laplace_cdf", lambda m, b, x: st.laplace.cdf(x, loc=m, scale=b))
    _cdf3(
        "weibull_cdf",
        lambda a, b, x: 1.0 - jnp.exp(-jnp.power(jnp.maximum(x, 0.0) / b, a)),
    )
    _cdf2("chi_squared_cdf", lambda df, x: st.chi2.cdf(x, df))
    _cdf2("poisson_cdf", lambda lam, k: st.poisson.cdf(jnp.floor(k), lam))
    _cdf3(
        "binomial_cdf",
        lambda n, p, k: special.betainc(
            jnp.maximum(n - jnp.floor(k), 1e-12),
            jnp.floor(k) + 1.0,
            1.0 - p,
        ),
    )

    @register("wilson_interval_lower", _double_infer)
    def _wil(succ: Val, trials: Val, z: Val, out_type: T.Type) -> Val:
        s, n, zz = _as_float(succ), _as_float(trials), _as_float(z)
        p = s / n
        denom = 1.0 + zz * zz / n
        center = p + zz * zz / (2 * n)
        spread = zz * jnp.sqrt(p * (1 - p) / n + zz * zz / (4 * n * n))
        return Val(
            (center - spread) / denom,
            and_valid(succ.valid, trials.valid, z.valid),
            T.DOUBLE,
        )

    @register("wilson_interval_upper", _double_infer)
    def _wiu(succ: Val, trials: Val, z: Val, out_type: T.Type) -> Val:
        s, n, zz = _as_float(succ), _as_float(trials), _as_float(z)
        p = s / n
        denom = 1.0 + zz * zz / n
        center = p + zz * zz / (2 * n)
        spread = zz * jnp.sqrt(p * (1 - p) / n + zz * zz / (4 * n * n))
        return Val(
            (center + spread) / denom,
            and_valid(succ.valid, trials.valid, z.valid),
            T.DOUBLE,
        )


_stats()


# ---------------------------------------------------------------------------
# URL extraction tail (reference UrlFunctions.java; the url_extract_*
# part family + url_decode/encode live in functions.py — only the
# parameter lookup is new here)
# ---------------------------------------------------------------------------


@register("url_extract_parameter", _varchar_infer)
def _url_extract_parameter(a: Val, namev: Val, out_type: T.Type) -> Val:
    from urllib.parse import parse_qs, urlparse

    pname = _require_literal(namev, "url parameter name")

    def f(s: str):
        try:
            q = parse_qs(urlparse(s).query, keep_blank_values=True)
        except Exception:  # noqa: BLE001 — unparseable URL -> SQL NULL
            return "", False
        vals = q.get(pname)
        return (vals[0], True) if vals else ("", False)

    return _dict_transform_nullable(a, f)


# ---------------------------------------------------------------------------
# datetime tail + teradata compatibility (reference DateTimeFunctions.java,
# presto-teradata-functions)
# ---------------------------------------------------------------------------


@register("to_iso8601", _varchar_infer)
def _to_iso8601(a: Val, out_type: T.Type) -> Val:
    """DATE -> 'YYYY-MM-DD'. Dates are device int32 day numbers; the
    output dictionary is built from the value RANGE observed at trace
    time is impossible under jit, so format through the date-table the
    datetime kernels already maintain."""
    from . import datetime_kernels as dt

    if not isinstance(a.type, T.DateType):
        raise NotImplementedError("to_iso8601 supports DATE values")
    y = dt.extract_year(a.data)
    m = dt.extract_month(a.data)
    d = dt.extract_day(a.data)
    # build dictionary of all dates in the representable window is huge;
    # instead emit the canonical digits via a fixed char dictionary is
    # not expressible — format on host over the set of distinct epoch
    # days is also trace-hostile. The pragmatic contract: delegate to
    # date_format, which already solves this.
    fmt = Val(
        jnp.asarray(0, jnp.int32),
        None,
        T.VARCHAR,
        intern_dictionary(("%Y-%m-%d",)),
        literal="%Y-%m-%d",
    )
    return FUNCTIONS["date_format"].impl(a, fmt, out_type=T.VARCHAR)


_alias("index", "strpos")  # teradata-functions: index(string, substring)


@register("char2hexint", _varchar_infer)
def _char2hexint(a: Val, out_type: T.Type) -> Val:
    """Teradata compat: hex of the UTF-16BE code units."""
    return _dict_transform(
        a,
        lambda s: "".join(
            format(u, "04X")
            for u in __import__("struct").unpack(
                f">{len(s.encode('utf-16-be')) // 2}H",
                s.encode("utf-16-be"),
            )
        ),
    )


@register("to_milliseconds", _bigint_infer)
def _to_milliseconds(a: Val, out_type: T.Type) -> Val:
    """INTERVAL DAY TO SECOND (stored as day count here) -> ms."""
    return Val(
        a.data.astype(jnp.int64) * 86_400_000, a.valid, T.BIGINT
    )


@register("parse_duration", _double_infer)
def _parse_duration(a: Val, out_type: T.Type) -> Val:
    """'3.5m'-style duration strings -> seconds (double)."""
    import re as _re

    units = {
        "ns": 1e-9, "us": 1e-6, "ms": 1e-3, "s": 1.0,
        "m": 60.0, "h": 3600.0, "d": 86400.0,
    }
    pat = _re.compile(r"^\s*([0-9]*\.?[0-9]+)\s*([a-z]+)\s*$")

    def f(s: str):
        m = pat.match(s)
        if not m or m.group(2) not in units:
            return 0.0, False
        return float(m.group(1)) * units[m.group(2)], True

    from .functions import _dict_table_nullable

    return _dict_table_nullable(a, f, np.float64, T.DOUBLE)


@register("human_readable_seconds", _varchar_infer)
def _human_readable_seconds(a: Val, out_type: T.Type) -> Val:
    """Seconds (bigint literal-ish column) -> '2 days, 3 hours ...'.
    Unbounded output dictionary for arbitrary columns, so literal-only
    (the common usage in reports)."""
    v = _require_literal(a, "human_readable_seconds value "
                            "(column inputs unsupported)")
    secs = int(v)
    parts = []
    for unit, span in (
        ("week", 604800), ("day", 86400), ("hour", 3600),
        ("minute", 60), ("second", 1),
    ):
        q, secs = divmod(secs, span)
        if q:
            parts.append(f"{q} {unit}" + ("s" if q != 1 else ""))
    s = ", ".join(parts) if parts else "0 seconds"
    return Val(
        jnp.zeros(a.data.shape, jnp.int32),
        a.valid,
        T.VARCHAR,
        intern_dictionary((s,)),
        literal=s,
    )


# ---------------------------------------------------------------------------
# color / bar rendering (reference ColorFunctions.java) — literal-oriented
# report helpers
# ---------------------------------------------------------------------------


_ANSI = {
    "black": 0, "red": 1, "green": 2, "yellow": 3, "blue": 4,
    "magenta": 5, "cyan": 6, "white": 7,
}


@register("color", _bigint_infer)
def _color(a: Val, out_type: T.Type) -> Val:
    """Color name/'#rgb' -> packed color code (bigint here; the reference
    uses a COLOR type)."""
    def f(s: str):
        if s.startswith("#") and len(s) == 4:
            return (
                int(s[1], 16) * 256 + int(s[2], 16) * 16 + int(s[3], 16),
                True,
            )
        c = _ANSI.get(s.lower())
        return (c, True) if c is not None else (0, False)

    from .functions import _dict_table_nullable

    return _dict_table_nullable(a, f, np.int64, T.BIGINT)


@register("rgb", _bigint_infer)
def _rgb(r: Val, g: Val, b: Val, out_type: T.Type) -> Val:
    x = (
        jnp.clip(r.data.astype(jnp.int64), 0, 255) * 65536
        + jnp.clip(g.data.astype(jnp.int64), 0, 255) * 256
        + jnp.clip(b.data.astype(jnp.int64), 0, 255)
    )
    return Val(x, and_valid(r.valid, g.valid, b.valid), T.BIGINT)


@register("bar", _varchar_infer)
def _bar(x: Val, width: Val, out_type: T.Type) -> Val:
    """Fraction -> unicode bar of literal width (reference bar(double,
    bigint)). Literal fraction only (unbounded output dictionary for
    columns — the usual usage renders a computed literal)."""
    frac = float(_require_literal(x, "bar fraction (column inputs "
                                     "unsupported)"))
    w = int(_require_literal(width, "bar width"))
    n = max(0, min(w, int(round(frac * w))))
    s = "█" * n + " " * (w - n)
    return Val(
        jnp.zeros(x.data.shape, jnp.int32),
        x.valid,
        T.VARCHAR,
        intern_dictionary((s,)),
        literal=s,
    )


# ---------------------------------------------------------------------------
# utf8 / session time tail
# ---------------------------------------------------------------------------


@register("to_utf8", _varchar_infer)
def _to_utf8(a: Val, out_type: T.Type) -> Val:
    """VARCHAR -> VARBINARY(utf8). This engine surfaces binary as the utf8
    string itself (module docstring), so this is the identity projection."""
    return Val(a.data, a.valid, T.VARCHAR, a.dict_id)


@register("from_utf8", _varchar_infer)
def _from_utf8(a: Val, out_type: T.Type) -> Val:
    return Val(a.data, a.valid, T.VARCHAR, a.dict_id)


def _session_day() -> int:
    """Query-submission date (reference: session start time). Expression
    trace time IS query planning time here."""
    import datetime as _dt

    return (_dt.date.today() - _dt.date(1970, 1, 1)).days


@register("current_date", lambda ts: T.DATE)
def _current_date(out_type: T.Type) -> Val:
    d = _session_day()
    return Val(jnp.asarray(d, jnp.int32), None, T.DATE, literal=d)


@register("now", lambda ts: T.TIMESTAMP)
def _now(out_type: T.Type) -> Val:
    import time as _time

    ms = int(_time.time() * 1000)
    return Val(jnp.asarray(ms, jnp.int64), None, T.TIMESTAMP, literal=ms)


_alias("current_timestamp", "now")
_alias("localtimestamp", "now")


@register("word_stem", _varchar_infer)
def _word_stem(a: Val, out_type: T.Type) -> Val:
    """English suffix stripping (Porter step-1-style; the reference wraps
    a Snowball stemmer — this covers the regular inflections)."""

    def stem(w: str) -> str:
        s = w.lower()
        if len(s) > 4:
            if s.endswith("sses"):
                return s[:-2]
            if s.endswith("ies"):
                return s[:-2]
            if s.endswith("ss"):
                return s
            if s.endswith("s") and not s.endswith("us"):
                return s[:-1]
            if s.endswith("ing") and len(s) > 5:
                return s[:-3]
            if s.endswith("ed") and len(s) > 4:
                return s[:-2]
        return s

    return _dict_transform(a, stem)


# ---------------------------------------------------------------------------
# geospatial toolkit (reference presto-geospatial / GeoFunctions.java +
# presto-geospatial-toolkit) — POINT-only subset, TPU-first: a point is an
# expression-layer ARRAY(DOUBLE) [x, y] (the same lanes-representation the
# engine uses for arrays), so every function below is a fused jnp kernel.
# Polygon/linestring machinery (Esri geometry, R-tree spatial joins) is out
# of scope without the Esri library.
# ---------------------------------------------------------------------------


@register("st_point", lambda ts: T.ArrayType(T.DOUBLE))
def _st_point(x: Val, y: Val, out_type: T.Type) -> Val:
    xd = _as_float(x)
    yd = _as_float(y)
    if xd.ndim == 0:
        xd = xd[None]
    if yd.ndim == 0:
        yd = yd[None]
    n = max(xd.shape[0], yd.shape[0])
    xd = jnp.broadcast_to(xd, (n,))
    yd = jnp.broadcast_to(yd, (n,))
    data = jnp.stack([xd, yd], axis=1)
    return Val(
        data,
        and_valid(x.valid, y.valid),
        T.ArrayType(T.DOUBLE),
        lengths=jnp.full((n,), 2, jnp.int32),
    )


def _point_xy(p: Val, what: str):
    if p.lengths is None or p.data.shape[1] < 2:
        raise TypeError(f"{what} requires a POINT (st_point) value")
    return p.data[:, 0], p.data[:, 1]


@register("st_x", _double_infer)
def _st_x(p: Val, out_type: T.Type) -> Val:
    x, _ = _point_xy(p, "st_x")
    return Val(x, p.valid, T.DOUBLE)


@register("st_y", _double_infer)
def _st_y(p: Val, out_type: T.Type) -> Val:
    _, y = _point_xy(p, "st_y")
    return Val(y, p.valid, T.DOUBLE)


@register("st_distance", _double_infer)
def _st_distance(a: Val, b: Val, out_type: T.Type) -> Val:
    ax, ay = _point_xy(a, "st_distance")
    bx, by = _point_xy(b, "st_distance")
    d = jnp.sqrt((ax - bx) ** 2 + (ay - by) ** 2)
    return Val(d, and_valid(a.valid, b.valid), T.DOUBLE)


@register("great_circle_distance", _double_infer)
def _great_circle_distance(
    lat1: Val, lon1: Val, lat2: Val, lon2: Val, out_type: T.Type
) -> Val:
    """Haversine distance in KILOMETERS (reference GeoFunctions.
    greatCircleDistance — same Earth radius constant)."""
    r = 6371.01
    p1, l1 = jnp.radians(_as_float(lat1)), jnp.radians(_as_float(lon1))
    p2, l2 = jnp.radians(_as_float(lat2)), jnp.radians(_as_float(lon2))
    h = (
        jnp.sin((p2 - p1) / 2) ** 2
        + jnp.cos(p1) * jnp.cos(p2) * jnp.sin((l2 - l1) / 2) ** 2
    )
    d = 2 * r * jnp.arcsin(jnp.sqrt(jnp.clip(h, 0.0, 1.0)))
    return Val(
        d,
        and_valid(lat1.valid, lon1.valid, lat2.valid, lon2.valid),
        T.DOUBLE,
    )


# ---------------------------------------------------------------------------
# map tail + binary/json/date leftovers (reference MapConcatFunction,
# JsonFunctions.jsonParse, VarbinaryFunctions to/from_big_endian_64,
# ColorFunctions.render, DateTimeFunctions timezone accessors)
# ---------------------------------------------------------------------------


@register("map_concat", lambda ts: ts[0])
def _map_concat(a: Val, *rest: Val, out_type: T.Type) -> Val:
    """Union of maps; on duplicate keys the LAST map wins (reference
    MapConcatFunction, variadic). Static-width: concatenate lanes, unify
    varchar dictionaries, then mask earlier occurrences of later keys."""
    out = a
    for b in rest:
        out = _map_concat2(out, b)
    return out


def _map_concat2(a: Val, b: Val) -> Val:
    from .functions import unify_dictionaries

    if a.keys is None or b.keys is None:
        raise TypeError("map_concat requires map values")
    ka, kb = a.keys, b.keys
    k_did = ka.dict_id
    kda, kdb = ka.data, kb.data
    if ka.dict_id != kb.dict_id and (
        ka.dict_id is not None or kb.dict_id is not None
    ):
        kda, kdb, k_did = unify_dictionaries(ka, kb)
    v_did = a.dict_id
    vda, vdb = a.data, b.data
    if a.dict_id != b.dict_id and (
        a.dict_id is not None or b.dict_id is not None
    ):
        vda, vdb, v_did = unify_dictionaries(a, b)
    wa, wb = kda.shape[1], kdb.shape[1]
    kdata = jnp.concatenate([kda, kdb], axis=1)
    vdata = jnp.concatenate(
        [vda, vdb.astype(vda.dtype) if vda.dtype != vdb.dtype else vdb],
        axis=1,
    )
    in_a = jnp.arange(wa)[None, :] < a.lengths[:, None]
    in_b = jnp.arange(wb)[None, :] < b.lengths[:, None]
    live = jnp.concatenate([in_a, in_b], axis=1)
    ev_a = (
        a.elem_valid
        if a.elem_valid is not None
        else jnp.ones(vda.shape[:2], bool)
    )
    ev_b = (
        b.elem_valid
        if b.elem_valid is not None
        else jnp.ones(vdb.shape[:2], bool)
    )
    ev = jnp.concatenate([ev_a, ev_b], axis=1)
    # kill an entry when any LATER live entry has the same key
    eq = kdata[:, :, None] == kdata[:, None, :]
    later = jnp.arange(wa + wb)[None, :] > jnp.arange(wa + wb)[:, None]
    dup = jnp.any(eq & later[None] & live[:, None, :], axis=2)
    keep = live & ~dup
    # compact kept entries to the front
    order = jnp.argsort(~keep, axis=1, stable=True)
    kdata = jnp.take_along_axis(kdata, order, axis=1)
    vdata = jnp.take_along_axis(vdata, order, axis=1)
    ev = jnp.take_along_axis(ev, order, axis=1)
    lens = jnp.sum(keep, axis=1).astype(jnp.int32)
    keys = Val(kdata, None, ka.type, k_did, lengths=lens)
    return Val(
        vdata,
        and_valid(a.valid, b.valid),
        a.type,
        v_did,
        lengths=lens,
        elem_valid=ev,
        keys=keys,
    )


@register("json_parse", _varchar_infer)
def _json_parse(a: Val, out_type: T.Type) -> Val:
    """Validate + canonicalize JSON text (reference jsonParse returning
    the JSON type; this engine's JSON values are canonical strings)."""

    def f(s: str):
        try:
            return (
                json.dumps(json.loads(s), separators=(",", ":")),
                True,
            )
        except ValueError:
            return "", False

    return _dict_transform_nullable(a, f)


@register("to_big_endian_64", _varchar_infer)
def _to_big_endian_64(a: Val, out_type: T.Type) -> Val:
    """bigint -> 8-byte big-endian, surfaced as 16 hex chars (binary
    rides the string layer here, see module docstring)."""
    v = _require_literal(
        a, "to_big_endian_64 value (column inputs unsupported: unbounded "
           "output dictionary)"
    )
    s = int(v).to_bytes(8, "big", signed=True).hex().upper()
    return Val(
        jnp.zeros(a.data.shape, jnp.int32),
        a.valid,
        T.VARCHAR,
        intern_dictionary((s,)),
        literal=s,
    )


@register("from_big_endian_64", _bigint_infer)
def _from_big_endian_64(a: Val, out_type: T.Type) -> Val:
    def f(s: str):
        if len(s) != 16:  # exactly 8 bytes (reference raises on != 8)
            return 0, False
        try:
            return int.from_bytes(bytes.fromhex(s), "big", signed=True), True
        except ValueError:
            return 0, False

    from .functions import _dict_table_nullable

    return _dict_table_nullable(a, f, np.int64, T.BIGINT)


@register("render", _varchar_infer)
def _render(b: Val, *rest, out_type: T.Type) -> Val:
    """render(boolean) -> ✓ / ✗ (reference ColorFunctions.render)."""
    d = ("✓", "✗")  # already sorted (U+2713 < U+2717)
    codes = jnp.where(b.data.astype(bool), jnp.int32(0), jnp.int32(1))
    return Val(codes, b.valid, T.VARCHAR, intern_dictionary(d))


@register("timezone_hour", _bigint_infer)
def _timezone_hour(a: Val, out_type: T.Type) -> Val:
    """This engine's temporal values are UTC (no session zones): 0."""
    return Val(jnp.zeros(a.data.shape[:1], jnp.int64), a.valid, T.BIGINT)


@register("timezone_minute", _bigint_infer)
def _timezone_minute(a: Val, out_type: T.Type) -> Val:
    return Val(jnp.zeros(a.data.shape[:1], jnp.int64), a.valid, T.BIGINT)


# ---------------------------------------------------------------------------
# ML scalars (reference presto-ml regress/classify over learned models)
# ---------------------------------------------------------------------------


@register("regress", _double_infer)
def _regress(features: Val, model: Val, out_type: T.Type) -> Val:
    """regress(features, model): dot(features, weights) + intercept —
    model is the ARRAY(DOUBLE) produced by learn_linear_regression."""
    from ..ops import mlreg

    if not isinstance(features.type, T.ArrayType) or not isinstance(
        model.type, T.ArrayType
    ):
        # an ndim check alone would silently accept long-decimal columns
        # (two storage lanes) and fit garbage
        raise TypeError("regress takes (features array, model array)")

    def _lens(v):
        # a fixed-width array rebuilt from a join/exchange may carry no
        # per-row lengths: every lane is live (same contract as
        # element_at)
        if v.lengths is not None:
            return v.lengths
        return jnp.full(v.data.shape[0], v.data.shape[1], jnp.int32)

    fdata = mlreg.logical_values(features.data, features.type)
    mdata = mlreg.logical_values(model.data, model.type)
    flens = _lens(features)
    mlens = _lens(model)
    if mdata.shape[1] == mlreg.MODEL_WIDTH:
        # learned model: the two trailing lanes are label bounds, not
        # weights (ops/mlreg.py MODEL layout; a hand-written literal of
        # exactly MODEL_WIDTH lanes is indistinguishable — documented)
        mdata = mdata[:, : mlreg.MODEL_WIDTH - 2]
        mlens = jnp.minimum(mlens, mlreg.MODEL_WIDTH - 2)
    n = fdata.shape[0]
    if mdata.shape[0] == 1 and n > 1:
        mdata = jnp.broadcast_to(mdata, (n, mdata.shape[1]))
        mlens = jnp.broadcast_to(mlens, (n,))
    out = mlreg.predict(fdata, flens, mdata, mlens)
    return Val(out, and_valid(features.valid, model.valid), T.DOUBLE)


# ---------------------------------------------------------------------------
# round-5 registry tail (reference metadata/FunctionRegistry.java:360)
# ---------------------------------------------------------------------------


_f1("asinh", jnp.arcsinh)
_f1("acosh", jnp.arccosh)
_f1("atanh", jnp.arctanh)
_f1("cot", lambda x: jnp.cos(x) / jnp.sin(x))


@register("to_ieee754_64", _varchar_infer)
def _to_ieee754_64(a: Val, out_type: T.Type) -> Val:
    """double -> IEEE754 big-endian 8 bytes as hex (binary rides the
    string layer; literal-only like to_big_endian_64)."""
    import struct

    v = _require_literal(
        a, "to_ieee754_64 value (column inputs unsupported: unbounded "
           "output dictionary)"
    )
    s = struct.pack(">d", float(v)).hex().upper()
    return Val(
        jnp.zeros(a.data.shape, jnp.int32), a.valid, T.VARCHAR,
        intern_dictionary((s,)), literal=s,
    )


@register("to_ieee754_32", _varchar_infer)
def _to_ieee754_32(a: Val, out_type: T.Type) -> Val:
    import struct

    v = _require_literal(
        a, "to_ieee754_32 value (column inputs unsupported: unbounded "
           "output dictionary)"
    )
    s = struct.pack(">f", float(v)).hex().upper()
    return Val(
        jnp.zeros(a.data.shape, jnp.int32), a.valid, T.VARCHAR,
        intern_dictionary((s,)), literal=s,
    )


def _dict_table_gather(a: Val, build, np_dtype, out_t: T.Type, what: str):
    """Per-dictionary-entry scalar decode -> device gather by code: the
    numeric-output sibling of functions.py's _dict_transform. `build`
    maps one dictionary string to a python scalar (raising ValueError for
    malformed entries, which become NULL rows)."""
    d = a.dictionary
    if d is None:
        raise TypeError(f"{what} expects a varchar value")
    vals = np.zeros(len(d), np_dtype)
    oks = np.zeros(len(d), np.bool_)
    for i, s in enumerate(d):
        try:
            vals[i] = build(s)
            oks[i] = True
        except (ValueError, OverflowError):
            pass
    codes = jnp.clip(a.data.astype(jnp.int32), 0, max(len(d) - 1, 0))
    return Val(
        jnp.asarray(vals)[codes],
        and_valid(a.valid, jnp.asarray(oks)[codes]),
        out_t,
    )


def _hex_dict_to_float(a: Val, fmt: str, width: int):
    """Decode each dictionary entry's hex bytes -> float, gather by code
    (column inputs fine: the dictionary is bounded)."""
    import struct

    def build(s):
        try:
            return struct.unpack(fmt, bytes.fromhex(s))[0]
        except struct.error as e:
            raise ValueError(str(e))

    return _dict_table_gather(a, build, np.float64, T.DOUBLE, "from_ieee754")


@register("from_ieee754_64", _double_infer)
def _from_ieee754_64(a: Val, out_type: T.Type) -> Val:
    return _hex_dict_to_float(a, ">d", 8)


@register("from_ieee754_32", _double_infer)
def _from_ieee754_32(a: Val, out_type: T.Type) -> Val:
    return _hex_dict_to_float(a, ">f", 4)


@register("current_timezone", _varchar_infer)
def _current_timezone(a: Val = None, out_type: T.Type = None) -> Val:
    # the engine runs in UTC (types.py timestamp semantics)
    return Val(
        jnp.zeros((1,), jnp.int32), None, T.VARCHAR,
        intern_dictionary(("UTC",)), literal="UTC",
    )


@register("value_at_quantile", _double_infer)
def _value_at_quantile(sk: Val, q: Val, out_type: T.Type) -> Val:
    """Read a quantile off a qdigest-analog sketch (ops/qsketch.py
    ARRAY(BIGINT) rows from qdigest_agg)."""
    from ..ops import qsketch as qs

    if sk.data.ndim != 2:
        raise TypeError("value_at_quantile expects a qdigest sketch value")
    frac = float(_require_literal(q, "value_at_quantile fraction"))
    vals = qs.percentile_value(sk.data, frac)
    valid = and_valid(sk.valid, jnp.sum(sk.data, axis=1) > 0)
    return Val(vals.astype(jnp.float64), valid, T.DOUBLE)


@register("quantile_at_value", _double_infer)
def _quantile_at_value(sk: Val, v: Val, out_type: T.Type) -> Val:
    """Inverse read: the rank (0..1) of `v` in the sketch's distribution."""
    from ..ops import qsketch as qs

    if sk.data.ndim != 2:
        raise TypeError("quantile_at_value expects a qdigest sketch value")
    x = v.data.astype(jnp.float64)
    bucket = qs.bucket_of(x)
    total = jnp.sum(sk.data, axis=1)
    # counts in buckets strictly below the value's bucket + half its own
    lane = jnp.arange(sk.data.shape[1])[None, :]
    below = jnp.sum(
        jnp.where(lane < bucket[:, None], sk.data, 0), axis=1
    )
    own = jnp.take_along_axis(sk.data, bucket[:, None], axis=1)[:, 0]
    rank = (below + 0.5 * own) / jnp.maximum(total, 1)
    valid = and_valid(and_valid(sk.valid, v.valid), total > 0)
    return Val(rank, valid, T.DOUBLE)


@register("cosine_similarity", _double_infer)
def _cosine_similarity(a: Val, b: Val, out_type: T.Type) -> Val:
    """cosine_similarity(map(varchar,double), map(varchar,double)) —
    sparse vectors keyed by string (reference
    operator/scalar/CosineSimilarityFunction)."""
    if a.keys is None or b.keys is None:
        raise TypeError("cosine_similarity expects two map values")
    ka, kb = a.keys, b.keys
    la = _elem_live(a)
    lb = _elem_live(b)
    va = jnp.where(la, a.data.astype(jnp.float64), 0.0)
    vb = jnp.where(lb, b.data.astype(jnp.float64), 0.0)
    kad, kbd = ka.data, kb.data
    if ka.dict_id is not None and ka.dict_id != kb.dict_id:
        # dictionary-coded keys from different dictionaries: remap both
        # onto the merged sorted dictionary before comparing codes
        from ..page import dictionary_by_id

        da = dictionary_by_id(ka.dict_id)
        db = dictionary_by_id(kb.dict_id)
        merged = {s: i for i, s in enumerate(sorted(set(da) | set(db)))}
        map_a = jnp.asarray(
            np.array([merged[s] for s in da], np.int32)
        )
        map_b = jnp.asarray(
            np.array([merged[s] for s in db], np.int32)
        )
        kad = map_a[jnp.clip(kad, 0, len(da) - 1)]
        kbd = map_b[jnp.clip(kbd, 0, len(db) - 1)]
    # dot over matching keys: compare every key pair (maps are small)
    keq = kad[:, :, None] == kbd[:, None, :]
    keq = keq & la[:, :, None] & lb[:, None, :]
    dot = jnp.sum(keq * va[:, :, None] * vb[:, None, :], axis=(1, 2))
    na = jnp.sqrt(jnp.sum(va * va, axis=1))
    nb = jnp.sqrt(jnp.sum(vb * vb, axis=1))
    denom = na * nb
    out = jnp.where(denom > 0, dot / jnp.maximum(denom, 1e-300), jnp.nan)
    return Val(out, and_valid(a.valid, b.valid), T.DOUBLE)


@register("from_iso8601_timestamp", lambda ts: T.TIMESTAMP)
def _from_iso8601_timestamp(a: Val, out_type: T.Type) -> Val:
    """ISO8601 string -> timestamp (micros); dictionary transform."""
    import datetime as pydt

    def build(s):
        dt = pydt.datetime.fromisoformat(s.replace("Z", "+00:00"))
        if dt.tzinfo is not None:
            dt = dt.astimezone(pydt.timezone.utc).replace(tzinfo=None)
        return int(
            (dt - pydt.datetime(1970, 1, 1)).total_seconds() * 1_000_000
        )

    return _dict_table_gather(
        a, build, np.int64, T.TIMESTAMP, "from_iso8601_timestamp"
    )


def _spooky(bits: int):
    def impl(a: Val, out_type: T.Type) -> Val:
        """Spooky-hash stand-in over utf8 bytes via the same host-side
        dictionary transform as md5/xxhash (the reference's exact
        SpookyHashV2 constants are not replicated; the contract — a
        stable 32/64-bit hash of the bytes — is)."""

        def build(s):
            h = hashlib.blake2b(s.encode(), digest_size=8).digest()
            v = int.from_bytes(h, "big", signed=False)
            return v & (0xFFFFFFFF if bits == 32 else 0x7FFFFFFFFFFFFFFF)

        return _dict_table_gather(
            a, build, np.int64, T.BIGINT, "spooky_hash"
        )

    return impl


register("spooky_hash_v2_32", _bigint_infer)(_spooky(32))
register("spooky_hash_v2_64", _bigint_infer)(_spooky(64))


@register("inverse_beta_cdf", _double_infer)
def _inverse_beta_cdf(a: Val, b: Val, p: Val, out_type: T.Type) -> Val:
    """Inverse of beta_cdf via fixed-iteration bisection (64 steps ->
    ~2^-64 interval; XLA unrolls the loop, no data-dependent control
    flow)."""
    cdf = FUNCTIONS["beta_cdf"].impl
    av, bv = a, b
    target = _as_float(p)
    lo = jnp.zeros_like(target)
    hi = jnp.ones_like(target)
    for _ in range(64):
        mid = 0.5 * (lo + hi)
        c = cdf(av, bv, Val(mid, None, T.DOUBLE), out_type=T.DOUBLE).data
        go_hi = c < target
        lo = jnp.where(go_hi, mid, lo)
        hi = jnp.where(go_hi, hi, mid)
    out = 0.5 * (lo + hi)
    valid = and_valid(a.valid, b.valid, p.valid)
    return Val(out, valid, T.DOUBLE)


@register("split_to_map", lambda ts: T.MapType(T.VARCHAR, T.VARCHAR))
def _split_to_map(a: Val, entry_d: Val, kv_d: Val, out_type: T.Type) -> Val:
    """split_to_map('a=1,b=2', ',', '=') — per-dictionary-entry parse,
    padded to the widest entry count (reference SplitToMapFunction)."""
    ed = _require_literal(entry_d, "split_to_map entry delimiter")
    kd = _require_literal(kv_d, "split_to_map key/value delimiter")
    d = a.dictionary
    if d is None:
        raise TypeError("split_to_map expects a varchar value")
    parsed = []
    for s in d:
        pairs = []
        for part in s.split(ed):
            if not part:
                continue
            k, _, v = part.partition(kd)
            pairs.append((k, v))
        parsed.append(pairs)
    width = max((len(p) for p in parsed), default=0) or 1
    keypool = tuple(sorted({k for ps in parsed for k, _v in ps})) or ("",)
    valpool = tuple(sorted({v for ps in parsed for _k, v in ps})) or ("",)
    kidx = {s: i for i, s in enumerate(keypool)}
    vidx = {s: i for i, s in enumerate(valpool)}
    kmat = np.zeros((len(d), width), np.int32)
    vmat = np.zeros((len(d), width), np.int32)
    lens = np.zeros(len(d), np.int32)
    for i, ps in enumerate(parsed):
        lens[i] = len(ps)
        for j, (k, v) in enumerate(ps):
            kmat[i, j] = kidx[k]
            vmat[i, j] = vidx[v]
    codes = jnp.clip(a.data.astype(jnp.int32), 0, max(len(d) - 1, 0))
    klens = jnp.asarray(lens)[codes]
    keys = Val(
        jnp.asarray(kmat)[codes], None, T.VARCHAR,
        intern_dictionary(keypool), lengths=klens,
    )
    return Val(
        jnp.asarray(vmat)[codes], a.valid,
        T.MapType(T.VARCHAR, T.VARCHAR), intern_dictionary(valpool),
        lengths=klens, keys=keys,
    )


# ---------------------------------------------------------------------------
# geometry engine (round 5): polygons/linestrings as padded vertex lanes
# (reference presto-geospatial GeoFunctions.java — the Esri-backed
# surface re-implemented on ops/geometry.py's vectorized kernels; a
# geometry VALUE is an ARRAY(DOUBLE) of interleaved [x0,y0,x1,y1,...]
# with lengths = 2 * vertex count, so st_point values compose directly)
# ---------------------------------------------------------------------------


def _geom_verts(g: Val, what: str):
    """Interleaved lanes -> ((n, V, 2) vertices, (n,) counts)."""
    if g.lengths is None or g.data.ndim != 2:
        raise TypeError(f"{what} requires a geometry value")
    d = g.data.astype(jnp.float64)
    if d.shape[1] % 2:
        d = d[:, :-1]
    v = d.reshape(d.shape[0], -1, 2)
    return v, (g.lengths // 2).astype(jnp.int32)


def _wkt_parse_val(a: Val, what: str) -> Val:
    from ..ops import geometry as geo

    d = a.dictionary
    if d is None:
        raise TypeError(f"{what} expects a varchar WKT value")
    geoms, oks = [], np.zeros(len(d), np.bool_)
    for i, s in enumerate(d):
        try:
            _kind, v = geo.parse_wkt(s)
            geoms.append(v)
            oks[i] = True
        except ValueError:
            geoms.append(np.zeros((1, 2), np.float64))
    verts, nv = geo.pack_vertices(geoms)
    flat = verts.reshape(len(d), -1)  # interleaved lanes
    codes = jnp.clip(a.data.astype(jnp.int32), 0, max(len(d) - 1, 0))
    data = jnp.asarray(flat)[codes]
    lens = (jnp.asarray(nv) * 2)[codes]
    valid = and_valid(a.valid, jnp.asarray(oks)[codes])
    return Val(data, valid, T.ArrayType(T.DOUBLE), lengths=lens)


@register("st_geometryfromtext", lambda ts: T.ArrayType(T.DOUBLE))
def _st_geometryfromtext(a: Val, out_type: T.Type) -> Val:
    return _wkt_parse_val(a, "st_geometryfromtext")


@register("st_polygon", lambda ts: T.ArrayType(T.DOUBLE))
def _st_polygon(a: Val, out_type: T.Type) -> Val:
    return _wkt_parse_val(a, "st_polygon")


@register("st_linefromtext", lambda ts: T.ArrayType(T.DOUBLE))
def _st_linefromtext(a: Val, out_type: T.Type) -> Val:
    return _wkt_parse_val(a, "st_linefromtext")


def _broadcast_geoms(a: Val, b: Val, what: str):
    va, na = _geom_verts(a, what)
    vb, nb = _geom_verts(b, what)
    n = max(va.shape[0], vb.shape[0])
    if va.shape[0] == 1 and n > 1:
        va = jnp.broadcast_to(va, (n,) + va.shape[1:])
        na = jnp.broadcast_to(na, (n,))
    if vb.shape[0] == 1 and n > 1:
        vb = jnp.broadcast_to(vb, (n,) + vb.shape[1:])
        nb = jnp.broadcast_to(nb, (n,))
    return va, na, vb, nb


@register("st_contains", _bool_infer)
def _st_contains(g: Val, p: Val, out_type: T.Type) -> Val:
    """st_contains(geometry, geometry): every vertex of the right operand
    inside the left ring AND no proper edge crossing — exact for points
    and for hole-free polygons including concave containers (boundary
    contact allowed, matching the reference's closure semantics)."""
    from ..ops import geometry as geo

    va, na, vb, nb = _broadcast_geoms(g, p, "st_contains")
    out = geo.contains_all_vertices(va, na, vb, nb)
    return Val(out, and_valid(g.valid, p.valid), T.BOOLEAN)


@register("st_within", _bool_infer)
def _st_within(p: Val, g: Val, out_type: T.Type) -> Val:
    return _st_contains(g, p, out_type=T.BOOLEAN)


@register("st_intersects", _bool_infer)
def _st_intersects(a: Val, b: Val, out_type: T.Type) -> Val:
    from ..ops import geometry as geo

    va, na, vb, nb = _broadcast_geoms(a, b, "st_intersects")
    out = geo.polygons_intersect(va, na, vb, nb)
    return Val(out, and_valid(a.valid, b.valid), T.BOOLEAN)


@register("st_disjoint", _bool_infer)
def _st_disjoint(a: Val, b: Val, out_type: T.Type) -> Val:
    v = _st_intersects(a, b, out_type=T.BOOLEAN)
    return Val(~v.data, v.valid, T.BOOLEAN)


@register("st_area", _double_infer)
def _st_area(g: Val, out_type: T.Type) -> Val:
    from ..ops import geometry as geo

    v, nv = _geom_verts(g, "st_area")
    return Val(geo.polygon_area(v, nv), g.valid, T.DOUBLE)


@register("st_centroid", lambda ts: T.ArrayType(T.DOUBLE))
def _st_centroid(g: Val, out_type: T.Type) -> Val:
    from ..ops import geometry as geo

    v, nv = _geom_verts(g, "st_centroid")
    cx, cy = geo.polygon_centroid(v, nv)
    data = jnp.stack([cx, cy], axis=1)
    return Val(
        data, g.valid, T.ArrayType(T.DOUBLE),
        lengths=jnp.full(data.shape[0], 2, jnp.int32),
    )


@register("st_length", _double_infer)
def _st_length(g: Val, out_type: T.Type) -> Val:
    from ..ops import geometry as geo

    v, nv = _geom_verts(g, "st_length")
    return Val(geo.line_length(v, nv), g.valid, T.DOUBLE)


@register("st_perimeter", _double_infer)
def _st_perimeter(g: Val, out_type: T.Type) -> Val:
    from ..ops import geometry as geo

    v, nv = _geom_verts(g, "st_perimeter")
    return Val(geo.ring_perimeter(v, nv), g.valid, T.DOUBLE)


def _geom_reduce(g: Val, what: str, axis_sel: int, fn):
    v, nv = _geom_verts(g, what)
    lanes = jnp.arange(v.shape[1])[None, :] < nv[:, None]
    coord = v[..., axis_sel]
    big = jnp.float64(jnp.inf)
    if fn == "min":
        out = jnp.min(jnp.where(lanes, coord, big), axis=1)
    else:
        out = jnp.max(jnp.where(lanes, coord, -big), axis=1)
    return Val(out, and_valid(g.valid, nv > 0), T.DOUBLE)


@register("st_xmin", _double_infer)
def _st_xmin(g: Val, out_type: T.Type) -> Val:
    return _geom_reduce(g, "st_xmin", 0, "min")


@register("st_xmax", _double_infer)
def _st_xmax(g: Val, out_type: T.Type) -> Val:
    return _geom_reduce(g, "st_xmax", 0, "max")


@register("st_ymin", _double_infer)
def _st_ymin(g: Val, out_type: T.Type) -> Val:
    return _geom_reduce(g, "st_ymin", 1, "min")


@register("st_ymax", _double_infer)
def _st_ymax(g: Val, out_type: T.Type) -> Val:
    return _geom_reduce(g, "st_ymax", 1, "max")


@register("st_envelope", lambda ts: T.ArrayType(T.DOUBLE))
def _st_envelope(g: Val, out_type: T.Type) -> Val:
    """Bounding-box polygon (closed 5-vertex ring)."""
    x0 = _geom_reduce(g, "st_envelope", 0, "min").data
    x1 = _geom_reduce(g, "st_envelope", 0, "max").data
    y0 = _geom_reduce(g, "st_envelope", 1, "min").data
    y1 = _geom_reduce(g, "st_envelope", 1, "max").data
    data = jnp.stack(
        [x0, y0, x1, y0, x1, y1, x0, y1, x0, y0], axis=1
    )
    return Val(
        data, g.valid, T.ArrayType(T.DOUBLE),
        lengths=jnp.full(data.shape[0], 10, jnp.int32),
    )


@register("st_isclosed", _bool_infer)
def _st_isclosed(g: Val, out_type: T.Type) -> Val:
    v, nv = _geom_verts(g, "st_isclosed")
    last = jnp.take_along_axis(
        v, jnp.maximum(nv - 1, 0)[:, None, None].astype(jnp.int32), axis=1
    )[:, 0]
    closed = jnp.all(v[:, 0] == last, axis=1) & (nv >= 3)
    return Val(closed, g.valid, T.BOOLEAN)


@register("st_isempty", _bool_infer)
def _st_isempty(g: Val, out_type: T.Type) -> Val:
    _v, nv = _geom_verts(g, "st_isempty")
    return Val(nv == 0, g.valid, T.BOOLEAN)


@register("st_numpoints", _bigint_infer)
def _st_numpoints(g: Val, out_type: T.Type) -> Val:
    _v, nv = _geom_verts(g, "st_numpoints")
    return Val(nv.astype(jnp.int64), g.valid, T.BIGINT)


@register("classify", _bigint_infer)
def _classify(features: Val, model: Val, out_type: T.Type) -> Val:
    """classify(features, model): predicted INTEGER class label
    (reference presto-ml MLFunctions.classify over libsvm SVC). The
    TPU-first classifier rounds the ridge score and CLAMPS it to the
    label range recorded in the model at training time (ops/mlreg.py
    MODEL layout), so the output is always within the trained label
    set's bounds — exact for {0,1}, {-1,1} and ordinal integer labels
    (kernelized multiclass is out of scope)."""
    from ..ops import mlreg

    v = _regress(features, model, out_type=T.DOUBLE)
    md = mlreg.logical_values(model.data, model.type)
    if md.shape[1] == mlreg.MODEL_WIDTH:
        lmin, lmax = md[:, -2], md[:, -1]
        n = v.data.shape[0]
        if lmin.shape[0] == 1 and n > 1:
            lmin = jnp.broadcast_to(lmin, (n,))
            lmax = jnp.broadcast_to(lmax, (n,))
        score = jnp.clip(v.data, lmin, lmax)
    else:
        score = v.data
    return Val(
        jnp.round(score).astype(jnp.int64), v.valid, T.BIGINT
    )


# ---------------------------------------------------------------------------
# Joda-pattern datetime formatting (reference DateTimeFunctions.java
# format_datetime/parse_datetime — Joda syntax, vs date_format's MySQL)
# ---------------------------------------------------------------------------


def _joda_to_strptime(fmt: str) -> str:
    """Joda pattern -> strptime. Repeat-counted letters; '' escapes."""
    out = []
    i, n = 0, len(fmt)
    while i < n:
        c = fmt[i]
        if c == "'":  # quoted literal ('' = literal quote)
            if i + 1 < n and fmt[i + 1] == "'":
                out.append("'")
                i += 2
                continue
            j = fmt.index("'", i + 1) if "'" in fmt[i + 1:] else n
            out.append(fmt[i + 1:j].replace("%", "%%"))
            i = j + 1
            continue
        if c.isalpha():
            j = i
            while j < n and fmt[j] == c:
                j += 1
            cnt = j - i
            i = j
            if c == "y" or c == "Y":
                out.append("%Y" if cnt != 2 else "%y")
            elif c == "M":
                out.append("%m" if cnt <= 2 else ("%b" if cnt == 3 else "%B"))
            elif c == "d":
                out.append("%d")
            elif c == "D":
                out.append("%j")
            elif c == "E":
                out.append("%a" if cnt <= 3 else "%A")
            elif c == "H":
                out.append("%H")
            elif c == "h":
                out.append("%I")
            elif c == "m":
                out.append("%M")
            elif c == "s":
                out.append("%S")
            elif c == "S":
                out.append("%f")
            elif c == "a":
                out.append("%p")
            else:
                raise NotImplementedError(f"parse_datetime Joda letter {c!r}")
        else:
            out.append(c.replace("%", "%%"))
            i += 1
    return "".join(out)


@register("parse_datetime", lambda ts: T.TIMESTAMP)
def _parse_datetime(a: Val, fmt: Val, out_type: T.Type) -> Val:
    import datetime as _dt

    from .functions import _TS_US, _dict_table_nullable

    f = _joda_to_strptime(_require_literal(fmt, "parse_datetime format"))
    epoch = _dt.datetime(1970, 1, 1)

    def parse(s: str):
        try:
            us = (_dt.datetime.strptime(s, f) - epoch).total_seconds()
            return int(us * _TS_US), True
        except ValueError:
            return 0, False

    return _dict_table_nullable(a, parse, np.int64, T.TIMESTAMP)


@register("format_datetime", _varchar_infer)
def _format_datetime(a: Val, fmt: Val, out_type: T.Type) -> Val:
    """Joda-pattern formatting of date/timestamp values. Date-valued like
    date_format: day strings come from a precomputed 1582..2500 day table
    (functions.py _date_format_table machinery); time-of-day letters on
    timestamps are rejected the same way date_format rejects %H/%i/%s."""
    from .functions import (
        _DATE_FMT_BASE,
        _DATE_FMT_N,
        _TS_US,
        _date_format_table,
        _mysql_format_date,  # noqa: F401  (documents the sibling model)
    )

    f = _require_literal(fmt, "format_datetime format")
    strp = _joda_to_strptime(f)  # validates letters; %-free = literal
    if isinstance(a.type, T.TimestampType):
        if any(s in strp for s in ("%H", "%I", "%M", "%S", "%f", "%p")):
            raise NotImplementedError(
                "format_datetime with time-of-day letters on timestamp"
            )
        days = (a.data // (86400 * _TS_US)).astype(jnp.int64)
    elif isinstance(a.type, T.DateType):
        days = a.data.astype(jnp.int64)
    else:
        raise TypeError(f"format_datetime on {a.type}")
    # reuse the cached day table keyed by the equivalent strftime string
    import datetime as _dt

    cache_key = ("joda", f)
    from .functions import _DATE_FMT_CACHE

    cached = _DATE_FMT_CACHE.get(cache_key)
    if cached is None:
        base = _dt.date(1582, 10, 15)
        strings = [
            (base + _dt.timedelta(days=i)).strftime(strp)
            for i in range(_DATE_FMT_N)
        ]
        dictionary = tuple(sorted(set(strings)))
        index = {s: i for i, s in enumerate(dictionary)}
        cached = (dictionary, np.array([index[s] for s in strings], np.int32))
        _DATE_FMT_CACHE[cache_key] = cached
    dictionary, mapping = cached
    off = days - _DATE_FMT_BASE
    in_range = (off >= 0) & (off < _DATE_FMT_N)
    codes = jnp.asarray(mapping)[
        jnp.clip(off, 0, _DATE_FMT_N - 1).astype(jnp.int32)
    ]
    return Val(
        codes,
        and_valid(a.valid, in_range),
        T.VARCHAR,
        intern_dictionary(dictionary),
    )


@register("parse_presto_data_size", _double_infer)
def _parse_presto_data_size(a: Val, out_type: T.Type) -> Val:
    """'2.3MB' -> bytes. Reference returns DECIMAL(38,0)
    (DataSizeFunctions.java); here DOUBLE — the unit ladder reaches ZB/YB
    which overflow int64, and the engine's numeric tower treats DOUBLE as
    the widest plain scalar."""
    import re as _re

    from .functions import _dict_table_nullable

    units = {
        "B": 1.0, "kB": 2.0**10, "MB": 2.0**20, "GB": 2.0**30,
        "TB": 2.0**40, "PB": 2.0**50, "EB": 2.0**60, "ZB": 2.0**70,
        "YB": 2.0**80,
    }
    pat = _re.compile(r"^\s*([0-9]*\.?[0-9]+)\s*([A-Za-z]+)\s*$")

    def f(s: str):
        m = pat.match(s)
        if not m or m.group(2) not in units:
            return 0.0, False
        return float(m.group(1)) * units[m.group(2)], True

    return _dict_table_nullable(a, f, np.float64, T.DOUBLE)


@register("array_concat", lambda ts: ts[0])
def _array_concat(a: Val, b: Val, out_type: T.Type) -> Val:
    """ARRAY || ARRAY (reference ArrayConcatFunction): output lane j is
    a's element j while j < len(a), then b's element j - len(a) — two
    take_along_axis gathers over the padded lanes, no per-row loops."""
    if a.data.ndim != 2 or b.data.ndim != 2:
        raise TypeError("array_concat requires array values")
    da, db, did = a.data, b.data, a.dict_id
    if (a.dict_id is not None or b.dict_id is not None) and (
        a.dict_id != b.dict_id
    ):
        from .functions import unify_dictionaries

        da, db, did = unify_dictionaries(a, b)
    if da.dtype != db.dtype:
        wide = jnp.promote_types(da.dtype, db.dtype)
        da, db = da.astype(wide), db.astype(wide)
    cap, wa = da.shape[0], da.shape[1]
    wb = db.shape[1]
    W = wa + wb
    la = (
        a.lengths
        if a.lengths is not None
        else jnp.full(cap, wa, jnp.int32)
    )
    lb = (
        b.lengths
        if b.lengths is not None
        else jnp.full(cap, wb, jnp.int32)
    )
    j = jnp.arange(W, dtype=jnp.int32)[None, :]
    from_a = j < la[:, None]
    ia = jnp.clip(j, 0, wa - 1)
    ib = jnp.clip(j - la[:, None], 0, wb - 1)
    ga = jnp.take_along_axis(da, ia, axis=1)
    gb = jnp.take_along_axis(db, ib, axis=1)
    data = jnp.where(from_a, ga, gb)
    eva = (
        a.elem_valid
        if a.elem_valid is not None
        else jnp.ones((cap, wa), jnp.bool_)
    )
    evb = (
        b.elem_valid
        if b.elem_valid is not None
        else jnp.ones((cap, wb), jnp.bool_)
    )
    ev = jnp.where(
        from_a,
        jnp.take_along_axis(eva, ia, axis=1),
        jnp.take_along_axis(evb, ib, axis=1),
    )
    return Val(
        data,
        and_valid(a.valid, b.valid),
        out_type,
        did,
        lengths=(la + lb).astype(jnp.int32),
        elem_valid=ev,
    )
