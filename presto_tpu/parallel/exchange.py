"""Hash-repartition exchange over the device mesh.

Re-designed equivalent of the reference's shuffle: producer side
PartitionedOutputOperator.partitionPage (presto-main/.../operator/
PartitionedOutputOperator.java:276 — row→partition hash, per-partition
PageBuilders) and consumer side ExchangeClient/ExchangeOperator
(operator/ExchangeClient.java:55) pulling serialized pages over HTTP.

TPU-first redesign: the whole exchange is ONE collective. Each shard scatters
its rows into a (num_parts, part_capacity) send buffer (shuffle_write — the
PartitionedOutputBuffer analog), `jax.lax.all_to_all` swaps buffers across the
mesh axis over ICI, and the receiver compacts occupancy (all_to_all_page — the
ExchangeClient analog). No serde, no compression, no HTTP: pages never leave
HBM. Broadcast build sides ride `all_gather` (the reference's
FIXED_BROADCAST_DISTRIBUTION / BroadcastOutputBuffer).

All functions here must run inside `shard_map` over the named mesh axis.
Static shapes: part_capacity bounds rows per (sender, partition); overflow is
counted and returned so the host can retry with a bigger capacity (the
reference instead blocks producers via OutputBufferMemoryManager — with
static shapes, detect-and-retry replaces backpressure).
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp

from ..expr.compiler import evaluate
from ..ops.filter import compact
from ..ops.hashing import hash_rows
from ..page import Block, Page


def shuffle_write(
    page: Page, key_exprs, num_parts: int, part_capacity: int
) -> Tuple[Page, jnp.ndarray, jnp.ndarray]:
    """Scatter live rows into per-partition slots by key hash.

    Returns (buffer_page, counts, dropped): buffer_page has capacity
    num_parts*part_capacity with partition p occupying rows
    [p*part_capacity, p*part_capacity + counts[p]); dropped counts overflow
    rows that exceeded part_capacity (host checks == 0)."""
    keys = [evaluate(e, page) for e in key_exprs]
    live = page.live_mask()
    h = hash_rows(keys)
    part = (h % jnp.uint64(num_parts)).astype(jnp.int32)
    part = jnp.where(live, part, num_parts)  # dead rows -> dropped
    return shuffle_write_parts(page, part, num_parts, part_capacity)


def shuffle_write_parts(
    page: Page, part: jnp.ndarray, num_parts: int, part_capacity: int
) -> Tuple[Page, jnp.ndarray, jnp.ndarray]:
    """shuffle_write over PRECOMPUTED per-row destinations: `part[i]` in
    [0, num_parts) routes row i, anything >= num_parts drops it (dead
    rows / overflow sentinel). Shared by the mesh repartition above and
    the hierarchical exchange producer (server/hier.py), whose routing —
    downstream partition modulo local device — is not a plain
    hash-modulo."""
    part = jnp.minimum(part.astype(jnp.int32), num_parts)
    order = jnp.argsort(part, stable=True)
    part_s = part[order]
    bins = jnp.arange(num_parts, dtype=part_s.dtype)
    starts = jnp.searchsorted(part_s, bins, side="left").astype(jnp.int32)
    ends = jnp.searchsorted(part_s, bins, side="right").astype(jnp.int32)
    cap = page.capacity
    within = jnp.arange(cap, dtype=jnp.int32) - starts[
        jnp.minimum(part_s, num_parts - 1)
    ]
    ok = (part_s < num_parts) & (within < part_capacity)
    total = num_parts * part_capacity
    dest = jnp.where(ok, part_s * part_capacity + within, total)

    blocks = []
    for b in page.blocks:
        data = jnp.zeros((total,) + b.data.shape[1:], b.data.dtype).at[
            dest
        ].set(b.data[order], mode="drop")
        valid = None
        if b.valid is not None:
            valid = jnp.zeros((total,), jnp.bool_).at[dest].set(
                b.valid[order], mode="drop"
            )
        blocks.append(Block(data, b.type, valid, b.dict_id))

    run = ends - starts
    counts = jnp.minimum(run, part_capacity)
    dropped = jnp.sum(jnp.maximum(run - part_capacity, 0))
    buf = Page(tuple(blocks), page.names, jnp.asarray(total, jnp.int32))
    return buf, counts, dropped


def all_to_all_page(
    buf: Page, counts: jnp.ndarray, axis_name: str, part_capacity: int
) -> Page:
    """Swap partition buffers across the mesh axis and compact received rows.

    Partition count must equal the axis size (one partition per chip —
    FIXED_HASH_DISTRIBUTION over the slice). Rides ICI; XLA overlaps the
    collective with surrounding compute where possible."""
    num_parts = buf.capacity // part_capacity

    def a2a(x):
        y = x.reshape((num_parts, part_capacity) + x.shape[1:])
        y = jax.lax.all_to_all(y, axis_name, split_axis=0, concat_axis=0)
        return y.reshape(x.shape)

    blocks = []
    for b in buf.blocks:
        data = a2a(b.data)
        valid = None if b.valid is None else a2a(b.valid)
        blocks.append(Block(data, b.type, valid, b.dict_id))
    counts_r = jax.lax.all_to_all(
        counts.reshape(num_parts, 1), axis_name, split_axis=0, concat_axis=0
    ).reshape(num_parts)
    occ = (
        jnp.arange(part_capacity, dtype=jnp.int32)[None, :] < counts_r[:, None]
    ).reshape(-1)
    page = Page(tuple(blocks), buf.names, jnp.asarray(buf.capacity, jnp.int32))
    return compact(page, occ)


def exchange_by_hash(
    page: Page, key_exprs, axis_name: str, num_parts: int, part_capacity: int
) -> Tuple[Page, jnp.ndarray]:
    """Full repartition: rows land on chip hash(keys) % num_parts.

    Returns (received_page, dropped). After this, rows with equal keys are
    co-resident on one chip — the invariant FIXED_HASH_DISTRIBUTION gives
    Presto's aggregations/joins."""
    buf, counts, dropped = shuffle_write(page, key_exprs, num_parts, part_capacity)
    return all_to_all_page(buf, counts, axis_name, part_capacity), dropped


def all_gather_page(page: Page, axis_name: str, axis_size: int) -> Page:
    """Replicate every shard's live rows on every chip (broadcast join build
    sides — the reference's BroadcastOutputBuffer + replicated join)."""
    counts = jax.lax.all_gather(page.count, axis_name)  # (P,)
    cap = page.capacity
    blocks = []
    for b in page.blocks:
        data = jax.lax.all_gather(b.data, axis_name)  # (P, cap, ...)
        data = data.reshape((axis_size * cap,) + b.data.shape[1:])
        valid = None
        if b.valid is not None:
            valid = jax.lax.all_gather(b.valid, axis_name).reshape(-1)
        blocks.append(Block(data, b.type, valid, b.dict_id))
    occ = (
        jnp.arange(cap, dtype=jnp.int32)[None, :] < counts[:, None]
    ).reshape(-1)
    out = Page(tuple(blocks), page.names, jnp.asarray(axis_size * cap, jnp.int32))
    return compact(out, occ)
