"""Distributed operator compositions over the mesh.

Each function here is a full SPMD *stage pipeline* — the analog of a Presto
multi-stage plan (partial agg stage → exchange → final agg stage, see the
SqlQueryScheduler stage wiring in SURVEY.md §3.2) collapsed into one
shard_map'd program: XLA sees the whole thing and can overlap the all_to_all
with local compute.

Output schemas of staged sub-plans are inferred with jax.eval_shape — Page is
a pytree whose aux data carries types/dictionaries, so shape inference gives
the exact post-exchange schema without running anything.

Compiled SPMD steps are cached on (mesh, schema, plan shape): re-running the
same query shape must NOT recompile (the reference compiles bytecode once per
plan in LocalExecutionPlanner, then reuses it for every page).
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..expr.ir import ColumnRef
from ..ops.aggregate import (
    AggSpec,
    apply_avg_post,
    decompose_partial,
    grouped_aggregate_sorted,
)
from ..ops.filter import compact
from ..page import Page
from .exchange import exchange_by_hash
from .mesh import page_from_arrays, page_schema, page_to_arrays, shard_rows


def _merge_shard_pages(out_leaves, out_schema, out_counts, rows_per_shard: int):
    """Concatenated per-shard outputs -> one compacted global Page.

    Shard counts are clamped to rows_per_shard; callers must separately check
    counts <= rows_per_shard to detect overflow (see dist_grouped_aggregate)."""
    n = out_counts.shape[0]
    occ = (
        jnp.arange(rows_per_shard, dtype=jnp.int32)[None, :]
        < jnp.minimum(out_counts, rows_per_shard)[:, None]
    ).reshape(-1)
    merged = page_from_arrays(out_leaves, out_schema, n * rows_per_shard)
    return compact(merged, occ)


_STEP_CACHE: dict = {}


def _agg_step(
    mesh,
    axis: str,
    schema,
    group_exprs,
    group_names,
    partial_specs,
    final_specs,
    max_groups: int,
    part_capacity: int,
    prelude,
    shard_shape_key,
):
    """Build (or fetch) the compiled SPMD aggregation step for this plan
    shape. Returns (step_fn, out_schema)."""
    key = (
        mesh,
        axis,
        schema,
        tuple(group_exprs),
        tuple(group_names),
        partial_specs,
        final_specs,
        max_groups,
        part_capacity,
        prelude,
        shard_shape_key,
    )
    hit = _STEP_CACHE.get(key)
    if hit is not None:
        return hit

    n = mesh.shape[axis]

    def local_partial(shard_leaves, count):
        local = page_from_arrays(shard_leaves, schema, count)
        if prelude is not None:
            local = prelude(local)
        return grouped_aggregate_sorted(
            local, group_exprs, group_names, partial_specs, max_groups
        )

    # static schema inference: the exchange preserves schema, so the final
    # aggregation's output schema follows from the partial page's schema
    shard_struct = tuple(
        jax.ShapeDtypeStruct(shape, dtype) for shape, dtype in shard_shape_key
    )
    count_struct = jax.ShapeDtypeStruct((), jnp.int32)
    partial_struct = jax.eval_shape(local_partial, shard_struct, count_struct)
    key_exprs = [ColumnRef(nm, partial_struct.block(nm).type) for nm in group_names]

    def local_final(recv: Page) -> Page:
        return grouped_aggregate_sorted(
            recv, key_exprs, group_names, final_specs, max_groups
        )

    final_struct = jax.eval_shape(local_final, partial_struct)
    out_schema = page_schema(final_struct)
    n_leaves = len(page_to_arrays(final_struct))

    from ..exec.dist import _shard_map

    @jax.jit
    @functools.partial(
        _shard_map,
        mesh=mesh,
        in_specs=(tuple(P(axis) for _ in schema_leaf_count(schema)), P(axis)),
        out_specs=(
            tuple(P(axis) for _ in range(n_leaves)),
            P(axis),
            P(axis),
            P(axis),
        ),
    )
    def step(shard_leaves, counts):
        partial = local_partial(shard_leaves, counts[0])
        recv, dropped = exchange_by_hash(partial, key_exprs, axis, n, part_capacity)
        final = local_final(recv)
        return (
            page_to_arrays(final),
            final.count.reshape(1),
            partial.count.reshape(1),
            dropped.reshape(1).astype(jnp.int32),
        )

    _STEP_CACHE[key] = (step, out_schema)
    return step, out_schema


def schema_leaf_count(schema):
    """One entry per flat leaf of a page with this schema (data + valids)."""
    leaves = []
    for name, typ, dict_id, has_valid in schema:
        leaves.append((name, "data"))
        if has_valid:
            leaves.append((name, "valid"))
    return leaves


def dist_grouped_aggregate(
    mesh,
    axis: str,
    page: Page,
    group_exprs,
    group_names: Sequence[str],
    aggs: Sequence[AggSpec],
    max_groups: int,
    part_capacity: int,
    prelude=None,
) -> Page:
    """Distributed GROUP BY: shard rows → [prelude: local scan-filter-project
    stage] → local partial agg → all_to_all repartition partial rows by
    group-key hash → final agg → merge shards.

    The canonical Presto two-stage aggregation (partial at the source stage,
    FIXED_HASH exchange, final at the middle stage) as one SPMD program.
    Returns a single compacted Page (the root stage output buffer analog).

    Raises RuntimeError if max_groups or part_capacity were undersized —
    static shapes make overflow a detect-and-retry condition, not silent
    truncation (the reference instead grows hash tables / blocks producers)."""
    n = mesh.shape[axis]
    page, shard_counts = shard_rows(page, n)
    schema = page_schema(page)
    leaves = page_to_arrays(page)
    partial_specs, final_specs, post = decompose_partial(aggs)
    shard_shape_key = tuple(
        ((l.shape[0] // n,) + l.shape[1:], l.dtype) for l in leaves
    )

    step, out_schema = _agg_step(
        mesh,
        axis,
        schema,
        group_exprs,
        group_names,
        partial_specs,
        final_specs,
        max_groups,
        part_capacity,
        prelude,
        shard_shape_key,
    )
    out_leaves, out_counts, partial_counts, dropped = step(leaves, shard_counts)
    if int(jnp.max(partial_counts)) > max_groups:
        raise RuntimeError(
            f"partial aggregation overflow: a shard produced "
            f"{int(jnp.max(partial_counts))} groups > max_groups={max_groups}"
        )
    if int(jnp.max(out_counts)) > max_groups:
        raise RuntimeError(
            f"final aggregation overflow: a shard holds "
            f"{int(jnp.max(out_counts))} groups > max_groups={max_groups}"
        )
    if int(jnp.sum(dropped)) != 0:
        raise RuntimeError(
            f"exchange overflow: {int(jnp.sum(dropped))} partial rows dropped; "
            "increase part_capacity"
        )
    merged = _merge_shard_pages(out_leaves, out_schema, out_counts, max_groups)
    return apply_avg_post(merged, aggs, post)
