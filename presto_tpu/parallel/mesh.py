"""Device mesh construction + Page <-> shard_map plumbing.

The reference's analog is node discovery + partitioning handles
(metadata/DiscoveryNodeManager.java, sql/planner/SystemPartitioningHandle.java:57-65):
FIXED_HASH_DISTRIBUTION over N workers becomes a jax.sharding.Mesh axis of N
chips. A Page's `count` is a scalar pytree leaf, which shard_map cannot split
by rows, so staged SPMD functions pass block arrays + a per-shard count vector
and rebuild local Pages inside the mapped function via `page_from_arrays`.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..page import Block, Page

WORKER_AXIS = "workers"


def default_mesh(n_devices: Optional[int] = None, axis: str = WORKER_AXIS):
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return jax.sharding.Mesh(np.array(devices), (axis,))


# -- Page <-> flat arrays (for shard_map in/out trees) ----------------------

# schema item: (name, Type, dict_id, has_valid)
Schema = Tuple[Tuple[str, object, Optional[int], bool], ...]


def page_schema(page: Page) -> Schema:
    return tuple(
        (n, b.type, b.dict_id, b.valid is not None)
        for n, b in zip(page.names, page.blocks)
    )


def page_to_arrays(page: Page):
    """Flatten to a tuple of arrays ordered (data, [valid]) per column."""
    leaves = []
    for b in page.blocks:
        leaves.append(b.data)
        if b.valid is not None:
            leaves.append(b.valid)
    return tuple(leaves)


def page_from_arrays(leaves: Sequence[jax.Array], schema: Schema, count) -> Page:
    blocks = []
    i = 0
    for name, typ, dict_id, has_valid in schema:
        data = leaves[i]
        i += 1
        valid = None
        if has_valid:
            valid = leaves[i]
            i += 1
        blocks.append(Block(data, typ, valid, dict_id))
    names = tuple(s[0] for s in schema)
    return Page(tuple(blocks), names, jnp.asarray(count, jnp.int32))


def shard_rows(page: Page, num_shards: int):
    """Split a host/global Page into contiguous row shards.

    Returns (padded_page, shard_counts) where padded_page's capacity is a
    multiple of num_shards (shard i owns rows [i*c, (i+1)*c)) and
    shard_counts[i] is the live row count of shard i. This is the analog of
    leaf-split assignment (SourcePartitionedScheduler): contiguous ranges of
    the table become per-worker morsels."""
    cap = page.capacity
    per = -(-cap // num_shards)  # ceil
    target = per * num_shards
    if target != cap:
        from ..page import _pad_block

        blocks = tuple(_pad_block(b, target) for b in page.blocks)
        page = Page(blocks, page.names, page.count)
    shard_counts = jnp.clip(
        page.count - jnp.arange(num_shards, dtype=jnp.int32) * per, 0, per
    ).astype(jnp.int32)
    return page, shard_counts
