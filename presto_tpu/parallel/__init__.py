"""Multi-chip parallelism: device mesh, page exchange, distributed operators.

The TPU-native replacement for the reference's exchange/communication layer
(SURVEY.md §2.7): where Presto shuffles LZ4-serialized pages over HTTP
(presto-main/.../execution/buffer/PagesSerde.java:39, operator/
ExchangeClient.java:55), this package repartitions device-resident Pages with
`jax.lax.all_to_all` over the ICI mesh inside `shard_map`, broadcasts build
sides with `all_gather`, and expresses every stage as an SPMD program.
"""

from .mesh import (  # noqa: F401
    default_mesh,
    page_from_arrays,
    page_schema,
    page_to_arrays,
    shard_rows,
)
from .exchange import (  # noqa: F401
    all_gather_page,
    all_to_all_page,
    exchange_by_hash,
    shuffle_write,
)
from .distributed import dist_grouped_aggregate  # noqa: F401
