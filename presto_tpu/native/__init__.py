"""Native (C++) runtime components, built on demand with the system
toolchain and loaded via ctypes.

The compute path is JAX/XLA; these are the host-side byte-bashing loops
the reference keeps in its own perf substrate (airlift Slice + pure-Java
LZ4) — here they are actual native code. First import compiles
`lz4.cpp` with g++ into `_ptpu_native.so` next to this file (cached by
mtime); environments without a toolchain fall back cleanly (`available()`
is False and callers use zlib).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path
from typing import Optional

_DIR = Path(__file__).resolve().parent
_SRC = _DIR / "lz4.cpp"
_SO = _DIR / "_ptpu_native.so"

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_error: Optional[str] = None


def _build() -> None:
    # compile to a per-pid temp file and rename atomically: concurrent
    # processes must never dlopen a half-written .so
    tmp = _SO.with_suffix(f".so.tmp.{os.getpid()}")
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-std=c++17",
        "-o", str(tmp), str(_SRC),
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, _SO)
    finally:
        tmp.unlink(missing_ok=True)


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_error
    if _lib is not None or _build_error is not None:
        return _lib
    with _lock:
        if _lib is not None or _build_error is not None:
            return _lib
        try:
            if not _SO.exists() or _SO.stat().st_mtime < _SRC.stat().st_mtime:
                _build()
            lib = ctypes.CDLL(str(_SO))
            lib.ptpu_lz4_compress.restype = ctypes.c_int
            lib.ptpu_lz4_compress.argtypes = [
                ctypes.c_char_p, ctypes.c_int,
                ctypes.POINTER(ctypes.c_uint8), ctypes.c_int,
            ]
            lib.ptpu_lz4_decompress.restype = ctypes.c_int
            lib.ptpu_lz4_decompress.argtypes = [
                ctypes.c_char_p, ctypes.c_int,
                ctypes.POINTER(ctypes.c_uint8), ctypes.c_int,
            ]
            _lib = lib
        except Exception as e:  # noqa: BLE001 - no toolchain / bad env
            _build_error = f"{type(e).__name__}: {e}"
    return _lib


def available() -> bool:
    return _load() is not None


def build_error() -> Optional[str]:
    _load()
    return _build_error


def lz4_compress(data: bytes) -> bytes:
    """LZ4 block-format compress. Raises RuntimeError if the native codec
    is unavailable or the buffer is incompressible past the bound."""
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native codec unavailable: {_build_error}")
    n = len(data)
    cap = n + n // 128 + 64  # worst case: tokens + length extensions
    dst = (ctypes.c_uint8 * cap)()
    out = lib.ptpu_lz4_compress(data, n, dst, cap)
    if out < 0:
        raise RuntimeError("lz4 compress overflow")
    return ctypes.string_at(dst, out)


def lz4_decompress(data: bytes, original_size: int) -> bytes:
    """Decode an LZ4 block. Falls back to a pure-Python decoder when the
    native library is unavailable, so a toolchain-less receiver can still
    read codec-2 pages produced by a peer that has one."""
    lib = _load()
    if lib is None:
        out = _py_lz4_decompress(data)
        if len(out) != original_size:
            raise ValueError(
                f"lz4 decompress: got {len(out)}, expected {original_size}"
            )
        return out
    dst = (ctypes.c_uint8 * max(original_size, 1))()
    out = lib.ptpu_lz4_decompress(data, len(data), dst, original_size)
    if out != original_size:
        raise ValueError(
            f"lz4 decompress: got {out}, expected {original_size}"
        )
    return ctypes.string_at(dst, original_size)


def _py_lz4_decompress(src: bytes) -> bytes:
    """Spec-faithful LZ4 block decoder (slow path; correctness fallback)."""
    out = bytearray()
    i = 0
    n = len(src)
    while i < n:
        token = src[i]
        i += 1
        lit = token >> 4
        if lit == 15:
            while True:
                if i >= n:
                    raise ValueError("truncated literal length")
                b = src[i]
                i += 1
                lit += b
                if b != 255:
                    break
        if i + lit > n:
            raise ValueError("truncated literals")
        out += src[i : i + lit]
        i += lit
        if i >= n:
            break
        if i + 2 > n:
            raise ValueError("truncated offset")
        off = src[i] | (src[i + 1] << 8)
        i += 2
        if off == 0 or off > len(out):
            raise ValueError("bad match offset")
        m = token & 15
        if m == 15:
            while True:
                if i >= n:
                    raise ValueError("truncated match length")
                b = src[i]
                i += 1
                m += b
                if b != 255:
                    break
        m += 4
        for _ in range(m):
            out.append(out[-off])
    return bytes(out)
