// LZ4 block-format codec for the page wire serde.
//
// Native equivalent of the reference's aircompressor Lz4Compressor /
// Lz4Decompressor used by PagesSerde
// (presto-main/.../execution/buffer/PagesSerde.java:18-34) — the one
// perf-critical byte-bashing loop in the exchange path that the JVM
// reference also keeps out of "interpreted" code. Emits/consumes the
// standard LZ4 *block* format (token | literals | 16-bit LE offset |
// match continuation), so output is interoperable with any LZ4 block
// decoder.
//
// Compressor: greedy single-pass with an 8k-entry position hash of the
// last 4-byte occurrence (the classic LZ4 fast level). Safety rules per
// the spec: the final 5 bytes are always literals and no match may start
// within the last 12 bytes.

#include <cstdint>
#include <cstring>

namespace {

inline uint32_t read32(const uint8_t* p) {
    uint32_t v;
    std::memcpy(&v, p, 4);
    return v;
}

inline uint32_t hash4(uint32_t v) { return (v * 2654435761u) >> 19; }

constexpr int HASH_BITS = 13;
constexpr int HASH_SIZE = 1 << HASH_BITS;
constexpr int MFLIMIT = 12;   // no match starts in the last 12 bytes
constexpr int LASTLITERALS = 5;  // final 5 bytes are literal-only

inline bool emit_length(uint8_t* dst, int cap, int& op, int len) {
    while (len >= 255) {
        if (op >= cap) return false;
        dst[op++] = 255;
        len -= 255;
    }
    if (op >= cap) return false;
    dst[op++] = static_cast<uint8_t>(len);
    return true;
}

}  // namespace

extern "C" {

// Returns compressed size, or -1 if dst (cap bytes) is too small.
int ptpu_lz4_compress(const uint8_t* src, int n, uint8_t* dst, int cap) {
    int32_t table[HASH_SIZE];
    for (int i = 0; i < HASH_SIZE; i++) table[i] = -1;

    int ip = 0, anchor = 0, op = 0;
    const int mflimit = n - MFLIMIT;

    while (ip < mflimit) {
        uint32_t h = hash4(read32(src + ip));
        int32_t ref = table[h];
        table[h] = ip;
        if (ref < 0 || ip - ref > 65535 || read32(src + ref) != read32(src + ip)) {
            ip++;
            continue;
        }
        // extend the match, leaving the last 5 bytes as literals
        int mlen = 4;
        const int limit = n - LASTLITERALS;
        while (ip + mlen < limit && src[ref + mlen] == src[ip + mlen]) mlen++;

        int lit = ip - anchor;
        if (op >= cap) return -1;
        uint8_t* token = dst + op++;
        if (lit >= 15) {
            *token = 15u << 4;
            if (!emit_length(dst, cap, op, lit - 15)) return -1;
        } else {
            *token = static_cast<uint8_t>(lit << 4);
        }
        if (op + lit > cap) return -1;
        std::memcpy(dst + op, src + anchor, lit);
        op += lit;

        int off = ip - ref;
        if (op + 2 > cap) return -1;
        dst[op++] = static_cast<uint8_t>(off & 0xff);
        dst[op++] = static_cast<uint8_t>((off >> 8) & 0xff);

        int m = mlen - 4;
        if (m >= 15) {
            *token |= 15;
            if (!emit_length(dst, cap, op, m - 15)) return -1;
        } else {
            *token |= static_cast<uint8_t>(m);
        }
        ip += mlen;
        anchor = ip;
    }

    // trailing literals
    int lit = n - anchor;
    if (op >= cap) return -1;
    uint8_t* token = dst + op++;
    if (lit >= 15) {
        *token = 15u << 4;
        if (!emit_length(dst, cap, op, lit - 15)) return -1;
    } else {
        *token = static_cast<uint8_t>(lit << 4);
    }
    if (op + lit > cap) return -1;
    std::memcpy(dst + op, src + anchor, lit);
    op += lit;
    return op;
}

// Returns decompressed size, or -1 on malformed input / overflow.
// Length accumulators are 64-bit: a hostile stream of 0xFF extension
// bytes must saturate the bounds checks, not wrap a 32-bit int into a
// negative that bypasses them.
int ptpu_lz4_decompress(const uint8_t* src, int n, uint8_t* dst, int cap) {
    int64_t ip = 0, op = 0;
    while (ip < n) {
        uint8_t token = src[ip++];
        int64_t lit = token >> 4;
        if (lit == 15) {
            uint8_t b;
            do {
                if (ip >= n) return -1;
                b = src[ip++];
                lit += b;
            } while (b == 255);
        }
        if (ip + lit > n || op + lit > cap) return -1;
        std::memcpy(dst + op, src + ip, static_cast<size_t>(lit));
        ip += lit;
        op += lit;
        if (ip >= n) break;  // last sequence carries literals only

        if (ip + 2 > n) return -1;
        int64_t off = src[ip] | (src[ip + 1] << 8);
        ip += 2;
        if (off == 0 || off > op) return -1;
        int64_t m = token & 15;
        if (m == 15) {
            uint8_t b;
            do {
                if (ip >= n) return -1;
                b = src[ip++];
                m += b;
            } while (b == 255);
        }
        m += 4;
        if (op + m > cap) return -1;
        const uint8_t* ref = dst + op - off;  // may overlap: copy forward
        for (int64_t i = 0; i < m; i++) dst[op + i] = ref[i];
        op += m;
    }
    return static_cast<int>(op);
}

}  // extern "C"
