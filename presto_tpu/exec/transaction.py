"""Multi-statement transactions: an overlay catalog buffering writes.

Re-designed equivalent of the reference's TransactionManager
(presto-main/.../transaction/TransactionManager.java: per-transaction
connector handles with commit/abort; most connectors commit buffered
state at transaction end — e.g. the memory/hive page sinks). TPU-first
shape: the transaction IS a catalog — an overlay over the session's
writable catalog where every DDL/DML lands in host-memory staging
tables. Reads inside the transaction see the overlay first
(read-your-writes); COMMIT replays the staged state onto the base
catalog table-by-table (the reference's connector-commit granularity —
cross-table atomicity is per-connector best effort there too);
ROLLBACK simply drops the overlay.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..connectors.spi import WritableConnector, WriteError
from ..page import Page


class TransactionCatalog(WritableConnector):
    """Overlay view of `base` plus staged writes."""

    def __init__(self, base):
        self.base = base
        self.name = getattr(base, "name", "txn")
        # staged state: table -> Page (full replacement) | None (dropped)
        self._staged: Dict[str, Optional[Page]] = {}
        self._created: List[str] = []
        # BASE tables dropped in this transaction (replayed as drops at
        # commit even when the name was re-created afterwards)
        self._dropped_base: set = set()

    # -- helpers --

    def _base_tables(self) -> List[str]:
        return list(self.base.table_names())

    def _staged_or_none(self, table: str) -> Optional[Page]:
        return self._staged.get(table)

    def _materialize(self, table: str) -> Page:
        """Current in-transaction content of a table (staged overlay or
        the base snapshot)."""
        if table in self._staged:
            pg = self._staged[table]
            if pg is None:
                raise WriteError(f"table {table!r} dropped in transaction")
            return pg
        return self.base.page(table)

    # -- metadata --

    def table_names(self) -> List[str]:
        names = [
            t for t in self._base_tables()
            if self._staged.get(t, "absent") is not None
        ]
        for t in self._staged:
            if self._staged[t] is not None and t not in names:
                names.append(t)
        return names

    def schema(self, table: str):
        if table in self._staged:
            pg = self._staged[table]
            if pg is None:
                raise KeyError(table)
            return {n: b.type for n, b in zip(pg.names, pg.blocks)}
        return self.base.schema(table)

    def row_count(self, table: str) -> int:
        if table in self._staged:
            return int(self._materialize(table).count)
        return self.base.row_count(table)

    def exact_row_count(self, table: str) -> int:
        if table in self._staged:
            return int(self._materialize(table).count)
        return self.base.exact_row_count(table)

    def unique_columns(self, table: str):
        if table in self._staged:
            return []
        return self.base.unique_columns(table)

    # -- reads --

    def page(self, table: str) -> Page:
        return self._materialize(table)

    def scan(self, table: str, start: int, stop: int, pad_to=None,
             columns=None, predicate=None):
        if table not in self._staged:
            return self.base.scan(
                table, start, stop, pad_to=pad_to, columns=columns,
                predicate=predicate,
            )
        from ..connectors.spi import Connector

        return Connector.scan(
            self, table, start, stop, pad_to=pad_to, columns=columns,
            predicate=predicate,
        )

    # -- writes (staged) --

    def create_table(self, table: str, schema) -> None:
        if table in self.table_names():
            raise WriteError(f"table {table} exists")
        from ..ops.union import empty_page

        self._staged[table] = empty_page(schema)
        self._created.append(table)

    def create_table_from_page(self, table: str, page: Page) -> None:
        if table in self.table_names():
            raise WriteError(f"table {table} exists")
        self._staged[table] = page
        self._created.append(table)

    def append(self, table: str, page: Page) -> None:
        from ..ops.union import concat_pages

        cur = self._materialize(table)
        self._staged[table] = (
            page if int(cur.count) == 0 else concat_pages([cur, page])
        )

    def replace(self, table: str, page: Page) -> None:
        if table not in self.table_names():
            raise WriteError(f"unknown table {table}")
        self._staged[table] = page

    def drop_table(self, table: str) -> None:
        if table not in self.table_names():
            raise WriteError(f"unknown table {table}")
        if table in self._created:
            self._created.remove(table)
            if table in self._dropped_base:
                # the name shadowed a dropped BASE table: keep the drop
                # visible in-transaction (base must not resurface)
                self._staged[table] = None
            else:
                self._staged.pop(table, None)
            return
        self._staged[table] = None
        self._dropped_base.add(table)

    # -- transaction end --

    def commit(self) -> None:
        """Replay staged state onto the base catalog: base-table drops
        first (a name may have been dropped then re-created in the same
        transaction), then creates, then replacements (table-granular,
        the reference's per-connector commit)."""
        for table in self._dropped_base:
            if table in self.base.table_names():
                self.base.drop_table(table)
        for table, pg in self._staged.items():
            if pg is None:
                continue  # drop already replayed
            if table in self._created:
                self.base.create_table_from_page(table, pg)
            else:
                self.base.replace(table, pg)
        self.rollback()  # clear staged state

    def rollback(self) -> None:
        self._staged.clear()
        self._created.clear()
        self._dropped_base.clear()
