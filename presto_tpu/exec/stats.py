"""Per-operator execution statistics — the EXPLAIN ANALYZE substrate.

Re-designed equivalent of the reference's operator stats tree
(presto-main/.../operator/OperatorStats.java, DriverStats, TaskStats rolled
into QueryStats) and ExplainAnalyzeContext
(presto-main/.../execution/ExplainAnalyzeContext.java). TPU-first
differences: the unit of accounting is a plan-node *kernel dispatch* (one
jitted XLA program) rather than a Java operator's addInput/getOutput calls,
and the memory number is the device-resident bytes of the node's output
page — the HBM footprint XLA must hold live between stages.

Wall time per node includes host sync (`block_until_ready` on the output
count), so the first call also includes XLA compile time; `calls` lets the
reader separate warm-up from steady state, and `retries` counts adaptive
capacity re-executions (the static-shape analog of the reference's page
growth, which its stats never see).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional


@dataclasses.dataclass
class NodeStats:
    calls: int = 0
    wall_s: float = 0.0
    rows_in: int = 0
    rows_out: int = 0
    retries: int = 0
    # device bytes of the node's output page. `out_bytes` is the LAST
    # call's page (the node's live footprint — the collector's
    # peak_bytes high-water sums these); multi-dispatch nodes report
    # honestly through the cumulative total and per-dispatch peak.
    out_bytes: int = 0
    out_bytes_total: int = 0  # cumulative across all dispatches
    out_bytes_peak: int = 0  # largest single dispatch
    detail: str = ""  # connector-provided annotation (e.g. file pruning)

    def line(self) -> str:
        ms = self.wall_s * 1e3
        parts = [
            f"{ms:,.1f}ms",
            f"in {self.rows_in:,} rows",
            f"out {self.rows_out:,} rows",
            f"{_fmt_bytes(self.out_bytes)}",
        ]
        if self.calls != 1:
            parts.append(f"{self.calls} calls")
            if self.out_bytes_total != self.out_bytes:
                parts.append(
                    f"Σ{_fmt_bytes(self.out_bytes_total)}"
                    f" (peak {_fmt_bytes(self.out_bytes_peak)})"
                )
        if self.retries:
            parts.append(f"{self.retries} retries")
        if self.detail:
            parts.append(self.detail)
        return "[" + ", ".join(parts) + "]"


def _fmt_bytes(n: int) -> str:
    for unit in ("B", "kB", "MB", "GB"):
        if abs(n) < 1024 or unit == "GB":
            return f"{n:,.1f}{unit}" if unit != "B" else f"{n}B"
        n /= 1024
    return f"{n}B"


def page_device_bytes(page) -> int:
    """Device-resident bytes of a Page's blocks (data + validity masks)."""
    total = 0
    for b in page.blocks:
        total += b.data.size * b.data.dtype.itemsize
        if b.valid is not None:
            total += b.valid.size * b.valid.dtype.itemsize
    return total


class StatsCollector:
    """Collects per-node stats keyed by plan-node identity (two structurally
    equal nodes at different tree positions stay distinct).

    Row counts are collected LAZILY by default: `record` accepts device
    int32 scalars (or lists of them) for rows_in/rows_out and parks them
    unresolved — reading a device scalar is a blocking host sync, and one
    per plan node was the dominant term in on-chip SQL wall time
    (TPU_STATUS §4b: ~5 syncs ≈ 2.5 s around a 14 ms aggregation).
    `resolve()` drains them in one batch at query end, which is when the
    EXPLAIN ANALYZE renderer needs integers anyway. Pass
    `sync_counts=True` to restore the old per-node blocking reads (then
    per-node wall time includes kernel completion, not just dispatch)."""

    def __init__(self, sync_counts: bool = False):
        self.by_node: Dict[int, NodeStats] = {}
        self.peak_bytes: int = 0  # high-water of summed live output bytes
        self.sync_counts = sync_counts
        self._pending: list = []  # (NodeStats, rows_in, rows_out) scalars

    def stats_for(self, node) -> NodeStats:
        s = self.by_node.get(id(node))
        if s is None:
            s = NodeStats()
            self.by_node[id(node)] = s
        return s

    @staticmethod
    def _count(x) -> int:
        if isinstance(x, (list, tuple)):
            return sum(int(v) for v in x)
        return int(x)

    def record(self, node, wall_s: float, rows_in, rows_out,
               out_bytes: int, retries: int = 0) -> None:
        s = self.stats_for(node)
        s.calls += 1
        s.wall_s += wall_s
        s.retries += retries
        s.out_bytes = out_bytes
        s.out_bytes_total += out_bytes
        s.out_bytes_peak = max(s.out_bytes_peak, out_bytes)
        if self.sync_counts:
            s.rows_in += self._count(rows_in)
            s.rows_out += self._count(rows_out)
        else:
            # keep the device scalars; resolved once at query end
            self._pending.append((s, rows_in, rows_out))
        live = sum(st.out_bytes for st in self.by_node.values())
        self.peak_bytes = max(self.peak_bytes, live)

    def resolve(self) -> None:
        """Fold all parked device row-count scalars into the integer
        stats — ONE sync point at query end instead of one per node."""
        pending, self._pending = self._pending, []
        for s, rows_in, rows_out in pending:
            s.rows_in += self._count(rows_in)
            s.rows_out += self._count(rows_out)
        from ..obs.export import export_node_stats

        export_node_stats(self.by_node)

    def lookup(self, node) -> Optional[NodeStats]:
        return self.by_node.get(id(node))

    def total_wall_s(self) -> float:
        return sum(s.wall_s for s in self.by_node.values())


def kernel_breaker_snapshot() -> Dict[str, dict]:
    """State of every kernel circuit breaker (exec/breaker.py) — part of
    the stats surface so EXPLAIN ANALYZE and operators can report that a
    kernel path is degraded, not silently slower."""
    from .breaker import BREAKERS

    return BREAKERS.snapshot()


def kernel_breaker_lines() -> List[str]:
    """Formatted one-per-breaker report lines for non-closed breakers."""
    lines = []
    for name, snap in sorted(kernel_breaker_snapshot().items()):
        if snap["state"] == "closed" and not snap["total_failures"]:
            continue
        parts = [f"breaker {name}: {snap['state']}"]
        if snap["total_failures"]:
            parts.append(f"{snap['total_failures']} failures")
        if snap.get("retry_in_s") is not None:
            parts.append(f"retry in {snap['retry_in_s']:.0f}s")
        if snap["last_error"]:
            parts.append(snap["last_error"].splitlines()[0][:80])
        lines.append(", ".join(parts))
    return lines
