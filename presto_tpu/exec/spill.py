"""Host-offload ("spill") execution for sort, window, and final aggregation.

Re-designed equivalent of the reference's spill-to-disk machinery:
OrderByOperator spill + MergeHashSort, SpillableHashAggregationBuilder
(operator/aggregation/builder/SpillableHashAggregationBuilder.java:209
`spillToDisk`), GenericPartitioningSpiller (spiller/, 18 files), and the
revocable-memory scheduler (execution/MemoryRevokingScheduler.java:46).

TPU-first redesign — device memory is the scarce resource and host RAM is
the spill target (SURVEY.md §5 "long-context analog"), and the heavy
compute stays on device:

* External sort = RANGE-PARTITIONED, not run-merge: offload the input to
  host, choose first-key value boundaries from a sample (the distributed
  sort's range partitioning turned inward), then upload one key-range at a
  time and fully sort it on device with ALL keys. Chunks come back in
  range order, so no k-way merge loop runs on the host — every comparison
  happens in a device kernel. Ties on the first key stay inside one chunk
  (boundaries are values, not positions), which keeps multi-key sorts
  correct; an oversized all-tie chunk recurses on the remaining keys.
* Aggregation spill = hash-partitioned partial states: when the merged
  group state outgrows the budget, partial-aggregate pages are partitioned
  by group-key hash onto the host (GenericPartitioningSpiller's layout);
  each partition holds a disjoint set of groups, so final aggregation
  runs per-partition on device and results concatenate.
* Window spill = partition-chunked execution: rows are hash-bucketed on
  the PARTITION BY keys (a window function never looks across partitions),
  each bucket runs the normal device window kernel.

Offloaded bytes live in numpy arrays (HostTable); device uploads are
budget-sized and accounted in the caller's MemoryPool; sorted/processed
chunks download to host immediately so the device never holds more than
its share. Results assemble into a HOST-backed Page (numpy blocks) —
downstream Output only selects/renames blocks and row materialization
reads numpy directly, so a beyond-HBM result never re-uploads.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..ops.sort import SortKey, asc_normalized_scalar_key, sort_page
from ..page import Block, Page, round_capacity
from .stats import page_device_bytes


def to_host_page(page: Page) -> Page:
    """Download a device page's live rows into numpy-backed blocks."""
    n = int(page.count)
    blocks = []
    for b in page.blocks:
        data = np.asarray(b.data[:n])
        valid = None if b.valid is None else np.asarray(b.valid[:n])
        blocks.append(Block(data, b.type, valid, b.dict_id))
    return Page(tuple(blocks), page.names, n)


def host_concat_pages(pages: Sequence[Page]) -> Page:
    """Concatenate host-backed pages column-wise into one host Page."""
    from ..ops.union import unify_block_dictionaries

    total = sum(int(p.count) for p in pages)
    first = pages[0]
    blocks = []
    for i in range(len(first.blocks)):
        bl, did = unify_block_dictionaries([p.blocks[i] for p in pages])
        any_valid = any(b.valid is not None for b in bl)
        datas, valids = [], []
        for p, b in zip(pages, bl):
            n = int(p.count)
            datas.append(np.asarray(b.data[:n]))
            if any_valid:
                valids.append(
                    np.asarray(b.valid[:n])
                    if b.valid is not None
                    else np.ones((n,), np.bool_)
                )
        data = (
            np.concatenate(datas)
            if datas
            else np.empty((0,), np.asarray(first.blocks[i].data).dtype)
        )
        valid = np.concatenate(valids) if any_valid else None
        blocks.append(Block(data, bl[0].type, valid, did))
    return Page(tuple(blocks), first.names, total)


def _default_host_spill_bytes() -> int:
    """Host-RAM bytes one offloaded store may hold before migrating to
    the disk spill tier (exec/spillspace.py). 0 forces everything to
    disk (tests / hosts with no RAM headroom)."""
    import os

    return int(os.environ.get("PRESTO_TPU_HOST_SPILL_BYTES", str(64 << 20)))


def _host_table_concat(tables):
    """Concatenate HostTable-shaped chunks on the host, unifying
    per-chunk string dictionaries (the numpy mirror of
    HostTable.append_page)."""
    from .stream import HostTable

    first = tables[0]
    out = HostTable(
        first.names,
        first.types,
        first.dict_ids,
        [c.copy() for c in first.columns],
        [None if v is None else v.copy() for v in first.valids],
    )
    for t in tables[1:]:
        out.append_host(t)
    return out


class SpilledRows:
    """Offloaded rows: host-RAM store (HostTable) that migrates to the
    CRC-checked disk tier (spillspace.DiskRows) once it outgrows
    PRESTO_TPU_HOST_SPILL_BYTES — the spill-file analog, now with an
    actual spill file underneath."""

    def __init__(self, host=None, space=None, tag: str = "spill"):
        self._host = host  # exec.stream.HostTable
        self._space = space  # spillspace.QuerySpillSpace (None = host-only)
        self._tag = tag
        self._disk = None  # spillspace.DiskRows once migrated
        self._host_limit = _default_host_spill_bytes()
        if host is not None:
            self._maybe_migrate()

    def append(self, page: Page) -> None:
        from .stream import HostTable

        chunk = HostTable.from_pages([page])
        if self._disk is not None:
            self._disk.append_chunk(
                chunk.columns, chunk.valids, chunk.dict_ids, chunk.num_rows
            )
            return
        if self._host is None:
            self._host = chunk
        else:
            self._host.append_host(chunk)
        self._maybe_migrate()

    def _maybe_migrate(self) -> None:
        """Host store crossed its RAM ceiling: stream it into a disk
        record store and drop the RAM copy (the second rung of the
        degradation ladder: device -> host -> disk)."""
        if self._space is None or self._host is None:
            return
        h = self._host
        if h.num_rows * max(h.row_bytes, 1) <= self._host_limit:
            return
        from .spillspace import DiskRows

        self._disk = DiskRows(self._space, self._tag, h.names, h.types)
        self._disk.append_chunk(h.columns, h.valids, h.dict_ids, h.num_rows)
        self._host = None

    @property
    def host(self):
        return self._host

    @property
    def on_disk(self) -> bool:
        return self._disk is not None

    @property
    def num_rows(self) -> int:
        if self._disk is not None:
            return self._disk.num_rows
        return 0 if self._host is None else self._host.num_rows

    @property
    def row_bytes(self) -> int:
        if self._disk is not None:
            return max(self._disk.row_bytes, 1)
        return 0 if self._host is None else max(self._host.row_bytes, 1)

    def iter_host_chunks(self):
        """HostTable chunks of the whole store (one for the RAM tier, one
        per CRC-verified record for the disk tier)."""
        from .stream import HostTable

        if self._disk is not None:
            for cols, vals, dict_ids, _rows in self._disk.iter_chunks():
                yield HostTable(
                    self._disk.names, self._disk.types, dict_ids,
                    list(cols), list(vals),
                )
        elif self._host is not None:
            yield self._host

    def _gather_host(self, indices: np.ndarray):
        """HostTable of the rows at `indices` (in `indices` order)."""
        if self._disk is None:
            h = self._host
            from .stream import HostTable

            return HostTable(
                h.names,
                h.types,
                h.dict_ids,
                [c[indices] for c in h.columns],
                [None if v is None else v[indices] for v in h.valids],
            )
        # disk tier: one sequential pass, gathering each record's share
        # in ascending order, then restore the caller's order
        order = np.argsort(indices, kind="stable")
        sorted_idx = np.asarray(indices)[order]
        chunks = []
        off = 0
        pos = 0
        from .stream import HostTable

        for cols, vals, dict_ids, rows in self._disk.iter_chunks():
            hi = np.searchsorted(sorted_idx, off + rows, side="left")
            if hi > pos:
                local = sorted_idx[pos:hi] - off
                chunks.append(
                    HostTable(
                        self._disk.names, self._disk.types, dict_ids,
                        [c[local] for c in cols],
                        [None if v is None else v[local] for v in vals],
                    )
                )
                pos = hi
            off += rows
            if pos == len(sorted_idx):
                break
        if not chunks:
            # empty selection: 0-row gather of the first record keeps the
            # true dtypes/dictionaries (a schema-correct empty table)
            cols, vals, dict_ids, _rows = self._disk.read_chunk(0)
            return HostTable(
                self._disk.names, self._disk.types, dict_ids,
                [c[:0] for c in cols],
                [None if v is None else v[:0] for v in vals],
            )
        ht = _host_table_concat(chunks)
        inverse = np.empty(len(order), np.int64)
        inverse[order] = np.arange(len(order))
        ht.columns = [c[inverse] for c in ht.columns]
        ht.valids = [None if v is None else v[inverse] for v in ht.valids]
        return ht

    def subset(self, indices: np.ndarray) -> "SpilledRows":
        if self._disk is None:
            return SpilledRows(
                self._gather_host(indices), space=self._space, tag=self._tag
            )
        # disk tier: stream the selection into a NEW record store so a
        # large subset never re-materializes in host RAM
        from .spillspace import DiskRows

        sorted_idx = np.sort(np.asarray(indices))
        sub = SpilledRows(space=self._space, tag=self._tag)
        sub._disk = DiskRows(
            self._space, self._tag, self._disk.names, self._disk.types
        )
        off = 0
        pos = 0
        for cols, vals, dict_ids, rows in self._disk.iter_chunks():
            hi = np.searchsorted(sorted_idx, off + rows, side="left")
            if hi > pos:
                local = sorted_idx[pos:hi] - off
                sub._disk.append_chunk(
                    [c[local] for c in cols],
                    [None if v is None else v[local] for v in vals],
                    dict_ids,
                    len(local),
                )
                pos = hi
            off += rows
            if pos == len(sorted_idx):
                break
        return sub

    def take_page(self, indices: np.ndarray) -> Page:
        """Gather rows by position into a device-uploadable Page."""
        h = self._gather_host(np.asarray(indices))
        n = len(indices)
        cap = round_capacity(max(n, 1))
        blocks = []
        for c, v, typ, did in zip(h.columns, h.valids, h.types, h.dict_ids):
            data = c
            if cap > n:
                pad = (cap - n,) + data.shape[1:]
                data = np.concatenate([data, np.zeros(pad, data.dtype)])
            valid = None
            if v is not None:
                valid = v
                if cap > n:
                    valid = np.concatenate(
                        [valid, np.zeros(cap - n, np.bool_)]
                    )
            blocks.append(
                Block(
                    jnp.asarray(data),
                    typ,
                    None if valid is None else jnp.asarray(valid),
                    did,
                )
            )
        return Page.from_blocks(blocks, h.names, count=n)

    def column_eval(
        self, eval_fn: Callable[[Page], jnp.ndarray], chunk_rows: int
    ) -> np.ndarray:
        """Evaluate a device function over the stored rows chunk-by-chunk,
        returning the concatenated host result (sort-key normalization,
        partition hashing)."""
        outs = []
        if self._disk is not None:
            from .stream import HostTable

            for cols, vals, dict_ids, rows in self._disk.iter_chunks():
                ht = HostTable(
                    self._disk.names, self._disk.types, dict_ids,
                    list(cols), list(vals),
                )
                # pad to the quantized capacity: records carry arbitrary
                # row counts, and one compiled kernel per distinct shape
                # would turn every pass into a compile storm
                page = ht.slice_page(0, rows, pad_to=round_capacity(rows))
                outs.append(np.asarray(eval_fn(page))[:rows])
            return np.concatenate(outs) if outs else np.empty((0,))
        n = self.num_rows
        step = max(chunk_rows, 1)
        for start in range(0, n, step):
            stop = min(start + step, n)
            page = self._host.slice_page(start, stop)
            outs.append(np.asarray(eval_fn(page))[: stop - start])
        return np.concatenate(outs) if outs else np.empty((0,))


def choose_boundaries(
    norm: np.ndarray, num_chunks: int, sample: int = 1 << 20
) -> np.ndarray:
    """Pick <= num_chunks-1 first-key VALUES splitting `norm` into roughly
    equal chunks. Value (not position) boundaries keep equal keys in one
    chunk — required for multi-key correctness."""
    if num_chunks <= 1 or len(norm) == 0:
        return np.empty((0,), norm.dtype)
    if len(norm) > sample:
        idx = np.linspace(0, len(norm) - 1, sample).astype(np.int64)
        s = np.sort(norm[idx])
    else:
        s = np.sort(norm)
    qs = [s[int(len(s) * k / num_chunks)] for k in range(1, num_chunks)]
    return np.unique(np.asarray(qs, norm.dtype))


def external_sort_chunks(
    spilled: SpilledRows,
    keys: Sequence[SortKey],
    chunk_rows: int,
    pool,
) -> List[Page]:
    """Sort spilled rows: range-partition on the first key, device-sort
    each range with ALL keys, download, return host chunks in global
    order. Device residency per chunk is reserved against `pool`."""
    first = keys[0]
    # exact: equal norm == equal first key (scalar keys). Long-decimal
    # lanes use a monotone float64 approximation (hi*2^32 + lo): correct
    # for range BOUNDARIES, but its ties are not key ties — tie chunks
    # then sort with the FULL key list instead of recursing on the rest.
    norm_exact = True

    def eval_norm(page: Page) -> jnp.ndarray:
        nonlocal norm_exact
        from ..expr.compiler import evaluate

        v = evaluate(first.expr, page)
        if isinstance(v.type, T.VarcharType):
            from ..expr.functions import require_sorted_dict

            require_sorted_dict(v, "ORDER BY")
        norm = asc_normalized_scalar_key(v.data, first.ascending)
        if norm is None:
            norm_exact = False
            approx = (
                v.data[:, 0].astype(jnp.float64) * float(1 << 32)
                + v.data[:, 1].astype(jnp.float64)
            )
            norm = approx if first.ascending else -approx
        return norm

    def eval_nulls(page: Page) -> jnp.ndarray:
        from ..expr.compiler import evaluate

        v = evaluate(first.expr, page)
        if v.valid is None:
            return jnp.ones((page.capacity,), jnp.bool_)
        return v.valid

    n = spilled.num_rows
    # float norms stay float (truncation would overflow large doubles);
    # range partitioning only needs a consistent total order
    norm = spilled.column_eval(eval_norm, chunk_rows)
    valid = spilled.column_eval(eval_nulls, chunk_rows).astype(np.bool_)
    has_nulls = not valid.all()
    null_idx = np.nonzero(~valid)[0] if has_nulls else np.empty(0, np.int64)
    live_idx = np.nonzero(valid)[0] if has_nulls else np.arange(n)

    chunks: List[Page] = []

    def device_sort(indices: np.ndarray, sub_keys) -> None:
        page = spilled.take_page(indices)
        nb = page_device_bytes(page)
        pool.reserve(nb, "external sort chunk")
        try:
            chunks.append(to_host_page(sort_page(page, sub_keys)))
        finally:
            pool.free(nb)

    def emit(indices: np.ndarray, sub_keys) -> None:
        if len(indices) == 0:
            return
        if len(indices) <= max(chunk_rows, 1):
            device_sort(indices, sub_keys)
            return
        sub_norm = norm[indices]
        uniq = np.unique(sub_norm)
        if len(uniq) > 1:
            bounds = choose_boundaries(
                sub_norm, -(-len(indices) // max(chunk_rows, 1))
            )
            part = np.searchsorted(bounds, sub_norm, side="right")
            sizes = np.bincount(part, minlength=len(bounds) + 1)
            if sizes.max() == len(indices):
                # quantile boundaries made no progress (one dominant value
                # swallowed every cut): split at the middle DISTINCT value,
                # which is strictly inside the range — guaranteed progress
                mid = uniq[len(uniq) // 2]
                emit(indices[sub_norm < mid], sub_keys)
                emit(indices[sub_norm >= mid], sub_keys)
                return
            for p in range(len(bounds) + 1):
                sel = indices[part == p]
                if len(sel) <= max(chunk_rows, 1) or len(
                    np.unique(norm[sel])
                ) > 1:
                    emit(sel, sub_keys)
                else:
                    emit_ties(sel, sub_keys)
            return
        emit_ties(indices, sub_keys)

    def emit_ties(indices: np.ndarray, sub_keys) -> None:
        """All first-key values equal: order falls to the remaining keys;
        with none, any order is valid — emit budget-sized slices. With an
        approximate norm, equal norm does NOT mean equal key: sort the
        whole tie chunk with every key (the pool bounds the upload)."""
        if not norm_exact and sub_keys is keys:
            device_sort(indices, sub_keys)
            return
        rest = list(sub_keys)[1:]
        if rest:
            chunks.extend(
                external_sort_chunks(
                    spilled.subset(indices), rest, chunk_rows, pool
                )
            )
            return
        step = max(chunk_rows, 1)
        for s in range(0, len(indices), step):
            page = spilled.take_page(indices[s : s + step])
            nb = page_device_bytes(page)
            pool.reserve(nb, "external sort tie slice")
            try:
                chunks.append(to_host_page(page))
            finally:
                pool.free(nb)

    # null first-key rows are all EQUAL on the first key: their order is
    # decided by the remaining keys (emit_ties), never by the garbage norm
    # values sitting in invalid slots
    if has_nulls and first.effective_nulls_first:
        emit_ties(null_idx, keys)
        emit(live_idx, keys)
    elif has_nulls:
        emit(live_idx, keys)
        emit_ties(null_idx, keys)
    else:
        emit(live_idx, keys)
    return chunks


def hash_partition_indices(
    spilled: SpilledRows,
    key_exprs,
    num_parts: int,
    chunk_rows: int,
    salt: int = 0,
    value_safe: bool = False,
) -> List[np.ndarray]:
    """Partition spilled row indices by device-computed key hash (the
    GenericPartitioningSpiller layout): rows with equal keys land in the
    same partition, so per-partition processing is complete. `salt`
    shifts the hash so recursive re-partitioning uses fresh bits.

    `value_safe=True` hashes varchar keys by dictionary VALUE
    (ops/hashing.hash_rows_values) so the two sides of a join partition
    identically even when their dictionaries differ — required whenever
    build and probe partitions must co-locate equal keys. Single-table
    partitioning (window buckets, aggregate finalize) can keep the
    cheaper code hash."""
    from ..expr.compiler import evaluate
    from ..ops.hashing import hash_rows, hash_rows_values

    def eval_hash(page: Page) -> jnp.ndarray:
        keys = [evaluate(e, page) for e in key_exprs]
        h = hash_rows_values(keys) if value_safe else hash_rows(keys)
        return (h >> np.uint64(salt)).astype(jnp.uint64)

    h = spilled.column_eval(eval_hash, chunk_rows).astype(np.uint64)
    part = (h % np.uint64(num_parts)).astype(np.int64)
    return [np.nonzero(part == p)[0] for p in range(num_parts)]
