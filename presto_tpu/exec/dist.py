"""Distributed plan executor: fragmented plans over the device mesh.

Re-designed equivalent of the reference's distributed execution stack —
SqlQueryScheduler wiring stages to remote tasks (execution/scheduler/
SqlQueryScheduler.java:112), exchange producers/consumers (execution/buffer/,
operator/ExchangeClient.java) — collapsed TPU-first:

* A "stage" is a shard_map'd SPMD program over the worker mesh axis; every
  worker runs the same static-shape kernel on its shard of each Page.
* Exchanges are collectives: `repartition` = shuffle_write + lax.all_to_all
  (rides ICI), `gather`/`replicate` = device-global compaction (XLA inserts
  the all_gathers) — no serde, no HTTP, pages never leave HBM.
* The host drives adaptive capacity retry BETWEEN stages using per-shard
  live counts/overflow scalars — the static-shape replacement for the
  reference's grow-as-you-go pages and output-buffer backpressure.

The executor walks ONE physical tree (plan/fragment.py) and keeps every
subtree either sharded (SPage) or single/replicated (plain Page). All
relational kernels are the same ones the single-node Executor runs — a
sharded stage is literally the local kernel wrapped in shard_map.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .. import types as T
from ..expr import ir
from ..ops.aggregate import (
    apply_avg_post,
    global_aggregate,
    grouped_aggregate_sorted,
)
from ..ops.filter import compact, filter_page
from ..ops.join import build, join_expand, join_n1
from ..ops.sort import distinct_page, limit_page, top_n
from ..expr.compiler import project_page
from ..page import Block, Page, round_capacity
from ..parallel.exchange import exchange_by_hash
from ..parallel.mesh import (
    WORKER_AXIS,
    page_from_arrays,
    page_schema,
    page_to_arrays,
    shard_rows,
)
from ..plan import nodes as N
from ..plan.fragment import AggFinalize, Exchange
from .executor import ExecutionError, Executor


def _shard_map(step, mesh, in_specs, out_specs):
    """shard_map across jax versions: jax>=0.8 exposes jax.shard_map with
    check_vma; older releases only have the experimental home with
    check_rep (same benchmark/micro.py compat shim)."""
    try:
        from jax import shard_map as _sm  # jax >= 0.8
    except ImportError:
        from jax.experimental.shard_map import shard_map as _sm
    try:
        return _sm(
            step, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    except TypeError:
        return _sm(
            step, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False,
        )


@dataclasses.dataclass
class SPage:
    """Host handle to a mesh-sharded page: global arrays whose leading dim is
    n_shards * shard_capacity (shard i owns the contiguous chunk
    [i*cap, (i+1)*cap)), plus per-shard live counts. The device-resident
    analog of a stage's partitioned output buffers."""

    leaves: Tuple[jax.Array, ...]
    schema: tuple  # parallel.mesh.Schema
    counts: jax.Array  # (n_shards,) int32
    n_shards: int

    @property
    def shard_capacity(self) -> int:
        return self.leaves[0].shape[0] // self.n_shards

    def max_count(self) -> int:
        return int(jnp.max(self.counts))

    def total_count(self) -> int:
        return int(jnp.sum(self.counts))


class DistributedExecutor:
    """Executes a fragmented plan over `mesh`'s worker axis. Single/\
replicated subtrees delegate to the single-node Executor."""

    def __init__(self, catalog, mesh, axis: str = WORKER_AXIS,
                 collector=None, exchange_budget: Optional[int] = None):
        self.catalog = catalog
        self.mesh = mesh
        self.axis = axis
        self.n = mesh.shape[axis]
        self.local = Executor(catalog, collector=collector)
        # estimate caches in the delegate key on mesh width (_est_env):
        # per-shard sizing derived at one width must not serve another
        self.local.mesh_n = self.n
        self._steps: Dict = {}
        self.collector = collector
        # per-shard byte budget for exchanged join intermediates: when an
        # exchange+join would materialize more than this, the hash space
        # is split into buckets processed one at a time (SURVEY §7
        # chunked ICI exchange; reference OutputBufferMemoryManager's
        # backpressure role). None = materialize whole intermediates.
        self.exchange_budget = exchange_budget
        self.exchange_events: List[dict] = []
        # dynamic filters shared with the local delegate: sharded joins
        # publish, and scans (which run through local.exec_node before
        # sharding) consume (exec/dynfilter.py)
        self.dyn_ctx = self.local.dyn_ctx

    # -- public --

    def run(self, root: N.PlanNode) -> Page:
        self.dyn_ctx.reset()  # filters are per-query state
        # per-query subtree memo: a node instance executes at most once
        # (the grouped-join probe may walk children the fallback path
        # revisits; without the memo that would double-execute stages)
        self._node_memo: Dict[int, object] = {}
        try:
            out = self._run(root)
        finally:
            self._node_memo = {}
        if isinstance(out, SPage):  # fragmenter gathers, but be safe
            out = self.to_single(out)
        return out

    # -- sharded step machinery --

    def _compile_step(self, cache_key, make_local, spages: Sequence[SPage],
                      rep_pages: Sequence[Page], n_extra: int):
        """Compile (or fetch) a shard_map'd stage.

        make_local(*local_pages, *rep_pages) -> Page | (Page, *extra_scalars).
        Returns (compiled_fn, out_schema). compiled_fn(leaves_tuples,
        counts_tuple, rep_pages) -> (out_leaves, out_counts, extra_vectors).
        """
        in_schemas = [sp.schema for sp in spages]
        rep_key = tuple((page_schema(rp), rp.capacity) for rp in rep_pages)
        key = (
            cache_key,
            tuple(in_schemas),
            tuple(sp.shard_capacity for sp in spages),
            rep_key,
            n_extra,
        )
        hit = self._steps.get(key)
        if hit is not None:
            return hit

        schema_box = {}

        def step(leaves_tuples, counts, reps):
            locals_ = [
                page_from_arrays(lv, sch, cnt[0])
                for lv, sch, cnt in zip(leaves_tuples, in_schemas, counts)
            ]
            out = make_local(*locals_, *reps)
            extras = ()
            if isinstance(out, tuple):
                out, *extras = out
            schema_box["out"] = page_schema(out)
            return (
                page_to_arrays(out),
                out.count.reshape(1),
                tuple(jnp.asarray(e).reshape(1) for e in extras),
            )

        smapped = _shard_map(
            step,
            mesh=self.mesh,
            in_specs=(P(self.axis), P(self.axis), P()),
            out_specs=P(self.axis),
        )
        fn = jax.jit(smapped)

        # one abstract trace to learn the output schema without running
        # (global shapes — shard_map needs the mesh context for collectives)
        leaf_structs = tuple(
            tuple(
                jax.ShapeDtypeStruct(l.shape, l.dtype) for l in sp.leaves
            )
            for sp in spages
        )
        count_structs = tuple(
            jax.ShapeDtypeStruct((self.n,), jnp.int32) for _ in in_schemas
        )
        jax.eval_shape(fn, leaf_structs, count_structs, tuple(rep_pages))
        out_schema = schema_box["out"]

        self._steps[key] = (fn, out_schema)
        return fn, out_schema

    def _apply(self, cache_key, make_local, spages: Sequence[SPage],
               rep_pages: Sequence[Page] = (), n_extra: int = 0):
        """Run a local kernel as one SPMD stage over the mesh.

        Returns (SPage, extra_vectors) where each extra is an (n_shards,)
        array of per-shard scalars (overflow counts etc.)."""
        fn, out_schema = self._compile_step(
            cache_key, make_local, spages, rep_pages, n_extra
        )
        out_leaves, out_counts, extras = fn(
            tuple(sp.leaves for sp in spages),
            tuple(sp.counts for sp in spages),
            tuple(rep_pages),
        )
        sp = SPage(tuple(out_leaves), out_schema, out_counts, self.n)
        return sp, tuple(extras)

    # -- SPage <-> Page --

    def from_page(self, page: Page) -> SPage:
        """Contiguous row shards (leaf split assignment)."""
        padded, counts = shard_rows(page, self.n)
        return SPage(
            page_to_arrays(padded), page_schema(padded), counts, self.n
        )

    def to_single(self, sp: SPage) -> Page:
        """Collect all shards' live rows into one compacted Page (the root
        stage output buffer; XLA inserts the cross-device gathers)."""
        cap = sp.shard_capacity
        key = ("to_single", sp.schema, cap, self.n)
        fn = self._steps.get(key)
        if fn is None:

            def collect(leaves, counts):
                # count = full capacity: every position participates, and the
                # occupancy mask alone decides liveness (compact intersects
                # with live_mask, so a smaller count would drop real rows)
                page = page_from_arrays(
                    leaves, sp.schema, self.n * cap
                )
                occ = (
                    jnp.arange(cap, dtype=jnp.int32)[None, :] < counts[:, None]
                ).reshape(-1)
                return compact(page, occ)

            fn = jax.jit(collect)
            self._steps[key] = fn
        out = fn(sp.leaves, sp.counts)
        return self.local._shrink(out)

    def _shrink_sp(self, sp: SPage) -> SPage:
        """Slice every shard down to the live-count bucket (bounded
        recompilation, like Executor._shrink but uniform across shards)."""
        cap = sp.shard_capacity
        new_cap = round_capacity(max(sp.max_count(), 1))
        if new_cap >= cap:
            return sp
        key = ("shrink", sp.schema, cap, new_cap, self.n)
        fn = self._steps.get(key)
        if fn is None:

            def shrink(leaves):
                return tuple(
                    l.reshape((self.n, cap) + l.shape[1:])[:, :new_cap]
                    .reshape((self.n * new_cap,) + l.shape[1:])
                    for l in leaves
                )

            fn = jax.jit(shrink)
            self._steps[key] = fn
        return SPage(fn(sp.leaves), sp.schema, sp.counts, self.n)

    # -- dispatch --

    def _run(self, node: N.PlanNode):
        memo = getattr(self, "_node_memo", None)
        if memo is not None and id(node) in memo:
            return memo[id(node)]
        out = self._run_timed(node)
        if memo is not None:
            memo[id(node)] = out
        return out

    def _run_timed(self, node: N.PlanNode):
        if self.collector is None:
            return self._run_inner(node)
        import time

        from .stats import page_device_bytes

        t0 = time.perf_counter()
        out = self._run_inner(node)
        if isinstance(out, SPage):
            rows = out.total_count()  # blocks until shards finish
            nbytes = sum(l.size * l.dtype.itemsize for l in out.leaves)
        else:
            rows = int(out.count)
            nbytes = page_device_bytes(out)
        wall = time.perf_counter() - t0
        # child time is recorded by the recursive call; subtract it so each
        # node's number is self time (the single-node path measures the same
        # way because exec_node receives materialized inputs)
        child_wall = sum(
            (self.collector.lookup(c) or type("S", (), {"wall_s": 0})).wall_s
            for c in node.children
        )
        self.collector.record(
            node, max(wall - child_wall, 0.0), 0, rows, nbytes
        )
        return out

    def _run_inner(self, node: N.PlanNode):
        m = getattr(self, f"_d_{type(node).__name__.lower()}", None)
        if m is not None:
            return m(node)
        # nodes without a distributed handler run single-node
        pages = []
        for c in node.children:
            v = self._run(c)
            if isinstance(v, SPage):
                raise ExecutionError(
                    f"{type(node).__name__} got sharded input but has no "
                    "distributed handler (fragmenter should have gathered)"
                )
            pages.append(v)
        return self.local.exec_node(node, *pages)

    # -- exchanges --

    def _d_exchange(self, node: Exchange):
        child = self._run(node.child)
        if node.kind in ("gather", "replicate"):
            return self.to_single(child) if isinstance(child, SPage) else child
        if node.kind == "repartition":
            if not isinstance(child, SPage):
                return child  # single data is trivially co-located
            return self._repartition(child, node.keys)
        raise ExecutionError(f"unknown exchange kind {node.kind!r}")

    def _repartition(self, sp: SPage, keys) -> SPage:
        import time

        cap = sp.shard_capacity
        n = self.n
        axis = self.axis

        def local(p: Page):
            # part_capacity = sender shard capacity -> overflow-free by
            # construction (a sender cannot emit more rows than it holds)
            recv, dropped = exchange_by_hash(p, keys, axis, n, cap)
            return recv, dropped

        t0 = time.perf_counter()
        out, (dropped,) = self._apply(
            ("repartition", tuple(keys)), local, [sp], n_extra=1
        )
        total_dropped = int(jnp.sum(dropped))  # host sync: collective done
        self.exchange_events.append({
            "kind": "repartition",
            "shards": n,
            "rows": out.total_count(),
            "collective_ms": round((time.perf_counter() - t0) * 1e3, 3),
        })
        if total_dropped != 0:  # cannot happen; fail loudly if it does
            raise ExecutionError("exchange dropped rows")
        return self._shrink_sp(out)

    # -- leaves --

    def _d_tablescan(self, node: N.TableScan):
        page = self.local.exec_node(node)  # applies apply_mask entries
        if node.dynamic_filters:
            # ALSO apply the hint-only entries: the SPMD Filter stages
            # above run pre-compiled shard_map kernels that cannot see
            # runtime filters, so the scan is this path's prune point
            page = self.local._apply_scan_masks(node, page, hint_entries=True)
        return self.from_page(page)

    # -- dynamic filters over sharded build sides --

    def _publish_dyn_filters_any(self, node, side) -> None:
        """Publish build-side filters from either a plain Page or an
        SPage (global leaves with per-shard live prefixes)."""
        from ..expr.compiler import evaluate as _ev
        from .breaker import BREAKERS
        from .dynfilter import derive_filter

        if isinstance(side, Page):
            self.local._publish_dynamic_filters(node, side)
            return
        if not self.local._dyn_enabled() or not self.local._dyn_worthwhile(
            node
        ):
            return
        sp: SPage = side
        cap = sp.shard_capacity
        page = page_from_arrays(
            sp.leaves, sp.schema, jnp.asarray(self.n * cap, jnp.int32)
        )
        # per-shard live prefix (NOT a global prefix): shard i's live rows
        # occupy [i*cap, i*cap + counts[i])
        occ = (
            jnp.arange(cap, dtype=jnp.int32)[None, :] < sp.counts[:, None]
        ).reshape(-1)
        keys = (
            node.right_keys if isinstance(node, N.Join) else node.source_keys
        )
        for fid, i, _c in node.dynamic_filters:
            try:
                val = _ev(keys[i], page)
                df = derive_filter(val, occ)
            except Exception as exc:  # noqa: BLE001 — degrade, don't fail
                BREAKERS.record_failure("dynamic_filter", repr(exc))
                return
            if df is not None:
                BREAKERS.record_success("dynamic_filter")
                self.dyn_ctx.publish(fid, df)

    # -- stateless row ops --

    def _unary(self, node, key, local_fn, shrink: bool = False):
        """Common unary-node shape: sharded input -> one SPMD stage;
        single input -> delegate to the single-node executor."""
        c = self._run(node.child)
        if not isinstance(c, SPage):
            return self.local.exec_node(node, c)
        out, _ = self._apply(key, local_fn, [c])
        return self._shrink_sp(out) if shrink else out

    def _d_unnest(self, node: N.Unnest):
        from ..ops.unnest import unnest_page

        return self._unary(
            node,
            ("unnest", node),
            lambda p: unnest_page(
                p, node.array_exprs, node.elem_channels,
                node.ordinality_channel,
            ),
            shrink=True,
        )

    def _d_sample(self, node):
        from ..ops.filter import sample_page

        axis = self.axis

        def fn(p):
            # per-shard component of the global row position: shard i's
            # rows occupy [i*capacity, i*capacity + count) — without it
            # every shard would reuse the identical positional mask
            # (systematic, not Bernoulli sampling)
            off = jax.lax.axis_index(axis).astype(jnp.uint64) * jnp.uint64(
                p.capacity
            )
            return sample_page(p, node.fraction, node.seed, off)

        return self._unary(node, ("sample", node), fn, shrink=True)

    def _d_filter(self, node: N.Filter):
        return self._unary(
            node,
            ("filter", node),
            lambda p: filter_page(p, node.predicate),
            shrink=True,
        )

    def _d_project(self, node: N.Project):
        return self._unary(
            node,
            ("project", node),
            lambda p: project_page(p, node.exprs, node.names),
        )

    # -- aggregation --

    def _d_aggregate(self, node: N.Aggregate):
        c = self._run(node.child)
        if not isinstance(c, SPage):
            return self.local.exec_node(node, c)
        if not node.group_exprs:
            out, _ = self._apply(
                ("gagg", node),
                lambda p: global_aggregate(p, node.aggs, node.mask),
                [c],
            )
            return out
        # collection aggregates (array_agg/map_agg/histogram) are not
        # decomposable, so the fragmenter always gathers them to the
        # local-executor path above (which owns the adaptive-width retry);
        # only scalar + HLL-register specs run on sharded inputs
        from ..ops.aggregate import COLLECTION_AGGS

        if any(a.func in COLLECTION_AGGS for a in node.aggs):
            raise ExecutionError(
                "collection aggregates must be gathered before the "
                "sharded aggregation path"
            )
        max_groups = round_capacity(min(max(c.max_count(), 1), 1 << 16))
        while True:
            mg = max_groups
            out, _ = self._apply(
                ("agg", node, mg),
                lambda p: grouped_aggregate_sorted(
                    p, node.group_exprs, node.group_names, node.aggs, mg,
                    node.mask,
                ),
                [c],
            )
            true_groups = out.max_count()
            if true_groups <= max_groups:
                break
            max_groups = round_capacity(true_groups)
        return self._shrink_sp(out)

    def _d_aggfinalize(self, node: AggFinalize):
        return self._unary(
            node,
            ("aggfin", node),
            lambda p: apply_avg_post(p, node.aggs, node.post),
        )

    def _d_distinct(self, node: N.Distinct):
        return self._unary(
            node,
            ("distinct", node),
            lambda p: distinct_page(p, p.capacity),
            shrink=True,
        )

    # -- joins --

    @staticmethod
    def _row_bytes(sp: "SPage") -> int:
        return sum(
            int(jnp.dtype(lf.dtype).itemsize)
            * (int(lf.shape[-1]) if lf.ndim > 2 else 1)
            for lf in sp.leaves
        )

    def _maybe_grouped_join(self, node: N.Join):
        """Grouped-execution exchange join (chunked ICI exchange): when
        repartitioning both sides would materialize more than
        exchange_budget bytes per shard, split the hash space into B
        buckets and run filter -> all_to_all -> build -> join ONE BUCKET
        at a time inside a single SPMD step each — the exchanged
        intermediate never exceeds ~1/B of the materializing path, and
        jax's async dispatch overlaps bucket b's compute with b+1's
        enqueue (the double-buffering the reference gets from paged
        OutputBuffers + ExchangeClient prefetch)."""
        if self.exchange_budget is None or node.unique_build:
            return None
        if node.kind not in ("inner", "left"):
            return None
        if not (
            isinstance(node.left, Exchange)
            and node.left.kind == "repartition"
            and isinstance(node.right, Exchange)
            and node.right.kind == "repartition"
        ):
            return None
        left = self._run(node.left.child)
        right = self._run(node.right.child)
        if not isinstance(left, SPage) or not isinstance(right, SPage):
            return None
        lcap, rcap = left.shard_capacity, right.shard_capacity
        est = self.n * (
            lcap * self._row_bytes(left) + rcap * self._row_bytes(right)
        )
        B = 1
        while B < 64 and est // B > self.exchange_budget:
            B *= 2
        if B == 1:
            return None  # fits the budget: the normal path materializes
        right_names = tuple(nm for nm, _ in node.right.fields)
        axis, n = self.axis, self.n
        # per-bucket capacities start at cap/B (hash buckets are balanced
        # in expectation); skew retries with doubled capacity on drops
        bl = max(round_capacity(-(-lcap // B)), 64)
        br = max(round_capacity(-(-rcap // B)), 64)
        out_cap = max(round_capacity(-(-lcap // B)), 64)
        parts: List[SPage] = []
        peak = 0
        from ..expr.compiler import evaluate as _ev
        from ..ops.hashing import hash_rows

        def bucket_filter(p: Page, keys, b):
            vals = [_ev(k, p) for k in keys]
            h = hash_rows(vals)
            live = jnp.arange(p.capacity) < p.count
            keep = live & (((h // n) % B) == b)
            return compact(p, keep)

        import numpy as _np

        b = 0
        while b < B:
            cbl, cbr, cout = bl, br, out_cap

            def step(l: Page, r: Page, bpage: Page, _cbl=cbl, _cbr=cbr,
                     _cout=cout) -> Page:
                # the bucket id arrives as a TRACED replicated scalar, so
                # ONE compiled step (keyed on capacities) serves every
                # bucket instead of B recompiles
                _b = bpage.blocks[0].data[0]
                lb = bucket_filter(l, node.left.keys, _b)
                rb = bucket_filter(r, node.right.keys, _b)
                lx, ldrop = exchange_by_hash(
                    lb, node.left.keys, axis, n, _cbl
                )
                rx, rdrop = exchange_by_hash(
                    rb, node.right.keys, axis, n, _cbr
                )
                out, overflow = join_expand(
                    lx,
                    build(rx, node.right_keys),
                    node.left_keys,
                    lx.names,
                    [(nm, nm) for nm in right_names],
                    out_capacity=_cout,
                    kind=node.kind,
                )
                return out, ldrop + rdrop, overflow

            bpage = Page.from_dict({"b": _np.asarray([b], _np.int32)})
            out, (dropped, overflow) = self._apply(
                (node, "gx", B, cbl, cbr, cout), step, [left, right],
                rep_pages=[bpage], n_extra=2,
            )
            if int(jnp.max(dropped)) > 0:
                bl, br = bl * 2, br * 2
                continue  # retry the same bucket with bigger exchange caps
            ov = int(jnp.max(overflow))
            if ov > 0:
                out_cap = round_capacity(out_cap + ov)
                continue
            peak = max(
                peak, n * (bl * self._row_bytes(left)
                           + br * self._row_bytes(right))
            )
            parts.append(self._shrink_sp(out))
            b += 1
        self.exchange_events.append(
            {"buckets": B, "per_shard_bytes": peak, "estimate": est}
        )
        if len(parts) == 1:
            out = parts[0]
        else:
            from ..ops.union import concat_pages

            out, _ = self._apply(
                (node, "gx-concat", B, tuple(p.shard_capacity for p in parts)),
                lambda *pages: concat_pages(pages),
                parts,
            )
            out = self._shrink_sp(out)
        if node.residual is not None:
            if node.kind != "inner":
                raise ExecutionError(
                    "residual on outer join not yet supported"
                )
            out, _ = self._apply(
                (node, "gx-resid"),
                lambda p: filter_page(p, node.residual),
                [out],
            )
            out = self._shrink_sp(out)
        return out

    def _d_join(self, node: N.Join):
        grouped = self._maybe_grouped_join(node)
        if grouped is not None:
            return grouped
        if node.dynamic_filters:
            # build side first: probe-side scans then see the filters
            right = self._run(node.right)
            self._publish_dyn_filters_any(node, right)
            left = self._run(node.left)
        else:
            left = self._run(node.left)
            right = self._run(node.right)
        if not isinstance(left, SPage):
            if isinstance(right, SPage):
                right = self.to_single(right)
            return self.local.exec_node(node, left, right)

        right_sp: Optional[SPage] = right if isinstance(right, SPage) else None
        right_names = tuple(n for n, _ in node.right.fields)

        def make_n1(l: Page, r: Page) -> Page:
            return join_n1(
                l,
                build(r, node.right_keys),
                node.left_keys,
                right_names,
                right_names,
                kind=node.kind,
            )

        if node.unique_build:
            ins, reps = self._join_inputs(left, right_sp, right)
            out, _ = self._apply((node, "n1"), make_n1, ins, reps)
            if node.residual is not None:
                if node.kind != "inner":
                    raise ExecutionError("residual on outer join not yet supported")
                out, _ = self._apply(
                    (node, "resid"),
                    lambda p: filter_page(p, node.residual),
                    [out],
                )
            return self._shrink_sp(out)

        cap = round_capacity(max(left.max_count(), 1))
        while True:
            c = cap

            def make_expand(l: Page, r: Page):
                return join_expand(
                    l,
                    build(r, node.right_keys),
                    node.left_keys,
                    l.names,
                    [(nm, nm) for nm in right_names],
                    out_capacity=c,
                    kind=node.kind,
                )

            ins, reps = self._join_inputs(left, right_sp, right)
            out, (overflow,) = self._apply(
                (node, "expand", c), make_expand, ins, reps, n_extra=1
            )
            ov = int(jnp.max(overflow))
            if ov == 0:
                break
            cap = round_capacity(cap + ov)
        if node.residual is not None:
            if node.kind != "inner":
                raise ExecutionError("residual on outer join not yet supported")
            out, _ = self._apply(
                (node, "resid2"),
                lambda p: filter_page(p, node.residual),
                [out],
            )
        return self._shrink_sp(out)

    @staticmethod
    def _join_inputs(left: SPage, right_sp: Optional[SPage], right):
        if right_sp is not None:
            return [left, right_sp], []
        return [left], [right]

    def _d_semijoin(self, node: N.SemiJoin):
        if node.dynamic_filters:
            source = self._run(node.source)
            self._publish_dyn_filters_any(node, source)
            probe = self._run(node.child)
        else:
            probe = self._run(node.child)
            source = self._run(node.source)
        if not isinstance(probe, SPage):
            if isinstance(source, SPage):
                source = self.to_single(source)
            return self.local.exec_node(node, probe, source)
        source_sp = source if isinstance(source, SPage) else None

        if node.residual is None:

            def local(p: Page, s: Page) -> Page:
                bs = build(s, node.source_keys)
                return join_n1(
                    p,
                    bs,
                    node.probe_keys,
                    [],
                    [],
                    kind="anti" if node.anti else "semi",
                )

            ins, reps = self._join_inputs(probe, source_sp, source)
            out, _ = self._apply((node, "semi"), local, ins, reps)
            return self._shrink_sp(out)

        # residual EXISTS: expand on equi keys, filter residual, keep probe
        # rows whose (per-shard) row id survived — all local to one shard
        # because the source side is replicated.
        if source_sp is not None:
            source = self.to_single(source_sp)
            source_sp = None
        rid = "$rid_d"
        rid_t = T.BIGINT
        needed = self.local._residual_channels(node.residual)
        cap = round_capacity(max(probe.max_count(), 1))
        while True:
            c = cap

            def local(p: Page, s: Page):
                p2 = self.local._with_row_id(p, rid)
                bs = build(s, node.source_keys)
                probe_out = [rid] + [nm for nm in p.names if nm in needed]
                build_out = [(nm, nm) for nm in s.names if nm in needed]
                expanded, overflow = join_expand(
                    p2,
                    bs,
                    node.probe_keys,
                    probe_out,
                    build_out,
                    out_capacity=c,
                    kind="inner",
                )
                matched = filter_page(expanded, node.residual)
                bs2 = build(matched, (ir.ColumnRef(rid, rid_t),))
                out = join_n1(
                    p2,
                    bs2,
                    (ir.ColumnRef(rid, rid_t),),
                    [],
                    [],
                    kind="anti" if node.anti else "semi",
                )
                blocks = tuple(
                    b for b, nm in zip(out.blocks, out.names) if nm != rid
                )
                names = tuple(nm for nm in out.names if nm != rid)
                return Page(blocks, names, out.count), overflow

            out, (overflow,) = self._apply(
                (node, "semiresid", c), local, [probe], [source], n_extra=1
            )
            ov = int(jnp.max(overflow))
            if ov == 0:
                break
            cap = round_capacity(cap + ov)
        return self._shrink_sp(out)

    def _d_scalarapply(self, node: N.ScalarApply):
        child = self._run(node.child)
        sub = self._run(node.subquery)
        if isinstance(sub, SPage):
            sub = self.to_single(sub)
        if not isinstance(child, SPage):
            return self.local.exec_node(node, child, sub)
        n_sub = int(sub.count)  # host-side check; the broadcast is pure
        if n_sub > 1:
            raise ExecutionError("scalar subquery returned more than one row")

        def local(p: Page, s: Page) -> Page:
            cap = p.capacity
            blocks = list(p.blocks)
            names = list(p.names)
            for b, (fname, _ftype) in zip(s.blocks, node.subquery.fields):
                if n_sub == 0:
                    data = jnp.zeros((cap,) + b.data.shape[1:], b.data.dtype)
                    valid = jnp.zeros((cap,), jnp.bool_)
                else:
                    data = jnp.broadcast_to(
                        b.data[0], (cap,) + b.data.shape[1:]
                    )
                    valid = (
                        None
                        if b.valid is None
                        else jnp.broadcast_to(b.valid[0], (cap,))
                    )
                blocks.append(Block(data, b.type, valid, b.dict_id))
                names.append(fname)
            return Page(tuple(blocks), tuple(names), p.count)

        out, _ = self._apply((node, "sapply", n_sub == 0), local, [child], [sub])
        return out

    # -- windows / ordering --

    def _d_window(self, node: N.Window):
        from ..ops.window import window_op

        return self._unary(
            node,
            ("window", node),
            lambda p: window_op(
                p, node.partition_exprs, node.order_keys, node.funcs
            ),
        )

    def _d_sort(self, node: N.Sort):
        """Distributed sort (reference admin/dist-sort.rst: per-task partial
        sort + single-node MergeOperator k-way merge). Stage 1 sorts every
        shard in parallel on the mesh; stage 2 merges on the root.

        Merge fast path (single non-null key): each row's global position is
        its in-run position plus, per other run, how many of that run's keys
        precede it (vmapped searchsorted over the sorted runs, ties broken
        by run index for stability) — one argsort over int32 ranks instead
        of re-running the full multi-pass key sort. Nullable or multi-key
        sorts fall back to sorting the gathered page."""
        import jax.numpy as jnp

        from ..expr.compiler import evaluate
        from ..ops.sort import sort_page
        from ..page import Block

        # the fragmenter plans ORDER BY as Sort(Exchange(gather, child));
        # run the gather's sharded input through the merge path instead of
        # materializing it unsorted on the root
        ch = node.child
        if isinstance(ch, Exchange) and ch.kind == "gather":
            if self.collector is not None:
                import time as _time

                t0 = _time.perf_counter()
                c = self._run(ch.child)
                below = _time.perf_counter() - t0
                sub = self.collector.lookup(ch.child)
                # keep the Exchange visible to EXPLAIN ANALYZE even though
                # the merge path absorbed it (Sort's self-time subtraction
                # reads its direct child)
                self.collector.record(
                    ch,
                    max(below - (sub.wall_s if sub else 0.0), 0.0),
                    0,
                    c.total_count() if isinstance(c, SPage) else int(c.count),
                    0,
                )
            else:
                c = self._run(ch.child)
        else:
            c = self._run(ch)
        if not isinstance(c, SPage):
            return self.local.exec_node(node, c)

        keys = node.keys
        single_key = len(keys) == 1 and not isinstance(
            keys[0].expr.type, T.VarcharType
        )
        if not single_key:
            # multi-key/varchar sorts gain nothing from per-shard sorting
            # (XLA's root sort cost is data-independent) — gather raw
            return self.local.exec_node(node, self.to_single(c))

        def local(p: Page):
            from ..ops.sort import asc_normalized_scalar_key

            s = sort_page(p, keys)
            v = evaluate(keys[0].expr, s)
            key_col = asc_normalized_scalar_key(v.data, keys[0].ascending)
            if key_col is None:  # long decimal: not merge-friendly
                has_nulls = jnp.ones((), jnp.int32)
                key_col = jnp.zeros(p.capacity, jnp.int64)
            else:
                live = s.live_mask()
                # only LIVE rows count — shard padding carries a zeroed
                # validity mask that is not a real NULL. NaN keys also
                # break searchsorted's ordering assumption: fall back.
                bad = jnp.zeros_like(live)
                if v.valid is not None:
                    bad = bad | ~v.valid
                if jnp.issubdtype(key_col.dtype, jnp.floating):
                    bad = bad | jnp.isnan(key_col)
                has_nulls = jnp.any(bad & live).astype(jnp.int32)
            kb = Block(
                key_col,
                T.DOUBLE
                if jnp.issubdtype(key_col.dtype, jnp.floating)
                else T.BIGINT,
            )
            return (
                Page(s.blocks + (kb,), s.names + ("__sortkey__",), s.count),
                has_nulls,
            )

        sorted_sp, (has_nulls,) = self._apply(
            ("dsort", keys, single_key), local, [c], n_extra=1
        )
        if single_key and int(jnp.sum(has_nulls)) == 0:
            return self._merge_sorted_runs(sorted_sp)
        page = self.to_single(sorted_sp)
        if single_key:  # drop the helper key column before the fallback
            page = Page(page.blocks[:-1], page.names[:-1], page.count)
        return self.local.exec_node(node, page)

    def _merge_sorted_runs(self, sp: SPage) -> Page:
        """Rank-merge n sorted runs whose last column is the asc-normalized
        merge key; returns the single merged Page without that column."""
        import jax.numpy as jnp

        cap = sp.shard_capacity
        n = self.n
        key = ("merge_runs", sp.schema, cap, n)
        fn = self._steps.get(key)
        if fn is None:

            def merge(leaves, counts):
                K = leaves[-1].reshape(n, cap)
                sentinel = (
                    jnp.inf
                    if jnp.issubdtype(K.dtype, jnp.floating)
                    else jnp.iinfo(K.dtype).max
                )
                pos = jnp.arange(cap, dtype=jnp.int32)[None, :]
                live = pos < counts[:, None]
                Kp = jnp.where(live, K, sentinel)
                flat = Kp.reshape(-1)
                ss_l = jax.vmap(
                    lambda a: jnp.searchsorted(a, flat, side="left")
                )(Kp)  # (n, n*cap)
                ss_r = jax.vmap(
                    lambda a: jnp.searchsorted(a, flat, side="right")
                )(Kp)
                cnt = counts.astype(jnp.int32)[:, None]
                ss_l = jnp.minimum(ss_l, cnt)
                ss_r = jnp.minimum(ss_r, cnt)
                run_of = jnp.repeat(
                    jnp.arange(n, dtype=jnp.int32), cap
                )  # (n*cap,)
                other = jnp.arange(n, dtype=jnp.int32)[:, None]
                before = jnp.where(other < run_of[None, :], ss_r, ss_l)
                contrib = jnp.where(
                    other == run_of[None, :], 0, before
                ).sum(axis=0)
                in_run = jnp.tile(pos[0], n)
                total = jnp.sum(counts).astype(jnp.int32)
                gidx = jnp.arange(n * cap, dtype=jnp.int32)
                rank = jnp.where(
                    live.reshape(-1),
                    in_run + contrib.astype(jnp.int32),
                    total + gidx,  # dead rows strictly after all live rows
                )
                perm = jnp.argsort(rank)
                # every leaf's leading dim is n*cap (SPage layout)
                merged = tuple(leaf[perm] for leaf in leaves)
                return merged, total

            fn = jax.jit(merge)
            self._steps[key] = fn
        merged, total = fn(sp.leaves, sp.counts)
        page = page_from_arrays(merged, sp.schema, total)
        # drop the __sortkey__ helper column
        page = Page(page.blocks[:-1], page.names[:-1], page.count)
        return self.local._shrink(page)

    def _d_topn(self, node: N.TopN):
        return self._unary(
            node,
            ("topn", node),
            lambda p: top_n(p, node.keys, node.count),
            shrink=True,
        )

    def _d_limit(self, node: N.Limit):
        return self._unary(
            node,
            ("limit", node),
            lambda p: limit_page(p, node.count),
            shrink=True,
        )
