"""Disk spill tier: quota-managed, CRC-checked spill files.

Re-designed equivalent of the reference's spill-space management
(spiller/FileSingleStreamSpillerFactory LocalSpillManager + the
`experimental.max-spill-per-node` / `query-max-spill-per-node` quotas,
and SpillSpaceTracker): a per-process SpillSpaceManager hands out
per-query SpillSpaces under a node-wide and a per-query byte quota, and
every byte written to disk is CRC-checked on the way back in — a corrupt
or truncated spill file fails the query with a structured error
(SpillCorruptionError), never returns wrong rows.

This is the tier BELOW exec/spill.py's host-RAM offload: SpilledRows
migrates to a DiskRows record store once its host footprint crosses
PRESTO_TPU_HOST_SPILL_BYTES. Records are column-chunk payloads (numpy
arrays + schema via pickle) framed as

    magic "PTS1" | uint64 payload length | uint32 crc32 | payload

so a torn write (crash mid-record) or bit rot is detected by length or
CRC mismatch before any row is produced.

Cleanup is guaranteed per query: QuerySpillSpace.release() unlinks every
file it created and returns its bytes to both quotas; the worker calls it
in the task's `finally` (so kills and failures clean up too), and the
streaming session calls it at `run()` end. `all_active_bytes()` sums the
live spill bytes of every manager in the process — the leak oracle the
test suite asserts is zero after every test.

Env knobs (docs/tuning.md):
* PRESTO_TPU_SPILL_DIR          spill directory (default: a per-process
                                tempdir, removed at interpreter exit)
* PRESTO_TPU_SPILL_NODE_QUOTA   max spill bytes per node/process
* PRESTO_TPU_SPILL_QUERY_QUOTA  max spill bytes per query
"""

from __future__ import annotations

import os
import pickle
import struct
import threading
import weakref
import zlib
from typing import Dict, List, Optional

_MAGIC = b"PTS1"
_HEADER = struct.Struct("<4sQI")  # magic, payload length, payload crc32

# every manager in the process, for the suite-wide leak oracle
_MANAGERS: "weakref.WeakSet[SpillSpaceManager]" = weakref.WeakSet()


class SpillError(RuntimeError):
    """Structured spill-tier failure. Fatal to the query (retrying on
    another worker would hit the same quota / the file is gone)."""


class SpillQuotaExceededError(SpillError):
    """Per-query or per-node spill quota exhausted (reference
    ExceededSpillLimitException)."""


class SpillCorruptionError(SpillError):
    """A spill file failed its CRC / framing check: the query must fail
    with this structured error, never produce wrong rows."""


def _env_bytes(name: str) -> Optional[int]:
    v = os.environ.get(name)
    return int(v) if v else None


class SpillSpaceManager:
    """Node-level spill accounting: hands out per-query spaces, enforces
    the per-node and per-query byte quotas, tracks lifetime counters."""

    def __init__(self, directory: Optional[str] = None,
                 node_quota: Optional[int] = None,
                 query_quota: Optional[int] = None):
        self._dir = directory
        self.node_quota = (
            node_quota if node_quota is not None
            else _env_bytes("PRESTO_TPU_SPILL_NODE_QUOTA")
        )
        self.query_quota = (
            query_quota if query_quota is not None
            else _env_bytes("PRESTO_TPU_SPILL_QUERY_QUOTA")
        )
        self._lock = threading.Lock()
        self.active_bytes = 0
        self.by_query: Dict[str, int] = {}
        self.total_written = 0  # lifetime bytes spilled to disk
        self.files_created = 0
        self.active_files = 0
        self.quota_rejections = 0
        _MANAGERS.add(self)

    # -- directory (lazy: importing this module must not touch disk) --

    def directory(self) -> str:
        with self._lock:
            if self._dir is None:
                import tempfile

                base = os.environ.get("PRESTO_TPU_SPILL_DIR")
                if base:
                    os.makedirs(base, exist_ok=True)
                    self._dir = tempfile.mkdtemp(prefix="spill_", dir=base)
                else:
                    self._dir = tempfile.mkdtemp(prefix="presto_tpu_spill_")
            else:
                os.makedirs(self._dir, exist_ok=True)
            return self._dir

    # -- quota ledger (called by SpillFile) --

    def _charge(self, query_id: str, nbytes: int) -> None:
        with self._lock:
            held = self.by_query.get(query_id, 0)
            if (
                self.query_quota is not None
                and held + nbytes > self.query_quota
            ):
                self.quota_rejections += 1
                raise SpillQuotaExceededError(
                    f"spill quota exceeded for query {query_id!r}: "
                    f"writing {nbytes:,}B past {held:,}B held would exceed "
                    f"the per-query quota of {self.query_quota:,}B"
                )
            if (
                self.node_quota is not None
                and self.active_bytes + nbytes > self.node_quota
            ):
                self.quota_rejections += 1
                raise SpillQuotaExceededError(
                    f"spill quota exceeded on this node: {nbytes:,}B past "
                    f"{self.active_bytes:,}B held would exceed the "
                    f"per-node quota of {self.node_quota:,}B"
                )
            self.by_query[query_id] = held + nbytes
            self.active_bytes += nbytes

    def _note_written(self, nbytes: int) -> None:
        """Lifetime spilled-bytes counter — bumped only AFTER a record
        actually reached the file, so failed writes never inflate the
        regression metric northstar/bench_gate track."""
        with self._lock:
            self.total_written += nbytes

    def _credit(self, query_id: str, nbytes: int) -> None:
        with self._lock:
            self.active_bytes = max(0, self.active_bytes - nbytes)
            left = self.by_query.get(query_id, 0) - nbytes
            if left > 0:
                self.by_query[query_id] = left
            else:
                self.by_query.pop(query_id, None)

    # -- spaces --

    def open(self, query_id: str) -> "QuerySpillSpace":
        return QuerySpillSpace(self, query_id)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "active_bytes": self.active_bytes,
                "active_files": self.active_files,
                "by_query": dict(self.by_query),
                "total_written": self.total_written,
                "files_created": self.files_created,
                "quota_rejections": self.quota_rejections,
                "node_quota": self.node_quota,
                "query_quota": self.query_quota,
            }


class QuerySpillSpace:
    """One query's (or one task's) handle on the manager: creates files,
    tracks them for guaranteed release."""

    def __init__(self, manager: SpillSpaceManager, query_id: str):
        self.manager = manager
        self.query_id = query_id
        self._files: List["SpillFile"] = []
        self._seq = 0
        self.written = 0  # lifetime bytes this space wrote

    def new_file(self, tag: str) -> "SpillFile":
        self._seq += 1
        safe_q = "".join(
            c if c.isalnum() or c in "._-" else "_" for c in self.query_id
        )
        safe_t = "".join(
            c if c.isalnum() or c in "._-" else "_" for c in tag
        )
        path = os.path.join(
            self.manager.directory(),
            f"{safe_q}.{safe_t}.{self._seq}.{id(self):x}.spill",
        )
        f = SpillFile(self, path)
        self._files.append(f)
        with self.manager._lock:
            self.manager.files_created += 1
            self.manager.active_files += 1
        return f

    def release(self) -> None:
        """Unlink every file this space created and return its quota
        bytes. Idempotent — the guaranteed-cleanup hook for query end,
        kill, and failure paths alike."""
        files, self._files = self._files, []
        for f in files:
            f.delete()

    @property
    def active_bytes(self) -> int:
        return sum(f.nbytes for f in self._files)


class SpillFile:
    """Append-only record file with per-record CRC framing."""

    def __init__(self, space: QuerySpillSpace, path: str):
        self.space = space
        self.path = path
        self._fh = open(path, "w+b")
        self._records: List[tuple] = []  # (offset, payload_len)
        self.nbytes = 0
        self._lock = threading.Lock()
        self._deleted = False

    def __len__(self) -> int:
        return len(self._records)

    def append(self, payload: bytes) -> int:
        """Write one CRC-framed record; returns its index. Charges the
        quotas BEFORE writing so an over-quota record never hits disk."""
        total = _HEADER.size + len(payload)
        self.space.manager._charge(self.space.query_id, total)
        try:
            with self._lock:
                if self._deleted:
                    raise SpillError(
                        f"spill file {self.path} used after release"
                    )
                off = self._fh.seek(0, os.SEEK_END)
                crc = zlib.crc32(payload) & 0xFFFFFFFF
                self._fh.write(_HEADER.pack(_MAGIC, len(payload), crc))
                self._fh.write(payload)
                self._fh.flush()  # records are visible to any handle
                self._records.append((off, len(payload)))
                self.nbytes += total
                self.space.written += total
            self.space.manager._note_written(total)
            return len(self._records) - 1
        except SpillError:
            self.space.manager._credit(self.space.query_id, total)
            raise
        except OSError as e:
            self.space.manager._credit(self.space.query_id, total)
            raise SpillError(
                f"spill write to {self.path} failed: {e}"
            ) from e

    def read(self, index: int) -> bytes:
        """Read + verify one record. Any framing/CRC mismatch raises
        SpillCorruptionError — the structured never-wrong-rows contract."""
        off, plen = self._records[index]
        with self._lock:
            if self._deleted:
                raise SpillError(
                    f"spill file {self.path} read after release"
                )
            self._fh.seek(off)
            header = self._fh.read(_HEADER.size)
            if len(header) != _HEADER.size:
                raise SpillCorruptionError(
                    f"spill file corrupt: {self.path} record {index} "
                    f"truncated header ({len(header)}B of {_HEADER.size}B)"
                )
            magic, length, crc = _HEADER.unpack(header)
            if magic != _MAGIC or length != plen:
                raise SpillCorruptionError(
                    f"spill file corrupt: {self.path} record {index} bad "
                    f"framing (magic={magic!r}, length {length} != {plen})"
                )
            payload = self._fh.read(plen)
        if len(payload) != plen:
            raise SpillCorruptionError(
                f"spill file corrupt: {self.path} record {index} truncated "
                f"payload ({len(payload)}B of {plen}B)"
            )
        if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
            raise SpillCorruptionError(
                f"spill file corrupt: {self.path} record {index} CRC "
                "mismatch (torn write or bit rot)"
            )
        return payload

    def delete(self) -> None:
        with self._lock:
            if self._deleted:
                return
            self._deleted = True
            nbytes = self.nbytes
            try:
                self._fh.close()
            except OSError:
                pass
            try:
                os.unlink(self.path)
            except OSError:
                pass
        self.space.manager._credit(self.space.query_id, nbytes)
        with self.space.manager._lock:
            self.space.manager.active_files -= 1


class DiskRows:
    """Disk-backed row store: a sequence of column-chunk records over one
    SpillFile (the GenericPartitioningSpiller file layout, one tier
    down from exec/spill.py's host store). Access is sequential-pass —
    the shape every external algorithm here needs."""

    # rows per record: bounds the host memory any single read touches
    MAX_RECORD_ROWS = 1 << 16

    def __init__(self, space: QuerySpillSpace, tag: str, names, types):
        self.file = space.new_file(tag)
        self.names = tuple(names)
        self.types = tuple(types)
        self.row_counts: List[int] = []
        self.num_rows = 0
        self._data_bytes = 0

    @property
    def row_bytes(self) -> int:
        if not self.num_rows:
            return 0
        return max(self._data_bytes // self.num_rows, 1)

    def append_chunk(self, columns, valids, dict_ids, rows: int) -> None:
        """Write one (columns, valids, dict_ids) chunk; splits chunks
        larger than MAX_RECORD_ROWS so no read re-materializes more."""
        if rows == 0:
            return
        step = self.MAX_RECORD_ROWS
        for start in range(0, rows, step):
            stop = min(start + step, rows)
            cols = [c[start:stop] for c in columns]
            vals = [None if v is None else v[start:stop] for v in valids]
            payload = pickle.dumps(
                (cols, vals, tuple(dict_ids)), protocol=4
            )
            self.file.append(payload)
            n = stop - start
            self.row_counts.append(n)
            self.num_rows += n
            self._data_bytes += sum(
                c.dtype.itemsize * c.size for c in cols
            ) + sum(1 for v in vals if v is not None) * n

    def read_chunk(self, index: int):
        """(columns, valids, dict_ids, rows) of one record, CRC-verified."""
        payload = self.file.read(index)
        try:
            cols, vals, dict_ids = pickle.loads(payload)
        except Exception as e:  # noqa: BLE001 - unpicklable = corrupt
            raise SpillCorruptionError(
                f"spill file corrupt: {self.file.path} record {index} "
                f"payload undecodable: {e!r}"
            ) from e
        rows = self.row_counts[index]
        if cols and len(cols[0]) != rows:
            raise SpillCorruptionError(
                f"spill file corrupt: {self.file.path} record {index} row "
                f"count mismatch ({len(cols[0])} != {rows})"
            )
        return cols, vals, dict_ids, rows

    def iter_chunks(self):
        for i in range(len(self.row_counts)):
            yield self.read_chunk(i)

    def delete(self) -> None:
        self.file.delete()


def all_active_bytes() -> int:
    """Live spill bytes across every manager in the process — the leak
    oracle: zero whenever no query is mid-flight."""
    return sum(m.active_bytes for m in list(_MANAGERS))


def all_active_files() -> int:
    return sum(m.active_files for m in list(_MANAGERS))


def total_written() -> int:
    """Lifetime bytes spilled to disk across every manager (northstar's
    per-query spilled_bytes counter reads deltas of this)."""
    return sum(m.total_written for m in list(_MANAGERS))


# default manager for in-process sessions (workers may carry their own
# quota-configured instance; all register in _MANAGERS for the oracle)
SPILL_MANAGER = SpillSpaceManager()
