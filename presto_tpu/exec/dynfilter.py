"""Runtime dynamic filters: build-side summaries pushed into probe scans.

Re-designed equivalent of the reference's dynamic filtering stack
(presto-main/.../operator/DynamicFilterSourceOperator collecting build-side
values, sql/planner/optimizations/ PredicatePushdown's dynamic filter
placeholders, LocalDynamicFiltersCollector waiting probe-side scans) —
the signature optimization of the Presto lineage for selective joins.

TPU-first reduction: after a join's build side materializes, the executor
derives ONE per-key summary on device and publishes it under a planner-
assigned filter id (plan/rules.annotate_dynamic_filters). Strategy picked
from the build side's real cardinality (the executors are host-driven and
adaptive, so this is a perfect-information choice, not an estimate):

  minmax   exact min/max of the build keys — always derived for ordered
           storage (ints, dates, short decimals, floats); doubles as the
           SPI pruning hint (ge/le conjuncts).
  inlist   exact sorted distinct values when build NDV <= in_limit —
           membership by vectorized binary search; zero false positives;
           exported as the SPI `in` hint so connectors prune row groups.
  bloom    blocked bloom filter over the engine row hash
           (ops/bloomfilter.py) otherwise — no false negatives, ~1-2%
           false positives, queried fully vectorized on device.

Application is fused into the probe side's existing Filter/TableScan
kernels (exec/executor.py, exec/stream.py): the dynamic mask ANDs into the
scan filter's keep mask so pruning costs no extra compaction pass. Probe
rows with NULL keys are pruned too (SQL equi-join semantics: NULL never
matches) — only INNER joins and plain semi joins are annotated, where
dropping non-matching probe rows early is an identity on the result.

Cross-task (server/cluster.py): build-stage workers accumulate HOST
summaries over their output pages (HostFilterAccumulator), the coordinator
merges per-task summaries with a bounded wait and ships them in probe-stage
task specs; a slow or failed build stage degrades to proceed-without-filter.
Everything runs behind the `dynamic_filter` kernel circuit breaker
(exec/breaker.py) with the legacy no-filter path as fallback.
"""

from __future__ import annotations

import base64
import dataclasses
import os
import threading
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..ops.bloomfilter import (
    bloom_build,
    bloom_build_host,
    bloom_merge_host,
    bloom_query,
    choose_log2_bits,
)
from ..ops.hashing import hash_column


def dynamic_filtering_enabled() -> bool:
    return os.environ.get("PRESTO_TPU_DYNFILTER", "1") != "0"


def in_list_limit() -> int:
    """Build NDV at or under this derives the exact IN-list filter."""
    return int(os.environ.get("PRESTO_TPU_DYNFILTER_IN_LIMIT", "8192"))


# IN-lists longer than this are not exported as SPI hints (a connector
# comparing thousands of values per row group beats nobody)
SPI_IN_LIMIT = 256


def _is_ordered_storage(typ) -> bool:
    """Types whose 1-D storage ints/floats order like the logical value."""
    return isinstance(
        typ,
        (
            T.BigintType, T.IntegerType, T.SmallintType, T.TinyintType,
            T.DateType, T.TimestampType, T.DoubleType, T.RealType,
        ),
    ) or (isinstance(typ, T.DecimalType) and not typ.is_long)


def _storage_to_logical(typ, v):
    """One STORAGE scalar -> the logical Python value the SPI expects
    (datetime.date for DATE, Decimal for decimals — matching what
    file-format statistics expose)."""
    import datetime as pydt
    import decimal as pydec

    if isinstance(typ, T.DateType):
        return pydt.date(1970, 1, 1) + pydt.timedelta(days=int(v))
    if isinstance(typ, T.DecimalType):
        return pydec.Decimal(int(v)).scaleb(-typ.scale)
    if isinstance(typ, (T.DoubleType, T.RealType)):
        return float(v)
    return int(v)


@dataclasses.dataclass
class DynamicFilter:
    """One derived build-side summary, queryable on device.

    `lo`/`hi`/`values`/`bloom_words` are device arrays (or None); the
    *_host twins are numpy/python values used for SPI hints and wire
    serialization. A filter always carries minmax when the key type is
    ordered; exactly one of values/bloom_words when membership is on."""

    strategy: str  # 'minmax' | 'inlist' | 'bloom'
    key_type: T.Type
    build_rows: int
    lo: Optional[jnp.ndarray] = None  # storage-unit scalars
    hi: Optional[jnp.ndarray] = None
    values: Optional[jnp.ndarray] = None  # sorted distinct storage values
    bloom_words: Optional[jnp.ndarray] = None
    log2_bits: int = 0
    lo_host: Optional[object] = None  # storage-unit numpy scalars
    hi_host: Optional[object] = None
    values_host: Optional[np.ndarray] = None
    str_values: Optional[Tuple[str, ...]] = None  # varchar IN-list (logical)
    empty_build: bool = False  # no live build rows: probe matches nothing

    # -- device application --

    def mask(self, val) -> jnp.ndarray:
        """Per-row keep mask over a probe key Val/Block: False rows can
        NEVER match the build side (no false negatives by construction)."""
        data = val.data
        if self.empty_build:
            return jnp.zeros(data.shape[:1], jnp.bool_)
        if self.str_values is not None:
            keep = self._varchar_mask(val)
        else:
            keep = jnp.ones(data.shape[:1], jnp.bool_)
            if self.lo is not None and data.ndim == 1:
                keep = (data >= self.lo) & (data <= self.hi)
            if self.values is not None and data.ndim == 1:
                pos = jnp.searchsorted(self.values, data)
                pos = jnp.minimum(pos, self.values.shape[0] - 1)
                keep = keep & (self.values[pos] == data)
            elif self.bloom_words is not None:
                h = hash_column(data)
                keep = keep & bloom_query(self.bloom_words, h, self.log2_bits)
        if val.valid is not None:
            keep = keep & val.valid  # NULL keys never equi-match
        return keep

    def _varchar_mask(self, val) -> jnp.ndarray:
        """Varchar membership via the probe DICTIONARY: a host lookup table
        over the (small) dictionary, gathered by code — O(dict) host work,
        O(rows) device gather. Codes are dictionary-local, so comparing
        them against build codes directly would be wrong; logical strings
        are the cross-dictionary currency."""
        from ..page import dictionary_by_id

        if val.dict_id is None:
            return jnp.ones(val.data.shape[:1], jnp.bool_)
        entries = dictionary_by_id(val.dict_id)
        members = frozenset(self.str_values)
        lut = np.fromiter(
            (s in members for s in entries), np.bool_, count=len(entries)
        )
        if not len(lut):
            return jnp.zeros(val.data.shape[:1], jnp.bool_)
        codes = jnp.clip(val.data, 0, len(lut) - 1)
        return jnp.asarray(lut)[codes]

    # -- SPI hints --

    def spi_conjuncts(self, source_col: str, typ=None) -> List[tuple]:
        """(column, op, logical value) pruning hints for connector scans
        (connectors/spi.py Predicate). Bloom filters export only their
        min/max envelope — a connector cannot evaluate the bit array.

        `typ` overrides the stored key type — wire-reconstructed filters
        (cluster cross-task) carry no type, and emitting raw STORAGE ints
        as logical values would wrongly refute units for decimal/date
        keys; with no type from either source, no hints are emitted."""
        t = typ if typ is not None else self.key_type
        if self.str_values is None and t is None:
            return []
        out: List[tuple] = []
        if self.str_values is not None and len(self.str_values) <= SPI_IN_LIMIT:
            out.append((source_col, "in", tuple(self.str_values)))
            return out
        if self.values_host is not None and len(self.values_host) <= SPI_IN_LIMIT:
            out.append(
                (
                    source_col,
                    "in",
                    tuple(_storage_to_logical(t, v) for v in self.values_host),
                )
            )
        if self.lo_host is not None:
            out.append((source_col, "ge", _storage_to_logical(t, self.lo_host)))
            out.append((source_col, "le", _storage_to_logical(t, self.hi_host)))
        return out

    def describe(self) -> str:
        if self.empty_build:
            return "empty"
        if self.strategy == "bloom":
            return f"bloom(n={self.build_rows},bits=2^{self.log2_bits})"
        if self.strategy == "inlist":
            n = (
                len(self.str_values)
                if self.str_values is not None
                else int(self.values.shape[0])
            )
            return f"inlist({n})"
        return f"minmax(n={self.build_rows})"


# ---------------------------------------------------------------------------
# derivation (device)
# ---------------------------------------------------------------------------


def _key_stats(data, valid):
    """(n, ndv, sorted_with_sentinel) in one device program. `data` must be
    1-D; the sort sends invalid rows to the dtype max sentinel so live
    distinct values occupy a prefix."""
    if jnp.issubdtype(data.dtype, jnp.floating):
        sentinel = jnp.asarray(jnp.inf, data.dtype)
    else:
        sentinel = jnp.asarray(jnp.iinfo(data.dtype).max, data.dtype)
    s = jnp.sort(jnp.where(valid, data, sentinel))
    n = jnp.sum(valid.astype(jnp.int64))
    cap = data.shape[0]
    idx = jnp.arange(cap, dtype=jnp.int64)
    boundary = jnp.concatenate(
        [jnp.ones(1, jnp.bool_), s[1:] != s[:-1]]
    )
    ndv = jnp.sum((boundary & (idx < n)).astype(jnp.int64))
    return n, ndv, s


def derive_filter(val, live: jnp.ndarray) -> Optional[DynamicFilter]:
    """Summarize one build-side key column (a Val/Block) into a
    DynamicFilter, or None when the type has no cheap summary.

    Host syncs: ONE batched fetch of 4 scalars to pick the strategy (the
    build side is already materialized, so this races nothing), plus the
    strategy's own payload. The caller runs this behind the
    `dynamic_filter` circuit breaker."""
    data = val.data
    valid = live if val.valid is None else (live & val.valid)
    typ = val.type

    if isinstance(typ, T.VarcharType):
        return _derive_varchar(val, valid)
    if data.ndim != 1 or data.dtype == jnp.bool_:
        return None  # long-decimal lanes / booleans: not worth a filter
    if not _is_ordered_storage(typ):
        return None
    if jnp.issubdtype(data.dtype, jnp.floating):
        # NaN build keys never equi-match (IEEE NaN != NaN, which is also
        # the engine's join compare) — and a NaN min/max would prune every
        # probe row. Treat them as absent from the build side.
        valid = valid & ~jnp.isnan(data)

    n_d, ndv_d, s = _key_stats(data, valid)
    n, ndv = (int(x) for x in jax.device_get((n_d, ndv_d)))
    if n == 0:
        return DynamicFilter(
            "minmax", typ, 0, empty_build=True
        )
    lo = s[0]
    hi = jnp.max(jnp.where(valid, data, s[0]))
    lo_h, hi_h = jax.device_get((lo, hi))
    if ndv <= in_list_limit():
        boundary = jnp.concatenate([jnp.ones(1, jnp.bool_), s[1:] != s[:-1]])
        pos = jnp.nonzero(boundary, size=ndv, fill_value=0)[0]
        values = s[pos]
        return DynamicFilter(
            "inlist", typ, n, lo=lo, hi=hi, values=values,
            lo_host=lo_h, hi_host=hi_h,
            values_host=np.asarray(jax.device_get(values)),
        )
    log2_bits = choose_log2_bits(ndv)
    words = bloom_build(hash_column(data), valid, log2_bits)
    return DynamicFilter(
        "bloom", typ, n, lo=lo, hi=hi, bloom_words=words,
        log2_bits=log2_bits, lo_host=lo_h, hi_host=hi_h,
    )


def _derive_varchar(val, valid) -> Optional[DynamicFilter]:
    """Varchar keys: dictionary codes are dictionary-LOCAL, so the only
    safe cross-column summary is the logical string set. Distinct codes
    among live rows map through the build dictionary; NDV above the limit
    means no filter (a bloom over codes would be wrong across dicts)."""
    from ..page import dictionary_by_id

    if val.dict_id is None:
        return None
    entries = dictionary_by_id(val.dict_id)
    if len(entries) > in_list_limit():
        return None
    nbits = max(len(entries), 1)
    seen = (
        jnp.zeros(nbits + 1, jnp.bool_)
        .at[jnp.where(valid, jnp.clip(val.data, 0, nbits - 1), nbits)]
        .set(True)
    )
    seen_h = np.asarray(jax.device_get(seen[:nbits]))
    n = int(seen_h.sum())
    if n == 0:
        return DynamicFilter("minmax", val.type, 0, empty_build=True)
    strs = tuple(s for s, flag in zip(entries, seen_h) if flag)
    return DynamicFilter("inlist", val.type, n, str_values=strs)


# ---------------------------------------------------------------------------
# context: publish / consume across one query
# ---------------------------------------------------------------------------


class DynamicFilterContext:
    """Per-query registry of derived filters. Single-process executors
    publish synchronously (the build side always completes before the
    probe side streams), so `get` never blocks; the bounded wait lives in
    the cluster coordinator, which resolves summaries between stages."""

    def __init__(self):
        self._filters: Dict[str, DynamicFilter] = {}
        self._lock = threading.Lock()
        # ids applied at a scan/filter (so joins skip the pre-probe pass)
        self.consumed: set = set()
        # observability: fid -> rows pruned at scan/filter vs pre-probe
        self.scan_pruned: Dict[str, int] = {}
        self.preprobe_pruned: Dict[str, int] = {}
        self.wait_s: float = 0.0  # cross-task filter wait (cluster path)

    def publish(self, fid: str, df: DynamicFilter) -> None:
        with self._lock:
            self._filters[fid] = df

    def get(self, fid: str) -> Optional[DynamicFilter]:
        with self._lock:
            return self._filters.get(fid)

    def note_pruned(self, fid: str, n: int, where: str = "scan") -> None:
        with self._lock:
            book = self.scan_pruned if where == "scan" else self.preprobe_pruned
            book[fid] = book.get(fid, 0) + int(n)

    def total_pruned(self) -> int:
        with self._lock:
            return sum(self.scan_pruned.values()) + sum(
                self.preprobe_pruned.values()
            )

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "filters": {
                    fid: df.describe() for fid, df in self._filters.items()
                },
                "scan_pruned": dict(self.scan_pruned),
                "preprobe_pruned": dict(self.preprobe_pruned),
                "wait_s": self.wait_s,
            }

    def reset(self) -> None:
        with self._lock:
            self._filters.clear()
            self.consumed.clear()
            self.scan_pruned.clear()
            self.preprobe_pruned.clear()
            self.wait_s = 0.0


# ---------------------------------------------------------------------------
# host accumulation + wire summaries (cluster cross-task shipping)
# ---------------------------------------------------------------------------


class HostFilterAccumulator:
    """Accumulates a filter summary on the HOST over pages as a worker task
    produces them (server/worker.py) — numpy only, no device work on the
    output path. Varchar and long-decimal channels are skipped (dictionary
    codes are process-local; 2-lane storage has no scalar summary)."""

    def __init__(self, channel: str):
        self.channel = channel
        self.typ = None
        self.count = 0
        self.lo = None
        self.hi = None
        self.values: Optional[set] = set()  # None once overflowed
        self.words: Optional[np.ndarray] = None
        self.log2_bits = choose_log2_bits(in_list_limit() * 4)
        self.unsupported = False

    def add_page(self, page) -> None:
        if self.unsupported or self.channel not in page.names:
            if self.channel not in page.names:
                self.unsupported = True
            return
        b = page.block(self.channel)
        n = int(page.count)
        data = np.asarray(b.data[:n])
        valid = None if b.valid is None else np.asarray(b.valid[:n])
        self.add_numpy(data, valid, b.type)

    def add_numpy(self, data: np.ndarray, valid, typ) -> None:
        """Accumulate raw host columns (HostTable spill stores and worker
        output pages share this path)."""
        if self.unsupported:
            return
        if data.ndim != 1 or isinstance(typ, T.VarcharType) or not (
            _is_ordered_storage(typ)
        ):
            self.unsupported = True
            return
        self.typ = typ
        if valid is not None:
            data = data[valid]
        if np.issubdtype(data.dtype, np.floating):
            data = data[~np.isnan(data)]  # NaN never equi-matches
        if not len(data):
            return
        self.count += len(data)
        lo, hi = data.min(), data.max()
        self.lo = lo if self.lo is None else min(self.lo, lo)
        self.hi = hi if self.hi is None else max(self.hi, hi)
        if self.values is not None:
            self.values.update(np.unique(data).tolist())
            if len(self.values) > in_list_limit():
                self.values = None  # overflow: bloom only from here on
        self.words = bloom_build_host(
            _host_hash(data), self.log2_bits, self.words
        )

    def summary(self) -> Optional[dict]:
        """JSON-able wire summary, or None when nothing useful accrued."""
        if self.unsupported or self.typ is None:
            return None
        out = {
            "count": self.count,
            "type": repr(self.typ),
            "lo": _json_scalar(self.lo),
            "hi": _json_scalar(self.hi),
            "float": isinstance(self.typ, (T.DoubleType, T.RealType)),
            # REAL keys hash by their float32 bit pattern: a values->bloom
            # conversion must re-hash at the same width
            "real": isinstance(self.typ, T.RealType),
        }
        if self.count == 0:
            out["empty"] = True
            return out
        if self.values is not None:
            out["values"] = [_json_scalar(v) for v in sorted(self.values)]
        else:
            out["bloom_b64"] = base64.b64encode(
                self.words.tobytes()
            ).decode()
            out["log2_bits"] = self.log2_bits
        return out


def _host_hash(data: np.ndarray) -> np.ndarray:
    """Host replica of ops/hashing.hash_column for 1-D numeric storage —
    bit-identical so host-built blooms answer device-hashed queries."""
    if np.issubdtype(data.dtype, np.floating):
        data = np.where(data == 0, np.zeros_like(data), data)
        data = np.where(np.isnan(data), np.full_like(data, np.nan), data)
        width = data.dtype.itemsize
        bits = data.view({4: np.uint32, 8: np.uint64}[width]).astype(np.uint64)
    else:
        bits = data.astype(np.uint64)
    with np.errstate(over="ignore"):
        x = bits
        x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        return x ^ (x >> np.uint64(31))


def _json_scalar(v):
    if v is None:
        return None
    if isinstance(v, (np.floating, float)):
        return float(v)
    return int(v)


def merge_summaries(parts: List[dict]) -> Optional[dict]:
    """Merge per-task wire summaries (min of lo, max of hi, union of value
    sets, OR of blooms — a part that fell back to bloom forces bloom). Any
    missing part means a task's keys are unaccounted for and the filter
    cannot be trusted — return None (no false negatives, ever)."""
    if not parts or any(not p for p in parts):
        return None
    out = dict(parts[0])
    for p in parts[1:]:
        if p.get("type") != out.get("type"):
            return None
        out["count"] = out.get("count", 0) + p.get("count", 0)
        for k, pick in (("lo", min), ("hi", max)):
            a, b = out.get(k), p.get(k)
            out[k] = pick(a, b) if a is not None and b is not None else (
                a if b is None else b
            )
        if "values" in out and "values" in p:
            merged = sorted(set(out["values"]) | set(p["values"]))
            if len(merged) <= in_list_limit():
                out["values"] = merged
                continue
        # membership degrades to an OR of blooms (values parts convert by
        # re-hashing — BEFORE dropping them from the summaries)
        wa = _words_of(out)
        wb = _words_of(p)
        out.pop("values", None)
        if wa is None or wb is None or len(wa) != len(wb):
            out.pop("bloom_b64", None)
            out.pop("log2_bits", None)
        else:
            out["bloom_b64"] = base64.b64encode(
                bloom_merge_host(wa, wb).tobytes()
            ).decode()
            out["log2_bits"] = (
                out.get("log2_bits")
                or p.get("log2_bits")
                or choose_log2_bits(in_list_limit() * 4)
            )
    out["empty"] = out.get("count", 0) == 0
    if (
        not out.get("empty")
        and "values" not in out
        and "bloom_b64" not in out
        and out.get("lo") is None
    ):
        return None
    return out


def _words_of(summary: dict) -> Optional[np.ndarray]:
    b64 = summary.get("bloom_b64")
    if b64 is None:
        # a pure value-set part converts to a bloom for OR-merging; the
        # hash must use the key's STORAGE width (REAL keys hash their
        # float32 bit pattern — re-hashing as float64 would insert
        # different bits than the device probe queries)
        vals = summary.get("values")
        if vals is None:
            return None
        lb = summary.get("log2_bits") or choose_log2_bits(
            in_list_limit() * 4
        )
        if summary.get("real"):
            dt = np.float32
        elif summary.get("float"):
            dt = np.float64
        else:
            dt = np.int64
        return bloom_build_host(_host_hash(np.asarray(vals, dt)), lb)
    return np.frombuffer(
        base64.b64decode(b64), np.uint32
    ).copy()


def filter_from_summary(summary: dict, key_type) -> Optional[DynamicFilter]:
    """Reconstruct a device-queryable DynamicFilter from a wire summary on
    the probe-side worker."""
    if summary is None:
        return None
    if summary.get("empty"):
        return DynamicFilter("minmax", key_type, 0, empty_build=True)
    dt = np.float64 if summary.get("float") else np.int64
    lo_h, hi_h = summary.get("lo"), summary.get("hi")
    lo = hi = None
    if lo_h is not None:
        lo = jnp.asarray(dt(lo_h))
        hi = jnp.asarray(dt(hi_h))
    if "values" in summary:
        values = np.asarray(summary["values"], dt)
        return DynamicFilter(
            "inlist", key_type, summary.get("count", len(values)),
            lo=lo, hi=hi, values=jnp.asarray(values),
            lo_host=lo_h, hi_host=hi_h, values_host=values,
        )
    if "bloom_b64" in summary:
        words = np.frombuffer(
            base64.b64decode(summary["bloom_b64"]), np.uint32
        )
        return DynamicFilter(
            "bloom", key_type, summary.get("count", 0), lo=lo, hi=hi,
            bloom_words=jnp.asarray(words),
            log2_bits=int(summary["log2_bits"]),
            lo_host=lo_h, hi_host=hi_h,
        )
    if lo is None:
        return None
    return DynamicFilter(
        "minmax", key_type, summary.get("count", 0), lo=lo, hi=hi,
        lo_host=lo_h, hi_host=hi_h,
    )
