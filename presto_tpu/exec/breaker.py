"""Kernel-fault circuit breakers: reusable degrade-to-XLA machinery.

Generalizes the ad-hoc `self.pallas_groupby = False` kill switch that
round-5 added after a Mosaic fault took down a whole SQL stage (see
exec/executor.py aggregation dispatch). Each experimental kernel path —
the Pallas group-by, the bucket-directory join probe (ops/join.py) and
the fused variadic sort (ops/sort.py) — now runs behind a named breaker
with the classic three states:

* CLOSED     — kernel allowed; consecutive failures are counted.
* OPEN       — kernel skipped (the safe XLA composition runs instead)
  until `recovery_timeout` elapses.
* HALF_OPEN  — after the timeout probe attempts are admitted again;
  success closes the breaker, failure re-opens it with a fresh timeout.

`allow()` is deliberately non-mutating (HALF_OPEN is derived from the
clock, transitions happen only in record_success / record_failure): the
executor consults the breaker when picking a kernel-cache key and the op
layer consults it again at trace time, and both must see one answer.

The registry is process-global (module singleton `BREAKERS`) because a
kernel that faults does so for every executor in the process — the
failure is a property of the (kernel, backend, libtpu) combination, not
of one query. Stats surface through exec/stats.py and EXPLAIN ANALYZE.

Env knobs:
* PRESTO_TPU_BREAKER_THRESHOLD   consecutive failures to open (default 1
  — matching the old behavior where a single Mosaic failure disabled the
  Pallas path for the process).
* PRESTO_TPU_BREAKER_RECOVERY_S  seconds an open breaker waits before a
  half-open probe (default 300).
* PRESTO_TPU_BREAKER_DISABLE=1   breakers never open (kernel faults
  still fall back per call, but every call re-attempts the kernel).
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Callable, Dict, Optional

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class KernelCircuitBreaker:
    """One kernel's failure state machine. Thread-safe: executors on
    worker task threads share the process-global registry."""

    def __init__(self, name: str, failure_threshold: int = 1,
                 recovery_timeout: float = 300.0,
                 clock: Callable[[], float] = time.monotonic):
        self.name = name
        self.failure_threshold = max(1, int(failure_threshold))
        self.recovery_timeout = float(recovery_timeout)
        self.clock = clock
        self.consecutive_failures = 0
        self.total_failures = 0
        self.total_successes = 0
        self.opened_at: Optional[float] = None  # None = closed
        self.last_error: str = ""
        self._lock = threading.RLock()

    @property
    def state(self) -> str:
        with self._lock:
            if self.opened_at is None:
                return CLOSED
            if self.clock() - self.opened_at >= self.recovery_timeout:
                return HALF_OPEN
            return OPEN

    def allow(self) -> bool:
        """May the kernel be attempted right now? Non-mutating: an open
        breaker past its recovery timeout admits half-open probes."""
        return self.state != OPEN

    def record_success(self) -> None:
        with self._lock:
            self.total_successes += 1
            self.consecutive_failures = 0
            self.opened_at = None  # a half-open probe succeeded: close

    def record_failure(self, error: str = "") -> None:
        with self._lock:
            self.total_failures += 1
            self.consecutive_failures += 1
            self.last_error = error[:300]
            if self.opened_at is not None:
                # half-open probe failed (or repeat fault while open):
                # re-arm a fresh recovery window
                self.opened_at = self.clock()
            elif self.consecutive_failures >= self.failure_threshold:
                self.opened_at = self.clock()

    def snapshot(self) -> dict:
        with self._lock:
            state = self.state
            wait = None
            if state == OPEN and self.opened_at is not None:
                wait = max(
                    0.0,
                    self.recovery_timeout - (self.clock() - self.opened_at),
                )
            return {
                "state": state,
                "consecutive_failures": self.consecutive_failures,
                "total_failures": self.total_failures,
                "total_successes": self.total_successes,
                "retry_in_s": wait,
                "last_error": self.last_error,
            }


class BreakerRegistry:
    """Lazily-created breakers keyed by kernel name."""

    def __init__(self, failure_threshold: Optional[int] = None,
                 recovery_timeout: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        self._breakers: Dict[str, KernelCircuitBreaker] = {}
        self._lock = threading.Lock()
        self._tls = threading.local()  # per-thread forced-fallback names
        self.clock = clock
        self.failure_threshold = failure_threshold
        self.recovery_timeout = recovery_timeout

    @contextlib.contextmanager
    def forced_fallback(self, name: str):
        """Force `allow(name)` to False on THIS thread for the duration —
        the executor's per-call fallback retry after a fault, regardless
        of breaker state (a below-threshold streak or
        PRESTO_TPU_BREAKER_DISABLE=1 must still fall back for the call
        that just faulted). Thread-local because the kernel's trace runs
        on the caller's thread."""
        prev = getattr(self._tls, "forced", frozenset())
        self._tls.forced = prev | {name}
        try:
            yield
        finally:
            self._tls.forced = prev

    def _config(self):
        threshold = self.failure_threshold
        if threshold is None:
            threshold = int(
                os.environ.get("PRESTO_TPU_BREAKER_THRESHOLD", "1")
            )
        recovery = self.recovery_timeout
        if recovery is None:
            recovery = float(
                os.environ.get("PRESTO_TPU_BREAKER_RECOVERY_S", "300")
            )
        return threshold, recovery

    def get(self, name: str) -> KernelCircuitBreaker:
        with self._lock:
            br = self._breakers.get(name)
            if br is None:
                threshold, recovery = self._config()
                br = KernelCircuitBreaker(
                    name, failure_threshold=threshold,
                    recovery_timeout=recovery, clock=self.clock,
                )
                self._breakers[name] = br
            return br

    def allow(self, name: str) -> bool:
        if name in getattr(self._tls, "forced", ()):
            return False
        if os.environ.get("PRESTO_TPU_BREAKER_DISABLE") == "1":
            return True
        return self.get(name).allow()

    def record_success(self, name: str) -> None:
        self.get(name).record_success()

    def record_failure(self, name: str, error: str = "") -> None:
        self.get(name).record_failure(error)

    def snapshot(self) -> Dict[str, dict]:
        with self._lock:
            items = list(self._breakers.items())
        return {name: br.snapshot() for name, br in items}

    def reset(self) -> None:
        """Forget all breaker state (tests)."""
        with self._lock:
            self._breakers.clear()


# process-global registry: kernel health is per (backend, libtpu), not
# per executor instance
BREAKERS = BreakerRegistry()
