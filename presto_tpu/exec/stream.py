"""Streaming (paged) plan execution with a device-memory budget.

Re-designed equivalent of the reference's worker streaming pipeline — the
Driver hot loop pulling pages operator-to-operator
(presto-main/.../operator/Driver.java:347-430), split/morsel scans
(SourcePartitionedScheduler + ConnectorPageSource), and the revocable-
memory/spill machinery (memory/MemoryPool.java:43,
operator/HashBuilderOperator.java:155-180 SPILLING_INPUT states,
spiller/). TPU-first redesign:

* A "page" is a fixed-capacity device batch (static shapes -> one compiled
  kernel chain reused for every batch); the host driver loop streams leaf
  batches through stateless kernels into accumulating sinks.
* Aggregations accumulate PARTIAL states on device and merge periodically —
  the same partial/final decomposition the distributed path uses
  (ops/aggregate.decompose_partial), so a base table is never resident.
* Join build sides materialize on device under a MemoryPool budget; when
  the budget would be exceeded they *offload to host RAM* (the disk-spill
  analog, SURVEY §5 "long-context analog") and INNER joins run
  chunk-by-chunk against re-streamed probes — the reference's grouped /
  bucket-wise execution (Lifespan + PipelineExecutionStrategy.GROUPED).
* Sinks short-circuit where the reference would (LIMIT stops the scan).

Everything falls back to the materializing Executor for node shapes that
need whole inputs (windows, full-outer composition, sorts beyond budget).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterator, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..expr import ir
from ..ops.aggregate import (
    apply_avg_post,
    decompose_partial,
    global_aggregate,
    grouped_aggregate_sorted,
)
from ..ops.filter import filter_page
from ..ops.join import build, join_expand, join_n1
from ..ops.sort import distinct_page, limit_page, sort_page, top_n
from ..ops.union import concat_pages
from ..page import Block, Page, round_capacity
from ..plan import nodes as N
from .executor import ExecutionError, Executor
from .memory import MemoryExceededError, MemoryPool
from .stats import page_device_bytes


def coalesce_pages(
    pages: Iterator[Page], target_rows: int
) -> Iterator[Page]:
    """Merge consecutive small pages into ~target_rows batches.

    The hierarchical exchange (server/hier.py) ships RAGGED paged
    partitions — wire pages of at most PRESTO_TPU_RAGGED_PAGE_ROWS live
    rows, so skew never pads the wire. The flip side is many small
    pages per batch; feeding them one-by-one into the streaming sinks
    would dispatch a device kernel per sliver. This coalescer restores
    batch efficiency on the consumer: accumulate until target_rows,
    concat once, hand the sinks full batches. A stream of only empty
    pages coalesces to ONE empty page, so schema survives; a truly
    empty iterator stays empty."""
    held: List[Page] = []
    held_rows = 0
    for page in pages:
        n = int(page.count)
        if n >= target_rows and not held:
            yield page
            continue
        held.append(page)
        held_rows += n
        if held_rows >= target_rows:
            yield held[0] if len(held) == 1 else concat_pages(held)
            held, held_rows = [], 0
    if held:
        yield held[0] if len(held) == 1 else concat_pages(held)


@dataclasses.dataclass
class HostTable:
    """Host-RAM offloaded rows (the spill-file analog): numpy columns +
    schema, uploadable chunk-by-chunk."""

    names: Tuple[str, ...]
    types: tuple
    dict_ids: tuple
    columns: List[np.ndarray]
    valids: List[Optional[np.ndarray]]

    @property
    def num_rows(self) -> int:
        return len(self.columns[0]) if self.columns else 0

    @property
    def row_bytes(self) -> int:
        if not self.num_rows:
            return 0
        per = sum(c.dtype.itemsize * (c.size // len(c)) for c in self.columns)
        per += sum(1 for v in self.valids if v is not None)
        return per

    def slice_page(self, start: int, stop: int, pad_to=None) -> Page:
        blocks = []
        for c, v, t2, d in zip(self.columns, self.valids, self.types, self.dict_ids):
            data = jnp.asarray(c[start:stop])
            valid = None if v is None else jnp.asarray(v[start:stop])
            blk = Block(data, t2, valid, d)
            if pad_to is not None and pad_to > stop - start:
                from ..page import _pad_block

                blk = _pad_block(blk, pad_to)
            blocks.append(blk)
        return Page.from_blocks(blocks, self.names, count=stop - start)

    @staticmethod
    def from_pages(pages: List[Page]) -> "HostTable":
        from ..ops.union import unify_block_dictionaries

        first = pages[0]
        cols: List[np.ndarray] = []
        valids: List[Optional[np.ndarray]] = []
        dict_ids: List[Optional[int]] = []
        for i in range(len(first.blocks)):
            # unify per-batch dictionaries BEFORE concatenating codes (same
            # invariant as concat_pages — codes are meaningless across
            # different dictionaries)
            blocks, did = unify_block_dictionaries([p.blocks[i] for p in pages])
            dict_ids.append(did)
            parts = []
            vparts = []
            any_valid = any(b.valid is not None for b in blocks)
            for p, b in zip(pages, blocks):
                n = int(p.count)
                parts.append(np.asarray(b.data[:n]))
                if any_valid:
                    vparts.append(
                        np.asarray(b.valid[:n])
                        if b.valid is not None
                        else np.ones((n,), np.bool_)
                    )
            cols.append(np.concatenate(parts) if parts else np.empty((0,)))
            valids.append(np.concatenate(vparts) if any_valid else None)
        return HostTable(
            first.names,
            tuple(b.type for b in first.blocks),
            tuple(dict_ids),
            cols,
            valids,
        )

    def append_page(self, page: Page) -> None:
        self.append_host(HostTable.from_pages([page]))

    def append_host(self, other: "HostTable") -> None:
        """Concatenate another host table's rows onto this one, unifying
        per-table string dictionaries."""
        from ..page import dictionary_by_id, intern_dictionary

        dict_ids = list(self.dict_ids)
        for i in range(len(self.columns)):
            a_id, b_id = dict_ids[i], other.dict_ids[i]
            b_col = other.columns[i]
            if a_id != b_id:
                # host-side dictionary unification: remap BOTH code arrays
                # onto the merged sorted dictionary
                da = dictionary_by_id(a_id) if a_id is not None else ()
                db = dictionary_by_id(b_id) if b_id is not None else ()
                merged = tuple(sorted(set(da) | set(db)))
                index = {s: j for j, s in enumerate(merged)}
                map_a = np.array([index[s] for s in da], np.int32)
                map_b = np.array([index[s] for s in db], np.int32)
                if len(da):
                    self.columns[i] = map_a[self.columns[i]]
                if len(db):
                    b_col = map_b[b_col]
                dict_ids[i] = intern_dictionary(merged)
            self.columns[i] = np.concatenate([self.columns[i], b_col])
            a, b = self.valids[i], other.valids[i]
            if a is None and b is None:
                continue
            if a is None:
                a = np.ones((len(self.columns[i]) - len(b_col),), np.bool_)
            if b is None:
                b = np.ones((other.num_rows,), np.bool_)
            self.valids[i] = np.concatenate([a, b])
        self.dict_ids = tuple(dict_ids)


def _pushdown_hints(predicate, scan_node: N.TableScan):
    """Extract (source_column, op, python_value) pruning hints from simple
    conjuncts over scanned columns (the TupleDomain-lite of the SPI)."""
    import datetime as pydt
    import decimal as pydec

    to_source = {ch: col for ch, col, _ in scan_node.columns}
    types = {ch: typ for ch, _, typ in scan_node.columns}
    conjuncts: List = []

    def split(e):
        if isinstance(e, ir.Call) and e.name == "and":
            for a in e.args:
                split(a)
        else:
            conjuncts.append(e)

    split(predicate)
    flip = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le", "eq": "eq"}
    hints = []

    def value_for(ch, lit):
        typ = types.get(ch)
        v = lit.value
        if v is None:
            return None
        if isinstance(typ, T.DateType):
            if isinstance(v, str):
                return pydt.date.fromisoformat(v)
            return pydt.date(1970, 1, 1) + pydt.timedelta(days=int(v))
        if isinstance(typ, T.DecimalType):
            # literal carries the LOGICAL value (planner _number_literal);
            # Decimal statistics compare fine against float in Python
            return float(v) if not isinstance(v, pydec.Decimal) else v
        if isinstance(typ, T.VarcharType):
            return v if isinstance(v, str) else None
        return v

    def in_values(e):
        """(channel, values) for `col IN (lit...)` and OR-of-equals over
        ONE column — both become the SPI 'in' hint (reference
        TupleDomain's discrete value sets)."""
        if e.name == "in" and isinstance(e.args[0], ir.ColumnRef):
            col, opts = e.args[0], e.args[1:]
            if all(isinstance(o, ir.Literal) for o in opts):
                return col.name, opts
            return None
        if e.name == "or":
            col = None
            opts = []
            for part in e.args:
                if not (
                    isinstance(part, ir.Call)
                    and part.name == "eq"
                    and len(part.args) == 2
                ):
                    return None
                a, b = part.args
                if isinstance(b, ir.ColumnRef) and isinstance(a, ir.Literal):
                    a, b = b, a
                if not (
                    isinstance(a, ir.ColumnRef) and isinstance(b, ir.Literal)
                ):
                    return None
                if col is None:
                    col = a.name
                elif col != a.name:
                    return None
                opts.append(b)
            return (col, tuple(opts)) if col is not None else None
        return None

    for e in conjuncts:
        if not isinstance(e, ir.Call):
            continue
        iv = in_values(e)
        if iv is not None:
            col_name, opts = iv
            if col_name in to_source:
                vals = tuple(value_for(col_name, o) for o in opts)
                if all(v is not None for v in vals):
                    hints.append((to_source[col_name], "in", vals))
            continue
        if e.name == "between" and isinstance(e.args[0], ir.ColumnRef):
            col, lo, hi = e.args
            if col.name in to_source and isinstance(lo, ir.Literal) and isinstance(hi, ir.Literal):
                vlo, vhi = value_for(col.name, lo), value_for(col.name, hi)
                if vlo is not None:
                    hints.append((to_source[col.name], "ge", vlo))
                if vhi is not None:
                    hints.append((to_source[col.name], "le", vhi))
            continue
        if e.name not in flip:
            continue
        a, b = e.args
        if isinstance(a, ir.ColumnRef) and isinstance(b, ir.Literal):
            col, lit, op = a, b, e.name
        elif isinstance(b, ir.ColumnRef) and isinstance(a, ir.Literal):
            col, lit, op = b, a, flip[e.name]
        else:
            continue
        if col.name not in to_source:
            continue
        v = value_for(col.name, lit)
        if v is not None:
            hints.append((to_source[col.name], op, v))
    return hints or None


class StreamingExecutor:
    """Host driver loop over device page batches (reference Driver +
    TaskExecutor collapsed: one Python loop, kernels stay on device)."""

    def __init__(
        self,
        catalog,
        batch_rows: int = 1 << 20,
        memory_budget: Optional[int] = None,
        collector=None,
        query_id: str = "",
        worker_pool=None,
        spill_space=None,
    ):
        self.catalog = catalog
        self.batch_rows = batch_rows
        self.query_id = query_id or f"local-{id(self):x}"
        # parent mirroring: on a worker, executor-held bytes show up in
        # the WorkerMemoryPool's execution ledger (/v1/memory)
        self.pool = MemoryPool(
            memory_budget, name=self.query_id, parent=worker_pool,
            query_id=self.query_id,
        )
        self.local = Executor(catalog, collector=collector)
        self.collector = collector
        # dynamic filters are shared with the delegate executor: joins
        # publish there, and scans/filters running through exec_node
        # consume the same registry (exec/dynfilter.py)
        self.dyn_ctx = self.local.dyn_ctx
        # which operators offloaded to host this query (tests/EXPLAIN assert
        # the spill path actually fired; reference: OperatorStats spill
        # counters)
        self.spill_events: List[str] = []
        # degradation-ladder observability (EXPLAIN ANALYZE memory line):
        # disk bytes written, hybrid-join partition count / recursion
        # depth, and chunk-loop fallbacks (all-ties / depth exhausted)
        self.spill_stats: Dict[str, int] = {
            "disk_bytes": 0,
            "hybrid_parts": 0,
            "hybrid_depth": 0,
            "chunk_fallbacks": 0,
            # ragged paged partition layout (ops/ragged.py): pages
            # allocated and live-slot occupancy percent for the last
            # hybrid join's build partitions
            "ragged_pages": 0,
            "ragged_occupancy_pct": 0,
            # batches the aggregate sink routed through the hash-slot
            # group-by instead of the sort composition
            "agg_hash_batches": 0,
        }
        self._spill_space = spill_space
        self._owns_spill = spill_space is None

    def _spill(self):
        """Lazily opened spill space (exec/spillspace.py): disk-tier
        quota accounting + guaranteed file cleanup at run() end (owned
        spaces) or task end (worker-provided spaces)."""
        if self._spill_space is None:
            from .spillspace import SPILL_MANAGER

            self._spill_space = SPILL_MANAGER.open(self.query_id)
            self._owns_spill = True
        return self._spill_space

    def _spill_share(self) -> int:
        """Device bytes one offloaded operator may hold at a time: half the
        budget remaining after resident reservations."""
        budget = self.pool.max_bytes or (1 << 62)
        return max((budget - self.pool.reserved) // 2, 1)

    def _collect_or_spill(self, child: N.PlanNode, tag: str):
        """Accumulate a child stream on device while the budget allows;
        past it — or when a revoke is pending — migrate everything to a
        SpilledRows store (host RAM, then the disk tier). Returns
        (first_batch, device_batches, held_bytes, spilled_or_None)."""
        from .spill import SpilledRows

        batches: List[Page] = []
        held = 0
        spilled = None
        first: Optional[Page] = None
        for b in self.stream(child):
            if first is None:
                first = b  # schema carrier for the all-empty case
            if int(b.count) == 0:
                continue
            nb = page_device_bytes(b)
            if spilled is None and self.pool.can_accumulate(held + nb):
                batches.append(b)
                held += nb
                self.pool.accumulated = held
                continue
            if spilled is None:
                self.spill_events.append(tag)
                spilled = SpilledRows(space=self._spill(), tag=tag)
                for p in batches:
                    spilled.append(p)
                batches = []
                self.pool.note_revoked(held)
                held = 0
                self.pool.accumulated = 0
            spilled.append(b)
        self.pool.accumulated = 0
        return first, batches, held, spilled

    # -- public --

    def run(self, node: N.PlanNode) -> Page:
        self.dyn_ctx.reset()  # filters are per-query state
        try:
            return self._run(node)
        finally:
            self.release_spill()

    def release_spill(self) -> None:
        """Guaranteed spill cleanup: fold disk-tier counters into the
        stats and unlink this query's spill files. Worker-provided spaces
        are released by the task's own finally (server/worker.py)."""
        if self._spill_space is not None:
            self.spill_stats["disk_bytes"] += self._spill_space.written
            self._spill_space.written = 0
            if self._owns_spill:
                self._spill_space.release()
                self._spill_space = None

    def rows(self, node: N.PlanNode) -> List[tuple]:
        return self.run(node).to_pylist()

    # -- top-level dispatch: sinks consume streams --

    def _run(self, node: N.PlanNode) -> Page:
        if isinstance(node, N.Output):
            return self.local.exec_node(node, self._run(node.child))
        if isinstance(node, N.Aggregate):
            return self._sink_aggregate(node)
        if isinstance(node, N.Distinct):
            return self._sink_distinct(node)
        if isinstance(node, N.TopN):
            return self._sink_topn(node)
        if isinstance(node, N.Limit):
            return self._sink_limit(node)
        if isinstance(node, N.Sort):
            return self._sink_sort(node)
        # everything else: materialize the stream
        return self._materialize(node)

    def _materialize(self, node: N.PlanNode) -> Page:
        pages: List[Page] = []
        first: Optional[Page] = None
        for p in self.stream(node):
            if first is None:
                first = p  # schema carrier for the all-empty case
            if int(p.count) > 0:
                pages.append(p)
        if not pages:
            return first
        if len(pages) == 1:
            return pages[0]
        return concat_pages(pages)

    # -- streaming core: generator of batches per node -----------------------

    def stream(self, node: N.PlanNode) -> Iterator[Page]:
        if isinstance(node, N.TableScan):
            yield from self._stream_scan(node)
        elif isinstance(node, N.Filter) and isinstance(node.child, N.TableScan):
            # predicate pushdown hint: simple conjuncts prune row groups /
            # partitions at the connector (reference TupleDomain pushdown);
            # the real filter kernel still runs on every delivered batch
            hints = _pushdown_hints(node.predicate, node.child)
            for batch in self._stream_scan(node.child, predicate=hints):
                yield self.local.exec_node(node, batch)
        elif isinstance(node, (N.Filter, N.Project, N.Unnest, N.Sample)):
            # all row-local and stateless: apply per batch (Unnest expands
            # within the batch, keeping the device-memory budget honest)
            for batch in self.stream(node.child):
                yield self.local.exec_node(node, batch)
        elif isinstance(node, N.Join) and node.kind in ("inner", "left") and not (
            node.kind == "left" and node.residual is not None
        ):
            yield from self._stream_join(node)
        elif isinstance(node, N.SemiJoin) and node.residual is None:
            yield from self._stream_semijoin(node)
        elif isinstance(node, N.ScalarApply):
            sub = self._run(node.subquery)
            for batch in self.stream(node.child):
                yield self.local.exec_node(node, batch, sub)
        elif isinstance(node, N.Union) and not node.distinct:
            first_names = None
            for child in node.children:
                for batch in self.stream(child):
                    if first_names is None:
                        first_names = batch.names
                    yield Page(batch.blocks, first_names, batch.count)
        elif isinstance(node, N.Window) and node.partition_exprs:
            yield from self._stream_window(node)
        elif isinstance(node, (N.Aggregate, N.Distinct, N.TopN, N.Limit, N.Sort)):
            # sink nodes reached mid-tree (e.g. Sort under the Project that
            # drops a hidden order channel) still go through their
            # budget-aware sinks, not the materializing fallback
            yield self._run(node)
        else:
            # window / outer compositions / distinct-union / exchanges:
            # materialize the subtree with the classic executor (its inputs
            # still stream where they can, via _run recursion)
            yield self._exec_fallback(node)

    def _exec_fallback(self, node: N.PlanNode) -> Page:
        pages = [self._run(c) for c in node.children]
        return self.local.exec_node(node, *pages)

    def _dyn_scan_hints(self, node: N.TableScan):
        """SPI pruning conjuncts from published dynamic filters (the
        scan-side half of dynamic filtering: connectors prune row groups /
        stripes before decode + upload)."""
        hints = []
        types = {ch: typ for ch, _col, typ in node.columns}
        for fid, ch, src_col, _apply in node.dynamic_filters:
            df = self.local.dyn_ctx.get(fid)
            if df is not None:
                try:
                    # the scan knows the channel's type — authoritative
                    # for wire-reconstructed (typeless) filters
                    hints.extend(df.spi_conjuncts(src_col, typ=types.get(ch)))
                except Exception:  # noqa: BLE001 — hints are best-effort
                    continue
        return hints

    def _scan_out(self, node: N.TableScan, page: Page) -> Page:
        """Post-scan dynamic mask for scans with no Filter above (the
        annotation's apply_mask entries); fused-into-Filter entries are
        applied by exec_node(Filter) downstream."""
        if node.dynamic_filters:
            return self.local._apply_scan_masks(node, page)
        return page

    def _stream_scan(self, node: N.TableScan, predicate=None) -> Iterator[Page]:
        # row_count is a planner ESTIMATE (statistics); drive the scan off
        # the actual batches until a short batch marks the end of the table
        est = self.catalog.row_count(node.table)
        B = self.batch_rows
        if node.dynamic_filters:
            dyn = self._dyn_scan_hints(node)
            if dyn:
                predicate = list(predicate or []) + dyn
        scan = getattr(self.catalog, "scan", None)
        if scan is None:
            yield self._scan_out(
                node, self._rename_scan(node, self.catalog.page(node.table))
            )
            return
        if est <= B // 2 and not predicate:
            try:
                src = self.catalog.page(node.table)
            except MemoryError:
                pass  # chunked catalogs refuse to materialize; stream below
            else:
                yield self._scan_out(node, self._rename_scan(node, src))
                return
        cols = [col for _, col, _ in node.columns]
        exact = getattr(self.catalog, "exact_row_count", None)
        total = exact(node.table) if exact is not None else None
        if total is None:
            # without an exact row count the short-batch heuristic is the
            # only end-of-table signal, and pruning may shorten any batch —
            # drop the (optional) hint rather than risk dropped rows
            predicate = None
        start = 0
        read_total = skipped_total = 0
        while True:
            src = scan(
                node.table, start, start + B, pad_to=B,
                columns=cols, predicate=predicate,
            )
            # connector pruning counters are per scan CALL; take the max
            # across batches — exact for partition pruning (every call sees
            # the full file set) and a per-batch high-water for stripe
            # pruning (each call only sees its range)
            skipped_total = max(
                skipped_total,
                getattr(self.catalog, "last_scan_files_skipped", 0) or 0,
            )
            read_total = max(
                read_total,
                getattr(self.catalog, "last_scan_files_read", 0) or 0,
            )
            n = int(src.count)
            if n > 0 or start == 0:
                yield self._scan_out(node, self._rename_scan(node, src))
            start += B
            done = (start >= total) if total is not None else (n < B)
            # n < B only marks table end without pruning (predicate hints
            # can legally shorten any batch)
            if done:
                # surface connector pruning in EXPLAIN ANALYZE (reference:
                # the hive split source reports skipped partitions)
                if skipped_total and self.collector is not None:
                    self.collector.stats_for(node).detail = (
                        f"files: {read_total} read, "
                        f"{skipped_total} pruned"
                    )
                return

    @staticmethod
    def _rename_scan(node: N.TableScan, src: Page) -> Page:
        blocks, names = [], []
        for ch, col, _typ in node.columns:
            blocks.append(src.block(col))
            names.append(ch)
        return Page(tuple(blocks), tuple(names), src.count)

    # -- joins ----------------------------------------------------------------

    def _collect_side(self, node: N.PlanNode):
        """Materialize a build side on device within budget; offload to a
        SpilledRows store (host RAM -> disk tier) when the budget runs
        out or a revoke is pending (HashBuilderOperator's
        revoke-to-spill)."""
        from .spill import SpilledRows

        batches: List[Page] = []
        held = 0
        spilled: Optional[SpilledRows] = None
        first: Optional[Page] = None
        for b in self.stream(node):
            if first is None:
                first = b
            if int(b.count) == 0:
                continue
            nb = page_device_bytes(b)
            if spilled is None and self.pool.can_accumulate(nb + held):
                batches.append(b)
                held += nb
                self.pool.accumulated = held
            else:
                if spilled is None:
                    self.spill_events.append("join_build")
                    spilled = SpilledRows(
                        space=self._spill(), tag="join_build"
                    )
                    for p in batches:
                        spilled.append(p)
                    batches = []
                    self.pool.note_revoked(held)
                    held = 0
                    self.pool.accumulated = 0
                spilled.append(b)
        self.pool.accumulated = 0
        if spilled is not None:
            return "spilled", spilled
        if not batches and first is not None:
            batches.append(first)  # keep schema carrier
        self.pool.reserve(held, "join build side")
        page = batches[0] if len(batches) == 1 else concat_pages(batches)
        return "device", (page, held)

    def _bucket_side_info(self, side: N.PlanNode):
        """(scan_node, wrappers, (bucket_cols, count)) when `side` is a
        Filter/Project chain over a TableScan of a BUCKETED table
        (reference: bucketed table detection feeding
        GROUPED_EXECUTION/Lifespan scheduling)."""
        wrappers = []
        n = side
        while isinstance(n, (N.Filter, N.Project)):
            wrappers.append(n)
            n = n.child
        if not isinstance(n, N.TableScan):
            return None
        bucketing = getattr(self.catalog, "bucketing", None)
        if bucketing is None:
            return None
        spec = bucketing(n.table)
        if spec is None:
            return None
        return n, tuple(reversed(wrappers)), spec

    def _grouped_join_spec(self, node: N.Join):
        """Detect a co-located bucket join: both sides bucketed with the
        same bucket count, and the equi-join keys are exactly the bucket
        columns (single-column buckets — the common spec)."""
        li = self._bucket_side_info(node.left)
        ri = self._bucket_side_info(node.right)
        if li is None or ri is None:
            return None
        (lscan, lwrap, (lcols, lcount)) = li
        (rscan, rwrap, (rcols, rcount)) = ri
        if lcount != rcount or len(lcols) != 1 or len(rcols) != 1:
            return None

        lsrc = {ch: col for ch, col, _ in lscan.columns}
        rsrc = {ch: col for ch, col, _ in rscan.columns}
        # the two bucket columns must be PAIRED at the same equi-key index:
        # checking each side independently would co-locate rows by
        # DIFFERENT keys (round-4 advisor: a crossed multi-key join — left
        # bucketed by k2, right by j1, on k1=j1 and k2=j2 — put matching
        # rows in different buckets and silently dropped them)
        paired = any(
            isinstance(lk, ir.ColumnRef)
            and isinstance(rk, ir.ColumnRef)
            and lsrc.get(lk.name) == lcols[0]
            and rsrc.get(rk.name) == rcols[0]
            for lk, rk in zip(node.left_keys, node.right_keys)
        )
        if not paired:
            return None
        return (lscan, lwrap), (rscan, rwrap), lcount

    def _stream_side_bucket(
        self, scan_node: N.TableScan, wrappers, bucket: int
    ) -> Iterator[Page]:
        """Batches of ONE bucket of a side, with its Filter/Project chain
        re-applied per batch."""
        cols = [col for _, col, _ in scan_node.columns]
        for lo, hi in self.catalog.bucket_row_ranges(scan_node.table, bucket):
            for s in range(lo, hi, self.batch_rows):
                src = self.catalog.scan(
                    scan_node.table, s, min(s + self.batch_rows, hi),
                    columns=cols,
                )
                page = self._rename_scan(scan_node, src)
                for w in wrappers:
                    page = self.local.exec_node(w, page)
                yield page

    def _grouped_bucket_join(self, node: N.Join, spec) -> Iterator[Page]:
        """Bucket-at-a-time execution (reference Lifespan.driverGroup +
        PipelineExecutionStrategy.GROUPED_EXECUTION): bucket i's build and
        probe run end-to-end before bucket i+1, bounding resident HBM to
        one bucket's build side."""
        (lscan, lwrap), (rscan, rwrap), count = spec
        right_names = tuple(n for n, _ in node.right.fields)
        for b in range(count):
            build_batches = [
                p
                for p in self._stream_side_bucket(rscan, rwrap, b)
                if int(p.count) > 0
            ]
            if not build_batches:
                continue  # inner join: an empty build bucket matches nothing
            # a skewed bucket can still exceed the budget: probe it in
            # build sub-chunks (inner joins distribute over build chunks —
            # the same contract as the host-offload path)
            chunks: List[List[Page]] = [[]]
            held = 0
            for p in build_batches:
                nb = page_device_bytes(p)
                if chunks[-1] and not self.pool.can_reserve(held + nb):
                    chunks.append([])
                    held = 0
                chunks[-1].append(p)
                held += nb
            for chunk in chunks:
                build_page = (
                    chunk[0] if len(chunk) == 1 else concat_pages(chunk)
                )
                nb = page_device_bytes(build_page)
                self.pool.reserve(nb, f"bucket {b} build side")
                try:
                    yield from self._probe_stream(
                        node,
                        build_page,
                        right_names,
                        probe=self._stream_side_bucket(lscan, lwrap, b),
                    )
                finally:
                    self.pool.free(nb)

    def _index_join_spec(self, node: N.Join):
        """Index join (reference operator/index/ IndexLoader +
        IndexJoinOptimizer): when the build side is a bare TableScan of a
        connector that can serve point lookups on the single equi-key,
        fetch ONLY the build rows matching each probe batch's keys instead
        of scanning the build table."""
        if not isinstance(node.right, N.TableScan):
            return None
        if len(node.right_keys) != 1 or len(node.left_keys) != 1:
            return None
        rkey, lkey = node.right_keys[0], node.left_keys[0]
        if not isinstance(rkey, ir.ColumnRef) or not isinstance(
            lkey, ir.ColumnRef
        ):
            return None
        # block values are ENCODED (varchar = dictionary codes, date = day
        # offsets) — only integral keys survive the trip to remote SQL
        if not (T.is_integral(rkey.type) and T.is_integral(lkey.type)):
            return None
        scan = node.right
        src = {ch: col for ch, col, _ in scan.columns}
        col = src.get(rkey.name)
        supports = getattr(self.catalog, "supports_index", None)
        if col is None or supports is None or not supports(scan.table, col):
            return None
        # cost gate (reference IndexJoinOptimizer): point lookups beat a
        # build-side scan only when the build table is large relative to a
        # probe batch's worth of keys
        if self.catalog.row_count(scan.table) < 4 * self.batch_rows:
            return None
        return scan, col, lkey.name

    def _stream_index_join(self, node: N.Join, spec) -> Iterator[Page]:
        scan, index_col, probe_ch = spec
        right_names = tuple(n for n, _ in node.right.fields)
        cols = [col for _, col, _ in scan.columns]
        for batch in self.stream(node.left):
            blk = batch.block(probe_ch)
            m = int(batch.count)
            keys = np.asarray(blk.data[:m])
            if blk.valid is not None:
                keys = keys[np.asarray(blk.valid[:m])]
            keys = np.unique(keys)
            rows = self.catalog.index_lookup(
                scan.table, index_col, keys.tolist(), cols
            )
            build_page = self._rename_scan(scan, rows)
            yield from self._probe_stream(
                node, build_page, right_names, probe=iter([batch])
            )

    def _stream_join(self, node: N.Join) -> Iterator[Page]:
        if node.kind == "inner":
            idx = self._index_join_spec(node)
            if idx is not None:
                self.spill_events.append("index_join")
                yield from self._stream_index_join(node, idx)
                return
        # grouped execution covers INNER joins (a LEFT join with an empty
        # build bucket would need schema-only null extension)
        grouped = (
            self._grouped_join_spec(node) if node.kind == "inner" else None
        )
        if grouped is not None:
            self.spill_events.append("grouped_bucket_join")
            yield from self._grouped_bucket_join(node, grouped)
            return
        kind, side = self._collect_side(node.right)
        right_names = tuple(n for n, _ in node.right.fields)
        if kind == "device":
            right_page, held = side
            if getattr(node, "dynamic_filters", ()):
                # the build side is complete: derive + publish BEFORE the
                # probe stream's scan generators start pulling batches
                self.local._publish_dynamic_filters(node, right_page)
            try:
                yield from self._probe_stream(node, right_page, right_names)
            finally:
                self.pool.free(held)
            return
        # offloaded build: partitioned hybrid hash join — INNER only
        if node.kind != "inner":
            raise MemoryExceededError(
                "outer join build side exceeds the device budget "
                "(chunked execution covers inner joins)"
            )
        spilled = side
        if getattr(node, "dynamic_filters", ()):
            self._publish_host_filters(node, spilled)
        from .breaker import BREAKERS

        if BREAKERS.allow("hybrid_join") and not self._hybrid_unsafe_keys(
            node, spilled
        ):
            try:
                # partitioning + resident-build SETUP runs before the
                # probe stream is touched: a fault here falls back
                # CLEANLY to the chunked path (no probe page consumed or
                # acked, no row emitted). Spill-tier errors stay fatal —
                # retrying cannot outrun a quota or a corrupt file.
                setup = self._hybrid_setup(node, spilled)
            except MemoryExceededError:
                raise
            except Exception as exc:  # noqa: BLE001 - degrade, don't fail
                from .spillspace import SpillError

                if isinstance(exc, SpillError):
                    raise
                BREAKERS.record_failure("hybrid_join", repr(exc))
            else:
                # once the probe pass starts its pages may be consumed
                # (and exchange-acked): no silent fallback — a fault
                # propagates, the breaker records it, and the NEXT
                # attempt takes the chunked path
                try:
                    yield from self._hybrid_hash_join(
                        node, spilled, right_names, setup
                    )
                except (MemoryExceededError, GeneratorExit):
                    raise
                except Exception as exc:  # noqa: BLE001
                    from .spillspace import SpillError

                    if not isinstance(exc, SpillError):
                        BREAKERS.record_failure("hybrid_join", repr(exc))
                    raise
                BREAKERS.record_success("hybrid_join")
                return
        self.spill_stats["chunk_fallbacks"] += 1
        yield from self._chunked_host_join(node, spilled, right_names)

    def _hybrid_unsafe_keys(self, node: N.Join, spilled) -> bool:
        """Hash partitioning requires build/probe key hashes to agree for
        equal VALUES. Varchar keys used to be routed to the chunked path
        categorically (dictionary codes hash per-table); PR 11 rehashes
        them by dictionary VALUE (ops/hashing.hash_rows_values), so
        varchar equi-joins take the partitioned/kernel path whenever the
        build-side dictionaries admit the one-time value-hash pass. Only
        a dictionary beyond PRESTO_TPU_VALUE_HASH_MAX_DICT (or one we
        cannot inspect) still forces the chunked path.

        Scope: only BUILD-side dictionaries are inspectable before the
        probe stream starts. A probe batch arriving later with an
        over-cap dictionary still hashes CORRECTLY (hash_rows_values
        computes whatever value table it needs, cached per dict_id) —
        the cap bounds predictable cost, it is not a correctness gate."""
        if not any(
            isinstance(getattr(k, "type", None), T.VarcharType)
            for k in tuple(node.left_keys) + tuple(node.right_keys)
        ):
            return False
        from ..expr.compiler import evaluate
        from ..ops.hashing import value_hashable

        try:
            sample = spilled.take_page(
                np.arange(min(spilled.num_rows, 1))
            )
            keys = [evaluate(e, sample) for e in node.right_keys]
        except Exception as exc:  # noqa: BLE001 — uninspectable: chunked
            self.spill_events.append(f"hybrid_varchar_probe_failed:{exc!r}")
            return True
        return not value_hashable(keys)

    def _chunked_host_join(self, node: N.Join, spilled, right_names):
        """Legacy offloaded-build execution (the hybrid join's circuit-
        breaker fallback): upload budget-sized build chunks, re-stream the
        whole probe against each (inner joins distribute over build
        chunks)."""
        share = self._spill_share()
        rows_per_chunk = max(int(share // max(spilled.row_bytes, 1)), 1)
        n = spilled.num_rows
        for start in range(0, max(n, 1), rows_per_chunk):
            stop = min(start + rows_per_chunk, n)
            chunk = spilled.take_page(np.arange(start, max(stop, start)))
            nb = page_device_bytes(chunk)
            self.pool.reserve(nb, "join build chunk")
            try:
                yield from self._probe_stream(node, chunk, right_names)
            finally:
                self.pool.free(nb)

    def _hybrid_partition_count(self, total_bytes: int, share: int,
                                cap: int = 64, node=None) -> int:
        import os

        env = int(os.environ.get("PRESTO_TPU_HYBRID_JOIN_PARTS", "0"))
        if env > 0:
            return env  # manual override beats both heuristics
        # 2x headroom per partition (arXiv:2112.02480: over-partitioning
        # is cheap, under-partitioning forces recursion)
        P = min(max(-(-total_bytes * 2 // max(share, 1)), 2), cap)
        if node is not None:
            P = self._hybrid_history_parts(node, P, cap)
        return P

    def _hybrid_history_parts(self, node: N.Join, P: int, cap: int) -> int:
        """History-based sizing (plan/history.py): a join frame that
        previously recursed with P0 partitions wants ~P0 * 2^depth up
        front — recursion repartitions the SAME rows on fresh hash bits,
        so pre-scaling buys the one-pass layout the byte estimate
        undersized. Never shrinks below the byte-derived count."""
        try:
            from ..plan.history import HISTORY, feedback_on, fingerprint

            if not feedback_on():
                return P
            ent = HISTORY.lookup(fingerprint(node), self.catalog)
        except Exception as exc:  # noqa: BLE001 — degrade, don't fail
            from .breaker import BREAKERS

            BREAKERS.record_failure("adaptive_plan", repr(exc))
            return P
        if ent is None or not ent.hybrid_parts:
            return P
        want = ent.hybrid_parts << max(int(ent.hybrid_depth), 0)
        return min(max(P, want), cap)

    def _hybrid_setup(self, node: N.Join, spilled) -> dict:
        """Eager setup phase of the hybrid hash join: hash-partition the
        build side, choose the resident set, and upload + build it. Runs
        BEFORE the probe stream is touched, so a fault here can fall back
        to the chunked path cleanly (nothing consumed, nothing acked,
        nothing emitted)."""
        import os

        from .spill import hash_partition_indices

        share = self._spill_share()
        row_b = max(spilled.row_bytes, 1)
        total_bytes = spilled.num_rows * row_b
        P = self._hybrid_partition_count(total_bytes, share, node=node)
        chunk_rows = max(share // (2 * row_b), 1 << 10)
        parts = hash_partition_indices(
            spilled, node.right_keys, P, chunk_rows, salt=0,
            value_safe=True,
        )
        # resident set: smallest partitions first, up to half the share
        # (the other half belongs to probe batches / output pages)
        resident: List[int] = []
        acc = 0
        for p in sorted(range(P), key=lambda q: len(parts[q])):
            nb = len(parts[p]) * row_b
            if len(parts[p]) and acc + nb <= share // 2:
                resident.append(p)
                acc += nb
        resident_set = frozenset(resident)
        deferred = [
            p for p in range(P)
            if p not in resident_set and len(parts[p])
        ]
        # ragged paged layout over the DEFERRED partitions (ops/ragged.py
        # — the ones handed to kernels later): skewed partitions allocate
        # unequal page counts instead of padding to the max, and the
        # occupancy lands in EXPLAIN ANALYZE's memory line. The layout
        # TAKES OVER the deferred row-id arrays (their `parts` entries
        # are dropped) so the memory-pressure path holds one copy, not
        # two; resident partitions never need pages.
        from ..ops import ragged as _ragged

        deferred_set = frozenset(deferred)
        rp = _ragged.from_partitions(
            [
                parts[p] if p in deferred_set else np.empty(0, np.int64)
                for p in range(P)
            ]
        )
        for p in deferred:
            parts[p] = None  # owned by the ragged layout now
        self.spill_stats["ragged_pages"] += rp.num_pages
        if rp.num_pages:
            self.spill_stats["ragged_occupancy_pct"] = int(
                rp.occupancy() * 100
            )
        bs_mem = None
        mem_held = 0
        if resident:
            idx = np.concatenate([parts[p] for p in sorted(resident)])
            mem_page = spilled.take_page(idx)
            mem_held = page_device_bytes(mem_page)
            self.pool.reserve(mem_held, "hybrid join resident build")
            try:
                bs_mem = build(mem_page, node.right_keys)
            except BaseException:
                self.pool.free(mem_held)
                raise
        res_np = np.zeros(P, np.bool_)
        res_np[resident] = True
        return {
            "P": P,
            "chunk_rows": chunk_rows,
            "parts": parts,
            "ragged": rp,
            "deferred": deferred,
            "bs_mem": bs_mem,
            "mem_held": mem_held,
            "res_np": res_np,
            "max_depth": int(
                os.environ.get("PRESTO_TPU_HYBRID_JOIN_MAX_DEPTH", "3")
            ),
        }

    def _hybrid_hash_join(self, node: N.Join, spilled, right_names, setup):
        """Partitioned hybrid hash join over an offloaded build side
        (reference HashBuilderOperator SPILLING_INPUT +
        GenericPartitioningSpiller; design trade-offs per
        arXiv:2112.02480): hash-partition build AND probe, keep the
        partitions that fit on device and probe them in ONE pass over the
        probe stream, spill the rest of the probe, then join each
        deferred (build, probe) partition pair — recursively
        repartitioning oversized partitions on fresh hash bits up to
        PRESTO_TPU_HYBRID_JOIN_MAX_DEPTH, after which an all-ties
        partition degrades to the chunked build loop."""
        from ..expr.compiler import evaluate
        from ..ops.filter import compact
        from ..ops.hashing import hash_rows_values
        from .spill import SpilledRows, hash_partition_indices, to_host_page

        P = setup["P"]
        chunk_rows = setup["chunk_rows"]
        parts = setup["parts"]
        deferred = setup["deferred"]
        bs_mem = setup["bs_mem"]
        mem_held = setup["mem_held"]
        max_depth = setup["max_depth"]
        self.spill_events.append("hybrid_hash_join")
        self.spill_stats["hybrid_parts"] = max(
            self.spill_stats["hybrid_parts"], P
        )
        depth_before = self.spill_stats["hybrid_depth"]
        res_lut = jnp.asarray(setup["res_np"])
        probe_spill = (
            SpilledRows(space=self._spill(), tag="hybrid_probe")
            if deferred else None
        )
        preprobe = getattr(node, "dynamic_filters", ()) and any(
            not consumed for _f, _i, consumed in node.dynamic_filters
        )
        first_probe: Optional[Page] = None
        yielded = False
        try:
            # ONE pass over the probe: resident partitions join now,
            # deferred partitions' rows spill alongside the build
            for batch in self.stream(node.left):
                if preprobe:
                    batch = self.local._apply_preprobe(node, batch)
                if first_probe is None:
                    first_probe = batch
                keys = [evaluate(e, batch) for e in node.left_keys]
                # value-safe: must agree with the build-side partitioning
                # for equal VALUES (varchar dictionaries differ per side)
                h = hash_rows_values(keys)
                part = (h % jnp.uint64(P)).astype(jnp.int32)
                live = batch.live_mask()
                if bs_mem is not None:
                    mem_batch = compact(batch, res_lut[part] & live)
                    if int(mem_batch.count) > 0:
                        for out in self._probe_with(
                            node, bs_mem, right_names, iter([mem_batch])
                        ):
                            yielded = True
                            yield out
                if probe_spill is not None:
                    d_batch = compact(batch, (~res_lut[part]) & live)
                    if int(d_batch.count) > 0:
                        probe_spill.append(to_host_page(d_batch))
        finally:
            if mem_held:
                self.pool.free(mem_held)
        bs_mem = None
        if probe_spill is not None and probe_spill.num_rows:
            pparts = hash_partition_indices(
                probe_spill, node.left_keys, P, chunk_rows, salt=0,
                value_safe=True,
            )
            ragged = setup["ragged"]
            for p in deferred:
                if not len(pparts[p]):
                    continue
                for out in self._join_partition(
                    node, spilled.subset(ragged.part_rows(p)),
                    probe_spill.subset(pparts[p]), right_names, 0,
                    chunk_rows, max_depth,
                ):
                    yielded = True
                    yield out
        if not yielded and first_probe is not None:
            # schema carrier: join one probe batch against an empty build
            # so downstream sinks always see the output schema. A probe
            # stream that yielded NOTHING (possible for an exchange source
            # whose producer finished empty) has no carrier to offer —
            # and nothing downstream to feed either.
            empty = spilled.take_page(np.empty(0, np.int64))
            yield from self._probe_with(
                node, build(empty, node.right_keys), right_names,
                iter([first_probe]),
            )
        self._record_hybrid_outcome(node, P, depth_before)

    def _record_hybrid_outcome(self, node: N.Join, P: int,
                               depth_before: int) -> None:
        """Remember how this join frame actually partitioned (the
        feedback half of _hybrid_history_parts). spill_stats tracks the
        query-wide max depth, so only depth growth since THIS join
        started is attributable to it."""
        try:
            from ..plan.history import HISTORY, feedback_on, fingerprint
            from .qcache import plan_tables

            if not feedback_on():
                return
            d = self.spill_stats["hybrid_depth"]
            HISTORY.record(
                fingerprint(node), catalog=self.catalog,
                tables=plan_tables(node),
                hybrid=(P, d - depth_before if d > depth_before else 0),
                kind="Join",
            )
        except Exception as exc:  # noqa: BLE001 — bookkeeping only
            from .breaker import BREAKERS

            BREAKERS.record_failure("adaptive_plan", repr(exc))

    def _join_partition(self, node: N.Join, build_sub, probe_sub,
                        right_names, depth: int, chunk_rows: int,
                        max_depth: int):
        """Join one deferred (build, probe) partition pair: upload the
        build whole when it fits, recursively repartition on fresh hash
        bits when it doesn't, and fall back to the chunked build loop
        when partitioning stops making progress (all-ties keys) or the
        depth bound is hit."""
        from .spill import hash_partition_indices

        share = self._spill_share()
        row_b = max(build_sub.row_bytes, 1)
        bbytes = build_sub.num_rows * row_b
        if bbytes * 2 <= share or build_sub.num_rows <= 1:
            page = build_sub.take_page(np.arange(build_sub.num_rows))
            nb = page_device_bytes(page)
            self.pool.reserve(nb, "hybrid join partition build")
            try:
                bs = build(page, node.right_keys)
                yield from self._probe_with(
                    node, bs, right_names,
                    self._spilled_pages(probe_sub, chunk_rows),
                )
            finally:
                self.pool.free(nb)
            return
        if depth < max_depth:
            P2 = self._hybrid_partition_count(bbytes, share, cap=16)
            salt = 7 * (depth + 1)  # fresh hash bits each level
            bparts = hash_partition_indices(
                build_sub, node.right_keys, P2, chunk_rows, salt=salt,
                value_safe=True,
            )
            if max(len(i) for i in bparts) < build_sub.num_rows:
                # made progress: recurse on each co-partition pair
                self.spill_stats["hybrid_depth"] = max(
                    self.spill_stats["hybrid_depth"], depth + 1
                )
                pparts = hash_partition_indices(
                    probe_sub, node.left_keys, P2, chunk_rows, salt=salt,
                    value_safe=True,
                )
                for p in range(P2):
                    if len(bparts[p]) and len(pparts[p]):
                        yield from self._join_partition(
                            node, build_sub.subset(bparts[p]),
                            probe_sub.subset(pparts[p]), right_names,
                            depth + 1, chunk_rows, max_depth,
                        )
                return
        # all-ties partition (one key value defeats every hash) or depth
        # exhausted: inner joins distribute over build chunks
        self.spill_stats["chunk_fallbacks"] += 1
        rows_per = max(int((share // 2) // row_b), 1)
        n = build_sub.num_rows
        for s in range(0, n, rows_per):
            page = build_sub.take_page(np.arange(s, min(s + rows_per, n)))
            nb = page_device_bytes(page)
            self.pool.reserve(nb, "hybrid join build chunk")
            try:
                bs = build(page, node.right_keys)
                yield from self._probe_with(
                    node, bs, right_names,
                    self._spilled_pages(probe_sub, chunk_rows),
                )
            finally:
                self.pool.free(nb)

    @staticmethod
    def _spilled_pages(spilled, chunk_rows: int):
        """Device pages of a spilled store, chunk-by-chunk."""
        n = spilled.num_rows
        step = max(chunk_rows, 1)
        for start in range(0, n, step):
            yield spilled.take_page(np.arange(start, min(start + step, n)))

    def _publish_host_filters(self, node: N.Join, spilled) -> None:
        """Derive filters from an offloaded build side (numpy columns,
        host or disk tier; the spilled-build analog of
        _publish_dynamic_filters)."""
        from ..expr import ir as _ir
        from .breaker import BREAKERS
        from .dynfilter import HostFilterAccumulator, filter_from_summary

        if not self.local._dyn_enabled() or not self.local._dyn_worthwhile(
            node
        ):
            return
        for fid, i, _c in node.dynamic_filters:
            key = node.right_keys[i]
            df = None
            try:
                acc = HostFilterAccumulator(key.name)
                key_type = None
                for chunk in spilled.iter_host_chunks():
                    if not isinstance(key, _ir.ColumnRef) or (
                        key.name not in chunk.names
                    ):
                        acc = None
                        break
                    idx = chunk.names.index(key.name)
                    key_type = chunk.types[idx]
                    acc.add_numpy(
                        chunk.columns[idx], chunk.valids[idx], key_type
                    )
                if acc is None:
                    continue
                df = filter_from_summary(acc.summary(), key_type)
            except Exception as exc:  # noqa: BLE001 — degrade, don't fail
                BREAKERS.record_failure("dynamic_filter", repr(exc))
                return
            if df is not None:
                BREAKERS.record_success("dynamic_filter")
                self.local.dyn_ctx.publish(fid, df)

    def _probe_stream(
        self, node: N.Join, right_page: Page, right_names, probe=None
    ) -> Iterator[Page]:
        bs = build(right_page, node.right_keys)
        preprobe = getattr(node, "dynamic_filters", ()) and any(
            not consumed for _f, _i, consumed in node.dynamic_filters
        )

        def batches():
            for batch in (
                probe if probe is not None else self.stream(node.left)
            ):
                if preprobe:
                    yield self.local._apply_preprobe(node, batch)
                else:
                    yield batch

        yield from self._probe_with(node, bs, right_names, batches())

    def _probe_with(
        self, node: N.Join, bs, right_names, batches
    ) -> Iterator[Page]:
        """Probe pre-filtered batches against a prepared BuildSide (the
        shared probe loop of the device, chunked, and hybrid join paths)."""
        for batch in batches:
            if node.unique_build:
                out = join_n1(
                    batch, bs, node.left_keys, right_names, right_names,
                    kind=node.kind,
                )
            else:
                cap = round_capacity(max(int(batch.count), 1))
                while True:
                    out, overflow = join_expand(
                        batch,
                        bs,
                        node.left_keys,
                        batch.names,
                        [(nm, nm) for nm in right_names],
                        out_capacity=cap,
                        kind=node.kind,
                    )
                    if int(overflow) == 0:
                        break
                    cap = round_capacity(cap + int(overflow))
            if node.residual is not None:
                out = filter_page(out, node.residual)
            yield self.local._shrink(out)

    def _stream_window(self, node: N.Window) -> Iterator[Page]:
        """Partitioned window under the budget: if the input fits, one
        device window kernel; otherwise partition-chunked execution — rows
        hash-bucketed on the PARTITION BY keys (a window function never
        reads across partitions), one device window kernel per bucket
        (reference: grouped execution via Lifespan + the spilling
        WindowOperator). Output keeps within-bucket (partition, order)
        ordering; bucket order is a hash order, which the SQL contract
        allows (a Sort node above imposes any required final order)."""
        from .spill import hash_partition_indices

        first, batches, held, spilled = self._collect_or_spill(
            node.child, "window"
        )
        if spilled is None:
            if not batches:
                yield self.local.exec_node(node, first)
                return
            self.pool.reserve(held, "window input")
            try:
                acc = batches[0] if len(batches) == 1 else concat_pages(batches)
                yield self.local.exec_node(node, acc)
            finally:
                self.pool.free(held)
            return
        chunk_rows = max(self._spill_share() // spilled.row_bytes, 1 << 10)
        num_parts = max(-(-spilled.num_rows // chunk_rows), 2)
        for idx in hash_partition_indices(
            spilled, node.partition_exprs, num_parts, chunk_rows
        ):
            if not len(idx):
                continue
            page = spilled.take_page(idx)
            nb = page_device_bytes(page)
            self.pool.reserve(nb, "window partition bucket")
            try:
                yield self.local.exec_node(node, page)
            finally:
                self.pool.free(nb)

    def _stream_semijoin(self, node: N.SemiJoin) -> Iterator[Page]:
        source = self._run(node.source)
        if getattr(node, "dynamic_filters", ()):
            self.local._publish_dynamic_filters(node, source)
        preprobe = getattr(node, "dynamic_filters", ()) and any(
            not consumed for _f, _i, consumed in node.dynamic_filters
        )
        held = self.pool.reserve(page_device_bytes(source), "semijoin source")
        try:
            bs = build(source, node.source_keys)
            for batch in self.stream(node.child):
                if preprobe:
                    batch = self.local._apply_preprobe(node, batch)
                if node.mark is not None:
                    from ..ops.join import semi_match_mask

                    mask = semi_match_mask(batch, bs, node.probe_keys)
                    yield self.local._attach_mark(batch, mask, node.mark)
                    continue
                out = join_n1(
                    batch, bs, node.probe_keys, [], [],
                    kind="anti" if node.anti else "semi",
                )
                yield self.local._shrink(out)
        finally:
            self.pool.free(held)

    # -- sinks ----------------------------------------------------------------

    def _hash_agg_attempt(
        self, page: Page, group_exprs, group_names, aggs, mask
    ) -> Optional[Page]:
        """Hash-slot grouped aggregation attempt for the streaming sink's
        partial/merge passes (ops/pallas_groupby.maybe_grouped_aggregate_hash
        behind the pallas_groupby_hash breaker); None falls back to the
        sort composition. Output schema matches grouped_aggregate_sorted,
        so partial pages from both strategies merge freely."""
        from ..ops.pallas_groupby import maybe_grouped_aggregate_hash
        from .breaker import BREAKERS

        if not BREAKERS.allow("pallas_groupby_hash"):
            return None
        try:
            out = maybe_grouped_aggregate_hash(
                page, group_exprs, group_names, aggs, mask
            )
        except Exception as exc:  # noqa: BLE001 — degrade, don't fail
            BREAKERS.record_failure("pallas_groupby_hash", repr(exc))
            return None
        if out is not None:
            BREAKERS.record_success("pallas_groupby_hash")
            self.spill_stats["agg_hash_batches"] += 1
        return out

    def _agg_input_stream(self, node: N.Aggregate) -> Iterator[Page]:
        """Child batches for a (possibly filter-fused) aggregation; a fused
        mask over a direct table scan still pushes pruning hints down."""
        if node.mask is not None and isinstance(node.child, N.TableScan):
            return self._stream_scan(
                node.child, predicate=_pushdown_hints(node.mask, node.child)
            )
        return self.stream(node.child)

    def _sink_aggregate(self, node: N.Aggregate) -> Page:
        try:
            partial, final, post = decompose_partial(node.aggs)
        except KeyError:
            # non-decomposable (min_by/max_by): aggregate the materialized
            # input in one pass (same choice the fragmenter makes)
            return self._exec_fallback(node)
        if not node.group_exprs:
            partials: List[Page] = []
            for batch in self._agg_input_stream(node):
                partials.append(global_aggregate(batch, partial, node.mask))
            acc = concat_pages(partials)
            out = global_aggregate(acc, final)
            return apply_avg_post(out, node.aggs, post)

        group_refs = tuple(
            ir.ColumnRef(nm, e.type)
            for nm, e in zip(node.group_names, node.group_exprs)
        )
        state: Optional[Page] = None
        state_held = 0
        merge_rows = max(self.batch_rows // 2, 1 << 14)
        pending: List[Page] = []
        pending_rows = 0
        spilled = None  # SpilledRows of partial-state pages

        def merge(parts: List[Page], bound: int) -> Page:
            acc = parts[0] if len(parts) == 1 else concat_pages(parts)
            out = self._hash_agg_attempt(
                acc, group_refs, node.group_names, final, None
            )
            if out is not None:
                return self.local._shrink(out)
            mg = round_capacity(min(max(bound, 1), 1 << 22))
            while True:
                out = grouped_aggregate_sorted(
                    acc, group_refs, node.group_names, final, mg
                )
                true_groups = int(out.count)
                if true_groups <= mg:
                    break
                mg = round_capacity(true_groups)
            return self.local._shrink(out)

        def spill_all(pages: List[Page]) -> None:
            """Move partial-state pages to the spill store (re-finalizable:
            `final` over partial columns is idempotent, so spilled merged
            state and raw partials share one schema)."""
            nonlocal spilled
            from .spill import SpilledRows

            if spilled is None:
                self.spill_events.append("aggregate")
                spilled = SpilledRows(space=self._spill(), tag="aggregate")
            for p in pages:
                if int(p.count) > 0 or spilled.num_rows == 0:
                    spilled.append(p)

        # state_held rotates through the loop; the finally releases
        # whatever is still reserved when a kernel faults or a
        # MemoryExceededError fires mid-stream (found by prestolint
        # memory-accounting: a leaked reservation here permanently
        # shrinks the worker's admission budget until task cleanup).
        # Normal paths zero state_held as they free so the finally is a
        # no-op for them.
        try:
            for batch in self._agg_input_stream(node):
                part = self._hash_agg_attempt(
                    batch, node.group_exprs, node.group_names, partial,
                    node.mask,
                )
                if part is None:
                    mg = round_capacity(
                        min(max(int(batch.count), 1), 1 << 16)
                    )
                    while True:
                        part = grouped_aggregate_sorted(
                            batch, node.group_exprs, node.group_names,
                            partial, mg, node.mask,
                        )
                        if int(part.count) <= mg:
                            break
                        mg = round_capacity(int(part.count))
                part = self.local._shrink(part)
                if spilled is not None:
                    spill_all([part])
                    continue
                pending.append(part)
                pending_rows += int(part.count)
                pending_bytes = sum(page_device_bytes(p) for p in pending)
                self.pool.accumulated = pending_bytes
                if pending_rows >= merge_rows or not self.pool.can_accumulate(
                    pending_bytes
                ):
                    parts = ([state] if state is not None else []) + pending
                    new_state = merge(parts, pending_rows + int(state.count if state is not None else 0))
                    self.pool.free(state_held)
                    state_held = 0
                    nb = page_device_bytes(new_state)
                    if self.pool.can_accumulate(nb):
                        state_held = self.pool.reserve(nb, "aggregation state")
                        state = new_state
                    else:
                        # group state outgrew the budget (or a revoke asked
                        # for it back): switch to spilling
                        # (SpillableHashAggregationBuilder.spillToDisk)
                        spill_all([new_state])
                        self.pool.note_revoked(nb)
                        state = None
                    pending = []
                    pending_rows = 0
                    self.pool.accumulated = 0
            self.pool.accumulated = 0
            if spilled is not None:
                spill_all(pending)
                return self._finalize_spilled_agg(
                    node, spilled, group_refs, final, post
                )
            # stream() always yields at least one batch: parts is non-empty
            parts = ([state] if state is not None else []) + pending
            est = sum(page_device_bytes(p) for p in parts)
            if not self.pool.can_reserve(est - state_held):
                # the final merged state itself would not fit: finish on
                # the spill path, which emits a host-backed result
                spill_all(parts)
                self.pool.free(state_held)
                state_held = 0
                return self._finalize_spilled_agg(
                    node, spilled, group_refs, final, post
                )
            out = merge(parts, pending_rows + int(state.count if state is not None else 0))
            self.pool.free(state_held)
            state_held = 0
            return apply_avg_post(out, node.aggs, post)
        finally:
            if state_held:
                self.pool.free(state_held)
            # pending partials are dropped with the exception — without
            # this the pool keeps reporting their bytes as revocable and
            # the revoking scheduler keeps picking a dead query whose
            # revoke can never complete
            self.pool.accumulated = 0

    def _finalize_spilled_agg(
        self, node: N.Aggregate, spilled, group_refs, final, post
    ) -> Page:
        """Final aggregation over host-spilled partial states: hash-
        partition rows by group key (equal keys share a partition), run the
        device final aggregation per partition, concatenate on the host.
        Skewed partitions re-partition recursively on fresh hash bits."""
        from .spill import (
            hash_partition_indices,
            host_concat_pages,
            to_host_page,
        )

        outs: List[Page] = []
        chunk_rows = max(self._spill_share() // spilled.row_bytes, 1 << 10)

        def finalize(sub, depth: int) -> None:
            n = sub.num_rows
            if n > chunk_rows and depth < 4:
                parts = max(-(-n // chunk_rows), 2)
                for idx in hash_partition_indices(
                    sub, group_refs, parts, chunk_rows, salt=13 * (depth + 1)
                ):
                    if len(idx):
                        finalize(sub.subset(idx), depth + 1)
                return
            # one partition's groups fit (or hashing cannot split further:
            # upload regardless and let the pool fail honestly)
            page = sub.take_page(np.arange(n))
            nb = page_device_bytes(page)
            self.pool.reserve(nb, "final aggregation partition")
            try:
                mg = round_capacity(max(int(page.count), 1))
                while True:
                    out = grouped_aggregate_sorted(
                        page, group_refs, node.group_names, final, mg
                    )
                    if int(out.count) <= mg:
                        break
                    mg = round_capacity(int(out.count))
                out = apply_avg_post(out, node.aggs, post)
                outs.append(to_host_page(out))
            finally:
                self.pool.free(nb)

        finalize(spilled, 0)
        return host_concat_pages(outs)

    def _sink_distinct(self, node: N.Distinct) -> Page:
        state: Optional[Page] = None
        for batch in self.stream(node.child):
            d = distinct_page(batch, batch.capacity)
            if state is None:
                state = d
            else:
                merged = concat_pages([state, d])
                state = distinct_page(merged, merged.capacity)
            state = self.local._shrink(state)
        return state if state is not None else next(self.stream(node.child))

    def _sink_topn(self, node: N.TopN) -> Page:
        state: Optional[Page] = None
        for batch in self.stream(node.child):
            t = top_n(batch, node.keys, node.count)
            if state is None:
                state = t
            else:
                state = top_n(concat_pages([state, t]), node.keys, node.count)
        return state if state is not None else next(self.stream(node.child))

    def _sink_limit(self, node: N.Limit) -> Page:
        got: List[Page] = []
        rows = 0
        for batch in self.stream(node.child):
            got.append(batch)
            rows += int(batch.count)
            if rows >= node.count:
                break  # short-circuit: stop pulling the scan
        if not got:
            got = [next(self.stream(node.child))]
        acc = got[0] if len(got) == 1 else concat_pages(got)
        return self.local._shrink(limit_page(acc, node.count))

    def _sink_sort(self, node: N.Sort) -> Page:
        """Full-table sort; beyond the budget it goes external: offload to
        host, range-partition on the first key, device-sort each range
        (spill.external_sort_chunks — the OrderByOperator-spill analog)."""
        from .spill import external_sort_chunks, host_concat_pages

        first, batches, held, spilled = self._collect_or_spill(
            node.child, "sort"
        )
        if spilled is None:
            if not batches:
                return sort_page(first, node.keys)
            self.pool.reserve(held, "sort input")
            try:
                acc = batches[0] if len(batches) == 1 else concat_pages(batches)
                return sort_page(acc, node.keys)
            finally:
                self.pool.free(held)
        chunk_rows = max(self._spill_share() // spilled.row_bytes, 1 << 10)
        chunks = external_sort_chunks(spilled, node.keys, chunk_rows, self.pool)
        return host_concat_pages(chunks)
