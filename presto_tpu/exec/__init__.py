"""Execution layer: plan -> kernel pipeline.

Equivalent of the reference's worker data plane (SURVEY.md §1 L6):
LocalExecutionPlanner compiling PlanFragments into operator pipelines
(presto-main/.../sql/planner/LocalExecutionPlanner.java:364) and the
Driver hot loop (operator/Driver.java:347-430). On TPU the "operators" are
whole-page kernels; the host walks the plan once per page-set and all
per-row work happens on device.
"""

from .executor import Executor  # noqa: F401
