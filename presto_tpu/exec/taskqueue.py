"""Multilevel feedback task scheduler — the worker's CPU-time fairness.

Re-designed equivalent of the reference's MultilevelSplitQueue +
TaskExecutor (presto-main/.../executor/MultilevelSplitQueue.java:34,
TaskExecutor.java): queries are binned into levels by ACCUMULATED
execution time, and scheduling targets a fixed utilization ratio
between adjacent levels (each level gets ~2x the time share of the next
slower one), so a fresh interactive query is never starved behind a
long-running scan.

TPU-first reduction: the reference time-slices thousands of splits
across a worker's cores; here a worker drives one device, so the
quantum is one streaming BATCH (the driver loop's natural yield point)
and the scheduler is a cooperative slot gate task threads pass through
between batches:

    with scheduler.quantum(query_id):
        page = next(stream)

Selection rule (MultilevelSplitQueue.pollSplit analog): among levels
with waiters, pick the one with the smallest scheduled_time/weight;
FIFO within a level. Weights halve per level, reproducing the
reference's LEVEL_CONTRIBUTION_CAP geometry.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

LEVEL_THRESHOLD_SECONDS = (0.0, 1.0, 10.0, 60.0, 300.0)
LEVEL_WEIGHTS = (16, 8, 4, 2, 1)


class MultilevelScheduler:
    def __init__(self, concurrency: int = 2):
        self._cv = threading.Condition()
        self._slots = max(1, int(concurrency))
        self._query_time: Dict[str, float] = {}
        self._level_time: List[float] = [0.0] * len(LEVEL_WEIGHTS)
        # FIFO arrival order: list of (query_id, ticket)
        self._waiting: List[Tuple[str, object]] = []
        self._running: Dict[object, Tuple[str, int, float]] = {}

    # -- level accounting --
    def level_of(self, query_id: str) -> int:
        t = self._query_time.get(query_id, 0.0)
        lev = 0
        for i, thr in enumerate(LEVEL_THRESHOLD_SECONDS):
            if t >= thr:
                lev = i
        return lev

    def _pick(self) -> Optional[object]:
        """Ticket to run next: level with min scheduled/weight, FIFO
        within the level. None when nothing waits."""
        if not self._waiting:
            return None
        best_lev, best_ratio = None, None
        by_level: Dict[int, object] = {}
        for qid, ticket in self._waiting:
            lev = self.level_of(qid)
            if lev not in by_level:
                by_level[lev] = ticket  # first-in at this level
        for lev, ticket in by_level.items():
            ratio = self._level_time[lev] / LEVEL_WEIGHTS[lev]
            if best_ratio is None or ratio < best_ratio:
                best_lev, best_ratio = lev, ratio
        return by_level[best_lev]

    # -- the gate --
    @contextmanager
    def quantum(self, query_id: str, max_wait: float = 2.0):
        """Slot gate around one batch of work.

        A quantum can BLOCK inside (a consumer task's next() waits on
        upstream pages) — the reference's blocked-split futures return
        their thread for that; the cooperative analog is a bounded
        wait: after `max_wait` the task proceeds WITHOUT a slot
        (bypass), so same-worker producer/consumer chains can never
        deadlock on the gate. Bypassed quanta still charge their time."""
        ticket = object()
        bypass = False
        deadline = time.perf_counter() + max_wait
        with self._cv:
            self._waiting.append((query_id, ticket))
            while not (self._slots > 0 and self._pick() is ticket):
                left = deadline - time.perf_counter()
                if left <= 0:
                    bypass = True
                    break
                self._cv.wait(timeout=left)
            self._waiting = [w for w in self._waiting if w[1] is not ticket]
            if not bypass:
                self._slots -= 1
            lev = self.level_of(query_id)
            self._running[ticket] = (
                query_id, lev, time.perf_counter(), bypass
            )
            # wake remaining waiters: with >1 slot the next-best ticket
            # can enter immediately (entry itself frees no slot to signal)
            self._cv.notify_all()
        try:
            yield
        finally:
            with self._cv:
                qid, lev, t0, byp = self._running.pop(ticket)
                dt = time.perf_counter() - t0
                self._query_time[qid] = self._query_time.get(qid, 0.0) + dt
                self._level_time[lev] += dt
                if not byp:
                    self._slots += 1
                self._cv.notify_all()

    # -- observability (system.runtime / tests) --
    def snapshot(self) -> dict:
        with self._cv:
            return {
                "levels": {
                    i: round(t, 6) for i, t in enumerate(self._level_time)
                },
                "queries": {
                    q: round(t, 6) for q, t in self._query_time.items()
                },
                "waiting": len(self._waiting),
                "running": len(self._running),
            }

    def charge(self, query_id: str, seconds: float) -> None:
        """Test/bookkeeping hook: attribute execution time directly.
        Books into the query's POST-update level — a bulk charge models
        time the query spent getting TO that level."""
        with self._cv:
            self._query_time[query_id] = (
                self._query_time.get(query_id, 0.0) + seconds
            )
            self._level_time[self.level_of(query_id)] += seconds
