"""Single-node plan executor.

The analog of the reference's LocalExecutionPlanner + Driver (SURVEY.md
§3.3): walks the PlanNode tree bottom-up, executing each node as one or a
few fused device kernels over capacity-padded Pages.

Design points (TPU-first):
* Static shapes with adaptive retry — joins whose candidate count exceeds
  the planned output capacity are re-run with doubled capacity (the
  reference instead grows pages dynamically; XLA needs detect-and-retry).
* Capacities are bucketed to powers of two (`round_capacity`) and pages are
  shrunk after selective operators, so recompilation is bounded
  (the reference's adaptive batch sizing in PageFunctionCompiler).
* The executor is host-driven and *adaptive*: it sees real row counts
  between kernels, picks build/probe strategies accordingly — the eager
  analog of Presto's cost-based decisions with perfect cardinalities.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from .. import types as T
from ..expr import ir
from ..ops.aggregate import global_aggregate, grouped_aggregate_sorted
from ..ops.filter import compact, filter_page
from ..ops.join import build, join_expand, join_n1
from ..ops.sort import distinct_page, limit_page, sort_page, top_n
from ..expr.compiler import project_page
from ..page import Block, Page, round_capacity
from ..plan import nodes as N


class ExecutionError(RuntimeError):
    pass


class Executor:
    def __init__(self, catalog, shrink: bool = True, jit: bool = True,
                 collector=None, pallas_groupby=None,
                 matmul_groupby=None):
        self.catalog = catalog
        self.shrink = shrink
        self.jit = jit
        # route eligible small-G aggregations through the Pallas kernel
        # (ops/pallas_groupby.py). None = auto: DEFAULT ON for TPU
        # backends — the reference's hot loop is its specialized group-by
        # (MultiChannelGroupByHash.java:54) and ours must be the Mosaic
        # path, not an opt-in — and OFF on CPU, where interpret mode
        # would crawl. The `pallas_groupby` session property forces
        # either way (resolved lazily so importing the executor never
        # initializes a backend).
        self.pallas_groupby = pallas_groupby
        # route eligible dense-key aggregations (G <= 4096) through the
        # one-hot-matmul MXU path (ops/matmul_agg.py) before falling back
        # to the sort strategy; same auto semantics as pallas_groupby
        self.matmul_groupby = matmul_groupby
        # (plan node, static params) -> jitted kernel. Backed by the
        # PROCESS-WIDE LRU (exec/qcache.KERNEL_CACHE) keyed additionally
        # on (backend, jit flag): back-to-back queries from different
        # sessions reuse traced executables — the analog of the reference
        # caching compiled PageProcessors per plan (LocalExecutionPlanner
        # compiles once, Drivers reuse), promoted cross-query.
        # PRESTO_TPU_COMPILE_CACHE_DIR additionally persists XLA
        # executables to disk so restarts warm-start.
        self._backend = None  # resolved lazily (never init jax at import)
        # kernels over time-/context-dependent expressions (now(), ...)
        # bake the value at TRACE time and must not outlive this
        # executor: they stay in a per-executor dict (the pre-PR-8
        # compile-once scope) instead of the process-wide cache
        self._local_kernels: Dict = {}
        self._det_keys: Dict = {}  # kernel key -> is-deterministic verdict
        # EXPLAIN ANALYZE support (exec/stats.py); None = no accounting
        self.collector = collector
        self._retries = 0  # adaptive-capacity re-runs since last snapshot
        # runtime dynamic filters (exec/dynfilter.py): per-query registry
        # of build-side summaries consumed by probe-side scans/filters
        from .dynfilter import DynamicFilterContext

        self.dyn_ctx = DynamicFilterContext()
        # session override (the `dynamic_filtering` session property);
        # PRESTO_TPU_DYNFILTER=0 disables engine-wide
        self.dynamic_filtering = True
        # TABLESAMPLE determinism: per-Sample-node running row offset
        # (streaming batches) + a per-worker/per-split salt set by the
        # fragment executors, mixed into the sample hash so positional
        # masks never repeat across batches/workers (ops/filter.py)
        self._sample_pos: Dict[int, int] = {}
        self.sample_salt = 0

    def _kernel(self, key, make_fn):
        """Compile-once cache for per-node kernels. jax.jit retraces per
        input shape bucket automatically; `key` carries the static config
        (the node itself plus capacity-like ints). The store is the
        process-wide bounded LRU in exec/qcache.py, keyed additionally on
        (backend, jit) — kernels close over plan-node config only, never
        the catalog, so cross-executor reuse is sound."""
        from .qcache import (
            KERNEL_CACHE,
            enable_persistent_compile_cache,
            plan_is_deterministic,
        )

        if self._backend is None:
            enable_persistent_compile_cache()
            self._backend = jax.default_backend()
        # determinism is static per key: memoize so the per-batch hot
        # path pays one dict probe, not a plan-subtree walk per call
        det = self._det_keys.get(key)
        if det is None:
            det = self._det_keys[key] = plan_is_deterministic(key)
        if not det:
            # now()/current_date/... are CONSTANTS baked at trace time:
            # sharing such a kernel across sessions would serve the
            # first trace's clock forever. Per-executor scope matches
            # the pre-cache behavior (one session reuses its own trace).
            fn = self._local_kernels.get(key)
            if fn is None:
                fn = self._build_kernel(make_fn)
                self._local_kernels[key] = fn
            return fn
        gkey = (self._backend, self.jit, key)
        fn = KERNEL_CACHE.get(gkey)
        if fn is None:
            fn = self._build_kernel(make_fn)
            KERNEL_CACHE.put(gkey, fn)
        return fn

    def _build_kernel(self, make_fn):
        """Cache-fill: jit (compilation itself is lazy, paid at the first
        call) and, when the observability plane is on, wrap in the
        compile-vs-execute profiler. The wrapper is stored in the cache
        so "first call" stays attached to the entry's lifetime; it is
        exception-transparent (the breaker protocol in _kernel_guarded
        classifies faults by the escaping exception)."""
        from ..obs.kernelprof import KERNEL_PROFILE, profiling_enabled

        fn = jax.jit(make_fn()) if self.jit else make_fn()
        if profiling_enabled():
            fn = KERNEL_PROFILE.wrap(fn)
        return fn

    def _kernel_guarded(self, breaker_name, key, make_fn, *args):
        """Run a jitted kernel under a kernel-fault circuit breaker
        (exec/breaker.py). The op layer consults `BREAKERS.allow(name)`
        at TRACE time to pick the experimental path vs. the safe XLA
        composition, so the breaker decision is part of the cache key.
        A fault records a failure and retries ONCE with the fallback
        FORCED — even when the breaker hasn't opened yet (streak below
        threshold, or PRESTO_TPU_BREAKER_DISABLE=1), the call that just
        faulted must still degrade rather than fail the query."""
        import contextlib

        from .breaker import BREAKERS

        for attempt in (0, 1):
            if attempt == 0:
                allowed = BREAKERS.allow(breaker_name)
                ctx = contextlib.nullcontext()
            else:
                allowed = False
                ctx = BREAKERS.forced_fallback(breaker_name)
            with ctx:
                try:
                    fn = self._kernel((key, breaker_name, allowed), make_fn)
                    out = fn(*args)
                except Exception as exc:
                    if attempt == 0 and allowed:
                        # the experimental path faulted: count it and
                        # retry on the forced fallback
                        BREAKERS.record_failure(breaker_name, repr(exc))
                        continue
                    if attempt:
                        # the FALLBACK failed right after the experimental
                        # path did: a semantic / user error, not a kernel
                        # fault — neutralize the breaker hit so a bad
                        # query can't degrade the kernel for the process
                        BREAKERS.record_success(breaker_name)
                    raise
            if allowed:
                BREAKERS.record_success(breaker_name)
            return out

    # -- public --
    def run(self, node: N.PlanNode) -> Page:
        self.dyn_ctx.reset()  # filters are per-query state
        page = self._run(node)
        return page

    def rows(self, node: N.PlanNode) -> List[tuple]:
        return self.run(node).to_pylist()

    # -- dispatch --
    def _run_children(self, node: N.PlanNode) -> List[Page]:
        """Execute a node's children — BUILD SIDE FIRST for dynamic-filter
        joins, so the derived filter is published before the probe side's
        scans run (the single-process analog of the reference's
        LocalDynamicFiltersCollector ordering)."""
        if (
            isinstance(node, (N.Join, N.SemiJoin))
            and getattr(node, "dynamic_filters", ())
        ):
            build = self._run(node.children[1])
            self._publish_dynamic_filters(node, build)
            probe = self._run(node.children[0])
            return [probe, build]
        return [self._run(c) for c in node.children]

    def _star_spec(self, node: N.PlanNode):
        """The inner Join of a fusable star shape: two stacked inner n1
        joins whose probe keys BOTH live on the shared fact side, no
        residuals — the multiway-probe shape of arXiv:1905.13376 (one
        pass over the fact resolves both dimensions; see
        ops/pallas_join.table_multiway_n1)."""
        from ..expr import ir as _ir
        from ..ops.pallas_join import pallas_join_mode

        if not isinstance(node, N.Join) or node.kind != "inner":
            return None
        if not node.unique_build or node.residual is not None:
            return None
        inner = node.left
        if not isinstance(inner, N.Join) or inner.kind != "inner":
            return None
        if not inner.unique_build or inner.residual is not None:
            return None
        fact_names = {n for n, _ in inner.left.fields}
        for k in node.left_keys:
            if not isinstance(k, _ir.ColumnRef) or k.name not in fact_names:
                return None
        if pallas_join_mode() == "off":
            return None
        from .breaker import BREAKERS

        if not (
            BREAKERS.allow("pallas_join_build")
            and BREAKERS.allow("pallas_join_probe")
        ):
            return None
        return inner

    def _run_star_join(self, node: N.Join, inner: N.Join) -> Page:
        """Fused multiway execution of a star pair; an ineligible side
        degrades to plain nested execution on the pages already run
        (materialized plan results — nothing is consume-once; the
        fact's preprobe re-application inside _exec_join is an
        idempotent re-filter)."""
        from ..ops.pallas_join import table_multiway_n1

        dim1 = self._run(inner.right)
        if getattr(inner, "dynamic_filters", ()):
            self._publish_dynamic_filters(inner, dim1)
        dim2 = self._run(node.right)
        if getattr(node, "dynamic_filters", ()):
            self._publish_dynamic_filters(node, dim2)
        fact = self._run(inner.left)
        if getattr(inner, "dynamic_filters", ()):
            fact = self._apply_preprobe(inner, fact)
        if getattr(node, "dynamic_filters", ()):
            fact = self._apply_preprobe(node, fact)
        bs1 = self._build_table_guarded(dim1, inner.right_keys)
        bs2 = self._build_table_guarded(dim2, node.right_keys)
        if bs1 is None or bs2 is None:
            mid = self._exec_join(inner, fact, dim1)
            return self._exec_join(node, mid, dim2)
        names1 = tuple(n for n, _ in inner.right.fields)
        names2 = tuple(n for n, _ in node.right.fields)
        try:
            out = table_multiway_n1(
                fact,
                (
                    (bs1, tuple(inner.left_keys), names1, names1),
                    (bs2, tuple(node.left_keys), names2, names2),
                ),
            )
        except Exception as exc:  # noqa: BLE001 — degrade, don't fail
            from .breaker import BREAKERS

            BREAKERS.record_failure("pallas_join_probe", repr(exc))
            mid = self._exec_join(inner, fact, dim1)
            return self._exec_join(node, mid, dim2)
        from .breaker import BREAKERS

        BREAKERS.record_success("pallas_join_probe")
        self._strategy_note(inner, "multiway-fused")
        self._strategy_note(
            node,
            f"multiway occ={int(bs1.occupancy() * 100)}%"
            f"/{int(bs2.occupancy() * 100)}%",
        )
        return self._shrink(out, node)

    def _run(self, node: N.PlanNode) -> Page:
        inner = self._star_spec(node)
        if inner is not None:
            if self.collector is None:
                return self._run_star_join(node, inner)
            import time

            from .stats import page_device_bytes

            t0 = time.perf_counter()
            out = self._run_star_join(node, inner)
            # fused execution: the outer node carries the pair's stats
            # (child scans/builds record their own rows via self._run)
            self.collector.record(
                node, time.perf_counter() - t0, [], out.count,
                page_device_bytes(out),
            )
            return out
        pages = self._run_children(node)
        if self.collector is None:
            return self.exec_node(node, *pages)
        import time

        from .stats import page_device_bytes

        sync = getattr(self.collector, "sync_counts", True)
        if sync:
            rows_in = sum(int(p.count) for p in pages)
        retries_before = self._retries
        t0 = time.perf_counter()
        out = self.exec_node(node, *pages)
        if sync:
            rows_out = int(out.count)  # blocks until the kernel finishes
        else:
            # keep row counts as device scalars — each int() here is a
            # blocking host round trip per plan node (the TPU_STATUS §4b
            # cost PR-1's _shrink already avoids); the collector resolves
            # them in one batch at query end. Wall then measures dispatch
            # + any syncs the node itself performs.
            rows_in = [p.count for p in pages]
            rows_out = out.count
        wall = time.perf_counter() - t0
        self.collector.record(
            node, wall, rows_in, rows_out, page_device_bytes(out),
            self._retries - retries_before,
        )
        return out

    def exec_node(self, node: N.PlanNode, *pages: Page) -> Page:
        """Apply one plan node to already-materialized input pages — the
        unit the distributed executor and the streaming driver both reuse."""
        method = getattr(self, f"_exec_{type(node).__name__.lower()}")
        return method(node, *pages)

    def _shrink(self, page: Page, node: "N.PlanNode" = None) -> Page:
        """Slice page capacity down to the live row count's bucket.

        Reading the count is a BLOCKING host sync — through the axon
        relay each one is a full tunnel round trip, and they were the
        dominant term in on-chip SQL wall time (TPU_STATUS §4b: ~5
        syncs ~= 2.5 s for a 14 ms aggregation). So the sync is only
        paid when shrinking can plausibly win: the page is big AND the
        CBO expects the live count to be well under capacity."""
        if not self.shrink:
            return page
        if page.capacity <= (1 << 14):
            return page  # too small for shrinking to pay for a sync
        if node is not None:
            est = self._est_rows(node)
            if est is not None and est >= 0.5 * page.capacity:
                return page  # expected near-full: skip the sync
        n = int(page.count)
        cap = round_capacity(max(n, 1))
        if cap >= page.capacity:
            return page
        idx = slice(0, cap)
        blocks = [b.take_rows(idx) for b in page.blocks]
        return Page(tuple(blocks), page.names, page.count)

    def _node_plan_stats(self, node):
        """Memoized full CBO PlanStats for a node (column min/max/NDV —
        the keypack planner's input). Same keying/bounding rules as
        _est_rows."""
        cache = getattr(self, "_ps_cache", None)
        if cache is None:
            from .qcache import LRUCache

            # bounded LRU, not clear-on-threshold: a long session crossing
            # the old wholesale clear() triggered a recompute storm over
            # every live plan's stats
            cache = self._ps_cache = LRUCache(
                max_entries=1024, name="plan_stats"
            )
        key = (node,) + self._est_env()
        hit = cache.get(key, count=False)
        if hit is not None:
            return hit[0]
        try:
            from ..plan.stats import derive

            ps = derive(node, self.catalog)
        except Exception:  # noqa: BLE001 — estimation is best-effort
            ps = None
        cache.put(key, (ps,))
        return ps

    def _est_env(self) -> tuple:
        """Environment half of the estimate-cache keys: the feedback
        store's generation (a history record/invalidation must never let
        a live executor keep serving estimates derived from superseded
        observations) plus the mesh width (a DistributedExecutor shares
        this object as its local delegate; per-shard sizing decisions
        must not alias across mesh shapes)."""
        from ..plan.history import plan_env_token

        return plan_env_token(), getattr(self, "mesh_n", 1)

    # -- composite-key packing (ops/keypack.py) --
    def _keypack_plan(self, node, keys, page: Page, equality_only=False,
                      allow_hashed=False, single_lane=False,
                      n_order_keys=0):
        """Choose a packing strategy for one order-sensitive node from the
        input page's blocks (types, nullability, dictionaries) plus the
        child's CBO column stats (sampled min/max tightens 64-bit keys;
        sampled lanes carry a runtime range check). Returns None when the
        keys don't pack — the node runs its legacy kernel."""
        from ..ops.keypack import (
            KeyInfo,
            key_info_from_block,
            keypack_enabled,
            plan_keypack,
        )
        from ..plan.stats import storage_bounds

        if not keypack_enabled():
            return None
        ps = self._node_plan_stats(node.children[0])
        infos = []
        for k in keys:
            e = getattr(k, "expr", k)
            typ = getattr(e, "type", None)
            if typ is None:
                return None
            if isinstance(e, ir.ColumnRef) and e.name in page.names:
                b = page.block(e.name)
                lo = hi = None
                if ps is not None:
                    bounds = storage_bounds(ps.column(e.name), b.type)
                    if bounds is not None:
                        lo, hi = bounds
                infos.append(key_info_from_block(b, lo=lo, hi=hi))
            else:
                infos.append(KeyInfo(type=typ, nullable=True))
        try:
            return plan_keypack(
                keys,
                infos,
                equality_only=equality_only,
                allow_hashed=allow_hashed,
                single_lane=single_lane,
                n_order_keys=n_order_keys,
            )
        except Exception:  # noqa: BLE001 — planning is best-effort
            return None

    def _run_packed(self, node, breaker_name: str, label: str, make_fn,
                    page: Page, plan):
        """Attempt one packed kernel behind its circuit breaker. Returns
        the output page, or None when the caller must run the legacy
        kernel (breaker open, kernel fault, or the plan's runtime range
        check tripped — sampled CBO bounds missed / a hash collided,
        which is expected adaptivity rather than a kernel fault)."""
        from .breaker import BREAKERS

        if not BREAKERS.allow(breaker_name):
            return None
        if plan.host_sort:
            # host-routed plans run numpy on the host. Commit
            # mesh-sharded pages (gathered from the distributed
            # executor) to one device first — cheap on the CPU backend,
            # and host-sort plans only exist there.
            page = self._commit_single_device(page)
        try:
            if plan.host_sort:
                # EAGER, never jitted: under jit the host step becomes a
                # jax.pure_callback, which deadlocks on the single-device
                # CPU runtime (main thread blocks synchronizing the
                # kernel while the callback thread starves — the PR 2
                # ORDER BY >= 14k wedge). Eagerly, ops/sort.py calls
                # numpy directly and there is nothing to deadlock; the
                # sort dominates the cost, so losing jit fusion of the
                # cheap pack arithmetic is noise.
                fn = make_fn()
            else:
                fn = self._kernel((node, label, plan), make_fn)
            out, ok = fn(page)
        except Exception as exc:  # noqa: BLE001 — degrade, don't fail
            BREAKERS.record_failure(breaker_name, repr(exc))
            return None
        if ok is not None and not bool(ok):
            self._strategy_note(
                node, f"keypack={plan.strategy}->legacy(range)"
            )
            return None
        BREAKERS.record_success(breaker_name)
        self._strategy_note(node, f"keypack={plan.strategy}")
        return out

    @staticmethod
    def _commit_single_device(page: Page) -> Page:
        """Move a page's arrays onto ONE device when any block is
        mesh-sharded. No-op for already-single-device pages."""
        try:
            multi = any(
                len(b.data.devices()) > 1 for b in page.blocks
            )
        except Exception:  # noqa: BLE001 — non-Array leaves: leave as-is
            return page
        if not multi:
            return page
        dev = jax.devices()[0]
        blocks = tuple(
            Block(
                jax.device_put(b.data, dev),
                b.type,
                None if b.valid is None else jax.device_put(b.valid, dev),
                b.dict_id,
            )
            for b in page.blocks
        )
        count = page.count
        if hasattr(count, "devices"):
            count = jax.device_put(count, dev)
        return Page(blocks, page.names, count)

    def _est_rows(self, node):
        """CBO row estimate for a node's output (cached per plan node).

        Keyed by the node OBJECT (kept referenced by the cache, so ids
        cannot be recycled mid-flight) and bounded by LRU eviction: a
        long-lived server session executes unboundedly many plans, and
        the old clear-everything-at-threshold caused recompute storms."""
        cache = getattr(self, "_est_cache", None)
        if cache is None:
            from .qcache import LRUCache

            cache = self._est_cache = LRUCache(
                max_entries=4096, name="row_est"
            )
        key = (node,) + self._est_env()
        hit = cache.get(key, count=False)
        if hit is not None:
            return hit[0]
        try:
            from ..plan.stats import derive

            est = float(derive(node, self.catalog).rows)
        except Exception:  # noqa: BLE001 — estimation is best-effort
            est = None
        cache.put(key, (est,))
        return est

    # -- dynamic filters (exec/dynfilter.py) --

    def _dyn_enabled(self) -> bool:
        from .breaker import BREAKERS
        from .dynfilter import dynamic_filtering_enabled

        return (
            self.dynamic_filtering
            and dynamic_filtering_enabled()
            and BREAKERS.allow("dynamic_filter")
        )

    def _dyn_worthwhile(self, node) -> bool:
        """CBO benefit gate: deriving costs a build-side pass plus a probe
        mask, so skip when the join barely filters (est output close to
        the probe input — e.g. an unfiltered FK->PK join keeps every
        row). Stats-less plans derive anyway (best-effort)."""
        import os

        if os.environ.get("PRESTO_TPU_DYNFILTER_FORCE") == "1":
            return True
        max_sel = float(
            os.environ.get("PRESTO_TPU_DYNFILTER_MAX_SEL", "0.7")
        )
        out_est = self._est_rows(node)
        probe_est = self._est_rows(node.children[0])
        if out_est is None or probe_est is None or probe_est <= 0:
            return True
        return out_est < max_sel * probe_est

    def _publish_dynamic_filters(self, node, build_page: Page) -> None:
        """Derive per-key summaries from a materialized build side and
        publish them under the planner-assigned ids. Behind the
        `dynamic_filter` breaker: a faulting derivation degrades the whole
        path to legacy no-filter execution, never fails the query."""
        import time

        from .breaker import BREAKERS
        from ..expr.compiler import evaluate
        from .dynfilter import derive_filter

        if not self._dyn_enabled() or not self._dyn_worthwhile(node):
            return
        keys = (
            node.right_keys
            if isinstance(node, N.Join)
            else node.source_keys
        )
        notes = []
        t0 = time.perf_counter()
        live = build_page.live_mask()
        for fid, i, _consumed in node.dynamic_filters:
            try:
                val = evaluate(keys[i], build_page)
                df = derive_filter(val, live)
            except Exception as exc:  # noqa: BLE001 — degrade, don't fail
                BREAKERS.record_failure("dynamic_filter", repr(exc))
                return
            if df is None:
                continue
            BREAKERS.record_success("dynamic_filter")
            self.dyn_ctx.publish(fid, df)
            notes.append(f"{fid}={df.describe()}")
        if notes and self.collector is not None:
            ms = (time.perf_counter() - t0) * 1e3
            self._append_detail(
                node, f"df[{', '.join(notes)}, derive {ms:.1f}ms]"
            )

    def _append_detail(self, node, txt: str) -> None:
        if self.collector is None:
            return
        s = self.collector.stats_for(node)
        if txt not in s.detail:
            s.detail = f"{s.detail}; {txt}" if s.detail else txt

    def _dyn_compact(self, page: Page, keep) -> Tuple[Page, int]:
        """Compact + shrink for dynamic-filter masks, which are typically
        VERY selective. The generic `compact` (argsort on the drop flag)
        pays a full-capacity sort and then `_shrink`'s CBO gate — which
        knows nothing about runtime filters — skips the slice. Here the
        exact survivor count is known (pruned-row accounting syncs it
        anyway), so the output is always sliced to the count's bucket; on
        the CPU backend the whole compaction routes through ONE host
        `np.flatnonzero` pass + a small gather instead of XLA's
        comparison sort (the keypack host-sort pattern, ops/keypack.py).
        Returns (page, survivor count)."""
        import numpy as np

        from ..ops.filter import compact

        keep = keep & page.live_mask()
        if jax.default_backend() == "cpu":
            nz = np.flatnonzero(np.asarray(keep))
            n = int(nz.size)
            cap = round_capacity(max(n, 1))
            idx = np.zeros(cap, np.int64)
            idx[:n] = nz
            idxd = jnp.asarray(idx)
            blocks = [b.take_rows(idxd) for b in page.blocks]
            return (
                Page(
                    tuple(blocks), page.names,
                    jnp.asarray(n, dtype=jnp.int32),
                ),
                n,
            )
        out = compact(page, keep)
        n = int(out.count)
        cap = round_capacity(max(n, 1))
        if cap < out.capacity:
            idx = slice(0, cap)
            out = Page(
                tuple(b.take_rows(idx) for b in out.blocks),
                out.names,
                out.count,
            )
        return out, n

    def _dyn_mask_page(self, node, page: Page, entries, where: str) -> Page:
        """AND every available dynamic-filter mask over `page` and compact.
        `entries` is [(fid, value_source)] where value_source is a channel
        name or a key RowExpression. No-ops when nothing is published."""
        from .breaker import BREAKERS
        from ..expr.compiler import evaluate

        picked = []
        for fid, src in entries:
            df = self.dyn_ctx.get(fid)
            if df is not None:
                picked.append((fid, src, df))
        if not picked or not self._dyn_enabled():
            return page
        try:
            keep = None
            for fid, src, df in picked:
                val = (
                    page.block(src)
                    if isinstance(src, str)
                    else evaluate(src, page)
                )
                m = df.mask(val)
                keep = m if keep is None else (keep & m)
            before = int(page.count)
            out, n = self._dyn_compact(page, keep)
            pruned = before - n
        except Exception as exc:  # noqa: BLE001 — degrade, don't fail
            BREAKERS.record_failure("dynamic_filter", repr(exc))
            return page
        BREAKERS.record_success("dynamic_filter")
        self._note_dyn_pruned(
            node, picked[0][0], pruned, where,
            ",".join(f"{fid}:{df.strategy}" for fid, _s, df in picked),
        )
        return out

    def _note_dyn_pruned(
        self, node, lead_fid: str, pruned: int, where: str, descs: str
    ) -> None:
        """Book pruned rows (combined mask attributed once, to the lead
        filter id) and refresh the node's EXPLAIN ANALYZE tag with the
        accumulated total (streaming overwrites it per batch)."""
        import re

        self.dyn_ctx.note_pruned(lead_fid, pruned, where)
        if self.collector is None:
            return
        book = self.dyn_ctx.scan_pruned if where == "scan" else (
            self.dyn_ctx.preprobe_pruned
        )
        total = book.get(lead_fid, pruned)
        s = self.collector.stats_for(node)
        tag = f"dyn_pruned={total:,} ({descs})"
        s.detail = (
            re.sub(r"dyn_pruned=[^;]*", tag, s.detail)
            if "dyn_pruned=" in s.detail
            else (f"{s.detail}; {tag}" if s.detail else tag)
        )

    def _apply_scan_masks(
        self, node: N.TableScan, page: Page, hint_entries: bool = False
    ) -> Page:
        """Scan-level dynamic pruning. Default: entries marked apply_mask
        (no Filter above fuses them). With `hint_entries`, ONLY the
        hint-only entries — the distributed executor applies those at the
        scan because its SPMD filter stages run pre-compiled kernels that
        cannot see runtime filters (apply-marked entries already ran in
        _exec_tablescan; re-applying them would pay a second compaction)."""
        entries = [
            (fid, ch)
            for fid, ch, _src, apply in node.dynamic_filters
            if apply != hint_entries
        ]
        if not entries:
            return page
        return self._dyn_mask_page(node, page, entries, "scan")

    def _apply_preprobe(self, node, probe: Page) -> Page:
        """On-device pre-probe filter for produced ids with NO scan
        consumer — join_n1/semi_match_mask then see only surviving rows."""
        keys = (
            node.left_keys if isinstance(node, N.Join) else node.probe_keys
        )
        entries = [
            (fid, keys[i])
            for fid, i, consumed in getattr(node, "dynamic_filters", ())
            if not consumed
        ]
        if not entries:
            return probe
        return self._dyn_mask_page(node, probe, entries, "preprobe")

    # -- physical nodes (fragmented plans executed single-node) --
    def _exec_exchange(self, node, page: Page) -> Page:
        return page  # all exchange kinds are identities on a single worker

    def _exec_aggfinalize(self, node, page: Page) -> Page:
        from ..ops.aggregate import apply_avg_post

        return apply_avg_post(page, node.aggs, node.post)

    # -- leaf --
    def _exec_singlerow(self, node: N.SingleRow) -> Page:
        import numpy as np

        blk = Block.from_numpy(np.zeros(1, dtype=np.int64), T.BIGINT)
        return Page((blk,), (node.channel,), 1)

    def _exec_tablescan(self, node: N.TableScan) -> Page:
        src = self.catalog.page(node.table)
        blocks = []
        names = []
        for ch, col, _typ in node.columns:
            blocks.append(src.block(col))
            names.append(ch)
        page = Page(tuple(blocks), tuple(names), src.count)
        if node.dynamic_filters:
            page = self._apply_scan_masks(node, page)
        return page

    # -- stateless row ops --
    def _exec_unnest(self, node: N.Unnest, page: Page) -> Page:
        from ..ops.unnest import unnest_page

        fn = self._kernel(
            node,
            lambda: lambda p: unnest_page(
                p, node.array_exprs, node.elem_channels,
                node.ordinality_channel,
            ),
        )
        return self._shrink(fn(page), node)

    def _exec_sample(self, node: N.Sample, page: Page) -> Page:
        from ..ops.filter import sample_page

        # global row position of this batch: per-node running offset
        # (advanced by CAPACITY, not count, so it needs no host sync) +
        # the per-worker/per-split salt — the same positional mask must
        # never repeat across batches or workers (Bernoulli, not
        # systematic sampling). Offset is a traced argument, so the
        # compiled kernel is shared across batches.
        pos = self._sample_pos.get(id(node), 0)
        self._sample_pos[id(node)] = pos + page.capacity
        offset = jnp.asarray(
            (self.sample_salt + pos) & 0xFFFFFFFFFFFFFFFF, jnp.uint64
        )
        fn = self._kernel(
            node,
            lambda: lambda p, off: sample_page(
                p, node.fraction, node.seed, off
            ),
        )
        return self._shrink(fn(page, offset), node)

    def _exec_filter(self, node: N.Filter, page: Page) -> Page:
        if node.dynamic_filters and any(
            self.dyn_ctx.get(fid) is not None
            for fid, _ch in node.dynamic_filters
        ):
            return self._exec_filter_dyn(node, page)
        fn = self._kernel(node, lambda: lambda p: filter_page(p, node.predicate))
        return self._shrink(fn(page), node)

    def _exec_filter_dyn(self, node: N.Filter, page: Page) -> Page:
        """Filter with fused dynamic-filter masks: ONE compaction pass for
        the predicate AND every published runtime filter (the fusion that
        makes dynamic pruning free of extra compactions). Runs eagerly —
        filter arrays are per-query runtime values, not plan constants."""
        from .breaker import BREAKERS
        from ..expr.compiler import evaluate
        from ..ops.filter import compact

        v = evaluate(node.predicate, page)
        keep = v.data
        if v.valid is not None:
            keep = keep & v.valid
        try:
            dmask = None
            picked = []
            for fid, ch in node.dynamic_filters:
                df = self.dyn_ctx.get(fid)
                if df is None:
                    continue
                m = df.mask(page.block(ch))
                dmask = m if dmask is None else (dmask & m)
                picked.append((fid, df))
            if dmask is None:
                return self._shrink(compact(page, keep), node)
            live_keep = keep & page.live_mask()
            would_keep = jnp.sum(live_keep.astype(jnp.int32))
            out, n = self._dyn_compact(page, live_keep & dmask)
            pruned = int(would_keep) - n
        except Exception as exc:  # noqa: BLE001 — degrade, don't fail
            BREAKERS.record_failure("dynamic_filter", repr(exc))
            fn = self._kernel(
                node, lambda: lambda p: filter_page(p, node.predicate)
            )
            return self._shrink(fn(page), node)
        BREAKERS.record_success("dynamic_filter")
        self._note_dyn_pruned(
            node, picked[0][0], pruned, "scan",
            ",".join(f"{fid}:{df.strategy}" for fid, df in picked),
        )
        return out

    def _exec_project(self, node: N.Project, page: Page) -> Page:
        fn = self._kernel(
            node, lambda: lambda p: project_page(p, node.exprs, node.names)
        )
        return fn(page)

    def _exec_output(self, node: N.Output, page: Page) -> Page:
        blocks = tuple(page.block(c) for c in node.channels)
        return Page(blocks, tuple(node.titles), page.count)

    def _strategy_note(self, node, name: str) -> None:
        """Record which aggregation strategy ran (EXPLAIN ANALYZE
        surfaces it — the 4-strategy choice is the engine's hottest
        decision and should be observable, not guessed)."""
        if self.collector is not None:
            self.collector.stats_for(node).detail = f"strategy={name}"

    # -- aggregation --
    def _exec_aggregate(self, node: N.Aggregate, page: Page) -> Page:
        if not node.group_exprs:
            fn = self._kernel(
                node,
                lambda: lambda p: global_aggregate(p, node.aggs, node.mask),
            )
            return fn(page)
        if self.pallas_groupby is None:
            import jax

            self.pallas_groupby = jax.default_backend() == "tpu"
        if self.pallas_groupby:
            from .breaker import BREAKERS
            from ..ops.pallas_groupby import maybe_grouped_aggregate

            out = None
            if BREAKERS.allow("pallas_groupby"):
                try:
                    out = maybe_grouped_aggregate(
                        page, node.group_exprs, node.group_names, node.aggs,
                        node.mask,
                    )
                except Exception as exc:
                    # a Mosaic lowering/compile failure must degrade to
                    # the XLA composition, not fail the query (round-5
                    # bench: the default-on kernel took down the whole
                    # SQL stage); the breaker keeps the faulting kernel
                    # from being re-attempted until its recovery window
                    BREAKERS.record_failure("pallas_groupby", repr(exc))
                    out = None
                else:
                    if out is not None:
                        BREAKERS.record_success("pallas_groupby")
            if out is not None:
                self._strategy_note(node, "pallas")
                return self._shrink(out, node)
        out = self._try_hash_groupby(node, page)
        if out is not None:
            return out
        if self.matmul_groupby is None:
            import jax

            self.matmul_groupby = jax.default_backend() == "tpu"
        if self.matmul_groupby:
            from ..ops.matmul_agg import maybe_matmul_grouped_aggregate

            try:
                out = maybe_matmul_grouped_aggregate(
                    page, node.group_exprs, node.group_names, node.aggs,
                    node.mask,
                )
            except Exception:  # noqa: BLE001 — fall back for THIS
                # aggregation only: the matmul path is plain XLA, so a
                # failure is shape-specific, unlike a Mosaic compile
                # failure (which disables pallas above)
                out = None
            if out is not None:
                self._strategy_note(node, "mxu-matmul")
                return self._shrink(out, node)
        self._strategy_note(node, "hash-sort")
        # groups <= live rows; guess low and retry with the true group count
        # (returned regardless of the bound) on overflow — the adaptive-
        # capacity pattern used by all static-shape operators here. The
        # initial guess comes from the CBO's NDV estimate (free) instead
        # of a blocking count sync; page.capacity bounds it above.
        est = self._est_rows(node)
        guess = int(est) if est is not None else page.capacity
        max_groups = round_capacity(
            min(max(guess, 1), page.capacity, 1 << 16)
        )
        max_elems = 128  # collection-aggregate width (adaptive, like mg)
        while True:
            mg, me = max_groups, max_elems
            fn = self._kernel(
                (node, mg, me),
                lambda: lambda p: grouped_aggregate_sorted(
                    p, node.group_exprs, node.group_names, node.aggs, mg,
                    node.mask, max_elems=me,
                ),
            )
            out = fn(page)
            true_groups = int(out.count)
            if true_groups > max_groups:
                max_groups = round_capacity(true_groups)
                self._retries += 1
                continue
            if "$collect_need" in out.names:
                need = int(out.block("$collect_need").data[0])
                if need > max_elems:
                    max_elems = round_capacity(need)
                    self._retries += 1
                    continue
                keep = [
                    (n, b)
                    for n, b in zip(out.names, out.blocks)
                    if n != "$collect_need"
                ]
                out = Page(
                    tuple(b for _, b in keep),
                    tuple(n for n, _ in keep),
                    out.count,
                )
            break
        return self._shrink(out, node)

    def _try_hash_groupby(self, node: N.Aggregate, page: Page) -> Optional[Page]:
        """Hash-slot grouped aggregation attempt (the PR 11 ceiling lift
        over the dense pallas path: arbitrary-valued keys, G to 512 on
        the kernel / 64k on the host twin) behind the pallas_groupby_hash
        breaker. None = ineligible or faulted; the caller falls through
        to the matmul / sort strategies unchanged."""
        from ..ops.pallas_groupby import maybe_grouped_aggregate_hash
        from .breaker import BREAKERS

        if not BREAKERS.allow("pallas_groupby_hash"):
            return None
        try:
            out = maybe_grouped_aggregate_hash(
                page, node.group_exprs, node.group_names, node.aggs,
                node.mask,
            )
        except Exception as exc:  # noqa: BLE001 — degrade, don't fail
            BREAKERS.record_failure("pallas_groupby_hash", repr(exc))
            return None
        if out is None:
            return None
        BREAKERS.record_success("pallas_groupby_hash")
        self._strategy_note(node, "hash-slot")
        return self._shrink(out, node)

    def _exec_distinct(self, node: N.Distinct, page: Page) -> Page:
        from ..expr.ir import ColumnRef

        key_exprs = tuple(
            ColumnRef(n, b.type) for n, b in zip(page.names, page.blocks)
        )
        # collection columns carry equality in companion arrays
        # (lengths/elem_valid/key_block) the packed key cannot see
        packable = all(
            b.lengths is None and b.key_block is None for b in page.blocks
        )
        plan = self._keypack_plan(
            node, key_exprs, page, equality_only=True, allow_hashed=True
        ) if packable else None
        if plan is not None:
            from ..ops.sort import distinct_packed

            out = self._run_packed(
                node, "keypack_distinct", "pdistinct",
                lambda: lambda p: distinct_packed(p, plan),
                page, plan,
            )
            if out is not None:
                return self._shrink(out, node)
        if self.matmul_groupby is None:
            self.matmul_groupby = jax.default_backend() == "tpu"
        if self.matmul_groupby:
            # DISTINCT over dense keys = the MXU strategy's occupancy-only
            # shape (no channels, no dot) — skips the full hash-sort
            from ..expr.ir import ColumnRef
            from ..ops.matmul_agg import maybe_matmul_grouped_aggregate

            exprs = tuple(
                ColumnRef(n, b.type)
                for n, b in zip(page.names, page.blocks)
            )
            try:
                out = maybe_matmul_grouped_aggregate(
                    page, exprs, page.names, (), None
                )
            except Exception:  # noqa: BLE001 — shape-specific matmul
                # fallback, same contract as _exec_aggregate's
                out = None
            if out is not None:
                self._strategy_note(node, "mxu-occupancy")
                return self._shrink(out, node)
        self._strategy_note(node, "hash-sort")
        fn = self._kernel(node, lambda: lambda p: distinct_page(p, p.capacity))
        return self._shrink(fn(page), node)

    # -- joins --
    def _build_table_guarded(self, page: Page, key_exprs):
        """build_table with build()'s breaker bookkeeping but WITHOUT
        build()'s sorted fallback — an ineligible table here must cost
        nothing (the jitted sorted path will build inside its own
        kernel; an eager sorted build would be discarded)."""
        from ..ops.pallas_join import build_table
        from .breaker import BREAKERS

        try:
            jt = build_table(page, key_exprs)
        except Exception as exc:  # noqa: BLE001 — degrade, don't fail
            BREAKERS.record_failure("pallas_join_build", repr(exc))
            return None
        if jt is not None:
            BREAKERS.record_success("pallas_join_build")
        return jt

    def _try_table_join(self, node: N.Join, left: Page, right: Page,
                        right_names) -> Optional[Page]:
        """EAGER hash-table join attempt (ops/pallas_join.py) — routed
        AROUND jit like host-sort plans (the PR 9 idiom): the table path
        needs concrete operands, and jitting its host scans would mean
        pure_callback on the single-device CPU runtime. None = take the
        jitted sorted-hash kernel path below. build()/join_n1()/
        join_expand() own the breaker bookkeeping and the degrade to the
        sorted layout on kernel faults."""
        from ..ops.pallas_join import TABLE_MAX_BUILD, pallas_join_mode

        if pallas_join_mode() == "off" or not node.right_keys:
            return None
        if right.capacity > TABLE_MAX_BUILD:
            return None
        from .breaker import BREAKERS

        if not (
            BREAKERS.allow("pallas_join_build")
            and BREAKERS.allow("pallas_join_probe")
        ):
            return None
        bs = self._build_table_guarded(right, node.right_keys)
        if bs is None:
            return None
        self._strategy_note(
            node,
            f"hash-table({pallas_join_mode()}) "
            f"occ={int(bs.occupancy() * 100)}%"
            + (f" of={len(bs.of_tag)}" if len(bs.of_tag) else ""),
        )
        if node.unique_build:
            out = join_n1(
                left, bs, node.left_keys, right_names, right_names,
                kind=node.kind,
            )
        else:
            est = self._est_rows(node)
            cap = round_capacity(
                max(left.capacity, int(est) if est is not None else 1, 1)
            )
            while True:
                out, overflow = join_expand(
                    left, bs, node.left_keys, left.names,
                    [(n, n) for n in right_names], out_capacity=cap,
                    kind=node.kind,
                )
                if int(overflow) == 0:
                    break
                cap = round_capacity(cap + int(overflow))
                self._retries += 1
        if node.residual is not None:
            if node.kind != "inner":
                raise ExecutionError("residual on outer join not yet supported")
            out = filter_page(out, node.residual)
        return self._shrink(out, node)

    def _exec_join(self, node: N.Join, left: Page, right: Page) -> Page:
        if node.kind == "full" or (
            node.kind != "inner" and node.residual is not None
        ):
            return self._exec_outer_join(node, left, right)
        if node.dynamic_filters:
            left = self._apply_preprobe(node, left)
        right_names = right.names
        table_out = self._try_table_join(node, left, right, right_names)
        if table_out is not None:
            return table_out
        if node.unique_build:
            out = self._kernel_guarded(
                "join_probe",
                (node, "n1"),
                lambda: lambda l, r: join_n1(
                    l,
                    build(r, node.right_keys),
                    node.left_keys,
                    right_names,
                    right_names,
                    kind=node.kind,
                ),
                left, right,
            )
            if node.residual is not None:
                if node.kind != "inner":
                    raise ExecutionError(
                        "residual on outer join not yet supported"
                    )
                out = filter_page(out, node.residual)
            return self._shrink(out, node)
        # general 1:N expansion with adaptive capacity retry; initial
        # guess = probe capacity vs CBO join-output estimate (no count
        # sync — each one is a tunnel round trip on TPU)
        est = self._est_rows(node)
        cap = round_capacity(
            max(left.capacity, int(est) if est is not None else 1, 1)
        )
        while True:
            c = cap
            out, overflow = self._kernel_guarded(
                "join_probe",
                (node, "expand", c),
                lambda: lambda l, r: join_expand(
                    l,
                    build(r, node.right_keys),
                    node.left_keys,
                    l.names,
                    [(n, n) for n in right_names],
                    out_capacity=c,
                    kind=node.kind,
                ),
                left, right,
            )
            if int(overflow) == 0:
                break
            cap = round_capacity(cap + int(overflow))
            self._retries += 1
        if node.residual is not None:
            if node.kind != "inner":
                raise ExecutionError("residual on outer join not yet supported")
            out = filter_page(out, node.residual)
        return self._shrink(out, node)

    def _exec_outer_join(self, node: N.Join, left: Page, right: Page) -> Page:
        """LEFT join with a residual ON filter, and FULL OUTER join.

        Composition (reference handles these inside LookupJoinOperator +
        OuterLookupSource; here they compose from the same primitive
        kernels): inner-expand on the equi keys, apply the residual, then
        null-extend the probe rows (and for FULL the build rows) whose row
        id has no surviving match."""
        from ..ops.union import concat_pages, extend_with_nulls

        full = node.kind == "full"
        taken = set(left.names) | set(right.names)
        i = 0
        while f"$ridL{i}" in taken or f"$ridR{i}" in taken:
            i += 1
        rid_l, rid_r = f"$ridL{i}", f"$ridR{i}"
        left2 = self._with_row_id(left, rid_l)
        right2 = self._with_row_id(right, rid_r)
        rid_t = T.BIGINT

        bs = build(right2, node.right_keys)
        probe_out = list(left.names) + [rid_l]
        build_out = [(n, n) for n in right.names] + [(rid_r, rid_r)]
        est = self._est_rows(node)
        cap = round_capacity(
            max(left.capacity, int(est) if est is not None else 1, 1)
        )
        while True:
            expanded, overflow = join_expand(
                left2,
                bs,
                node.left_keys,
                probe_out,
                build_out,
                out_capacity=cap,
                kind="inner",
            )
            if int(overflow) == 0:
                break
            cap = round_capacity(cap + int(overflow))
            self._retries += 1
        matched = (
            filter_page(expanded, node.residual)
            if node.residual is not None
            else expanded
        )
        matched = self._shrink(matched, node)

        def drop(page: Page, names) -> Page:
            keep = [
                (b, n)
                for b, n in zip(page.blocks, page.names)
                if n not in names
            ]
            return Page(
                tuple(b for b, _ in keep), tuple(n for _, n in keep), page.count
            )

        parts = [drop(matched, {rid_l, rid_r})]

        # probe rows with no surviving match -> null build columns
        bs_l = build(matched, (ir.ColumnRef(rid_l, rid_t),))
        left_un = join_n1(
            left2, bs_l, (ir.ColumnRef(rid_l, rid_t),), [], [], kind="anti"
        )
        parts.append(
            extend_with_nulls(
                drop(left_un, {rid_l}),
                right.names,
                [b.type for b in right.blocks],
                [b.dict_id for b in right.blocks],
            )
        )
        if full:
            bs_r = build(matched, (ir.ColumnRef(rid_r, rid_t),))
            right_un = join_n1(
                right2, bs_r, (ir.ColumnRef(rid_r, rid_t),), [], [], kind="anti"
            )
            parts.append(
                extend_with_nulls(
                    drop(right_un, {rid_r}),
                    left.names,
                    [b.type for b in left.blocks],
                    [b.dict_id for b in left.blocks],
                    prepend=True,
                )
            )
        return self._shrink(concat_pages(parts), node)

    @staticmethod
    def _attach_mark(probe: Page, mask, name: str) -> Page:
        return Page(
            probe.blocks + (Block(mask, T.BOOLEAN, None),),
            probe.names + (name,),
            probe.count,
        )

    def _exec_semijoin(self, node: N.SemiJoin, probe: Page, source: Page) -> Page:
        if node.dynamic_filters:
            probe = self._apply_preprobe(node, probe)
        if node.residual is None:
            bs = build(source, node.source_keys)
            if node.mark is not None:
                from ..ops.join import semi_match_mask

                mask = semi_match_mask(probe, bs, node.probe_keys)
                return self._attach_mark(probe, mask, node.mark)
            out = join_n1(
                probe,
                bs,
                node.probe_keys,
                [],
                [],
                kind="anti" if node.anti else "semi",
            )
            return self._shrink(out, node)
        # residual EXISTS: expand probe x source on equi keys, filter the
        # residual, then keep probe rows whose row-id survived
        rid = self._row_id_channel(probe)
        probe2 = self._with_row_id(probe, rid)
        bs = build(source, node.source_keys)
        needed = self._residual_channels(node.residual)
        probe_out = [rid] + [n for n in probe.names if n in needed]
        build_out = [(n, n) for n in source.names if n in needed]
        cap = round_capacity(max(probe.capacity, 1))  # no count sync
        while True:
            expanded, overflow = join_expand(
                probe2,
                bs,
                node.probe_keys,
                probe_out,
                build_out,
                out_capacity=cap,
                kind="inner",
            )
            if int(overflow) == 0:
                break
            cap = round_capacity(cap + int(overflow))
            self._retries += 1
        matched = filter_page(expanded, node.residual)
        matched = self._shrink(matched, node)
        rid_type = T.BIGINT
        bs2 = build(matched, (ir.ColumnRef(rid, rid_type),))
        if node.mark is not None:
            from ..ops.join import semi_match_mask

            mask = semi_match_mask(
                probe2, bs2, (ir.ColumnRef(rid, rid_type),)
            )
            return self._attach_mark(probe, mask, node.mark)
        out = join_n1(
            probe2,
            bs2,
            (ir.ColumnRef(rid, rid_type),),
            [],
            [],
            kind="anti" if node.anti else "semi",
        )
        # drop the row-id column
        blocks = tuple(
            b for b, n in zip(out.blocks, out.names) if n != rid
        )
        names = tuple(n for n in out.names if n != rid)
        return self._shrink(Page(blocks, names, out.count), node)

    def _row_id_channel(self, page: Page) -> str:
        i = 0
        while f"$rid{i}" in page.names:
            i += 1
        return f"$rid{i}"

    def _with_row_id(self, page: Page, name: str) -> Page:
        rid = Block(
            jnp.arange(page.capacity, dtype=jnp.int64), T.BIGINT, None, None
        )
        return Page(page.blocks + (rid,), page.names + (name,), page.count)

    def _residual_channels(self, e: ir.RowExpression) -> set:
        out: set = set()

        def walk(x):
            if isinstance(x, ir.ColumnRef):
                out.add(x.name)
            elif isinstance(x, ir.Call):
                for a in x.args:
                    walk(a)

        walk(e)
        return out

    def _exec_scalarapply(self, node: N.ScalarApply, page: Page, sub: Page) -> Page:
        n = int(sub.count)
        if n > 1:
            raise ExecutionError("scalar subquery returned more than one row")
        cap = page.capacity
        blocks = list(page.blocks)
        names = list(page.names)
        for b, (fname, ftype) in zip(sub.blocks, node.subquery.fields):
            if n == 0:
                data = jnp.zeros((cap,) + b.data.shape[1:], b.data.dtype)
                valid = jnp.zeros((cap,), jnp.bool_)
            else:
                data = jnp.broadcast_to(b.data[0], (cap,) + b.data.shape[1:])
                if b.valid is None:
                    valid = None
                else:
                    valid = jnp.broadcast_to(b.valid[0], (cap,))
            blocks.append(Block(data, b.type, valid, b.dict_id))
            names.append(fname)
        return Page(tuple(blocks), tuple(names), page.count)

    def _exec_window(self, node: N.Window, page: Page) -> Page:
        from ..ops.sort import SortKey
        from ..ops.window import window_op

        specs = tuple(SortKey(e) for e in node.partition_exprs) + tuple(
            node.order_keys
        )
        plan = self._keypack_plan(
            node, specs, page, single_lane=True,
            n_order_keys=len(node.order_keys),
        ) if specs else None
        if plan is not None:
            from ..ops.window import window_op_packed

            out = self._run_packed(
                node, "keypack_window", "pwindow",
                lambda: lambda p: window_op_packed(
                    p, node.partition_exprs, node.order_keys, node.funcs,
                    plan,
                ),
                page, plan,
            )
            if out is not None:
                return out
        fn = self._kernel(
            node,
            lambda: lambda p: window_op(
                p, node.partition_exprs, node.order_keys, node.funcs
            ),
        )
        return fn(page)

    # -- ordering / limits --
    def _exec_sort(self, node: N.Sort, page: Page) -> Page:
        plan = self._keypack_plan(node, node.keys, page)
        if plan is not None:
            from ..ops.sort import sort_page_packed

            out = self._run_packed(
                node, "keypack_sort", "psort",
                lambda: lambda p: sort_page_packed(p, node.keys, plan),
                page, plan,
            )
            if out is not None:
                return out
        return self._kernel_guarded(
            "fused_sort",
            (node, "sort"),
            lambda: lambda p: sort_page(p, node.keys),
            page,
        )

    def _exec_topn(self, node: N.TopN, page: Page) -> Page:
        plan = self._keypack_plan(node, node.keys, page)
        if plan is not None:
            from ..ops.sort import top_n_packed

            out = self._run_packed(
                node, "keypack_topn", "ptopn",
                lambda: lambda p: top_n_packed(
                    p, node.keys, node.count, plan
                ),
                page, plan,
            )
            if out is not None:
                return out
        fn = self._kernel(
            node, lambda: lambda p: top_n(p, node.keys, node.count)
        )
        return fn(page)

    def _exec_limit(self, node: N.Limit, page: Page) -> Page:
        return self._shrink(limit_page(page, node.count), node)

    def _exec_union(self, node: N.Union, *pages: Page) -> Page:
        from ..ops.union import concat_pages

        # positional union: output schema/names follow the first branch
        return self._shrink(concat_pages(pages, distinct=node.distinct), node)
