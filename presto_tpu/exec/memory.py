"""Device-memory accounting for query execution.

Re-designed equivalent of the reference's node memory management
(presto-main/.../memory/MemoryPool.java:43 reserve/reserveRevocable,
presto-memory-context/ hierarchical contexts). TPU-first reduction: one
pool per query tracking HBM-resident page bytes; "revocable" memory is the
streaming driver's build/accumulator state, which it can offload to host
RAM and from there to the disk spill tier (exec/stream.py + exec/spill.py
+ exec/spillspace.py).

Enforcement is cooperative: kernels are static-shape, so the driver checks
the budget BEFORE materializing (reserve raises MemoryExceededError and the
caller switches to a bounded strategy — smaller batches or chunked build
execution), instead of the reference's blocking futures.

Two additions over the original design:

* Parent mirroring: a pool created with `parent=` (the worker's
  WorkerMemoryPool) mirrors every reserve/free into the worker's
  execution ledger, so `/v1/memory` reflects executor-held bytes (build
  tables, accumulator state) alongside output buffers and the cluster
  memory manager kills based on REAL usage.
* Cooperative revocation (the MemoryRevokingScheduler analog,
  execution/MemoryRevokingScheduler.java:46): `request_revoke()` flips
  the pool into a state where `can_accumulate` answers False, so the
  driver's accumulators take their offload path at the next batch
  boundary and then call `note_revoked`. Revocation is the rung between
  "blocked" and "killed" on the degradation ladder.

Over-frees (freeing more than is reserved) are COUNTED, not silently
clamped away: a nonzero `over_frees` means a double-free accounting bug,
and the test suite fails on it (tests/conftest.py memory guard).
"""

from __future__ import annotations

import time
from typing import Optional


class MemoryExceededError(RuntimeError):
    """Query exceeded its device-memory budget (reference
    ExceededMemoryLimitException)."""


# process-wide over-free aggregate: the suite-level guard asserts its
# delta is zero after every test (a double-free anywhere is a bug even if
# the owning pool was short-lived)
GLOBAL_ACCOUNTING = {"over_frees": 0, "over_freed_bytes": 0}


class MemoryPool:
    def __init__(self, max_bytes: int | None = None, name: str = "query",
                 parent=None, query_id: str = ""):
        self.max_bytes = max_bytes
        self.name = name
        self.parent = parent  # server.worker.WorkerMemoryPool (or None)
        self.query_id = query_id or name
        self.reserved = 0
        self.peak = 0
        # double-free observability (never silently clamp)
        self.over_frees = 0
        self.over_freed_bytes = 0
        # cooperative revocation state
        self.revocations = 0  # completed revoke cycles
        self.accumulated = 0  # driver-held device bytes not yet reserved
        self._revoke_requested_at: Optional[float] = None
        self.revoke_grace_s = 5.0

    # -- reservation --

    def can_reserve(self, nbytes: int) -> bool:
        return self.max_bytes is None or self.reserved + nbytes <= self.max_bytes

    def can_accumulate(self, nbytes: int) -> bool:
        """May the driver keep accumulating device state? False while a
        revoke is pending — the accumulator then takes its offload path
        (host RAM -> disk) exactly as if the budget ran out."""
        if self.revoke_pending:
            return False
        return self.can_reserve(nbytes)

    def reserve(self, nbytes: int, what: str = "") -> int:
        if not self.can_reserve(nbytes):
            raise MemoryExceededError(
                f"{self.name}: reserving {nbytes:,}B for {what or 'pages'} "
                f"exceeds budget ({self.reserved:,}B reserved of "
                f"{self.max_bytes:,}B)"
            )
        self.reserved += nbytes
        self.peak = max(self.peak, self.reserved)
        if self.parent is not None:
            self.parent.reserve_execution(self.query_id, nbytes)
        return nbytes

    def free(self, nbytes: int) -> None:
        if nbytes > self.reserved:
            # a double-free: count it loudly instead of clamping silently
            self.over_frees += 1
            over = nbytes - self.reserved
            self.over_freed_bytes += over
            GLOBAL_ACCOUNTING["over_frees"] += 1
            GLOBAL_ACCOUNTING["over_freed_bytes"] += over
            nbytes = self.reserved
        self.reserved -= nbytes
        if self.parent is not None and nbytes:
            self.parent.free_execution(self.query_id, nbytes)

    # -- revocation (cooperative; see exec/stream.py accumulators) --

    @property
    def revoke_pending(self) -> bool:
        t = self._revoke_requested_at
        if t is None:
            return False
        if time.monotonic() - t > self.revoke_grace_s:
            # the driver never reached a revocation point (e.g. blocked
            # in a kernel): expire the request so an eventually-healthy
            # query is not forced to spill forever
            self._revoke_requested_at = None
            return False
        return True

    def request_revoke(self) -> bool:
        """Ask the driver to offload revocable state at its next batch
        boundary. Returns True when a new request was armed."""
        if self._revoke_requested_at is not None:
            return False
        self._revoke_requested_at = time.monotonic()
        return True

    def note_revoked(self, nbytes: int) -> None:
        """The driver offloaded `nbytes` of device state. Completes a
        pending revoke request (no-op when none is pending — the normal
        budget-exhaustion offload calls this too)."""
        if self._revoke_requested_at is not None:
            self._revoke_requested_at = None
            self.revocations += 1

    def revocable_bytes(self) -> int:
        """Estimate of bytes a revoke could free: accumulator-held pages
        plus reserved operator state (largest-revocable-first ordering in
        the worker's revoking scheduler)."""
        return self.accumulated + self.reserved

    def snapshot(self) -> dict:
        return {
            "name": self.name,
            "max_bytes": self.max_bytes,
            "reserved": self.reserved,
            "peak": self.peak,
            "accumulated": self.accumulated,
            "over_frees": self.over_frees,
            "over_freed_bytes": self.over_freed_bytes,
            "revocations": self.revocations,
            "revoke_pending": self.revoke_pending,
        }
