"""Device-memory accounting for query execution.

Re-designed equivalent of the reference's node memory management
(presto-main/.../memory/MemoryPool.java:43 reserve/reserveRevocable,
presto-memory-context/ hierarchical contexts). TPU-first reduction: one
pool per query tracking HBM-resident page bytes; "revocable" memory is the
streaming driver's build/accumulator state, which it can offload to host
RAM (exec/stream.py) — the disk-spill analog from SURVEY §5.

Enforcement is cooperative: kernels are static-shape, so the driver checks
the budget BEFORE materializing (reserve raises MemoryExceededError and the
caller switches to a bounded strategy — smaller batches or chunked build
execution), instead of the reference's blocking futures."""

from __future__ import annotations


class MemoryExceededError(RuntimeError):
    """Query exceeded its device-memory budget (reference
    ExceededMemoryLimitException)."""


class MemoryPool:
    def __init__(self, max_bytes: int | None = None, name: str = "query"):
        self.max_bytes = max_bytes
        self.name = name
        self.reserved = 0
        self.peak = 0

    def can_reserve(self, nbytes: int) -> bool:
        return self.max_bytes is None or self.reserved + nbytes <= self.max_bytes

    def reserve(self, nbytes: int, what: str = "") -> int:
        if not self.can_reserve(nbytes):
            raise MemoryExceededError(
                f"{self.name}: reserving {nbytes:,}B for {what or 'pages'} "
                f"exceeds budget ({self.reserved:,}B reserved of "
                f"{self.max_bytes:,}B)"
            )
        self.reserved += nbytes
        self.peak = max(self.peak, self.reserved)
        return nbytes

    def free(self, nbytes: int) -> None:
        self.reserved = max(0, self.reserved - nbytes)
