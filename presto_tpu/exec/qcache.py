"""Query-serving caches: the repeated-traffic fast path.

Re-designed equivalent of the reference's serving-side caches: prepared
statements + plan reuse (presto-main QueryPreparer / the analyzer's
parameter rewriting), the per-split result caching of Presto's
`fragment-result-cache` (presto-main/.../operator/FragmentResultCache),
and cross-query compiled-code reuse (the reference compiles page
processors once per plan via PageFunctionCompiler's guava cache;
our XLA executables are the analog).

Three stacked caches, all process-wide and observable:

* PLAN_CACHE   — (normalized statement AST, catalog identity, planning
  env, connector snapshot versions) -> optimized plan. EXECUTE of a
  prepared statement stores a *skeleton* whose parameter literals are
  tagged (`ir.Literal.param`) so new parameter values rebind by a tree
  walk instead of a full re-plan.
* RESULT_CACHE — byte-bounded LRU of materialized result pages keyed on
  the executed plan + snapshot versions. A connector without snapshot
  versioning (`table_version` -> None) is NEVER cached — stale reads are
  impossible by construction, not by TTL. Bytes can be accounted into a
  server.worker.WorkerMemoryPool (attach_cache) where the PR 7 revoking
  scheduler shrinks the cache FIRST under memory pressure.
* KERNEL_CACHE — process-wide LRU of jitted per-node kernels keyed on
  (backend, jit flag, node + static config). Promotes the per-Executor
  compile-once dict so back-to-back queries from different sessions
  reuse traced executables. PRESTO_TPU_COMPILE_CACHE_DIR additionally
  enables JAX's persistent compilation cache so worker restarts
  warm-start from disk.

Validity rule shared by the plan and result caches: every entry records
the tables it read and their connector snapshot versions AT PLAN/EXECUTE
time (read BEFORE execution, so a concurrent writer can only ever make a
fresh entry unservable, never a served entry stale), plus a weakref to
the catalog object so an id()-recycled catalog can never alias.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import weakref
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


# ---------------------------------------------------------------------------
# generic bounded LRU with stats
# ---------------------------------------------------------------------------


class CacheStats:
    __slots__ = (
        "hits", "misses", "stores", "evictions", "invalidations", "bytes",
        "revoked_bytes", "patches",
    )

    def __init__(self):
        self.hits = 0
        self.misses = 0
        self.stores = 0
        self.evictions = 0
        self.invalidations = 0  # version-bump / identity-mismatch drops
        self.bytes = 0
        self.revoked_bytes = 0  # evicted under memory pressure
        self.patches = 0  # stale entries updated in place from deltas

    def snapshot(self) -> dict:
        total = self.hits + self.misses
        return {
            "hits": self.hits,
            "misses": self.misses,
            "stores": self.stores,
            "evictions": self.evictions,
            "invalidations": self.invalidations,
            "bytes": self.bytes,
            "revoked_bytes": self.revoked_bytes,
            "patches": self.patches,
            "hit_rate": round(self.hits / total, 4) if total else None,
        }


class LRUCache:
    """Thread-safe bounded LRU. Entries carry a byte size so the cache can
    be bounded by entries, bytes, or both. max_entries/max_bytes of 0
    disables the cache entirely (get always misses, put is a no-op)."""

    def __init__(self, max_entries: Optional[int] = None,
                 max_bytes: Optional[int] = None, name: str = "cache"):
        self.max_entries = max_entries
        self.max_bytes = max_bytes
        self.name = name
        self.stats = CacheStats()
        self._lock = threading.Lock()
        self._data: "OrderedDict[Any, Tuple[Any, int]]" = OrderedDict()

    @property
    def enabled(self) -> bool:
        return self.max_entries != 0 and self.max_bytes != 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._data)

    def get(self, key, count: bool = True):
        with self._lock:
            ent = self._data.get(key)
            if ent is None:
                if count:
                    self.stats.misses += 1
                return None
            self._data.move_to_end(key)
            if count:
                self.stats.hits += 1
            return ent[0]

    def put(self, key, value, nbytes: int = 0) -> None:
        if not self.enabled:
            return
        with self._lock:
            old = self._data.pop(key, None)
            if old is not None:
                self.stats.bytes -= old[1]
            self._data[key] = (value, nbytes)
            self.stats.bytes += nbytes
            self.stats.stores += 1
            self._evict_locked()

    def _evict_locked(self) -> None:
        while self._data and (
            (self.max_entries is not None
             and len(self._data) > self.max_entries)
            or (self.max_bytes is not None
                and self.stats.bytes > self.max_bytes)
        ):
            _k, (_v, nb) = self._data.popitem(last=False)
            self.stats.bytes -= nb
            self.stats.evictions += 1

    def invalidate(self, key) -> None:
        with self._lock:
            ent = self._data.pop(key, None)
            if ent is not None:
                self.stats.bytes -= ent[1]
                self.stats.invalidations += 1

    def revoke(self, nbytes: int) -> int:
        """Evict LRU-first until `nbytes` are freed (memory-pressure path:
        the worker pool calls this BEFORE asking executors to spill).
        Returns the bytes actually freed."""
        with self._lock:
            freed = 0
            while self._data and freed < nbytes:
                _k, (_v, nb) = self._data.popitem(last=False)
                freed += nb
                self.stats.evictions += 1
                self.stats.revoked_bytes += nb
            self.stats.bytes -= freed
            return freed

    def clear(self) -> None:
        with self._lock:
            self._data.clear()
            self.stats.bytes = 0

    def reset(self) -> None:
        """Drop every entry AND zero the counters. The stats swap must
        happen under the lock: a concurrent store() holds the lock while
        it increments stats.bytes, and swapping the object between its
        insert and its increment strands the increment on the old stats
        — leaving the NEW stats claiming 0 bytes for a non-empty map."""
        with self._lock:
            self._data.clear()
            self.stats = CacheStats()

    def snapshot(self) -> dict:
        out = self.stats.snapshot()
        with self._lock:
            out["entries"] = len(self._data)
        out["max_entries"] = self.max_entries
        out["max_bytes"] = self.max_bytes
        return out


# ---------------------------------------------------------------------------
# snapshot-version validity
# ---------------------------------------------------------------------------


def table_versions(catalog, tables) -> Optional[Tuple[int, ...]]:
    """Snapshot-version vector for `tables`, or None when ANY table (or the
    catalog itself) lacks versioning — the uncacheable-never-stale rule."""
    fn = getattr(catalog, "table_version", None)
    if fn is None:
        return None
    out = []
    for tname in tables:
        try:
            v = fn(tname)
        except Exception:  # noqa: BLE001 — dropped table etc.: uncacheable
            return None
        if v is None:
            return None
        out.append(int(v))
    return tuple(out)


def delta_tokens(catalog, tables) -> Optional[Tuple[Any, ...]]:
    """Per-table delta cursors (connector delta_token(), e.g. shardstore
    seq high-water marks), or None when any table's connector cannot
    produce one. Tokens make a result entry PATCHABLE: on a version
    mismatch the cache can apply the (token, now] delta instead of
    evicting (the matview maintenance machinery)."""
    fn = getattr(catalog, "delta_token", None)
    if fn is None:
        return None
    out = []
    for tname in tables:
        try:
            tok = fn(tname)
        except Exception:  # noqa: BLE001 — dropped table: not patchable
            return None
        if tok is None:
            return None
        out.append(tok)
    return tuple(out)


def _walk(obj, visit) -> None:
    """Generic traversal over plan/expression trees: `visit(leaf)` on
    every node, recursing through dataclass fields and tuples (the only
    containers plan nodes and RowExpressions use)."""
    visit(obj)
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        for f in dataclasses.fields(obj):
            _walk(getattr(obj, f.name), visit)
    elif isinstance(obj, tuple):
        for v in obj:
            _walk(v, visit)


def plan_tables(node) -> Tuple[str, ...]:
    """Every connector table a plan reads (TableScan walk through all
    dataclass fields, so scalar-subquery plans are covered too)."""
    from ..plan import nodes as N

    seen: List[str] = []

    def visit(obj):
        if isinstance(obj, N.TableScan) and obj.table not in seen:
            seen.append(obj.table)

    _walk(node, visit)
    return tuple(seen)


def plan_is_deterministic(node) -> bool:
    """False when the plan contains TABLESAMPLE or a context-dependent
    function (random/now/...): such results must never be served twice."""
    from ..expr import ir
    from ..plan import nodes as N
    from ..plan.rules import _NONDETERMINISTIC

    ok = [True]

    def visit(obj):
        if isinstance(obj, N.Sample) or (
            isinstance(obj, ir.Call) and obj.name in _NONDETERMINISTIC
        ):
            ok[0] = False

    _walk(node, visit)
    return ok[0]


# ---------------------------------------------------------------------------
# parameter-skeleton rebinding (EXECUTE fast path)
# ---------------------------------------------------------------------------
#
# The planner tags literals that came from EXECUTE parameters with their
# parameter index (ir.Literal.param). A cached skeleton plan is rebound to
# new values by a pure tree walk; plan-shape safety comes from three
# guards: (1) param-tagged literals are opaque to constant folding and
# value-sensitive rules (plan/rules.py), (2) a skeleton is only cached
# when EVERY parameter index survives into the plan (a value consumed at
# plan time — LIMIT ?, folded negation — disqualifies it), and (3) the
# first rebind to genuinely new values is verified against a direct
# re-plan once, then trusted.


def _walk_rebuild(obj, fn):
    """Generic rebuild over plan/expression dataclass trees and tuples.
    `fn(leaf)` returns a replacement or the leaf itself."""
    new = fn(obj)
    if new is not obj:
        return new
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        changes = {}
        for f in dataclasses.fields(obj):
            v = getattr(obj, f.name)
            nv = _walk_rebuild(v, fn)
            if nv is not v:
                changes[f.name] = nv
        return dataclasses.replace(obj, **changes) if changes else obj
    if isinstance(obj, tuple):
        newt = tuple(_walk_rebuild(v, fn) for v in obj)
        if any(a is not b for a, b in zip(newt, obj)):
            return newt
        return obj
    return obj


def collect_param_indices(node) -> set:
    from ..expr import ir

    found: set = set()

    def visit(obj):
        if isinstance(obj, ir.Literal) and obj.param is not None:
            found.add(obj.param)

    _walk(node, visit)
    return found


def rebind_plan(node, values: Tuple[Any, ...]):
    """Swap every param-tagged literal's value for values[param]."""
    from ..expr import ir

    def fn(obj):
        if isinstance(obj, ir.Literal) and obj.param is not None:
            v = values[obj.param]
            if v != obj.value:  # NaN != NaN: always replaced, still right
                return dataclasses.replace(obj, value=v)
        return obj

    return _walk_rebuild(node, fn)


def strip_params(node):
    """Drop param tags (for equality comparison against a direct plan)."""
    from ..expr import ir

    def fn(obj):
        if isinstance(obj, ir.Literal) and obj.param is not None:
            return dataclasses.replace(obj, param=None)
        return obj

    return _walk_rebuild(node, fn)


# ---------------------------------------------------------------------------
# plan cache
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class PlanEntry:
    plan: Any
    tables: Tuple[str, ...]
    versions: Tuple[int, ...]
    catalog_ref: Any  # weakref.ref to the catalog (identity guard)
    # EXECUTE skeletons only:
    rebindable: bool = False
    verified: bool = False
    values0: Optional[Tuple[Any, ...]] = None  # values the skeleton planned with


class SnapshotValidatedCache(LRUCache):
    """LRU whose entries carry (tables, versions, catalog weakref) and are
    only served while the catalog object is the same AND every table's
    connector snapshot version still matches — the ONE staleness rule
    both the plan and result caches share."""

    def lookup(self, key, catalog):
        ent = self.get(key, count=False)
        if ent is None:
            with self._lock:
                self.stats.misses += 1
            return None
        if ent.catalog_ref() is not catalog or (
            table_versions(catalog, ent.tables) != ent.versions
        ):
            self.invalidate(key)
            with self._lock:
                self.stats.misses += 1
            return None
        with self._lock:
            self.stats.hits += 1
        return ent


class PlanCache(SnapshotValidatedCache):
    def __init__(self, max_entries: int):
        super().__init__(max_entries=max_entries, name="plan")

    def store(self, key, plan, catalog, **kw) -> Optional[PlanEntry]:
        """Cache `plan` keyed by `key` iff every referenced table reports a
        snapshot version (unversioned -> uncacheable, never stale)."""
        if not self.enabled:
            return None
        tables = plan_tables(plan)
        versions = table_versions(catalog, tables)
        if versions is None:
            return None
        try:
            ref = weakref.ref(catalog)
        except TypeError:
            return None
        ent = PlanEntry(plan, tables, versions, ref, **kw)
        self.put(key, ent)
        return ent


# ---------------------------------------------------------------------------
# result cache
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ResultEntry:
    page: Any
    titles: Tuple[str, ...]
    tables: Tuple[str, ...]
    versions: Tuple[int, ...]
    catalog_ref: Any
    nbytes: int = 0
    # per-table delta cursors recorded with the entry; None = the entry
    # can only hit or invalidate, never patch
    tokens: Optional[Tuple[Any, ...]] = None


class ResultCache(SnapshotValidatedCache):
    def __init__(self, max_bytes: int):
        super().__init__(max_bytes=max_bytes, name="result")

    def preversions(self, plan, catalog):
        """(tables, versions, tokens) read BEFORE execution — the
        ordering that makes a concurrent write waste the entry instead
        of staling it — or None when any table is unversioned (bypass).
        Tokens are read AFTER the version vector; store() only keeps
        them when the versions still match post-execution, which pins
        the executed data to exactly the tokens' snapshot (a delta
        applied later can never double-count rows that raced in during
        execution)."""
        tables = plan_tables(plan)
        versions = table_versions(catalog, tables)
        if versions is None:
            return None
        return (tables, versions, delta_tokens(catalog, tables))

    def store(self, key, page, titles, catalog, pre) -> None:
        if not self.enabled or pre is None:
            return
        from .stats import page_device_bytes

        try:
            ref = weakref.ref(catalog)
        except TypeError:
            return
        try:
            nbytes = int(page_device_bytes(page))
        except Exception:  # noqa: BLE001 — unsizable page: skip caching
            return
        if self.max_bytes is not None and nbytes > self.max_bytes:
            return  # bigger than the whole cache: not worth thrashing
        tables, versions, tokens = pre
        if tokens is not None and (
            table_versions(catalog, tables) != versions
        ):
            # a writer raced the execution: the page may hold rows newer
            # than the tokens claim, and patching from them would apply
            # those rows twice — keep the entry but make it unpatchable
            tokens = None
        self.put(key, ResultEntry(page, tuple(titles), tables, versions,
                                  ref, nbytes, tokens), nbytes=nbytes)

    def lookup(self, key, catalog):
        """Hit / patch / invalidate: the snapshot-validated lookup plus a
        third verdict — an entry whose base tables moved by pure appends
        is brought up to date IN PLACE from the (token, now] delta when
        the plan is delta-patchable (matview maintenance planner),
        instead of being evicted and recomputed."""
        ent = self.get(key, count=False)
        if ent is None:
            with self._lock:
                self.stats.misses += 1
            return None
        if ent.catalog_ref() is not catalog:
            self.invalidate(key)
            with self._lock:
                self.stats.misses += 1
            return None
        if table_versions(catalog, ent.tables) == ent.versions:
            with self._lock:
                self.stats.hits += 1
            return ent
        patched = None
        if ent.tokens is not None and len(key) >= 2:
            try:
                from ..matview.patch import patch_entry

                patched = patch_entry(key[1], ent, catalog)
            except Exception:  # noqa: BLE001 — patch is best-effort; a
                patched = None  # failure falls back to plain invalidate
        if patched is not None:
            self.put(key, patched, nbytes=patched.nbytes)
            with self._lock:
                self.stats.patches += 1
                self.stats.hits += 1
            return patched
        self.invalidate(key)
        with self._lock:
            self.stats.misses += 1
        return None


# ---------------------------------------------------------------------------
# process-wide instances + persistent XLA cache
# ---------------------------------------------------------------------------

PLAN_CACHE = PlanCache(_env_int("PRESTO_TPU_PLAN_CACHE_ENTRIES", 256))
RESULT_CACHE = ResultCache(_env_int("PRESTO_TPU_RESULT_CACHE_BYTES", 64 << 20))
KERNEL_CACHE = LRUCache(
    max_entries=_env_int("PRESTO_TPU_COMPILE_CACHE_ENTRIES", 1024),
    name="kernel",
)
# observed-cardinality feedback entries (plan/history.py HistoryStore):
# byte-bounded like the result cache — an entry is ~a few hundred bytes,
# so the default bound holds tens of thousands of plan-node frames
HISTORY_CACHE = LRUCache(
    max_entries=_env_int("PRESTO_TPU_FEEDBACK_ENTRIES", 8192),
    max_bytes=_env_int("PRESTO_TPU_FEEDBACK_BYTES", 4 << 20),
    name="history",
)

_persistent_enabled = [False]


def enable_persistent_compile_cache() -> Optional[str]:
    """Point JAX's persistent compilation cache at
    PRESTO_TPU_COMPILE_CACHE_DIR (idempotent; no-op when unset or on a
    jax without the knob). Worker restarts then warm-start their XLA
    executables from disk instead of re-tracing + re-compiling."""
    cache_dir = os.environ.get("PRESTO_TPU_COMPILE_CACHE_DIR")
    if not cache_dir or _persistent_enabled[0]:
        return cache_dir if _persistent_enabled[0] else None
    try:
        import jax

        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # cache every executable, however small/fast — dashboard-query
        # kernels are exactly the small ones the default thresholds skip
        for knob, val in (
            ("jax_persistent_cache_min_entry_size_bytes", 0),
            ("jax_persistent_cache_min_compile_time_secs", 0.0),
        ):
            try:
                jax.config.update(knob, val)
            except Exception:  # noqa: BLE001 — older jax: keep defaults
                pass
        try:
            # a compile that ran BEFORE the dir was configured latches the
            # cache in its initialized-without-a-backend state; reset so
            # the next compile re-initializes against the new dir
            from jax.experimental.compilation_cache import (
                compilation_cache as _cc,
            )

            _cc.reset_cache()
        except Exception:  # noqa: BLE001 — older jax: best effort
            pass
        _persistent_enabled[0] = True
        return cache_dir
    except Exception:  # noqa: BLE001 — never fail serving for a cache dir
        return None


def snapshot_all() -> Dict[str, dict]:
    return {
        "plan": PLAN_CACHE.snapshot(),
        "result": RESULT_CACHE.snapshot(),
        "kernel": KERNEL_CACHE.snapshot(),
        "history": HISTORY_CACHE.snapshot(),
    }


def format_summary(snap: Dict[str, dict]) -> str:
    """One-line cache summary for EXPLAIN ANALYZE surfaces (the single
    formatter both the single-process and cluster renders share)."""
    parts = []
    for name in ("plan", "result", "kernel", "history"):
        s = snap.get(name)
        if s is None:
            continue
        line = f"{name} {s['hits']}h/{s['misses']}m/{s['evictions']}e"
        if s.get("patches"):
            line += f"/{s['patches']}p"
        if s.get("bytes"):
            line += f" {s['bytes']:,}B"
        parts.append(line)
    return ", ".join(parts)


def reset_all() -> None:
    """Test hook: drop every cached entry AND zero the counters."""
    for c in (PLAN_CACHE, RESULT_CACHE, KERNEL_CACHE, HISTORY_CACHE):
        c.reset()
    # the feedback store layers a generation counter and its own stats
    # over HISTORY_CACHE; reset those too or a cleared cache would keep
    # serving a stale generation to executor-side estimate caches
    from ..plan import history as _history

    _history.HISTORY.reset()
