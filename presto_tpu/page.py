"""Columnar Page/Block core as device-resident JAX arrays.

Re-designed equivalent of the reference data-plane representation
(presto-spi/src/main/java/com/facebook/presto/spi/Page.java:34 — "A Page is a
Block[]" — and the ~25 Block implementations under presto-spi/.../spi/block/).
TPU-first differences:

* A Block is a fixed-capacity device array plus a validity (non-null) mask,
  instead of variable-size heap memory. Static shapes keep everything
  jit-compilable; live row count is a *device scalar* on the Page.
* Rows in [0, capacity) beyond the live set are garbage and masked out by
  `Page.live_mask()`. This replaces the reference's dynamic page sizes and is
  the engine-wide convention all kernels in ops/ follow (capacity-padded pages
  + valid counts — the XLA answer to data-dependent shapes).
* Strings are dictionary codes (int32) over a host-side sorted tuple — the
  reference's DictionaryBlock (spi/block/DictionaryBlock.java) promoted to the
  *only* string representation on device.
* Block and Page are registered pytrees, so whole pages flow through jit /
  shard_map / all_to_all without manual flattening.

The reference's LazyBlock/RunLengthEncodedBlock have no device analog yet;
RLE-style constant blocks are represented by broadcasting at trace time.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import types as T


# Host-side dictionary interning: blocks carry a small int id instead of the
# string tuple, so (a) jit cache keys stay tiny, (b) equal dictionaries share
# one id and never force recompilation. Dictionaries are expected to be
# table-global per column (the tpch connector guarantees this), mirroring how
# the reference shares one DictionaryBlock dictionary across a whole segment.
_DICT_INTERN: dict = {}
_DICT_BY_ID: list = []


class LazyDict:
    """A dictionary whose entries are computed on demand — for huge formatted
    string domains (c_name = 'Customer#%09d', phones, …) where materializing
    tuples of millions of python strings would defeat the point of dictionary
    encoding. Subclasses must be hashable value objects and implement
    __len__/__getitem__; `is_sorted` declares whether entry order equals
    lexicographic order (required for <,>,ORDER BY on codes)."""

    is_sorted: bool = True

    def __len__(self) -> int:
        raise NotImplementedError

    def __getitem__(self, i: int) -> str:
        raise NotImplementedError

    def __iter__(self):
        return (self[i] for i in range(len(self)))


def intern_dictionary(d) -> int:
    key = d if isinstance(d, LazyDict) else tuple(d)
    did = _DICT_INTERN.get(key)
    if did is None:
        did = len(_DICT_BY_ID)
        _DICT_INTERN[key] = did
        _DICT_BY_ID.append(key)
    return did


def dictionary_by_id(did: int) -> Tuple[str, ...]:
    return _DICT_BY_ID[did]


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class Block:
    """One column: `data[capacity]` storage + `valid[capacity]` non-null mask.

    `valid is None` means "no nulls" (common fast path — skips mask math).
    `dict_id` identifies a host-side sorted tuple of strings for VARCHAR
    blocks (see intern_dictionary; static pytree aux data).
    """

    data: jax.Array
    type: T.Type
    valid: Optional[jax.Array] = None
    dict_id: Optional[int] = None
    # collection blocks only (ArrayType / MapType results, e.g. array_agg):
    # data is (capacity, width), `lengths` the per-row element counts,
    # `elem_valid` an optional per-element null mask, and `key_block` the
    # companion keys column of a MAP (reference ArrayBlock/MapBlock)
    lengths: Optional[jax.Array] = None
    elem_valid: Optional[jax.Array] = None
    key_block: Optional["Block"] = None

    @property
    def dictionary(self) -> Optional[Tuple[str, ...]]:
        return None if self.dict_id is None else dictionary_by_id(self.dict_id)

    # -- pytree protocol --
    def tree_flatten(self):
        children = [self.data]
        mask = 0
        if self.valid is not None:
            children.append(self.valid)
            mask |= 1
        if self.lengths is not None:
            children.append(self.lengths)
            mask |= 2
        if self.elem_valid is not None:
            children.append(self.elem_valid)
            mask |= 4
        if self.key_block is not None:
            children.append(self.key_block)
            mask |= 8
        return tuple(children), (self.type, self.dict_id, mask)

    @classmethod
    def tree_unflatten(cls, aux, children):
        typ, dict_id, mask = aux
        it = iter(children)
        data = next(it)
        valid = next(it) if mask & 1 else None
        lengths = next(it) if mask & 2 else None
        elem_valid = next(it) if mask & 4 else None
        key_block = next(it) if mask & 8 else None
        return cls(
            data=data, type=typ, valid=valid, dict_id=dict_id,
            lengths=lengths, elem_valid=elem_valid, key_block=key_block,
        )

    @property
    def capacity(self) -> int:
        return self.data.shape[0]

    def take_rows(self, idx) -> "Block":
        """Reindex every row-aligned array (gather/slice/permutation),
        preserving collection companions (lengths/elem_valid/key_block)."""
        return Block(
            self.data[idx],
            self.type,
            None if self.valid is None else self.valid[idx],
            self.dict_id,
            lengths=None if self.lengths is None else self.lengths[idx],
            elem_valid=(
                None if self.elem_valid is None else self.elem_valid[idx]
            ),
            key_block=(
                None if self.key_block is None else self.key_block.take_rows(idx)
            ),
        )

    def valid_mask(self) -> jax.Array:
        if self.valid is None:
            return jnp.ones(self.data.shape[0], dtype=jnp.bool_)
        return self.valid

    def with_dictionary(self, dictionary: Sequence[str]) -> "Block":
        return Block(self.data, self.type, self.valid, intern_dictionary(dictionary))

    # -- host-side constructors --
    @staticmethod
    def from_numpy(
        arr: np.ndarray,
        typ: T.Type,
        valid: Optional[np.ndarray] = None,
        dictionary: Optional[Sequence[str]] = None,
    ) -> "Block":
        data = jnp.asarray(arr, dtype=typ.storage_dtype)
        v = None if valid is None else jnp.asarray(valid, dtype=jnp.bool_)
        did = intern_dictionary(dictionary) if dictionary is not None else None
        return Block(data, typ, v, did)

    @staticmethod
    def from_strings(
        values: Sequence[Optional[str]],
        dictionary: Optional[Sequence[str]] = None,
    ) -> "Block":
        """Dictionary-encode python strings into a sorted-dictionary block.

        Pass a shared, pre-sorted `dictionary` whenever encoding repeated
        batches of one logical column — per-call derived dictionaries grow the
        intern table and force fresh jit compilations (see intern_dictionary).
        """
        present = [v for v in values if v is not None]
        if dictionary is None:
            dictionary = tuple(sorted(set(present)))
        else:
            dictionary = tuple(dictionary)
        index = {s: i for i, s in enumerate(dictionary)}
        codes = np.array([index[v] if v is not None else 0 for v in values], np.int32)
        valid = (
            None
            if len(present) == len(values)
            else np.array([v is not None for v in values], np.bool_)
        )
        return Block.from_numpy(codes, T.VARCHAR, valid, dictionary)

    def to_numpy(self) -> np.ndarray:
        return np.asarray(self.data)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class Page:
    """A batch of rows: positional blocks + column names + live row count.

    `count` is a device int32 scalar — the number of live rows. Live rows
    always occupy positions [0, count); kernels that produce scattered
    liveness (filters) compact or mask via `live_mask()`.
    """

    blocks: Tuple[Block, ...]
    names: Tuple[str, ...]
    count: jax.Array  # int32 scalar

    def tree_flatten(self):
        return (tuple(self.blocks), self.count), (self.names,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        blocks, count = children
        (names,) = aux
        return cls(blocks=tuple(blocks), names=names, count=count)

    # -- shape info --
    @property
    def capacity(self) -> int:
        return self.blocks[0].capacity if self.blocks else 0

    @property
    def num_columns(self) -> int:
        return len(self.blocks)

    def live_mask(self) -> jax.Array:
        return jnp.arange(self.capacity, dtype=jnp.int32) < self.count

    def block(self, name: str) -> Block:
        return self.blocks[self.names.index(name)]

    def channel(self, i: int) -> Block:
        return self.blocks[i]

    def types(self) -> Tuple[T.Type, ...]:
        return tuple(b.type for b in self.blocks)

    def with_columns(self, blocks: Sequence[Block], names: Sequence[str]) -> "Page":
        return Page(tuple(blocks), tuple(names), self.count)

    def select(self, names: Sequence[str]) -> "Page":
        return Page(tuple(self.block(n) for n in names), tuple(names), self.count)

    # -- construction --
    @staticmethod
    def from_blocks(blocks: Sequence[Block], names: Sequence[str], count=None) -> "Page":
        blocks = tuple(blocks)
        if count is None:
            count = blocks[0].capacity if blocks else 0
        return Page(blocks, tuple(names), jnp.asarray(count, jnp.int32))

    @staticmethod
    def from_dict(columns: dict, pad_to: Optional[int] = None) -> "Page":
        """Build a device page from {name: numpy array | (array, Type) | Block |
        list-of-strings}. Pads every column to `pad_to` capacity if given."""
        blocks = []
        names = []
        n = None
        for name, value in columns.items():
            blk = _to_block(value)
            if n is None:
                n = blk.capacity
            elif blk.capacity != n:
                raise ValueError(
                    f"column {name!r} has {blk.capacity} rows, expected {n}"
                )
            blocks.append(blk)
            names.append(name)
        if n is None:
            n = 0
        if pad_to is not None and pad_to != n:
            if pad_to < n:
                raise ValueError("pad_to smaller than data")
            blocks = [_pad_block(b, pad_to) for b in blocks]
        return Page.from_blocks(blocks, names, count=n)

    # -- host materialization --
    def to_pylist(self) -> list:
        """Materialize live rows as python tuples (decoding dictionaries;
        collection blocks decode to lists / dicts)."""
        n = int(self.count)
        cols = []
        for b in self.blocks:
            data = np.asarray(b.data[:n])
            valid = None if b.valid is None else np.asarray(b.valid[:n])
            if b.lengths is not None:
                cols.append(_collection_pylist(b, data, valid, n))
                continue
            col = []
            for i in range(n):
                if valid is not None and not valid[i]:
                    col.append(None)
                else:
                    col.append(b.type.to_python(data[i], b.dictionary))
            cols.append(col)
        return [tuple(row) for row in zip(*cols)] if cols else []

    def to_dict_of_numpy(self) -> dict:
        n = int(self.count)
        return {name: np.asarray(b.data[:n]) for name, b in zip(self.names, self.blocks)}


def _collection_pylist(b: Block, data, valid, n: int) -> list:
    """Decode an ArrayType / MapType block's rows to lists / dicts."""
    lens = np.asarray(b.lengths[:n])
    ev = None if b.elem_valid is None else np.asarray(b.elem_valid[:n])
    if isinstance(b.type, T.MapType):
        kb = b.key_block
        kdata = np.asarray(kb.data[:n])
        kt, vt = b.type.key, b.type.value
        col = []
        if data.ndim == 3:
            # array-valued map (multimap_agg): values per key ride the
            # third axis, liveness in the 3-D elem_valid
            et = vt.element
            for i in range(n):
                if valid is not None and not valid[i]:
                    col.append(None)
                    continue
                row = {}
                for j in range(int(lens[i])):
                    k = kt.to_python(kdata[i, j], kb.dictionary)
                    row[k] = [
                        et.to_python(data[i, j, e], b.dictionary)
                        for e in range(data.shape[2])
                        if ev is None or ev[i, j, e]
                    ]
                col.append(row)
            return col
        for i in range(n):
            if valid is not None and not valid[i]:
                col.append(None)
                continue
            row = {}
            for j in range(int(lens[i])):
                k = kt.to_python(kdata[i, j], kb.dictionary)
                if ev is not None and not ev[i, j]:
                    row[k] = None
                else:
                    row[k] = vt.to_python(data[i, j], b.dictionary)
            col.append(row)
        return col
    et = b.type.element
    col = []
    for i in range(n):
        if valid is not None and not valid[i]:
            col.append(None)
            continue
        col.append(
            [
                None
                if ev is not None and not ev[i, j]
                else et.to_python(data[i, j], b.dictionary)
                for j in range(int(lens[i]))
            ]
        )
    return col


def _to_block(value) -> Block:
    if isinstance(value, Block):
        return value
    if isinstance(value, tuple) and len(value) == 2 and isinstance(value[1], T.Type):
        arr, typ = value
        return Block.from_numpy(np.asarray(arr), typ)
    if isinstance(value, (list,)) and value and isinstance(value[0], (str, type(None))):
        return Block.from_strings(value)
    arr = np.asarray(value)
    typ = _infer_type(arr)
    return Block.from_numpy(arr, typ)


def _infer_type(arr: np.ndarray) -> T.Type:
    if arr.dtype == np.bool_:
        return T.BOOLEAN
    if np.issubdtype(arr.dtype, np.integer):
        return T.BIGINT if arr.dtype.itemsize > 4 else T.INTEGER
    if np.issubdtype(arr.dtype, np.floating):
        return T.DOUBLE
    raise TypeError(f"cannot infer SQL type for dtype {arr.dtype}")


def _pad_block(b: Block, capacity: int) -> Block:
    n = b.capacity
    pad = capacity - n

    def padarr(x, fill_bool=False):
        if x is None:
            return None
        z = (
            jnp.zeros((pad,) + x.shape[1:], x.dtype)
            if not fill_bool
            else jnp.zeros((pad,) + x.shape[1:], jnp.bool_)
        )
        return jnp.concatenate([x, z])

    return Block(
        padarr(b.data),
        b.type,
        padarr(b.valid, True),
        b.dict_id,
        lengths=padarr(b.lengths),
        elem_valid=padarr(b.elem_valid, True),
        key_block=None if b.key_block is None else _pad_block(b.key_block, capacity),
    )


def round_capacity(n: int, minimum: int = 16) -> int:
    """Bucket a row count to the next power of two (bounded recompilation —
    the analog of the reference's adaptive batch sizing in
    presto-main/.../sql/gen/PageFunctionCompiler)."""
    cap = minimum
    while cap < n:
        cap *= 2
    return cap
