"""Query verifier: replay a suite against two engines and diff results.

Re-designed equivalent of presto-verifier (presto-verifier/src/main/java/
com/facebook/presto/verifier/Verifier.java + Validator.java: run each
query on a control and a test cluster, compare row counts and checksums,
report mismatches). Targets are either REST coordinator URIs or
in-process Sessions; comparison uses an order-insensitive row digest
with type-aware float tolerance, like Validator's checksum queries.

CLI:  python -m presto_tpu.verifier --control URI --test URI suite.sql
      (suite file: semicolon-separated statements; lines starting with
      -- are comments)
"""

from __future__ import annotations

import dataclasses
import hashlib
import math
import time
from typing import List, Optional, Sequence, Tuple


@dataclasses.dataclass
class VerifyResult:
    query: str
    status: str  # MATCH | MISMATCH | CONTROL_FAILED | TEST_FAILED
    detail: str = ""
    control_ms: float = 0.0
    test_ms: float = 0.0
    control_rows: Optional[int] = None
    test_rows: Optional[int] = None


class SessionTarget:
    """In-process target (LocalQueryRunner analog)."""

    def __init__(self, session):
        self.session = session

    def execute(self, sql: str) -> List[tuple]:
        return self.session.query(sql).rows()


class RestTarget:
    """REST coordinator target (the verifier's JDBC analog)."""

    def __init__(self, uri: str):
        from .server.client import Client

        self.client = Client(uri)

    def execute(self, sql: str) -> List[tuple]:
        _cols, rows = self.client.execute(sql)
        return [tuple(r) for r in rows]


def _canon_value(v, float_digits: int = 6):
    if v is None:
        return "\x00null"
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float):
        if math.isnan(v):
            return "nan"
        return f"{v:.{float_digits}e}"
    try:  # numpy scalars, Decimal, dates
        if isinstance(v, complex):
            raise TypeError(v)
        f = float(v)
        if not isinstance(v, int) and f != int(f):
            return f"{f:.{float_digits}e}"
    except (TypeError, ValueError):
        pass
    return str(v)


def row_digest(rows: Sequence[tuple]) -> Tuple[int, str]:
    """(count, order-insensitive content digest). Modular SUM of per-row
    hashes — order-free and mergeable like the reference's checksum
    aggregation, but unlike XOR it does not cancel rows that repeat an
    even number of times."""
    acc = 0
    for r in rows:
        h = hashlib.sha256(
            "\x01".join(_canon_value(v) for v in r).encode()
        ).digest()
        acc = (acc + int.from_bytes(h[:16], "big")) % (1 << 128)
    return len(rows), f"{acc:032x}"


def verify_query(control, test, sql: str) -> VerifyResult:
    t0 = time.perf_counter()
    try:
        c_rows = control.execute(sql)
    except Exception as e:  # noqa: BLE001 - reported, not raised
        return VerifyResult(
            sql, "CONTROL_FAILED", f"{type(e).__name__}: {e}"
        )
    t1 = time.perf_counter()
    try:
        t_rows = test.execute(sql)
    except Exception as e:  # noqa: BLE001 - reported, not raised
        return VerifyResult(
            sql, "TEST_FAILED", f"{type(e).__name__}: {e}",
            control_ms=(t1 - t0) * 1e3,
        )
    t2 = time.perf_counter()
    cn, cd = row_digest(c_rows)
    tn, td = row_digest(t_rows)
    if cn != tn:
        status, detail = "MISMATCH", f"row count {cn} != {tn}"
    elif cd != td:
        status, detail = "MISMATCH", "checksum differs"
    else:
        status, detail = "MATCH", ""
    return VerifyResult(
        sql, status, detail,
        control_ms=(t1 - t0) * 1e3, test_ms=(t2 - t1) * 1e3,
        control_rows=cn, test_rows=tn,
    )


def verify_suite(control, test, queries: Sequence[str]) -> List[VerifyResult]:
    return [verify_query(control, test, q) for q in queries]


def load_suite(path: str) -> List[str]:
    text = open(path).read()
    lines = [
        line for line in text.splitlines()
        if not line.strip().startswith("--")
    ]
    return [q.strip() for q in "\n".join(lines).split(";") if q.strip()]


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--control", required=True, help="control coordinator URI")
    p.add_argument("--test", required=True, help="test coordinator URI")
    p.add_argument("suite", help="semicolon-separated SQL file")
    args = p.parse_args(argv)
    results = verify_suite(
        RestTarget(args.control), RestTarget(args.test),
        load_suite(args.suite),
    )
    bad = 0
    for r in results:
        line = f"{r.status:16s} {r.control_ms:8.1f}ms {r.test_ms:8.1f}ms  "
        line += r.query.replace("\n", " ")[:80]
        if r.detail:
            line += f"  [{r.detail}]"
        print(line)
        bad += r.status != "MATCH"
    print(f"# {len(results) - bad}/{len(results)} matched")
    return 1 if bad else 0


if __name__ == "__main__":
    raise SystemExit(main())
