"""Session: SQL in, rows out.

The single-process equivalent of the reference's LocalQueryRunner
(presto-main/.../testing/LocalQueryRunner.java:204 — full
parse->plan->execute in one process, no HTTP), and the embedding API the
CLI/server layers build on.
"""

from __future__ import annotations

from typing import List, Optional

from .exec.executor import Executor
from .plan import nodes as N
from .sql import tree as t
from .sql.parser import parse
from .sql.planner import Planner


class QueryResult:
    def __init__(self, page, titles):
        self.page = page
        self.titles = list(titles)

    def rows(self) -> List[tuple]:
        return self.page.to_pylist()

    def row_count(self) -> int:
        return int(self.page.count)


class Session:
    """mesh=None runs single-device; passing a jax.sharding.Mesh fragments
    every plan (plan/fragment.py) and executes it distributed over the
    mesh's worker axis (exec/dist.py) — the analog of LocalQueryRunner vs
    DistributedQueryRunner (presto-tests/.../DistributedQueryRunner.java:75)."""

    def __init__(
        self,
        catalog,
        mesh=None,
        broadcast_threshold: int = 1_000_000,
        streaming: bool = False,
        batch_rows: int = 1 << 20,
        memory_budget=None,
    ):
        self.catalog = catalog
        self.mesh = mesh
        self.broadcast_threshold = broadcast_threshold
        if mesh is not None:
            from .exec.dist import DistributedExecutor

            self.executor = DistributedExecutor(catalog, mesh)
        elif streaming:
            from .exec.stream import StreamingExecutor

            self.executor = StreamingExecutor(
                catalog, batch_rows=batch_rows, memory_budget=memory_budget
            )
        else:
            self.executor = Executor(catalog)
        self.streaming = streaming
        self.batch_rows = batch_rows
        self.memory_budget = memory_budget

    def plan(self, sql: str) -> N.PlanNode:
        ast = parse(sql)
        if isinstance(ast, t.Explain):
            ast = ast.query
        if not isinstance(ast, t.Query):
            raise ValueError("only SELECT queries supported here")
        planner = Planner(self.catalog)
        rp = planner.plan_query(ast, outer=None, ctes={})
        scope = rp.scope
        channels = tuple(f.channel for f in scope.fields)
        titles = tuple(f.name for f in scope.fields)
        from .plan.optimizer import optimize

        node = optimize(N.Output(rp.node, channels, titles))
        if self.mesh is not None:
            from .plan.fragment import fragment_plan

            node = fragment_plan(
                node, self.catalog, self.broadcast_threshold
            )
        return node

    def explain(self, sql: str) -> str:
        return N.plan_tree_str(self.plan(sql))

    def query(self, sql: str) -> QueryResult:
        ast = parse(sql)
        node = self.plan(sql)
        if isinstance(ast, t.Explain):
            from .page import Page

            if ast.analyze:
                lines = self.explain_analyze_plan(node).split("\n")
            else:
                lines = N.plan_tree_str(node).split("\n")
            pg = Page.from_dict({"Query Plan": lines})
            return QueryResult(pg, ("Query Plan",))
        page = self.executor.run(node)
        return QueryResult(page, node.titles)

    def explain_analyze_plan(self, node: N.PlanNode) -> str:
        """Execute the plan with per-operator accounting and render the
        annotated tree (reference EXPLAIN ANALYZE via ExplainAnalyzeOperator,
        presto-main/.../execution/ExplainAnalyzeContext.java)."""
        from .exec.stats import StatsCollector

        collector = StatsCollector()
        if self.mesh is not None:
            from .exec.dist import DistributedExecutor

            ex = DistributedExecutor(self.catalog, self.mesh, collector=collector)
        elif self.streaming:
            # profile the SAME engine the session runs: streamed batches
            # under the session's memory budget (per-node stats cover the
            # kernels the streaming driver delegates to the local executor)
            from .exec.stream import StreamingExecutor

            ex = StreamingExecutor(
                self.catalog,
                batch_rows=self.batch_rows,
                memory_budget=self.memory_budget,
                collector=collector,
            )
        else:
            ex = Executor(self.catalog, collector=collector)
        ex.run(node)
        tree = N.plan_tree_str(node, collector=collector)
        total_ms = collector.total_wall_s() * 1e3
        peak = collector.peak_bytes / (1024 * 1024)
        return (
            f"{tree}\n"
            f"-- total {total_ms:,.1f}ms, peak live output {peak:,.2f}MB"
        )

    def explain_analyze(self, sql: str) -> str:
        return self.explain_analyze_plan(self.plan(sql))
