"""Session: SQL in, rows out.

The single-process equivalent of the reference's LocalQueryRunner
(presto-main/.../testing/LocalQueryRunner.java:204 — full
parse->plan->execute in one process, no HTTP), and the embedding API the
CLI/server layers build on.
"""

from __future__ import annotations

from typing import List, Optional

from .exec.executor import Executor
from .plan import nodes as N
from .sql import tree as t
from .sql.parser import parse
from .sql.planner import Planner


def _opt_f64(values):
    """Optional-float column: (data, valid-aware) numpy for Page.from_dict."""
    import numpy as np

    from .page import Block
    from . import types as T

    data = np.array(
        [0.0 if v is None else float(v) for v in values], np.float64
    )
    valid = np.array([v is not None for v in values], bool)
    return Block.from_numpy(
        data, T.DOUBLE, valid=None if valid.all() else valid
    )


class QueryResult:
    def __init__(self, page, titles):
        self.page = page
        self.titles = list(titles)
        # observability plane (docs/observability.md): set by the traced
        # dispatch paths; None when tracing is off or N/A (DDL, EXPLAIN)
        self.trace_id: Optional[str] = None
        self.phase_ms: Optional[dict] = None

    def rows(self) -> List[tuple]:
        return self.page.to_pylist()

    def row_count(self) -> int:
        return int(self.page.count)


# system session properties: per-query engine overrides (reference
# SystemSessionProperties — 49+ properties; these are the ones this
# engine's executors actually read). Each entry: parser from string.
def _parse_bool(v: str) -> bool:
    if str(v).lower() in ("true", "1", "yes"):
        return True
    if str(v).lower() in ("false", "0", "no"):
        return False
    raise ValueError(f"invalid boolean {v!r}")


SESSION_PROPERTIES = {
    "broadcast_threshold": int,   # join build-side broadcast cutover (rows)
    "streaming": _parse_bool,     # paged scans through the streaming driver
    "batch_rows": int,            # streaming scan batch size
    "memory_budget": int,         # device-memory budget (bytes)
    "query_priority": int,        # resource-group query_priority policy
    "pallas_groupby": _parse_bool,  # small-G aggregation via the Pallas kernel
    "matmul_groupby": _parse_bool,  # dense-key aggregation via MXU matmuls
    "dynamic_filtering": _parse_bool,  # build-side runtime filters on probes
    "plan_cache": _parse_bool,    # serve plans from exec/qcache.PLAN_CACHE
    "result_cache": _parse_bool,  # serve results from exec/qcache.RESULT_CACHE
}


def parse_session_properties(text: str) -> dict:
    """Parse 'k=v,k=v' (the X-Presto-Session header format,
    presto-client/.../PrestoHeaders.java) with type checking."""
    props = {}
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"invalid session property {part!r}")
        k, v = part.split("=", 1)
        k = k.strip().lower()
        parser = SESSION_PROPERTIES.get(k)
        if parser is None:
            raise ValueError(f"unknown session property {k!r}")
        props[k] = parser(v.strip())
    return props


class Session:
    """mesh=None runs single-device; passing a jax.sharding.Mesh fragments
    every plan (plan/fragment.py) and executes it distributed over the
    mesh's worker axis (exec/dist.py) — the analog of LocalQueryRunner vs
    DistributedQueryRunner (presto-tests/.../DistributedQueryRunner.java:75)."""

    def __init__(
        self,
        catalog,
        mesh=None,
        broadcast_threshold=None,  # None = cost-based distribution
        streaming: bool = False,
        batch_rows: int = 1 << 20,
        memory_budget=None,
        access_control=None,
        user: str = "user",
        pallas_groupby=None,  # None = auto (ON on TPU, OFF on CPU)
        matmul_groupby=None,  # None = auto (ON on TPU, OFF on CPU)
        exchange_budget=None,  # per-shard bytes for exchanged joins
        dynamic_filtering: bool = True,  # build-side runtime join filters
        plan_cache: bool = True,    # plan/skeleton reuse (exec/qcache.py)
        result_cache: bool = True,  # snapshot-validated result reuse
    ):
        self.access_control = access_control
        self.user = user
        self.catalog = catalog
        self.mesh = mesh
        self.broadcast_threshold = broadcast_threshold
        self.exchange_budget = exchange_budget
        if mesh is not None:
            from .exec.dist import DistributedExecutor

            self.executor = DistributedExecutor(
                catalog, mesh, exchange_budget=exchange_budget
            )
        elif streaming:
            from .exec.stream import StreamingExecutor

            self.executor = StreamingExecutor(
                catalog, batch_rows=batch_rows, memory_budget=memory_budget
            )
        else:
            self.executor = Executor(catalog)
        self.streaming = streaming
        self.batch_rows = batch_rows
        self.memory_budget = memory_budget
        self.pallas_groupby = pallas_groupby
        self.matmul_groupby = matmul_groupby
        self.dynamic_filtering = dynamic_filtering
        self.plan_cache = plan_cache
        self.result_cache = result_cache
        local = getattr(self.executor, "local", self.executor)
        if pallas_groupby is not None and hasattr(local, "pallas_groupby"):
            local.pallas_groupby = pallas_groupby
        if matmul_groupby is not None and hasattr(local, "matmul_groupby"):
            local.matmul_groupby = matmul_groupby
        if hasattr(local, "dynamic_filtering"):
            local.dynamic_filtering = dynamic_filtering
        # statement-layer state (shared BY REFERENCE with derived
        # property-override sessions, see with_properties)
        self.views: dict = {}  # name -> view query SQL
        self.prepared: dict = {}  # name -> prepared statement SQL
        self.schemas = {"default"}
        self._session_overrides: dict = {}  # SET SESSION k = v
        from .matview.manager import MatViewManager

        self.matviews_mgr = MatViewManager(self)
        self._attach_matviews()

    def _attach_matviews(self) -> None:
        """Point the routing SystemCatalog (if any, connectors/system.py)
        at this session's MV registry so system.runtime.materialized_views
        serves live rows. Walks the .wrapped chain; only a catalog that
        DECLARES the slot (SystemCatalog sets it to None in __init__)
        gets it — __getattr__ delegators must not be tricked by hasattr."""
        probe = self.catalog
        while probe is not None:
            if "matview_manager" in getattr(probe, "__dict__", {}):
                probe.matview_manager = self.matviews_mgr
                return
            probe = getattr(probe, "wrapped", None)

    def _swap_catalog(self, catalog) -> None:
        """Point the session AND its executors at a different catalog
        (transaction overlay enter/exit)."""
        self.catalog = catalog
        self.executor.catalog = catalog
        local = getattr(self.executor, "local", None)
        if local is not None:
            local.catalog = catalog

    def with_properties(self, props: dict) -> "Session":
        """A sibling session with per-query property overrides applied
        (reference: Session.withSystemProperty). Non-engine properties
        (query_priority) are admission-control metadata and ignored here.
        Derived sessions are cached per property set so repeat clients
        reuse compiled kernels instead of rebuilding executors."""
        engine = {k: v for k, v in props.items() if k != "query_priority"}
        if not engine:
            return self
        key = tuple(sorted(engine.items()))
        cache = getattr(self, "_prop_sessions", None)
        if cache is None:
            cache = self._prop_sessions = {}
        derived = cache.get(key)
        if derived is not None and derived.catalog is not self.catalog:
            # the base session's catalog moved (transaction overlay
            # enter/exit) after this derived session was cached — repoint
            # it or reads would miss the transaction's own writes
            derived._swap_catalog(self.catalog)
        if derived is None:
            if len(cache) >= 16:  # bound server memory: FIFO-evict
                cache.pop(next(iter(cache)))
            derived = Session(
                self.catalog,
                mesh=self.mesh,
                broadcast_threshold=engine.get(
                    "broadcast_threshold", self.broadcast_threshold
                ),
                streaming=engine.get("streaming", self.streaming),
                batch_rows=engine.get("batch_rows", self.batch_rows),
                memory_budget=engine.get("memory_budget", self.memory_budget),
                access_control=self.access_control,
                user=self.user,
                pallas_groupby=engine.get(
                    "pallas_groupby", self.pallas_groupby
                ),
                matmul_groupby=engine.get(
                    "matmul_groupby", self.matmul_groupby
                ),
                dynamic_filtering=engine.get(
                    "dynamic_filtering", self.dynamic_filtering
                ),
                exchange_budget=self.exchange_budget,
                plan_cache=engine.get("plan_cache", self.plan_cache),
                result_cache=engine.get("result_cache", self.result_cache),
            )
            # statement-layer state is session-wide, not per-override
            derived.views = self.views
            derived.prepared = self.prepared
            derived.schemas = self.schemas
            derived.matviews_mgr = self.matviews_mgr
            # derived's __init__ attached its own (now orphaned) manager
            # to the shared SystemCatalog — re-attach the session-wide one
            self._attach_matviews()
            cache[key] = derived
        return derived

    def plan(self, sql: str) -> N.PlanNode:
        ast = parse(sql)
        if isinstance(ast, t.Explain):
            ast = ast.query
        if not isinstance(ast, t.Query):
            raise ValueError("only SELECT queries supported here")
        return self._plan_query_cached(ast)

    # -- plan cache (exec/qcache.py) --

    def _plan_env_key(self):
        """Planning-relevant session state: plans keyed by the same AST
        are only interchangeable within one catalog object, view set,
        join-distribution config, mesh width, and feedback-store
        generation (plan/history.py: a recorded observation or an
        invalidation must re-plan, never reuse a plan built on
        superseded history)."""
        from .plan.history import plan_env_token

        mesh_n = self.mesh.devices.size if self.mesh is not None else 0
        views_fp = tuple(sorted(self.views.items())) if self.views else ()
        return (id(self.catalog), mesh_n, self.broadcast_threshold,
                views_fp, plan_env_token())

    def _engine_env_key(self):
        """Execution-engine identity, part of the RESULT cache key: two
        sessions only share materialized pages when they would execute
        the same way. Results are oracle-equal across engines, but what
        an execution PRODUCES also includes observability (spill events,
        dynamic-filter stats, breaker counters) and A/B harnesses rely
        on differently-configured sessions actually executing."""
        return (
            type(self.executor).__name__,
            self.streaming,
            self.batch_rows,
            self.memory_budget,
            self.exchange_budget,
            self.pallas_groupby,
            self.matmul_groupby,
            self.dynamic_filtering,
        )

    def _plan_query_uncached(self, ast: t.Query) -> N.PlanNode:
        planner = Planner(self.catalog, views=self.views)
        rp = planner.plan_query(ast, outer=None, ctes={})
        scope = rp.scope
        channels = tuple(f.channel for f in scope.fields)
        titles = tuple(f.name for f in scope.fields)
        from .plan.optimizer import optimize

        node = optimize(N.Output(rp.node, channels, titles))
        if self.mesh is not None:
            from .plan.fragment import fragment_plan

            node = fragment_plan(
                node, self.catalog, self.broadcast_threshold,
                num_workers=self.mesh.devices.size,
            )
        return node

    def _plan_query_cached(self, ast: t.Query) -> N.PlanNode:
        """Plan via the process-wide plan cache. Entries are validated
        against the catalog object AND every referenced table's connector
        snapshot version, so a write (which can change schemas and the
        CBO stats planning depends on) replans; unversioned connectors
        are never cached."""
        from .exec import qcache

        if not self.plan_cache:
            return self._plan_query_uncached(ast)
        key = ("q", ast, self._plan_env_key())
        ent = qcache.PLAN_CACHE.lookup(key, self.catalog)
        if ent is not None:
            return ent.plan
        node = self._plan_query_uncached(ast)
        qcache.PLAN_CACHE.store(key, node, self.catalog)
        return node

    def explain(self, sql: str) -> str:
        from .plan.stats import StatsDeriver

        return N.plan_tree_str(
            self.plan(sql), stats_of=StatsDeriver(self.catalog).stats
        )

    def query(self, sql: str, user: Optional[str] = None) -> QueryResult:
        ast = parse(sql)
        # explicit empty-string identity must NOT fall back to the
        # (possibly privileged) session default
        effective = self.user if user is None else user
        if self.access_control is not None:
            from .security import enforce

            enforce(self.access_control, effective, ast, views=self.views)
        if isinstance(
            ast,
            (t.CreateTable, t.DropTable, t.Insert, t.Delete, t.ShowTables,
             t.ShowColumns, t.StartTransaction, t.Commit, t.Rollback,
             t.CreateView, t.DropView, t.ShowCreateView, t.CreateSchema,
             t.DropSchema, t.ShowSchemas, t.Prepare, t.ExecutePrepared,
             t.Deallocate, t.DescribeInput, t.DescribeOutput, t.SetSession,
             t.ResetSession, t.ShowSession, t.RenameTable, t.RenameColumn,
             t.AddColumn, t.DropColumn, t.Grant, t.Revoke,
             t.ShowFunctions, t.ShowCatalogs, t.ShowCreateTable,
             t.ShowStats, t.Use, t.Analyze, t.ShowGrants,
             t.CreateMaterializedView, t.RefreshMaterializedView,
             t.DropMaterializedView),
        ):
            # the user travels as an argument: the Session is shared across
            # QueryManager worker threads, so instance state would race
            return self._execute_statement(ast, effective)
        if self._session_overrides:
            # SET SESSION overrides route plain queries through the
            # derived-session cache (reference: Session.withSystemProperty)
            return self.with_properties(dict(self._session_overrides))._dispatch_query(
                sql, ast, effective
            )
        return self._dispatch_query(sql, ast, effective)

    def _dispatch_query(self, sql, ast, effective):
        if not isinstance(ast, t.Explain):
            # plain SELECT: the result-cache fast path, under plan /
            # execute phase spans when the observability plane is on
            return self._run_select_traced(sql)
        node = self.plan(sql)
        from .page import Page

        etype = getattr(ast, "etype", "logical")
        if ast.analyze:
            lines = self.explain_analyze_plan(node).split("\n")
        elif etype == "validate":
            # reference ExplainTask TYPE VALIDATE: analysis+planning
            # succeeded if we got here
            pg = Page.from_dict({"Valid": [True]})
            return QueryResult(pg, ("Valid",))
        elif etype == "io":
            # reference IOPlanPrinter: the tables/columns the plan reads
            scans = []

            def walk(n):
                if isinstance(n, N.TableScan):
                    cols = ", ".join(c for _, c, _ in n.columns)
                    scans.append(f"{n.table} [{cols}]")
                for c in n.children:
                    walk(c)

            walk(node)
            pg = Page.from_dict({"Table": scans or [None]})
            if not scans:
                pg = Page(pg.blocks, pg.names, 0)
            return QueryResult(pg, ("Table",))
        elif etype == "distributed":
            # reference PlanPrinter.textDistributedPlan over fragments
            from .plan.fragment import fragment_plan

            workers = (
                self.mesh.devices.size if self.mesh is not None else 2
            )
            froot = fragment_plan(
                node, self.catalog, self.broadcast_threshold,
                num_workers=workers,
            )
            lines = N.plan_tree_str(froot).split("\n")
        else:
            lines = N.plan_tree_str(node).split("\n")
        pg = Page.from_dict({"Query Plan": lines})
        return QueryResult(pg, ("Query Plan",))

    def _run_select_traced(self, sql: str) -> QueryResult:
        """Plan + execute with per-phase spans. The trace lands in the
        process TraceStore (system.runtime.tasks), the phase timings on
        the QueryResult (and from there on the query_completed event),
        and the completion counters in the metrics registry."""
        from .obs import span as obs_span

        if not obs_span.enabled():
            return self._execute_plan_cached(self.plan(sql))
        from .obs.export import export_query

        trace = obs_span.TRACES.new_trace()
        root = trace.begin("query", sql=sql[:200])
        status = "ok"
        phase_ms: dict = {}
        try:
            span = trace.begin("plan", parent=root)
            node = self.plan(sql)
            trace.finish(span)
            phase_ms["plan"] = round(span.wall_s * 1e3, 3)
            span = trace.begin("execute", parent=root)
            res = self._execute_plan_cached(node)
            trace.finish(span, rows=res.row_count())
            phase_ms["execute"] = round(span.wall_s * 1e3, 3)
            res.trace_id = trace.trace_id
            res.phase_ms = phase_ms
            return res
        except Exception:
            status = "error"
            raise
        finally:
            trace.finish(root, status)
            export_query(status, root.wall_s, phase_ms)

    def _execute_plan_cached(self, node) -> QueryResult:
        """Execute a planned query through the result cache: a hit serves
        the materialized page without touching the executor; a miss
        executes and stores under the snapshot versions read BEFORE
        execution (a concurrent writer can only waste the entry, never
        stale it). Plans over unversioned connectors, TABLESAMPLE, or
        nondeterministic functions bypass the cache entirely."""
        from .exec import qcache

        if not self.result_cache:
            return QueryResult(self.executor.run(node), node.titles)
        key = ("r", node, self._plan_env_key(), self._engine_env_key())
        hit = qcache.RESULT_CACHE.lookup(key, self.catalog)
        if hit is not None:
            return QueryResult(hit.page, hit.titles)
        pre = qcache.RESULT_CACHE.preversions(node, self.catalog)
        page = self._run_observed(node)
        if pre is not None and qcache.plan_is_deterministic(node):
            qcache.RESULT_CACHE.store(
                key, page, node.titles, self.catalog, pre
            )
        return QueryResult(page, node.titles)

    def _run_observed(self, node):
        """Observe-once execution hook for history-based feedback
        (plan/history.py): when the plane is on AND the store lacks a
        live entry for some frame of this plan, run through a fresh
        collector-attached executor (the explain_analyze construction —
        the shared session executor can't have a collector swapped in
        per query under the server's concurrency) and fold the observed
        cardinalities in at completion. Plans whose frames are all
        remembered take the plain path: the warm cost is one store walk,
        not an instrumented run."""
        try:
            from .plan import history as H

            observe = H.feedback_on() and H.HISTORY.wants_observation(
                node, self.catalog
            )
        except Exception as exc:  # noqa: BLE001 — degrade, don't fail
            from .exec.breaker import BREAKERS

            BREAKERS.record_failure("adaptive_plan", repr(exc))
            observe = False
        if not observe:
            return self.executor.run(node)
        from .exec.stats import StatsCollector

        collector = StatsCollector()
        ex = self._collector_executor(collector)
        page = ex.run(node)
        try:
            H.HISTORY.record_plan(node, collector, self.catalog)
        except Exception as exc:  # noqa: BLE001 — bookkeeping only
            from .exec.breaker import BREAKERS

            BREAKERS.record_failure("adaptive_plan", repr(exc))
        return page

    # -- DDL / DML tasks (reference execution/CreateTableTask.java,
    # CreateTableAsSelect via TableWriter/TableFinish operators,
    # operator/TableWriterOperator.java, operator/DeleteOperator.java;
    # re-designed: the coordinator task runs the source plan through the
    # session's executor and hands final pages to the writable connector) --

    def _writable(self):
        from .connectors.spi import WritableConnector, WriteError

        # unwrap routing catalogs (connectors/system.py SystemCatalog)
        cat = self.catalog
        probe = cat
        while probe is not None and not isinstance(probe, WritableConnector):
            probe = getattr(probe, "wrapped", None)
        if probe is None:
            raise WriteError(
                f"catalog {getattr(cat, 'name', '?')!r} is read-only"
            )
        return cat

    def _run_query_ast(self, ast: t.Query):
        """Plan + execute a Query AST; returns (page, titles, scope).
        Plans come from the snapshot-validated plan cache; results are
        NOT result-cached here (DML sources execute fresh)."""
        node = self._plan_query_cached(ast)
        return self.executor.run(node), node.titles, None

    def _table_schema(self, cat, name: str):
        if name not in cat.table_names():
            raise ValueError(f"table {name!r} does not exist")
        return cat.schema(name)

    @staticmethod
    def _row_count_result(n: int) -> QueryResult:
        import numpy as np

        from .page import Page

        pg = Page.from_dict({"rows": np.array([n], dtype=np.int64)})
        return QueryResult(pg, ("rows",))

    @staticmethod
    def _like_filter(names, pat):
        """SQL LIKE pattern over a name list (SHOW ... LIKE 'x%')."""
        if pat is None:
            return names
        import re

        rx = re.compile(
            "^" + re.escape(pat).replace("%", ".*").replace("_", ".") + "$",
            re.IGNORECASE,
        )
        return [n for n in names if rx.match(n)]

    def _execute_statement(self, ast, user: Optional[str] = None) -> QueryResult:
        from .page import Page

        if user is None:
            user = self.user

        if isinstance(ast, t.Use):
            # reference UseTask: switch the session default catalog/schema.
            # With a CatalogStore the used catalog becomes the FIRST
            # bare-name resolver (per-session copy, no global mutation).
            from .server.catalog_store import CatalogStore

            cat_name, schema = ast.catalog, ast.schema
            if cat_name is None and isinstance(self.catalog, CatalogStore) \
                    and schema in self.catalog.catalogs:
                cat_name, schema = schema, "default"
            if cat_name is not None:
                if not isinstance(self.catalog, CatalogStore) or \
                        cat_name not in self.catalog.catalogs:
                    raise ValueError(f"catalog {cat_name!r} does not exist")
                ordered = {cat_name: self.catalog.catalogs[cat_name]}
                ordered.update(self.catalog.catalogs)
                self._swap_catalog(CatalogStore(ordered))
            elif schema not in self.schemas:
                raise ValueError(f"schema {schema!r} does not exist")
            self.current_schema = schema
            return self._row_count_result(0)

        if isinstance(ast, t.Analyze):
            # reference AnalyzeTask: collect and materialize table stats
            # (here: force column-stat derivation through the CBO path and
            # report the analyzed row count)
            name = ast.table.lower()
            schema = self._table_schema(self.catalog, name)
            get = getattr(self.catalog, "column_stats", None)
            if get is not None:
                for c in schema:
                    get(name, c)  # populates the connector's stats cache
            return self._row_count_result(
                int(self.catalog.row_count(name))
            )

        if isinstance(ast, t.ShowTables):
            # views list alongside tables (reference ShowQueriesRewrite:
            # information_schema.tables carries both)
            names = sorted(set(self.catalog.table_names()) | set(self.views))
            names = self._like_filter(names, ast.like)
            if self.access_control is not None:
                # filter out tables the user cannot read (reference
                # SystemAccessControl.filterTables)
                from .security import AccessDeniedError

                visible = []
                for n in names:
                    try:
                        self.access_control.check_can_select_from_table(
                            user, n
                        )
                        visible.append(n)
                    except AccessDeniedError:
                        pass
                names = visible
            pg = Page.from_dict({"Table": list(names) or [None]})
            if not names:
                pg = Page(pg.blocks, pg.names, 0)
            return QueryResult(pg, ("Table",))
        if isinstance(ast, t.ShowColumns):
            schema = self._table_schema(self.catalog, ast.table.lower())
            pg = Page.from_dict(
                {
                    "Column": list(schema),
                    "Type": [str(ty) for ty in schema.values()],
                }
            )
            return QueryResult(pg, ("Column", "Type"))
        if isinstance(ast, t.StartTransaction):
            if getattr(self, "_txn", None) is not None:
                raise ValueError("transaction already in progress")
            from .exec.transaction import TransactionCatalog

            self._txn_base = self.catalog
            self._txn = TransactionCatalog(self._writable())
            self._swap_catalog(self._txn)
            return self._row_count_result(0)
        if isinstance(ast, (t.Commit, t.Rollback)):
            txn = getattr(self, "_txn", None)
            if txn is None:
                raise ValueError("no transaction in progress")
            try:
                if isinstance(ast, t.Commit):
                    txn.commit()
                else:
                    txn.rollback()
            finally:
                self._swap_catalog(self._txn_base)
                self._txn = None
            return self._row_count_result(0)
        if isinstance(ast, t.CreateTable):
            return self._create_table(ast)
        if isinstance(ast, t.DropTable):
            cat = self._writable()
            name = ast.name.lower()
            if name in self.matviews_mgr.views:
                raise ValueError(
                    f"{name!r} is a materialized view; "
                    "use DROP MATERIALIZED VIEW"
                )
            if name not in cat.table_names():
                if ast.if_exists:
                    return self._row_count_result(0)
                raise ValueError(f"table {ast.name!r} does not exist")
            cat.drop_table(name)
            return self._row_count_result(0)
        if isinstance(ast, t.Insert):
            return self._insert(ast)
        if isinstance(ast, t.Delete):
            return self._delete(ast)

        # -- views (reference execution/CreateViewTask.java,
        # DropViewTask.java; expansion happens in the planner) --
        if isinstance(ast, t.CreateView):
            name = ast.name.lower()
            if name in self.matviews_mgr.views:
                raise ValueError(
                    f"materialized view {name!r} already exists"
                )
            if name in self.catalog.table_names():
                raise ValueError(f"table {name!r} already exists")
            if name in self.views and not ast.or_replace:
                raise ValueError(f"view {name!r} already exists")
            # validate now: the view text must parse AND plan — against
            # the NEW binding (name excluded), so OR REPLACE cannot store
            # a self-reference that only fails at first use
            from .sql.parser import parse as _parse

            vast = _parse(ast.query_sql)
            if not isinstance(vast, t.Query):
                raise ValueError("CREATE VIEW requires a SELECT query")
            probe = {k: v for k, v in self.views.items() if k != name}
            Planner(self.catalog, views=probe).plan_query(
                vast, outer=None, ctes={}
            )
            self.views[name] = ast.query_sql
            return self._row_count_result(0)
        if isinstance(ast, t.DropView):
            name = ast.name.lower()
            if name not in self.views:
                if ast.if_exists:
                    return self._row_count_result(0)
                raise ValueError(f"view {name!r} does not exist")
            del self.views[name]
            return self._row_count_result(0)
        if isinstance(ast, t.ShowCreateView):
            name = ast.name.lower()
            if name not in self.views:
                raise ValueError(f"view {name!r} does not exist")
            txt = f"CREATE VIEW {name} AS {self.views[name]}"
            pg = Page.from_dict({"Create View": [txt]})
            return QueryResult(pg, ("Create View",))

        # -- materialized views (matview/manager.py; reference
        # execution/CreateMaterializedViewTask.java) --
        if isinstance(ast, t.CreateMaterializedView):
            self.matviews_mgr.create(
                ast.name, ast.query_sql, ast.if_not_exists
            )
            return self._row_count_result(0)
        if isinstance(ast, t.RefreshMaterializedView):
            self.matviews_mgr.refresh(ast.name, full=ast.full)
            return self._row_count_result(0)
        if isinstance(ast, t.DropMaterializedView):
            self.matviews_mgr.drop(ast.name, ast.if_exists)
            return self._row_count_result(0)

        # -- schemas (reference CreateSchemaTask.java, DropSchemaTask) --
        if isinstance(ast, t.CreateSchema):
            name = ast.name.lower()
            if name in self.schemas:
                if ast.if_not_exists:
                    return self._row_count_result(0)
                raise ValueError(f"schema {name!r} already exists")
            self.schemas.add(name)
            return self._row_count_result(0)
        if isinstance(ast, t.DropSchema):
            name = ast.name.lower()
            if name == "default":
                raise ValueError("cannot drop the default schema")
            if name not in self.schemas:
                if ast.if_exists:
                    return self._row_count_result(0)
                raise ValueError(f"schema {name!r} does not exist")
            held = [
                tn for tn in self.catalog.table_names()
                if tn.lower().startswith(name + ".")
            ]
            if held:
                raise ValueError(f"schema {name!r} is not empty: {held}")
            self.schemas.discard(name)
            return self._row_count_result(0)
        if isinstance(ast, t.ShowFunctions):
            # reference ShowQueriesRewrite SHOW FUNCTIONS over the
            # registry; kind mirrors FunctionKind
            from .sql.planner import AGG_FUNCS, LAMBDA_FUNCS, REWRITE_AGG_FUNCS
            from .expr.functions import FUNCTIONS
            from .ops.window import AGGREGATE, OFFSET, RANKING, VALUE

            # one row per name; precedence aggregate > scalar > lambda >
            # window (sum/avg/min/max/count exist both as aggregates and
            # window reducers — Presto lists them once, as aggregates)
            kind_of = {}
            for n in RANKING | OFFSET | VALUE | AGGREGATE:
                kind_of[n] = "window"
            for n in LAMBDA_FUNCS:
                kind_of[n] = "lambda"
            for n in FUNCTIONS:
                kind_of[n] = "scalar"
            for n in AGG_FUNCS | REWRITE_AGG_FUNCS:
                kind_of[n] = "aggregate"
            rows = sorted(
                (n, k)
                for n, k in kind_of.items()
                if n in set(self._like_filter(list(kind_of), ast.like))
            )
            pg = Page.from_dict(
                {
                    "Function": [r[0] for r in rows],
                    "Kind": [r[1] for r in rows],
                }
            )
            return QueryResult(pg, ("Function", "Kind"))
        if isinstance(ast, t.ShowGrants):
            # surface the active rule set (reference: SHOW GRANTS reads
            # information_schema.table_privileges); filtered to rules
            # whose table pattern covers the named table
            rules = getattr(self.access_control, "rules", []) or []
            rows = [(r.user, r.table, r.privileges) for r in rules]
            if ast.table is not None:
                import re as _re

                rows = [
                    (u, tp, p) for (u, tp, p) in rows
                    if _re.fullmatch(tp, ast.table.lower())
                ]
            pg = Page.from_dict(
                {
                    "Grantee": [r[0] for r in rows] or [None],
                    "Table": [r[1] for r in rows] or [None],
                    "Privilege": [r[2] for r in rows] or [None],
                }
            )
            if not rows:
                pg = Page(pg.blocks, pg.names, 0)
            return QueryResult(pg, ("Grantee", "Table", "Privilege"))
        if isinstance(ast, t.ShowCatalogs):
            pg = Page.from_dict(
                {"Catalog": [str(getattr(self.catalog, "name", "default"))]}
            )
            return QueryResult(pg, ("Catalog",))
        if isinstance(ast, t.ShowCreateTable):
            name = ast.name.lower()
            if name in self.views:
                raise ValueError(
                    f"{name!r} is a view; use SHOW CREATE VIEW"
                )
            schema = self._table_schema(self.catalog, name)
            cols = ",\n   ".join(f"{c} {ty}" for c, ty in schema.items())
            txt = f"CREATE TABLE {name} (\n   {cols}\n)"
            pg = Page.from_dict({"Create Table": [txt]})
            return QueryResult(pg, ("Create Table",))
        if isinstance(ast, t.ShowStats):
            # reference ShowStatsRewrite: per-column CBO statistics —
            # NDV, null fraction, logical min/max + a summary row with
            # the table row count
            name = ast.name.lower()
            schema = self._table_schema(self.catalog, name)
            stats_fn = getattr(self.catalog, "column_stats", None)
            rows_total = None
            erc = getattr(self.catalog, "exact_row_count", None)
            if erc is not None:
                try:
                    rows_total = float(erc(name))
                except Exception:  # noqa: BLE001 - summary is advisory
                    rows_total = None
            cols, ndvs, nfs, lows, highs = [], [], [], [], []
            for c in schema:
                st = None
                if stats_fn is not None:
                    try:
                        st = stats_fn(name, c)
                    except Exception:  # noqa: BLE001 - per-column stats
                        # are advisory, same contract as rows_total above
                        st = None
                cols.append(c)
                ndvs.append(None if st is None else st.ndv)
                nfs.append(None if st is None else st.null_fraction)
                lows.append(None if st is None or st.min is None
                            else str(st.min))
                highs.append(None if st is None or st.max is None
                             else str(st.max))
            # summary row (column_name NULL, row_count set) — the
            # reference's layout
            cols.append(None)
            ndvs.append(None)
            nfs.append(None)
            lows.append(None)
            highs.append(None)
            rc = [None] * (len(cols) - 1) + [rows_total]
            pg = Page.from_dict(
                {
                    "column_name": cols,
                    "distinct_values_count": _opt_f64(ndvs),
                    "nulls_fraction": _opt_f64(nfs),
                    "row_count": _opt_f64(rc),
                    "low_value": lows,
                    "high_value": highs,
                }
            )
            return QueryResult(
                pg,
                ("column_name", "distinct_values_count", "nulls_fraction",
                 "row_count", "low_value", "high_value"),
            )
        if isinstance(ast, t.ShowSchemas):
            names = sorted(self.schemas)
            pg = Page.from_dict({"Schema": names})
            return QueryResult(pg, ("Schema",))

        # -- prepared statements (reference execution/PrepareTask.java,
        # DeallocateTask.java; DESCRIBE INPUT/OUTPUT statements) --
        if isinstance(ast, t.Prepare):
            from .sql.parser import parse as _parse

            _parse(ast.statement_sql)  # must at least parse
            self.prepared[ast.name.lower()] = ast.statement_sql
            return self._row_count_result(0)
        if isinstance(ast, t.Deallocate):
            if self.prepared.pop(ast.name.lower(), None) is None:
                raise ValueError(f"prepared statement {ast.name!r} not found")
            return self._row_count_result(0)
        if isinstance(ast, t.ExecutePrepared):
            return self._execute_prepared(ast, user)
        if isinstance(ast, t.DescribeInput):
            sql2 = self._prepared_sql(ast.name)
            from .sql.parser import parse as _parse

            n_params = t.count_parameters(_parse(sql2))
            import numpy as np

            pg = Page.from_dict(
                {
                    "Position": np.arange(max(n_params, 1), dtype=np.int64),
                    "Type": ["unknown"] * max(n_params, 1),
                }
            )
            if n_params == 0:
                pg = Page(pg.blocks, pg.names, 0)
            return QueryResult(pg, ("Position", "Type"))
        if isinstance(ast, t.DescribeOutput):
            sql2 = self._prepared_sql(ast.name)
            from .sql.parser import parse as _parse

            past = _parse(sql2)
            n_params = t.count_parameters(past)
            past = t.substitute_parameters(
                past, tuple(t.NullLiteral() for _ in range(n_params))
            )
            if not isinstance(past, t.Query):
                pg = Page.from_dict({"Column": [None], "Type": [None]})
                return QueryResult(
                    Page(pg.blocks, pg.names, 0), ("Column", "Type")
                )
            # column names/types are metadata: same privilege as reading
            # (SHOW COLUMNS enforces this; DESCRIBE OUTPUT must too)
            if self.access_control is not None:
                from .security import enforce

                enforce(self.access_control, user, past, views=self.views)
            planner = Planner(self.catalog, views=self.views)
            rp = planner.plan_query(past, outer=None, ctes={})
            pg = Page.from_dict(
                {
                    "Column": [f.name for f in rp.scope.fields],
                    "Type": [str(f.type) for f in rp.scope.fields],
                }
            )
            return QueryResult(pg, ("Column", "Type"))

        # -- session properties (reference SetSessionTask.java,
        # ResetSessionTask.java) --
        if isinstance(ast, t.SetSession):
            key = ast.name.lower()
            if key not in SESSION_PROPERTIES:
                raise ValueError(f"unknown session property {key!r}")
            self._session_overrides[key] = SESSION_PROPERTIES[key](
                str(self._literal_value(ast.value))
            )
            return self._row_count_result(0)
        if isinstance(ast, t.ResetSession):
            self._session_overrides.pop(ast.name.lower(), None)
            return self._row_count_result(0)
        if isinstance(ast, t.ShowSession):
            rows = sorted(SESSION_PROPERTIES)
            vals = [
                str(self._session_overrides.get(k, "")) for k in rows
            ]
            pg = Page.from_dict({"Name": rows, "Value": vals})
            return QueryResult(pg, ("Name", "Value"))

        # -- ALTER TABLE (reference RenameTableTask.java,
        # RenameColumnTask.java, AddColumnTask.java, DropColumnTask) --
        if isinstance(ast, (t.RenameTable, t.RenameColumn, t.AddColumn,
                            t.DropColumn)):
            return self._alter_table(ast)

        # -- GRANT / REVOKE wired into security.py (reference
        # GrantTask.java, RevokeTask.java) --
        if isinstance(ast, (t.Grant, t.Revoke)):
            ac = self.access_control
            if ac is None or not hasattr(ac, "grant"):
                raise ValueError(
                    "GRANT/REVOKE requires a mutable access control "
                    "(security.RuleBasedAccessControl)"
                )
            table = ast.table.lower()
            if isinstance(ast, t.Grant):
                ac.grant(ast.grantee, table, ast.privilege)
            else:
                ac.revoke(ast.grantee, table, ast.privilege)
            return self._row_count_result(0)
        raise ValueError(f"unsupported statement {type(ast).__name__}")

    def _prepared_sql(self, name: str) -> str:
        sql = self.prepared.get(name.lower())
        if sql is None:
            raise ValueError(f"prepared statement {name!r} not found")
        return sql

    # -- EXECUTE fast path (exec/qcache.py plan skeletons) --

    def _execute_prepared(self, ast: t.ExecutePrepared, user) -> QueryResult:
        """EXECUTE binds USING values as TYPED CONSTANTS into a cached
        plan skeleton: N executions of one dashboard statement parse and
        plan once, and identical (statement, values, snapshot) executions
        serve straight from the result cache. There is no text
        substitution anywhere on this path — a string parameter is a
        varchar constant, never SQL."""
        sql2 = self._prepared_sql(ast.name)
        from .sql.parser import parse as _parse

        past = _parse(sql2)
        n_params = t.count_parameters(past)
        if len(ast.params) != n_params:
            raise ValueError(
                f"prepared statement {ast.name!r} expects {n_params} "
                f"parameters, got {len(ast.params)}"
            )
        bound = t.substitute_parameters(past, ast.params)
        # the prepared text was an opaque string to the PREPARE-time
        # check: the BOUND statement must pass the same enforcement a
        # direct query would (EXECUTE is not a privilege bypass)
        if self.access_control is not None:
            from .security import enforce

            enforce(self.access_control, user, bound, views=self.views)
        if not isinstance(bound, t.Query):
            return self._execute_statement(bound, user)
        # SET SESSION overrides apply to prepared executions the same as
        # to direct queries
        target = (
            self.with_properties(dict(self._session_overrides))
            if self._session_overrides
            else self
        )
        node = target._plan_prepared(past, ast.params, bound)
        return target._execute_plan_cached(node)

    def _plan_prepared(
        self, past, params, bound: t.Query
    ) -> N.PlanNode:
        """Plan an EXECUTE through the skeleton cache: parameters become
        param-tagged typed literals, the optimized plan is cached once
        per (statement, parameter-type signature, planning env), and new
        values REBIND the cached tree instead of re-planning. Guards, in
        order: (1) the skeleton is only kept when every parameter index
        survives into the plan (a value consumed at plan time — LIMIT ?,
        a folded negation — disqualifies it), (2) the first rebind to new
        values is verified against one direct re-plan, then trusted,
        (3) anything non-rebindable falls back to the ordinary per-value
        plan cache."""
        from .exec import qcache

        if not self.plan_cache or not params:
            return self._plan_query_cached(bound)
        lits = [self._param_literal(p) for p in params]
        if any(lv is None for lv in lits):
            # non-literal USING expressions: per-value plan cache only
            return self._plan_query_cached(bound)
        values = tuple(lv.value for lv in lits)
        sig = tuple(str(lv.type) for lv in lits)
        key = ("x", past, sig, self._plan_env_key())
        ent = qcache.PLAN_CACHE.lookup(key, self.catalog)
        if ent is not None and ent.rebindable:
            if values == ent.values0:
                return ent.plan
            plan = qcache.rebind_plan(ent.plan, values)
            if not ent.verified:
                direct = self._plan_query_uncached(bound)
                if qcache.strip_params(plan) == direct:
                    ent.verified = True
                else:
                    ent.rebindable = False
                    return direct
            return plan
        if ent is not None:  # known-non-rebindable statement shape
            return self._plan_query_cached(bound)
        wrapped = t.substitute_parameters(
            past,
            tuple(t.BoundParameter(i, p) for i, p in enumerate(params)),
        )
        try:
            skel = self._plan_query_uncached(wrapped)
        except Exception:  # noqa: BLE001 — param in a literal-only spot
            skel = None
        rebindable = skel is not None and (
            qcache.collect_param_indices(skel) == set(range(len(params)))
        )
        if not rebindable:
            fallback = self._plan_query_cached(bound)
            qcache.PLAN_CACHE.store(
                key, fallback, self.catalog,
                rebindable=False, values0=values,
            )
            return fallback
        qcache.PLAN_CACHE.store(
            key, skel, self.catalog,
            rebindable=True, verified=False, values0=values,
        )
        return skel

    @staticmethod
    def _param_literal(node):
        """Plan one USING argument as a typed ir constant (mirrors the
        planner's literal cases), or None when it is not a plain literal."""
        from .expr import ir
        from . import types as T
        from .sql.planner import _number_literal, _parse_timestamp_literal

        if isinstance(node, t.UnaryOp) and node.op == "-" and isinstance(
            node.operand, t.NumberLiteral
        ):
            lit = _number_literal(node.operand.text)
            if not isinstance(lit.value, (int, float)):
                return None  # Decimal lanes stay symbolic (planner parity)
            return ir.Literal(-lit.value, lit.type)
        if isinstance(node, t.NumberLiteral):
            return _number_literal(node.text)
        if isinstance(node, t.StringLiteral):
            return ir.Literal(node.value, T.VARCHAR)
        if isinstance(node, t.BooleanLiteral):
            return ir.Literal(node.value, T.BOOLEAN)
        if isinstance(node, t.NullLiteral):
            return ir.Literal(None, T.UNKNOWN)
        if isinstance(node, t.DateLiteral):
            return ir.Literal(node.value, T.DATE)
        if isinstance(node, t.TimestampLiteral):
            return ir.Literal(
                _parse_timestamp_literal(node.value), T.TIMESTAMP
            )
        if isinstance(node, t.IntervalLiteral):
            n = int(node.value) * (-1 if node.negative else 1)
            if node.unit in ("year", "month"):
                months = n * (12 if node.unit == "year" else 1)
                return ir.Literal(months, T.INTERVAL_YEAR_MONTH)
            if node.unit == "day":
                return ir.Literal(n, T.INTERVAL_DAY)
        return None

    @staticmethod
    def _literal_value(node):
        if isinstance(node, t.StringLiteral):
            return node.value
        if isinstance(node, t.NumberLiteral):
            return node.text
        if isinstance(node, t.BooleanLiteral):
            return node.value
        raise ValueError("SET SESSION requires a literal value")

    def _alter_table(self, ast) -> QueryResult:
        """ALTER TABLE against a writable connector: metadata-only ops are
        implemented as a page rewrite + replace (the in-memory connectors
        have no separate metadata store)."""
        import numpy as np

        from . import types as T
        from .page import Block, Page

        cat = self._writable()
        name = (ast.name if isinstance(ast, t.RenameTable) else ast.table).lower()
        if name not in cat.table_names():
            raise ValueError(f"table {name!r} does not exist")
        page = cat.page(name)
        if isinstance(ast, t.RenameTable):
            new = ast.new_name.lower()
            if new in cat.table_names() or new in self.views:
                raise ValueError(f"table {new!r} already exists")
            cat.create_table_from_page(new, page)
            cat.drop_table(name)
            return self._row_count_result(0)
        cols = list(page.names)
        blocks = list(page.blocks)
        if isinstance(ast, t.RenameColumn):
            old = ast.name.lower()
            new = ast.new_name.lower()
            if old not in cols:
                raise ValueError(f"column {old!r} does not exist")
            if new in cols:
                raise ValueError(f"column {new!r} already exists")
            cols[cols.index(old)] = new
        elif isinstance(ast, t.AddColumn):
            cname = ast.column.name.lower()
            if cname in cols:
                raise ValueError(f"column {cname!r} already exists")
            typ = T.parse_type(ast.column.type_name)
            import jax.numpy as jnp

            data = jnp.zeros(page.capacity, typ.storage_dtype)
            valid = jnp.zeros(page.capacity, bool)  # all NULL
            cols.append(cname)
            blocks.append(Block(data, typ, valid))
        elif isinstance(ast, t.DropColumn):
            cname = ast.name.lower()
            if cname not in cols:
                raise ValueError(f"column {cname!r} does not exist")
            if len(cols) == 1:
                raise ValueError("cannot drop the only column")
            i = cols.index(cname)
            del cols[i]
            del blocks[i]
        cat.replace(name, Page(tuple(blocks), tuple(cols), page.count))
        return self._row_count_result(0)

    def _create_table(self, ast: t.CreateTable) -> QueryResult:
        from . import types as T
        from .page import Page

        cat = self._writable()
        name = ast.name.lower()
        if name in self.views:
            # the planner resolves views first, so a same-named table
            # would be permanently shadowed — reject the collision both
            # ways (CREATE VIEW already checks tables)
            raise ValueError(f"view {name!r} already exists")
        if name in self.matviews_mgr.views:
            raise ValueError(f"materialized view {name!r} already exists")
        if name in cat.table_names():
            if ast.if_not_exists:
                return self._row_count_result(0)
            raise ValueError(f"table {name!r} already exists")
        if ast.query is None:
            schema = {}
            for col in ast.columns:
                cname = col.name.lower()
                if cname in schema:
                    raise ValueError(f"duplicate column {cname!r}")
                schema[cname] = T.parse_type(col.type_name)
            cat.create_table(name, schema)
            return self._row_count_result(0)
        page, titles, _scope = self._run_query_ast(ast.query)
        lowered = tuple(tl.lower() for tl in titles)
        if len(set(lowered)) != len(lowered):
            raise ValueError("CREATE TABLE AS requires unique column names")
        for tl, blk in zip(lowered, page.blocks):
            if isinstance(blk.type, T.UnknownType):
                raise ValueError(
                    f"CREATE TABLE AS column {tl!r} has unknown type "
                    "(all-NULL); cast it to a concrete type"
                )
        cat.create_table_from_page(name, Page(page.blocks, lowered, page.count))
        return self._row_count_result(int(page.count))

    def _insert(self, ast: t.Insert) -> QueryResult:
        from . import types as T
        from .expr import ir
        from .expr.compiler import project_page
        from .ops.union import null_block
        from .page import Page

        cat = self._writable()
        name = ast.table.lower()
        schema = self._table_schema(cat, name)
        targets = (
            tuple(c.lower() for c in ast.columns)
            if ast.columns
            else tuple(schema)
        )
        if len(set(targets)) != len(targets):
            raise ValueError("duplicate column in INSERT target list")
        for c in targets:
            if c not in schema:
                raise ValueError(f"column {c!r} not in table {name!r}")
        page, _titles, _scope = self._run_query_ast(
            ast.query if isinstance(ast.query, t.Query) else t.Query(ast.query)
        )
        if page.num_columns != len(targets):
            raise ValueError(
                f"INSERT has {page.num_columns} columns, expected {len(targets)}"
            )
        # positional channels, then cast each source column to the target type
        chans = tuple(f"c{i}" for i in range(page.num_columns))
        page = Page(page.blocks, chans, page.count)
        exprs = []
        for ch, blk, col in zip(chans, page.blocks, targets):
            ref = ir.ColumnRef(ch, blk.type)
            want = schema[col]
            exprs.append(ref if blk.type == want else ir.cast(ref, want))
        cast_pg = project_page(page, tuple(exprs), targets)
        # assemble full-width page in table column order; unmentioned
        # columns are NULL
        by_name = dict(zip(targets, cast_pg.blocks))
        cap = cast_pg.capacity if cast_pg.blocks else 1
        blocks = []
        for col, ty in schema.items():
            if col in by_name:
                blocks.append(by_name[col])
            else:
                did = None
                if isinstance(ty, T.VarcharType):
                    from .page import intern_dictionary

                    did = intern_dictionary(())
                blocks.append(null_block(ty, cap, did))
        cat.append(name, Page(tuple(blocks), tuple(schema), page.count))
        return self._row_count_result(int(page.count))

    def _delete(self, ast: t.Delete) -> QueryResult:
        cat = self._writable()
        name = ast.table.lower()
        schema = self._table_schema(cat, name)
        before = int(cat.page(name).count)
        if ast.where is None:
            from .ops.union import empty_page

            cat.replace(name, empty_page(schema))
            return self._row_count_result(before)
        # keep rows where the predicate is NOT TRUE (false or null)
        keep = t.Case(
            None,
            ((ast.where, t.BooleanLiteral(False)),),
            t.BooleanLiteral(True),
        )
        sel = t.Select(
            items=(t.Star(),),
            from_=t.Table(name),
            where=keep,
            group_by=(),
            having=None,
            distinct=False,
        )
        page, titles, _scope = self._run_query_ast(t.Query(sel))
        from .page import Page

        cat.replace(name, Page(page.blocks, tuple(tl.lower() for tl in titles), page.count))
        return self._row_count_result(before - int(page.count))

    def _collector_executor(self, collector):
        """Fresh executor with a per-query stats collector, matching the
        engine the session actually runs (mesh / streaming / local plus
        the session's strategy overrides). Used by EXPLAIN ANALYZE and
        by the feedback plane's observe-once runs: the shared executor
        can't have a collector swapped in per query under concurrency."""
        if self.mesh is not None:
            from .exec.dist import DistributedExecutor

            ex = DistributedExecutor(self.catalog, self.mesh, collector=collector)
        elif self.streaming:
            # profile the SAME engine the session runs: streamed batches
            # under the session's memory budget (per-node stats cover the
            # kernels the streaming driver delegates to the local executor)
            from .exec.stream import StreamingExecutor

            ex = StreamingExecutor(
                self.catalog,
                batch_rows=self.batch_rows,
                memory_budget=self.memory_budget,
                collector=collector,
            )
        else:
            ex = Executor(self.catalog, collector=collector)
        # profile with the session's strategy overrides (pallas/matmul
        # group-by), matching the executor the session actually runs
        local = getattr(ex, "local", ex)
        if self.pallas_groupby is not None and hasattr(local, "pallas_groupby"):
            local.pallas_groupby = self.pallas_groupby
        if self.matmul_groupby is not None and hasattr(local, "matmul_groupby"):
            local.matmul_groupby = self.matmul_groupby
        if hasattr(local, "dynamic_filtering"):
            local.dynamic_filtering = self.dynamic_filtering
        return ex

    def explain_analyze_plan(self, node: N.PlanNode) -> str:
        """Execute the plan with per-operator accounting and render the
        annotated tree (reference EXPLAIN ANALYZE via ExplainAnalyzeOperator,
        presto-main/.../execution/ExplainAnalyzeContext.java)."""
        from .exec.stats import StatsCollector

        collector = StatsCollector()
        ex = self._collector_executor(collector)
        from .obs import span as obs_span
        from .obs.kernelprof import KERNEL_PROFILE

        traced = obs_span.enabled()
        kprof_before = KERNEL_PROFILE.snapshot()
        trace = root = exec_span = None
        if traced:
            trace = obs_span.TRACES.new_trace()
            root = trace.begin("query")
            exec_span = trace.begin("execute", parent=root)
        ex.run(node)
        # fold parked device row-count scalars in one batch (the lazy
        # collector avoids a blocking host sync per plan node)
        collector.resolve()
        if traced:
            trace.finish(exec_span)
            trace.finish(root)
            # graft per-node stats as synthetic spans so the -- trace:
            # footer ranks the same units the cluster path ships
            def _graft(n):
                s = collector.lookup(n)
                if s is not None:
                    trace.add_synthetic(
                        type(n).__name__, exec_span, s.wall_s,
                        rows=s.rows_out, bytes=s.out_bytes_total,
                    )
                for c in n.children:
                    _graft(c)

            _graft(node)
        tree = N.plan_tree_str(node, collector=collector)
        total_ms = collector.total_wall_s() * 1e3
        peak = collector.peak_bytes / (1024 * 1024)
        from .exec.stats import kernel_breaker_lines

        breakers = kernel_breaker_lines()
        breaker_txt = "".join(f"\n-- {line}" for line in breakers)
        dyn_txt = ""
        dyn_ctx = getattr(
            ex, "dyn_ctx", getattr(getattr(ex, "local", None), "dyn_ctx", None)
        )
        if dyn_ctx is not None and dyn_ctx.snapshot()["filters"]:
            snap = dyn_ctx.snapshot()
            filters = ", ".join(
                f"{fid}={d}" for fid, d in sorted(snap["filters"].items())
            )
            scan_p = sum(snap["scan_pruned"].values())
            pre_p = sum(snap["preprobe_pruned"].values())
            dyn_txt = (
                f"\n-- dynamic filters: {filters}; rows_pruned="
                f"{scan_p + pre_p:,} (scan {scan_p:,}, pre-probe {pre_p:,})"
            )
            if snap["wait_s"]:
                dyn_txt += f", wait {snap['wait_s']:.2f}s"
        # memory-arbitration line: every rung of the degradation ladder
        # the query touched (offload events, disk tier, hybrid-join
        # partitioning/recursion, revocations) + over-free accounting
        mem_txt = ""
        spill_ev = getattr(ex, "spill_events", None)
        if spill_ev is not None:
            st = getattr(ex, "spill_stats", {}) or {}
            pool = getattr(ex, "pool", None)
            revs = getattr(pool, "revocations", 0) if pool else 0
            overs = getattr(pool, "over_frees", 0) if pool else 0
            if spill_ev or revs or overs or st.get("disk_bytes"):
                parts = []
                if spill_ev:
                    parts.append("spill " + ",".join(sorted(set(spill_ev))))
                if st.get("disk_bytes"):
                    parts.append(f"disk {st['disk_bytes']:,}B")
                if st.get("hybrid_parts"):
                    parts.append(
                        f"hybrid parts={st['hybrid_parts']} "
                        f"depth={st.get('hybrid_depth', 0)}"
                    )
                if st.get("ragged_pages"):
                    # ragged paged partition layout (ops/ragged.py):
                    # pages allocated for the hybrid build partitions and
                    # their live-slot occupancy (pad-to-max would be 100%
                    # only under zero skew)
                    parts.append(
                        f"ragged pages={st['ragged_pages']} "
                        f"occ={st.get('ragged_occupancy_pct', 0)}%"
                    )
                if st.get("agg_hash_batches"):
                    parts.append(
                        f"agg_hash_batches={st['agg_hash_batches']}"
                    )
                if st.get("chunk_fallbacks"):
                    parts.append(f"chunk_fallbacks={st['chunk_fallbacks']}")
                if revs:
                    parts.append(f"revocations={revs}")
                if overs:
                    parts.append(f"over_frees={overs}")
                mem_txt = "\n-- memory: " + ", ".join(parts)
        # mesh-exchange line: repartition collectives (ICI all_to_all
        # wall, measured to host sync) and grouped-join chunked exchanges
        exch_txt = ""
        ex_ev = getattr(ex, "exchange_events", None)
        if ex_ev:
            reparts = [e for e in ex_ev if e.get("kind") == "repartition"]
            grouped = [e for e in ex_ev if "buckets" in e]
            parts = []
            if reparts:
                coll_ms = sum(e["collective_ms"] for e in reparts)
                rows = sum(e["rows"] for e in reparts)
                parts.append(
                    f"{len(reparts)} repartition collectives over "
                    f"{reparts[0]['shards']} shards, {rows:,} rows, "
                    f"device {coll_ms:,.1f}ms"
                )
            for e in grouped:
                parts.append(
                    f"grouped join buckets={e['buckets']} "
                    f"peak {e['per_shard_bytes']:,}B/shard"
                )
            exch_txt = "\n-- exchange: " + "; ".join(parts)
        # serving-cache observability (exec/qcache.py): process-wide
        # hits/misses/evictions/bytes for the plan, result and kernel
        # caches — EXPLAIN ANALYZE itself always re-executes, so these
        # are the counters the profiled query runs alongside
        from .exec import qcache

        cache_txt = "\n-- caches: " + qcache.format_summary(
            qcache.snapshot_all()
        )
        # adaptive-execution feedback (plan/history.py): fold this run's
        # observed cardinalities into the history store, then surface the
        # plane's counters — lookup hits, estimate-vs-observed relative
        # error, and mid-query replans — so a profiled query shows both
        # what history it consumed and what it contributed
        feedback_txt = ""
        from .plan import history as _H

        if _H.feedback_on():
            try:
                _H.HISTORY.record_plan(node, collector, self.catalog)
            except Exception as exc:  # noqa: BLE001 — bookkeeping only
                from .exec.breaker import BREAKERS

                BREAKERS.record_failure("adaptive_plan", repr(exc))
            fs = _H.HISTORY.stats.snapshot()
            err = fs["mean_abs_rel_err"]
            feedback_txt = (
                f"\n-- feedback: hits={fs['hits']} misses={fs['misses']}"
                f" records={fs['records']} est-err="
                f"{'n/a' if err is None else f'{err:.2f}'}"
                f" replans={fs['replans']}"
            )
        # materialized-view freshness (matview/manager.py): which views
        # exist, delta vs recompute maintenance, and how stale each is
        matview_txt = ""
        mgr = getattr(self, "matviews_mgr", None)
        if mgr is not None and mgr.views:
            matview_txt = "\n-- matview: " + mgr.format_summary()
        # observability footers (docs/observability.md): the critical
        # path from the SAME span-tree renderer the cluster path uses,
        # and the compile-vs-execute split this run added to the
        # process-wide kernel profile
        trace_txt = kernel_txt = ""
        if traced:
            from .server import knobs as _knobs

            trace_txt = "\n-- trace: " + obs_span.render_critical_path(
                trace, _knobs.trace_topk()
            )
            kp = KERNEL_PROFILE.snapshot()
            d_comp = kp["compiles"] - kprof_before["compiles"]
            d_exec = kp["executions"] - kprof_before["executions"]
            if d_comp or d_exec:
                d_comp_s = kp["compile_s"] - kprof_before["compile_s"]
                d_exec_s = kp["execute_s"] - kprof_before["execute_s"]
                kernel_txt = (
                    f"\n-- kernels: compile +{d_comp}"
                    f" ({d_comp_s * 1e3:,.1f}ms),"
                    f" execute +{d_exec} ({d_exec_s * 1e3:,.1f}ms)"
                )
        return (
            f"{tree}{dyn_txt}{breaker_txt}{mem_txt}{exch_txt}{cache_txt}"
            f"{feedback_txt}{matview_txt}{trace_txt}{kernel_txt}\n"
            f"-- total {total_ms:,.1f}ms, peak live output {peak:,.2f}MB"
        )

    def explain_analyze(self, sql: str) -> str:
        return self.explain_analyze_plan(self.plan(sql))
