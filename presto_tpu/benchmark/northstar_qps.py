"""northstar_qps — concurrent prepared-statement serving benchmark.

The north star is thousands of small dashboard queries, not one big
scan; this driver measures that shape directly. N client threads replay
a MIXED prepared-statement workload (each statement's parameters drawn
from a small rotating pool, the dashboard pattern) against one shared
Session (default) or over HTTP against an embedded CoordinatorServer
(--http), and report:

* cold p50/p99 — first-ever execution per statement: parse + plan +
  trace + XLA compile + execute (what every query paid before the
  serving fast path existed),
* warm p50/p99 + aggregate QPS under concurrency — the steady state the
  plan/result/kernel caches (exec/qcache.py) are built for,
* per-cache hit rates over the run (the same counters /v1/status serves).

Reference protocol: presto-benchto concurrency benchmarks (tpch.yaml
`concurrency:` runs). Gated by tools/bench_gate.py against the
BASELINE.json `qps_gate` floors (warm p50 ceiling, QPS floor, and the
>=5x warm-vs-cold p50 acceptance line).

    python -m presto_tpu.benchmark.northstar_qps --sf 0.01 --clients 8 \
        --iters 30 [--http] [--no-cache]

Prints ONE JSON line. The workload is join-free on purpose: the shared
Session's dynamic-filter registry is per-query state and this driver's
point is cache behavior under concurrency, not join planning.

--no-cache (the A/B baseline) is best run with --clients 1: with caches
off every request re-plans and re-traces, and concurrent re-tracing can
trip the pre-existing single-process pure_callback deadlock the cached
path never reaches (one more reason the serving path wants the caches).
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
from typing import Dict, List, Optional

# (name, prepared SQL, parameter value pool rendered as SQL literals)
WORKLOAD = (
    ("cust_orders",
     "select count(*) c, sum(o_totalprice) s from orders "
     "where o_custkey = ?",
     ("37", "755", "1234", "400")),
    ("segment_count",
     "select count(*) c from customer where c_mktsegment = ?",
     ("'BUILDING'", "'MACHINERY'", "'AUTOMOBILE'", "'FURNITURE'")),
    ("order_lines",
     "select count(*) c, sum(l_extendedprice) s from lineitem "
     "where l_orderkey = ?",
     ("1", "357", "1988", "4000")),
    ("open_orders",
     "select count(*) c from orders "
     "where o_orderdate >= date '1995-01-01' and o_orderstatus = ?",
     ("'O'", "'F'", "'P'", "'O'")),
    ("top_orders",
     "select o_orderkey, o_totalprice from orders "
     "order by o_totalprice desc limit ?",
     ("10", "25", "10", "50")),
)


def _pctl(samples: List[float], q: float) -> float:
    if not samples:
        return 0.0
    s = sorted(samples)
    i = min(int(len(s) * q), len(s) - 1)
    return s[i]


class _SessionTransport:
    def __init__(self, sess):
        self.sess = sess

    def execute(self, sql: str):
        return self.sess.query(sql).rows()


class _HttpTransport:
    def __init__(self, uri: str):
        from ..server.client import Client

        self.client = Client(uri)

    def execute(self, sql: str):
        _cols, rows = self.client.execute(sql)
        return rows


def run(sf: float = 0.01, clients: int = 8, iters: int = 30,
        http: bool = False, use_cache: bool = True,
        workload=WORKLOAD, join_timeout_s: float = 300.0) -> Dict:
    import jax

    from ..connectors.tpch import TpchCatalog
    from ..exec import qcache
    from ..session import Session

    cat = TpchCatalog(sf=sf)
    sess = Session(cat, plan_cache=use_cache, result_cache=use_cache)
    server = None
    try:
        if http:
            from ..server.coordinator import CoordinatorServer

            server = CoordinatorServer(
                sess, max_concurrent=max(clients, 2)
            ).start()
            # the served session is a sibling wrapping a SystemCatalog:
            # propagate the cache switches the A/B flag selected
            server.manager.session.plan_cache = use_cache
            server.manager.session.result_cache = use_cache
            transport = _HttpTransport(server.uri)
        else:
            transport = _SessionTransport(sess)

        for name, sql, _pool in workload:
            transport.execute(f"prepare {name} from {sql}")

        def exec_stmt(name: str, pool, k: int):
            return f"execute {name} using {pool[k % len(pool)]}"

        # cold: first-ever execution per statement (plan+compile+run)
        cache0 = qcache.snapshot_all()
        cold_ms: List[float] = []
        for name, _sql, pool in workload:
            t0 = time.perf_counter()
            transport.execute(exec_stmt(name, pool, 0))
            cold_ms.append((time.perf_counter() - t0) * 1e3)

        # warm-up sweep: every (statement, value) combination once, so
        # the concurrent phase measures steady-state serving
        for k in range(max(len(p) for _n, _s, p in workload)):
            for name, _sql, pool in workload:
                transport.execute(exec_stmt(name, pool, k))

        # concurrent phase
        lat_ms: List[float] = []
        errors: List[str] = []
        lock = threading.Lock()
        counter = [0]
        barrier = threading.Barrier(clients + 1)

        def client_loop(cid: int):
            local: List[float] = []
            t = (
                _HttpTransport(server.uri)
                if http else _SessionTransport(sess)
            )
            barrier.wait()
            for i in range(iters):
                for name, _sql, pool in workload:
                    with lock:
                        k = counter[0]
                        counter[0] += 1
                    t0 = time.perf_counter()
                    try:
                        t.execute(exec_stmt(name, pool, k))
                    except Exception as e:  # noqa: BLE001 — record
                        with lock:
                            errors.append(repr(e)[:200])
                        continue
                    local.append((time.perf_counter() - t0) * 1e3)
            with lock:
                lat_ms.extend(local)

        threads = [
            threading.Thread(target=client_loop, args=(c,), daemon=True)
            for c in range(clients)
        ]
        for th in threads:
            th.start()
        barrier.wait()
        t_start = time.perf_counter()
        # bounded joins: a wedged query must turn into a reported error,
        # never an infinite hang (client threads are daemons)
        deadline = t_start + join_timeout_s
        for th in threads:
            th.join(timeout=max(deadline - time.perf_counter(), 0.1))
        wedged = sum(1 for th in threads if th.is_alive())
        if wedged:
            with lock:
                errors.append(
                    f"{wedged} client thread(s) wedged past "
                    f"{join_timeout_s:.0f}s — aborting the run"
                )
        wall_s = time.perf_counter() - t_start
        cache1 = qcache.snapshot_all()

        def delta(name: str) -> dict:
            h = cache1[name]["hits"] - cache0[name]["hits"]
            m = cache1[name]["misses"] - cache0[name]["misses"]
            return {
                "hits": h,
                "misses": m,
                "hit_rate": round(h / (h + m), 4) if h + m else None,
                "bytes": cache1[name]["bytes"],
                "evictions": cache1[name]["evictions"]
                - cache0[name]["evictions"],
            }

        n_req = len(lat_ms)
        cold_p50 = _pctl(cold_ms, 0.50)
        warm_p50 = _pctl(lat_ms, 0.50)
        return {
            "suite": "northstar_qps",
            "backend": jax.devices()[0].platform,
            "sf": sf,
            "clients": clients,
            "iters": iters,
            "http": http,
            "use_cache": use_cache,
            "requests": n_req,
            "errors": len(errors),
            "error_sample": errors[:3],
            "qps": round(n_req / wall_s, 1) if wall_s else None,
            "wall_s": round(wall_s, 3),
            "cold_p50_ms": round(cold_p50, 2),
            "cold_p99_ms": round(_pctl(cold_ms, 0.99), 2),
            "warm_p50_ms": round(warm_p50, 2),
            "warm_p99_ms": round(_pctl(lat_ms, 0.99), 2),
            "speedup_p50": round(cold_p50 / warm_p50, 1) if warm_p50 else None,
            "caches": {
                "plan": delta("plan"),
                "result": delta("result"),
                "kernel": delta("kernel"),
            },
        }
    finally:
        if server is not None:
            server.stop()


def main(argv: Optional[List[str]] = None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--sf", type=float, default=0.01)
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--iters", type=int, default=30)
    ap.add_argument("--http", action="store_true",
                    help="drive an embedded CoordinatorServer over HTTP")
    ap.add_argument("--no-cache", action="store_true",
                    help="A/B baseline: disable the plan + result caches")
    ap.add_argument("--cpu", action="store_true", help="force CPU backend")
    args = ap.parse_args(argv)
    if args.cpu:
        import os
        import re

        os.environ["JAX_PLATFORMS"] = "cpu"
        # >=2 virtual devices: the single-device CPU runtime has a known
        # flaky pure_callback deadlock on the host-routed TopN kernel
        # (pre-existing; the test harness always runs 8 virtual devices)
        flags = os.environ.get("XLA_FLAGS", "")
        flags = re.sub(
            r"--xla_force_host_platform_device_count=\d+", "", flags
        )
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=2"
        ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    import presto_tpu  # noqa: F401  (enables x64)

    out = run(args.sf, clients=args.clients, iters=args.iters,
              http=args.http, use_cache=not args.no_cache)
    print(json.dumps(out), flush=True)
    return out


if __name__ == "__main__":
    main()
    sys.stdout.flush()
    import os

    os._exit(0)  # skip native teardown (see bench.py)
