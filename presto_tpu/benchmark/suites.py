"""Macro-benchmark suite definitions — the benchto-benchmarks analog.

Re-designed equivalent of presto-benchto-benchmarks' YAML suite files
(presto-benchto-benchmarks/src/main/resources/benchmarks/presto/
tpch.yaml:1-16, tpcds.yaml, distributed_sort.yaml): each suite names its
data source + scale factors, query set, run counts and prewarms, and a
frequency for scheduled execution. Declarative python dicts instead of
YAML (no external deps); `run()` executes a suite in-process through a
Session (the LocalQueryRunner mode) or against a live coordinator
through benchmark/driver.py (the Benchto agent mode).

    python -m presto_tpu.benchmark.suites --suite tpch --sf 0.1
    python -m presto_tpu.benchmark.suites --suite tpch --server http://...
"""

from __future__ import annotations

import argparse
import json
import time
from typing import Dict, List, Optional

from .tpch_sql import QUERIES as TPCH_QUERIES
from .tpcds_sql import QUERIES as TPCDS_QUERIES

# mirror of the reference's suite protocol constants (tpch.yaml:1-16):
# 6 measured runs + 2 prewarms, weekly frequency
SUITES: Dict[str, dict] = {
    "tpch": {
        "datasource": "tpch",
        "scale_factors": [1.0, 10.0, 100.0],  # ref: sf300/sf1000/sf3000 ORC
        "queries": sorted(TPCH_QUERIES),
        "runs": 6,
        "prewarms": 2,
        "frequency_days": 7,
    },
    "tpcds": {
        "datasource": "tpcds",
        "scale_factors": [1.0, 10.0],  # ref: sf10..sf10000 ORC
        "queries": sorted(TPCDS_QUERIES),
        "runs": 6,
        "prewarms": 2,
        "frequency_days": 7,
    },
    "distributed_sort": {
        "datasource": "tpch",
        "scale_factors": [1.0, 100.0],  # ref: sf100..sf3000
        "queries": ["sort_1col", "sort_6col"],
        "extra_sql": {
            "sort_1col": (
                "select * from lineitem order by l_shipdate limit 10"
            ),
            "sort_6col": (
                "select * from lineitem order by l_returnflag, l_linestatus,"
                " l_shipdate, l_quantity, l_discount, l_orderkey limit 10"
            ),
        },
        "runs": 2,
        "prewarms": 1,
        "frequency_days": 7,
    },
}


def _sql_for(suite: dict, qname) -> str:
    extra = suite.get("extra_sql", {})
    if qname in extra:
        return extra[qname]
    src = TPCH_QUERIES if suite["datasource"] == "tpch" else TPCDS_QUERIES
    return src[qname]


def run(
    name: str,
    sf: float = 0.1,
    server: Optional[str] = None,
    queries: Optional[List[str]] = None,
    runs: Optional[int] = None,
) -> dict:
    """Execute one suite at one scale factor; returns per-query wall-ms
    percentiles in the driver's shape."""
    suite = SUITES[name]
    qnames = queries or suite["queries"]
    n_runs = runs if runs is not None else suite["runs"]
    qmap = {str(q): _sql_for(suite, q) for q in qnames}
    from .driver import run_suite

    if server is not None:
        from ..verifier import RestTarget

        target = RestTarget(server)
    else:
        # in-process = the LocalQueryRunner mode, through the SAME driver
        # protocol as the live-cluster path (verifier.SessionTarget wraps
        # a Session with the target interface)
        from ..session import Session
        from ..verifier import SessionTarget

        if suite["datasource"] == "tpch":
            from ..connectors.tpch import TpchCatalog

            target = SessionTarget(Session(TpchCatalog(sf=sf)))
        else:
            from ..connectors.tpcds import TpcdsCatalog

            target = SessionTarget(Session(TpcdsCatalog(sf=sf)))
    benches = run_suite(
        target, qmap, runs=n_runs, warmup=suite["prewarms"]
    )
    return {
        "suite": name,
        # server mode runs at whatever scale the coordinator's catalog was
        # started with — reporting the client-side flag would mislabel
        "sf": None if server is not None else sf,
        "queries": {
            b.name: {
                "rows": b.rows,
                "p50_ms": round(b.percentile(50), 1),
                "p90_ms": round(b.percentile(90), 1),
                "error": b.error,
            }
            for b in benches
        },
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--suite", choices=sorted(SUITES), default="tpch")
    ap.add_argument("--sf", type=float, default=0.1)
    ap.add_argument("--server", default=None)
    ap.add_argument("--queries", nargs="*", default=None)
    ap.add_argument("--runs", type=int, default=None)
    args = ap.parse_args(argv)
    qs = None
    if args.queries:
        qs = [int(q) if q.isdigit() else q for q in args.queries]
    out = run(args.suite, sf=args.sf, server=args.server, queries=qs,
              runs=args.runs)
    print(json.dumps(out, indent=1))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
