"""Per-operator kernel microbenchmark suite — the JMH analog.

Reference: presto-benchmark's in-process operator suite
(presto-benchmark/.../BenchmarkSuite.java:32, AbstractOperatorBenchmark.java)
plus the 62 JMH kernel benchmarks (presto-main/src/test/.../operator/
Benchmark*.java: BenchmarkGroupByHash, BenchmarkHashBuildAndJoinOperators,
BenchmarkPartitionedOutputOperator, BenchmarkWindowOperator, ...). Same idea,
TPU-first: each entry times ONE relational kernel over device-resident TPC-H
pages and reports rows/s, runnable unchanged on CPU or TPU from one entry
point:

    python -m presto_tpu.benchmark.micro --sf 0.1 --runs 5 [--out micro.json]

Timing protocol: device benchmarks chain each run's input on the previous
run's output (a zero-valued data dependency) and end the chain in a single
host transfer — `block_until_ready` through the axon tunnel returns at
enqueue, so independent per-run timing would measure dispatch latency, not
kernel time (see bench.py `_chained_device_time`). Host benchmarks (serde)
time plain wall clock.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import time
from typing import Callable, Dict, List, Optional

import numpy as np

RUNS = 5
REPS = 3


@dataclasses.dataclass
class Bench:
    name: str
    rows: int  # input rows processed per run (rows/s denominator)
    step: Callable  # (acc: int64, *args) -> int64  (jittable unless eager)
    args: tuple
    note: str = ""
    # eager steps run UNJITTED — the hash-table join / hash group-by
    # engine defaults route around jit (host scans need concrete
    # operands; the ops/sort.py host-sort idiom), so their micros must
    # measure the same eager dispatch the engine uses
    eager: bool = False


# Peak HBM bandwidth by device kind (bytes/s), for utilization accounting —
# the MFU analog of a scan-bound engine: achieved streaming bandwidth
# (input bytes read per kernel pass / elapsed) over the chip's peak. Values
# from public TPU system specs (cloud.google.com/tpu/docs/system-architecture).
_PEAK_HBM_BPS = {
    "TPU v5 lite": 819e9,  # v5e: 16 GiB HBM2 @ 819 GB/s
    "TPU v5e": 819e9,
    "TPU v5": 819e9,
    "TPU v5p": 2765e9,
    "TPU v4": 1228e9,
    "TPU v3": 900e9,
    "TPU v2": 700e9,
}


def _peak_hbm_bps() -> Optional[float]:
    import jax

    kind = getattr(jax.devices()[0], "device_kind", "")
    # longest prefix first, so e.g. "TPU v5p" matches its own entry and
    # not the shorter "TPU v5"
    for prefix in sorted(_PEAK_HBM_BPS, key=len, reverse=True):
        if kind.startswith(prefix):
            return _PEAK_HBM_BPS[prefix]
    return None


def _arg_bytes(args) -> int:
    """Input working set per run: bytes of every device/host array in args
    (Pages, Blocks, raw arrays). This is the bytes READ by one streaming
    pass; kernels that also write large outputs (sort, join) achieve more
    traffic than this accounts for, so hbm_read_pct is a lower bound."""
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(args):
        nbytes = getattr(leaf, "nbytes", None)
        if nbytes is None and hasattr(leaf, "dtype") and hasattr(leaf, "size"):
            nbytes = leaf.size * leaf.dtype.itemsize
        if nbytes:
            total += int(nbytes)
    return total


def _chain(x, acc):
    """Inject a zero-valued dependency on the carried accumulator into an
    input array, forcing serial execution of chained runs."""
    import jax.numpy as jnp

    return x + (acc * 0).astype(x.dtype)


def _consume(out, samples: int = 1024):
    """Reduce an output (Page / Val / array / dict of arrays) to an int64
    that depends on a strided sample of every produced array, so XLA cannot
    dead-code-eliminate the work while the reduction stays O(samples)."""
    import jax.numpy as jnp

    acc = jnp.int64(0)
    arrays: List = []
    if hasattr(out, "blocks"):  # Page
        arrays = [b.data for b in out.blocks]
        arrays.append(out.count)
    elif hasattr(out, "data"):  # Val / Block
        arrays = [out.data]
    elif isinstance(out, dict):
        arrays = list(out.values())
    elif isinstance(out, (list, tuple)):
        arrays = list(out)
    else:
        arrays = [out]
    for a in arrays:
        a = jnp.asarray(a)
        if a.ndim == 0:
            acc = acc + a.astype(jnp.int64)
            continue
        stride = max(1, a.shape[0] // samples)
        acc = acc + jnp.sum(a[::stride].astype(jnp.int64))
    return acc


def _chained_page(page, acc):
    """Perturb the first block of a Page with the accumulator dependency."""
    from ..page import Block, Page

    b0 = page.blocks[0]
    blocks = (Block(_chain(b0.data, acc), b0.type, b0.valid, b0.dict_id),) + tuple(
        page.blocks[1:]
    )
    return Page(blocks, page.names, page.count)


def time_device_bench(b: Bench, runs: int = RUNS, reps: int = REPS) -> float:
    """Best-of-reps seconds per run for a chained device benchmark."""
    import jax
    import jax.numpy as jnp

    f = b.step if b.eager else jax.jit(b.step)
    acc = f(jnp.int64(0), *b.args)
    int(acc)  # compile + warm
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        s = jnp.int64(0)
        for _ in range(runs):
            s = f(s, *b.args)
        int(s)
        best = min(best, (time.perf_counter() - t0) / runs)
    return best


# ---------------------------------------------------------------------------
# benchmark constructors (each returns a Bench over device-resident pages)
# ---------------------------------------------------------------------------


def bench_filter_compact(sf: float) -> Bench:
    """Predicate filter + compaction (ref: BenchmarkPageProcessor /
    PredicateFilterBenchmark — Q6 predicate over lineitem)."""
    from ..ops.filter import filter_page
    from .handcoded import Q6_PREDICATE, lineitem_q6_page

    page = lineitem_q6_page(sf)

    def step(acc, p):
        out = filter_page(_chained_page(p, acc), Q6_PREDICATE)
        return _consume(out)

    return Bench("filter_compact", int(page.count), step, (page,))


def bench_agg_direct(sf: float) -> Bench:
    """Small-domain grouped aggregation, mask-reduce strategy (ref:
    HandTpchQuery1 / BenchmarkHashAggregationOperator DIRECT path)."""
    from ..ops.aggregate import grouped_aggregate_direct
    from .handcoded import (
        Q1_DOMAINS,
        Q1_GROUP_NAMES,
        Q1_GROUPS,
        Q1_PREDICATE,
        lineitem_q1_page,
        q1_aggs,
    )

    page = lineitem_q1_page(sf)

    def step(acc, p):
        out = grouped_aggregate_direct(
            _chained_page(p, acc),
            Q1_GROUPS,
            Q1_GROUP_NAMES,
            q1_aggs(),
            Q1_DOMAINS,
            pre_mask=Q1_PREDICATE,
        )
        return _consume(out)

    return Bench("agg_direct_q1", int(page.count), step, (page,))


def bench_agg_pallas(sf: float) -> Bench:
    """The SAME Q1 aggregation as agg_direct_q1 through the Pallas
    grouped-aggregation kernel (ops/pallas_groupby.py) — the suite
    reports both so pallas-vs-XLA is one artifact diff (judge round-4
    directive 4). Mosaic-compiled on TPU; interpret mode elsewhere."""
    from ..ops.pallas_groupby import maybe_grouped_aggregate
    from .handcoded import (
        Q1_GROUP_NAMES,
        Q1_GROUPS,
        Q1_PREDICATE,
        lineitem_q1_page,
        q1_aggs,
    )

    page = lineitem_q1_page(sf)

    def step(acc, p):
        out = maybe_grouped_aggregate(
            _chained_page(p, acc),
            Q1_GROUPS,
            Q1_GROUP_NAMES,
            q1_aggs(),
            Q1_PREDICATE,
        )
        if out is None:
            raise RuntimeError("pallas path unexpectedly ineligible")
        return _consume(out)

    return Bench("agg_pallas_q1", int(page.count), step, (page,))


def bench_agg_sorted(sf: float) -> Bench:
    """High-cardinality grouped aggregation, hash-sort strategy (ref:
    BenchmarkGroupByHash — group by l_suppkey, NDV = 10k x sf)."""
    from .. import types as T
    from ..expr.ir import col
    from ..ops.aggregate import AggSpec, grouped_aggregate_sorted
    from .handcoded import DEC12_2, _table_page

    page = _table_page(
        "lineitem", sf, ("l_suppkey", "l_quantity", "l_extendedprice")
    )
    ndv = max(int(10_000 * sf), 1) + 1
    max_groups = 1 << (ndv - 1).bit_length()
    qty = col("l_quantity", DEC12_2)
    aggs = (
        AggSpec("sum", qty, "s", AggSpec.infer_output_type("sum", DEC12_2)),
        AggSpec("count_star", None, "c", T.BIGINT),
    )

    def step(acc, p):
        out = grouped_aggregate_sorted(
            _chained_page(p, acc),
            (col("l_suppkey", T.BIGINT),),
            ("l_suppkey",),
            aggs,
            max_groups,
        )
        return _consume(out)

    return Bench(
        "agg_sorted_suppkey",
        int(page.count),
        step,
        (page,),
        note=f"groups<={max_groups}",
    )


def bench_agg_matmul(sf: float) -> Bench:
    """Same shape as agg_sorted_suppkey through the MXU one-hot-matmul
    strategy (ops/matmul_agg.py) — the A/B that shows what moving a
    group-by from the sort network to the systolic array buys."""
    from .. import types as T
    from ..expr.ir import col
    from ..ops.aggregate import AggSpec
    from ..ops.matmul_agg import maybe_matmul_grouped_aggregate
    from .handcoded import DEC12_2, _table_page

    page = _table_page(
        "lineitem", sf, ("l_suppkey", "l_quantity", "l_extendedprice")
    )
    qty = col("l_quantity", DEC12_2)
    aggs = (
        AggSpec("sum", qty, "s", AggSpec.infer_output_type("sum", DEC12_2)),
        AggSpec("count_star", None, "c", T.BIGINT),
    )
    gexprs = (col("l_suppkey", T.BIGINT),)
    from ..ops.matmul_agg import plan_matmul_grouped_aggregate

    # plan on the host (min/max sync), execute traced under jit
    plan = plan_matmul_grouped_aggregate(page, gexprs, aggs, None)
    if plan is None:  # NDV beyond the dense budget at this sf
        raise RuntimeError(f"ineligible at sf={sf} (NDV > dense budget)")
    probe = maybe_matmul_grouped_aggregate(
        page, gexprs, ("l_suppkey",), aggs, None, plan=plan
    )

    def step(acc, p):
        out = maybe_matmul_grouped_aggregate(
            _chained_page(p, acc), gexprs, ("l_suppkey",), aggs, None,
            plan=plan,
        )
        return _consume(out)

    return Bench(
        "agg_matmul_suppkey",
        int(page.count),
        step,
        (page,),
        note=f"groups={int(probe.count)} (MXU one-hot matmul)",
    )


def _orders_keys_page(sf: float):
    from .handcoded import _table_page

    return _table_page("orders", sf, ("o_orderkey", "o_custkey", "o_totalprice"))


def bench_join_build(sf: float) -> Bench:
    """Build-side index construction through the ENGINE-DEFAULT path
    (ref: BenchmarkHashBuildAndJoinOperators build phase /
    HashBuilderOperator.finish). Since PR 11 build() produces the
    linear-probe hash table (ops/pallas_join.py) on cpu/tpu, eagerly —
    the micro measures exactly what the executor dispatches; the sorted
    fallback layout is measured by forcing PRESTO_TPU_PALLAS_JOIN=off."""
    from .. import types as T
    from ..expr.ir import col
    from ..ops.join import build
    from ..ops.pallas_join import JoinTable, pallas_join_mode

    page = _orders_keys_page(sf)
    keys = (col("o_orderkey", T.BIGINT),)

    def step(acc, p):
        bs = build(_chained_page(p, acc), keys)
        if isinstance(bs, JoinTable):
            return _consume((bs.slot_tag, bs.slot_row, bs.count))
        return _consume((bs.sorted_hash, bs.order, bs.count))

    return Bench(
        "join_build", int(page.count), step, (page,),
        note=f"mode={pallas_join_mode()}",
        eager=pallas_join_mode() != "off",
    )


def bench_join_probe(sf: float) -> Bench:
    """FK->PK probe through the ENGINE-DEFAULT path: lineitem x orders
    (ref: join phase of BenchmarkHashBuildAndJoinOperators; rows/s counts
    PROBE rows). The build side is prepared once (the executor's
    _probe_stream shape); each run probes the full lineitem page."""
    from .. import types as T
    from ..expr.ir import col
    from ..ops.join import build, join_n1
    from ..ops.pallas_join import pallas_join_mode
    from .handcoded import _table_page

    probe = _table_page("lineitem", sf, ("l_orderkey", "l_extendedprice"))
    bs = build(_orders_keys_page(sf), (col("o_orderkey", T.BIGINT),))
    pkeys = (col("l_orderkey", T.BIGINT),)
    out_names = ("o_custkey", "o_totalprice")

    if pallas_join_mode() == "off":
        # sorted-layout mode runs JITTED: thread the build arrays as
        # runtime args (a closure would bake them in as trace constants
        # and let XLA fold build-side work — not comparable to the
        # BENCH_r05 baseline this measures against)
        import dataclasses as dc

        def step(acc, p, sorted_hash, order, bpage, count):
            b = dc.replace(bs, sorted_hash=sorted_hash, order=order,
                           page=bpage, count=count)
            return _consume(
                join_n1(_chained_page(p, acc), b, pkeys, out_names,
                        out_names)
            )

        return Bench(
            "join_probe_n1", int(probe.count), step,
            (probe, bs.sorted_hash, bs.order, bs.page, bs.count),
            note="mode=off",
        )

    def step(acc, p):
        out = join_n1(
            _chained_page(p, acc), bs, pkeys, out_names, out_names
        )
        return _consume(out)

    return Bench(
        "join_probe_n1",
        int(probe.count),
        step,
        (probe,),
        note=f"mode={pallas_join_mode()}",
        eager=True,
    )


def bench_pallas_join_build(sf: float) -> Bench:
    """The hash-table build kernel in isolation (ops/pallas_join.py
    build_table: parallel linear-probing insert + overflow handling) —
    gated so the kernel path stays fast even if engine defaults move."""
    from .. import types as T
    from ..expr.ir import col
    from ..ops.pallas_join import build_table

    page = _orders_keys_page(sf)
    keys = (col("o_orderkey", T.BIGINT),)

    def step(acc, p):
        jt = build_table(_chained_page(p, acc), keys)
        if jt is None:
            raise RuntimeError("hash-table build unexpectedly ineligible")
        return _consume((jt.slot_tag, jt.slot_row))

    return Bench("pallas_join_build", int(page.count), step, (page,),
                 eager=True)


def bench_pallas_join_probe(sf: float) -> Bench:
    """The hash-table probe kernel in isolation (first-verified-match
    scan + emit, ops/pallas_join.table_join_n1)."""
    from .. import types as T
    from ..expr.ir import col
    from ..ops.pallas_join import build_table, table_join_n1
    from .handcoded import _table_page

    probe = _table_page("lineitem", sf, ("l_orderkey", "l_extendedprice"))
    jt = build_table(_orders_keys_page(sf), (col("o_orderkey", T.BIGINT),))
    if jt is None:
        raise RuntimeError("hash-table build unexpectedly ineligible")
    pkeys = (col("l_orderkey", T.BIGINT),)

    def step(acc, p):
        out = table_join_n1(
            _chained_page(p, acc), jt, pkeys,
            ("o_custkey", "o_totalprice"), ("o_custkey", "o_totalprice"),
        )
        return _consume(out)

    return Bench(
        "pallas_join_probe", int(probe.count), step, (probe,),
        note=f"occ={int(jt.occupancy() * 100)}%", eager=True,
    )


def bench_pallas_groupby_hash(sf: float) -> Bench:
    """Hash-slot grouped aggregation (ops/pallas_groupby.
    maybe_grouped_aggregate_hash): the arbitrary-key / lifted-ceiling
    group-by — same l_suppkey shape as agg_sorted_suppkey so the
    strategy A/B is one artifact diff."""
    from .. import types as T
    from ..expr.ir import col
    from ..ops.aggregate import AggSpec
    from ..ops.pallas_groupby import maybe_grouped_aggregate_hash
    from .handcoded import DEC12_2, _table_page

    page = _table_page(
        "lineitem", sf, ("l_suppkey", "l_quantity", "l_extendedprice")
    )
    qty = col("l_quantity", DEC12_2)
    aggs = (
        AggSpec("sum", qty, "s", AggSpec.infer_output_type("sum", DEC12_2)),
        AggSpec("count_star", None, "c", T.BIGINT),
    )
    gexprs = (col("l_suppkey", T.BIGINT),)

    def step(acc, p):
        out = maybe_grouped_aggregate_hash(
            _chained_page(p, acc), gexprs, ("l_suppkey",), aggs, None
        )
        if out is None:
            raise RuntimeError("hash group-by unexpectedly ineligible")
        return _consume(out)

    probe = maybe_grouped_aggregate_hash(
        page, gexprs, ("l_suppkey",), aggs, None
    )
    if probe is None:
        raise RuntimeError("hash group-by unexpectedly ineligible")
    return Bench(
        "pallas_groupby_hash", int(page.count), step, (page,),
        note=f"groups={int(probe.count)}", eager=True,
    )


def bench_bloom_build_query(sf: float) -> Bench:
    """Blocked bloom filter: build over the orders key domain + query every
    lineitem key (ops/bloomfilter.py) — the dynamic-filter membership
    kernel (reference: BloomFilter in dynamic filtering). rows/s counts
    PROBE rows; the build rides inside the step like join_build does."""
    import jax.numpy as jnp

    from ..ops.bloomfilter import bloom_build, bloom_query, choose_log2_bits
    from ..ops.hashing import hash_column
    from .handcoded import _table_page

    bpage = _orders_keys_page(sf)
    probe = _table_page("lineitem", sf, ("l_orderkey",))
    lb = choose_log2_bits(int(bpage.count))
    bkeys = bpage.block("o_orderkey").data
    bvalid = jnp.arange(bpage.capacity) < bpage.count

    def step(acc, bk, p):
        words = bloom_build(hash_column(_chain(bk, acc)), bvalid, lb)
        hits = bloom_query(words, hash_column(p.block("l_orderkey").data), lb)
        return _consume(hits)

    return Bench(
        "bloom_build_query", int(probe.count), step, (bkeys, probe),
        note=f"bits=2^{lb}",
    )


def bench_join_probe_filtered(sf: float) -> Bench:
    """The dynamic-filter probe path end-to-end: bloom mask over the full
    probe, compact + slice to the survivor bucket, then join_n1 against a
    SELECTIVE build side (1/16 of orders — the Q3/Q5/Q17 shape where most
    probe rows cannot match). rows/s counts ORIGINAL probe rows, so this
    is directly comparable with the unfiltered join_probe_n1 floor."""
    import jax.numpy as jnp

    from .. import types as T
    from ..exec.dynfilter import derive_filter
    from ..expr.ir import col
    from ..ops.filter import compact
    from ..ops.join import build, join_n1
    from ..page import Page, round_capacity
    from .handcoded import _table_page

    import jax

    orders = _orders_keys_page(sf)
    probe = _table_page("lineitem", sf, ("l_orderkey", "l_extendedprice"))
    # selective build: orders with o_orderkey % 16 == 0
    okey = orders.block("o_orderkey")
    sel = (okey.data % 16 == 0) & (jnp.arange(orders.capacity) < orders.count)
    bpage = compact(orders, sel)
    bs = build(bpage, (col("o_orderkey", T.BIGINT),))
    df = derive_filter(okey, sel)
    if df is None:
        raise RuntimeError("derive_filter unexpectedly ineligible")
    pkeys = (col("l_orderkey", T.BIGINT),)
    # static survivor bucket: ~1/16 of probes match (+ bloom fp margin)
    out_cap = round_capacity(max(int(probe.count) // 8, 1024))
    host_route = jax.default_backend() == "cpu"

    def host_sel(keep):
        # the executor's CPU compaction route (Executor._dyn_compact):
        # ONE flatnonzero pass + a small gather instead of a
        # full-capacity sort-based compact
        nz = np.flatnonzero(np.asarray(keep))[:out_cap]
        idx = np.zeros(out_cap, np.int64)
        idx[: nz.size] = nz
        return idx, np.int32(nz.size)

    def step(acc, p):
        page = _chained_page(p, acc)
        keep = df.mask(page.block("l_orderkey")) & (
            jnp.arange(page.capacity) < page.count
        )
        if host_route:
            # prestolint: allow(tracing-host-callback) -- benchmarks the
            # executor's CPU compaction route as deployed; the harness
            # pins >= 2 virtual devices so the jitted callback is safe
            idx, n = jax.pure_callback(
                host_sel,
                (
                    jax.ShapeDtypeStruct((out_cap,), jnp.int64),
                    jax.ShapeDtypeStruct((), jnp.int32),
                ),
                keep,
            )
            sliced = Page(
                tuple(b.take_rows(idx) for b in page.blocks),
                page.names,
                n,
            )
        else:
            small = compact(page, keep)
            sliced = Page(
                tuple(b.take_rows(slice(0, out_cap)) for b in small.blocks),
                small.names,
                jnp.minimum(small.count, out_cap),
            )
        out = join_n1(
            sliced, bs, pkeys, ("o_custkey",), ("o_custkey",)
        )
        return _consume(out)

    return Bench(
        "join_probe_filtered", int(probe.count), step, (probe,),
        note=f"df={df.strategy}, out_cap={out_cap}"
        + (", host-compact" if host_route else ""),
    )


def _sort_bench_inputs(sf: float):
    from .. import types as T
    from ..expr.ir import col
    from ..ops.sort import SortKey
    from .handcoded import DEC12_2, _table_page

    page = _table_page("lineitem", sf, ("l_extendedprice", "l_orderkey"))
    keys = (
        SortKey(col("l_extendedprice", DEC12_2), ascending=False),
        SortKey(col("l_orderkey", T.BIGINT)),
    )
    return page, keys


def bench_sort(sf: float) -> Bench:
    """Full-table sort, ENGINE-DEFAULT path (ref: OrderByBenchmark /
    BenchmarkWindowOperator's sort phase). With keypack on (the default)
    this is the packed composite-key sort the executor would pick;
    PRESTO_TPU_KEYPACK=0 measures the legacy variadic sort — diff against
    sort_2key_packed for the packed-vs-legacy delta."""
    from ..ops.keypack import keypack_enabled, plan_from_page
    from ..ops.sort import sort_page, sort_page_packed

    page, keys = _sort_bench_inputs(sf)
    plan = plan_from_page(page, keys) if keypack_enabled() else None
    if plan is not None:
        def step(acc, p):
            out, _ok = sort_page_packed(_chained_page(p, acc), keys, plan)
            return _consume(out)

        return Bench("sort_2key", int(page.count), step, (page,),
                     note=f"keypack={plan.strategy}")

    def step(acc, p):
        return _consume(sort_page(_chained_page(p, acc), keys))

    return Bench("sort_2key", int(page.count), step, (page,))


def bench_sort_packed(sf: float) -> Bench:
    """sort_2key FORCED through the packed composite-key path
    (ops/keypack.py), regardless of the engine default — keeps the
    packed-vs-legacy delta visible in every BENCH_r* artifact."""
    from ..ops.keypack import plan_from_page
    from ..ops.sort import sort_page_packed

    page, keys = _sort_bench_inputs(sf)
    plan = plan_from_page(page, keys)
    if plan is None:
        raise RuntimeError("sort_2key keys unexpectedly unpackable")

    def step(acc, p):
        out, _ok = sort_page_packed(_chained_page(p, acc), keys, plan)
        return _consume(out)

    return Bench("sort_2key_packed", int(page.count), step, (page,),
                 note=f"keypack={plan.strategy}")


def bench_top_n(sf: float) -> Bench:
    """TopN, engine-default path (ref: TopNBenchmark /
    BenchmarkTopNOperator). Packed single-lane keys select via
    `lax.top_k` instead of any sort."""
    from ..expr.ir import col
    from ..ops.keypack import keypack_enabled, plan_from_page
    from ..ops.sort import SortKey, top_n, top_n_packed
    from .handcoded import DEC12_2, _table_page

    page = _table_page("lineitem", sf, ("l_extendedprice", "l_orderkey"))
    keys = (SortKey(col("l_extendedprice", DEC12_2), ascending=False),)
    plan = plan_from_page(page, keys) if keypack_enabled() else None
    if plan is not None:
        def step(acc, p):
            out, _ok = top_n_packed(_chained_page(p, acc), keys, 100, plan)
            return _consume(out)

        return Bench("top_n_100", int(page.count), step, (page,),
                     note=f"keypack={plan.strategy}")

    def step(acc, p):
        return _consume(top_n(_chained_page(p, acc), keys, 100))

    return Bench("top_n_100", int(page.count), step, (page,))


def bench_window(sf: float) -> Bench:
    """Partitioned window: rank + running sum over o_custkey, engine-
    default path (ref: BenchmarkWindowOperator). A single-lane packed
    (partition, order) key collapses the hash + per-key stable-argsort
    cascade into one sort with boundaries from integer compares."""
    from .. import types as T
    from ..expr.ir import col
    from ..ops.keypack import keypack_enabled, plan_from_page
    from ..ops.sort import SortKey
    from ..ops.window import WindowFunc, window_op, window_op_packed

    page = _orders_keys_page(sf)
    DEC = T.DecimalType(12, 2)
    funcs = (
        WindowFunc("row_number", None, "rn", T.BIGINT),
        WindowFunc(
            "sum",
            col("o_totalprice", DEC),
            "running",
            AggSpec_sum_type(DEC),
            running=True,
        ),
    )
    parts = (col("o_custkey", T.BIGINT),)
    order = (SortKey(col("o_orderkey", T.BIGINT)),)
    plan = None
    if keypack_enabled():
        specs = tuple(SortKey(e) for e in parts) + order
        plan = plan_from_page(
            page, specs, single_lane=True, n_order_keys=len(order)
        )
    if plan is not None:
        def step(acc, p):
            out, _ok = window_op_packed(
                _chained_page(p, acc), parts, order, funcs, plan
            )
            return _consume(out)

        return Bench("window_rank_runsum", int(page.count), step, (page,),
                     note=f"keypack={plan.strategy}")

    def step(acc, p):
        return _consume(window_op(_chained_page(p, acc), parts, order, funcs))

    return Bench("window_rank_runsum", int(page.count), step, (page,))


def AggSpec_sum_type(t):
    from ..ops.aggregate import AggSpec

    return AggSpec.infer_output_type("sum", t)


def bench_hash_rows(sf: float) -> Bench:
    """Row hashing over two key columns (ref: BenchmarkGroupByHash's
    hashPosition / InterpretedHashGenerator)."""
    from ..ops.hashing import hash_rows

    page = _orders_keys_page(sf)
    b0, b1 = page.block("o_orderkey"), page.block("o_custkey")

    def step(acc, x0, x1):
        import jax.numpy as jnp

        class V:
            pass

        v0, v1 = V(), V()
        v0.data, v0.valid = _chain(x0, acc), None
        v1.data, v1.valid = x1, None
        return _consume(hash_rows([v0, v1]))

    return Bench("hash_rows_2key", int(page.count), step, (b0.data, b1.data))


def bench_semi_join(sf: float) -> Bench:
    """Semi-join membership mask: lineitem.l_orderkey IN orders-subset
    (ref: HashSemiJoinOperator / BenchmarkHashBuildAndJoinOperators'
    semi variant; rows/s counts probe rows)."""
    from .. import types as T
    from ..expr.ir import col
    from ..ops.join import build, semi_match_mask
    from .handcoded import _table_page

    probe = _table_page("lineitem", sf, ("l_orderkey",))
    bs = build(_orders_keys_page(sf), (col("o_orderkey", T.BIGINT),))
    pkeys = (col("l_orderkey", T.BIGINT),)

    def step(acc, p):
        return _consume(semi_match_mask(_chained_page(p, acc), bs, pkeys))

    return Bench("semi_join_mark", int(probe.count), step, (probe,))


def _distinct_plan(page, equality_only=True):
    from ..expr.ir import ColumnRef
    from ..ops.keypack import plan_from_page

    exprs = tuple(
        ColumnRef(n, b.type) for n, b in zip(page.names, page.blocks)
    )
    return plan_from_page(
        page, exprs, equality_only=equality_only, allow_hashed=True
    )


def bench_distinct(sf: float) -> Bench:
    """High-NDV DISTINCT over two key columns, engine-default path (ref:
    BenchmarkGroupByHash distinct mode / MarkDistinctOperator): packed
    sorted-adjacent-unique instead of the grouped-aggregation machinery."""
    from ..ops.keypack import keypack_enabled
    from ..ops.sort import distinct_packed, distinct_page
    from .handcoded import _table_page

    page = _table_page("lineitem", sf, ("l_suppkey", "l_partkey"))
    cap = int(page.capacity)
    plan = _distinct_plan(page) if keypack_enabled() else None
    if plan is not None:
        def step(acc, p):
            out, _ok = distinct_packed(_chained_page(p, acc), plan)
            return _consume(out)

        return Bench("distinct_2key", int(page.count), step, (page,),
                     note=f"keypack={plan.strategy}")

    def step(acc, p):
        return _consume(distinct_page(_chained_page(p, acc), cap))

    return Bench("distinct_2key", int(page.count), step, (page,))


def bench_distinct_packed(sf: float) -> Bench:
    """distinct_2key FORCED through the packed path (see
    sort_2key_packed)."""
    from ..ops.sort import distinct_packed
    from .handcoded import _table_page

    page = _table_page("lineitem", sf, ("l_suppkey", "l_partkey"))
    plan = _distinct_plan(page)
    if plan is None:
        raise RuntimeError("distinct_2key keys unexpectedly unpackable")

    def step(acc, p):
        out, _ok = distinct_packed(_chained_page(p, acc), plan)
        return _consume(out)

    return Bench("distinct_2key_packed", int(page.count), step, (page,),
                 note=f"keypack={plan.strategy}")


def bench_expr_case_chain(sf: float) -> Bench:
    """Expression-heavy projection: CASE + math chain over doubles (ref:
    BenchmarkPageProcessor / hand-written expression benchmarks)."""
    from .. import types as T
    from ..expr import ir
    from ..expr.compiler import evaluate
    from .handcoded import DEC4_2, DEC12_2, _table_page

    page = _table_page("lineitem", sf, ("l_extendedprice", "l_discount"))
    price = ir.cast(ir.col("l_extendedprice", DEC12_2), T.DOUBLE)
    disc = ir.cast(ir.col("l_discount", DEC4_2), T.DOUBLE)
    rev = ir.Call(
        "multiply",
        (
            price,
            ir.Call(
                "subtract", (ir.Literal(1.0, T.DOUBLE), disc), T.DOUBLE
            ),
        ),
        T.DOUBLE,
    )
    expr = ir.Call(
        "if",
        (
            ir.Call(
                "gt", (disc, ir.Literal(0.05, T.DOUBLE)), T.BOOLEAN
            ),
            ir.Call("sqrt", (rev,), T.DOUBLE),
            ir.Call(
                "ln",
                (
                    ir.Call(
                        "add", (rev, ir.Literal(1.0, T.DOUBLE)), T.DOUBLE
                    ),
                ),
                T.DOUBLE,
            ),
        ),
        T.DOUBLE,
    )

    def step(acc, p):
        return _consume(evaluate(expr, _chained_page(p, acc)))

    return Bench("expr_case_chain", int(page.count), step, (page,))


def bench_like_dictionary(sf: float) -> Bench:
    """LIKE over a dictionary varchar column — evaluates once per DICT
    entry then remaps codes (ref: BenchmarkLikeFunctions; the dictionary
    design makes this O(dict) not O(rows), which is the point)."""
    from .. import types as T
    from ..expr import ir
    from ..expr.compiler import evaluate
    from .handcoded import _table_page

    page = _table_page("part", sf, ("p_brand",))
    expr = ir.Call(
        "like",
        (
            ir.col("p_brand", T.VARCHAR),
            ir.Literal("%#3%", T.VARCHAR),
        ),
        T.BOOLEAN,
    )

    def step(acc, p):
        return _consume(evaluate(expr, _chained_page(p, acc)))

    return Bench("like_dictionary", int(page.count), step, (page,))


def bench_decimal_chain(sf: float) -> Bench:
    """Decimal128 arithmetic chain: extendedprice * (1 - discount) in
    exact decimal lanes (ref: BenchmarkDecimalOperators)."""
    from ..expr import ir
    from ..expr.compiler import evaluate
    from .handcoded import DEC4_2, DEC12_2, _table_page
    from .. import types as T

    page = _table_page("lineitem", sf, ("l_extendedprice", "l_discount"))
    one = ir.Literal("1.00", T.DecimalType(3, 2))
    disc_price = ir.Call(
        "multiply",
        (
            ir.col("l_extendedprice", DEC12_2),
            ir.Call(
                "subtract",
                (one, ir.col("l_discount", DEC4_2)),
                T.DecimalType(4, 2),
            ),
        ),
        T.DecimalType(17, 4),
    )

    def step(acc, p):
        return _consume(evaluate(disc_price, _chained_page(p, acc)))

    return Bench("decimal_mul_chain", int(page.count), step, (page,))


DEVICE_BENCHES = {
    "filter_compact": bench_filter_compact,
    "agg_direct_q1": bench_agg_direct,
    "agg_pallas_q1": bench_agg_pallas,
    "agg_sorted_suppkey": bench_agg_sorted,
    "agg_matmul_suppkey": bench_agg_matmul,
    "join_build": bench_join_build,
    "join_probe_n1": bench_join_probe,
    "pallas_join_build": bench_pallas_join_build,
    "pallas_join_probe": bench_pallas_join_probe,
    "pallas_groupby_hash": bench_pallas_groupby_hash,
    "join_probe_filtered": bench_join_probe_filtered,
    "bloom_build_query": bench_bloom_build_query,
    "semi_join_mark": bench_semi_join,
    "distinct_2key": bench_distinct,
    "distinct_2key_packed": bench_distinct_packed,
    "sort_2key": bench_sort,
    "sort_2key_packed": bench_sort_packed,
    "top_n_100": bench_top_n,
    "window_rank_runsum": bench_window,
    "hash_rows_2key": bench_hash_rows,
    "expr_case_chain": bench_expr_case_chain,
    "like_dictionary": bench_like_dictionary,
    "decimal_mul_chain": bench_decimal_chain,
}


# ---------------------------------------------------------------------------
# host-side benchmarks
# ---------------------------------------------------------------------------


def run_serde_bench(sf: float, runs: int = RUNS) -> Dict:
    """Page wire serde + LZ4 (ref: BenchmarkBlockSerde /
    BenchmarkDataSerialization; PagesSerde.java:39). Host-side: measures the
    DCN exchange codec, not device compute."""
    from ..server.serde import deserialize_page, serialize_page
    from .handcoded import lineitem_q6_page

    page = lineitem_q6_page(sf)
    page.block("l_quantity").data.block_until_ready()
    wire = serialize_page(page)
    deserialize_page(wire)  # warm
    t_ser = t_des = float("inf")
    for _ in range(runs):
        t0 = time.perf_counter()
        wire = serialize_page(page)
        t_ser = min(t_ser, time.perf_counter() - t0)
        t0 = time.perf_counter()
        deserialize_page(wire)
        t_des = min(t_des, time.perf_counter() - t0)
    raw_bytes = sum(
        np.asarray(b.data).nbytes for b in page.blocks
    )
    n = int(page.count)
    return {
        "name": "serde_lz4",
        "rows": n,
        "rows_per_s": round(n / (t_ser + t_des)),
        "ms": round((t_ser + t_des) * 1e3, 3),
        "serialize_MBps": round(raw_bytes / t_ser / 1e6, 1),
        "deserialize_MBps": round(raw_bytes / t_des / 1e6, 1),
        "wire_bytes": len(wire),
        "raw_bytes": raw_bytes,
        "note": f"host codec {('zstd' if __import__('presto_tpu.server.serde', fromlist=['_zstd_c'])._zstd_c is not None else 'lz4')}",
    }


def run_serde_encoded_bench(sf: float, runs: int = RUNS) -> Dict:
    """Wire v2 light-weight encodings end to end (server/serde.py):
    serialize+deserialize a page whose columns exercise dict/delta/off/
    bits paths, reporting throughput AND the achieved wire ratio. The
    companion serde_lz4 row measures the engine-default path on the Q6
    page; this row keeps the encoding win visible even if defaults
    change."""
    from ..server.serde import deserialize_page, serialize_page
    from .handcoded import _table_page

    page = _table_page(
        "lineitem", sf,
        ("l_quantity", "l_discount", "l_shipdate", "l_returnflag",
         "l_linestatus", "l_orderkey"),
    )
    page.block("l_quantity").data.block_until_ready()
    caps = {"version": 2, "codecs": ["zstd", "lz4", "zlib", "raw"]}
    wire = serialize_page(page, caps=caps)
    deserialize_page(wire)  # warm
    t_ser = t_des = float("inf")
    for _ in range(runs):
        t0 = time.perf_counter()
        wire = serialize_page(page, caps=caps)
        t_ser = min(t_ser, time.perf_counter() - t0)
        t0 = time.perf_counter()
        deserialize_page(wire)
        t_des = min(t_des, time.perf_counter() - t0)
    raw_bytes = sum(np.asarray(b.data).nbytes for b in page.blocks)
    n = int(page.count)
    return {
        "name": "serde_encoded",
        "rows": n,
        "rows_per_s": round(n / (t_ser + t_des)),
        "ms": round((t_ser + t_des) * 1e3, 3),
        "serialize_MBps": round(raw_bytes / t_ser / 1e6, 1),
        "deserialize_MBps": round(raw_bytes / t_des / 1e6, 1),
        "wire_bytes": len(wire),
        "raw_bytes": raw_bytes,
        "note": f"ratio {round(raw_bytes / len(wire), 2)}x "
                "(dict/delta/off/bits + stripes)",
    }


def run_serde_stripes_bench(sf: float, runs: int = RUNS) -> Dict:
    """Striped parallel compression on a codec-bound payload (tiled
    random int64 defeats the encodings; the 8KB repeat period keeps LZ4
    effective inside each stripe), so this row isolates what the stripe
    pool buys over one sequential codec pass."""
    from ..server import serde
    from ..server.serde import deserialize_page, serialize_page
    from ..page import Page

    rng = np.random.default_rng(5)
    rows = max(int(2_000_000 * sf * 10), 1 << 16)
    piece = rng.integers(0, 2**62, 1024, dtype=np.int64)
    page = Page.from_dict({"a": np.tile(piece, rows // 1024 + 1)[:rows]})
    caps = {"version": 2, "codecs": ["zstd", "lz4", "zlib", "raw"]}
    wire = serialize_page(page, caps=caps)
    nstripes = int.from_bytes(wire[5:9], "little")
    deserialize_page(wire)  # warm
    t_ser = t_des = float("inf")
    for _ in range(runs):
        t0 = time.perf_counter()
        wire = serialize_page(page, caps=caps)
        t_ser = min(t_ser, time.perf_counter() - t0)
        t0 = time.perf_counter()
        deserialize_page(wire)
        t_des = min(t_des, time.perf_counter() - t0)
    raw_bytes = rows * 8
    return {
        "name": "serde_parallel_stripes",
        "rows": rows,
        "rows_per_s": round(rows / (t_ser + t_des)),
        "ms": round((t_ser + t_des) * 1e3, 3),
        "serialize_MBps": round(raw_bytes / t_ser / 1e6, 1),
        "deserialize_MBps": round(raw_bytes / t_des / 1e6, 1),
        "wire_bytes": len(wire),
        "raw_bytes": raw_bytes,
        "note": f"{nstripes} stripes x {serde._STRIPE_BYTES >> 10}KB, "
                f"pool={serde._stripe_pool() is not None}",
    }


def run_exchange_pull_bench(sf: float, runs: int = RUNS) -> Dict:
    """Pipelined concurrent shuffle client vs the sequential drain
    (server/exchange.ExchangeClient vs worker._pull_buffer): two
    in-process workers hold identical pre-serialized buffers; rows/s
    counts rows landed at the consumer, note reports the speedup."""
    import threading

    from ..connectors.tpch import TpchCatalog
    from ..server.serde import deserialize_page, serialize_page
    from ..server.exchange import ExchangeClient, ExchangeStats
    from ..server.worker import (
        OutputBuffers,
        TaskState,
        WorkerServer,
        _pull_buffer,
    )
    from .handcoded import lineitem_q6_page

    page = lineitem_q6_page(min(sf, 0.02))
    page.block("l_quantity").data.block_until_ready()
    data = serialize_page(page)
    n_pages = 8
    workers = []
    for _ in range(2):
        w = WorkerServer(TpchCatalog(sf=0.001))
        t = TaskState(query_id="qb")
        t.buffers = OutputBuffers(w.pool, "qb", threading.Event(), bound=None)
        for _i in range(n_pages):
            t.buffers.put(0, data)
        t.buffers.finish()
        t.state = "FINISHED"
        t.done.set()
        w.tasks["tb"] = t
        workers.append(w.start())
    try:
        locs = [(w.uri, "tb", 0) for w in workers]
        rows = int(page.count) * n_pages * 2

        def pull_pipelined():
            stats = ExchangeStats()
            client = ExchangeClient(locs, ack=False, stats=stats)
            got = sum(1 for _ in client.pages())
            assert got == n_pages * 2
            return stats

        def pull_sequential():
            got = 0
            for uri, task, buf in locs:
                for d in _pull_buffer(uri, task, buf, ack=False):
                    deserialize_page(d)
                    got += 1
            assert got == n_pages * 2

        pull_pipelined()  # warm
        t_pipe = t_seq = float("inf")
        for _ in range(runs):
            t0 = time.perf_counter()
            stats = pull_pipelined()
            t_pipe = min(t_pipe, time.perf_counter() - t0)
            t0 = time.perf_counter()
            pull_sequential()
            t_seq = min(t_seq, time.perf_counter() - t0)
        return {
            "name": "exchange_pull_pipelined",
            "rows": rows,
            "rows_per_s": round(rows / t_pipe),
            "ms": round(t_pipe * 1e3, 3),
            "wire_bytes": stats.snapshot()["wire_bytes"],
            "note": f"{round(t_seq / t_pipe, 2)}x vs sequential "
                    f"({round(rows / t_seq):,} rows/s), "
                    f"peak {stats.snapshot()['peak_concurrent']} pullers",
        }
    finally:
        for w in workers:
            w.stop()


def run_hybrid_join_spill_bench(sf: float, runs: int = RUNS) -> Dict:
    """Partitioned hybrid hash join with the build side forced through
    the offload + disk-spill tier (exec/stream._hybrid_hash_join under a
    budget ~1/8 of the build bytes, host-RAM ceiling 0 so every spilled
    byte hits the CRC-checked disk files). Gates the whole degradation
    ladder: a regression here means overload queries got slower even if
    the in-memory path stayed fast."""
    import os

    from ..connectors.memory import MemoryCatalog
    from ..page import Page
    from ..session import Session

    n_build = max(int(600_000 * sf), 8_000)
    n_probe = 4 * n_build
    rng = np.random.default_rng(11)
    build_page = Page.from_dict(
        {
            "bk": np.arange(n_build, dtype=np.int64),
            "bv": rng.integers(0, 1000, n_build).astype(np.int64),
        }
    )
    probe_page = Page.from_dict(
        {
            "pk": rng.integers(0, n_build, n_probe).astype(np.int64),
            "pv": rng.integers(0, 1000, n_probe).astype(np.int64),
        }
    )
    cat = MemoryCatalog({"b": build_page, "p": probe_page})
    build_bytes = 16 * n_build
    sql = "select count(*) c, sum(bv + pv) s from p join b on pk = bk"
    prev = os.environ.get("PRESTO_TPU_HOST_SPILL_BYTES")
    os.environ["PRESTO_TPU_HOST_SPILL_BYTES"] = "0"
    try:
        sess = Session(
            cat, streaming=True, batch_rows=1 << 16,
            memory_budget=max(build_bytes // 8, 96 << 10),
            result_cache=False,  # timing EXECUTION, not cache serving
        )
        sess.query(sql).rows()  # warm (compile)
        best = float("inf")
        for _ in range(runs):
            t0 = time.perf_counter()
            sess.query(sql).rows()
            best = min(best, time.perf_counter() - t0)
        ev = set(sess.executor.spill_events)
        note = "hybrid" if "hybrid_hash_join" in ev else "no-spill?"
    finally:
        if prev is None:
            os.environ.pop("PRESTO_TPU_HOST_SPILL_BYTES", None)
        else:
            os.environ["PRESTO_TPU_HOST_SPILL_BYTES"] = prev
    return {
        "name": "hybrid_join_spill",
        "rows": n_probe,
        "rows_per_s": round(n_probe / best),
        "ms": round(best * 1e3, 3),
        "note": note,
    }


def run_external_sort_disk_bench(sf: float, runs: int = RUNS) -> Dict:
    """External sort through the disk spill tier: the input offloads to
    CRC-checked spill files (host ceiling 0) and range-partitioned
    device sorting reads it back chunk-by-chunk."""
    import os

    from ..connectors.memory import MemoryCatalog
    from ..page import Page
    from ..session import Session

    n = max(int(2_000_000 * sf), 30_000)
    rng = np.random.default_rng(7)
    page = Page.from_dict(
        {
            "a": rng.random(n),
            "b": rng.integers(0, 1 << 40, n).astype(np.int64),
        }
    )
    cat = MemoryCatalog({"t": page})
    sql = "select a, b from t order by a, b"
    prev = os.environ.get("PRESTO_TPU_HOST_SPILL_BYTES")
    os.environ["PRESTO_TPU_HOST_SPILL_BYTES"] = "0"
    try:
        sess = Session(
            cat, streaming=True, batch_rows=1 << 16,
            memory_budget=max(16 * n // 8, 128 << 10),
            result_cache=False,  # timing EXECUTION, not cache serving
        )
        sess.query(sql).rows()  # warm
        best = float("inf")
        for _ in range(runs):
            t0 = time.perf_counter()
            sess.query(sql).rows()
            best = min(best, time.perf_counter() - t0)
        ev = set(sess.executor.spill_events)
        note = "disk" if "sort" in ev else "no-spill?"
    finally:
        if prev is None:
            os.environ.pop("PRESTO_TPU_HOST_SPILL_BYTES", None)
        else:
            os.environ["PRESTO_TPU_HOST_SPILL_BYTES"] = prev
    return {
        "name": "external_sort_disk",
        "rows": n,
        "rows_per_s": round(n / best),
        "ms": round(best * 1e3, 3),
        "note": note,
    }


def run_plan_cache_bench(sf: float, runs: int = RUNS) -> Dict:
    """Warm serving fast path end to end (exec/qcache.py): repeated
    EXECUTE of one prepared dashboard statement through the plan-skeleton
    + result caches — parse + cache lookups + validated page serve, no
    re-plan, no kernel dispatch. rows/s counts the orders rows each
    served result logically covers (the serving analog of a scan micro);
    raises when the warm path failed to hit either cache so the gate
    catches a broken fast path, not just a slow one."""
    from ..connectors.tpch import TpchCatalog
    from ..exec import qcache
    from ..session import Session

    cat = TpchCatalog(sf=min(sf, 0.1))
    sess = Session(cat)
    rows_per_exec = cat.exact_row_count("orders")
    sess.query(
        "prepare qps_micro from select count(*) c, sum(o_totalprice) s "
        "from orders where o_custkey > ?"
    )
    sess.query("execute qps_micro using 100")  # cold: plan+compile+store
    execs = 100
    s0 = qcache.snapshot_all()
    best = float("inf")
    for _ in range(runs):
        t0 = time.perf_counter()
        for _i in range(execs):
            sess.query("execute qps_micro using 100")
        best = min(best, time.perf_counter() - t0)
    s1 = qcache.snapshot_all()
    ph = s1["plan"]["hits"] - s0["plan"]["hits"]
    rh = s1["result"]["hits"] - s0["result"]["hits"]
    if ph == 0 or rh == 0:
        raise RuntimeError(
            f"warm EXECUTE missed the caches (plan +{ph}, result +{rh})"
        )
    n = rows_per_exec * execs
    return {
        "name": "plan_cache_hit",
        "rows": n,
        "rows_per_s": round(n / best),
        "ms": round(best * 1e3, 3),
        "note": f"{execs} warm EXECUTEs at {round(best / execs * 1e6)}us "
                f"each; hits plan+{ph} result+{rh}",
    }


def _matview_fixture(sf: float, unique: bool = False):
    """(catalog, session, base_rows) over a fresh shardstore events
    table sized by sf — shared setup for the matview/ingest micros."""
    import tempfile

    from .. import types as T
    from ..connectors.shardstore import ShardStoreCatalog
    from ..page import Page
    from ..session import Session

    n = max(int(2_000_000 * sf), 20_000)
    cat = ShardStoreCatalog(tempfile.mkdtemp(prefix="mv_micro_"))
    cat.create_table(
        "events", {"k": T.BIGINT, "v": T.BIGINT},
        unique_columns=["k"] if unique else None,
    )
    rng = np.random.default_rng(7)
    page = Page.from_dict({
        "k": (rng.integers(0, 256, n).astype(np.int64), T.BIGINT),
        "v": (rng.integers(0, 1000, n).astype(np.int64), T.BIGINT),
    })
    cat.append("events", page)
    return cat, Session(cat), n


def run_matview_refresh_delta_bench(sf: float, runs: int = RUNS) -> Dict:
    """Incremental view maintenance (matview/): delta refresh of an
    aggregate MV after appending 1% of the base rows, vs a forced full
    recompute of the same view. RAISES when the refresh did not take the
    delta path, so the gate catches a broken classifier/scan_delta as
    well as a slow one; `speedup_vs_full` carries the >=5x acceptance
    ratio (BASELINE.json ratio_floors)."""
    from .. import types as T
    from ..page import Page

    cat, sess, n = _matview_fixture(sf)
    sess.query(
        "create materialized view mv_micro as "
        "select k, count(*) as n, sum(v) as total from events group by k"
    )
    mgr = sess.matviews_mgr
    d = max(n // 100, 1)
    rng = np.random.default_rng(11)
    # warmup cycle: both paths compile their kernels untimed (delta's
    # merge shapes are stable across iterations, so one cycle suffices)
    cat.append("events", Page.from_dict({
        "k": (rng.integers(0, 256, d).astype(np.int64), T.BIGINT),
        "v": (rng.integers(0, 1000, d).astype(np.int64), T.BIGINT),
    }))
    if mgr.refresh("mv_micro") != "delta":
        raise RuntimeError("warmup refresh missed the delta path")
    mgr.refresh("mv_micro", full=True)
    best_delta = best_full = float("inf")
    for _ in range(runs):
        cat.append("events", Page.from_dict({
            "k": (rng.integers(0, 256, d).astype(np.int64), T.BIGINT),
            "v": (rng.integers(0, 1000, d).astype(np.int64), T.BIGINT),
        }))
        t0 = time.perf_counter()
        mode = mgr.refresh("mv_micro")
        best_delta = min(best_delta, time.perf_counter() - t0)
        if mode != "delta":
            raise RuntimeError(
                f"refresh took mode={mode!r}, expected 'delta' "
                f"({mgr.views['mv_micro'].last_reason})"
            )
        t0 = time.perf_counter()
        mgr.refresh("mv_micro", full=True)
        best_full = min(best_full, time.perf_counter() - t0)
    speedup = best_full / best_delta
    return {
        "name": "matview_refresh_delta",
        "rows": n,
        "rows_per_s": round(n / best_delta),
        "ms": round(best_delta * 1e3, 3),
        "speedup_vs_full": round(speedup, 2),
        "note": f"1% delta ({d} rows) {best_delta * 1e3:.1f}ms vs full "
                f"{best_full * 1e3:.1f}ms = {speedup:.1f}x",
    }


def run_ingest_append_bench(sf: float, runs: int = RUNS) -> Dict:
    """High-rate ingest (shardstore.append_batch): land a batch of many
    small pages as ONE shard + ONE version bump. rows/s counts rows
    durably written (parquet + metadata txn) per wall second."""
    from .. import types as T
    from ..page import Page

    cat, _sess, _n = _matview_fixture(sf)
    pages_per_batch = 32
    rows_per_page = max(int(50_000 * sf), 500)
    rng = np.random.default_rng(13)
    batch = [
        Page.from_dict({
            "k": (rng.integers(0, 256, rows_per_page).astype(np.int64),
                  T.BIGINT),
            "v": (rng.integers(0, 1000, rows_per_page).astype(np.int64),
                  T.BIGINT),
        })
        for _ in range(pages_per_batch)
    ]
    total = pages_per_batch * rows_per_page
    best = float("inf")
    for _ in range(runs):
        t0 = time.perf_counter()
        wrote = cat.append_batch("events", batch)
        best = min(best, time.perf_counter() - t0)
        if wrote != total:
            raise RuntimeError(f"append_batch wrote {wrote} != {total}")
    return {
        "name": "ingest_append",
        "rows": total,
        "rows_per_s": round(total / best),
        "ms": round(best * 1e3, 3),
        "note": f"{pages_per_batch} pages x {rows_per_page} rows as one "
                "shard/version bump",
    }


def run_mixed_soak_qps_bench(sf: float, runs: int = RUNS) -> Dict:
    """Mixed read/write serving: a writer thread sustains ingest while
    the reader runs warm prepared-statement EXECUTEs of a decomposable
    dashboard aggregate — every write stales the cached result, and the
    qcache PATCH verdict (matview/patch.py) must keep the warm path warm
    instead of recomputing. rows/s counts base rows each served read
    logically covers; RAISES when no read was served by a patch."""
    import threading

    from .. import types as T
    from ..exec import qcache
    from ..page import Page

    cat, sess, n = _matview_fixture(sf)
    sess.query(
        "prepare soak_dash from "
        "select k, count(*) as n, sum(v) as total from events group by k"
    )
    sess.query("execute soak_dash")  # cold: plan+compile+store
    reads = 40
    d = max(n // 200, 1)
    rng = np.random.default_rng(17)
    stop = threading.Event()

    def writer():
        # ~20 appends/s: sustained staleness pressure without growing
        # the shard set (and with it every later delta scan) unboundedly
        while not stop.is_set():
            cat.append("events", Page.from_dict({
                "k": (rng.integers(0, 256, d).astype(np.int64), T.BIGINT),
                "v": (rng.integers(0, 1000, d).astype(np.int64), T.BIGINT),
            }))
            stop.wait(0.05)

    s0 = qcache.snapshot_all()
    best = float("inf")
    for _ in range(runs):
        th = threading.Thread(target=writer, daemon=True)
        stop.clear()
        th.start()
        try:
            t0 = time.perf_counter()
            for _i in range(reads):
                sess.query("execute soak_dash")
            best = min(best, time.perf_counter() - t0)
        finally:
            stop.set()
            th.join(timeout=10)
    s1 = qcache.snapshot_all()
    patches = s1["result"]["patches"] - s0["result"]["patches"]
    if patches == 0:
        raise RuntimeError(
            "mixed soak served zero patched reads — the patch verdict "
            "is broken or every read recomputed"
        )
    rows = n * reads
    return {
        "name": "mixed_soak_qps",
        "rows": rows,
        "rows_per_s": round(rows / best),
        "ms": round(best * 1e3, 3),
        "note": f"{reads} EXECUTEs under sustained ingest at "
                f"{round(best / reads * 1e3, 1)}ms each; "
                f"result patches +{patches}",
    }


def run_metrics_scrape_bench(sf: float, runs: int = RUNS) -> Dict:
    """Prometheus scrape cost of the unified registry (obs/metrics.py):
    `render()` with the default producers registered plus a synthetic
    series population — the /v1/metrics handler's hot path, which a
    per-15s scraper must never make a serving-latency event. rows/s
    counts samples rendered per wall second."""
    from ..obs.metrics import METRICS

    # realistic series population on top of the default exports: 64
    # labeled counter series + histogram observations
    for i in range(64):
        METRICS.counter(
            "presto_bench_scrape_total", 1, {"series": f"s{i:02d}"}
        )
        METRICS.observe("presto_bench_scrape_seconds", 0.0002 * (i + 1))
    nsamples = len(METRICS.collect())
    iters = 50
    best = float("inf")
    for _ in range(max(runs, 1)):
        t0 = time.perf_counter()
        for _i in range(iters):
            text = METRICS.render()
        best = min(best, (time.perf_counter() - t0) / iters)
    if "presto_bench_scrape_total" not in text:
        raise RuntimeError("scrape output missing the bench series")
    return {
        "name": "metrics_scrape",
        "rows": nsamples,
        "rows_per_s": round(nsamples / best),
        "ms": round(best * 1e3, 3),
        "note": f"{nsamples} samples per scrape at "
                f"{best * 1e6:.0f}us each ({len(text)} bytes)",
    }


class _MisleadingStatsCatalog:
    """Delegating wrapper whose column_stats answers come from a fixed
    table — the feedback micro's stand-in for a connector with stale
    statistics, steering the static planner into a provably bad join
    order that only recorded history can correct."""

    def __init__(self, inner, ndvs):
        self.inner = inner
        self._ndvs = ndvs

    def column_stats(self, table, column):
        from ..plan.stats import ColumnStats

        ndv = self._ndvs.get((table, column))
        return None if ndv is None else ColumnStats(ndv=float(ndv))

    def __getattr__(self, item):
        return getattr(self.inner, item)


def _feedback_fixture(sf: float):
    """(catalog, session, sql, probe_rows): a 3-way join whose stale
    catalog stats make the greedy planner start from the exploding
    dup-side join (~n*m/64 intermediate rows) instead of the selective
    one (~0.6% of probe)."""
    from .. import types as T
    from ..connectors.memory import MemoryCatalog
    from ..page import Page
    from ..session import Session

    n = max(int(2_000_000 * sf), 100_000)
    # dup scales with sf too: the misordered intermediate is ~n*m/8
    # rows, and the suite-runnability test (sf=0.005) must not pay the
    # gate-scale (sf=0.1, m=2000) explosion several runs over
    m, s = max(int(20_000 * sf), 200), 64
    rng = np.random.default_rng(3)
    inner = MemoryCatalog({
        "probe": Page.from_dict({
            "pk": (rng.integers(0, 64, n).astype(np.int64), T.BIGINT),
            "ps": (rng.integers(0, 10_000, n).astype(np.int64), T.BIGINT),
            "pv": (rng.integers(0, 1000, n).astype(np.int64), T.BIGINT),
        }),
        "dup": Page.from_dict({
            "d": (rng.integers(0, 8, m).astype(np.int64), T.BIGINT),
            "dv": (rng.integers(0, 1000, m).astype(np.int64), T.BIGINT),
        }),
        "sel": Page.from_dict({
            "s": (np.arange(s, dtype=np.int64), T.BIGINT),
            "sv": (rng.integers(0, 1000, s).astype(np.int64), T.BIGINT),
        }),
    })
    # the lies: dup.d claims unique (its 8-value skew is what explodes),
    # while the genuinely selective sel join claims NDV 50 — so the
    # static cost model prefers building dup*probe first
    cat = _MisleadingStatsCatalog(inner, {
        ("dup", "d"): m, ("probe", "ps"): 50, ("sel", "s"): 50,
    })
    sql = (
        "select count(*) c, sum(pv) v from probe, dup, sel "
        "where probe.pk = dup.d and probe.ps = sel.s"
    )
    return cat, Session(cat), sql, n


def run_feedback_replan_bench(sf: float, runs: int = RUNS) -> Dict:
    """History-based adaptive execution (plan/history.py): the same
    3-way join planned cold from misleading catalog stats (greedy order
    explodes an intermediate) vs planned warm from recorded observed
    cardinalities (selective join first). RAISES when the warm plan's
    history lookups never hit, so the gate catches a dead feedback loop
    as well as a slow one; `speedup_vs_full` carries the >=1.5x
    acceptance ratio (BASELINE.json ratio_floors)."""
    import os

    from ..exec import qcache
    from ..plan.history import HISTORY

    cat, sess, sql, n = _feedback_fixture(sf)
    prev = os.environ.get("PRESTO_TPU_FEEDBACK")
    os.environ["PRESTO_TPU_FEEDBACK"] = "0"
    try:
        HISTORY.reset()
        sess.query(sql)  # static warmup: compiles the bad order's kernels
        best_static = float("inf")
        for _ in range(max(runs, 1)):
            qcache.RESULT_CACHE.reset()
            t0 = time.perf_counter()
            r_static = sess.query(sql).rows()
            best_static = min(best_static, time.perf_counter() - t0)
        os.environ["PRESTO_TPU_FEEDBACK"] = "1"
        qcache.RESULT_CACHE.reset()
        sess.query(sql)  # observe-once: records the misordered run
        qcache.RESULT_CACHE.reset()
        sess.query(sql)  # warm warmup: compiles the corrected order
        h0 = HISTORY.stats.snapshot()["hits"]
        best_warm = float("inf")
        for _ in range(max(runs, 1)):
            qcache.RESULT_CACHE.reset()
            t0 = time.perf_counter()
            r_warm = sess.query(sql).rows()
            best_warm = min(best_warm, time.perf_counter() - t0)
        if HISTORY.stats.snapshot()["hits"] == h0:
            raise RuntimeError("warm runs never consulted plan history")
        if r_warm != r_static:
            raise RuntimeError(
                f"adaptive plan changed the answer: {r_warm} != {r_static}"
            )
    finally:
        if prev is None:
            os.environ.pop("PRESTO_TPU_FEEDBACK", None)
        else:
            os.environ["PRESTO_TPU_FEEDBACK"] = prev
    speedup = best_static / best_warm
    return {
        "name": "feedback_replan",
        "rows": n,
        "rows_per_s": round(n / best_warm),
        "ms": round(best_warm * 1e3, 3),
        "speedup_vs_full": round(speedup, 2),
        "note": f"history-driven {best_warm * 1e3:.1f}ms vs static "
                f"{best_static * 1e3:.1f}ms = {speedup:.1f}x",
    }


def run_feedback_lookup_bench(sf: float, runs: int = RUNS) -> Dict:
    """Warm-path cost of the feedback store itself: fingerprint + lookup
    of every recordable frame of a live 3-join plan against a populated
    store — the exact work StatsDeriver adds to each plan when history
    is on. rows/s counts frame lookups; keeps the lookup overhead
    visible so the <=5% budget on the serving fast path stays honest."""
    import os

    from ..plan.history import HISTORY, fingerprint, _walk_plan

    cat, sess, sql, n = _feedback_fixture(sf)
    prev = os.environ.get("PRESTO_TPU_FEEDBACK")
    os.environ["PRESTO_TPU_FEEDBACK"] = "1"
    try:
        HISTORY.reset()
        sess.query(sql)  # populate the store with this plan's frames
        node = sess.plan(sql)
        nodes: list = []
        _walk_plan(node, nodes.append)
        iters = 200
        best = float("inf")
        for _ in range(max(runs, 1)):
            t0 = time.perf_counter()
            for _i in range(iters):
                memo: dict = {}
                for nd in nodes:
                    HISTORY.lookup(fingerprint(nd, memo), cat)
            best = min(best, (time.perf_counter() - t0) / iters)
        hits = HISTORY.stats.snapshot()["hits"]
        if hits == 0:
            raise RuntimeError("lookup loop never hit the store")
    finally:
        if prev is None:
            os.environ.pop("PRESTO_TPU_FEEDBACK", None)
        else:
            os.environ["PRESTO_TPU_FEEDBACK"] = prev
    lookups = len(nodes)
    return {
        "name": "feedback_lookup",
        "rows": lookups,
        "rows_per_s": round(lookups / best),
        "ms": round(best * 1e3, 4),
        "note": f"{lookups} frame lookups at {best / lookups * 1e9:.0f}ns "
                f"each over a {len(nodes)}-node plan",
    }


HOST_BENCHES = {
    "serde_lz4": run_serde_bench,
    "serde_encoded": run_serde_encoded_bench,
    "serde_parallel_stripes": run_serde_stripes_bench,
    "exchange_pull_pipelined": run_exchange_pull_bench,
    "hybrid_join_spill": run_hybrid_join_spill_bench,
    "external_sort_disk": run_external_sort_disk_bench,
    "plan_cache_hit": run_plan_cache_bench,
    "matview_refresh_delta": run_matview_refresh_delta_bench,
    "ingest_append": run_ingest_append_bench,
    "mixed_soak_qps": run_mixed_soak_qps_bench,
    "metrics_scrape": run_metrics_scrape_bench,
    "feedback_replan": run_feedback_replan_bench,
    "feedback_lookup": run_feedback_lookup_bench,
}


def run_exchange_bench(sf: float, runs: int = RUNS) -> Optional[Dict]:
    """Hash-repartition all_to_all over the device mesh (ref:
    BenchmarkPartitionedOutputOperator + ExchangeOperator; the ICI data
    plane). Requires >1 device; returns None (skipped) on a single chip."""
    import jax

    n_dev = len(jax.devices())
    if n_dev < 2:
        return None
    import jax.numpy as jnp
    try:
        from jax import shard_map  # jax >= 0.8 home
    except ImportError:
        from jax.experimental.shard_map import shard_map
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from .. import types as T
    from ..expr.ir import col
    from ..page import Page
    from ..parallel.exchange import exchange_by_hash
    from ..parallel.mesh import default_mesh

    mesh = default_mesh(n_dev)
    axis = mesh.axis_names[0]
    rows_per_shard = max(int(600_000 * sf) // n_dev, 1024)
    rows_per_shard = -(-rows_per_shard // 128) * 128
    total = n_dev * rows_per_shard
    rng = np.random.default_rng(0)
    key = rng.integers(0, 1 << 40, size=(total,), dtype=np.int64)
    payload = np.arange(total, dtype=np.int64)
    sh = NamedSharding(mesh, P(axis))
    key_d = jax.device_put(jnp.asarray(key), sh)
    pay_d = jax.device_put(jnp.asarray(payload), sh)
    # uniform hash: per-destination rows ~ rows_per_shard/n_dev; 2x slack
    part_capacity = -(-2 * rows_per_shard // n_dev // 128) * 128
    key_exprs = (col("k", T.BIGINT),)

    def shard_fn(acc, k, v):
        page = Page.from_blocks(
            [Block_(_chain(k, acc), T.BIGINT), Block_(v, T.BIGINT)],
            ("k", "v"),
            count=k.shape[0],
        )
        out, dropped = exchange_by_hash(
            page, key_exprs, axis, n_dev, part_capacity
        )
        return _consume(out) + dropped.astype(jnp.int64)

    def Block_(data, t):
        from ..page import Block

        return Block(data, t, None)

    try:
        smapped = shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=(P(), P(axis), P(axis)),
            out_specs=P(),
            check_vma=False,  # jax >= 0.8 spelling
        )
    except TypeError:
        smapped = shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=(P(), P(axis), P(axis)),
            out_specs=P(),
            check_rep=False,
        )

    def step(acc, k, v):
        return smapped(acc, k, v)

    b = Bench("exchange_all_to_all", total, step, (key_d, pay_d))
    sec = time_device_bench(b, runs)
    # bytes crossing the interconnect per pass: both int64 columns move
    exchanged = total * (key.itemsize + payload.itemsize)
    return {
        "name": b.name,
        "rows": b.rows,
        "rows_per_s": round(b.rows / sec),
        "ms": round(sec * 1e3, 3),
        "wire_bytes": exchanged,
        "wire_GBps": round(exchanged / sec / 1e9, 2),
        "note": f"{n_dev} devices",
    }


def run_exchange_hier_bench(sf: float, runs: int = RUNS) -> Optional[Dict]:
    """Hierarchical producer regroup (server/hier.hier_partition: ONE
    device step, then ragged wire pages) vs the flat per-partition
    compact loop (server/worker._hash_partition, nparts device dispatches
    per batch) on the same batch and topology. Requires >1 device;
    returns None (skipped) on a single chip."""
    import jax

    n_dev = len(jax.devices())
    if n_dev < 2:
        return None
    from .. import types as T
    from ..expr.ir import col
    from ..page import Page
    from ..server.hier import hier_partition
    from ..server.serde import local_capabilities
    from ..server.worker import _hash_partition

    # fan-out where the flat loop's O(nparts) dispatches dominate — the
    # shape of a real fleet (16 consumers); hier's cost is ~flat in
    # nparts so the ratio grows with fan-out beyond this
    nparts = 16
    rows = max(int(400_000 * sf), 8192)
    rng = np.random.default_rng(0)
    page = Page.from_dict({
        "k": rng.integers(0, 1 << 40, rows).astype(np.int64),
        "v": np.arange(rows, dtype=np.int64),
    })
    caps = local_capabilities()
    key_exprs = (col("k", T.BIGINT),)

    def _best(fn):
        fn()  # warm: compile + caches
        best = float("inf")
        for _ in range(max(runs, 1)):
            t0 = time.perf_counter()
            fn()
            best = min(best, time.perf_counter() - t0)
        return best

    hier_s = _best(
        lambda: hier_partition(page, key_exprs, nparts, caps=caps)
    )
    flat_s = _best(
        lambda: _hash_partition(page, key_exprs, nparts, caps=caps)
    )
    wire = sum(
        len(d)
        for datas in hier_partition(page, key_exprs, nparts,
                                    caps=caps).values()
        for d in datas
    )
    return {
        "name": "exchange_hier",
        "rows": rows,
        "rows_per_s": round(rows / hier_s),
        "ms": round(hier_s * 1e3, 3),
        "flat_ms": round(flat_s * 1e3, 3),
        "speedup_vs_flat": round(flat_s / hier_s, 3),
        "wire_bytes": wire,
        "note": f"{n_dev} devices, {nparts} partitions",
    }


# ---------------------------------------------------------------------------
# suite runner
# ---------------------------------------------------------------------------


def run_suite(
    sf: float = 0.1,
    runs: int = RUNS,
    only: Optional[List[str]] = None,
) -> Dict:
    import jax

    results: List[Dict] = []
    errors: Dict[str, str] = {}
    peak_bps = _peak_hbm_bps()
    for name, ctor in DEVICE_BENCHES.items():
        if only and name not in only:
            continue
        try:
            b = ctor(sf)
            sec = time_device_bench(b, runs)
            r = {
                "name": b.name,
                "rows": b.rows,
                "rows_per_s": round(b.rows / sec),
                "ms": round(sec * 1e3, 3),
            }
            nbytes = _arg_bytes(b.args)
            if nbytes:
                r["read_bytes"] = nbytes
                r["read_GBps"] = round(nbytes / sec / 1e9, 2)
                if peak_bps:
                    r["hbm_read_pct"] = round(100 * nbytes / sec / peak_bps, 1)
            if b.note:
                r["note"] = b.note
            results.append(r)
        except Exception as e:  # noqa: BLE001 - suite entries are independent
            errors[name] = repr(e)[:300]
    for hname, hctor in HOST_BENCHES.items():
        if only and hname not in only:
            continue
        try:
            results.append(hctor(sf, runs))
        except Exception as e:  # noqa: BLE001
            errors[hname] = repr(e)[:300]
    for xname, xctor in (
        ("exchange_all_to_all", run_exchange_bench),
        ("exchange_hier", run_exchange_hier_bench),
    ):
        if only and xname not in only:
            continue
        try:
            r = xctor(sf, runs)
            if r is not None:
                results.append(r)
            else:
                errors[xname] = "skipped: single device"
        except Exception as e:  # noqa: BLE001
            errors[xname] = repr(e)[:300]
    return {
        "suite": "operator_micro",
        "backend": jax.devices()[0].platform,
        "device_kind": getattr(jax.devices()[0], "device_kind", ""),
        "n_devices": len(jax.devices()),
        "peak_hbm_GBps": round(peak_bps / 1e9) if peak_bps else None,
        "sf": sf,
        "results": results,
        "errors": errors,
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--sf", type=float, default=0.1)
    ap.add_argument("--runs", type=int, default=RUNS)
    ap.add_argument("--only", nargs="*", default=None)
    ap.add_argument("--out", default=None, help="write JSON here too")
    ap.add_argument("--cpu", action="store_true", help="force CPU backend")
    ap.add_argument(
        "--virtual-devices",
        type=int,
        default=0,
        help="force an N-device virtual CPU mesh (exchange benches on a "
        "single-chip box; implies --cpu)",
    )
    args = ap.parse_args(argv)
    if args.virtual_devices:
        import os
        import re

        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        flags = re.sub(
            r"--xla_force_host_platform_device_count=\d+", "", flags
        )
        os.environ["XLA_FLAGS"] = (
            flags
            + f" --xla_force_host_platform_device_count={args.virtual_devices}"
        ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    if args.cpu:
        import os

        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
    import presto_tpu  # noqa: F401  (enables x64)

    table = run_suite(args.sf, args.runs, args.only)
    txt = json.dumps(table, indent=2)
    print(txt)
    if args.out:
        with open(args.out, "w") as f:
            f.write(txt)
    return table


if __name__ == "__main__":
    main()
    sys.stdout.flush()
    import os

    os._exit(0)  # skip native teardown (see bench.py)
