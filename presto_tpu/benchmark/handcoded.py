"""Hand-composed TPC-H Q1 / Q6 kernel pipelines.

The analog of the reference's hand-coded operator benchmarks
(presto-benchmark/.../HandTpchQuery1.java, HandTpchQuery6.java): the query is
expressed directly against the kernel library, bypassing the SQL frontend.
These are the flagship single-chip and multi-chip execution paths until the
planner takes over; bench.py and __graft_entry__.py drive them.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from .. import types as T
from ..connectors import tpch
from ..expr import ir
from ..expr.ir import col, comparison, lit
from ..ops.aggregate import AggSpec, grouped_aggregate_direct
from ..ops.filter import filter_page
from ..ops.sort import SortKey, sort_page
from ..page import Block, Page

DEC12_2 = T.DecimalType(12, 2)
DEC4_2 = T.DecimalType(4, 2)

Q1_COLUMNS = (
    "l_quantity",
    "l_extendedprice",
    "l_discount",
    "l_tax",
    "l_returnflag",
    "l_linestatus",
    "l_shipdate",
)

Q6_COLUMNS = ("l_quantity", "l_extendedprice", "l_discount", "l_shipdate")


def _table_page(name: str, sf: float, columns, pad_to: Optional[int] = None) -> Page:
    """Benchmark pages are GENERATED ON DEVICE via benchgen whenever it
    covers the requested columns — the axon tunnel wedges on bulk
    host->device uploads (see benchgen docstring), so the hand-coded
    benchmark paths must never ship table data to the chip. Unsupported
    columns fall back to the host tpch connector (transfer)."""
    from . import benchgen

    if benchgen.supports(name, columns):
        return benchgen.device_page(name, sf, tuple(columns), pad_to=pad_to)
    t = tpch.table(name, sf)
    data = {}
    for c in columns:
        colm = t.columns[c]
        data[c] = Block.from_numpy(colm.data, colm.type, dictionary=colm.dictionary)
    return Page.from_dict(data, pad_to=pad_to)


def lineitem_q1_page(sf: float, pad_to: Optional[int] = None) -> Page:
    return _table_page("lineitem", sf, Q1_COLUMNS, pad_to)


def lineitem_q6_page(sf: float, pad_to: Optional[int] = None) -> Page:
    return _table_page("lineitem", sf, Q6_COLUMNS, pad_to)


# ---------------------------------------------------------------------------
# Q1: pricing summary report
# ---------------------------------------------------------------------------

Q1_PREDICATE = comparison(
    "le", col("l_shipdate", T.DATE), ir.Literal("1998-09-02", T.DATE)
)

Q1_GROUPS = (col("l_returnflag", T.VARCHAR), col("l_linestatus", T.VARCHAR))
Q1_GROUP_NAMES = ("l_returnflag", "l_linestatus")
Q1_DOMAINS = (3, 2)  # returnflag in {A,N,R}, linestatus in {F,O}


def q1_aggs():
    qty = col("l_quantity", DEC12_2)
    price = col("l_extendedprice", DEC12_2)
    disc = col("l_discount", DEC4_2)
    tax = col("l_tax", DEC4_2)
    one_minus_disc = ir.binary("subtract", lit(1), disc)
    disc_price = ir.binary("multiply", price, one_minus_disc)
    one_plus_tax = ir.binary("add", lit(1), tax)
    charge = ir.binary("multiply", disc_price, one_plus_tax)

    def agg(func, inp, name):
        in_t = None if inp is None else inp.type
        return AggSpec(func, inp, name, AggSpec.infer_output_type(func, in_t))

    return (
        agg("sum", qty, "sum_qty"),
        agg("sum", price, "sum_base_price"),
        agg("sum", disc_price, "sum_disc_price"),
        agg("sum", charge, "sum_charge"),
        agg("avg", qty, "avg_qty"),
        agg("avg", price, "avg_price"),
        agg("avg", disc, "avg_disc"),
        agg("count_star", None, "count_order"),
    )


def q1_local(page: Page) -> Page:
    """Single-chip Q1: filter fused as an aggregation mask (no compaction —
    masked reductions run at memory bandwidth; compaction would cost a full
    sort+gather of the table) → direct grouped aggregation → sort.
    Jittable end-to-end (Pages are pytrees)."""
    out = grouped_aggregate_direct(
        page, Q1_GROUPS, Q1_GROUP_NAMES, q1_aggs(), Q1_DOMAINS,
        pre_mask=Q1_PREDICATE,
    )
    return sort_page(
        out,
        (
            SortKey(col("l_returnflag", T.VARCHAR)),
            SortKey(col("l_linestatus", T.VARCHAR)),
        ),
    )


def _q1_prelude(page: Page) -> Page:
    """Module-level (stable identity) so the compiled SPMD step caches."""
    return filter_page(page, Q1_PREDICATE)


def q1_distributed(mesh, page: Page, axis: str = "workers", max_groups: int = 16):
    """Multi-chip Q1: shard lineitem over the mesh (≈ split-parallel leaf
    stage), filter + partial-aggregate locally, all_to_all repartition partial
    rows by group hash (≈ FIXED_HASH exchange), final-aggregate, merge.

    For a SQL MPP engine the parallelism axes are data-parallel splits and
    hash repartition (SURVEY.md §2.6) — this exercises both collectively."""
    from ..parallel.distributed import dist_grouped_aggregate

    out = dist_grouped_aggregate(
        mesh,
        axis,
        page,
        Q1_GROUPS,
        Q1_GROUP_NAMES,
        q1_aggs(),
        max_groups=max_groups,
        part_capacity=max(2 * max_groups, 32),
        prelude=_q1_prelude,
    )
    return sort_page(
        out,
        (
            SortKey(col("l_returnflag", T.VARCHAR)),
            SortKey(col("l_linestatus", T.VARCHAR)),
        ),
    )


# ---------------------------------------------------------------------------
# Q6: forecasting revenue change
# ---------------------------------------------------------------------------

Q6_PREDICATE = ir.and_(
    comparison("ge", col("l_shipdate", T.DATE), ir.Literal("1994-01-01", T.DATE)),
    comparison("lt", col("l_shipdate", T.DATE), ir.Literal("1995-01-01", T.DATE)),
    ir.between(
        col("l_discount", DEC4_2),
        ir.Literal(0.05, DEC4_2),
        ir.Literal(0.07, DEC4_2),
    ),
    comparison("lt", col("l_quantity", DEC12_2), lit(24)),
)


def q6_local(page: Page) -> Page:
    from ..ops.aggregate import global_aggregate

    revenue = ir.binary(
        "multiply", col("l_extendedprice", DEC12_2), col("l_discount", DEC4_2)
    )
    return global_aggregate(
        page,
        (
            AggSpec(
                "sum",
                revenue,
                "revenue",
                AggSpec.infer_output_type("sum", revenue.type),
            ),
        ),
        pre_mask=Q6_PREDICATE,
    )


def q1_local_pallas(page: Page) -> Page:
    """Q1 via the hand-written single-pass Pallas kernel
    (ops/pallas_agg.py) — the custom-kernel analog of the reference's
    hand-coded benchmarks. Produces the same Page as q1_local; group ids
    are emitted in (returnflag, linestatus) order so no final sort is
    needed."""
    import jax
    import jax.numpy as jnp

    from ..expr import datetime_kernels as dtk
    from ..ops import decimal128 as d128
    from ..ops.aggregate import avg_from_sum_count
    from ..ops.filter import compact
    from ..ops.pallas_agg import BLK_ROWS, combine, q1_partial_sums

    def col32(name):
        return page.block(name).data.astype(jnp.int32)

    n = page.capacity
    padded = -(-n // BLK_ROWS) * BLK_ROWS

    def pad(x):
        return jnp.pad(x, (0, padded - n)) if padded != n else x

    cutoff = jnp.int32(dtk.parse_date_literal("1998-09-02"))
    partials = q1_partial_sums(
        pad(col32("l_quantity")),
        pad(col32("l_extendedprice")),
        pad(col32("l_discount")),
        pad(col32("l_tax")),
        pad(col32("l_returnflag")),
        pad(col32("l_linestatus")),
        pad(col32("l_shipdate")),
        page.count.astype(jnp.int32),
        cutoff,
    )
    s = combine(partials)

    rf_b = page.block("l_returnflag")
    ls_b = page.block("l_linestatus")
    cnt = s["count"]
    DEC38_2 = T.DecimalType(38, 2)
    DEC38_4 = T.DecimalType(38, 4)
    DEC38_6 = T.DecimalType(38, 6)
    blocks = [
        Block(jnp.asarray([0, 0, 1, 1, 2, 2], jnp.int32), T.VARCHAR,
              None, rf_b.dict_id),
        Block(jnp.asarray([0, 1, 0, 1, 0, 1], jnp.int32), T.VARCHAR,
              None, ls_b.dict_id),
        Block(d128.from_int64(s["sum_qty"]), DEC38_2, cnt > 0),
        Block(d128.from_int64(s["sum_price"]), DEC38_2, cnt > 0),
        Block(s["sum_disc_price"], DEC38_4, cnt > 0),
        Block(s["sum_charge"], DEC38_6, cnt > 0),
        Block(
            avg_from_sum_count(
                d128.from_int64(s["sum_qty"]), cnt, DEC12_2, DEC12_2
            ),
            DEC12_2, cnt > 0,
        ),
        Block(
            avg_from_sum_count(
                d128.from_int64(s["sum_price"]), cnt, DEC12_2, DEC12_2
            ),
            DEC12_2, cnt > 0,
        ),
        Block(
            avg_from_sum_count(
                d128.from_int64(s["sum_disc"]), cnt, DEC4_2, DEC4_2
            ),
            DEC4_2, cnt > 0,
        ),
        Block(cnt, T.BIGINT, None),
    ]
    names = (
        "l_returnflag", "l_linestatus", "sum_qty", "sum_base_price",
        "sum_disc_price", "sum_charge", "avg_qty", "avg_price",
        "avg_disc", "count_order",
    )
    out = Page.from_blocks(blocks, names, count=6)
    return compact(out, cnt > 0)
