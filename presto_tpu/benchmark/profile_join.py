"""On-chip join-stage decomposition: where do join_probe_n1's ms go?

Times each stage of the FK->PK probe independently with the chained-
dependency protocol (bench.py `_chained_device_time` rationale): probe-key
hashing, candidate-range lookup (bucket directory vs the searchsorted it
replaced), collision scan, payload gather, and the full join_n1 — so a
TPU regression or win is attributable to a stage, not guessed.

    python -m presto_tpu.benchmark.profile_join --sf 0.1 --runs 5

Reference analog: BenchmarkHashBuildAndJoinOperators breaks build/probe
phases apart for the same reason.
"""

from __future__ import annotations

import argparse
import json
import time


def _chained(fn, n_runs=5, reps=3):
    import jax
    import jax.numpy as jnp

    f = jax.jit(fn)
    s = f(jnp.int64(0))
    int(s)  # compile + warm
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        s = jnp.int64(0)
        for _ in range(n_runs):
            s = f(s)
        int(s)
        best = min(best, (time.perf_counter() - t0) / n_runs)
    return best


def main(sf: float = 0.1, runs: int = 5):
    import jax.numpy as jnp

    from .. import types as T
    from ..expr.compiler import evaluate
    from ..expr.ir import col
    from ..ops import join as J
    from ..ops.hashing import hash_rows
    from .handcoded import _table_page
    from .micro import _orders_keys_page

    probe = _table_page("lineitem", sf, ("l_orderkey", "l_extendedprice"))
    bpage = _orders_keys_page(sf)
    kexpr = (col("o_orderkey", T.BIGINT),)
    pkexpr = (col("l_orderkey", T.BIGINT),)
    # this profiler decomposes the SORTED-hash layout's stages; the
    # hash-table default (ops/pallas_join.py) has its own micros
    # (pallas_join_build / pallas_join_probe in benchmark/micro.py)
    bs = J.build_sorted(bpage, kexpr)
    pkeys = [evaluate(e, probe) for e in pkexpr]
    h = hash_rows(pkeys)
    n = int(probe.count)
    out = {"sf": sf, "probe_rows": n, "build_rows": int(bpage.count)}

    def dep(acc):
        # zero-valued dependency folded into the probe hash input
        return [type(v)(v.data + (acc * 0).astype(v.data.dtype), v.valid,
                        v.type, v.dict_id) for v in pkeys]

    def t_hash(acc):
        return jnp.sum(hash_rows(dep(acc)).astype(jnp.int64))

    def t_ranges(acc):
        _, lo, hi = J._probe_ranges(bs, dep(acc), probe.capacity)
        return jnp.sum(lo.astype(jnp.int64)) + jnp.sum(hi.astype(jnp.int64))

    def t_ranges_searchsorted(acc):
        hh = hash_rows(dep(acc))
        lo = jnp.searchsorted(bs.sorted_hash, hh, side="left")
        hi = jnp.searchsorted(bs.sorted_hash, hh, side="right")
        return jnp.sum(lo.astype(jnp.int64)) + jnp.sum(hi.astype(jnp.int64))

    def t_scan(acc):
        ks = dep(acc)
        _, lo, hi = J._probe_ranges(bs, ks, probe.capacity)
        m, br = J._collision_scan(bs, ks, lo, hi)
        return jnp.sum(br.astype(jnp.int64)) + jnp.sum(m.astype(jnp.int64))

    def t_full(acc):
        from ..page import Block, Page

        b0 = probe.blocks[0]
        blocks = (Block(b0.data + (acc * 0).astype(b0.data.dtype), b0.type,
                        b0.valid, b0.dict_id),) + probe.blocks[1:]
        p = Page(blocks, probe.names, probe.count)
        o = J.join_n1(p, bs, pkexpr, ("o_custkey", "o_totalprice"),
                      ("o_custkey", "o_totalprice"))
        acc2 = jnp.int64(0)
        for b in o.blocks:
            acc2 = acc2 + jnp.sum(b.data[0].astype(jnp.int64))
        return acc2

    for name, fn in (
        ("hash_ms", t_hash),
        ("ranges_bucket_ms", t_ranges),
        ("ranges_searchsorted_ms", t_ranges_searchsorted),
        ("scan_ms", t_scan),
        ("join_full_ms", t_full),
    ):
        try:
            out[name] = round(_chained(fn, runs) * 1e3, 3)
        except Exception as e:  # noqa: BLE001 - the error IS the
            # recorded measurement for this row
            out[name] = f"error: {repr(e)[:120]}"
    import jax

    out["backend"] = jax.default_backend()
    print(json.dumps(out))
    return out


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--sf", type=float, default=0.1)
    ap.add_argument("--runs", type=int, default=5)
    a = ap.parse_args()
    main(a.sf, a.runs)
