"""North-star scale runs: SF10 end-to-end SQL, SF100 streaming scans.

Reference protocol: presto-benchto-benchmarks tpch.yaml runs sf300-sf3000
macro suites against Hive; this engine's ramp (BASELINE.md) is SF1 -> SF10
(joins + group-by through the full SQL path under a device budget) ->
SF100 (the q1/q6/q3/q5/q17/q18 north stars over BATCHED scans from a
chunk-generated source that never holds any table in host RAM).

    python -m presto_tpu.benchmark.scale --sf 10
    python -m presto_tpu.benchmark.scale --sf100   # north stars, streamed
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import types as T

Q1 = (
    "select l_returnflag, l_linestatus, sum(l_quantity) as sum_qty, "
    "sum(l_extendedprice) as sum_base_price, "
    "sum(l_extendedprice * (1 - l_discount)) as sum_disc_price, "
    "avg(l_quantity) as avg_qty, avg(l_discount) as avg_disc, "
    "count(*) as count_order "
    "from lineitem where l_shipdate <= date '1998-09-02' "
    "group by l_returnflag, l_linestatus "
    "order by l_returnflag, l_linestatus"
)
Q6 = (
    "select sum(l_extendedprice * l_discount) as revenue from lineitem "
    "where l_shipdate >= date '1994-01-01' "
    "and l_shipdate < date '1995-01-01' "
    "and l_discount between 0.05 and 0.07 and l_quantity < 24"
)
Q3 = (
    "select l_orderkey, sum(l_extendedprice * (1 - l_discount)) as rev, "
    "o_orderdate, o_shippriority "
    "from customer, orders, lineitem "
    "where c_mktsegment = 'BUILDING' and c_custkey = o_custkey "
    "and l_orderkey = o_orderkey and o_orderdate < date '1995-03-15' "
    "and l_shipdate > date '1995-03-15' "
    "group by l_orderkey, o_orderdate, o_shippriority "
    "order by rev desc, o_orderdate limit 10"
)
Q18_SHAPE = (
    "select c_custkey, sum(o_totalprice) tp, count(*) n "
    "from customer, orders "
    "where c_custkey = o_custkey "
    "group by c_custkey order by tp desc limit 100"
)
# the BASELINE.json north stars (round-4 verdict weak#2): Q5 is the
# 6-table join-order stressor; Q17 the large-build correlated-agg /
# spill-path stressor; Q18 the big-group HAVING semi-join. Spec texts
# adapted only where the chunked generator lacks a column (Q18: c_name)
Q5 = (
    "select n_name, sum(l_extendedprice * (1 - l_discount)) as revenue "
    "from customer, orders, lineitem, supplier, nation, region "
    "where c_custkey = o_custkey and l_orderkey = o_orderkey "
    "and l_suppkey = s_suppkey and c_nationkey = s_nationkey "
    "and s_nationkey = n_nationkey and n_regionkey = r_regionkey "
    "and r_name = 'ASIA' and o_orderdate >= date '1994-01-01' "
    "and o_orderdate < date '1995-01-01' "
    "group by n_name order by revenue desc"
)
Q17 = (
    "select sum(l_extendedprice) / 7.0 as avg_yearly "
    "from lineitem, part "
    "where p_partkey = l_partkey and p_brand = 'Brand#23' "
    "and p_container = 'MED BOX' "
    "and l_quantity < ("
    "select 0.2 * avg(l_quantity) from lineitem "
    "where l_partkey = p_partkey)"
)
Q18 = (
    "select c_custkey, o_orderkey, o_orderdate, o_totalprice, "
    "sum(l_quantity) "
    "from customer, orders, lineitem "
    "where o_orderkey in ("
    "select l_orderkey from lineitem group by l_orderkey "
    "having sum(l_quantity) > 300) "
    "and c_custkey = o_custkey and o_orderkey = l_orderkey "
    "group by c_custkey, o_orderkey, o_orderdate, o_totalprice "
    "order by o_totalprice desc, o_orderdate limit 100"
)

QUERIES = {
    "q1": Q1, "q6": Q6, "q3": Q3, "q18_shape": Q18_SHAPE,
    "q5": Q5, "q17": Q17, "q18": Q18,
}


_SEGMENTS = ("AUTOMOBILE", "BUILDING", "FURNITURE", "HOUSEHOLD", "MACHINERY")
_STARTDATE, _ENDDATE = 8035, 10591  # 1992-01-01 .. 1998-12-31 (days)


class ChunkedTpchCatalog:
    """Seven-table TPC-H catalog generating rows ON DEMAND in chunked
    batches — the SF100 scan source. Every column is a pure function of
    the row index (benchgen's splitmix64 counter streams; customer/
    supplier/part delegate to benchgen's generators outright), so
    lineitem and orders agree on per-order attributes WITHOUT shared
    state, host RAM holds at most ~2 chunks, and re-scans are
    deterministic (reference: the connector split contract — splits are
    independently regeneratable). lineitem/orders/customer stream the Q3
    join; part/supplier/nation/region complete the Q5/Q17/Q18 north-star
    shapes."""

    name = "tpch_chunked"
    CHUNK_ORDERS = 1 << 21  # ~2M orders -> ~8.4M lineitem rows per chunk

    _LI_SCHEMA = {
        "l_orderkey": T.BIGINT,
        "l_partkey": T.BIGINT,
        "l_suppkey": T.BIGINT,
        "l_quantity": T.DecimalType(12, 2),
        "l_extendedprice": T.DecimalType(12, 2),
        "l_discount": T.DecimalType(12, 2),
        "l_tax": T.DecimalType(12, 2),
        "l_returnflag": T.VARCHAR,
        "l_linestatus": T.VARCHAR,
        "l_shipdate": T.DATE,
    }
    _ORD_SCHEMA = {
        "o_orderkey": T.BIGINT,
        "o_custkey": T.BIGINT,
        "o_totalprice": T.DecimalType(12, 2),
        "o_orderdate": T.DATE,
        "o_shippriority": T.BIGINT,
    }
    _CUST_SCHEMA = {
        "c_custkey": T.BIGINT,
        "c_nationkey": T.BIGINT,
        "c_mktsegment": T.VARCHAR,
        "c_acctbal": T.DecimalType(12, 2),
    }
    _PART_SCHEMA = {
        "p_partkey": T.BIGINT,
        "p_brand": T.VARCHAR,
        "p_container": T.VARCHAR,
    }
    _SUPP_SCHEMA = {
        "s_suppkey": T.BIGINT,
        "s_nationkey": T.BIGINT,
    }
    _NATION_SCHEMA = {
        "n_nationkey": T.BIGINT,
        "n_name": T.VARCHAR,
        "n_regionkey": T.BIGINT,
    }
    _REGION_SCHEMA = {
        "r_regionkey": T.BIGINT,
        "r_name": T.VARCHAR,
    }
    # one source of truth for the decode pools: the benchgen twins use
    # the same splitmix64 streams, so the dictionaries must never drift
    from .benchgen import _BRAND_POOL as _BRANDS
    from .benchgen import _CONTAINER_POOL as _CONTAINERS

    from ..connectors.tpch import REGIONS as _REGION_LIST

    _REGION_NAMES = tuple(sorted(_REGION_LIST))
    _DICTS = {
        "l_returnflag": ("A", "N", "R"),
        "l_linestatus": ("F", "O"),
        "c_mktsegment": _SEGMENTS,
        "p_brand": _BRANDS,
        "p_container": _CONTAINERS,
        "r_name": _REGION_NAMES,
    }

    def __init__(self, sf: float):
        self.sf = sf
        self.n_orders = int(1_500_000 * sf)
        from . import benchgen

        sizes = benchgen._sizes(sf)
        # dimension sizes come from benchgen so the delegated generators
        # and the foreign-key bounds (streams 11/3/12) can never disagree
        self.n_cust = sizes["customer"]
        self.n_part = sizes["part"]
        self.n_supp = sizes["supplier"]
        # nation dictionary sorted by name; region of each sorted nation
        from ..connectors.tpch import NATIONS

        names = sorted(n for n, _r in NATIONS)
        region_of = dict(NATIONS)
        self._nation_names = tuple(names)
        self._nation_regions = np.array(
            [region_of[n] for n in names], np.int64
        )
        self._dicts = dict(self._DICTS)
        self._dicts["n_name"] = self._nation_names
        n_chunks = -(-self.n_orders // self.CHUNK_ORDERS)
        # deterministic per-order line counts -> exact chunk row offsets
        # (one cheap vectorized pass; 150M orders ~ seconds)
        counts = np.empty(max(n_chunks, 1), np.int64)
        counts[:] = 0
        for c in range(n_chunks):
            o0, o1 = self._order_range(c)
            counts[c] = self._lines_for(np.arange(o0, o1)).sum()
        self._offsets = np.concatenate([[0], np.cumsum(counts)])
        self._cache: Dict[int, dict] = {}  # tiny LRU of generated chunks

    # -- metadata (planner Catalog protocol) --

    def table_names(self) -> List[str]:
        return ["lineitem", "orders", "customer", "part", "supplier",
                "nation", "region"]

    def _schema_for(self, table: str):
        return {
            "lineitem": self._LI_SCHEMA,
            "orders": self._ORD_SCHEMA,
            "customer": self._CUST_SCHEMA,
            "part": self._PART_SCHEMA,
            "supplier": self._SUPP_SCHEMA,
            "nation": self._NATION_SCHEMA,
            "region": self._REGION_SCHEMA,
        }[table]

    def schema(self, table: str):
        return dict(self._schema_for(table))

    def row_count(self, table: str) -> int:
        return {
            "lineitem": int(self._offsets[-1]),
            "orders": self.n_orders,
            "customer": self.n_cust,
            "part": self.n_part,
            "supplier": self.n_supp,
            "nation": 25,
            "region": 5,
        }[table]

    def exact_row_count(self, table: str) -> int:
        return self.row_count(table)

    def unique_columns(self, table: str):
        return {
            "orders": [("o_orderkey",)],
            "customer": [("c_custkey",)],
            "part": [("p_partkey",)],
            "supplier": [("s_suppkey",)],
            "nation": [("n_nationkey",)],
            "region": [("r_regionkey",)],
        }.get(table, [])

    # -- stateless per-index column functions --

    def _order_range(self, chunk: int) -> Tuple[int, int]:
        o0 = chunk * self.CHUNK_ORDERS
        return o0, min(o0 + self.CHUNK_ORDERS, self.n_orders)

    @staticmethod
    def _lines_for(order_idx: np.ndarray) -> np.ndarray:
        """1..7 lineitems per order, stateless in the order index."""
        h = (order_idx.astype(np.uint64) * np.uint64(2654435761)) >> np.uint64(7)
        return (h % np.uint64(7)).astype(np.int64) + 1

    @staticmethod
    def _u(stream: int, i: np.ndarray, lo: int, hi: int) -> np.ndarray:
        from .benchgen import _uni

        return _uni(np, stream, i.astype(np.uint64), lo, hi)

    def _orderdate(self, order_idx: np.ndarray) -> np.ndarray:
        return self._u(7, order_idx, _STARTDATE, _ENDDATE - 151 + 1)

    def _custkey(self, order_idx: np.ndarray) -> np.ndarray:
        return self._u(11, order_idx, 1, self.n_cust + 1)

    def _li_chunk(self, c: int) -> dict:
        got = self._cache.get(c)
        if got is not None:
            return got
        o0, o1 = self._order_range(c)
        order_idx = np.arange(o0, o1)
        lines = self._lines_for(order_idx)
        li = int(self._offsets[c]) + np.arange(int(lines.sum()))
        l_orderdate = np.repeat(self._orderdate(order_idx), lines)
        qty = self._u(4, li, 1, 51)
        cols = {
            "l_orderkey": np.repeat(order_idx + 1, lines),
            "l_partkey": self._u(3, li, 1, self.n_part + 1),
            "l_suppkey": self._u(12, li, 1, self.n_supp + 1),
            "l_quantity": qty * 100,
            "l_extendedprice": (90_000 + (qty * 100_000) % 110_001) * qty // 100,
            "l_discount": self._u(5, li, 0, 11),
            "l_tax": self._u(6, li, 0, 9),
            "l_returnflag": self._u(10, li, 0, 3).astype(np.int32),
            "l_linestatus": self._u(13, li, 0, 2).astype(np.int32),
            "l_shipdate": (l_orderdate + self._u(8, li, 1, 122)).astype(
                np.int32
            ),
        }
        self._cache[c] = cols
        if len(self._cache) > 2:  # keep host RAM bounded
            self._cache.pop(next(iter(self._cache)))
        return cols

    def _range_cols(self, table: str, start: int, stop: int) -> dict:
        """orders/customer columns for a row range, generated directly."""
        i = np.arange(start, stop)
        if table == "orders":
            return {
                "o_orderkey": i + 1,
                "o_custkey": self._custkey(i),
                "o_totalprice": self._u(15, i, 100, 60_000_000),
                "o_orderdate": self._orderdate(i).astype(np.int32),
                "o_shippriority": np.zeros(len(i), np.int64),
            }
        if table in ("customer", "part", "supplier"):
            # single source of truth: benchgen's generators produce these
            # columns (same streams, same pools) for any index range
            from . import benchgen

            cols = benchgen._GENERATORS[table](
                np, self.sf, tuple(self._schema_for(table)),
                idx=i.astype(np.uint64),
            )
            out = {}
            for nm, arr in cols.items():
                pool = benchgen.SCHEMAS[table][nm][1]
                out[nm] = arr.astype(np.int32) if pool is not None else arr
            return out
        if table == "nation":
            return {
                "n_nationkey": i,
                "n_name": i.astype(np.int32),
                "n_regionkey": self._nation_regions[i],
            }
        return {
            "r_regionkey": i,
            "r_name": i.astype(np.int32),
        }

    def page(self, table: str):
        n = self.row_count(table)
        if n > 4_000_000:
            raise MemoryError(
                "chunked catalog never materializes a large table; "
                "use scan(start, stop)"
            )
        # small dimensions (nation/region; part/supplier at low SF) may
        # materialize — the streaming driver short-circuits them
        return self.scan(table, 0, n)

    def scan(self, table: str, start: int, stop: int, pad_to=None,
             columns=None, predicate=None):
        from ..page import Block, Page, _pad_block

        schema = self._schema_for(table)
        stop = min(stop, self.row_count(table))
        count = max(stop - start, 0)
        names = list(columns) if columns is not None else list(schema)
        if table == "lineitem":
            c0 = int(np.searchsorted(self._offsets, start, "right")) - 1
            c1 = int(
                np.searchsorted(self._offsets, max(stop - 1, start), "right")
            ) - 1
            pieces = {nm: [] for nm in names}
            for c in range(max(c0, 0), max(c1, c0) + 1):
                cols = self._li_chunk(c)
                lo = max(start - int(self._offsets[c]), 0)
                hi = min(stop - int(self._offsets[c]),
                         int(self._offsets[c + 1] - self._offsets[c]))
                for nm in names:
                    pieces[nm].append(cols[nm][lo:hi])
            data_by_name = {
                nm: (
                    np.concatenate(pieces[nm])
                    if pieces[nm]
                    else np.empty(0, np.int64)
                )
                for nm in names
            }
        else:
            cols = self._range_cols(table, start, max(stop, start))
            data_by_name = {nm: cols[nm] for nm in names}
        blocks = []
        for nm in names:
            blk = Block.from_numpy(
                data_by_name[nm], schema[nm],
                dictionary=self._dicts.get(nm),
            )
            if pad_to is not None and pad_to > count:
                blk = _pad_block(blk, pad_to)
            blocks.append(blk)
        return Page.from_blocks(blocks, names, count=count)


# back-compat alias (pre-round-4 name, lineitem-only then)
ChunkedLineitemCatalog = ChunkedTpchCatalog


def run_scale(
    sf: float,
    queries=("q1", "q6", "q3", "q18_shape"),
    memory_budget: int = 512 << 20,
    batch_rows: int = 1 << 20,
) -> dict:
    """SF-N through the full SQL path under the streaming driver."""
    from ..connectors.tpch import TpchCatalog
    from ..session import Session

    cat = TpchCatalog(sf=sf)
    sess = Session(
        cat, streaming=True, batch_rows=batch_rows,
        memory_budget=memory_budget,
        result_cache=False,  # timing execution, not cache serving
    )
    n_li = cat.exact_row_count("lineitem")
    out = {"sf": sf, "memory_budget": memory_budget, "queries": {}}
    for name in queries:
        sql = QUERIES[name]
        t0 = time.perf_counter()
        rows = sess.query(sql).rows()
        warm = time.perf_counter() - t0
        t0 = time.perf_counter()
        rows = sess.query(sql).rows()
        hot = time.perf_counter() - t0
        digest = hash(tuple(map(str, rows[:100]))) & 0xFFFFFFFF
        out["queries"][name] = {
            "warm_s": round(warm, 2),
            "hot_s": round(hot, 2),
            "rows_per_s": round(n_li / hot) if name in ("q1", "q6") else None,
            "result_rows": len(rows),
            "digest": digest,
            "spill": list(sess.executor.spill_events),
        }
        sess.executor.spill_events.clear()
    return out


def run_sf100(
    sf: float = 100.0,
    queries=("q6", "q1", "q3", "q5", "q17", "q18"),
    memory_budget: int = 512 << 20,
    batch_rows: int = 1 << 22,
) -> dict:
    """The BASELINE north stars at SF100 over batched chunk-generated
    scans: the tables never exist anywhere in full — each batch is
    generated, scanned, and reduced. Q3/Q5 stream lineitem against
    spill-bounded build sides; Q17 exercises the correlated-agg large
    build; Q18 the HAVING semi-join."""
    from ..session import Session

    cat = ChunkedTpchCatalog(sf)
    sess = Session(
        cat, streaming=True, batch_rows=batch_rows,
        memory_budget=memory_budget,
        result_cache=False,  # timing execution, not cache serving
    )
    n = cat.row_count("lineitem")
    out = {"sf": sf, "rows": n, "memory_budget": memory_budget, "queries": {}}
    for name in queries:
        sql = QUERIES[name]
        t0 = time.perf_counter()
        rows = sess.query(sql).rows()
        wall = time.perf_counter() - t0
        out["queries"][name] = {
            "wall_s": round(wall, 1),
            "rows_per_s": round(n / wall),
            "result": [tuple(map(str, r)) for r in rows[:4]],
            "peak_device_bytes": sess.executor.pool.peak,
        }
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--sf", type=float, default=10.0)
    ap.add_argument("--sf100", action="store_true",
                    help="chunk-scan north stars (q1/q6/q3/q5/q17/q18) "
                         "instead of the full SQL suite")
    ap.add_argument("--queries", nargs="*", default=None)
    ap.add_argument("--budget", type=int, default=512 << 20)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args(argv)
    if args.cpu:
        import os

        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
    import presto_tpu  # noqa: F401

    if args.sf100:
        res = run_sf100(
            args.sf if args.sf != 10.0 else 100.0,
            queries=tuple(
                args.queries or ("q6", "q1", "q3", "q5", "q17", "q18")
            ),
            memory_budget=args.budget,
        )
    else:
        res = run_scale(
            args.sf,
            queries=tuple(args.queries or ("q1", "q6", "q3", "q18_shape")),
            memory_budget=args.budget,
        )
    print(json.dumps(res, indent=2))
    return res


if __name__ == "__main__":
    main()
    sys.stdout.flush()
    import os

    os._exit(0)
