"""Hand-composed benchmark pipelines (reference: presto-benchmark module,
presto-benchmark/src/main/java/com/facebook/presto/benchmark/BenchmarkSuite.java:32
— HandTpchQuery1/HandTpchQuery6 and operator micro-benchmarks)."""

from .handcoded import (  # noqa: F401
    lineitem_q1_page,
    lineitem_q6_page,
    q1_aggs,
    q1_local,
    q1_distributed,
    q6_local,
)
