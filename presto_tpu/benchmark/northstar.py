"""North-star queries (BASELINE.json: TPC-H Q3/Q5/Q17/Q18) on the
DEVICE-RESIDENT catalog.

The chunked SF100 runner (`benchmark/scale.py`) generates on HOST and is
therefore unusable through the axon tunnel (bulk host->device transfers
wedge the relay — TPU_STATUS.md §1). This runner instead drives the same
north-star shapes through `DeviceTpchCatalog`: every scan batch is
generated ON DEVICE from splitmix64 counter streams, so tunnel traffic
is scalars only and the run is safe at any SF that fits HBM.

Reference protocol: presto-benchto-benchmarks tpch.yaml (runs + prewarm
per query); targets from BASELINE.json north_star (Q3/Q5/Q17/Q18
wall-clock, rows/sec/chip tracked per query).

    python -m presto_tpu.benchmark.northstar --sf 1 --runs 3

Prints ONE JSON line: per-query wall ms (best + mean), lineitem rows/s,
backend/device, and the SF.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from .scale import Q3, Q5, Q17, Q18

QUERIES = {"q3": Q3, "q5": Q5, "q17": Q17, "q18": Q18}


def run(sf: float, runs: int = 3, prewarm: int = 1, queries=None):
    import jax

    from ..connectors.tpch_device import DeviceTpchCatalog
    from ..session import Session

    dev = jax.devices()[0]
    cat = DeviceTpchCatalog(sf=sf)
    # result_cache off: this driver times EXECUTION — serving repeats
    # from the result cache would time a dictionary lookup instead (the
    # serving fast path has its own driver, northstar_qps)
    sess = Session(cat, result_cache=False)
    li_rows = cat.exact_row_count("lineitem")
    out = {
        "suite": "northstar_device_sql",
        "backend": dev.platform,
        "device_kind": getattr(dev, "device_kind", dev.platform),
        "sf": sf,
        "lineitem_rows": li_rows,
        "runs": runs,
        "results": [],
    }
    from ..exec import spillspace
    from ..server.serde import GLOBAL_WIRE_STATS

    for name in queries or QUERIES:
        sql = QUERIES[name]
        try:
            for _ in range(prewarm):
                rows = sess.query(sql).rows()  # compile + caches
            samples = []
            wire0 = GLOBAL_WIRE_STATS.snapshot()
            spilled0 = spillspace.total_written()
            rev0 = getattr(
                getattr(sess.executor, "pool", None), "revocations", 0
            )
            for _ in range(runs):
                t0 = time.perf_counter()
                rows = sess.query(sql).rows()
                samples.append((time.perf_counter() - t0) * 1e3)
            # per-query wire traffic (serde.GLOBAL_WIRE_STATS delta):
            # zero on the single-process ICI path, the real exchange
            # bytes + compression ratio when the query crossed workers
            wire1 = GLOBAL_WIRE_STATS.snapshot()
            wire_bytes = (wire1["wire_bytes"] - wire0["wire_bytes"]) // runs
            raw_b = (wire1["raw_bytes"] - wire0["raw_bytes"]) // runs
            best = min(samples)
            # dynamic-filter pruning observability (exec/dynfilter.py):
            # rows the runtime filters dropped before probe kernels, per
            # query, alongside wall-clock
            dyn = getattr(sess.executor, "dyn_ctx", None)
            snap = dyn.snapshot() if dyn is not None else {}
            out["results"].append(
                {
                    "name": name,
                    "ms": round(best, 1),
                    "mean_ms": round(sum(samples) / len(samples), 1),
                    "lineitem_rows_per_s": round(li_rows / (best / 1e3)),
                    "out_rows": len(rows),
                    "rows_pruned": (
                        sum(snap.get("scan_pruned", {}).values())
                        + sum(snap.get("preprobe_pruned", {}).values())
                    ),
                    "dyn_filters": snap.get("filters") or {},
                    "wire_bytes": wire_bytes,
                    "wire_ratio": (
                        round(raw_b / wire_bytes, 2) if wire_bytes else None
                    ),
                    # degradation-path observability (exec/spillspace.py +
                    # exec/memory.py): disk bytes the query spilled and
                    # revocation cycles it absorbed — a regression here
                    # (suddenly spilling, or revoking every run) is a perf
                    # bug even when wall-clock still looks fine
                    "spilled_bytes": (
                        (spillspace.total_written() - spilled0) // runs
                    ),
                    "revocations": getattr(
                        getattr(sess.executor, "pool", None),
                        "revocations", 0,
                    ) - rev0,
                }
            )
        except Exception as e:  # noqa: BLE001 — record, keep going
            out["results"].append({"name": name, "error": repr(e)[:300]})
        print(f"# {name}: {out['results'][-1]}", file=sys.stderr, flush=True)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--sf", type=float, default=1.0)
    ap.add_argument("--runs", type=int, default=3)
    ap.add_argument("--queries", type=str, default="")
    args = ap.parse_args()
    qs = [q for q in args.queries.split(",") if q] or None
    print(json.dumps(run(args.sf, runs=args.runs, queries=qs)), flush=True)


if __name__ == "__main__":
    main()
