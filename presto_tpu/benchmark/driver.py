"""Benchmark driver: run SQL suites against a live coordinator and report
wall-clock percentiles.

Re-designed equivalent of presto-benchmark-driver
(presto-benchmark-driver/.../BenchmarkDriver.java + suite.json: named
suites of queries, N runs each, wall/CPU percentiles per query against a
running cluster over the client protocol).

Suite file (JSON):
    {"runs": 5, "warmup": 1,
     "queries": {"q1": "select ...", "counts": "select count(*) ..."}}

CLI:  python -m presto_tpu.benchmark.driver --server URI suite.json
"""

from __future__ import annotations

import dataclasses
import json
import time
from typing import Dict, List


@dataclasses.dataclass
class QueryBench:
    name: str
    runs_ms: List[float]
    rows: int
    error: str = ""

    def percentile(self, p: float) -> float:
        if not self.runs_ms:
            return float("nan")
        s = sorted(self.runs_ms)
        k = min(int(round(p / 100 * (len(s) - 1))), len(s) - 1)
        return s[k]


def run_suite(target, queries: Dict[str, str], runs: int = 3,
              warmup: int = 1) -> List[QueryBench]:
    """`target` has .execute(sql) -> rows (verifier.RestTarget/SessionTarget)."""
    out = []
    for name, sql in queries.items():
        times: List[float] = []
        rows = 0
        error = ""
        try:
            for _ in range(warmup):
                target.execute(sql)
            for _ in range(runs):
                t0 = time.perf_counter()
                result = target.execute(sql)
                times.append((time.perf_counter() - t0) * 1e3)
                rows = len(result)
        except Exception as e:  # noqa: BLE001 - reported per query
            error = f"{type(e).__name__}: {e}"
        out.append(QueryBench(name, times, rows, error))
    return out


def render(benches: List[QueryBench]) -> str:
    lines = [
        f"{'query':20s} {'runs':>4s} {'rows':>8s} {'p50ms':>9s} "
        f"{'p90ms':>9s} {'max':>9s}"
    ]
    for b in benches:
        if b.error:
            lines.append(f"{b.name:20s} FAILED  {b.error[:60]}")
            continue
        worst = max(b.runs_ms) if b.runs_ms else float("nan")
        lines.append(
            f"{b.name:20s} {len(b.runs_ms):>4d} {b.rows:>8d} "
            f"{b.percentile(50):>9.1f} {b.percentile(90):>9.1f} "
            f"{worst:>9.1f}"
        )
    return "\n".join(lines)


def main(argv=None) -> int:
    import argparse

    from ..verifier import RestTarget

    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--server", required=True, help="coordinator URI")
    p.add_argument("suite", help="JSON suite file")
    args = p.parse_args(argv)
    spec = json.load(open(args.suite))
    benches = run_suite(
        RestTarget(args.server),
        spec["queries"],
        runs=int(spec.get("runs", 3)),
        warmup=int(spec.get("warmup", 1)),
    )
    print(render(benches))
    return 1 if any(b.error for b in benches) else 0


if __name__ == "__main__":
    raise SystemExit(main())
