"""Deterministic dual host/device TPC-H benchmark data generator.

Round-4 field finding: the axon TPU tunnel wedges on bulk host->device
transfers (an SF1 lineitem upload of ~340 MB hung the tunnel hard enough
that even `jax.devices()` stopped responding for every later process).
The benchmark therefore never ships data to the chip at all: every column
is a pure function of the row index through a splitmix64 counter RNG, so
the DEVICE PATH generates its input on-device under `jit` (transfers:
a few scalars), and the CPU BASELINE generates bit-identical columns with
the numpy twin of the same code. This mirrors how the reference's
benchmark connector generates synthetic pages worker-side from splits
instead of shipping them (presto-tpch/src/main/java/com/facebook/presto/
tpch/TpchPageSourceProvider ... via io.airlift.tpch; BenchmarkQueryRunner
.java:55) — generation-at-the-operator is the MPP-native (and here
TPU-native) way to feed a benchmark.

Distributions follow connectors/tpch.py (TPC-H spec shapes: §4.2.3
pricing formulas, date windows, returnflag/linestatus rules) with one
simplification for static shapes under jit: every order has exactly 4
lineitems (the spec's 1..7 uniform has mean 4), so order rollups are a
reshape instead of a ragged segment-sum. The SQL-path oracle tests keep
using connectors/tpch.py — this module feeds benchmarks only.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from .. import types as T
from ..page import Block, Page

STARTDATE = 8035  # 1992-01-01
CURRENTDATE = 9298  # 1995-06-17
ENDDATE = 10591  # 1998-12-31

DEC12_2 = T.DecimalType(12, 2)
DEC4_2 = T.DecimalType(4, 2)

_RF_POOL = ("A", "N", "R")
_LS_POOL = ("F", "O")
_SEG_POOL = ("AUTOMOBILE", "BUILDING", "FURNITURE", "HOUSEHOLD", "MACHINERY")
_BRAND_POOL = tuple(
    f"Brand#{m}{x}" for m in range(1, 6) for x in range(1, 6)
)  # already sorted lexically
_CONTAINER_POOL = tuple(
    sorted(
        f"{a} {b}"
        for a in ("JUMBO", "LG", "MED", "SM", "WRAP")
        for b in ("BAG", "BOX", "CAN", "CASE", "DRUM", "JAR", "PACK", "PKG")
    )
)

LINES_PER_ORDER = 4

# name -> (Type, dictionary pool | None); the static schema so callers can
# test coverage without running a generator
SCHEMAS: Dict[str, Dict[str, Tuple[T.Type, Optional[tuple]]]] = {
    "lineitem": {
        "l_orderkey": (T.BIGINT, None),
        "l_partkey": (T.BIGINT, None),
        "l_suppkey": (T.BIGINT, None),
        "l_linenumber": (T.BIGINT, None),
        "l_quantity": (DEC12_2, None),
        "l_extendedprice": (DEC12_2, None),
        "l_discount": (DEC4_2, None),
        "l_tax": (DEC4_2, None),
        "l_returnflag": (T.VARCHAR, _RF_POOL),
        "l_linestatus": (T.VARCHAR, _LS_POOL),
        "l_shipdate": (T.DATE, None),
        "l_receiptdate": (T.DATE, None),
    },
    "orders": {
        "o_orderkey": (T.BIGINT, None),
        "o_custkey": (T.BIGINT, None),
        "o_totalprice": (DEC12_2, None),
        "o_orderdate": (T.DATE, None),
        "o_shippriority": (T.BIGINT, None),
    },
    "customer": {
        "c_custkey": (T.BIGINT, None),
        "c_nationkey": (T.BIGINT, None),
        "c_acctbal": (DEC12_2, None),
        "c_mktsegment": (T.VARCHAR, _SEG_POOL),
    },
    "supplier": {
        "s_suppkey": (T.BIGINT, None),
        "s_nationkey": (T.BIGINT, None),
        "s_acctbal": (DEC12_2, None),
    },
    "part": {
        "p_partkey": (T.BIGINT, None),
        "p_size": (T.BIGINT, None),
        "p_retailprice": (DEC12_2, None),
        "p_brand": (T.VARCHAR, _BRAND_POOL),
        "p_container": (T.VARCHAR, _CONTAINER_POOL),
    },
}


def _u64(xp, stream: int, i):
    """splitmix64 finalizer over (stream, row-index) counters — identical
    bit stream from the numpy and jax.numpy implementations."""
    base = (stream * 0xA0761D6478BD642F) & 0xFFFFFFFFFFFFFFFF  # python-int wrap
    z = (i + xp.uint64(base)) * xp.uint64(0x9E3779B97F4A7C15)
    z = (z ^ (z >> xp.uint64(30))) * xp.uint64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> xp.uint64(27))) * xp.uint64(0x94D049BB133111EB)
    return z ^ (z >> xp.uint64(31))


def _uni(xp, stream: int, i, lo: int, hi: int):
    """Uniform int64 in [lo, hi) (modulo bias is irrelevant here and, more
    to the point, identical across the twins)."""
    return (_u64(xp, stream, i) % xp.uint64(hi - lo)).astype(xp.int64) + lo


def _retail_price_cents(xp, partkey):
    # p_retailprice = 90000 + ((pk/10) mod 20001) + 100*(pk mod 1000)  (§4.2.3)
    pk = partkey
    return 90000 + (pk // 10) % 20001 + 100 * (pk % 1000)


def _sizes(sf: float) -> Dict[str, int]:
    n_orders = max(int(1_500_000 * sf), 8)
    return {
        "orders": n_orders,
        "lineitem": n_orders * LINES_PER_ORDER,
        "customer": max(int(150_000 * sf), 4),
        "part": max(int(200_000 * sf), 4),
        "supplier": max(int(10_000 * sf), 2),
    }


class _Memo:
    """Compute shared intermediates once per generation call."""

    def __init__(self):
        self.vals = {}

    def get(self, key, fn):
        if key not in self.vals:
            self.vals[key] = fn()
        return self.vals[key]


def _gen_lineitem(xp, sf: float, columns: Sequence[str], idx=None):
    s = _sizes(sf)
    n = s["lineitem"]
    m = _Memo()
    i = lambda: m.get(
        "i", lambda: xp.arange(n, dtype=xp.uint64) if idx is None else idx
    )
    order = lambda: m.get("order", lambda: i() // xp.uint64(LINES_PER_ORDER))
    partkey = lambda: m.get("pk", lambda: _uni(xp, 3, i(), 1, s["part"] + 1))
    qty = lambda: m.get("qty", lambda: _uni(xp, 4, i(), 1, 51))
    orderdate = lambda: m.get(
        "od", lambda: _uni(xp, 7, order(), STARTDATE, ENDDATE - 151 + 1)
    )
    shipdate = lambda: m.get(
        "ship", lambda: orderdate() + _uni(xp, 8, i(), 1, 122)
    )
    receiptdate = lambda: m.get(
        "rcpt", lambda: shipdate() + _uni(xp, 9, i(), 1, 31)
    )
    fns = {
        "l_orderkey": lambda: order().astype(xp.int64) + 1,
        "l_partkey": partkey,
        "l_suppkey": lambda: _uni(xp, 12, i(), 1, s["supplier"] + 1),
        "l_linenumber": lambda: (i() % xp.uint64(LINES_PER_ORDER)).astype(xp.int64)
        + 1,
        "l_quantity": lambda: qty() * 100,
        "l_extendedprice": lambda: qty() * _retail_price_cents(xp, partkey()),
        "l_discount": lambda: _uni(xp, 5, i(), 0, 11),
        "l_tax": lambda: _uni(xp, 6, i(), 0, 9),
        "l_returnflag": lambda: xp.where(
            receiptdate() <= CURRENTDATE,
            xp.where(_u64(xp, 10, i()) % xp.uint64(2) == 0, 0, 2),
            1,
        ).astype(xp.int32),
        "l_linestatus": lambda: (shipdate() > CURRENTDATE).astype(xp.int32),
        "l_shipdate": lambda: shipdate().astype(xp.int32),
        "l_receiptdate": lambda: receiptdate().astype(xp.int32),
    }
    return {c: fns[c]() for c in columns}


def _gen_orders(xp, sf: float, columns: Sequence[str], idx=None):
    s = _sizes(sf)
    n = s["orders"]
    m = _Memo()
    o = lambda: m.get(
        "o", lambda: xp.arange(n, dtype=xp.uint64) if idx is None else idx
    )

    def totalprice():
        # per-order sum of gross over its 4 lines, using the same streams
        # the lineitem twin uses, so the rollup is consistent
        li = (
            o()[:, None] * xp.uint64(LINES_PER_ORDER)
            + xp.arange(LINES_PER_ORDER, dtype=xp.uint64)[None, :]
        ).reshape(-1)
        pk = _uni(xp, 3, li, 1, s["part"] + 1)
        qty = _uni(xp, 4, li, 1, 51)
        price = qty * _retail_price_cents(xp, pk)
        disc = _uni(xp, 5, li, 0, 11)
        tax = _uni(xp, 6, li, 0, 9)
        net = price * (100 - disc) // 100
        gross = net * (100 + tax) // 100
        return gross.reshape(-1, LINES_PER_ORDER).sum(axis=1)

    fns = {
        "o_orderkey": lambda: o().astype(xp.int64) + 1,
        "o_custkey": lambda: _uni(xp, 11, o(), 1, s["customer"] + 1),
        "o_totalprice": totalprice,
        "o_orderdate": lambda: _uni(
            xp, 7, o(), STARTDATE, ENDDATE - 151 + 1
        ).astype(xp.int32),
        "o_shippriority": lambda: xp.zeros(o().shape, xp.int64),
    }
    return {c: fns[c]() for c in columns}


def _gen_customer(xp, sf: float, columns: Sequence[str], idx=None):
    s = _sizes(sf)
    i = xp.arange(s["customer"], dtype=xp.uint64) if idx is None else idx
    fns = {
        "c_custkey": lambda: i.astype(xp.int64) + 1,
        "c_nationkey": lambda: _uni(xp, 21, i, 0, 25),
        "c_acctbal": lambda: _uni(xp, 22, i, -99999, 1000000),
        "c_mktsegment": lambda: (
            _u64(xp, 23, i) % xp.uint64(len(_SEG_POOL))
        ).astype(xp.int32),
    }
    return {c: fns[c]() for c in columns}


def _gen_supplier(xp, sf: float, columns: Sequence[str], idx=None):
    s = _sizes(sf)
    i = xp.arange(s["supplier"], dtype=xp.uint64) if idx is None else idx
    fns = {
        "s_suppkey": lambda: i.astype(xp.int64) + 1,
        "s_nationkey": lambda: _uni(xp, 31, i, 0, 25),
        "s_acctbal": lambda: _uni(xp, 32, i, -99999, 1000000),
    }
    return {c: fns[c]() for c in columns}


def _gen_part(xp, sf: float, columns: Sequence[str], idx=None):
    s = _sizes(sf)
    i = xp.arange(s["part"], dtype=xp.uint64) if idx is None else idx
    pk = lambda: i.astype(xp.int64) + 1
    fns = {
        "p_partkey": pk,
        "p_size": lambda: _uni(xp, 41, i, 1, 51),
        "p_retailprice": lambda: _retail_price_cents(xp, pk()),
        # brand code (m-1)*5+(x-1) with m,x uniform 1..5 — the sorted
        # Brand#11..Brand#55 pool makes the code purely arithmetic
        "p_brand": lambda: (
            (_uni(xp, 42, i, 0, 5) * 5 + _uni(xp, 43, i, 0, 5))
        ).astype(xp.int32),
        "p_container": lambda: (
            _u64(xp, 44, i) % xp.uint64(len(_CONTAINER_POOL))
        ).astype(xp.int32),
    }
    return {c: fns[c]() for c in columns}


_GENERATORS = {
    "lineitem": _gen_lineitem,
    "orders": _gen_orders,
    "customer": _gen_customer,
    "supplier": _gen_supplier,
    "part": _gen_part,
}


def supports(table: str, columns: Sequence[str]) -> bool:
    return table in SCHEMAS and all(c in SCHEMAS[table] for c in columns)


def numpy_columns(
    table: str, sf: float, columns: Sequence[str]
) -> Dict[str, np.ndarray]:
    """Host twin: {name: numpy array} bit-identical to the device page."""
    return _GENERATORS[table](np, sf, tuple(columns))


def numpy_columns_range(
    table: str, sf: float, columns: Sequence[str], start: int, count: int
) -> Dict[str, np.ndarray]:
    """Host twin of device_range: rows [start, start+count)."""
    idx = np.arange(start, start + count, dtype=np.uint64)
    return _GENERATORS[table](np, sf, tuple(columns), idx=idx)


_RANGE_FN_CACHE: Dict[tuple, object] = {}


def device_range(
    table: str, sf: float, columns: Sequence[str], start: int, count: int
):
    """Column arrays for rows [start, start+count) generated ON DEVICE.

    The jit is cached per (table, columns, count) with `start` TRACED, so
    a ranged catalog scan compiles once per batch shape and every batch
    thereafter costs one scalar transfer — the device-resident equivalent
    of the reference's worker-side split generation
    (presto-tpch/.../TpchRecordSet.java: data originates where compute
    runs, never crossing the coordinator link)."""
    import jax
    import jax.numpy as jnp

    columns = tuple(columns)
    key = (table, sf, columns, count, jax.default_backend())
    fn = _RANGE_FN_CACHE.get(key)
    if fn is None:
        schema = SCHEMAS[table]

        def gen(start_):
            idx = start_ + jnp.arange(count, dtype=jnp.uint64)
            cols = _GENERATORS[table](jnp, sf, columns, idx=idx)
            return tuple(
                cols[c].astype(schema[c][0].storage_dtype) for c in columns
            )

        fn = jax.jit(gen)
        _RANGE_FN_CACHE[key] = fn
    return fn(jnp.uint64(start))


_PAGE_CACHE: Dict[tuple, Page] = {}


def device_page(
    table: str, sf: float, columns: Sequence[str], pad_to: Optional[int] = None
) -> Page:
    """Generate the requested columns ON DEVICE (one jit call, no bulk
    host->device transfer) and wrap them as an engine Page."""
    import jax

    columns = tuple(columns)
    key = (table, sf, columns, pad_to, jax.default_backend())
    if key in _PAGE_CACHE:
        return _PAGE_CACHE[key]
    schema = SCHEMAS[table]

    def gen():
        import jax.numpy as jnp

        cols = _GENERATORS[table](jnp, sf, columns)
        return tuple(
            cols[c].astype(schema[c][0].storage_dtype) for c in columns
        )

    arrays = jax.jit(gen)()
    from ..page import intern_dictionary

    blocks = {}
    for c, arr in zip(columns, arrays):
        typ, pool = schema[c]
        did = intern_dictionary(tuple(pool)) if pool is not None else None
        blocks[c] = Block(arr, typ, None, did)
    page = Page.from_dict(blocks, pad_to=pad_to)
    _PAGE_CACHE[key] = page
    return page
