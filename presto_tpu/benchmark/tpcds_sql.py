"""TPC-DS query texts (spec-mandated queries, default substitution
parameters — same category as the TPC-H texts in tpch_sql.py; the
reference ships them under presto-benchto-benchmarks and tests them via
presto-tpcds). Subset chosen to exercise every supported engine feature:
multi-fact joins, date-dim filters, CASE buckets, correlated scalar
subqueries, EXISTS, CTE full-outer joins, count(distinct), day-diff
buckets. Queries combining GROUPING SETS with window functions (Q36/Q86)
are excluded until windows can run over the unioned sets.
"""

QUERIES = {
    3: """
select d_year, i_brand_id brand_id, i_brand brand,
       sum(ss_ext_sales_price) sum_agg
from date_dim dt, store_sales, item
where dt.d_date_sk = store_sales.ss_sold_date_sk
  and store_sales.ss_item_sk = item.i_item_sk
  and item.i_manufact_id = 128
  and dt.d_moy = 11
group by d_year, i_brand_id, i_brand
order by d_year, sum_agg desc, brand_id
limit 100
""",
    7: """
select i_item_id,
       avg(ss_quantity) agg1,
       avg(ss_list_price) agg2,
       avg(ss_coupon_amt) agg3,
       avg(ss_sales_price) agg4
from store_sales, customer_demographics, date_dim, item, promotion
where ss_sold_date_sk = d_date_sk
  and ss_item_sk = i_item_sk
  and ss_cdemo_sk = cd_demo_sk
  and ss_promo_sk = p_promo_sk
  and cd_gender = 'M'
  and cd_marital_status = 'S'
  and cd_education_status = 'College'
  and (p_channel_email = 'N' or p_channel_event = 'N')
  and d_year = 2000
group by i_item_id
order by i_item_id
limit 100
""",

    12: """
select i_item_id, i_item_desc, i_category, i_class, i_current_price,
       sum(ws_ext_sales_price) as itemrevenue,
       sum(ws_ext_sales_price) * 100.0 /
         sum(sum(ws_ext_sales_price)) over (partition by i_class) as revenueratio
from web_sales, item, date_dim
where ws_item_sk = i_item_sk
  and i_category in ('Sports', 'Books', 'Home')
  and ws_sold_date_sk = d_date_sk
  and d_date between date '1999-02-22' and (date '1999-02-22' + interval '30' day)
group by i_item_id, i_item_desc, i_category, i_class, i_current_price
order by i_category, i_class, i_item_id, i_item_desc, revenueratio
limit 100
""",
    20: """
select i_item_id, i_item_desc, i_category, i_class, i_current_price,
       sum(cs_ext_sales_price) as itemrevenue,
       sum(cs_ext_sales_price) * 100.0 /
         sum(sum(cs_ext_sales_price)) over (partition by i_class) as revenueratio
from catalog_sales, item, date_dim
where cs_item_sk = i_item_sk
  and i_category in ('Sports', 'Books', 'Home')
  and cs_sold_date_sk = d_date_sk
  and d_date between date '1999-02-22' and (date '1999-02-22' + interval '30' day)
group by i_item_id, i_item_desc, i_category, i_class, i_current_price
order by i_category, i_class, i_item_id, i_item_desc, revenueratio
limit 100
""",
    53: """
select manufact_id, sum_sales, avg_quarterly_sales
from (select i_manufact_id manufact_id,
             sum(ss_sales_price) sum_sales,
             avg(sum(ss_sales_price)) over (partition by i_manufact_id) avg_quarterly_sales
      from item, store_sales, date_dim, store
      where ss_item_sk = i_item_sk
        and ss_sold_date_sk = d_date_sk
        and ss_store_sk = s_store_sk
        and d_month_seq in (1200, 1200 + 1, 1200 + 2, 1200 + 3, 1200 + 4,
                            1200 + 5, 1200 + 6, 1200 + 7, 1200 + 8, 1200 + 9,
                            1200 + 10, 1200 + 11)
        and ((i_category in ('Books', 'Children', 'Electronics')
              and i_class in ('books class 01', 'children class 02',
                              'electronics class 03'))
          or (i_category in ('Women', 'Music', 'Men')
              and i_class in ('women class 01', 'music class 02',
                              'men class 03')))
      group by i_manufact_id, d_qoy) tmp1
where case when avg_quarterly_sales > 0
           then abs(sum_sales - avg_quarterly_sales) / avg_quarterly_sales
           else null end > 0.1
order by avg_quarterly_sales, sum_sales, manufact_id
limit 100
""",
    89: """
select i_category, i_class, i_brand, s_store_name, s_company_name,
       d_moy, sum_sales, avg_monthly_sales
from (select i_category, i_class, i_brand, s_store_name, s_company_name,
             d_moy, sum(ss_sales_price) sum_sales,
             avg(sum(ss_sales_price)) over
               (partition by i_category, i_brand, s_store_name,
                             s_company_name) avg_monthly_sales
      from item, store_sales, date_dim, store
      where ss_item_sk = i_item_sk
        and ss_sold_date_sk = d_date_sk
        and ss_store_sk = s_store_sk
        and d_year in (1999)
        and ((i_category in ('Books', 'Electronics', 'Sports')
              and i_class in ('books class 01', 'electronics class 02',
                              'sports class 03'))
          or (i_category in ('Men', 'Jewelry', 'Women')
              and i_class in ('men class 01', 'jewelry class 02',
                              'women class 03')))
      group by i_category, i_class, i_brand, s_store_name, s_company_name,
               d_moy) tmp1
where case when avg_monthly_sales <> 0
           then abs(sum_sales - avg_monthly_sales) / avg_monthly_sales
           else null end > 0.1
order by sum_sales - avg_monthly_sales, s_store_name
limit 100
""",
    98: """
select i_item_id, i_item_desc, i_category, i_class, i_current_price,
       sum(ss_ext_sales_price) as itemrevenue,
       sum(ss_ext_sales_price) * 100.0 /
         sum(sum(ss_ext_sales_price)) over (partition by i_class) as revenueratio
from store_sales, item, date_dim
where ss_item_sk = i_item_sk
  and i_category in ('Sports', 'Books', 'Home')
  and ss_sold_date_sk = d_date_sk
  and d_date between date '1999-02-22' and (date '1999-02-22' + interval '30' day)
group by i_item_id, i_item_desc, i_category, i_class, i_current_price
order by i_category, i_class, i_item_id, i_item_desc, revenueratio
""",
    15: """
select ca_zip, sum(cs_sales_price) total_sales
from catalog_sales, customer, customer_address, date_dim
where cs_bill_customer_sk = c_customer_sk
  and c_current_addr_sk = ca_address_sk
  and (substr(ca_zip, 1, 5) in ('85669','86197','88274','83405','86475',
                                '85392','85460','80348','81792')
       or ca_state in ('CA','WA','GA')
       or cs_sales_price > 500)
  and cs_sold_date_sk = d_date_sk
  and d_qoy = 2 and d_year = 2001
group by ca_zip
order by ca_zip
limit 100
""",

    18: """
select i_item_id, ca_country, ca_state, ca_county,
       avg(cast(cs_quantity as double)) agg1,
       avg(cast(cs_list_price as double)) agg2,
       avg(cast(cs_coupon_amt as double)) agg3,
       avg(cast(cs_sales_price as double)) agg4,
       avg(cast(cs_net_profit as double)) agg5,
       avg(cast(c_birth_year as double)) agg6,
       avg(cast(cd1.cd_dep_count as double)) agg7
from catalog_sales, customer_demographics cd1, customer_demographics cd2,
     customer, customer_address, date_dim, item
where cs_sold_date_sk = d_date_sk
  and cs_item_sk = i_item_sk
  and cs_bill_cdemo_sk = cd1.cd_demo_sk
  and cs_bill_customer_sk = c_customer_sk
  and cd1.cd_gender = 'F'
  and cd1.cd_education_status = 'Unknown'
  and c_current_cdemo_sk = cd2.cd_demo_sk
  and c_current_addr_sk = ca_address_sk
  and c_birth_month in (1, 6, 8, 9, 12, 2)
  and d_year = 1998
  and ca_state in ('MS', 'IN', 'ND', 'OK', 'NM', 'VA', 'MS')
group by rollup(i_item_id, ca_country, ca_state, ca_county)
order by ca_country, ca_state, ca_county, i_item_id
limit 100
""",
    22: """
select i_product_name, i_brand, i_class, i_category,
       avg(inv_quantity_on_hand) qoh
from inventory, date_dim, item
where inv_date_sk = d_date_sk
  and inv_item_sk = i_item_sk
  and d_month_seq between 1200 and 1200 + 11
group by rollup(i_product_name, i_brand, i_class, i_category)
order by qoh, i_product_name, i_brand, i_class, i_category
limit 100
""",
    27: """
select i_item_id, s_state, grouping(i_item_id, s_state) g_state,
       avg(ss_quantity) agg1,
       avg(ss_list_price) agg2,
       avg(ss_coupon_amt) agg3,
       avg(ss_sales_price) agg4
from store_sales, customer_demographics, date_dim, store, item
where ss_sold_date_sk = d_date_sk
  and ss_item_sk = i_item_sk
  and ss_store_sk = s_store_sk
  and ss_cdemo_sk = cd_demo_sk
  and cd_gender = 'M'
  and cd_marital_status = 'S'
  and cd_education_status = 'College'
  and d_year = 2002
  and s_state in ('TN', 'TN', 'TN', 'TN', 'TN', 'TN')
group by rollup(i_item_id, s_state)
order by i_item_id, s_state
limit 100
""",
    19: """
select i_brand_id brand_id, i_brand brand, i_manufact_id, i_manufact,
       sum(ss_ext_sales_price) ext_price
from date_dim, store_sales, item, customer, customer_address, store
where d_date_sk = ss_sold_date_sk
  and ss_item_sk = i_item_sk
  and i_manager_id = 8
  and d_moy = 11
  and d_year = 1998
  and ss_customer_sk = c_customer_sk
  and c_current_addr_sk = ca_address_sk
  and substr(ca_zip, 1, 5) <> substr(s_zip, 1, 5)
  and ss_store_sk = s_store_sk
group by i_brand_id, i_brand, i_manufact_id, i_manufact
order by ext_price desc, i_brand, i_brand_id, i_manufact_id, i_manufact
limit 100
""",
    25: """
select i_item_id, i_item_desc, s_store_id, s_store_name,
       max(ss_net_profit) as store_sales_profit,
       max(sr_net_loss) as store_returns_loss,
       max(cs_net_profit) as catalog_sales_profit
from store_sales, store_returns, catalog_sales,
     date_dim d1, date_dim d2, date_dim d3, store, item
where d1.d_moy = 4
  and d1.d_year = 2001
  and d1.d_date_sk = ss_sold_date_sk
  and i_item_sk = ss_item_sk
  and s_store_sk = ss_store_sk
  and ss_customer_sk = sr_customer_sk
  and ss_item_sk = sr_item_sk
  and ss_ticket_number = sr_ticket_number
  and sr_returned_date_sk = d2.d_date_sk
  and d2.d_moy between 4 and 10
  and d2.d_year = 2001
  and sr_customer_sk = cs_bill_customer_sk
  and sr_item_sk = cs_item_sk
  and cs_sold_date_sk = d3.d_date_sk
  and d3.d_moy between 4 and 10
  and d3.d_year = 2001
group by i_item_id, i_item_desc, s_store_id, s_store_name
order by i_item_id, i_item_desc, s_store_id, s_store_name
limit 100
""",
    26: """
select i_item_id,
       avg(cs_quantity) agg1,
       avg(cs_list_price) agg2,
       avg(cs_coupon_amt) agg3,
       avg(cs_sales_price) agg4
from catalog_sales, customer_demographics, date_dim, item, promotion
where cs_sold_date_sk = d_date_sk
  and cs_item_sk = i_item_sk
  and cs_bill_cdemo_sk = cd_demo_sk
  and cs_promo_sk = p_promo_sk
  and cd_gender = 'M'
  and cd_marital_status = 'S'
  and cd_education_status = 'College'
  and (p_channel_email = 'N' or p_channel_event = 'N')
  and d_year = 2000
group by i_item_id
order by i_item_id
limit 100
""",
    29: """
select i_item_id, i_item_desc, s_store_id, s_store_name,
       sum(ss_quantity) as store_sales_quantity,
       sum(sr_return_quantity) as store_returns_quantity,
       sum(cs_quantity) as catalog_sales_quantity
from store_sales, store_returns, catalog_sales,
     date_dim d1, date_dim d2, date_dim d3, store, item
where d1.d_moy = 9
  and d1.d_year = 1999
  and d1.d_date_sk = ss_sold_date_sk
  and i_item_sk = ss_item_sk
  and s_store_sk = ss_store_sk
  and ss_customer_sk = sr_customer_sk
  and ss_item_sk = sr_item_sk
  and ss_ticket_number = sr_ticket_number
  and sr_returned_date_sk = d2.d_date_sk
  and d2.d_moy between 9 and 12
  and d2.d_year = 1999
  and sr_customer_sk = cs_bill_customer_sk
  and sr_item_sk = cs_item_sk
  and cs_sold_date_sk = d3.d_date_sk
  and d3.d_year in (1999, 2000, 2001)
group by i_item_id, i_item_desc, s_store_id, s_store_name
order by i_item_id, i_item_desc, s_store_id, s_store_name
limit 100
""",
    37: """
select i_item_id, i_item_desc, i_current_price
from item, inventory, date_dim, catalog_sales
where i_current_price between 68 and 68 + 30
  and inv_item_sk = i_item_sk
  and d_date_sk = inv_date_sk
  and d_date between date '2000-02-01' and (date '2000-02-01' + interval '60' day)
  and i_manufact_id in (677, 940, 694, 808)
  and inv_quantity_on_hand between 100 and 500
  and cs_item_sk = i_item_sk
group by i_item_id, i_item_desc, i_current_price
order by i_item_id
limit 100
""",

    38: """
select count(*) from (
    select distinct c_last_name, c_first_name, d_date
    from store_sales, date_dim, customer
    where store_sales.ss_sold_date_sk = date_dim.d_date_sk
      and store_sales.ss_customer_sk = customer.c_customer_sk
      and d_month_seq between 1200 and 1200 + 11
  intersect
    select distinct c_last_name, c_first_name, d_date
    from catalog_sales, date_dim, customer
    where catalog_sales.cs_sold_date_sk = date_dim.d_date_sk
      and catalog_sales.cs_bill_customer_sk = customer.c_customer_sk
      and d_month_seq between 1200 and 1200 + 11
  intersect
    select distinct c_last_name, c_first_name, d_date
    from web_sales, date_dim, customer
    where web_sales.ws_sold_date_sk = date_dim.d_date_sk
      and web_sales.ws_bill_customer_sk = customer.c_customer_sk
      and d_month_seq between 1200 and 1200 + 11
) hot_cust
limit 100
""",
    87: """
select count(*) from (
    select distinct c_last_name, c_first_name, d_date
    from store_sales, date_dim, customer
    where store_sales.ss_sold_date_sk = date_dim.d_date_sk
      and store_sales.ss_customer_sk = customer.c_customer_sk
      and d_month_seq between 1200 and 1200 + 11
  except
    select distinct c_last_name, c_first_name, d_date
    from catalog_sales, date_dim, customer
    where catalog_sales.cs_sold_date_sk = date_dim.d_date_sk
      and catalog_sales.cs_bill_customer_sk = customer.c_customer_sk
      and d_month_seq between 1200 and 1200 + 11
  except
    select distinct c_last_name, c_first_name, d_date
    from web_sales, date_dim, customer
    where web_sales.ws_sold_date_sk = date_dim.d_date_sk
      and web_sales.ws_bill_customer_sk = customer.c_customer_sk
      and d_month_seq between 1200 and 1200 + 11
) cool_cust
""",
    42: """
select d_year, i_category_id, i_category, sum(ss_ext_sales_price) total
from date_dim dt, store_sales, item
where dt.d_date_sk = store_sales.ss_sold_date_sk
  and store_sales.ss_item_sk = item.i_item_sk
  and item.i_manager_id = 1
  and dt.d_moy = 11
  and dt.d_year = 2000
group by d_year, i_category_id, i_category
order by total desc, d_year, i_category_id, i_category
limit 100
""",
    43: """
select s_store_name, s_store_id,
       sum(case when (d_day_name = 'Sunday') then ss_sales_price else null end) sun_sales,
       sum(case when (d_day_name = 'Monday') then ss_sales_price else null end) mon_sales,
       sum(case when (d_day_name = 'Tuesday') then ss_sales_price else null end) tue_sales,
       sum(case when (d_day_name = 'Wednesday') then ss_sales_price else null end) wed_sales,
       sum(case when (d_day_name = 'Thursday') then ss_sales_price else null end) thu_sales,
       sum(case when (d_day_name = 'Friday') then ss_sales_price else null end) fri_sales,
       sum(case when (d_day_name = 'Saturday') then ss_sales_price else null end) sat_sales
from date_dim, store_sales, store
where d_date_sk = ss_sold_date_sk
  and s_store_sk = ss_store_sk
  and s_gmt_offset = -5
  and d_year = 2000
group by s_store_name, s_store_id
order by s_store_name, s_store_id, sun_sales, mon_sales, tue_sales,
         wed_sales, thu_sales, fri_sales, sat_sales
limit 100
""",
    48: """
select sum(ss_quantity)
from store_sales, store, customer_demographics, customer_address, date_dim
where s_store_sk = ss_store_sk
  and ss_sold_date_sk = d_date_sk and d_year = 2000
  and ((cd_demo_sk = ss_cdemo_sk
        and cd_marital_status = 'M'
        and cd_education_status = '4 yr Degree'
        and ss_sales_price between 100.00 and 150.00)
    or (cd_demo_sk = ss_cdemo_sk
        and cd_marital_status = 'D'
        and cd_education_status = '2 yr Degree'
        and ss_sales_price between 50.00 and 100.00)
    or (cd_demo_sk = ss_cdemo_sk
        and cd_marital_status = 'S'
        and cd_education_status = 'College'
        and ss_sales_price between 150.00 and 200.00))
  and ((ss_addr_sk = ca_address_sk
        and ca_country = 'United States'
        and ca_state in ('CO','OH','TX')
        and ss_net_profit between 0 and 2000)
    or (ss_addr_sk = ca_address_sk
        and ca_country = 'United States'
        and ca_state in ('OR','MN','KY')
        and ss_net_profit between 150 and 3000)
    or (ss_addr_sk = ca_address_sk
        and ca_country = 'United States'
        and ca_state in ('VA','CA','MS')
        and ss_net_profit between 50 and 25000))
""",
    50: """
select s_store_name, s_company_id, s_street_number, s_street_name,
       s_street_type, s_suite_number, s_city, s_county, s_state, s_zip,
       sum(case when (sr_returned_date_sk - ss_sold_date_sk <= 30) then 1 else 0 end) as days_30,
       sum(case when (sr_returned_date_sk - ss_sold_date_sk > 30) and
                     (sr_returned_date_sk - ss_sold_date_sk <= 60) then 1 else 0 end) as days_60,
       sum(case when (sr_returned_date_sk - ss_sold_date_sk > 60) and
                     (sr_returned_date_sk - ss_sold_date_sk <= 90) then 1 else 0 end) as days_90,
       sum(case when (sr_returned_date_sk - ss_sold_date_sk > 90) and
                     (sr_returned_date_sk - ss_sold_date_sk <= 120) then 1 else 0 end) as days_120,
       sum(case when (sr_returned_date_sk - ss_sold_date_sk > 120) then 1 else 0 end) as days_more_120
from store_sales, store_returns, store, date_dim d1, date_dim d2
where d2.d_year = 2001
  and d2.d_moy = 8
  and ss_ticket_number = sr_ticket_number
  and ss_item_sk = sr_item_sk
  and ss_sold_date_sk = d1.d_date_sk
  and sr_returned_date_sk = d2.d_date_sk
  and ss_customer_sk = sr_customer_sk
  and ss_store_sk = s_store_sk
group by s_store_name, s_company_id, s_street_number, s_street_name,
         s_street_type, s_suite_number, s_city, s_county, s_state, s_zip
order by s_store_name, s_company_id, s_street_number, s_street_name,
         s_street_type, s_suite_number, s_city, s_county, s_state, s_zip
limit 100
""",
    52: """
select d_year, i_brand_id brand_id, i_brand brand,
       sum(ss_ext_sales_price) ext_price
from date_dim dt, store_sales, item
where dt.d_date_sk = store_sales.ss_sold_date_sk
  and store_sales.ss_item_sk = item.i_item_sk
  and item.i_manager_id = 1
  and dt.d_moy = 11
  and dt.d_year = 2000
group by d_year, i_brand_id, i_brand
order by d_year, ext_price desc, brand_id
limit 100
""",
    55: """
select i_brand_id brand_id, i_brand brand,
       sum(ss_ext_sales_price) ext_price
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk
  and ss_item_sk = i_item_sk
  and i_manager_id = 28
  and d_moy = 11
  and d_year = 1999
group by i_brand, i_brand_id
order by ext_price desc, i_brand_id
limit 100
""",
    62: """
select substr(w_warehouse_name, 1, 20) wname, sm_type, web_name,
       sum(case when (ws_ship_date_sk - ws_sold_date_sk <= 30) then 1 else 0 end) as days_30,
       sum(case when (ws_ship_date_sk - ws_sold_date_sk > 30) and
                     (ws_ship_date_sk - ws_sold_date_sk <= 60) then 1 else 0 end) as days_60,
       sum(case when (ws_ship_date_sk - ws_sold_date_sk > 60) and
                     (ws_ship_date_sk - ws_sold_date_sk <= 90) then 1 else 0 end) as days_90,
       sum(case when (ws_ship_date_sk - ws_sold_date_sk > 90) and
                     (ws_ship_date_sk - ws_sold_date_sk <= 120) then 1 else 0 end) as days_120,
       sum(case when (ws_ship_date_sk - ws_sold_date_sk > 120) then 1 else 0 end) as days_more_120
from web_sales, warehouse, ship_mode, web_site, date_dim
where d_month_seq between 1200 and 1200 + 11
  and ws_ship_date_sk = d_date_sk
  and ws_warehouse_sk = w_warehouse_sk
  and ws_ship_mode_sk = sm_ship_mode_sk
  and ws_web_site_sk = web_site_sk
group by substr(w_warehouse_name, 1, 20), sm_type, web_name
order by wname, sm_type, web_name
limit 100
""",
    65: """
select s_store_name, i_item_desc, sc.revenue, i_current_price,
       i_wholesale_cost, i_brand
from store, item,
     (select ss_store_sk, avg(revenue) as ave
      from (select ss_store_sk, ss_item_sk, sum(ss_sales_price) as revenue
            from store_sales, date_dim
            where ss_sold_date_sk = d_date_sk
              and d_month_seq between 1176 and 1176 + 11
            group by ss_store_sk, ss_item_sk) sa
      group by ss_store_sk) sb,
     (select ss_store_sk, ss_item_sk, sum(ss_sales_price) as revenue
      from store_sales, date_dim
      where ss_sold_date_sk = d_date_sk
        and d_month_seq between 1176 and 1176 + 11
      group by ss_store_sk, ss_item_sk) sc
where sb.ss_store_sk = sc.ss_store_sk
  and sc.revenue <= 0.1 * sb.ave
  and s_store_sk = sc.ss_store_sk
  and i_item_sk = sc.ss_item_sk
order by s_store_name, i_item_desc, sc.revenue, i_current_price,
         i_wholesale_cost, i_brand
limit 100
""",
    79: """
select c_last_name, c_first_name, substr(s_city, 1, 30) city_part,
       ss_ticket_number, amt, profit
from (select ss_ticket_number, ss_customer_sk, store.s_city,
             sum(ss_coupon_amt) amt,
             sum(ss_net_profit) profit
      from store_sales, date_dim, store, household_demographics
      where store_sales.ss_sold_date_sk = date_dim.d_date_sk
        and store_sales.ss_store_sk = store.s_store_sk
        and store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
        and (household_demographics.hd_dep_count = 6
             or household_demographics.hd_vehicle_count > 2)
        and date_dim.d_dow = 1
        and date_dim.d_year in (1999, 2000, 2001)
        and store.s_number_employees between 200 and 295
      group by ss_ticket_number, ss_customer_sk, ss_addr_sk,
               store.s_city) ms, customer
where ss_customer_sk = c_customer_sk
order by c_last_name, c_first_name, city_part, profit, ss_ticket_number, amt
limit 100
""",
    82: """
select i_item_id, i_item_desc, i_current_price
from item, inventory, date_dim, store_sales
where i_current_price between 62 and 62 + 30
  and inv_item_sk = i_item_sk
  and d_date_sk = inv_date_sk
  and d_date between date '2000-05-25' and (date '2000-05-25' + interval '60' day)
  and i_manufact_id in (129, 270, 821, 423)
  and inv_quantity_on_hand between 100 and 500
  and ss_item_sk = i_item_sk
group by i_item_id, i_item_desc, i_current_price
order by i_item_id
limit 100
""",

    90: """
select cast(amc as decimal(15,4)) / cast(pmc as decimal(15,4)) am_pm_ratio
from (select count(*) amc from web_sales, household_demographics,
             time_dim, web_page
      where ws_sold_time_sk = time_dim.t_time_sk
        and ws_ship_hdemo_sk = household_demographics.hd_demo_sk
        and ws_web_page_sk = web_page.wp_web_page_sk
        and time_dim.t_hour between 8 and 8 + 1
        and household_demographics.hd_dep_count = 6
        and web_page.wp_char_count between 5000 and 5200) at_,
     (select count(*) pmc from web_sales, household_demographics,
             time_dim, web_page
      where ws_sold_time_sk = time_dim.t_time_sk
        and ws_ship_hdemo_sk = household_demographics.hd_demo_sk
        and ws_web_page_sk = web_page.wp_web_page_sk
        and time_dim.t_hour between 19 and 19 + 1
        and household_demographics.hd_dep_count = 6
        and web_page.wp_char_count between 5000 and 5200) pt
order by am_pm_ratio
limit 100
""",
    91: """
select cc_call_center_id call_center, cc_name call_center_name,
       cc_manager manager, sum(cr_net_loss) returns_loss
from call_center, catalog_returns, date_dim, customer,
     customer_address, customer_demographics, household_demographics
where cr_call_center_sk = cc_call_center_sk
  and cr_returned_date_sk = d_date_sk
  and cr_returning_customer_sk = c_customer_sk
  and cd_demo_sk = c_current_cdemo_sk
  and hd_demo_sk = c_current_hdemo_sk
  and ca_address_sk = c_current_addr_sk
  and d_year = 1998
  and d_moy = 11
  and ((cd_marital_status = 'M' and cd_education_status = 'Unknown')
       or (cd_marital_status = 'W' and cd_education_status = 'Advanced Degree'))
  and hd_buy_potential like '>10000%'
  and ca_gmt_offset = -7
group by cc_call_center_id, cc_name, cc_manager,
         cd_marital_status, cd_education_status
order by returns_loss desc
""",
    92: """
select sum(ws_ext_discount_amt) as excess_discount_amount
from web_sales, item, date_dim
where i_manufact_id = 350
  and i_item_sk = ws_item_sk
  and d_date between date '2000-01-27' and (date '2000-01-27' + interval '90' day)
  and d_date_sk = ws_sold_date_sk
  and ws_ext_discount_amt >
      (select 1.3 * avg(ws_ext_discount_amt)
       from web_sales, date_dim
       where ws_item_sk = i_item_sk
         and d_date between date '2000-01-27' and (date '2000-01-27' + interval '90' day)
         and d_date_sk = ws_sold_date_sk)
order by sum(ws_ext_discount_amt)
limit 100
""",
    93: """
select ss_customer_sk, sum(act_sales) sumsales
from (select ss_item_sk, ss_ticket_number, ss_customer_sk,
             case when sr_return_quantity is not null
                  then (ss_quantity - sr_return_quantity) * ss_sales_price
                  else (ss_quantity * ss_sales_price) end act_sales
      from store_sales
      left outer join store_returns
        on (sr_item_sk = ss_item_sk and sr_ticket_number = ss_ticket_number)
      , reason
      where sr_reason_sk = r_reason_sk
        and r_reason_desc = 'Did not fit') t
group by ss_customer_sk
order by sumsales, ss_customer_sk
limit 100
""",
    95: """
with ws_wh as
  (select ws1.ws_order_number, ws1.ws_warehouse_sk wh1, ws2.ws_warehouse_sk wh2
   from web_sales ws1, web_sales ws2
   where ws1.ws_order_number = ws2.ws_order_number
     and ws1.ws_warehouse_sk <> ws2.ws_warehouse_sk)
select count(distinct ws_order_number) as order_count,
       sum(ws_ext_ship_cost) as total_shipping_cost,
       sum(ws_net_profit) as total_net_profit
from web_sales ws1, date_dim, customer_address, web_site
where d_date between date '1999-02-01' and (date '1999-02-01' + interval '60' day)
  and ws1.ws_ship_date_sk = d_date_sk
  and ws1.ws_ship_addr_sk = ca_address_sk
  and ca_state = 'IL'
  and ws1.ws_web_site_sk = web_site_sk
  and web_company_name = 'able'
  and ws1.ws_order_number in (select ws_order_number from ws_wh)
  and ws1.ws_order_number in (select wr_order_number
                              from web_returns, ws_wh
                              where wr_order_number = ws_wh.ws_order_number)
order by count(distinct ws_order_number)
limit 100
""",
    96: """
select count(*) cnt
from store_sales, household_demographics, time_dim, store
where ss_sold_time_sk = time_dim.t_time_sk
  and ss_hdemo_sk = household_demographics.hd_demo_sk
  and ss_store_sk = s_store_sk
  and time_dim.t_hour = 20
  and time_dim.t_minute >= 30
  and household_demographics.hd_dep_count = 7
  and store.s_store_name = 'ese'
order by count(*)
limit 100
""",
    97: """
with ssci as
  (select ss_customer_sk customer_sk, ss_item_sk item_sk
   from store_sales, date_dim
   where ss_sold_date_sk = d_date_sk
     and d_month_seq between 1200 and 1200 + 11
   group by ss_customer_sk, ss_item_sk),
 csci as
  (select cs_bill_customer_sk customer_sk, cs_item_sk item_sk
   from catalog_sales, date_dim
   where cs_sold_date_sk = d_date_sk
     and d_month_seq between 1200 and 1200 + 11
   group by cs_bill_customer_sk, cs_item_sk)
select sum(case when ssci.customer_sk is not null and csci.customer_sk is null then 1 else 0 end) store_only,
       sum(case when ssci.customer_sk is null and csci.customer_sk is not null then 1 else 0 end) catalog_only,
       sum(case when ssci.customer_sk is not null and csci.customer_sk is not null then 1 else 0 end) store_and_catalog
from ssci full outer join csci
  on (ssci.customer_sk = csci.customer_sk and ssci.item_sk = csci.item_sk)
limit 100
""",
    99: """
select substr(w_warehouse_name, 1, 20) wname, sm_type, cc_name,
       sum(case when (cs_ship_date_sk - cs_sold_date_sk <= 30) then 1 else 0 end) as days_30,
       sum(case when (cs_ship_date_sk - cs_sold_date_sk > 30) and
                     (cs_ship_date_sk - cs_sold_date_sk <= 60) then 1 else 0 end) as days_60,
       sum(case when (cs_ship_date_sk - cs_sold_date_sk > 60) and
                     (cs_ship_date_sk - cs_sold_date_sk <= 90) then 1 else 0 end) as days_90,
       sum(case when (cs_ship_date_sk - cs_sold_date_sk > 90) and
                     (cs_ship_date_sk - cs_sold_date_sk <= 120) then 1 else 0 end) as days_120,
       sum(case when (cs_ship_date_sk - cs_sold_date_sk > 120) then 1 else 0 end) as days_more_120
from catalog_sales, warehouse, ship_mode, call_center, date_dim
where d_month_seq between 1200 and 1200 + 11
  and cs_ship_date_sk = d_date_sk
  and cs_warehouse_sk = w_warehouse_sk
  and cs_ship_mode_sk = sm_ship_mode_sk
  and cs_call_center_sk = cc_call_center_sk
group by substr(w_warehouse_name, 1, 20), sm_type, cc_name
order by wname, sm_type, cc_name
limit 100
""",
    9: """
select case when (select count(*) from store_sales
                  where ss_quantity between 1 and 20) > 74129
            then (select avg(ss_ext_discount_amt) from store_sales
                  where ss_quantity between 1 and 20)
            else (select avg(ss_net_paid) from store_sales
                  where ss_quantity between 1 and 20) end bucket1,
       case when (select count(*) from store_sales
                  where ss_quantity between 21 and 40) > 122840
            then (select avg(ss_ext_discount_amt) from store_sales
                  where ss_quantity between 21 and 40)
            else (select avg(ss_net_paid) from store_sales
                  where ss_quantity between 21 and 40) end bucket2,
       case when (select count(*) from store_sales
                  where ss_quantity between 41 and 60) > 56580
            then (select avg(ss_ext_discount_amt) from store_sales
                  where ss_quantity between 41 and 60)
            else (select avg(ss_net_paid) from store_sales
                  where ss_quantity between 41 and 60) end bucket3,
       case when (select count(*) from store_sales
                  where ss_quantity between 61 and 80) > 10097
            then (select avg(ss_ext_discount_amt) from store_sales
                  where ss_quantity between 61 and 80)
            else (select avg(ss_net_paid) from store_sales
                  where ss_quantity between 61 and 80) end bucket4,
       case when (select count(*) from store_sales
                  where ss_quantity between 81 and 100) > 165306
            then (select avg(ss_ext_discount_amt) from store_sales
                  where ss_quantity between 81 and 100)
            else (select avg(ss_net_paid) from store_sales
                  where ss_quantity between 81 and 100) end bucket5
from reason
where r_reason_sk = 1
""",
    13: """
select avg(ss_quantity) aq,
       avg(ss_ext_sales_price) aesp,
       avg(ss_ext_wholesale_cost) aewc,
       sum(ss_ext_wholesale_cost) sewc
from store_sales, store, customer_demographics, household_demographics,
     customer_address, date_dim
where s_store_sk = ss_store_sk
  and ss_sold_date_sk = d_date_sk and d_year = 2001
  and ((ss_hdemo_sk = hd_demo_sk
        and cd_demo_sk = ss_cdemo_sk
        and cd_marital_status = 'M'
        and cd_education_status = 'Advanced Degree'
        and ss_sales_price between 100.00 and 150.00
        and hd_dep_count = 3)
    or (ss_hdemo_sk = hd_demo_sk
        and cd_demo_sk = ss_cdemo_sk
        and cd_marital_status = 'S'
        and cd_education_status = 'College'
        and ss_sales_price between 50.00 and 100.00
        and hd_dep_count = 1)
    or (ss_hdemo_sk = hd_demo_sk
        and cd_demo_sk = ss_cdemo_sk
        and cd_marital_status = 'W'
        and cd_education_status = '2 yr Degree'
        and ss_sales_price between 150.00 and 200.00
        and hd_dep_count = 1))
  and ((ss_addr_sk = ca_address_sk
        and ca_country = 'United States'
        and ca_state in ('TX', 'OH', 'TX')
        and ss_net_profit between 100 and 200)
    or (ss_addr_sk = ca_address_sk
        and ca_country = 'United States'
        and ca_state in ('OR', 'NM', 'KY')
        and ss_net_profit between 150 and 300)
    or (ss_addr_sk = ca_address_sk
        and ca_country = 'United States'
        and ca_state in ('VA', 'TX', 'MS')
        and ss_net_profit between 50 and 250))
""",
    16: """
select count(distinct cs_order_number) as order_count,
       sum(cs_ext_ship_cost) as total_shipping_cost,
       sum(cs_net_profit) as total_net_profit
from catalog_sales cs1, date_dim, customer_address, call_center
where d_date between date '2002-02-01' and (date '2002-02-01' + interval '60' day)
  and cs1.cs_ship_date_sk = d_date_sk
  and cs1.cs_ship_addr_sk = ca_address_sk
  and ca_state = 'GA'
  and cs1.cs_call_center_sk = cc_call_center_sk
  and cc_county = 'Williamson County'
  and exists (select *
              from catalog_sales cs2
              where cs1.cs_order_number = cs2.cs_order_number
                and cs1.cs_warehouse_sk <> cs2.cs_warehouse_sk)
  and not exists (select *
                  from catalog_returns cr1
                  where cs1.cs_order_number = cr1.cr_order_number)
order by count(distinct cs_order_number)
limit 100
""",
    21: """
select *
from (select w_warehouse_name, i_item_id,
             sum(case when d_date < date '2000-03-11'
                      then inv_quantity_on_hand else 0 end) as inv_before,
             sum(case when d_date >= date '2000-03-11'
                      then inv_quantity_on_hand else 0 end) as inv_after
      from inventory, warehouse, item, date_dim
      where i_current_price between 0.99 and 1.49
        and i_item_sk = inv_item_sk
        and inv_warehouse_sk = w_warehouse_sk
        and inv_date_sk = d_date_sk
        and d_date between date '2000-02-10' and (date '2000-03-11' + interval '30' day)
      group by w_warehouse_name, i_item_id) x
where (case when inv_before > 0 then inv_after / inv_before else null end)
      between 0.666667 and 1.5
order by w_warehouse_name, i_item_id
limit 100
""",
    28: """
select *
from (select avg(ss_list_price) b1_lp, count(ss_list_price) b1_cnt,
             count(distinct ss_list_price) b1_cntd
      from store_sales
      where ss_quantity between 0 and 5
        and (ss_list_price between 8 and 18
             or ss_coupon_amt between 459 and 1459
             or ss_wholesale_cost between 57 and 77)) b1,
     (select avg(ss_list_price) b2_lp, count(ss_list_price) b2_cnt,
             count(distinct ss_list_price) b2_cntd
      from store_sales
      where ss_quantity between 6 and 10
        and (ss_list_price between 90 and 100
             or ss_coupon_amt between 2323 and 3323
             or ss_wholesale_cost between 31 and 51)) b2,
     (select avg(ss_list_price) b3_lp, count(ss_list_price) b3_cnt,
             count(distinct ss_list_price) b3_cntd
      from store_sales
      where ss_quantity between 11 and 15
        and (ss_list_price between 142 and 152
             or ss_coupon_amt between 12214 and 13214
             or ss_wholesale_cost between 79 and 99)) b3,
     (select avg(ss_list_price) b4_lp, count(ss_list_price) b4_cnt,
             count(distinct ss_list_price) b4_cntd
      from store_sales
      where ss_quantity between 16 and 20
        and (ss_list_price between 135 and 145
             or ss_coupon_amt between 6071 and 7071
             or ss_wholesale_cost between 38 and 58)) b4,
     (select avg(ss_list_price) b5_lp, count(ss_list_price) b5_cnt,
             count(distinct ss_list_price) b5_cntd
      from store_sales
      where ss_quantity between 21 and 25
        and (ss_list_price between 122 and 132
             or ss_coupon_amt between 836 and 1836
             or ss_wholesale_cost between 17 and 37)) b5,
     (select avg(ss_list_price) b6_lp, count(ss_list_price) b6_cnt,
             count(distinct ss_list_price) b6_cntd
      from store_sales
      where ss_quantity between 26 and 30
        and (ss_list_price between 154 and 164
             or ss_coupon_amt between 7326 and 8326
             or ss_wholesale_cost between 7 and 27)) b6
limit 100
""",
    32: """
select sum(cs_ext_discount_amt) as excess_discount_amount
from catalog_sales, item, date_dim
where i_manufact_id = 977
  and i_item_sk = cs_item_sk
  and d_date between date '2000-01-27' and (date '2000-01-27' + interval '90' day)
  and d_date_sk = cs_sold_date_sk
  and cs_ext_discount_amt >
      (select 1.3 * avg(cs_ext_discount_amt)
       from catalog_sales, date_dim
       where cs_item_sk = i_item_sk
         and d_date between date '2000-01-27' and (date '2000-01-27' + interval '90' day)
         and d_date_sk = cs_sold_date_sk)
limit 100
""",
    34: """
select c_last_name, c_first_name, c_salutation, c_preferred_cust_flag,
       ss_ticket_number, cnt
from (select ss_ticket_number, ss_customer_sk, count(*) cnt
      from store_sales, date_dim, store, household_demographics
      where store_sales.ss_sold_date_sk = date_dim.d_date_sk
        and store_sales.ss_store_sk = store.s_store_sk
        and store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
        and (date_dim.d_dom between 1 and 3 or date_dim.d_dom between 25 and 28)
        and (household_demographics.hd_buy_potential = '>10000'
             or household_demographics.hd_buy_potential = 'Unknown')
        and household_demographics.hd_vehicle_count > 0
        and (case when household_demographics.hd_vehicle_count > 0
                  then household_demographics.hd_dep_count / household_demographics.hd_vehicle_count
                  else null end) > 1.2
        and date_dim.d_year in (1999, 2000, 2001)
        and store.s_county = 'Williamson County'
      group by ss_ticket_number, ss_customer_sk) dn, customer
where ss_customer_sk = c_customer_sk
  and cnt between 15 and 20
order by c_last_name asc, c_first_name asc, c_salutation asc,
         c_preferred_cust_flag desc, ss_ticket_number asc
""",
    40: """
select w_state, i_item_id,
       sum(case when d_date < date '2000-03-11'
                then cs_sales_price - coalesce(cr_refunded_cash, 0)
                else 0 end) as sales_before,
       sum(case when d_date >= date '2000-03-11'
                then cs_sales_price - coalesce(cr_refunded_cash, 0)
                else 0 end) as sales_after
from catalog_sales
     left outer join catalog_returns
       on (cs_order_number = cr_order_number and cs_item_sk = cr_item_sk),
     warehouse, item, date_dim
where i_current_price between 0.99 and 1.49
  and i_item_sk = cs_item_sk
  and cs_warehouse_sk = w_warehouse_sk
  and cs_sold_date_sk = d_date_sk
  and d_date between date '2000-02-10' and (date '2000-03-11' + interval '30' day)
group by w_state, i_item_id
order by w_state, i_item_id
limit 100
""",
    41: """
select distinct i_product_name
from item i1
where i_manufact_id between 738 and 778
  and (select count(*) as item_cnt
       from item
       where (i_manufact = i1.i_manufact
              and ((i_category = 'Women'
                    and (i_color = 'powder' or i_color = 'khaki')
                    and (i_units = 'Ounce' or i_units = 'Oz')
                    and (i_size = 'medium' or i_size = 'extra large'))
                or (i_category = 'Women'
                    and (i_color = 'brown' or i_color = 'honeydew')
                    and (i_units = 'Bunch' or i_units = 'Ton')
                    and (i_size = 'N/A' or i_size = 'small'))
                or (i_category = 'Men'
                    and (i_color = 'floral' or i_color = 'deep')
                    and (i_units = 'N/A' or i_units = 'Dozen')
                    and (i_size = 'petite' or i_size = 'large'))
                or (i_category = 'Men'
                    and (i_color = 'light' or i_color = 'cornflower')
                    and (i_units = 'Box' or i_units = 'Pound')
                    and (i_size = 'medium' or i_size = 'extra large'))))
          or (i_manufact = i1.i_manufact
              and ((i_category = 'Women'
                    and (i_color = 'midnight' or i_color = 'snow')
                    and (i_units = 'Pallet' or i_units = 'Gross')
                    and (i_size = 'medium' or i_size = 'extra large'))
                or (i_category = 'Women'
                    and (i_color = 'cyan' or i_color = 'papaya')
                    and (i_units = 'Cup' or i_units = 'Dram')
                    and (i_size = 'N/A' or i_size = 'small'))
                or (i_category = 'Men'
                    and (i_color = 'orange' or i_color = 'frosted')
                    and (i_units = 'Each' or i_units = 'Tbl')
                    and (i_size = 'petite' or i_size = 'large'))
                or (i_category = 'Men'
                    and (i_color = 'forest' or i_color = 'ghost')
                    and (i_units = 'Lb' or i_units = 'Bundle')
                    and (i_size = 'medium' or i_size = 'extra large'))))) > 0
order by i_product_name
limit 100
""",
    45: """
select ca_zip, ca_city, sum(ws_sales_price) total_price
from web_sales, customer, customer_address, date_dim, item
where ws_bill_customer_sk = c_customer_sk
  and c_current_addr_sk = ca_address_sk
  and ws_item_sk = i_item_sk
  and (substr(ca_zip, 1, 5) in ('85669', '86197', '88274', '83405',
                                '86475', '85392', '85460', '80348', '81792')
       or i_item_id in (select i_item_id
                        from item
                        where i_item_sk in (2, 3, 5, 7, 11, 13, 17, 19, 23, 29)))
  and ws_sold_date_sk = d_date_sk
  and d_qoy = 2 and d_year = 2001
group by ca_zip, ca_city
order by ca_zip, ca_city
limit 100
""",
    73: """
select c_last_name, c_first_name, c_salutation, c_preferred_cust_flag,
       ss_ticket_number, cnt
from (select ss_ticket_number, ss_customer_sk, count(*) cnt
      from store_sales, date_dim, store, household_demographics
      where store_sales.ss_sold_date_sk = date_dim.d_date_sk
        and store_sales.ss_store_sk = store.s_store_sk
        and store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
        and date_dim.d_dom between 1 and 2
        and (household_demographics.hd_buy_potential = '>10000'
             or household_demographics.hd_buy_potential = 'Unknown')
        and household_demographics.hd_vehicle_count > 0
        and (case when household_demographics.hd_vehicle_count > 0
                  then household_demographics.hd_dep_count / household_demographics.hd_vehicle_count
                  else null end) > 1
        and date_dim.d_year in (1999, 2000, 2001)
        and store.s_county = 'Williamson County'
      group by ss_ticket_number, ss_customer_sk) dj, customer
where ss_customer_sk = c_customer_sk
  and cnt between 1 and 5
order by cnt desc, c_last_name asc
""",
    84: """
select c_customer_id as customer_id,
       coalesce(c_last_name, '') || ', ' || coalesce(c_first_name, '') as customername
from customer, customer_address, customer_demographics,
     household_demographics, income_band, store_returns
where ca_city = 'Edgewood'
  and c_current_addr_sk = ca_address_sk
  and ib_lower_bound >= 38128
  and ib_upper_bound <= 88128
  and ib_income_band_sk = hd_income_band_sk
  and cd_demo_sk = c_current_cdemo_sk
  and hd_demo_sk = c_current_hdemo_sk
  and sr_cdemo_sk = cd_demo_sk
order by c_customer_id
limit 100
""",
    88: """
select *
from (select count(*) h8_30_to_9
      from store_sales, household_demographics, time_dim, store
      where ss_sold_time_sk = time_dim.t_time_sk
        and ss_hdemo_sk = household_demographics.hd_demo_sk
        and ss_store_sk = s_store_sk
        and time_dim.t_hour = 8 and time_dim.t_minute >= 30
        and ((household_demographics.hd_dep_count = 4
              and household_demographics.hd_vehicle_count <= 6)
          or (household_demographics.hd_dep_count = 2
              and household_demographics.hd_vehicle_count <= 4)
          or (household_demographics.hd_dep_count = 0
              and household_demographics.hd_vehicle_count <= 2))
        and store.s_store_name = 'ese') s1,
     (select count(*) h9_to_9_30
      from store_sales, household_demographics, time_dim, store
      where ss_sold_time_sk = time_dim.t_time_sk
        and ss_hdemo_sk = household_demographics.hd_demo_sk
        and ss_store_sk = s_store_sk
        and time_dim.t_hour = 9 and time_dim.t_minute < 30
        and ((household_demographics.hd_dep_count = 4
              and household_demographics.hd_vehicle_count <= 6)
          or (household_demographics.hd_dep_count = 2
              and household_demographics.hd_vehicle_count <= 4)
          or (household_demographics.hd_dep_count = 0
              and household_demographics.hd_vehicle_count <= 2))
        and store.s_store_name = 'ese') s2,
     (select count(*) h9_30_to_10
      from store_sales, household_demographics, time_dim, store
      where ss_sold_time_sk = time_dim.t_time_sk
        and ss_hdemo_sk = household_demographics.hd_demo_sk
        and ss_store_sk = s_store_sk
        and time_dim.t_hour = 9 and time_dim.t_minute >= 30
        and ((household_demographics.hd_dep_count = 4
              and household_demographics.hd_vehicle_count <= 6)
          or (household_demographics.hd_dep_count = 2
              and household_demographics.hd_vehicle_count <= 4)
          or (household_demographics.hd_dep_count = 0
              and household_demographics.hd_vehicle_count <= 2))
        and store.s_store_name = 'ese') s3,
     (select count(*) h10_to_10_30
      from store_sales, household_demographics, time_dim, store
      where ss_sold_time_sk = time_dim.t_time_sk
        and ss_hdemo_sk = household_demographics.hd_demo_sk
        and ss_store_sk = s_store_sk
        and time_dim.t_hour = 10 and time_dim.t_minute < 30
        and ((household_demographics.hd_dep_count = 4
              and household_demographics.hd_vehicle_count <= 6)
          or (household_demographics.hd_dep_count = 2
              and household_demographics.hd_vehicle_count <= 4)
          or (household_demographics.hd_dep_count = 0
              and household_demographics.hd_vehicle_count <= 2))
        and store.s_store_name = 'ese') s4,
     (select count(*) h10_30_to_11
      from store_sales, household_demographics, time_dim, store
      where ss_sold_time_sk = time_dim.t_time_sk
        and ss_hdemo_sk = household_demographics.hd_demo_sk
        and ss_store_sk = s_store_sk
        and time_dim.t_hour = 10 and time_dim.t_minute >= 30
        and ((household_demographics.hd_dep_count = 4
              and household_demographics.hd_vehicle_count <= 6)
          or (household_demographics.hd_dep_count = 2
              and household_demographics.hd_vehicle_count <= 4)
          or (household_demographics.hd_dep_count = 0
              and household_demographics.hd_vehicle_count <= 2))
        and store.s_store_name = 'ese') s5,
     (select count(*) h11_to_11_30
      from store_sales, household_demographics, time_dim, store
      where ss_sold_time_sk = time_dim.t_time_sk
        and ss_hdemo_sk = household_demographics.hd_demo_sk
        and ss_store_sk = s_store_sk
        and time_dim.t_hour = 11 and time_dim.t_minute < 30
        and ((household_demographics.hd_dep_count = 4
              and household_demographics.hd_vehicle_count <= 6)
          or (household_demographics.hd_dep_count = 2
              and household_demographics.hd_vehicle_count <= 4)
          or (household_demographics.hd_dep_count = 0
              and household_demographics.hd_vehicle_count <= 2))
        and store.s_store_name = 'ese') s6,
     (select count(*) h11_30_to_12
      from store_sales, household_demographics, time_dim, store
      where ss_sold_time_sk = time_dim.t_time_sk
        and ss_hdemo_sk = household_demographics.hd_demo_sk
        and ss_store_sk = s_store_sk
        and time_dim.t_hour = 11 and time_dim.t_minute >= 30
        and ((household_demographics.hd_dep_count = 4
              and household_demographics.hd_vehicle_count <= 6)
          or (household_demographics.hd_dep_count = 2
              and household_demographics.hd_vehicle_count <= 4)
          or (household_demographics.hd_dep_count = 0
              and household_demographics.hd_vehicle_count <= 2))
        and store.s_store_name = 'ese') s7,
     (select count(*) h12_to_12_30
      from store_sales, household_demographics, time_dim, store
      where ss_sold_time_sk = time_dim.t_time_sk
        and ss_hdemo_sk = household_demographics.hd_demo_sk
        and ss_store_sk = s_store_sk
        and time_dim.t_hour = 12 and time_dim.t_minute < 30
        and ((household_demographics.hd_dep_count = 4
              and household_demographics.hd_vehicle_count <= 6)
          or (household_demographics.hd_dep_count = 2
              and household_demographics.hd_vehicle_count <= 4)
          or (household_demographics.hd_dep_count = 0
              and household_demographics.hd_vehicle_count <= 2))
        and store.s_store_name = 'ese') s8
""",
    94: """
select count(distinct ws_order_number) as order_count,
       sum(ws_ext_ship_cost) as total_shipping_cost,
       sum(ws_net_profit) as total_net_profit
from web_sales ws1, date_dim, customer_address, web_site
where d_date between date '1999-02-01' and (date '1999-02-01' + interval '60' day)
  and ws1.ws_ship_date_sk = d_date_sk
  and ws1.ws_ship_addr_sk = ca_address_sk
  and ca_state = 'IL'
  and ws1.ws_web_site_sk = web_site_sk
  and web_company_name = 'pri'
  and exists (select *
              from web_sales ws2
              where ws1.ws_order_number = ws2.ws_order_number
                and ws1.ws_warehouse_sk <> ws2.ws_warehouse_sk)
  and not exists (select *
                  from web_returns wr1
                  where ws1.ws_order_number = wr1.wr_order_number)
order by count(distinct ws_order_number)
limit 100
""",
}
