"""Iterative rule-rewrite pass over logical plans.

Re-designed equivalent of the reference's IterativeOptimizer + rule set
(presto-main/.../sql/planner/iterative/IterativeOptimizer.java with the
81 rules under iterative/rule/, driven by PlanOptimizers.java:132).
Differences, on purpose:

* Plans here are immutable frozen dataclasses, so the Memo/GroupReference
  machinery collapses to structural rewriting: one bottom-up walk applies
  every rule at every node until a full pass changes nothing (rule count
  and plan depth are small — no lookup tables needed).
* Rules that the reference needs for correctness of its bytecode pipeline
  (HashGenerationOptimizer etc.) have no analog: XLA fuses and hashes.

The rules here are the semantic cleanups with real wins on the TPU path —
fewer kernels launched, fewer channels resident in HBM:

  RemoveIdentityProject   Project that forwards child channels unchanged
  MergeProjects           Project(Project) -> one Project (substitution)
  MergeFilters            Filter(Filter) -> conjunction
  PushFilterThroughProject  evaluate cheap predicates before projection
  PushLimitThroughProject Limit(Project) -> Project(Limit)
  LimitOverSortToTopN     Limit(Sort) -> TopN (device top-k, no full sort)
  CollapseLimits          Limit(Limit) -> min; Limit over TopN tightening
  RemoveFalseFilter       Filter(false/null) -> Limit 0
  RemoveTrueFilter        Filter(true) -> child
  DistinctOverDistinct    Distinct(Distinct) -> Distinct
  InferTransitiveEquality,
PushLimitThroughUnion, PushLimitThroughOuterJoin, PushTopNThroughProject,
DistinctOverAggregate a=b AND a=lit  adds  b=lit inside a Filter
                          (feeds the scan-pushdown that already exists)
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Tuple

from ..expr import ir
from . import nodes as N
from .matching import Pattern, pattern

MAX_PASSES = 10


@dataclasses.dataclass(frozen=True)
class Rule:
    name: str
    pattern: Pattern
    apply: Callable[[N.PlanNode, dict], Optional[N.PlanNode]]


def _replace_child(node: N.PlanNode, i: int, new_child: N.PlanNode):
    kids = node.children
    if isinstance(node, N.Union):
        inputs = tuple(
            new_child if j == i else c for j, c in enumerate(node.inputs)
        )
        return dataclasses.replace(node, inputs=inputs)
    names = [
        f.name
        for f in dataclasses.fields(node)
        if isinstance(getattr(node, f.name), N.PlanNode)
    ]
    return dataclasses.replace(node, **{names[i]: new_child})


def rewrite_tree(
    root: N.PlanNode, rules: List[Rule], trace: Optional[list] = None
) -> N.PlanNode:
    """Bottom-up fixpoint application: children first, then try every rule
    at this node until none fires, re-descending into rewritten results."""

    def visit(node: N.PlanNode, depth: int = 0) -> N.PlanNode:
        if depth > 200:  # defensive: a rule pair must not ping-pong
            return node
        kids = node.children
        for i, c in enumerate(kids):
            nc = visit(c, depth + 1)
            if nc is not c:
                node = _replace_child(node, i, nc)
        changed = True
        while changed:
            changed = False
            for rule in rules:
                caps = rule.pattern.match(node)
                if caps is None:
                    continue
                out = rule.apply(node, caps)
                if out is None or out is node:
                    continue
                if trace is not None:
                    trace.append((rule.name, type(node).__name__))
                node = visit(out, depth + 1)
                changed = True
                break
        return node

    for _ in range(MAX_PASSES):
        new = visit(root)
        if new is root:
            return root
        root = new
    return root


# ---------------------------------------------------------------------------
# expression helpers
# ---------------------------------------------------------------------------


def _substitute(e: ir.RowExpression, env: Dict[str, ir.RowExpression]):
    if isinstance(e, ir.ColumnRef):
        return env.get(e.name, e)
    if isinstance(e, ir.Call):
        args = tuple(_substitute(a, env) for a in e.args)
        return ir.Call(e.name, args, e.type) if args != e.args else e
    if isinstance(e, ir.Lambda):
        inner = {k: v for k, v in env.items() if k not in e.params}
        body = _substitute(e.body, inner)
        return (
            dataclasses.replace(e, body=body) if body is not e.body else e
        )
    return e


def _refs(e: ir.RowExpression, out: set):
    if isinstance(e, ir.ColumnRef):
        out.add(e.name)
    elif isinstance(e, ir.Call):
        for a in e.args:
            _refs(a, out)
    elif isinstance(e, ir.Lambda):
        inner: set = set()
        _refs(e.body, inner)
        out |= inner - set(e.params)


def split_conjuncts(e: ir.RowExpression) -> List[ir.RowExpression]:
    if isinstance(e, ir.Call) and e.name == "and":
        out: List[ir.RowExpression] = []
        for a in e.args:
            out.extend(split_conjuncts(a))
        return out
    return [e]


def _conjoin(parts: List[ir.RowExpression]) -> ir.RowExpression:
    return parts[0] if len(parts) == 1 else ir.and_(*parts)


def _is_literal(e, value=None) -> bool:
    # value-sensitive matches ignore param-tagged literals: a plan shape
    # decided by one EXECUTE's value would be wrong after a rebind
    return (
        isinstance(e, ir.Literal)
        and e.param is None
        and (value is None or e.value == value)
    )


# ---------------------------------------------------------------------------
# the rules
# ---------------------------------------------------------------------------


def _identity_project(node: N.PlanNode, caps) -> Optional[N.PlanNode]:
    child = node.child
    child_names = child.field_names()
    if node.names != tuple(child_names):
        return None
    for e, n in zip(node.exprs, node.names):
        if not (isinstance(e, ir.ColumnRef) and e.name == n):
            return None
    return child


def _merge_projects(node: N.Project, caps) -> Optional[N.PlanNode]:
    inner: N.Project = node.child
    # inline only when safe-and-cheap: every inner channel the outer uses
    # more than once must be a bare column/literal (no duplicated compute)
    uses: Dict[str, int] = {}

    def count(e):
        if isinstance(e, ir.ColumnRef):
            uses[e.name] = uses.get(e.name, 0) + 1
        elif isinstance(e, ir.Call):
            for a in e.args:
                count(a)
        elif isinstance(e, ir.Lambda):
            count(e.body)

    for e in node.exprs:
        count(e)
    env = dict(zip(inner.names, inner.exprs))
    for n, cnt in uses.items():
        if cnt > 1 and not isinstance(
            env.get(n, ir.Literal(0, None)), (ir.ColumnRef, ir.Literal)
        ):
            return None
    exprs = tuple(_substitute(e, env) for e in node.exprs)
    return N.Project(inner.child, exprs, node.names)


def _merge_filters(node: N.Filter, caps) -> Optional[N.PlanNode]:
    inner: N.Filter = node.child
    return N.Filter(
        inner.child,
        _conjoin(
            split_conjuncts(inner.predicate) + split_conjuncts(node.predicate)
        ),
    )


def _push_filter_through_project(node: N.Filter, caps) -> Optional[N.PlanNode]:
    proj: N.Project = node.child
    env = dict(zip(proj.names, proj.exprs))
    # substitute; bail when the predicate would duplicate real compute
    refs: set = set()
    _refs(node.predicate, refs)
    for n in refs:
        if not isinstance(env.get(n), (ir.ColumnRef, ir.Literal)):
            return None
    pred = _substitute(node.predicate, env)
    return N.Project(
        N.Filter(proj.child, pred), proj.exprs, proj.names
    )


def _push_limit_through_project(node: N.Limit, caps) -> Optional[N.PlanNode]:
    proj: N.Project = node.child
    return N.Project(
        N.Limit(proj.child, node.count), proj.exprs, proj.names
    )


def _limit_sort_to_topn(node: N.Limit, caps) -> Optional[N.PlanNode]:
    srt: N.Sort = node.child
    return N.TopN(srt.child, srt.keys, node.count)


def _collapse_limits(node: N.Limit, caps) -> Optional[N.PlanNode]:
    inner = node.child
    if isinstance(inner, N.Limit):
        return N.Limit(inner.child, min(node.count, inner.count))
    if isinstance(inner, N.TopN):
        if node.count >= inner.count:
            return inner
        return N.TopN(inner.child, inner.keys, node.count)
    return None


def _false_filter(node: N.Filter, caps) -> Optional[N.PlanNode]:
    p = node.predicate
    if isinstance(p, ir.Literal) and p.param is None and (
        p.value is False or p.value is None
    ):
        return N.Limit(node.child, 0)
    return None


def _true_filter(node: N.Filter, caps) -> Optional[N.PlanNode]:
    return node.child if _is_literal(node.predicate, True) else None


def _distinct_distinct(node: N.Distinct, caps) -> Optional[N.PlanNode]:
    return node.child


def _infer_transitive_equality(node: N.Filter, caps) -> Optional[N.PlanNode]:
    """a=b AND a=<lit>  =>  add b=<lit> (reference PredicatePushDown's
    equality inference; feeds scan pushdown + join pruning)."""
    parts = split_conjuncts(node.predicate)
    col_eq: List[Tuple[str, str]] = []
    lit_eq: Dict[str, ir.Literal] = {}
    have = set()
    for p in parts:
        if isinstance(p, ir.Call) and p.name == "eq" and len(p.args) == 2:
            a, b = p.args
            if isinstance(a, ir.ColumnRef) and isinstance(b, ir.ColumnRef):
                col_eq.append((a.name, b.name))
            elif isinstance(a, ir.ColumnRef) and isinstance(b, ir.Literal):
                lit_eq[a.name] = b
                have.add((a.name, repr(b.value)))
            elif isinstance(b, ir.ColumnRef) and isinstance(a, ir.Literal):
                lit_eq[b.name] = a
                have.add((b.name, repr(a.value)))
    if not col_eq or not lit_eq:
        return None
    from .. import types as T

    types = dict(node.child.fields)
    new: List[ir.RowExpression] = []
    for a, b in col_eq:
        for src, dst in ((a, b), (b, a)):
            litv = lit_eq.get(src)
            if litv is not None and (dst, repr(litv.value)) not in have:
                have.add((dst, repr(litv.value)))
                new.append(
                    ir.Call(
                        "eq",
                        (ir.ColumnRef(dst, types.get(dst, litv.type)), litv),
                        T.BOOLEAN,
                    )
                )
    if not new:
        return None
    return N.Filter(node.child, _conjoin(parts + new))


def _push_limit_through_union(node: N.Limit, caps) -> Optional[N.PlanNode]:
    """limit n (union all ...) => limit n (union all (limit n)...) —
    reference PushLimitThroughUnion; each branch stops producing early."""
    u: N.Union = node.child
    if u.distinct:
        return None
    if all(
        isinstance(i, (N.Limit, N.TopN)) and i.count <= node.count
        for i in u.inputs
    ):
        return None  # already pushed (fixpoint)
    return N.Limit(
        N.Union(
            tuple(N.Limit(i, node.count) for i in u.inputs), False
        ),
        node.count,
    )


def _push_limit_through_outer_join(node: N.Limit, caps) -> Optional[N.PlanNode]:
    """limit n (left join ...) => limit n (left join (limit n probe) ...)
    — reference PushLimitThroughOuterJoin: every probe row survives a
    LEFT join at least once, so n probe rows suffice."""
    j: N.Join = node.child
    if j.kind != "left" or j.residual is not None:
        return None
    if isinstance(j.left, (N.Limit, N.TopN)) and j.left.count <= node.count:
        return None
    return N.Limit(
        dataclasses.replace(j, left=N.Limit(j.left, node.count)),
        node.count,
    )


def _push_topn_through_project(node: N.TopN, caps) -> Optional[N.PlanNode]:
    """topN over a renaming projection reorders BEFORE projecting —
    reference PushTopNThroughProject (sort keys must map to plain column
    refs; computed keys stay put)."""
    proj: N.Project = node.child
    env = {n: e for n, e in zip(proj.names, proj.exprs)}
    new_keys = []
    for k in node.keys:
        e = k.expr
        if not isinstance(e, ir.ColumnRef):
            return None
        src = env.get(e.name)
        if not isinstance(src, ir.ColumnRef):
            return None
        new_keys.append(dataclasses.replace(k, expr=src))
    return N.Project(
        N.TopN(proj.child, tuple(new_keys), node.count),
        proj.exprs,
        proj.names,
    )


def _distinct_over_aggregate(node: N.Distinct, caps) -> Optional[N.PlanNode]:
    """Aggregation output rows are unique per key set (and a global
    aggregate is one row) — reference RemoveRedundantDistinct."""
    return node.child


_NONDETERMINISTIC = {"random", "rand", "uuid", "shuffle"}


def _deterministic(e: ir.RowExpression) -> bool:
    if isinstance(e, ir.Call):
        if e.name in _NONDETERMINISTIC:
            return False
        return all(_deterministic(a) for a in e.args)
    if isinstance(e, ir.Lambda):
        return _deterministic(e.body)
    return True


def _push_filter_through_join(node: N.Filter, caps) -> Optional[N.PlanNode]:
    """Single-side conjuncts move below the join (reference:
    PredicatePushDown.java join pushdown). Inner joins push to both
    sides; LEFT joins only to the probe side — filtering the
    null-extended side below the join would turn dropped rows into
    null-extended ones."""
    j = node.child
    if not isinstance(j, N.Join) or j.kind not in ("inner", "left"):
        return None
    lnames = {n for n, _ in j.left.fields}
    rnames = {n for n, _ in j.right.fields}
    stay: List[ir.RowExpression] = []
    lpush: List[ir.RowExpression] = []
    rpush: List[ir.RowExpression] = []
    for c in split_conjuncts(node.predicate):
        refs: set = set()
        _refs(c, refs)
        if refs and refs <= lnames and _deterministic(c):
            lpush.append(c)
        elif (
            refs and refs <= rnames and j.kind == "inner"
            and _deterministic(c)
        ):
            rpush.append(c)
        else:
            stay.append(c)
    if not lpush and not rpush:
        return None
    left = N.Filter(j.left, _conjoin(lpush)) if lpush else j.left
    right = N.Filter(j.right, _conjoin(rpush)) if rpush else j.right
    out: N.PlanNode = dataclasses.replace(j, left=left, right=right)
    return N.Filter(out, _conjoin(stay)) if stay else out


def _push_filter_through_union(node: N.Filter, caps) -> Optional[N.PlanNode]:
    """Filter distributes over UNION [ALL] inputs (reference:
    PushDownDereferencesThroughUnion's simpler cousin —
    the engine's union inputs already share the first input's channel
    names, so the predicate applies verbatim to each input)."""
    u = node.child
    if not isinstance(u, N.Union) or not _deterministic(node.predicate):
        return None
    return dataclasses.replace(
        u,
        inputs=tuple(N.Filter(i, node.predicate) for i in u.inputs),
    )


def _push_filter_through_aggregate(node: N.Filter, caps) -> Optional[N.PlanNode]:
    """HAVING conjuncts that reference only group keys filter ROWS below
    the aggregation instead of groups above it (reference:
    PushPredicateThroughAggregation semantics inside PredicatePushDown):
    a group exists iff some row has its key, so key-only predicates
    commute with grouping."""
    a = node.child
    if not isinstance(a, N.Aggregate) or not a.group_exprs:
        return None
    env = {n: e for n, e in zip(a.group_names, a.group_exprs)}
    gnames = set(a.group_names)
    push: List[ir.RowExpression] = []
    stay: List[ir.RowExpression] = []
    for c in split_conjuncts(node.predicate):
        refs: set = set()
        _refs(c, refs)
        if refs and refs <= gnames and _deterministic(c):
            push.append(_substitute(c, env))
        else:
            stay.append(c)
    if not push:
        return None
    out: N.PlanNode = dataclasses.replace(
        a, child=N.Filter(a.child, _conjoin(push))
    )
    return N.Filter(out, _conjoin(stay)) if stay else out


_ORDER_SENSITIVE_AGGS = {"array_agg", "map_agg", "multimap_agg", "histogram"}


def _remove_redundant_sort(node: N.PlanNode, caps) -> Optional[N.PlanNode]:
    """A Sort feeding an order-insensitive consumer is dead work
    (reference: RemoveRedundantSort / PruneOrderByInAggregation)."""
    child = node.child
    if not isinstance(child, N.Sort):
        return None
    if isinstance(node, N.Aggregate) and any(
        a.func in _ORDER_SENSITIVE_AGGS for a in node.aggs
    ):
        return None
    return dataclasses.replace(node, child=child.child)


# functions whose value depends on evaluation context, never foldable
# (reference: FunctionRegistry isDeterministic + CURRENT_* special forms)
_NONDETERMINISTIC = {
    "random", "rand", "now", "uuid", "shuffle",
    "current_date", "current_timestamp", "current_time",
    "current_timezone", "localtimestamp", "localtime",
}


def _foldable(e: ir.RowExpression) -> bool:
    """No column refs, no lambdas, no nondeterministic calls anywhere."""
    if isinstance(e, ir.ColumnRef):
        return False
    if isinstance(e, ir.Lambda):
        return False
    if isinstance(e, ir.Call):
        if e.name in _NONDETERMINISTIC:
            return False
        return all(_foldable(a) for a in e.args)
    # param-tagged literals (EXECUTE skeletons, exec/qcache.py) must stay
    # symbolic: folding would bake one execution's value into the plan
    return isinstance(e, ir.Literal) and e.param is None


def _fold_expr(e: ir.RowExpression) -> Tuple[ir.RowExpression, bool]:
    """Bottom-up constant folding (reference SimplifyExpressions /
    ExpressionInterpreter): a ref-free deterministic subtree is evaluated
    ONCE at plan time — on the host CPU backend so planning never touches
    the accelerator — and replaced by a Literal. Arrays/maps and decimal
    lanes stay unfolded (no scalar literal form)."""
    if isinstance(e, ir.Call) and e.args:
        if (
            _foldable(e)
            and not isinstance(e, ir.Literal)
            and _scalar_literal_type(e.type)
        ):
            v = _eval_const(e)
            if v is not _FOLD_FAIL:
                return ir.Literal(v, e.type), True
        changed = False
        new_args = []
        for a in e.args:
            na, ch = _fold_expr(a)
            new_args.append(na)
            changed = changed or ch
        if changed:
            return (
                dataclasses.replace(e, args=tuple(new_args)),
                True,
            )
    return e, False


_FOLD_FAIL = object()


def _scalar_literal_type(t) -> bool:
    from .. import types as T

    return isinstance(
        t,
        (
            T.BigintType, T.IntegerType, T.DoubleType, T.BooleanType,
            T.VarcharType, T.DateType, T.TimestampType,
        ),
    )


def _eval_const(e: ir.Call):
    import jax
    import numpy as np

    from .. import types as T
    from ..expr.compiler import evaluate
    from ..page import Page

    try:
        cpu = jax.devices("cpu")[0]
    except RuntimeError:
        return _FOLD_FAIL
    try:
        with jax.default_device(cpu):
            page = Page.from_dict({"__row__": np.zeros(1, np.int64)})
            val = evaluate(e, page)
            if val.data.ndim != 1:
                return _FOLD_FAIL
            if val.valid is not None and not bool(val.valid[0]):
                return None
            x = val.data[0].item()
            if isinstance(e.type, T.VarcharType):
                d = val.dictionary
                if d is None:
                    return _FOLD_FAIL
                return d[int(x)]
            if isinstance(e.type, T.BooleanType):
                return bool(x)
            return x
    except Exception:  # noqa: BLE001 — unfoldable stays symbolic
        return _FOLD_FAIL


def _merge_adjacent_unions(node: N.Union, caps) -> Optional[N.PlanNode]:
    """Union(Union(a,b),c) -> Union(a,b,c) (reference MergeAdjacentUnions
    / pruning SetOperationNodeUtils): one concat kernel instead of a
    chain. A DISTINCT child union cannot be inlined into an ALL parent
    (it dedupes first); any child inlines into a DISTINCT parent."""
    flat = []
    changed = False
    for c in node.inputs:
        if isinstance(c, N.Union) and (node.distinct or not c.distinct):
            # channel names already unified by the planner contract
            flat.extend(c.inputs)
            changed = True
        else:
            flat.append(c)
    if not changed:
        return None
    return dataclasses.replace(node, inputs=tuple(flat))


def _simplify_filter(node: N.Filter, caps) -> Optional[N.PlanNode]:
    ne, changed = _fold_expr(node.predicate)
    return dataclasses.replace(node, predicate=ne) if changed else None


def _simplify_project(node: N.Project, caps) -> Optional[N.PlanNode]:
    changed = False
    out = []
    for ex in node.exprs:
        ne, c = _fold_expr(ex)
        out.append(ne)
        changed = changed or c
    return (
        dataclasses.replace(node, exprs=tuple(out)) if changed else None
    )


def default_rules() -> List[Rule]:
    P = pattern
    return [
        Rule("RemoveTrueFilter", P(N.Filter), _true_filter),
        Rule("RemoveFalseFilter", P(N.Filter), _false_filter),
        Rule(
            "MergeFilters",
            P(N.Filter).child(P(N.Filter)),
            _merge_filters,
        ),
        Rule(
            "RemoveIdentityProject", P(N.Project), _identity_project
        ),
        Rule(
            "MergeProjects",
            P(N.Project).child(P(N.Project)),
            _merge_projects,
        ),
        Rule(
            "PushFilterThroughProject",
            P(N.Filter).child(P(N.Project)),
            _push_filter_through_project,
        ),
        Rule(
            "PushLimitThroughProject",
            P(N.Limit).child(P(N.Project)),
            _push_limit_through_project,
        ),
        Rule(
            "LimitOverSortToTopN",
            P(N.Limit).child(P(N.Sort)),
            _limit_sort_to_topn,
        ),
        Rule(
            "CollapseLimits",
            P(N.Limit).child(P(N.Limit, N.TopN)),
            _collapse_limits,
        ),
        Rule(
            "DistinctOverDistinct",
            P(N.Distinct).child(P(N.Distinct)),
            _distinct_distinct,
        ),
        Rule(
            "InferTransitiveEquality",
            P(N.Filter),
            _infer_transitive_equality,
        ),
        Rule(
            "PushLimitThroughUnion",
            P(N.Limit).child(P(N.Union)),
            _push_limit_through_union,
        ),
        Rule(
            "PushLimitThroughOuterJoin",
            P(N.Limit).child(P(N.Join)),
            _push_limit_through_outer_join,
        ),
        Rule(
            "PushTopNThroughProject",
            P(N.TopN).child(P(N.Project)),
            _push_topn_through_project,
        ),
        Rule(
            "DistinctOverAggregate",
            P(N.Distinct).child(P(N.Aggregate)),
            _distinct_over_aggregate,
        ),
        Rule(
            "PushFilterThroughJoin",
            P(N.Filter).child(P(N.Join)),
            _push_filter_through_join,
        ),
        Rule(
            "PushFilterThroughUnion",
            P(N.Filter).child(P(N.Union)),
            _push_filter_through_union,
        ),
        Rule(
            "PushFilterThroughAggregate",
            P(N.Filter).child(P(N.Aggregate)),
            _push_filter_through_aggregate,
        ),
        Rule(
            "RemoveRedundantSort",
            P(N.Aggregate, N.Distinct).child(P(N.Sort)),
            _remove_redundant_sort,
        ),
        Rule("SimplifyFilterExpressions", P(N.Filter), _simplify_filter),
        Rule("SimplifyProjectExpressions", P(N.Project), _simplify_project),
        Rule("MergeAdjacentUnions", P(N.Union), _merge_adjacent_unions),
    ]


def rewrite(root: N.PlanNode, trace: Optional[list] = None) -> N.PlanNode:
    return rewrite_tree(root, default_rules(), trace)


# ---------------------------------------------------------------------------
# dynamic-filter annotation (reference: PredicatePushDown's dynamic filter
# placeholders + DynamicFilterSourceOperator placement). Runs LAST in
# optimize(), over the pruned tree, so channel names are final.
# ---------------------------------------------------------------------------


def _df_attach_consumer(node: N.PlanNode, channel: str, fid: str):
    """Push a dynamic-filter consumer annotation down the probe side to the
    TableScan producing `channel`. Returns the rewritten subtree or None
    when no scan is reachable through row-pruning-safe nodes.

    Safety contract: the filter only drops rows that CANNOT survive the
    annotated join. That is sound exactly through nodes where one input
    row maps to output rows carrying the same traced-channel value and
    dropping it drops only those outputs: Filter, renaming Project, the
    streamed side of joins (both sides of inner, the probe side of left),
    and a plain semi join's child. Aggregates, windows, sorts, limits,
    samples, unions change other rows' results when inputs vanish — stop.
    """
    if isinstance(node, N.TableScan):
        src = {ch: col for ch, col, _ in node.columns}
        if channel not in src:
            return None
        return dataclasses.replace(
            node,
            dynamic_filters=node.dynamic_filters
            + ((fid, channel, src[channel], True),),
        )
    if isinstance(node, N.Filter):
        child = _df_attach_consumer(node.child, channel, fid)
        if child is None:
            return None
        if (
            isinstance(child, N.TableScan)
            and child.dynamic_filters
            and child.dynamic_filters[-1][0] == fid
        ):
            # fuse the device mask into THIS filter's compaction (one
            # compact pass); the scan keeps the entry for SPI hints only
            fe = child.dynamic_filters[-1]
            child = dataclasses.replace(
                child,
                dynamic_filters=child.dynamic_filters[:-1]
                + ((fe[0], fe[1], fe[2], False),),
            )
            return dataclasses.replace(
                node,
                child=child,
                dynamic_filters=node.dynamic_filters + ((fid, channel),),
            )
        return dataclasses.replace(node, child=child)
    if isinstance(node, N.Project):
        env = dict(zip(node.names, node.exprs))
        e = env.get(channel)
        if not isinstance(e, ir.ColumnRef):
            return None
        child = _df_attach_consumer(node.child, e.name, fid)
        if child is None:
            return None
        return dataclasses.replace(node, child=child)
    if isinstance(node, N.Join):
        if node.kind not in ("inner", "left"):
            return None
        lnames = {n for n, _ in node.left.fields}
        if channel in lnames:
            child = _df_attach_consumer(node.left, channel, fid)
            return (
                None
                if child is None
                else dataclasses.replace(node, left=child)
            )
        if node.kind == "inner":
            child = _df_attach_consumer(node.right, channel, fid)
            return (
                None
                if child is None
                else dataclasses.replace(node, right=child)
            )
        return None
    if isinstance(node, N.SemiJoin):
        if node.mark is not None:
            return None  # mark joins keep every probe row
        child = _df_attach_consumer(node.child, channel, fid)
        return (
            None if child is None else dataclasses.replace(node, child=child)
        )
    return None


def _df_comparable_types(a, b) -> bool:
    """Key pair eligible for a storage-level dynamic filter: identical
    types (the planner coerces equi-join keys, so this is the common
    case); differing types would compare different storage units."""
    return a == b


def annotate_dynamic_filters(root: N.PlanNode) -> N.PlanNode:
    """Assign dynamic-filter ids linking each eligible equi-join's build
    keys to probe-side Filter/TableScan consumers. Eligible: INNER joins
    and plain semi joins — kinds where dropping provably-non-matching
    probe rows early is an identity on the result."""
    counter = [0]

    def visit(node: N.PlanNode) -> N.PlanNode:
        replace = {}
        for f in dataclasses.fields(node):
            v = getattr(node, f.name)
            if isinstance(v, N.PlanNode):
                nv = visit(v)
                if nv is not v:
                    replace[f.name] = nv
            elif isinstance(v, tuple) and v and isinstance(v[0], N.PlanNode):
                nv = tuple(visit(c) for c in v)
                if nv != v:
                    replace[f.name] = nv
        if replace:
            node = dataclasses.replace(node, **replace)

        if isinstance(node, N.Join) and node.kind == "inner" and node.left_keys:
            probe_attr, probe_keys, build_keys = (
                "left", node.left_keys, node.right_keys
            )
        elif (
            isinstance(node, N.SemiJoin)
            and not node.anti
            and node.mark is None
            and node.probe_keys
        ):
            probe_attr, probe_keys, build_keys = (
                "child", node.probe_keys, node.source_keys
            )
        else:
            return node

        produce = []
        probe = getattr(node, probe_attr)
        for i, (pk, bk) in enumerate(zip(probe_keys, build_keys)):
            if not _df_comparable_types(pk.type, bk.type):
                continue
            fid = f"df{counter[0]}"
            consumed = False
            if isinstance(pk, ir.ColumnRef):
                new_probe = _df_attach_consumer(probe, pk.name, fid)
                if new_probe is not None:
                    probe = new_probe
                    consumed = True
            produce.append((fid, i, consumed))
            counter[0] += 1
        if not produce:
            return node
        return dataclasses.replace(
            node, **{probe_attr: probe, "dynamic_filters": tuple(produce)}
        )

    return visit(root)
