"""Pattern-matching substrate for optimizer rules.

Re-designed equivalent of the reference's presto-matching module
(presto-matching/src/main/java/com/facebook/presto/matching/: Pattern,
Matcher, Captures — consumed by the 81 iterative rules). The TPU build's
plan nodes are frozen dataclasses, so a pattern is a plain predicate tree:
node-class check + property predicates + per-child sub-patterns, with
named captures collected into a dict. No bytecode, no reflection — a
pattern match is one recursive function call.

    P = pattern(N.Limit).child(pattern(N.Sort).capture("sort")).capture("limit")
    caps = P.match(node)      # {"limit": node, "sort": node.child} | None
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional, Sequence, Tuple, Type

from . import nodes as N


Captures = Dict[str, N.PlanNode]


@dataclasses.dataclass(frozen=True)
class Pattern:
    node_types: Tuple[type, ...]  # () = any node
    predicates: Tuple[Callable[[N.PlanNode], bool], ...] = ()
    child_patterns: Tuple[Tuple[int, "Pattern"], ...] = ()  # (child idx, sub)
    capture_name: Optional[str] = None

    def matching(self, pred: Callable[[N.PlanNode], bool]) -> "Pattern":
        return dataclasses.replace(
            self, predicates=self.predicates + (pred,)
        )

    def child(self, sub: "Pattern", index: int = 0) -> "Pattern":
        return dataclasses.replace(
            self, child_patterns=self.child_patterns + ((index, sub),)
        )

    def capture(self, name: str) -> "Pattern":
        return dataclasses.replace(self, capture_name=name)

    def match(self, node: N.PlanNode) -> Optional[Captures]:
        caps: Captures = {}
        return caps if self._match_into(node, caps) else None

    def _match_into(self, node: N.PlanNode, caps: Captures) -> bool:
        if self.node_types and not isinstance(node, self.node_types):
            return False
        for pred in self.predicates:
            if not pred(node):
                return False
        kids = node.children
        for idx, sub in self.child_patterns:
            if idx >= len(kids) or not sub._match_into(kids[idx], caps):
                return False
        if self.capture_name is not None:
            caps[self.capture_name] = node
        return True


def pattern(*node_types: Type[N.PlanNode]) -> Pattern:
    return Pattern(tuple(node_types))


def any_node() -> Pattern:
    return Pattern(())


# ---------------------------------------------------------------------------
# plan-assertion DSL (reference sql/planner/assertions/PlanMatchPattern):
# tests assert on the SHAPE of an optimized plan
# ---------------------------------------------------------------------------


def assert_plan(node: N.PlanNode, shape) -> None:
    """`shape` is a nested tuple (NodeType, pred_or_None, *child_shapes);
    NodeType may be a type or tuple of types; pred is an optional
    node->bool. Raises AssertionError with the offending subtree."""
    if not isinstance(shape, tuple):
        shape = (shape,)
    node_type, rest = shape[0], shape[1:]
    pred = None
    if rest and (rest[0] is None or callable(rest[0])) and not (
        isinstance(rest[0], tuple) or isinstance(rest[0], type)
    ):
        pred, rest = rest[0], rest[1:]
    if not isinstance(node, node_type):
        raise AssertionError(
            f"expected {node_type} got {type(node).__name__}: {node}"
        )
    if pred is not None and not pred(node):
        raise AssertionError(f"predicate failed on {node}")
    kids = node.children
    if len(rest) != len(kids):
        raise AssertionError(
            f"{type(node).__name__}: expected {len(rest)} children, "
            f"has {len(kids)}"
        )
    for sub, kid in zip(rest, kids):
        assert_plan(kid, sub)
