"""Plan optimizations that run after logical planning.

The planner (sql/planner.py) already folds in pushdown and join ordering;
passes here are cross-cutting rewrites over the finished tree:

* prune_columns — the reference's PruneUnreferencedOutputs +
  PruneTableScanColumns (sql/planner/iterative/rule/): drop every channel a
  parent never reads. On TPU this directly cuts HBM traffic and transfer
  volume, the dominant cost.
"""

from __future__ import annotations

import dataclasses
from typing import Set, Tuple

from ..expr import ir
from ..ops.aggregate import AggSpec
from . import nodes as N


def _expr_channels(e, out: Set[str]):
    if isinstance(e, ir.ColumnRef):
        out.add(e.name)
    elif isinstance(e, ir.Call):
        for a in e.args:
            _expr_channels(a, out)


def prune_columns(node: N.PlanNode, needed: Set[str]) -> N.PlanNode:
    """Rewrite `node` so it outputs (at least) `needed` channels, recursively
    dropping everything else."""
    if isinstance(node, N.TableScan):
        cols = tuple(c for c in node.columns if c[0] in needed)
        if not cols:  # keep one column: count(*)-only scans still need rows
            cols = node.columns[:1]
        return dataclasses.replace(node, columns=cols)

    if isinstance(node, N.SingleRow):
        return node

    if isinstance(node, N.Sample):
        return dataclasses.replace(
            node, child=prune_columns(node.child, needed)
        )

    if isinstance(node, N.Unnest):
        child_have = set(node.child.field_names())
        child_needed = needed & child_have
        for e in node.array_exprs:
            _expr_channels(e, child_needed)
        return dataclasses.replace(
            node, child=prune_columns(node.child, child_needed)
        )

    if isinstance(node, N.Filter):
        child_needed = set(needed)
        _expr_channels(node.predicate, child_needed)
        return N.Filter(prune_columns(node.child, child_needed), node.predicate)

    if isinstance(node, N.Project):
        keep = [
            (e, n) for e, n in zip(node.exprs, node.names) if n in needed
        ]
        if not keep:
            keep = [(node.exprs[0], node.names[0])]
        child_needed: Set[str] = set()
        for e, _ in keep:
            _expr_channels(e, child_needed)
        child = prune_columns(node.child, child_needed)
        return N.Project(
            child, tuple(e for e, _ in keep), tuple(n for _, n in keep)
        )

    if isinstance(node, N.Aggregate):
        keep_aggs = tuple(a for a in node.aggs if a.name in needed)
        if not keep_aggs and not node.group_exprs:
            # a global aggregate must keep one accumulator to emit its one
            # row (the GROUP BY () part of a ROLLUP with no aggregates)
            keep_aggs = node.aggs[:1]
        child_needed: Set[str] = set()
        for e in node.group_exprs:
            _expr_channels(e, child_needed)
        for a in keep_aggs:
            if a.input is not None:
                _expr_channels(a.input, child_needed)
            if a.input2 is not None:
                _expr_channels(a.input2, child_needed)
        if node.mask is not None:
            _expr_channels(node.mask, child_needed)
        child = prune_columns(node.child, child_needed)
        return N.Aggregate(
            child, node.group_exprs, node.group_names, keep_aggs, node.mask
        )

    if isinstance(node, N.Join):
        left_have = set(node.left.field_names())
        right_have = set(node.right.field_names())
        left_needed = needed & left_have
        right_needed = needed & right_have
        for e in node.left_keys:
            _expr_channels(e, left_needed)
        for e in node.right_keys:
            _expr_channels(e, right_needed)
        if node.residual is not None:
            res: Set[str] = set()
            _expr_channels(node.residual, res)
            left_needed |= res & left_have
            right_needed |= res & right_have
        return dataclasses.replace(
            node,
            left=prune_columns(node.left, left_needed),
            right=prune_columns(node.right, right_needed),
        )

    if isinstance(node, N.SemiJoin):
        child_have = set(node.child.field_names())
        source_have = set(node.source.field_names())
        child_needed = needed & child_have
        source_needed: Set[str] = set()
        for e in node.probe_keys:
            _expr_channels(e, child_needed)
        for e in node.source_keys:
            _expr_channels(e, source_needed)
        if node.residual is not None:
            res = set()
            _expr_channels(node.residual, res)
            child_needed |= res & child_have
            source_needed |= res & source_have
        return dataclasses.replace(
            node,
            child=prune_columns(node.child, child_needed),
            source=prune_columns(node.source, source_needed),
        )

    if isinstance(node, N.ScalarApply):
        sub_have = set(node.subquery.field_names())
        child_needed = needed - sub_have
        return dataclasses.replace(
            node,
            child=prune_columns(node.child, child_needed),
            subquery=node.subquery,
        )

    if isinstance(node, N.Window):
        child_needed = {n for n in needed if n in set(node.child.field_names())}
        for e in node.partition_exprs:
            _expr_channels(e, child_needed)
        for k in node.order_keys:
            _expr_channels(k.expr, child_needed)
        for f in node.funcs:
            if f.input is not None:
                _expr_channels(f.input, child_needed)
        return dataclasses.replace(
            node, child=prune_columns(node.child, child_needed)
        )

    if isinstance(node, (N.Sort, N.TopN)):
        child_needed = set(needed)
        for k in node.keys:
            _expr_channels(k.expr, child_needed)
        return dataclasses.replace(
            node, child=prune_columns(node.child, child_needed)
        )

    if isinstance(node, N.Limit):
        return dataclasses.replace(
            node, child=prune_columns(node.child, set(needed))
        )

    if isinstance(node, N.Distinct):
        # DISTINCT semantics depend on the full column set — never prune
        # through it, only below via its child's own needs
        return dataclasses.replace(
            node,
            child=prune_columns(node.child, set(node.child.field_names())),
        )

    if isinstance(node, N.Union):
        # channel names are aligned across inputs by the planner
        return dataclasses.replace(
            node,
            inputs=tuple(prune_columns(c, set(needed)) for c in node.inputs),
        )

    if isinstance(node, N.Output):
        child = prune_columns(node.child, set(node.channels))
        return dataclasses.replace(node, child=child)

    raise TypeError(f"prune_columns: unhandled node {type(node).__name__}")


def fuse_filter_into_aggregates(node: N.PlanNode) -> N.PlanNode:
    """Aggregate(Filter(x, p)) -> Aggregate(x, mask=p).

    TPU-first rewrite: a standalone filter materializes a compacted page
    (sort + gathers); aggregation consumes a selection MASK for free inside
    its fused reduction kernels. The reference's analog is
    ScanFilterAndProjectOperator fusing the filter into the page processor."""
    replace = {}
    for f in dataclasses.fields(node):
        v = getattr(node, f.name)
        if isinstance(v, N.PlanNode):
            nv = fuse_filter_into_aggregates(v)
            if nv is not v:
                replace[f.name] = nv
        elif (
            isinstance(v, tuple) and v and isinstance(v[0], N.PlanNode)
        ):
            nv = tuple(fuse_filter_into_aggregates(c) for c in v)
            if nv != v:
                replace[f.name] = nv
    if replace:
        node = dataclasses.replace(node, **replace)
    if (
        isinstance(node, N.Aggregate)
        and node.mask is None
        and isinstance(node.child, N.Filter)
    ):
        flt = node.child
        node = dataclasses.replace(node, child=flt.child, mask=flt.predicate)
    return node


def optimize(root: N.PlanNode) -> N.PlanNode:
    from .rules import annotate_dynamic_filters, rewrite

    root = rewrite(root)  # iterative rule pass (plan/rules.py)
    root = fuse_filter_into_aggregates(root)
    if isinstance(root, N.Output):
        root = prune_columns(root, set(root.channels))
    else:
        root = prune_columns(root, set(root.field_names()))
    # LAST: channel names are final after pruning, so build->probe dynamic
    # filter links (runtime filtering, exec/dynfilter.py) bind correctly
    return annotate_dynamic_filters(root)
